import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.columnar import (arrow_to_device, device_to_arrow,
                                       bucket_rows)


def roundtrip(rb: pa.RecordBatch) -> pa.RecordBatch:
    return device_to_arrow(arrow_to_device(rb))


def test_bucket_rows():
    assert bucket_rows(0) == 128
    assert bucket_rows(128) == 128
    assert bucket_rows(129) == 256
    assert bucket_rows(1000) == 1024


@pytest.mark.parametrize("atype,values", [
    (pa.int32(), [1, 2, None, -7, 2**31 - 1]),
    (pa.int64(), [None, 0, -(2**63), 2**63 - 1]),
    (pa.int8(), [1, None, -128, 127]),
    (pa.int16(), [300, None, -32768]),
    (pa.float32(), [1.5, None, float("nan"), float("inf")]),
    (pa.float64(), [None, -0.0, 1e300, float("-inf")]),
    (pa.bool_(), [True, None, False, True]),
])
def test_fixed_width_roundtrip(atype, values):
    rb = pa.record_batch({"a": pa.array(values, type=atype)})
    out = roundtrip(rb)
    assert out.column(0).equals(rb.column(0)) or (
        # NaN != NaN under Arrow equals; compare via numpy
        np.array_equal(out.column(0).to_numpy(zero_copy_only=False),
                       rb.column(0).to_numpy(zero_copy_only=False),
                       equal_nan=True))


def test_string_roundtrip():
    vals = ["hello", "", None, "wörld", "a" * 1000, None, "x"]
    rb = pa.record_batch({"s": pa.array(vals, type=pa.string())})
    out = roundtrip(rb)
    assert out.column(0).to_pylist() == vals


def test_binary_roundtrip():
    vals = [b"\x00\x01", None, b"", b"abc"]
    rb = pa.record_batch({"b": pa.array(vals, type=pa.binary())})
    assert roundtrip(rb).column(0).to_pylist() == vals


def test_date_timestamp_roundtrip():
    import datetime
    d = [datetime.date(2020, 1, 1), None, datetime.date(1969, 12, 31)]
    ts = [datetime.datetime(2021, 6, 1, 12, 30, 15, 123456), None, None]
    rb = pa.record_batch({
        "d": pa.array(d, type=pa.date32()),
        "t": pa.array(ts, type=pa.timestamp("us", tz="UTC")),
    })
    out = roundtrip(rb)
    assert out.column(0).to_pylist() == d
    got = out.column(1).to_pylist()
    assert got[1] is None and got[2] is None
    assert got[0].replace(tzinfo=None) == ts[0]


def test_decimal_roundtrip():
    import decimal
    vals = [decimal.Decimal("123.45"), None, decimal.Decimal("-0.01"),
            decimal.Decimal("99999999999999.99")]
    rb = pa.record_batch({"d": pa.array(vals, type=pa.decimal128(16, 2))})
    assert roundtrip(rb).column(0).to_pylist() == vals


def test_sliced_input():
    arr = pa.array(["aa", "bb", "cc", "dd", None, "ff"]).slice(2, 3)
    rb = pa.record_batch({"s": arr})
    assert roundtrip(rb).column(0).to_pylist() == ["cc", "dd", None]


def test_schema_mapping():
    rb = pa.record_batch({"i": pa.array([1], pa.int32()),
                          "s": pa.array(["x"])})
    b = arrow_to_device(rb)
    assert b.schema.names == ["i", "s"]
    assert b.schema.types == [dt.INT32, dt.STRING]
    assert b.num_rows == 1
    assert b.capacity == 128
