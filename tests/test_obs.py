"""Observability tier tests: span tracer, metrics registry, Prometheus
exposition, Chrome trace export, critical-path mining, event-log reader
guarantees — plus the ISSUE acceptance test: a process-cluster query
with an injected worker crash produces ONE stitched Chrome trace with
driver query/stage spans, both task attempts (failed + retried) under
the right parents, and worker-side operator spans."""
import importlib.util
import json
import os
import threading
import urllib.request

import pyarrow as pa
import pytest

from data_gen import IntegerGen, LongGen, gen_table

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.obs.metrics import (MetricsRegistry, dump_prometheus,
                                          render_merged_snapshots)
from spark_rapids_tpu.obs.tracer import (NULL_TRACER, Tracer,
                                         load_chrome_trace,
                                         tracer_from_conf)
from spark_rapids_tpu.tools.profiling import (critical_path,
                                              format_critical_path,
                                              profile_trace)


def _load_checker():
    """The CI schema checker doubles as the test oracle for emitted
    observability artifacts."""
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "check_obs_output.py")
    spec = importlib.util.spec_from_file_location("check_obs", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --- tracer -----------------------------------------------------------------

def test_disabled_tracer_is_shared_noop():
    t = tracer_from_conf(RapidsConf())
    assert t is NULL_TRACER and not t.enabled
    # span() must return ONE shared object: no allocation when disabled
    assert t.span("a") is t.span("b")
    with t.span("x") as sp:
        assert sp.span_id is None
    assert t.drain() == [] and t.write_chrome("/nonexistent") == ""


def test_tracer_from_conf_enabled(tmp_path):
    conf = RapidsConf({"spark.rapids.trace.dir": str(tmp_path),
                       "spark.rapids.trace.maxSpans": 7})
    t = tracer_from_conf(conf, pid=3)
    assert t.enabled and t.pid == 3 and t.max_spans == 7


def test_span_nesting_thread_local_stack():
    t = Tracer()
    with t.span("outer", cat="query") as o:
        with t.span("inner", cat="op"):
            pass
    spans = {s["name"]: s for s in t.drain()}
    assert spans["outer"]["parent_id"] is None
    assert spans["inner"]["parent_id"] == spans["outer"]["span_id"]
    assert spans["inner"]["dur"] <= spans["outer"]["dur"]


def test_span_stack_is_per_thread():
    t = Tracer()
    seen = {}

    def work(name):
        with t.span(name):
            seen[name] = t._stack()[:]

    with t.span("root"):
        th = threading.Thread(target=work, args=("other-thread",))
        th.start()
        th.join()
    # the other thread must not have nested under this thread's root
    other = [s for s in t.drain() if s["name"] == "other-thread"][0]
    assert other["parent_id"] is None


def test_emit_deterministic_ids_and_absorb():
    t = Tracer(trace_id="abc", pid=0)
    sid = t.emit("attempt t1 a0", "attempt", ts=100.0, dur=2.0,
                 span_id="t1.a0", parent_id=None)
    assert sid == "t1.a0"
    # a worker serialized spans parented on the attempt id
    t.absorb([{"name": "task t1 a0", "cat": "task", "span_id": "t1.a0.1.1",
               "parent_id": "t1.a0", "ts": 100.5, "dur": 1.0, "pid": 1},
              {"garbage": True},  # torn entry: skipped, not fatal
              {"name": "no-id"}])
    spans = t.drain()
    assert len(spans) == 2
    task = [s for s in spans if s["cat"] == "task"][0]
    assert task["parent_id"] == "t1.a0" and task["pid"] == 1


def test_span_buffer_bound_counts_drops():
    t = Tracer(max_spans=3)
    for i in range(5):
        with t.span(f"s{i}"):
            pass
    assert len(t.drain()) == 3 and t.dropped == 2


def test_worker_id_prefix_prevents_collisions():
    a = Tracer(trace_id="x", pid=1, id_prefix="t1.a0.")
    b = Tracer(trace_id="x", pid=1, id_prefix="t1.a1.")
    with a.span("s"):
        pass
    with b.span("s"):
        pass
    ids = {a.drain()[0]["span_id"], b.drain()[0]["span_id"]}
    assert len(ids) == 2


def test_chrome_roundtrip(tmp_path):
    t = Tracer(trace_id="deadbeef", pid=0)
    with t.span("query q1", cat="query", args={"fingerprint": "f"}):
        with t.span("stage map s1", cat="stage"):
            pass
    t.absorb([{"name": "task", "cat": "task", "span_id": "w.1",
               "parent_id": None, "ts": 1.0, "dur": 0.5, "pid": 2}])
    path = t.write_chrome(str(tmp_path))
    assert os.path.basename(path) == "trace-deadbeef.json"
    # the checker is the schema oracle
    assert _load_checker().check_trace(path) == []
    back = load_chrome_trace(path)
    by_name = {s["name"]: s for s in back}
    assert by_name["stage map s1"]["parent_id"] == \
        by_name["query q1"]["span_id"]
    assert by_name["task"]["pid"] == 2
    assert abs(by_name["task"]["dur"] - 0.5) < 1e-6
    # process metadata rows for driver + worker 1
    doc = json.load(open(path))
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M"}
    assert {"driver", "worker 1"} <= names


def test_summary_rolls_up_by_category():
    t = Tracer()
    t.emit("a", "shuffle", 0.0, 2.0)
    t.emit("b", "shuffle", 0.0, 3.0)
    t.emit("c", "op", 0.0, 1.0)
    s = t.summary()
    assert s["spans"] == 3
    assert s["by_cat"]["shuffle"] == {"spans": 2, "total_s": 5.0}


# --- metrics registry -------------------------------------------------------

def test_counter_gauge_histogram_basics():
    r = MetricsRegistry()
    c = r.counter("c_total", "help", ("k",))
    c.labels("x").inc()
    c.labels("x").inc(2)
    g = r.gauge("g")
    g.set(5)
    g.dec(2)
    h = r.histogram("h_seconds", buckets=(0.1, 1.0, float("inf")))
    for v in (0.05, 0.5, 10.0):
        h.observe(v)
    snap = r.snapshot()
    assert snap["c_total"]["samples"]["x"] == 3
    assert snap["g"]["samples"][""] == 3
    hs = snap["h_seconds"]["samples"][""]
    # bucket counts are CUMULATIVE (Prometheus histogram semantics)
    assert hs["count"] == 3 and hs["counts"] == [1, 2, 3]
    assert abs(hs["sum"] - 10.55) < 1e-9


def test_family_redeclaration_idempotent_kind_checked():
    r = MetricsRegistry()
    assert r.counter("x") is r.counter("x")
    with pytest.raises(ValueError):
        r.gauge("x")


def test_bounded_label_sets_overflow_to_other():
    from spark_rapids_tpu.obs.metrics import MAX_CHILDREN, _OTHER
    r = MetricsRegistry()
    c = r.counter("c", "", ("id",))
    for i in range(MAX_CHILDREN + 10):
        c.labels(f"id{i}").inc()
    snap = r.snapshot()["c"]["samples"]
    assert len(snap) == MAX_CHILDREN + 1
    assert snap[_OTHER] == 10  # the overflow collapsed into one series


def test_prometheus_text_valid_per_checker():
    r = MetricsRegistry()
    r.counter("rapids_test_total", 'escapes "quoted" help',
              ("a",)).labels('v"1"').inc()
    r.histogram("rapids_wait_seconds").observe(0.2)
    text = dump_prometheus(r)
    assert _load_checker().check_prometheus(text) == []
    assert "# TYPE rapids_test_total counter" in text
    assert 'le="+Inf"' in text


def test_merged_snapshots_proc_labels():
    d, w = MetricsRegistry(), MetricsRegistry()
    d.counter("c_total").inc(1)
    w.counter("c_total").inc(41)
    text = render_merged_snapshots([("driver", d.snapshot()),
                                    ("w0", w.snapshot())])
    assert 'c_total{proc="driver"} 1' in text
    assert 'c_total{proc="w0"} 41' in text
    # one TYPE line per family, not per process
    assert text.count("# TYPE c_total") == 1
    assert _load_checker().check_prometheus(text) == []


def test_http_metrics_endpoint():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    from spark_rapids_tpu.obs import metrics as M
    conf = RapidsConf({"spark.rapids.metrics.port": port})
    bound = M.maybe_start_http_server(conf)
    if bound is None and M._http_server == "failed":
        pytest.skip("port raced away")
    assert bound == port
    # idempotent: second call reuses the server
    assert M.maybe_start_http_server(conf) == port
    M.REGISTRY.counter("rapids_http_test_total").inc()
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
    assert _load_checker().check_prometheus(body) == []
    assert urllib.request.urlopen(
        f"http://127.0.0.1:{port}/", timeout=5).status == 200
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(f"http://127.0.0.1:{port}/nope",
                               timeout=5)


def test_worker_snapshot_flush_and_read(tmp_path):
    from spark_rapids_tpu.obs.metrics import (flush_worker_metrics,
                                              read_worker_metrics)
    r = MetricsRegistry()
    r.counter("n_total").inc(7)
    flush_worker_metrics(str(tmp_path), 0, r)
    # a torn snapshot must not break the merge
    with open(os.path.join(str(tmp_path), "metrics", "w1.json"),
              "w") as f:
        f.write('{"torn":')
    tagged = read_worker_metrics(str(tmp_path))
    assert [t for t, _ in tagged] == ["w0"]
    assert tagged[0][1]["n_total"]["samples"][""] == 7


# --- critical path ----------------------------------------------------------

def _span(name, cat, sid, parent, ts, dur, pid=0, args=None):
    return {"name": name, "cat": cat, "span_id": sid, "parent_id": parent,
            "ts": ts, "dur": dur, "pid": pid, "args": args or {}}


def test_critical_path_follows_dominant_child():
    spans = [
        _span("query", "query", "q", None, 0.0, 10.0),
        _span("stage 1", "stage", "s1", "q", 0.0, 2.0),
        _span("stage 2", "stage", "s2", "q", 2.0, 7.0),
        _span("shuffle_fetch", "shuffle", "f", "s2", 2.0, 6.2, pid=1),
    ]
    path = critical_path(spans)
    assert [p["name"] for p in path] == ["query", "stage 2",
                                         "shuffle_fetch"]
    leaf = path[-1]
    assert leaf["self_s"] == pytest.approx(6.2)
    assert leaf["frac"] == pytest.approx(0.62)
    text = "\n".join(format_critical_path(spans))
    assert "62% of wall time is shuffle_fetch (shuffle)" in text


def test_critical_path_names_retry_overhead():
    spans = [
        _span("query", "query", "q", None, 0.0, 10.0),
        _span("attempt t1 a0", "attempt", "t1.a0", "q", 0.0, 4.0,
              pid=1, args={"state": "err"}),
        _span("attempt t1 a1", "attempt", "t1.a1", "q", 4.0, 6.0,
              pid=2, args={"state": "ok"}),
    ]
    text = "\n".join(format_critical_path(spans))
    assert "retry overhead" in text and "attempt t1 a0" in text
    assert "40% of wall" in text


def test_critical_path_empty_and_orphans():
    assert critical_path([]) == []
    # orphan parents (dropped spans) must not crash the miner
    spans = [_span("a", "op", "1", "gone", 0.0, 1.0)]
    assert [p["name"] for p in critical_path(spans)] == ["a"]


# --- hotspot keying on stable instance ids (satellite) ----------------------
# The old name-based dedup across AQE-duplicated instance labels is
# GONE: planner-assigned #op<N> ids make AQE deep copies of a reused
# sub-plan accumulate into one metric row at the store itself, while
# two genuinely distinct instances of the same operator class rank as
# separate hotspots (per-instance attribution).

def test_profile_report_keys_hotspots_on_stable_instance_ids():
    from spark_rapids_tpu.exec.base import TpuMetric
    from spark_rapids_tpu.exec import HostBatchSourceExec, TpuProjectExec
    from spark_rapids_tpu.expr import Alias, UnresolvedColumn as col
    from spark_rapids_tpu.planner import overrides
    from spark_rapids_tpu.tools import profile_report
    src = HostBatchSourceExec([gen_table([IntegerGen()], 50, seed=1)])
    pp = overrides(TpuProjectExec([Alias(col("c0"), "x")], src),
                   RapidsConf())
    pp.collect()
    ctx = pp.last_ctx
    # an AQE-reused exchange keeps ONE stable label, so both uses hit
    # the same store entry; a second exchange instance keeps its own
    for label, v in (("ShuffleExchangeExec#op90", 0.75),
                     ("ShuffleExchangeExec#op91", 0.25)):
        m = TpuMetric("opTime")
        m.value = v
        ctx.metrics[label] = {"opTime": m}
    rep = profile_report(pp)
    assert "ShuffleExchangeExec#op90" in rep
    assert "ShuffleExchangeExec#op91" in rep
    assert "(x2)" not in rep  # the merge hack is gone
    assert "750.00ms" in rep and "250.00ms" in rep


# --- event-log reader guarantees (satellite) --------------------------------

def test_read_event_logs_tolerates_torn_last_line(tmp_path):
    from spark_rapids_tpu.tools.event_log import read_event_logs
    p = tmp_path / "app-1-1.jsonl"
    p.write_text(json.dumps({"a": 1}) + "\n"
                 + json.dumps({"b": 2}) + "\n"
                 + '{"torn": tru')  # crashed writer mid-line
    evs = list(read_event_logs(str(tmp_path)))
    assert evs == [{"a": 1}, {"b": 2}]


def test_plan_fingerprint_stable_and_sensitive():
    from spark_rapids_tpu.exec import HostBatchSourceExec, TpuProjectExec
    from spark_rapids_tpu.expr import Alias, UnresolvedColumn as col
    from spark_rapids_tpu.tools.event_log import plan_fingerprint

    def build(extra_project):
        src = HostBatchSourceExec([gen_table([IntegerGen()], 10, seed=1)])
        plan = TpuProjectExec([Alias(col("c0"), "x")], src)
        if extra_project:
            plan = TpuProjectExec([Alias(col("x"), "y")], plan)
        return plan

    # stable across runs: instance ids (#N) differ between the two
    # builds but must not leak into the fingerprint
    assert plan_fingerprint(build(False)) == plan_fingerprint(build(False))
    # sensitive to the operator tree
    assert plan_fingerprint(build(False)) != plan_fingerprint(build(True))


# --- ML path query events (satellite) ---------------------------------------

def test_ml_path_emits_query_events(tmp_path):
    from spark_rapids_tpu import TpuSession
    from spark_rapids_tpu.ml import columnar_rdd, to_feature_matrix
    from spark_rapids_tpu.tools.event_log import read_event_logs
    trace_dir = str(tmp_path / "traces")
    s = TpuSession({"spark.rapids.eventLog.dir": str(tmp_path),
                    "spark.rapids.trace.dir": trace_dir})
    df = s.create_dataframe({"a": [1.0, 2.0, 3.0], "b": [4, 5, 6]})
    list(columnar_rdd(df))
    to_feature_matrix(df, ["a"], "b")
    evs = [e for e in read_event_logs(str(tmp_path))
           if e.get("type") != "scheduler"]
    assert len(evs) == 2
    assert all("fingerprint" in e and e["wall_s"] > 0 for e in evs)
    # the embedded trace summary must reference a trace that EXISTS
    written = {n for n in os.listdir(trace_dir)}
    for e in evs:
        assert f"trace-{e['trace']['trace_id']}.json" in written


# --- the acceptance test: stitched trace across a worker crash --------------

def _crash_plan():
    """2-stage query (map shuffle + reduce agg), two source batches so
    the map stage splits across both workers."""
    from spark_rapids_tpu.exec.base import HostBatchSourceExec
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.expr import Alias, UnresolvedColumn as col
    from spark_rapids_tpu.expr.aggregates import Sum
    from spark_rapids_tpu.shuffle.partitioner import HashPartitioning
    rbs = [gen_table([IntegerGen(min_val=0, max_val=9, nullable=False),
                      LongGen(nullable=False)], n, seed=s,
                     names=["k", "v"])
           for n, s in [(400, 1), (350, 2)]]
    src = HostBatchSourceExec(rbs)
    exch = TpuShuffleExchangeExec(HashPartitioning([col("k")], 4), src)
    return TpuHashAggregateExec(
        [col("k")], [Alias(Sum(col("v")), "s")], exch)


def test_cluster_crash_produces_single_stitched_trace(tmp_path):
    """ISSUE acceptance: injected worker crash; ONE Chrome trace JSON
    holding driver query/stage spans, BOTH attempts of the crashed task
    (failed + retried) with correct parent linkage, and worker-side
    operator spans; metrics aggregate across processes; the trace
    profiler names the retry overhead."""
    from spark_rapids_tpu.cluster import TpuProcessCluster
    from spark_rapids_tpu.exec.base import ExecCtx
    trace_dir = str(tmp_path / "traces")
    conf = RapidsConf({
        "spark.rapids.tpu.test.injectFaults": "crash:q1s1m0:0",
        "spark.rapids.trace.dir": trace_dir,
        "spark.rapids.metrics.enabled": True,
    })
    plan = _crash_plan()
    with TpuProcessCluster(n_workers=2, conf=conf) as c:
        got = c.run_query(plan)
        trace_path = c.last_trace_path
        prom = c.prometheus_text()

    # correct results despite the crash
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_schema
    want = pa.Table.from_batches(
        list(plan.execute_cpu(ExecCtx())),
        schema=arrow_schema(plan.output_schema))
    key = lambda t: sorted(t.to_pylist(), key=lambda d: d["k"])
    assert key(got) == key(want)

    # ONE stitched trace file, schema-valid
    assert trace_path and os.path.dirname(trace_path) == trace_dir
    assert [n for n in os.listdir(trace_dir)
            if n.endswith(".json")] == [os.path.basename(trace_path)]
    assert _load_checker().check_trace(trace_path) == []

    spans = load_chrome_trace(trace_path)
    by_id = {s["span_id"]: s for s in spans}
    # driver query + stage spans
    query = [s for s in spans if s["cat"] == "query"]
    assert len(query) == 1 and query[0]["pid"] == 0
    stages = {s["name"]: s for s in spans if s["cat"] == "stage"}
    assert "stage map s1" in stages and "stage final" in stages
    assert all(s["parent_id"] == query[0]["span_id"]
               for s in stages.values())
    # both attempts of the crashed task, linked under the map stage
    atts = {s["name"]: s for s in spans if s["cat"] == "attempt"
            and "q1s1m0" in s["name"]}
    assert set(atts) == {"attempt q1s1m0 a0", "attempt q1s1m0 a1"}
    assert atts["attempt q1s1m0 a0"]["args"]["state"] == "err"
    assert atts["attempt q1s1m0 a1"]["args"]["state"] == "ok"
    for s in atts.values():
        assert by_id[s["parent_id"]]["name"] == "stage map s1"
    # the retried attempt ran on a worker: its task span parents onto
    # the deterministic attempt span id, and operator spans nest below
    task = [s for s in spans if s["cat"] == "task"
            and s["name"].startswith("task q1s1m0 a1")]
    assert len(task) == 1 and task[0]["pid"] > 0
    assert task[0]["parent_id"] == atts["attempt q1s1m0 a1"]["span_id"]
    ops = [s for s in spans if s["cat"] == "op" and s["pid"] > 0]
    assert ops, "no worker-side operator spans"
    shuf = [s for s in spans if s["cat"] == "shuffle" and s["pid"] > 0]
    assert any(s["name"].startswith("shuffle_write") for s in shuf)

    # cross-process metrics: driver scheduler counters + worker flushes
    assert _load_checker().check_prometheus(prom) == []
    assert ('rapids_scheduler_events_total{event="task_failed",'
            'proc="driver"}') in prom
    assert 'proc="w' in prom
    assert "rapids_shuffle_partitions_written_total" in prom

    # the critical-path miner names the retry overhead
    rep = profile_trace(trace_path)
    assert "retry overhead" in rep and "attempt q1s1m0 a0" in rep


def test_cluster_trace_disabled_has_zero_surface(tmp_path):
    """With tracing off nothing is written and task payloads carry no
    trace context (the near-zero-overhead-when-disabled guarantee)."""
    from spark_rapids_tpu.cluster import TpuProcessCluster
    plan = _crash_plan()
    with TpuProcessCluster(n_workers=2) as c:
        c.run_query(plan)
        assert c.last_trace_path is None
        assert c.last_scheduler.tracer is NULL_TRACER \
            or not c.last_scheduler.tracer.enabled
