"""Seeded typed data generators — the property-based backbone of the
dual-run equivalence harness (reference: integration_tests data_gen.py —
SURVEY.md §4.1; built from capability description, mount empty).

Each generator produces a pyarrow array with configurable null fraction and
the nasty special values (NaN, ±0.0, INT_MIN/MAX, empty/unicode strings).
"""
from __future__ import annotations

import datetime
import decimal
import string as _string

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import datatypes as dt

DEFAULT_SEED = 1234


class DataGen:
    def __init__(self, dtype: dt.DataType, nullable=True, null_frac=0.1):
        self.dtype = dtype
        self.nullable = nullable
        self.null_frac = null_frac if nullable else 0.0

    def _nulls(self, rng, n):
        if not self.null_frac:
            return None
        return rng.random(n) < self.null_frac

    def generate(self, rng: np.random.Generator, n: int) -> pa.Array:
        vals = self._values(rng, n)
        nulls = self._nulls(rng, n)
        if nulls is not None:
            vals = [None if m else v for v, m in zip(vals, nulls)]
        return pa.array(vals, type=dt.to_arrow(self.dtype))


class IntegerGen(DataGen):
    def __init__(self, dtype=dt.INT32, nullable=True, null_frac=0.1,
                 min_val=None, max_val=None, special=True):
        super().__init__(dtype, nullable, null_frac)
        info = np.iinfo(dtype.np_dtype)
        self.min_val = info.min if min_val is None else min_val
        self.max_val = info.max if max_val is None else max_val
        self.special = special and min_val is None and max_val is None

    def _values(self, rng, n):
        out = rng.integers(self.min_val, self.max_val, size=n,
                           endpoint=True, dtype=np.int64).tolist()
        if self.special and n >= 4:
            out[0], out[1], out[2] = self.min_val, self.max_val, 0
        return out


class LongGen(IntegerGen):
    def __init__(self, **kw):
        kw.setdefault("dtype", dt.INT64)
        super().__init__(**kw)


class ByteGen(IntegerGen):
    def __init__(self, **kw):
        kw.setdefault("dtype", dt.INT8)
        super().__init__(**kw)


class ShortGen(IntegerGen):
    def __init__(self, **kw):
        kw.setdefault("dtype", dt.INT16)
        super().__init__(**kw)


class BooleanGen(DataGen):
    def __init__(self, nullable=True, null_frac=0.1):
        super().__init__(dt.BOOL, nullable, null_frac)

    def _values(self, rng, n):
        return rng.integers(0, 2, n).astype(bool).tolist()


class FloatGen(DataGen):
    def __init__(self, dtype=dt.FLOAT64, nullable=True, null_frac=0.1,
                 special=True, no_nans=False):
        super().__init__(dtype, nullable, null_frac)
        self.special = special
        self.no_nans = no_nans

    def _values(self, rng, n):
        lane = self.dtype.np_dtype
        out = (rng.standard_normal(n) *
               rng.choice([1.0, 100.0, 1e6], n)).astype(lane).tolist()
        if self.special and n >= 6:
            out[0], out[1], out[2] = 0.0, -0.0, 1.0
            if not self.no_nans:
                out[3] = float("nan")
                out[4] = float("inf")
                out[5] = float("-inf")
        return out


class DoubleGen(FloatGen):
    pass


class StringGen(DataGen):
    def __init__(self, nullable=True, null_frac=0.1, max_len=20,
                 charset=None, special=True, ascii_only=False):
        super().__init__(dt.STRING, nullable, null_frac)
        self.max_len = max_len
        self.charset = charset or (_string.ascii_letters + _string.digits
                                   + " ,.;-_")
        self.special = special
        self.ascii_only = ascii_only

    def _values(self, rng, n):
        lens = rng.integers(0, self.max_len, n)
        chars = np.array(list(self.charset))
        out = ["".join(rng.choice(chars, size=l)) for l in lens]
        if self.special and n >= 4:
            out[0] = ""
            out[1] = "A" * self.max_len
            if not self.ascii_only:
                out[2] = "héllo wörld"
                out[3] = "日本語"
        return out


class DecimalGen(DataGen):
    def __init__(self, precision=10, scale=2, nullable=True, null_frac=0.1):
        super().__init__(dt.DecimalType(precision, scale), nullable,
                         null_frac)

    def _values(self, rng, n):
        p, s = self.dtype.precision, self.dtype.scale
        lim = 10 ** p - 1
        unscaled = rng.integers(-lim, lim, size=n, endpoint=True)
        q = decimal.Decimal(1).scaleb(-s)
        return [decimal.Decimal(int(u)).scaleb(-s).quantize(q)
                for u in unscaled]


class DateGen(DataGen):
    def __init__(self, nullable=True, null_frac=0.1,
                 start_days=-25567, end_days=40000):  # 1900..2079
        super().__init__(dt.DATE, nullable, null_frac)
        self.start_days, self.end_days = start_days, end_days

    def _values(self, rng, n):
        days = rng.integers(self.start_days, self.end_days, n)
        epoch = datetime.date(1970, 1, 1)
        return [epoch + datetime.timedelta(days=int(d)) for d in days]


class TimestampGen(DataGen):
    def __init__(self, nullable=True, null_frac=0.1):
        super().__init__(dt.TIMESTAMP, nullable, null_frac)

    def _values(self, rng, n):
        us = rng.integers(-2208988800_000_000, 3250368000_000_000, n)
        return [datetime.datetime.fromtimestamp(
            int(u) / 1e6, tz=datetime.timezone.utc) for u in us]


class ArrayGen(DataGen):
    """array<element> with configurable length range; sub-generator
    drives the element values (nested-type gens — SURVEY.md §4.1)."""

    def __init__(self, element_gen: DataGen, nullable=True, null_frac=0.1,
                 max_len=6):
        super().__init__(dt.ArrayType(element_gen.dtype), nullable,
                         null_frac)
        self.element_gen = element_gen
        self.max_len = max_len

    def _values(self, rng, n):
        lens = rng.integers(0, self.max_len, n)
        out = []
        for l in lens:
            out.append(self.element_gen.generate(rng, int(l)).to_pylist())
        if n >= 2:
            out[0] = []  # empty array special
        return out

    def generate(self, rng, n):
        vals = self._values(rng, n)
        nulls = self._nulls(rng, n)
        if nulls is not None:
            vals = [None if m else v for v, m in zip(vals, nulls)]
        return pa.array(vals, type=dt.to_arrow(self.dtype))


class StructGen(DataGen):
    def __init__(self, fields, nullable=True, null_frac=0.1):
        """fields: list of (name, DataGen)."""
        self.field_gens = list(fields)
        st = dt.StructType([dt.StructField(n, g.dtype, g.nullable)
                            for n, g in self.field_gens])
        super().__init__(st, nullable, null_frac)

    def generate(self, rng, n):
        children = {name: g.generate(rng, n).to_pylist()
                    for name, g in self.field_gens}
        vals = [{name: children[name][i] for name, _ in self.field_gens}
                for i in range(n)]
        nulls = self._nulls(rng, n)
        if nulls is not None:
            vals = [None if m else v for v, m in zip(vals, nulls)]
        return pa.array(vals, type=dt.to_arrow(self.dtype))


class MapGen(DataGen):
    def __init__(self, key_gen: DataGen, value_gen: DataGen,
                 nullable=True, null_frac=0.1, max_len=4):
        super().__init__(dt.MapType(key_gen.dtype, value_gen.dtype),
                         nullable, null_frac)
        self.key_gen = key_gen
        self.value_gen = value_gen
        self.max_len = max_len

    def generate(self, rng, n):
        lens = rng.integers(0, self.max_len, n)
        vals = []
        for l in lens:
            l = int(l)
            ks = self.key_gen.generate(rng, l).to_pylist()
            vs = self.value_gen.generate(rng, l).to_pylist()
            # map keys must be unique and non-null
            seen, items = set(), []
            for k, v in zip(ks, vs):
                if k is None or k in seen:
                    continue
                seen.add(k)
                items.append((k, v))
            vals.append(items)
        nulls = self._nulls(rng, n)
        if nulls is not None:
            vals = [None if m else v for v, m in zip(vals, nulls)]
        return pa.array(vals, type=dt.to_arrow(self.dtype))


# canonical generator sets, mirroring the reference's groupings
numeric_gens = [ByteGen(), ShortGen(), IntegerGen(), LongGen(),
                FloatGen(dt.FLOAT32), FloatGen(dt.FLOAT64)]
integral_gens = [ByteGen(), ShortGen(), IntegerGen(), LongGen()]
all_basic_gens = numeric_gens + [BooleanGen(), StringGen(), DateGen(),
                                 TimestampGen(), DecimalGen()]


def gen_table(gens, n=256, seed=DEFAULT_SEED, names=None) -> pa.RecordBatch:
    """Build a RecordBatch from generators (column per gen)."""
    rng = np.random.default_rng(seed)
    arrays = [g.generate(rng, n) for g in gens]
    names = names or [f"c{i}" for i in range(len(gens))]
    return pa.record_batch(dict(zip(names, arrays)))
