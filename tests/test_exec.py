"""Physical operator tests: project / filter / range via the plan-level
dual-run harness (reference: basicPhysicalOperators tests — SURVEY.md §4)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.exec import (HostBatchSourceExec, TpuFilterExec,
                                   TpuProjectExec, TpuRangeExec)
from spark_rapids_tpu.expr import (Add, Alias, And, Cast, GreaterThan,
                                   IsNotNull, LessThan, Literal, Multiply,
                                   UnresolvedColumn as col)

from asserts import assert_tpu_and_cpu_plan_equal
from data_gen import (BooleanGen, DoubleGen, FloatGen, IntegerGen, LongGen,
                      StringGen, all_basic_gens, gen_table)


def source(gens, n=256, seed=1234, names=None):
    return HostBatchSourceExec([gen_table(gens, n, seed, names)])


def test_project_arithmetic():
    plan = TpuProjectExec(
        [Alias(Add(col("c0"), col("c1")), "s"),
         Alias(Multiply(col("c0"), Literal(3)), "m")],
        source([IntegerGen(), IntegerGen()]))
    assert_tpu_and_cpu_plan_equal(plan)


def test_project_identity_all_types():
    gens = all_basic_gens
    names = [f"c{i}" for i in range(len(gens))]
    plan = TpuProjectExec([col(n) for n in names], source(gens, names=names))
    assert_tpu_and_cpu_plan_equal(plan)


def test_filter_simple():
    plan = TpuFilterExec(
        GreaterThan(col("c0"), Literal(0)),
        source([IntegerGen(), StringGen(), DoubleGen()]))
    assert_tpu_and_cpu_plan_equal(plan)


def test_filter_null_predicate_drops():
    # Nullable comparison: null predicate rows must be dropped, not kept.
    plan = TpuFilterExec(
        LessThan(col("c0"), col("c1")),
        source([IntegerGen(null_frac=0.3), IntegerGen(null_frac=0.3)]))
    assert_tpu_and_cpu_plan_equal(plan)


def test_filter_compound_and_project():
    src = source([IntegerGen(), DoubleGen(), StringGen()])
    filt = TpuFilterExec(
        And(IsNotNull(col("c1")), GreaterThan(col("c0"), Literal(-100))),
        src)
    plan = TpuProjectExec(
        [Alias(Add(col("c0"), Literal(1)), "a"), col("c2")], filt)
    assert_tpu_and_cpu_plan_equal(plan)


def test_filter_none_pass():
    plan = TpuFilterExec(Literal(False), source([IntegerGen()]))
    assert_tpu_and_cpu_plan_equal(plan)


def test_filter_all_pass():
    plan = TpuFilterExec(Literal(True), source([IntegerGen(), StringGen()]))
    assert_tpu_and_cpu_plan_equal(plan)


def test_filter_strings_compact():
    plan = TpuFilterExec(col("c1"),
                         source([StringGen(null_frac=0.2), BooleanGen()]))
    assert_tpu_and_cpu_plan_equal(plan)


def test_range_basic():
    assert_tpu_and_cpu_plan_equal(TpuRangeExec(0, 1000))


def test_range_step_negative():
    assert_tpu_and_cpu_plan_equal(TpuRangeExec(100, -5, -3))


def test_range_multi_batch():
    assert_tpu_and_cpu_plan_equal(
        TpuRangeExec(0, 5000, 7, max_rows_per_batch=1024))


def test_range_empty():
    assert_tpu_and_cpu_plan_equal(TpuRangeExec(10, 10))


def test_range_filter_project_q6_shape():
    # TPC-H q6 shape over range data: scan -> filter -> project.
    rng = TpuRangeExec(0, 4096)
    filt = TpuFilterExec(
        And(GreaterThan(col("id"), Literal(100, dt.INT64)),
            LessThan(col("id"), Literal(4000, dt.INT64))), rng)
    plan = TpuProjectExec(
        [Alias(Multiply(Cast(col("id"), dt.FLOAT64), Literal(0.07)), "rev")],
        filt)
    assert_tpu_and_cpu_plan_equal(plan, approx_float=True)


def test_multi_batch_source():
    rbs = [gen_table([IntegerGen(), StringGen()], n, seed=s)
           for n, s in [(100, 1), (57, 2), (300, 3)]]
    plan = TpuFilterExec(GreaterThan(col("c0"), Literal(0)),
                         HostBatchSourceExec(rbs))
    assert_tpu_and_cpu_plan_equal(plan)


# --- union / expand / sample ----------------------------------------------

def test_union_all():
    from spark_rapids_tpu.exec import TpuUnionExec
    kids = [HostBatchSourceExec([gen_table([IntegerGen(), StringGen()],
                                           n, seed=s)])
            for n, s in [(80, 1), (50, 2), (120, 3)]]
    plan = TpuUnionExec(kids)
    assert_tpu_and_cpu_plan_equal(plan)


def test_expand_grouping_sets_shape():
    from spark_rapids_tpu.exec import TpuExpandExec
    from spark_rapids_tpu.expr import Literal
    src = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=5),
                    IntegerGen(min_val=0, max_val=3),
                    LongGen()], 150, seed=4)])
    # ROLLUP(c0, c1)-style projections with a grouping-id literal
    projections = [
        [col("c0"), col("c1"), col("c2"), Literal(0, dt.INT32)],
        [col("c0"), Literal(None, dt.INT32), col("c2"),
         Literal(1, dt.INT32)],
        [Literal(None, dt.INT32), Literal(None, dt.INT32), col("c2"),
         Literal(3, dt.INT32)],
    ]
    plan = TpuExpandExec(projections, ["c0", "c1", "c2", "gid"], src)
    assert_tpu_and_cpu_plan_equal(plan)


def test_expand_feeds_rollup_aggregate():
    from spark_rapids_tpu.exec import TpuExpandExec
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.expr import Alias, Literal
    from spark_rapids_tpu.expr.aggregates import Sum
    src = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=4), LongGen()], 200,
                   seed=6)])
    exp = TpuExpandExec(
        [[col("c0"), col("c1"), Literal(0, dt.INT32)],
         [Literal(None, dt.INT32), col("c1"), Literal(1, dt.INT32)]],
        ["c0", "c1", "gid"], src)
    plan = TpuHashAggregateExec([col("c0"), col("gid")],
                                [Alias(Sum(col("c1")), "s")], exp)
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


@pytest.mark.parametrize("fraction", [0.0, 0.3, 1.0])
def test_sample(fraction):
    from spark_rapids_tpu.exec import TpuSampleExec
    src = HostBatchSourceExec(
        [gen_table([IntegerGen(), StringGen()], 150, seed=s)
         for s in (1, 2)])
    plan = TpuSampleExec(fraction, seed=42, child=src)
    got = assert_tpu_and_cpu_plan_equal(plan)
    if fraction == 0.0:
        assert got.num_rows == 0
    if fraction == 1.0:
        assert got.num_rows == 300


def test_sample_deterministic():
    from spark_rapids_tpu.exec import TpuSampleExec
    from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow
    src = HostBatchSourceExec([gen_table([IntegerGen()], 200, seed=9)])
    a = collect_arrow(TpuSampleExec(0.5, 7, src), ExecCtx())
    b = collect_arrow(TpuSampleExec(0.5, 7, src), ExecCtx())
    assert a.to_pylist() == b.to_pylist()


def test_pallas_masked_product_sum_matches_xla():
    # interpret mode on the CPU mesh; the real-chip A/B lives in bench.py
    import numpy as np
    import jax.numpy as jnp
    from spark_rapids_tpu.ops.pallas_kernels import (
        masked_product_sum_pallas, masked_product_sum_xla)
    n = 2048 * 128
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.uniform(1, 50, n).astype(np.float32))
    p = jnp.asarray(rng.uniform(900, 105000, n).astype(np.float32))
    d = jnp.asarray((rng.integers(0, 11, n) / 100.0).astype(np.float32))
    s = jnp.asarray(rng.integers(8000, 10600, n).astype(np.int32))
    want = float(masked_product_sum_xla(q, p, d, s))
    got = float(masked_product_sum_pallas(q, p, d, s, True))
    assert abs(got - want) <= 1e-3 * max(1.0, abs(want)), (got, want)


def test_pallas_bitonic_sort_matches_xla():
    # interpret mode on the CPU mesh; the real-chip A/B lives in
    # bench.py (pallas_sort_ab)
    import numpy as np
    import jax.numpy as jnp
    from spark_rapids_tpu.ops.pallas_kernels import sort_pallas, sort_xla
    rng = np.random.default_rng(5)
    for n, dtype in ((256, np.float32), (4096, np.float32),
                     (1024, np.int32)):
        if dtype == np.float32:
            k = rng.uniform(-1e6, 1e6, n).astype(dtype)
        else:
            k = rng.integers(-10**6, 10**6, n).astype(dtype)
        got = np.asarray(sort_pallas(jnp.asarray(k), True))
        want = np.asarray(sort_xla(jnp.asarray(k)))
        assert (got == want).all(), (n, dtype)
    # non-power-of-two and tiny inputs are rejected, not silently wrong
    import pytest as _pytest
    with _pytest.raises(ValueError):
        sort_pallas(jnp.zeros(300, jnp.float32), True)
