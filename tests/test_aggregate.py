"""Group-by / aggregate tests via the dual-run harness
(reference: hash_aggregate_test.py — SURVEY.md §4.1)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.exec import HostBatchSourceExec
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.expr import Alias, UnresolvedColumn as col
from spark_rapids_tpu.expr.aggregates import (Average, Count, First, Last,
                                              Max, Min, StddevPop,
                                              StddevSamp, Sum, VariancePop,
                                              VarianceSamp)

from asserts import assert_tpu_and_cpu_plan_equal
from data_gen import (BooleanGen, ByteGen, DateGen, DecimalGen, DoubleGen,
                      FloatGen, IntegerGen, LongGen, ShortGen, StringGen,
                      TimestampGen, gen_table)


def source(gens, n=256, seed=1234, names=None):
    return HostBatchSourceExec([gen_table(gens, n, seed, names)])


def kv_source(key_gen, val_gen, n=512, seed=7):
    return source([key_gen, val_gen], n, seed)


def agg_plan(src, keys, aggs):
    return TpuHashAggregateExec(keys, aggs, src)


key_gens = [IntegerGen(min_val=0, max_val=10), LongGen(),
            StringGen(max_len=6), DateGen(), BooleanGen(),
            DoubleGen(null_frac=0.2)]


@pytest.mark.parametrize("kg", key_gens,
                         ids=lambda g: g.dtype.simple_string())
def test_groupby_count_star(kg):
    plan = agg_plan(kv_source(kg, IntegerGen()), [col("c0")],
                    [Alias(Count(), "cnt")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


@pytest.mark.parametrize("vg", [ByteGen(), ShortGen(), IntegerGen(),
                                LongGen(), FloatGen(dt.FLOAT32),
                                DoubleGen()],
                         ids=lambda g: g.dtype.simple_string())
def test_groupby_sum(vg):
    plan = agg_plan(
        kv_source(IntegerGen(min_val=0, max_val=20), vg),
        [col("c0")], [Alias(Sum(col("c1")), "s")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True,
                                  approx_float=True)


def test_groupby_sum_decimal():
    plan = agg_plan(
        kv_source(IntegerGen(min_val=0, max_val=10),
                  DecimalGen(precision=7, scale=2)),
        [col("c0")], [Alias(Sum(col("c1")), "s")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


@pytest.mark.parametrize("vg", [IntegerGen(null_frac=0.3), LongGen(),
                                DoubleGen(), DateGen(), TimestampGen(),
                                BooleanGen()],
                         ids=lambda g: g.dtype.simple_string())
def test_groupby_min_max(vg):
    plan = agg_plan(
        kv_source(IntegerGen(min_val=0, max_val=15), vg),
        [col("c0")],
        [Alias(Min(col("c1")), "mn"), Alias(Max(col("c1")), "mx")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_groupby_avg():
    plan = agg_plan(
        kv_source(IntegerGen(min_val=0, max_val=12), LongGen()),
        [col("c0")], [Alias(Average(col("c1")), "a")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True,
                                  approx_float=True)


def test_groupby_avg_decimal():
    plan = agg_plan(
        kv_source(IntegerGen(min_val=0, max_val=5),
                  DecimalGen(precision=4, scale=1)),
        [col("c0")], [Alias(Average(col("c1")), "a")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_groupby_count_column():
    plan = agg_plan(
        kv_source(IntegerGen(min_val=0, max_val=8),
                  IntegerGen(null_frac=0.4)),
        [col("c0")],
        [Alias(Count(col("c1")), "c"), Alias(Count(), "cs")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_groupby_first_last():
    # first/last are order-dependent: make values unique per key via a
    # single-batch source with ignore_nulls both ways
    plan = agg_plan(
        kv_source(IntegerGen(min_val=0, max_val=6, nullable=False),
                  IntegerGen(null_frac=0.5), n=64),
        [col("c0")],
        [Alias(First(col("c1"), ignore_nulls=True), "f"),
         Alias(Last(col("c1"), ignore_nulls=True), "l")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_groupby_stddev_variance():
    plan = agg_plan(
        kv_source(IntegerGen(min_val=0, max_val=10),
                  DoubleGen(special=False)),
        [col("c0")],
        [Alias(StddevSamp(col("c1")), "ss"),
         Alias(StddevPop(col("c1")), "sp"),
         Alias(VarianceSamp(col("c1")), "vs"),
         Alias(VariancePop(col("c1")), "vp")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True,
                                  approx_float=True)


def test_groupby_multi_key():
    plan = agg_plan(
        source([IntegerGen(min_val=0, max_val=4), StringGen(max_len=3),
                BooleanGen(), LongGen()], n=512),
        [col("c0"), col("c1"), col("c2")],
        [Alias(Sum(col("c3")), "s"), Alias(Count(), "c")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_groupby_float_key_specials():
    # NaN groups as one; -0.0 and 0.0 group together
    plan = agg_plan(
        kv_source(DoubleGen(null_frac=0.2), IntegerGen()),
        [col("c0")], [Alias(Count(), "c")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_groupby_null_keys_group():
    plan = agg_plan(
        kv_source(IntegerGen(null_frac=0.5), LongGen()),
        [col("c0")], [Alias(Sum(col("c1")), "s"), Alias(Count(), "c")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_global_agg():
    plan = agg_plan(
        source([IntegerGen(), DoubleGen()], n=300), [],
        [Alias(Sum(col("c0")), "s"), Alias(Count(), "c"),
         Alias(Min(col("c0")), "mn"), Alias(Max(col("c1")), "mx"),
         Alias(Average(col("c0")), "a")])
    assert_tpu_and_cpu_plan_equal(plan, approx_float=True)


def test_global_agg_empty_input():
    empty = pa.record_batch(
        {"c0": pa.array([], pa.int32()), "c1": pa.array([], pa.float64())})
    plan = agg_plan(HostBatchSourceExec([empty]), [],
                    [Alias(Sum(col("c0")), "s"), Alias(Count(), "c"),
                     Alias(Min(col("c1")), "mn")])
    assert_tpu_and_cpu_plan_equal(plan)


def test_groupby_empty_input():
    empty = pa.record_batch(
        {"c0": pa.array([], pa.int32()), "c1": pa.array([], pa.int64())})
    plan = agg_plan(HostBatchSourceExec([empty]), [col("c0")],
                    [Alias(Sum(col("c1")), "s")])
    assert_tpu_and_cpu_plan_equal(plan)


def test_groupby_multi_batch_merge():
    rbs = [gen_table([IntegerGen(min_val=0, max_val=10), LongGen()],
                     n, seed=s) for n, s in [(200, 1), (150, 2), (300, 3)]]
    plan = agg_plan(HostBatchSourceExec(rbs), [col("c0")],
                    [Alias(Sum(col("c1")), "s"), Alias(Count(), "c"),
                     Alias(Min(col("c1")), "mn"),
                     Alias(Max(col("c1")), "mx")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_groupby_string_keys_multi_batch():
    rbs = [gen_table([StringGen(max_len=4), IntegerGen()], n, seed=s)
           for n, s in [(120, 5), (180, 6)]]
    plan = agg_plan(HostBatchSourceExec(rbs), [col("c0")],
                    [Alias(Count(), "c"), Alias(Sum(col("c1")), "s")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_groupby_computed_key_with_nulls():
    # Regression: null==null must hold for computed group keys whose data
    # lane holds garbage under nulls.
    from spark_rapids_tpu.expr import Add
    plan = agg_plan(
        kv_source(IntegerGen(null_frac=0.4), IntegerGen(null_frac=0.4)),
        [Alias(Add(col("c0"), col("c1")), "k")],
        [Alias(Count(), "c")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_stddev_large_mean_no_cancellation():
    # Regression: sum/sumsq formulation catastrophically cancels when the
    # mean is large relative to the spread; Welford (n, mean, M2) must not.
    vals = [1e9, 1e9 + 1, 1e9 + 2, 1e9 + 3] * 3
    rb = pa.record_batch({"k": pa.array([0, 0, 0, 1, 1, 1] * 2, pa.int32()),
                          "v": pa.array(vals, pa.float64())})
    plan = agg_plan(HostBatchSourceExec([rb]), [col("k")],
                    [Alias(VarianceSamp(col("v")), "vs"),
                     Alias(StddevSamp(col("v")), "ss")])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True,
                                  approx_float=True)


def test_stddev_samp_single_element_group_is_null():
    """Spark 3.1+ (legacy.statisticalAggregate=false): sample stddev/var
    of a single value is NULL, not NaN (advisor round-1 medium)."""
    rb = pa.RecordBatch.from_arrays(
        [pa.array([1, 2, 2], pa.int32()),
         pa.array([5.0, 7.0, 9.0], pa.float64())], names=["c0", "c1"])
    for fn in (StddevSamp, VarianceSamp):
        plan = agg_plan(HostBatchSourceExec([rb]), [col("c0")],
                        [Alias(fn(col("c1")), "v")])
        assert_tpu_and_cpu_plan_equal(plan, ignore_order=True,
                                      approx_float=True)
        from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow
        out = collect_arrow(plan)
        by_key = dict(zip(out.column(0).to_pylist(),
                          out.column(1).to_pylist()))
        assert by_key[1] is None  # single-element group
        assert by_key[2] is not None


def test_decimal_sum_overflow_semantics():
    """Sum over wide decimals: the oracle follows Spark (overflow vs the
    REAL result precision p+10, up to 38), the device caps at 18 digits
    and flags itself unsupported for wider results (advisor round-1)."""
    import decimal
    from spark_rapids_tpu.expr.base import BoundReference, EvalCtx, ExprError
    # result decimal(28,0): device-unsupported, oracle returns true sum
    big = decimal.Decimal("900000000000000000")  # 9e17, precision 18
    rb = pa.RecordBatch.from_arrays(
        [pa.array([1, 1], pa.int32()),
         pa.array([big, big], pa.decimal128(18, 0))], names=["c0", "c1"])
    plan = agg_plan(HostBatchSourceExec([rb]), [col("c0")],
                    [Alias(Sum(col("c1")), "s")])
    assert plan.tpu_supported() is not None  # falls back, oracle rules
    from spark_rapids_tpu.exec.base import collect_arrow_cpu
    out = collect_arrow_cpu(plan)
    assert out.column(1).to_pylist() == [decimal.Decimal(2) * big]
    # direct oracle: overflow past precision 38 -> NULL / ANSI error
    s38 = Sum(BoundReference(0, dt.DecimalType(28, 0), True))
    huge = decimal.Decimal(10) ** 37 * 9  # 9e37; two of them pass 10^38
    assert s38.cpu_agg([huge, huge]) is None
    try:
        s38.cpu_agg([huge, huge], EvalCtx(ansi=True))
        assert False, "expected ExprError"
    except ExprError:
        pass
    # long sum ANSI overflow -> error; non-ANSI wraps like java
    slong = Sum(BoundReference(0, dt.INT64, True))
    wrap = slong.cpu_agg([2 ** 62, 2 ** 62])
    assert wrap == -(2 ** 63)
    try:
        slong.cpu_agg([2 ** 62, 2 ** 62], EvalCtx(ansi=True))
        assert False, "expected ExprError"
    except ExprError:
        pass


# --- collect_list / collect_set (single-pass, array results) ---------------

def _collect_plan(agg_cls, val_gen, n=200):
    from spark_rapids_tpu.expr.aggregates import CollectList, CollectSet
    from data_gen import gen_table
    src = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=6, null_frac=0.1),
                    val_gen], n, seed=31 + i) for i in range(2)])
    return TpuHashAggregateExec(
        [col("c0")], [Alias(agg_cls(col("c1")), "vals")], src)


@pytest.mark.parametrize("val_gen", [LongGen(null_frac=0.2),
                                     StringGen(max_len=5, null_frac=0.2),
                                     DoubleGen(null_frac=0.2)],
                         ids=["long", "string", "double"])
def test_collect_list(val_gen):
    from spark_rapids_tpu.expr.aggregates import CollectList
    assert_tpu_and_cpu_plan_equal(_collect_plan(CollectList, val_gen),
                                  ignore_order=True)


@pytest.mark.parametrize("val_gen", [LongGen(null_frac=0.2),
                                     StringGen(max_len=4, null_frac=0.2),
                                     DoubleGen(null_frac=0.2)],
                         ids=["long", "string", "double"])
def test_collect_set(val_gen):
    from spark_rapids_tpu.expr.aggregates import CollectSet
    assert_tpu_and_cpu_plan_equal(_collect_plan(CollectSet, val_gen),
                                  ignore_order=True)


def test_collect_mixed_with_other_aggs():
    from spark_rapids_tpu.expr.aggregates import CollectList
    from data_gen import gen_table
    src = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=4, null_frac=0.0),
                    LongGen(null_frac=0.1)], 150, seed=3)])
    plan = TpuHashAggregateExec(
        [col("c0")],
        [Alias(CollectList(col("c1")), "vals"),
         Alias(Sum(col("c1")), "s"), Alias(Count(), "n")], src)
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_collect_global_no_keys():
    from spark_rapids_tpu.expr.aggregates import CollectList, CollectSet
    from data_gen import gen_table
    src = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=9, null_frac=0.3)], 80,
                   seed=8)])
    for cls in (CollectList, CollectSet):
        plan = TpuHashAggregateExec([], [Alias(cls(col("c0")), "vals")],
                                    src)
        assert_tpu_and_cpu_plan_equal(plan)


# --- approx_percentile (SURVEY.md:177; exact sort-based build) ------------

def _percentile_plan(gen, pcts, n=300, keys=True):
    from spark_rapids_tpu.expr.aggregates import ApproxPercentile
    src = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=6, nullable=False),
                    gen], n, seed=17, names=["k", "v"])])
    keyexprs = [col("k")] if keys else []
    return TpuHashAggregateExec(
        keyexprs, [Alias(ApproxPercentile(col("v"), pcts), "p")], src)


@pytest.mark.parametrize("gen", [IntegerGen(null_frac=0.2), LongGen(),
                                 DoubleGen(null_frac=0.1),
                                 FloatGen(dt.FLOAT32)],
                         ids=lambda g: g.dtype.simple_string())
def test_approx_percentile_scalar(gen):
    plan = _percentile_plan(gen, 0.5)
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_approx_percentile_list_and_edges():
    plan = _percentile_plan(DoubleGen(null_frac=0.15),
                            [0.0, 0.25, 0.5, 0.9, 1.0])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_approx_percentile_global_and_all_null():
    from spark_rapids_tpu.expr.aggregates import ApproxPercentile
    import pyarrow as pa
    rb = pa.record_batch({"v": pa.array([None] * 8, pa.float64())})
    src = HostBatchSourceExec([rb])
    plan = TpuHashAggregateExec(
        [], [Alias(ApproxPercentile(col("v"), [0.5, 0.9]), "p")], src)
    assert_tpu_and_cpu_plan_equal(plan)
    plan2 = _percentile_plan(LongGen(nullable=False), 0.99, keys=False)
    assert_tpu_and_cpu_plan_equal(plan2)


def test_approx_percentile_rejects_strings():
    from spark_rapids_tpu.expr.aggregates import ApproxPercentile
    src = HostBatchSourceExec([gen_table([StringGen()], 10, 1,
                                         names=["s"])])
    plan = TpuHashAggregateExec(
        [], [Alias(ApproxPercentile(col("s"), 0.5), "p")], src)
    from spark_rapids_tpu.planner import TpuOverrides
    pp = TpuOverrides().apply(plan)
    assert pp.fallback_nodes(), "string percentile must fall back"


# --- mergeable percentile sketch (VERDICT r4 #6) ---------------------------

def _sketch_conf():
    from spark_rapids_tpu.config import RapidsConf
    return RapidsConf({"spark.rapids.sql.approxPercentile.exact":
                       "false"})


def _rank_error(got, data, p):
    """|rank(got) - p*n| / n, with rank = count of values <= got."""
    import numpy as np
    d = np.sort(np.asarray([v for v in data if v is not None]))
    n = len(d)
    lo = np.searchsorted(d, got, side="left")
    hi = np.searchsorted(d, got, side="right")
    target = max(int(np.ceil(p * n)) - 1, 0)
    if lo <= target < hi:
        return 0.0
    return min(abs(lo - target), abs(hi - 1 - target)) / max(n, 1)


def test_approx_percentile_mergeable_multibatch_rank_bound():
    """Sketch mode: percentile partials/merges across MANY batches; the
    result's rank error stays within the summary's bound (~2/K with one
    merge level)."""
    import numpy as np
    from spark_rapids_tpu.exec.base import ExecCtx
    from spark_rapids_tpu.expr.aggregates import ApproxPercentile
    from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
    import pyarrow as pa
    rng = np.random.default_rng(11)
    # 8 batches, skewed distribution, 2 group keys
    batches, all_vals = [], {0: [], 1: []}
    for b in range(8):
        k = rng.integers(0, 2, 500).astype(np.int32)
        v = (rng.lognormal(0, 2, 500) * 100).astype(np.int64)
        for kk, vv in zip(k, v):
            all_vals[int(kk)].append(int(vv))
        batches.append(pa.record_batch({"k": pa.array(k),
                                        "v": pa.array(v)}))
    src = HostBatchSourceExec(batches)
    agg = ApproxPercentile(col("v"), [0.1, 0.5, 0.9, 0.99])
    plan = TpuHashAggregateExec([col("k")], [Alias(agg, "p")], src)
    ctx = ExecCtx(_sketch_conf())
    outs = [device_to_arrow(b) for b in plan.execute(ctx)]
    t = pa.Table.from_batches(outs).to_pydict()
    assert sorted(t["k"]) == [0, 1]
    bound = 2.5 / agg.K  # one merge level + evaluate snap
    for kk, plist in zip(t["k"], t["p"]):
        for p, got in zip(agg.percentages, plist):
            err = _rank_error(got, all_vals[kk], p)
            assert err <= bound, (kk, p, got, err, bound)
            # sketch points are actual data values, never interpolated
            assert got in all_vals[kk]


def test_approx_percentile_mergeable_global_scalar():
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.exec.base import ExecCtx
    from spark_rapids_tpu.expr.aggregates import ApproxPercentile
    from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
    rng = np.random.default_rng(5)
    data = rng.normal(0, 1000, 3000)
    batches = [pa.record_batch({"v": pa.array(data[i::3])})
               for i in range(3)]
    agg = ApproxPercentile(col("v"), 0.5)
    plan = TpuHashAggregateExec([], [Alias(agg, "p")],
                                HostBatchSourceExec(batches))
    ctx = ExecCtx(_sketch_conf())
    outs = [device_to_arrow(b) for b in plan.execute(ctx)]
    got = outs[0].column("p")[0].as_py()
    assert _rank_error(got, list(data), 0.5) <= 2.5 / agg.K


def test_approx_percentile_sketch_exact_when_small():
    """n <= K per group: the summary holds every value, so even the
    sketch path reproduces the exact Spark rank answer."""
    import pyarrow as pa
    from spark_rapids_tpu.exec.base import ExecCtx
    from spark_rapids_tpu.expr.aggregates import ApproxPercentile
    from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
    vals = [5, 1, 9, 3, 7, None, 2]
    rb = pa.record_batch({"v": pa.array(vals, pa.int64())})
    agg = ApproxPercentile(col("v"), [0.0, 0.5, 1.0])
    plan = TpuHashAggregateExec([], [Alias(agg, "p")],
                                HostBatchSourceExec([rb]))
    ctx = ExecCtx(_sketch_conf())
    outs = [device_to_arrow(b) for b in plan.execute(ctx)]
    assert outs[0].column("p")[0].as_py() == [1, 3, 9]
