"""Tooling tier tests: udf-compiler, qualification, profiling,
supported-ops generation (reference: udf-compiler + tools modules —
SURVEY.md §2.2-F; capability-built, mount empty)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.exec import HostBatchSourceExec, TpuProjectExec
from spark_rapids_tpu.expr import Alias, UnresolvedColumn as col
from spark_rapids_tpu.tools import (compile_udf, generate_supported_ops,
                                    profile_report, qualify)

from asserts import assert_tpu_and_cpu_plan_equal
from data_gen import DoubleGen, IntegerGen, LongGen, StringGen, gen_table


def source(gens, n=120, seed=3):
    return HostBatchSourceExec([gen_table(gens, n, seed)])


def compiled(fn, cols_, src):
    return compile_udf(fn, cols_, schema=src.output_schema)


# --- udf compiler ----------------------------------------------------------

def test_udf_compile_arithmetic():
    src = source([IntegerGen(), IntegerGen()])
    c = compiled(lambda x, y: (x + y) * 2 - x / 4,
                 [col("c0"), col("c1")], src)
    assert c is not None
    plan = TpuProjectExec([Alias(c.expr, "out")], src)
    assert_tpu_and_cpu_plan_equal(plan, approx_float=True)


def test_udf_compile_conditional_and_math():
    from spark_rapids_tpu.tools.udf_compiler import trace_math as m

    def udf(x, y):
        return m.where(x > y, m.sqrt(abs(x)), y * 1.5)

    src = source([DoubleGen(), DoubleGen()])
    c = compiled(udf, [col("c0"), col("c1")], src)
    assert c is not None
    plan = TpuProjectExec([Alias(c.expr, "out")], src)
    assert_tpu_and_cpu_plan_equal(plan, approx_float=True)


def test_udf_compile_comparison_chain():
    src = source([IntegerGen(null_frac=0.2)])
    c = compiled(lambda x: (x > 3) & (x < 100) | (x == -1),
                 [col("c0")], src)
    assert c is not None
    plan = TpuProjectExec([Alias(c.expr, "flag")], src)
    assert_tpu_and_cpu_plan_equal(plan)


def test_udf_data_dependent_branch_falls_back():
    def bad(x):
        if x > 0:  # python branch on data: not compilable
            return x
        return -x
    assert compile_udf(bad, [col("c0")]) is None


def test_udf_unsupported_call_falls_back():
    import math
    assert compile_udf(lambda x: math.erf(x), [col("c0")]) is None


# --- qualification ---------------------------------------------------------

def test_qualification_full_acceleration():
    from spark_rapids_tpu.expr import Add, Literal
    plan = TpuProjectExec([Alias(Add(col("c0"), Literal(1, dt.INT32)),
                                 "x")], source([IntegerGen()]))
    rep = qualify(plan)
    assert rep.score == 1.0
    assert "fully accelerated" in rep.render()


def test_qualification_reports_fallbacks():
    from spark_rapids_tpu.exec.sort import SortOrder, TpuSortExec
    from data_gen import StructGen
    plan = TpuSortExec(
        [SortOrder(col("c0"))],
        source([StructGen([("a", IntegerGen())]), LongGen()]))
    rep = qualify(plan)
    assert rep.score < 1.0
    assert any("SortExec" in r for r in rep.fallback_reasons)


# --- profiling -------------------------------------------------------------

def test_profile_report_renders():
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.expr.aggregates import Sum
    from spark_rapids_tpu.planner import overrides
    conf = RapidsConf({"spark.rapids.sql.metrics.level": "DEBUG"})
    plan = TpuHashAggregateExec(
        [col("c0")], [Alias(Sum(col("c1")), "s")],
        source([IntegerGen(min_val=0, max_val=5), LongGen()], 200))
    pp = overrides(plan, conf)
    pp.collect()
    rep = profile_report(pp)
    assert "TPU profile" in rep
    assert "HashAggregateExec" in rep
    assert "hotspots" in rep


# --- supported-ops doc + config validation ---------------------------------

def test_generate_supported_ops():
    doc = generate_supported_ops()
    for name in ("TpuHashAggregateExec", "TpuWindowExec",
                 "TpuGenerateExec", "TpuShuffleExchangeExec",
                 "XxHash64", "WindowExpression", "GetStructField"):
        assert name in doc, name


def test_validate_configs_no_dead_confs():
    from spark_rapids_tpu.tools.api_validation import validate_configs
    out = validate_configs()
    assert len(out["checked"]) > 30
    # every registered conf must be consumed somewhere in the package
    assert out["unused"] == [], out["unused"]


def test_supported_ops_doc_in_sync():
    """SUPPORTED_OPS.md is generated, never handwritten: the committed
    file must match the live registry (regenerate with
    python -c "from spark_rapids_tpu.tools import generate_supported_ops;
    print(generate_supported_ops())")."""
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "SUPPORTED_OPS.md")) as f:
        committed = f.read().rstrip("\n")
    assert committed == generate_supported_ops().rstrip("\n"), \
        "SUPPORTED_OPS.md is stale; regenerate it"


# --- event logs + offline tools (VERDICT r4 missing #8) --------------------

def _run_logged_queries(tmp_path, sql_enabled=True):
    import pyarrow as pa

    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec.base import HostBatchSourceExec
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.exec.basic import TpuFilterExec
    from spark_rapids_tpu.expr import (Alias, GreaterThan, Literal,
                                       UnresolvedColumn as col)
    from spark_rapids_tpu.expr.aggregates import Count, Sum
    from spark_rapids_tpu import datatypes as dt
    from spark_rapids_tpu.planner import TpuOverrides
    import numpy as np
    log_dir = str(tmp_path / "events")
    conf = RapidsConf({
        "spark.rapids.eventLog.dir": log_dir,
        "spark.rapids.sql.enabled": str(sql_enabled).lower()})
    rng = np.random.default_rng(1)
    rb = pa.record_batch({
        "k": pa.array(rng.integers(0, 9, 500).astype(np.int32)),
        "v": pa.array(rng.integers(0, 100, 500).astype(np.int64))})
    for _ in range(2):  # two runs of the same fingerprint
        src = HostBatchSourceExec([rb])
        filt = TpuFilterExec(GreaterThan(col("v"), Literal(10, dt.INT64)),
                             src)
        agg = TpuHashAggregateExec(
            [col("k")], [Alias(Sum(col("v")), "s"),
                         Alias(Count(), "n")], filt)
        TpuOverrides(conf).apply(agg).collect()
    return log_dir


def test_event_log_written_and_profiled(tmp_path):
    from spark_rapids_tpu.tools.event_log import read_event_logs
    from spark_rapids_tpu.tools.profiling import profile_event_logs
    log_dir = _run_logged_queries(tmp_path)
    events = list(read_event_logs(log_dir))
    assert len(events) == 2
    assert events[0]["fingerprint"] == events[1]["fingerprint"]
    assert events[0]["nodes"] and events[0]["wall_s"] > 0
    report = profile_event_logs(log_dir)
    assert "operator coverage" in report
    assert "HashAggregateExec" in report


def test_event_log_qualification_cpu_run(tmp_path):
    """The reference tool's mode: logs from a CPU run (sql disabled)
    still carry would-be placement; qualification models the speedup."""
    from spark_rapids_tpu.tools.qualification import qualify_event_logs
    log_dir = _run_logged_queries(tmp_path, sql_enabled=False)
    rep = qualify_event_logs(log_dir)
    assert rep.queries == 2
    # sql.enabled=false tags every node ineligible -> est ~1x, and the
    # kill switch is the blocker
    assert rep.est_speedup <= 1.05
    assert any("spark.rapids.sql.enabled" in r for r in rep.top_blockers)
    out = rep.render()
    assert "estimated speedup" in out


def test_event_log_qualification_eligible_run(tmp_path):
    from spark_rapids_tpu.tools.qualification import qualify_event_logs
    log_dir = _run_logged_queries(tmp_path, sql_enabled=True)
    rep = qualify_event_logs(log_dir)
    assert rep.est_speedup > 3  # fully eligible plan models well
    assert rep.top_blockers == []
