"""tpu-lint 2.0 dataflow engine: CFG construction specimens, worklist
convergence, call-graph summary propagation, per-analysis seeded-defect
fixtures, and the runtime lock-order watchdog (ISSUE 10)."""
import ast
import os

import pytest

from spark_rapids_tpu.analysis.dataflow import (Analysis, CFG,
                                                BranchTest, LoopIter,
                                                Project, WithEnter,
                                                WithExit, solve)
from spark_rapids_tpu.analysis import lockwatch
from spark_rapids_tpu.analysis.jit_taint import analyze_jit_taint
from spark_rapids_tpu.analysis.ledger import analyze_ledger
from spark_rapids_tpu.analysis.locks import (LOCK_HIERARCHY,
                                             analyze_locks,
                                             collect_locks, lock_graph,
                                             lock_level)


def _cfg(src):
    return CFG(ast.parse(src).body[0])


def _project(src, name="mod.py"):
    return Project([(os.path.join("/tmp/dfproj", name),
                     ast.parse(src))])


def _rules(findings):
    return sorted({f["rule"] for f in findings})


# --- CFG construction specimens ---------------------------------------------


class _Trace(Analysis):
    """Records which statement kinds flow to which exits — enough to
    assert structural properties without a real lattice."""

    def initial(self):
        return frozenset()

    def join(self, a, b):
        return a | b

    def transfer(self, stmt, fact):
        if isinstance(stmt, WithExit):
            return fact | {("exit", stmt.lineno)}
        if isinstance(stmt, (WithEnter, LoopIter, BranchTest)):
            return fact
        node = getattr(stmt, "node", stmt)
        if isinstance(node, ast.Expr) and isinstance(node.value,
                                                     ast.Call):
            names = [n.id for n in ast.walk(node.value)
                     if isinstance(n, ast.Name)]
            return fact | {("call", names[0] if names else "?")}
        return fact


def test_cfg_try_finally_runs_on_all_exits():
    cfg = _cfg(
        "def f(cond):\n"
        "    try:\n"
        "        if cond:\n"
        "            return 1\n"
        "        work()\n"
        "    finally:\n"
        "        cleanup()\n"
        "    return 2\n")
    facts = solve(cfg, _Trace())
    # cleanup() reaches the normal exit (early return AND fallthrough)
    assert ("call", "cleanup") in facts[cfg.exit]
    # and the exceptional exit (work() raising)
    assert ("call", "cleanup") in facts[cfg.raise_exit]


def test_cfg_with_exit_on_exception_edge():
    cfg = _cfg(
        "def f(lock):\n"
        "    with lock:\n"
        "        work()\n")
    facts = solve(cfg, _Trace())
    # __exit__ runs before the exception propagates out
    assert any(k == "exit" for k, _ in facts[cfg.raise_exit])
    assert any(k == "exit" for k, _ in facts[cfg.exit])


def test_cfg_break_unwinds_with():
    cfg = _cfg(
        "def f(lock, items):\n"
        "    for x in items:\n"
        "        with lock:\n"
        "            if x:\n"
        "                break\n"
        "    return 0\n")
    facts = solve(cfg, _Trace())
    # the break path still ran the with-exit before leaving the loop
    assert any(k == "exit" for k, _ in facts[cfg.exit])


def test_cfg_nested_loops_and_unreachable_code():
    cfg = _cfg(
        "def f(rows):\n"
        "    total = 0\n"
        "    for r in rows:\n"
        "        for c in r:\n"
        "            if c:\n"
        "                continue\n"
        "            total += 1\n"
        "    return total\n")
    facts = solve(cfg, _Trace())
    assert cfg.exit in facts  # converged, exit reachable


def test_solver_converges_on_loop():
    """A genuinely growing fact across a back edge must reach a
    fixpoint, not oscillate."""

    class Accum(Analysis):
        def initial(self):
            return frozenset()

        def join(self, a, b):
            return a | b

        def transfer(self, stmt, fact):
            node = getattr(stmt, "node", stmt)
            if isinstance(node, ast.Assign) \
                    and isinstance(node.targets[0], ast.Name):
                return fact | {node.targets[0].id}
            return fact

    cfg = _cfg(
        "def f(n):\n"
        "    i = 0\n"
        "    while i < n:\n"
        "        a = work()\n"
        "        b = work()\n"
        "        i = i + 1\n"
        "    return i\n")
    facts = solve(cfg, Accum())
    assert {"i", "a", "b"} <= facts[cfg.exit]


# --- call-graph summaries ----------------------------------------------------


def test_lock_summary_flows_through_helper_calls():
    """A lock acquired two helpers deep creates an order edge from the
    caller's held lock — the one-level summary pass at fixpoint."""
    src = (
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._outer = threading.Lock()\n"
        "        self._inner = threading.Lock()\n"
        "    def deep(self):\n"
        "        with self._inner:\n"
        "            pass\n"
        "    def mid(self):\n"
        "        self.deep()\n"
        "    def top(self):\n"
        "        with self._outer:\n"
        "            self.mid()\n")
    g = lock_graph(_project(src))
    edges = {(e["from"], e["to"]) for e in g["edges"]}
    assert ("W._outer", "W._inner") in edges
    assert g["cycles"] == []


def test_allocator_summary_two_levels():
    """register() behind two helper returns still creates an
    obligation at the outer call site."""
    src = (
        "def build(mm, b):\n"
        "    sb = mm.register(b)\n"
        "    return sb\n"
        "def acquire(mm, b):\n"
        "    return build(mm, b)\n"
        "def use(mm, b, risky):\n"
        "    sb = acquire(mm, b)\n"
        "    risky()\n")  # never released, never escapes
    out = analyze_ledger(_project(src))
    # flagged on the normal AND the exception exit
    assert _rules(out) == ["ledger-leak-path"] and len(out) == 2
    assert all("use" in f["message"] for f in out)


# --- seeded-defect fixtures per analysis -------------------------------------


def test_seeded_lock_order_cycle():
    src = (
        "import threading\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._alock = threading.Lock()\n"
        "        self._block = threading.Lock()\n"
        "    def ab(self):\n"
        "        with self._alock:\n"
        "            with self._block:\n"
        "                pass\n"
        "    def ba(self):\n"
        "        with self._block:\n"
        "            with self._alock:\n"
        "                pass\n")
    out = analyze_locks(_project(src))
    assert _rules(out) == ["lock-order-cycle"]
    assert "A._alock" in out[0]["message"] \
        and "A._block" in out[0]["message"]
    # consistent order in both methods: no cycle
    clean = src.replace(
        "with self._block:\n            with self._alock:",
        "with self._alock:\n            with self._block:")
    assert analyze_locks(_project(clean)) == []


def test_seeded_lock_order_inversion_against_hierarchy():
    """Class/attr names matching the declared hierarchy patterns are
    checked against it even without a cycle."""
    src = (
        "import threading\n"
        "class DeviceMemoryManager:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.RLock()\n"
        "class SpillableBatch:\n"
        "    def __init__(self, mgr: 'DeviceMemoryManager'):\n"
        "        self._state_lock = threading.RLock()\n"
        "        self._mgr = mgr\n"
        "    def bad(self):\n"
        "        with self._mgr._lock:\n"
        "            with self._state_lock:\n"
        "                pass\n")
    out = analyze_locks(_project(src))
    assert "lock-order-inversion" in _rules(out)
    inv = [f for f in out if f["rule"] == "lock-order-inversion"][0]
    assert "level 50" in inv["message"] and "level 40" in inv["message"]


def test_seeded_blocking_under_lock_direct_and_via_helper():
    src = (
        "import threading, time\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def direct(self, fut):\n"
        "        with self._lock:\n"
        "            fut.result()\n"
        "    def slow(self):\n"
        "        time.sleep(1)\n"
        "    def indirect(self):\n"
        "        with self._lock:\n"
        "            self.slow()\n")
    out = analyze_locks(_project(src))
    blocking = [f for f in out if f["rule"] == "blocking-under-lock"]
    assert len(blocking) == 2
    assert any("via W.slow" in f["message"] for f in blocking)
    # a try-acquired lock does not make the same calls findings-free —
    # but bounded calls do
    clean = src.replace("fut.result()", "fut.result(timeout=5)") \
               .replace("time.sleep(1)", "pass")
    assert [f for f in analyze_locks(_project(clean))
            if f["rule"] == "blocking-under-lock"] == []


def test_seeded_condition_wait_on_own_lock_is_exempt():
    src = (
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._cv = threading.Condition()\n"
        "    def waiter(self):\n"
        "        with self._cv:\n"
        "            while True:\n"
        "                self._cv.wait()\n")
    assert [f for f in analyze_locks(_project(src))
            if f["rule"] == "blocking-under-lock"] == []


def test_seeded_unlocked_mutation_acquire_style_augassign():
    """The PR 6 rule's false negative: acquire()/release() critical
    sections guarded nothing, so `self.x += 1` outside was invisible.
    The dataflow port sees lock-held-ness as a fact."""
    src = (
        "import threading\n"
        "class Store:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.x = 0\n"
        "    def f(self):\n"
        "        self._lock.acquire()\n"
        "        self.x += 1\n"
        "        self._lock.release()\n"
        "    def g(self):\n"
        "        self.x += 1\n")
    out = analyze_locks(_project(src))
    muts = [f for f in out if f["rule"] == "unlocked-shared-mutation"]
    assert [f["line"] for f in muts] == [11]
    # mutation after an early release() on the same path is caught too
    src2 = src.replace(
        "    def g(self):\n        self.x += 1\n",
        "")
    src2 += "    def h(self):\n" \
            "        self._lock.acquire()\n" \
            "        self._lock.release()\n" \
            "        self.x += 1\n"
    out2 = analyze_locks(_project(src2))
    assert [f["rule"] for f in out2] == ["unlocked-shared-mutation"]


def test_seeded_ledger_leak_and_fixed_variant():
    leaky = (
        "def f(mm, items, risky):\n"
        "    sbs = []\n"
        "    for b in items:\n"
        "        sbs.append(mm.register(b))\n"
        "    risky()\n"
        "    for sb in sbs:\n"
        "        sb.release()\n")
    out = analyze_ledger(_project(leaky))
    assert _rules(out) == ["ledger-leak-path"]
    assert "exception path" in out[0]["message"]
    fixed = (
        "def f(mm, items, risky):\n"
        "    sbs = []\n"
        "    try:\n"
        "        for b in items:\n"
        "            sbs.append(mm.register(b))\n"
        "        risky()\n"
        "    except BaseException:\n"
        "        for sb in sbs:\n"
        "            sb.release()\n"
        "        raise\n"
        "    for sb in sbs:\n"
        "        sb.release()\n")
    assert analyze_ledger(_project(fixed)) == []


def test_seeded_ledger_comprehension_and_discard():
    src = (
        "def f(mm, batches):\n"
        "    sbs = [mm.register(b) for b in batches]\n"
        "    for sb in sbs:\n"
        "        sb.release()\n"
        "def g(mm, b):\n"
        "    mm.register(b)\n")
    out = analyze_ledger(_project(src))
    msgs = sorted(f["message"][:20] for f in out)
    assert len(out) == 2
    assert any("comprehension" in f["message"] for f in out)
    assert any("discarded" in f["message"] for f in out), msgs


def test_seeded_ledger_ownership_transfers_are_clean():
    src = (
        "def ret(mm, b):\n"
        "    sb = mm.register(b)\n"
        "    return sb\n"
        "class H:\n"
        "    def store(self, mm, b):\n"
        "        self._sb = mm.register(b)\n"
        "def closure(mm, b):\n"
        "    sb = mm.register(b)\n"
        "    def replay():\n"
        "        sb.release()\n"
        "    return replay\n"
        "def handoff(mm, b, inflight):\n"
        "    sb = mm.register(b)\n"
        "    inflight.add(sb)\n")
    assert analyze_ledger(_project(src)) == []


def test_seeded_transient_reservation_forms():
    src = (
        "def good(mm, n):\n"
        "    with mm.transient_reservation(n):\n"
        "        work()\n"
        "def assigned(mm, n):\n"
        "    charge = mm.transient_reservation(n)\n"
        "    with charge:\n"
        "        work()\n"
        "def bad(mm, n):\n"
        "    mm.transient_reservation(n)\n"
        "    work()\n")
    out = analyze_ledger(_project(src))
    assert len(out) == 1
    assert "never releases" in out[0]["message"]


def test_seeded_jit_taint_interprocedural():
    src = (
        "import jax\n"
        "import numpy as np\n"
        "def helper2(x):\n"
        "    return np.asarray(x)\n"
        "def helper(x):\n"
        "    return helper2(x) + 1\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    return helper(x)\n"
        "def host_only(x):\n"
        "    return np.asarray(x)\n")  # unreachable from jit: clean
    out = analyze_jit_taint(_project(src))
    assert [f["line"] for f in out] == [4]
    assert "kernel -> helper -> helper2" in out[0]["message"]


def test_seeded_jit_taint_method_and_module_forms():
    src = (
        "import jax\n"
        "class K:\n"
        "    def run(self, b):\n"
        "        self._jit = jax.jit(self._impl)\n"
        "        return self._jit(b)\n"
        "    def _impl(self, b):\n"
        "        return b.item()\n"
        "def decode(blob):\n"
        "    return blob.block_until_ready()\n"
        "fn = jax.jit(decode)\n")
    out = analyze_jit_taint(_project(src))
    assert sorted(f["line"] for f in out) == [7, 9]


# --- package-wide invariants -------------------------------------------------


@pytest.fixture(scope="module")
def package_project():
    from spark_rapids_tpu.analysis.lint import (_iter_py_files,
                                                package_dir)
    pkg = package_dir()
    parsed = []
    for p in _iter_py_files([pkg]):
        try:
            parsed.append((p, ast.parse(open(p).read())))
        except SyntaxError:
            continue
    return Project(parsed, root=pkg)


def test_package_lock_graph_has_no_cycles_and_all_levels_declared(
        package_project):
    """The acceptance gate: the package lock graph is cycle-free, every
    edge ascends the declared hierarchy, and every lock the registry
    finds maps to a declared level (no unexplained locks)."""
    g = lock_graph(package_project)
    assert g["cycles"] == []
    unleveled = [lid for lid, meta in g["locks"].items()
                 if meta["level"] is None]
    assert unleveled == [], unleveled
    for e in g["edges"]:
        la, lb = lock_level(e["from"]), lock_level(e["to"])
        assert la is not None and lb is not None
        assert la <= lb, e


def test_package_lock_registry_matches_known_locks(package_project):
    reg = collect_locks(package_project)
    for expected in ("DeviceMemoryManager._lock",
                     "SpillableBatch._state_lock",
                     "HostShuffleTransport._lock",
                     "_WeightedWindow._cv"):
        assert expected in reg, sorted(reg)


# --- runtime lock-order watchdog ---------------------------------------------


@pytest.mark.skipif(not lockwatch.env_enabled(),
                    reason="needs RAPIDS_TPU_LOCKWATCH=1 (conftest "
                           "bootstrap) — CI step 12 runs it")
def test_import_time_singleton_locks_are_watched():
    """The conftest bootstrap installs the watchdog BEFORE the package
    imports, so module-level singleton locks created at import time
    (flight recorder, metrics guards) are watched proxies that resolve
    their declared hierarchy level lazily."""
    assert lockwatch.installed()
    from spark_rapids_tpu.obs import metrics
    from spark_rapids_tpu.obs.recorder import RECORDER
    for lk, want in ((RECORDER._lock, 70),
                     (metrics._update_lock, 85)):
        assert type(lk).__name__ == "_WatchedLock", type(lk)
        with lk:
            pass
        lk._resolve()
        assert lk._level == want, (lk._label, lk._level)


@pytest.fixture
def watchdog():
    """Install (if not already via RAPIDS_TPU_LOCKWATCH), snapshot the
    inversion count, and restore state afterwards."""
    was_installed = lockwatch.installed()
    if not was_installed:
        lockwatch.install()
    before = len(lockwatch.report()["inversions"])
    yield lockwatch
    # drop only what this test added, keep session-level evidence
    with lockwatch._state_lock:
        del lockwatch._inversions[before:]
    if not was_installed:
        lockwatch.uninstall()


def _mem_pair():
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.memory import (DeviceMemoryManager,
                                         SpillableBatch)

    class FakeBatch:
        schema = None

        def device_size_bytes(self):
            return 128

    mgr = DeviceMemoryManager(RapidsConf(
        {"spark.rapids.memory.device.budgetBytes": str(1 << 30)}))
    return mgr, SpillableBatch(mgr, FakeBatch())


def test_watchdog_levels_and_inversion(watchdog):
    mgr, sb = _mem_pair()
    # hierarchy levels resolve lazily (locks can be created before the
    # package finishes importing under the conftest bootstrap)
    mgr._lock._resolve()
    sb._state_lock._resolve()
    assert mgr._lock._level == 50
    assert sb._state_lock._level == 40
    base = len(watchdog.report()["inversions"])
    with sb._state_lock:      # 40 then 50: declared order
        with mgr._lock:
            pass
    assert len(watchdog.report()["inversions"]) == base
    with mgr._lock:           # 50 then 40: inversion
        with sb._state_lock:
            pass
    rep = watchdog.report()
    assert len(rep["inversions"]) == base + 1
    inv = rep["inversions"][-1]
    assert "SpillableBatch._state_lock" in inv["why"]
    assert any("DeviceMemoryManager._lock" in h for h in inv["held"])


def test_watchdog_try_acquire_and_reentrancy_exempt(watchdog):
    mgr, sb = _mem_pair()
    base = len(watchdog.report()["inversions"])
    with mgr._lock:
        got = sb._state_lock.acquire(blocking=False)  # try: exempt
        if got:
            sb._state_lock.release()
        with mgr._lock:  # RLock reentrancy: exempt
            pass
    assert len(watchdog.report()["inversions"]) == base


def test_watchdog_self_deadlock_on_plain_lock(watchdog):
    import threading
    lk = threading.Lock()  # watched (factory is patched)
    base = len(watchdog.report()["inversions"])
    lk.acquire()
    try:
        got = lk.acquire(blocking=False)  # try-acquire: no record
        assert not got
        assert len(watchdog.report()["inversions"]) == base
        # a BLOCKING re-acquire would hang: the check records the
        # self-deadlock BEFORE blocking, so probe via a short timeout
        got = lk.acquire(True, 0.01)
        assert not got
    finally:
        lk.release()
    rep = watchdog.report()
    assert len(rep["inversions"]) == base + 1
    assert "self-deadlock" in rep["inversions"][-1]["why"]


def test_watchdog_condition_machinery_stays_healthy(watchdog):
    import queue
    import threading as th
    q = queue.Queue(maxsize=1)

    def worker():
        for i in range(50):
            q.put(i)

    t = th.Thread(target=worker)
    t.start()
    got = [q.get(timeout=5) for _ in range(50)]
    t.join(5)
    assert got == list(range(50))
    from spark_rapids_tpu.pipeline import pipelined_map
    assert list(pipelined_map(lambda x: x * 2, range(8), threads=2,
                              window=2, weigher=lambda x: 1,
                              max_weight=2)) == [0, 2, 4, 6, 8, 10,
                                                 12, 14]


def test_watchdog_report_and_assert_clean(watchdog, tmp_path):
    mgr, sb = _mem_pair()
    path = str(tmp_path / "lw.json")
    out = watchdog.write_report(path)
    assert out == path
    import json
    doc = json.load(open(path))
    assert doc["installed"] is True
    assert doc["counts"]["checked"] >= 0
    base = len(watchdog.report()["inversions"])
    if base == 0:
        watchdog.assert_clean()
    with mgr._lock:
        with sb._state_lock:
            pass
    with pytest.raises(AssertionError):
        watchdog.assert_clean()
