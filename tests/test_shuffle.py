"""Shuffle tests: murmur3 parity, partitioners, exchange execs, and the
ICI all-to-all SPMD exchange on the virtual 8-device mesh
(reference: repart_test.py + RapidsShuffleClient/ServerSuite —
SURVEY.md §4.1/4.3)."""
import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.columnar import arrow_to_device
from spark_rapids_tpu.exec import HostBatchSourceExec
from spark_rapids_tpu.exec.exchange import (TpuBroadcastExchangeExec,
                                            TpuCoalesceBatchesExec,
                                            TpuShuffleExchangeExec)
from spark_rapids_tpu.expr import UnresolvedColumn as col
from spark_rapids_tpu.expr.base import EvalCtx, bind_expr
from spark_rapids_tpu.ops.hash import (hash_columns_device,
                                       hash_columns_numpy, pmod)
from spark_rapids_tpu.shuffle import (HashPartitioning,
                                      LocalShuffleTransport,
                                      RoundRobinPartitioning,
                                      SinglePartitioning)
from spark_rapids_tpu.shuffle.ici import make_ici_all_to_all

from asserts import assert_tpu_and_cpu_plan_equal
from data_gen import (BooleanGen, DateGen, DecimalGen, DoubleGen, FloatGen,
                      IntegerGen, LongGen, StringGen, TimestampGen,
                      gen_table)


def source(gens, n=256, seed=1234, names=None):
    return HostBatchSourceExec([gen_table(gens, n, seed, names)])


# --- murmur3 device/host parity ------------------------------------------

@pytest.mark.parametrize("gen", [IntegerGen(), LongGen(), BooleanGen(),
                                 FloatGen(dt.FLOAT32), DoubleGen(),
                                 DateGen(), TimestampGen(),
                                 DecimalGen(precision=12), StringGen()],
                         ids=lambda g: g.dtype.simple_string())
def test_murmur3_device_matches_host(gen):
    rb = gen_table([gen], 200, seed=42)
    schema_types = [gen.dtype]
    host = hash_columns_numpy([rb.column(0)], schema_types, rb.num_rows)
    batch = arrow_to_device(rb)
    dev = np.asarray(jax.device_get(
        hash_columns_device(batch.columns)))[:rb.num_rows]
    assert (host == dev).all(), \
        f"first diff at {np.nonzero(host != dev)[0][:5]}"


def test_murmur3_known_spark_values():
    # Spark: SELECT hash(1) == -559580957, hash(0) == 933211791,
    # hash(1L) == -1712319331, hash("abc") == 4 known? -- verified subset:
    # these come from Spark's Murmur3HashFunction (seed 42) definition.
    rb = pa.record_batch({"i": pa.array([1, 0], pa.int32())})
    h = hash_columns_numpy([rb.column(0)], [dt.INT32], 2)
    assert list(h) == [-559580957, 933211791]


def test_multi_column_hash_seed_threading():
    rb = gen_table([IntegerGen(), StringGen(), DoubleGen()], 100, seed=3)
    types = [dt.INT32, dt.STRING, dt.FLOAT64]
    host = hash_columns_numpy([rb.column(i) for i in range(3)], types, 100)
    batch = arrow_to_device(rb)
    dev = np.asarray(jax.device_get(
        hash_columns_device(batch.columns)))[:100]
    assert (host == dev).all()


# --- exchange execs -------------------------------------------------------

@pytest.mark.parametrize("n_parts", [1, 2, 7])
def test_hash_shuffle_exchange(n_parts):
    plan = TpuShuffleExchangeExec(
        HashPartitioning([col("c0")], n_parts),
        source([IntegerGen(null_frac=0.2), StringGen(), LongGen()], 300))
    assert_tpu_and_cpu_plan_equal(plan)


def test_hash_shuffle_string_keys():
    plan = TpuShuffleExchangeExec(
        HashPartitioning([col("c1")], 4),
        source([IntegerGen(), StringGen(max_len=8)], 250))
    assert_tpu_and_cpu_plan_equal(plan)


def test_round_robin_exchange():
    plan = TpuShuffleExchangeExec(
        RoundRobinPartitioning(3),
        source([IntegerGen(), DoubleGen()], 200))
    assert_tpu_and_cpu_plan_equal(plan)


def test_single_partition_exchange():
    rbs = [gen_table([IntegerGen()], n, seed=s)
           for n, s in [(50, 1), (80, 2)]]
    plan = TpuShuffleExchangeExec(SinglePartitioning(),
                                  HostBatchSourceExec(rbs))
    assert_tpu_and_cpu_plan_equal(plan)


def test_broadcast_exchange():
    rbs = [gen_table([IntegerGen(), StringGen()], n, seed=s)
           for n, s in [(60, 1), (40, 2)]]
    plan = TpuBroadcastExchangeExec(HostBatchSourceExec(rbs))
    assert_tpu_and_cpu_plan_equal(plan)


def test_coalesce_batches():
    rbs = [gen_table([IntegerGen(), StringGen()], 64, seed=s)
           for s in range(6)]
    plan = TpuCoalesceBatchesExec(HostBatchSourceExec(rbs),
                                  target_rows=150)
    assert_tpu_and_cpu_plan_equal(plan)


def test_shuffle_then_groupby():
    # the reduce-side shape: exchange feeding an aggregate
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.expr import Alias
    from spark_rapids_tpu.expr.aggregates import Count, Sum
    src = source([IntegerGen(min_val=0, max_val=30), LongGen()], 400)
    ex = TpuShuffleExchangeExec(HashPartitioning([col("c0")], 4), src)
    plan = TpuHashAggregateExec([col("c0")],
                                [Alias(Sum(col("c1")), "s"),
                                 Alias(Count(), "c")], ex)
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_transport_seam_mock():
    # the transport interface is mockable (SURVEY.md §4.3): a recording
    # transport observes every write the exchange makes
    class RecordingTransport(LocalShuffleTransport):
        def __init__(self):
            super().__init__()
            self.writes = []

        def writer(self, sid, mid):
            inner = super().writer(sid, mid)
            rec = self

            class W:
                def write_unsplit(self, b, pids):
                    import numpy as np
                    live = np.asarray(b.live_mask())
                    for p in sorted(set(np.asarray(pids)[live].tolist())):
                        rec.writes.append((mid, int(p)))
                    inner.write_unsplit(b, pids)

                def close(self):
                    pass
            return W()

    t = RecordingTransport()
    plan = TpuShuffleExchangeExec(
        HashPartitioning([col("c0")], 3),
        source([IntegerGen()], 100), transport=t)
    from spark_rapids_tpu.exec.base import collect_arrow
    collect_arrow(plan)
    assert sorted(set(p for _, p in t.writes)) == [0, 1, 2]


# --- ICI SPMD all-to-all on the 8-device virtual mesh ---------------------

def _mesh():
    return Mesh(np.array(jax.devices()[:8]), ("x",))


def test_ici_all_to_all_routes_rows():
    ndev, cap = 8, 64
    rng = np.random.default_rng(5)
    data = rng.integers(0, 1000, (ndev, cap)).astype(np.int64)
    valid = np.ones((ndev, cap), bool)
    rcs = rng.integers(10, cap, (ndev,)).astype(np.int32)
    live = np.arange(cap)[None, :] < rcs[:, None]
    pids = rng.integers(0, ndev, (ndev, cap)).astype(np.int32)
    mesh = _mesh()
    fn = make_ici_all_to_all(mesh)
    (od,), (ov,), ol, orc, _ = fn((jnp.asarray(data),),
                               (jnp.asarray(valid),),
                               jnp.asarray(pids), jnp.asarray(live))
    od, ol, orc = map(np.asarray, (od, ol, orc))
    # every live row must land on the device its pid names
    expected = {d: [] for d in range(ndev)}
    for d in range(ndev):
        for r in range(rcs[d]):
            expected[pids[d, r]].append(data[d, r])
    for d in range(ndev):
        got = sorted(od[d][ol[d]].tolist())
        assert got == sorted(expected[d]), f"device {d}"
        assert orc[d] == len(expected[d])


def test_ici_all_to_all_nonprefix_live_and_2d_lanes():
    # selection-mask shaped liveness (holes) + a (cap, B) byte-matrix lane
    ndev, cap, B = 8, 32, 4
    rng = np.random.default_rng(9)
    d1 = rng.integers(-50, 50, (ndev, cap)).astype(np.int32)
    mat = rng.integers(0, 255, (ndev, cap, B)).astype(np.uint8)
    v1 = rng.random((ndev, cap)) > 0.3
    live = rng.random((ndev, cap)) > 0.4
    pids = (np.abs(d1) % ndev).astype(np.int32)
    mesh = _mesh()
    fn = make_ici_all_to_all(mesh)
    (o1, om), (ov1, _), ol, orc, _ = fn(
        (jnp.asarray(d1), jnp.asarray(mat)),
        (jnp.asarray(v1), jnp.asarray(v1)),
        jnp.asarray(pids), jnp.asarray(live))
    o1, om, ov1, ol = map(np.asarray, (o1, om, ov1, ol))
    for d in range(ndev):
        exp = []
        for s in range(ndev):
            for r in range(cap):
                if live[s, r] and pids[s, r] == d:
                    exp.append((int(d1[s, r]), bool(v1[s, r]),
                                tuple(mat[s, r].tolist())))
        got = [(int(a), bool(b), tuple(m.tolist()))
               for a, b, m in zip(o1[d][ol[d]], ov1[d][ol[d]],
                                  om[d][ol[d]])]
        assert sorted(got) == sorted(exp), f"device {d}"


# --- engine path over the mesh: exchange exec -> ICI transport ------------

def _ici_exchange_plan(gens, n_batches=8, rows=40, n_parts=8, key="c0"):
    from spark_rapids_tpu.shuffle.ici import IciShuffleTransport
    rbs = [gen_table(gens, rows, seed=100 + i) for i in range(n_batches)]
    src = HostBatchSourceExec(rbs)
    return TpuShuffleExchangeExec(
        HashPartitioning([col(key)], n_parts), src,
        transport=IciShuffleTransport(_mesh()))


def test_ici_exchange_engine_path_fixed_width():
    plan = _ici_exchange_plan([IntegerGen(null_frac=0.2), LongGen(),
                               DoubleGen(null_frac=0.1)])
    assert_tpu_and_cpu_plan_equal(plan)


def test_ici_exchange_engine_path_strings():
    # strings ride the collective as byte-matrix + length lanes
    plan = _ici_exchange_plan(
        [IntegerGen(), StringGen(max_len=12, null_frac=0.15)])
    assert_tpu_and_cpu_plan_equal(plan)


def test_ici_exchange_string_keys():
    plan = _ici_exchange_plan([StringGen(max_len=6), LongGen()], key="c0")
    assert_tpu_and_cpu_plan_equal(plan)


def test_ici_exchange_feeds_aggregate_through_planner():
    # THE multi-chip engine shape: planner-built exchange -> aggregate
    # over the mesh, asserted against the CPU oracle (VERDICT r2 item 2)
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.expr import Alias
    from spark_rapids_tpu.expr.aggregates import Count, Sum
    from spark_rapids_tpu.planner import TpuOverrides
    from spark_rapids_tpu.exec.base import collect_arrow_cpu
    ex = _ici_exchange_plan([IntegerGen(min_val=0, max_val=20,
                                        null_frac=0.1), LongGen()])
    agg = TpuHashAggregateExec([col("c0")],
                               [Alias(Sum(col("c1")), "s"),
                                Alias(Count(), "n")], ex)
    plan = TpuOverrides().apply(agg)
    assert not plan.fallback_nodes(), plan.explain("ALL")
    tpu = plan.collect().to_pandas().sort_values("c0").reset_index(
        drop=True)
    cpu = collect_arrow_cpu(agg).to_pandas().sort_values("c0").reset_index(
        drop=True)
    import pandas.testing as pdt
    pdt.assert_frame_equal(tpu, cpu, check_dtype=False)


def test_ici_exchange_partition_folding():
    # partition counts != mesh size fold onto devices p mod D, the
    # original pid riding an extra lane (VERDICT r3 weak #3)
    for parts in (3, 16):
        plan = _ici_exchange_plan([IntegerGen(null_frac=0.2), LongGen(),
                                   StringGen(max_len=8, null_frac=0.1)],
                                  n_parts=parts)
        assert_tpu_and_cpu_plan_equal(plan, label=f"parts={parts}")


def test_ici_exchange_multi_epoch_map_schedule():
    # more map blocks than mesh positions -> multiple collective epochs
    plan = _ici_exchange_plan([IntegerGen(null_frac=0.2), LongGen()],
                              n_batches=20, rows=17)
    assert_tpu_and_cpu_plan_equal(plan)


def test_ici_multiple_batches_per_map_id_all_rows_survive():
    # round 3's _realize dropped all but the LAST batch per map id
    # (VERDICT r3 weak #5 latent row-loss bug); every written batch must
    # land now
    import pyarrow as pa
    from spark_rapids_tpu import datatypes as dt
    from spark_rapids_tpu.columnar.arrow_bridge import (arrow_to_device,
                                                        device_to_arrow)
    from spark_rapids_tpu.shuffle.ici import IciShuffleTransport
    t = IciShuffleTransport(_mesh())
    t.register_shuffle(7, 8)
    w = t.writer(7, map_id=0)
    schema = dt.Schema([dt.StructField("v", dt.INT64, False),
                        dt.StructField("s", dt.STRING, True)])
    rows = []
    for k in range(3):  # 3 batches from ONE map task
        vals = list(range(k * 10, k * 10 + 10))
        strs = [f"m0b{k}r{v}" for v in vals]
        rows += list(zip(vals, strs))
        rb = pa.record_batch({"v": pa.array(vals, pa.int64()),
                              "s": pa.array(strs)})
        b = arrow_to_device(rb, schema)
        import jax.numpy as jnp
        pids = jnp.asarray((np.array(vals) % 8).astype(np.int32))
        import numpy as _np
        w.write_unsplit(b, pids)
    got = []
    for p in range(8):
        for b in t.read_partition(7, p):
            tb = device_to_arrow(b)
            got += list(zip(tb.column("v").to_pylist(),
                            tb.column("s").to_pylist()))
            assert all(v % 8 == p for v in tb.column("v").to_pylist())
    assert sorted(got) == sorted(rows)


# --- device RangePartitioning: sampled bounds -> searchsorted --------------

def _range_exchange(gens, orders_cols, n=300, parts=4, n_batches=2,
                    transport=None, **order_kw):
    from spark_rapids_tpu.exec.sort import SortOrder
    from spark_rapids_tpu.shuffle.partitioner import RangePartitioning
    rbs = [gen_table(gens, n, seed=50 + i) for i in range(n_batches)]
    src = HostBatchSourceExec(rbs)
    orders = [SortOrder(col(c), **order_kw) for c in orders_cols]
    return TpuShuffleExchangeExec(
        RangePartitioning(orders, parts), src,
        transport=transport) if transport else TpuShuffleExchangeExec(
        RangePartitioning(orders, parts), src)


@pytest.mark.parametrize("asc", [True, False])
def test_range_partition_int_keys(asc):
    plan = _range_exchange([IntegerGen(null_frac=0.1), LongGen()],
                           ["c0"], ascending=asc)
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_range_partition_string_keys():
    plan = _range_exchange([StringGen(max_len=8, null_frac=0.1),
                            LongGen()], ["c0"])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_range_partition_float_multi_key():
    plan = _range_exchange([DoubleGen(null_frac=0.15),
                            IntegerGen(min_val=0, max_val=5)],
                           ["c1", "c0"])
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_range_partition_device_matches_host_ids():
    """Device pid kernel must place every row exactly where the host
    _row_partition comparison does."""
    import numpy as np
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    from spark_rapids_tpu.exec.sort import SortOrder
    from spark_rapids_tpu.expr.base import EvalCtx, bind_expr
    from spark_rapids_tpu.shuffle.partitioner import RangePartitioning
    rb = gen_table([DoubleGen(null_frac=0.2), StringGen(max_len=5,
                                                        null_frac=0.2)],
                   400, seed=77)
    from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
    schema = engine_schema(rb.schema)
    for cols, kw in ((["c0"], {}), (["c1"], {}),
                     (["c0", "c1"], {"ascending": False,
                                     "nulls_first": False})):
        part = RangePartitioning(
            [SortOrder(col(c), **kw) for c in cols], 5).bind(schema)
        part.compute_bounds([rb], EvalCtx())
        cpu_ids = part.partition_ids_cpu(rb, EvalCtx())
        dev = arrow_to_device(rb, schema)
        dev_ids = np.asarray(part.partition_ids_device(dev, EvalCtx()))
        assert (dev_ids[:rb.num_rows] == cpu_ids).all(), cols


def test_distributed_global_sort_via_range_shuffle():
    """Range shuffle + per-partition sort == total sort (the distributed
    global-sort story — VERDICT r2 item 6)."""
    from spark_rapids_tpu.exec.sort import (SortOrder, TpuSortExec,
                                            cpu_sort_table)
    from spark_rapids_tpu.shuffle.partitioner import RangePartitioning
    from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow
    rb = gen_table([IntegerGen(null_frac=0.1), LongGen()], 500, seed=9)
    src = HostBatchSourceExec([rb])
    orders = [SortOrder(col("c0")), SortOrder(col("c1"))]
    ex = TpuShuffleExchangeExec(RangePartitioning(orders, 4), src)
    # single map batch => one batch per partition => per-batch sort of
    # the partition-major stream is a global sort
    plan = TpuSortExec(orders, ex, global_sort=False)
    got = collect_arrow(plan, ExecCtx())
    import dataclasses
    import pyarrow as _pa
    t = _pa.Table.from_batches([rb])
    from spark_rapids_tpu.expr.base import EvalCtx as _E, bind_expr as _b
    bound_orders = [dataclasses.replace(o, child=_b(o.child,
                                                    plan.output_schema))
                    for o in orders]
    karrs = [o.child.eval_cpu(rb, _E()) for o in bound_orders]
    want = cpu_sort_table(t, karrs, bound_orders)
    assert got.to_pylist() == want.to_pylist()


def test_range_shuffle_over_ici_mesh():
    """Range partitioning drives the ICI collective over the 8-device
    mesh: range-shuffled rows land shard-monotone."""
    from spark_rapids_tpu.shuffle.ici import IciShuffleTransport
    plan = _range_exchange([IntegerGen(null_frac=0.1), LongGen()],
                           ["c0"], parts=8, n_batches=8, n=64,
                           transport=IciShuffleTransport(_mesh()))
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


# --- host Arrow-IPC transport (ladder rung 1) ------------------------------

@pytest.mark.parametrize("codec", ["none", "lz4", "zstd"])
@pytest.mark.parametrize("mode", ["HOST", "MULTITHREADED"])
def test_host_shuffle_transport(mode, codec):
    """Exchange over the Arrow-IPC file transport: same dual-run results
    as the device-resident store, per codec and threading mode
    (SURVEY.md §5.8 ladder rungs 1-2; VERDICT r2 item 7)."""
    from spark_rapids_tpu.config import RapidsConf
    conf = RapidsConf({"spark.rapids.shuffle.mode": mode,
                       "spark.rapids.shuffle.compression.codec": codec})
    plan = TpuShuffleExchangeExec(
        HashPartitioning([col("c0")], 3),
        source([IntegerGen(null_frac=0.2), StringGen(max_len=10),
                DoubleGen(null_frac=0.1)], 300))
    assert_tpu_and_cpu_plan_equal(plan, conf=conf)


def test_host_shuffle_files_cleaned_up():
    import os
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow
    from spark_rapids_tpu.shuffle.host import HostShuffleTransport
    t = HostShuffleTransport(threads=2)
    plan = TpuShuffleExchangeExec(
        HashPartitioning([col("c0")], 4),
        source([IntegerGen(), LongGen()], 200), transport=t)
    out = collect_arrow(plan, ExecCtx())
    assert out.num_rows == 200
    assert os.listdir(t.root) == []  # shuffle dirs removed on unregister
    t.close()
    assert not os.path.exists(t.root)


def test_host_shuffle_writer_side_partition_stats():
    """The host transport records per-partition byte counts at WRITE
    time (the writer downloaded + split the map batch anyway) and
    serves them under free_only with no device access; a FRESH
    transport over the same root rebuilds them from the committed
    manifests' `raw` entries."""
    import os
    import pyarrow as pa
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.shuffle.host import HostShuffleTransport
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    t = HostShuffleTransport(threads=0)
    try:
        t.register_shuffle(7, 3)
        rb = pa.record_batch({"v": pa.array(list(range(90)), pa.int64())})
        b = arrow_to_device(rb)
        w = t.writer(7, 0)
        # pre-split writes: partition 0 twice as large as partition 2
        w.write(0, arrow_to_device(rb.slice(0, 60)))
        w.write(2, arrow_to_device(rb.slice(60, 30)))
        w.close()
        stats = t.partition_stats(7, free_only=True)
        assert stats is not None and len(stats) == 3
        assert stats[1] == 0 and stats[0] > stats[2] > 0, stats
        assert t.stage_bytes(7) == sum(stats)
        # attempt-protocol writes land in the stats only when committed
        d = t.begin_task_attempt(7, "m9", 0)
        sub = t.writer(7, 9, subdir=d)
        sub.write(1, b)
        sub.close()
        assert t.commit_task_attempt(7, "m9", 0)
        stats2 = t.partition_stats(7, free_only=True)
        assert stats2[1] > 0, stats2
        # a fresh instance over the same root: this shuffle mixes flat
        # legacy blocks (no recorded byte counts) with a committed
        # manifest — partial stats would mis-plan coalescing, so the
        # rebuild WITHHOLDS rather than misleads
        t2 = HostShuffleTransport(threads=0, root=t.root)
        try:
            assert t2.partition_stats(7, free_only=True) is None
            # a shuffle whose root holds ONLY committed manifests
            # rebuilds exactly
            t.register_shuffle(8, 3)
            d8 = t.begin_task_attempt(8, "m0", 0)
            w8 = t.writer(8, 8, subdir=d8)
            w8.write(1, b)
            w8.close()
            assert t.commit_task_attempt(8, "m0", 0)
            want8 = t.partition_stats(8, free_only=True)
            rebuilt = t2.partition_stats(8, free_only=True)
            assert rebuilt is not None and rebuilt[1] == want8[1] > 0, \
                (rebuilt, want8)
        finally:
            t2._own_root = False
            t2.close()
    finally:
        t.close()


def test_host_shuffle_zombie_attempt_never_counts():
    """Attempt-staged writes credit the stats at COMMIT, not at write:
    an in-flight speculative duplicate must not transiently inflate a
    partition for a concurrent AQE stats read, and losing/aborted
    attempts never touch the stats at all."""
    import pyarrow as pa
    from spark_rapids_tpu.shuffle.host import HostShuffleTransport
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    t = HostShuffleTransport(threads=0)
    try:
        t.register_shuffle(3, 2)
        rb = pa.record_batch({"v": pa.array(list(range(50)), pa.int64())})
        b = arrow_to_device(rb)
        d0 = t.begin_task_attempt(3, "m0", 0)
        w0 = t.writer(3, 0, subdir=d0)
        w0.write(0, b)
        w0.close()
        # staged but uncommitted: invisible to stats (no transient
        # double-count window during speculation)
        assert (t.partition_stats(3, free_only=True) or [0])[0] == 0
        assert t.commit_task_attempt(3, "m0", 0)
        committed = t.partition_stats(3, free_only=True)[0]
        assert committed > 0
        # a second attempt writes the same output then loses the race
        d1 = t.begin_task_attempt(3, "m0", 1)
        w1 = t.writer(3, 0, subdir=d1)
        w1.write(0, b)
        w1.close()
        assert t.partition_stats(3, free_only=True)[0] == committed
        assert not t.commit_task_attempt(3, "m0", 1)
        assert t.partition_stats(3, free_only=True)[0] == committed
        # an aborted attempt never counts either
        d2 = t.begin_task_attempt(3, "m0", 2)
        w2 = t.writer(3, 0, subdir=d2)
        w2.write(0, b)
        w2.close()
        t.abort_task_attempt(3, "m0", 2)
        assert t.partition_stats(3, free_only=True)[0] == committed
    finally:
        t.close()


def test_local_transport_writer_side_stats_unsplit():
    """LocalShuffleTransport with stats recording on: write_unsplit
    folds per-partition counts in at write time, and free_only serves
    them; with recording off the old behavior (None) is preserved."""
    import jax.numpy as jnp
    import pyarrow as pa
    from spark_rapids_tpu.shuffle.transport import LocalShuffleTransport
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    rb = pa.record_batch({"v": pa.array(list(range(100)), pa.int64())})
    b = arrow_to_device(rb)
    pids = jnp.asarray((np.arange(b.capacity) % 4).astype(np.int32))
    t = LocalShuffleTransport()
    t.set_stats_recording(True)
    t.register_shuffle(1, 4)
    w = t.writer(1, 0)
    w.write_unsplit(b, pids)
    stats = t.partition_stats(1, free_only=True)
    assert stats is not None and len(stats) == 4
    assert all(s > 0 for s in stats), stats
    t.unregister_shuffle(1)
    t2 = LocalShuffleTransport()  # recording defaults off
    t2.register_shuffle(2, 4)
    w2 = t2.writer(2, 0)
    w2.write_unsplit(b, pids)
    assert t2.partition_stats(2, free_only=True) is None
    assert t2.partition_stats(2) is not None  # sync path still works
    t2.unregister_shuffle(2)


def test_host_shuffle_bad_codec_rejected():
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.shuffle.host import HostShuffleTransport
    with pytest.raises(ValueError):
        HostShuffleTransport(RapidsConf(
            {"spark.rapids.shuffle.compression.codec": "snappy"}))


def test_host_shuffle_feeds_groupby():
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.expr import Alias
    from spark_rapids_tpu.expr.aggregates import Count, Sum
    conf = RapidsConf({"spark.rapids.shuffle.mode": "MULTITHREADED"})
    src = source([IntegerGen(min_val=0, max_val=30), LongGen()], 400)
    ex = TpuShuffleExchangeExec(HashPartitioning([col("c0")], 4), src)
    plan = TpuHashAggregateExec([col("c0")],
                                [Alias(Sum(col("c1")), "s"),
                                 Alias(Count(), "c")], ex)
    assert_tpu_and_cpu_plan_equal(plan, conf=conf, ignore_order=True)


# --- ICI broadcast: build-side replication via all_gather ------------------

def test_ici_broadcast_replicates_on_every_device():
    from spark_rapids_tpu.shuffle.ici import ici_broadcast_batches
    from spark_rapids_tpu.columnar.arrow_bridge import (arrow_to_device,
                                                        device_to_arrow)
    rbs = [gen_table([IntegerGen(null_frac=0.1), LongGen(),
                      StringGen(max_len=9, null_frac=0.2)], 30,
                     seed=70 + i) for i in range(8)]
    batches = [arrow_to_device(rb) for rb in rbs]
    mesh = _mesh()
    out = ici_broadcast_batches(mesh, batches)
    assert len(out) == 1
    got = device_to_arrow(out[0])
    want = pa.Table.from_batches(rbs).combine_chunks()
    gt = got.sort_by([("c1", "ascending")])
    wt = want.sort_by([("c1", "ascending")]).to_batches()[0]
    assert gt.num_rows == want.num_rows
    assert gt.equals(wt), (gt, wt)
    # the gathered lanes are replicated: every device's shard holds the
    # FULL table (all 8 rows of the (D, D*cap) global are identical)
    d0 = out[0].columns[0].data
    assert d0.shape[0] == 8 * batches[0].capacity


def test_ici_broadcast_multi_epoch():
    from spark_rapids_tpu.shuffle.ici import ici_broadcast_batches
    from spark_rapids_tpu.columnar.arrow_bridge import (arrow_to_device,
                                                        device_to_arrow)
    rbs = [gen_table([IntegerGen(nullable=False),
                      LongGen(nullable=False)], 11, seed=90 + i)
           for i in range(13)]  # > mesh size -> 2 epochs
    out = ici_broadcast_batches(_mesh(), [arrow_to_device(rb)
                                          for rb in rbs])
    assert len(out) == 2
    got = sorted(v for b in out for v in
                 device_to_arrow(b).column("c1").to_pylist())
    want = sorted(v for rb in rbs for v in rb.column(1).to_pylist())
    assert got == want


def test_broadcast_hash_join_over_mesh():
    # BHJ with the build side replicated by the collective: no one-chip
    # materialization (VERDICT r3 item 9)
    from spark_rapids_tpu.exec.joins import TpuBroadcastHashJoinExec
    import pandas.testing as pdt
    from spark_rapids_tpu.exec.base import (collect_arrow,
                                            collect_arrow_cpu)
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=60, null_frac=0.1),
                    LongGen()], 200, seed=5, names=["lk", "lv"])])
    right_rbs = [gen_table([IntegerGen(min_val=0, max_val=60),
                            LongGen()], 25, seed=40 + i,
                           names=["rk", "rv"]) for i in range(8)]
    bcast = TpuBroadcastExchangeExec(HostBatchSourceExec(right_rbs),
                                     mesh=_mesh())
    join = TpuBroadcastHashJoinExec([col("lk")], [col("rk")], "inner",
                                    left, bcast)
    g = collect_arrow(join)
    w = collect_arrow_cpu(join)
    got = g.to_pandas().sort_values(list(g.column_names)).reset_index(
        drop=True)
    want = w.to_pandas().sort_values(list(w.column_names)).reset_index(
        drop=True)
    pdt.assert_frame_equal(got, want, check_dtype=False)


def test_ici_string_outlier_does_not_inflate_exchange():
    """VERDICT r4 weak #6: strings ride the collective as flat
    per-destination payloads sized by ACTUAL bytes — one 4 KB outlier
    row must not multiply the exchange by rows x 4 KB."""
    import pyarrow as pa
    from spark_rapids_tpu import datatypes as dt
    from spark_rapids_tpu.columnar.arrow_bridge import (arrow_to_device,
                                                        device_to_arrow)
    from spark_rapids_tpu.shuffle.ici import (IciShuffleTransport,
                                              _discover_epoch_caps,
                                              _lane_spec)
    import jax.numpy as jnp
    n = 512
    strs = [f"s{i}" for i in range(n)]
    strs[137] = "X" * 4096  # the outlier
    vals = list(range(n))
    schema = dt.Schema([dt.StructField("v", dt.INT64, False),
                        dt.StructField("s", dt.STRING, True)])
    rb = pa.record_batch({"v": pa.array(vals, pa.int64()),
                          "s": pa.array(strs)})
    b = arrow_to_device(rb, schema)
    pids = jnp.asarray((np.array(vals) % 8).astype(np.int32))
    blocks = [(0, b, pids)]
    spec = _lane_spec(schema)
    _, char_caps = _discover_epoch_caps(blocks, spec, 8, False, {})
    cb = char_caps[(1, ())]
    total_bytes = sum(len(s) for s in strs)
    # per-pair bucket is bounded by the actual payload (~total/8 +
    # outlier), NOT rows x max_len (512 x 4096 = 2 MB)
    assert cb <= 2 * (total_bytes // 8 + 4096), cb
    assert cb < n * 4096 // 8, "matrix-style inflation is back"
    # and the exchange is still exact
    t = IciShuffleTransport(_mesh())
    t.register_shuffle(42, 8)
    w = t.writer(42, 0)
    w.write_unsplit(b, pids)
    got = []
    for p in range(8):
        for ob in t.read_partition(42, p):
            tb = device_to_arrow(ob)
            got += list(zip(tb.column("v").to_pylist(),
                            tb.column("s").to_pylist()))
    assert sorted(got) == sorted(zip(vals, strs))


def test_ici_hierarchical_dcn_mesh():
    """Cross-slice exchange (SURVEY.md §5.8/:201): the transport over a
    2-D (dcn, ici) mesh — 2 'slices' x 4 chips — routes rows across
    BOTH axes in one collective; XLA places the inter-slice hop on DCN
    on real pods. Parity vs the same exchange on a flat 8-mesh."""
    import pyarrow as pa
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from spark_rapids_tpu import datatypes as dt
    from spark_rapids_tpu.columnar.arrow_bridge import (arrow_to_device,
                                                        device_to_arrow)
    from spark_rapids_tpu.shuffle.ici import IciShuffleTransport
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh2d = Mesh(devs, ("dcn", "ici"))
    t = IciShuffleTransport(mesh2d, axis=("dcn", "ici"))
    assert t.ndev == 8
    n = 300
    rng = np.random.default_rng(12)
    vals = rng.integers(0, 1000, n).astype(np.int64)
    strs = [f"row{v}" for v in vals]
    rb = pa.record_batch({"v": pa.array(vals),
                          "s": pa.array(strs, pa.string())})
    b = arrow_to_device(rb)
    pids = jnp.asarray((vals % 8).astype(np.int32))
    t.register_shuffle(1, 8)
    w = t.writer(1, 0)
    w.write_unsplit(b, pids)
    got = []
    for p in range(8):
        for ob in t.read_partition(1, p):
            tb = device_to_arrow(ob)
            rows = list(zip(tb.column("v").to_pylist(),
                            tb.column("s").to_pylist()))
            assert all(v % 8 == p for v, _ in rows)
            got += rows
    assert sorted(got) == sorted(zip(vals.tolist(), strs))
    # stats ride the same epoch readback on the hierarchical mesh too
    stats = t.partition_stats(1, free_only=True)
    assert stats is not None and sum(1 for s in stats if s > 0) == 8
