"""Static plan verifier: the NDS corpus must verify clean, and each
seeded defect class must be rejected with its specific named reason
(analysis/plan_verifier.py; ISSUE 6 tentpole)."""
import os

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.analysis.plan_verifier import (PlanVerificationError,
                                                     verify_plan)
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec.base import HostBatchSourceExec
from spark_rapids_tpu.expr import UnresolvedColumn
from spark_rapids_tpu.planner import overrides
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.tools import nds


def _source(n=64, seed=0, extra_string=False):
    rng = np.random.default_rng(seed)
    cols = {"a": pa.array(rng.integers(0, 100, n), pa.int64()),
            "b": pa.array(rng.uniform(0, 1, n), pa.float64()),
            "c": pa.array(rng.integers(0, 10, n), pa.int32())}
    if extra_string:
        cols["s"] = pa.array([f"v{i}" for i in range(n)])
    rb = pa.record_batch(cols)
    return HostBatchSourceExec([rb])


def _col(name):
    return UnresolvedColumn(name)


# --- positive: the whole NDS corpus verifies clean --------------------------

@pytest.mark.parametrize("name", sorted(nds.QUERIES))
def test_nds_corpus_verifies_clean(name):
    session = TpuSession(RapidsConf())
    tables = nds.gen_tables(1 << 10)
    plan = nds.build_query(name, session, tables)._node
    report = verify_plan(plan, session.conf)
    assert report.ok, report.summary()
    assert report.nodes_checked > 1
    # and through the planner (transitions + AQE wrappers included),
    # with verification enabled by default
    pp = overrides(plan, session.conf)
    assert pp is not None


def test_report_is_machine_readable():
    session = TpuSession(RapidsConf())
    tables = nds.gen_tables(1 << 9)
    report = verify_plan(nds.build_query("q3", session, tables)._node)
    d = report.to_dict()
    assert d["ok"] is True
    assert d["violations"] == []
    assert d["nodes_checked"] == report.nodes_checked
    assert d["hbm_budget_bytes"] > 0


# --- negative: seeded defects, each with its named reason -------------------

def test_rejects_schema_mismatch_out_of_range():
    """A project rebuilt over a narrower child references ordinals the
    new child does not have (the stale with_new_children class)."""
    from spark_rapids_tpu.exec.basic import TpuProjectExec
    proj = TpuProjectExec([_col("a"), _col("b"), _col("c")], _source())
    narrow = HostBatchSourceExec(
        [pa.record_batch({"a": pa.array([1, 2], pa.int64())})])
    broken = proj.with_new_children([narrow])
    report = verify_plan(broken)
    assert not report.ok
    assert "schema_mismatch" in report.reasons(), report.summary()


def test_rejects_schema_mismatch_dtype():
    """Same shape, same arity, different column dtype under a bound
    reference."""
    from spark_rapids_tpu.exec.basic import TpuProjectExec
    proj = TpuProjectExec([_col("a")], _source())
    other = HostBatchSourceExec(
        [pa.record_batch({"a": pa.array(["x", "y"]),
                          "b": pa.array([0.1, 0.2], pa.float64()),
                          "c": pa.array([1, 2], pa.int32())})])
    broken = proj.with_new_children([other])
    report = verify_plan(broken)
    assert not report.ok
    assert "schema_mismatch" in report.reasons(), report.summary()


def test_rejects_union_width_mismatch_as_named_reason():
    """A union rebuilt over children of different widths must come back
    as a schema_mismatch rejection, not a raw IndexError/TypeError from
    the derivation hook."""
    from spark_rapids_tpu.exec.misc import TpuUnionExec
    union = TpuUnionExec([_source(seed=1), _source(seed=2)])
    narrow = HostBatchSourceExec(
        [pa.record_batch({"a": pa.array([1], pa.int64())})])
    broken = union.with_new_children([_source(seed=1), narrow])
    report = verify_plan(broken)
    assert not report.ok
    assert "schema_mismatch" in report.reasons(), report.summary()


def test_rejects_nullability_lie():
    """A bound reference claiming non-nullable over a nullable input
    column: downstream kernels would elide null handling."""
    from spark_rapids_tpu.exec.basic import TpuProjectExec
    from spark_rapids_tpu.expr.base import BoundReference
    src = _source()
    assert src.output_schema.fields[0].nullable
    lie = BoundReference(0, dt.INT64, nullable_=False, name="a")
    proj = TpuProjectExec([lie], src)
    report = verify_plan(proj)
    assert not report.ok
    assert "nullability_lie" in report.reasons(), report.summary()


def test_rejects_missing_exchange_copartition():
    """A shuffled hash join whose children are hash exchanges with
    different partition counts: equal keys land in different
    partitions."""
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.exec.joins import TpuShuffledHashJoinExec
    from spark_rapids_tpu.shuffle.partitioner import HashPartitioning
    left = _source(seed=1)
    right = _source(seed=2)
    lex = TpuShuffleExchangeExec(
        HashPartitioning([_col("a")], 4), left)
    rex = TpuShuffleExchangeExec(
        HashPartitioning([_col("a")], 8), right)
    join = TpuShuffledHashJoinExec([_col("a")], [_col("a")], "inner",
                                   lex, rex)
    report = verify_plan(join)
    assert not report.ok
    assert "missing_exchange" in report.reasons(), report.summary()
    # co-partitioned children (same scheme, same n) are fine
    ok = TpuShuffledHashJoinExec(
        [_col("a")], [_col("a")], "inner",
        TpuShuffleExchangeExec(HashPartitioning([_col("a")], 4), left),
        TpuShuffleExchangeExec(HashPartitioning([_col("a")], 4), right))
    assert verify_plan(ok).ok


def test_rejects_hbm_over_budget():
    """A broadcast build whose static estimate exceeds the ledger
    budget must be rejected up front instead of OOMing mid-query."""
    from spark_rapids_tpu.exec.exchange import TpuBroadcastExchangeExec
    src = _source(n=4096)
    bytes_est = src.static_bytes_estimate()
    assert bytes_est > 2048
    plan = TpuBroadcastExchangeExec(src)
    conf = RapidsConf({"spark.rapids.memory.device.budgetBytes": 2048})
    report = verify_plan(plan, conf)
    assert not report.ok
    assert "hbm_over_budget" in report.reasons(), report.summary()
    assert report.hbm_budget_bytes == 2048
    # with a real budget the same plan verifies clean
    assert verify_plan(plan, RapidsConf()).ok


def test_rejects_malformed_aqe_wrapper():
    from spark_rapids_tpu.exec.aqe import (TpuAQEJoinExec,
                                           TpuAQEShuffleReadExec)
    report = verify_plan(TpuAQEShuffleReadExec(_source()))
    assert not report.ok
    assert "malformed_aqe_wrapper" in report.reasons(), report.summary()
    report = verify_plan(TpuAQEJoinExec(_source()))
    assert "malformed_aqe_wrapper" in report.reasons(), report.summary()


def test_rejects_unsupported_dtype_map_key():
    """Sorting by a map column: no engine path can compare maps."""
    from spark_rapids_tpu.exec.sort import SortOrder, TpuSortExec
    rb = pa.record_batch({
        "m": pa.array([[("k", 1)], [("j", 2)]],
                      pa.map_(pa.string(), pa.int64())),
        "v": pa.array([1, 2], pa.int64())})
    src = HostBatchSourceExec([rb])
    plan = TpuSortExec([SortOrder(_col("m"))], src)
    report = verify_plan(plan)
    assert not report.ok
    assert "unsupported_dtype" in report.reasons(), report.summary()
    # TopN wires its sort internally (not via children) — same defect,
    # same named rejection
    from spark_rapids_tpu.exec.sort import TpuTopNExec
    topn = TpuTopNExec(3, [SortOrder(_col("m"))],
                       HostBatchSourceExec([rb]))
    report = verify_plan(topn)
    assert not report.ok
    assert "unsupported_dtype" in report.reasons(), report.summary()


# --- fail-fast wiring -------------------------------------------------------

def test_planner_raises_and_kill_switch_disables():
    from spark_rapids_tpu.exec.aqe import TpuAQEShuffleReadExec
    broken = TpuAQEShuffleReadExec(_source())
    with pytest.raises(PlanVerificationError) as ei:
        overrides(broken, RapidsConf())
    assert "malformed_aqe_wrapper" in str(ei.value)
    assert ei.value.report.violations
    # the kill switch turns verification off (plan still mis-executes
    # later, but that is the operator's problem again)
    pp = overrides(broken, RapidsConf(
        {"spark.rapids.sql.verifyPlan": "false"}))
    assert pp is not None


def test_rejection_is_observable(tmp_path):
    """Satellite 6: a rejected plan leaves a plan_rejected event-log
    line and a flight-recorder ring entry — the evidence `profiling
    triage` renders for a query that never ran."""
    from spark_rapids_tpu.exec.aqe import TpuAQEShuffleReadExec
    from spark_rapids_tpu.obs.recorder import RECORDER
    from spark_rapids_tpu.tools.event_log import read_event_logs
    conf = RapidsConf({"spark.rapids.eventLog.dir": str(tmp_path)})
    RECORDER.configure(conf)
    RECORDER.clear()
    broken = TpuAQEShuffleReadExec(_source())
    with pytest.raises(PlanVerificationError):
        overrides(broken, conf)
    events = list(read_event_logs(str(tmp_path)))
    rejected = [e for e in events if e.get("type") == "plan_rejected"]
    assert len(rejected) == 1
    rep = rejected[0]["report"]
    assert rep["ok"] is False
    assert any(v["reason"] == "malformed_aqe_wrapper"
               for v in rep["violations"])
    assert "AQEShuffleReadExec" in rejected[0]["plan"]
    ring = [e for e in RECORDER.snapshot()
            if e.get("kind") == "plan" and e.get("ev") == "plan_rejected"]
    assert ring, "flight-recorder ring has no plan_rejected entry"
    assert "malformed_aqe_wrapper" in ring[-1]["reasons"]


def test_cluster_rejection_emits_incident(tmp_path):
    """Process-cluster path: run_query must reject before scheduling a
    single task, emit a plan_rejected scheduler event, and harvest an
    incident bundle that `profiling triage` renders with the reason."""
    from spark_rapids_tpu.cluster import TpuProcessCluster
    from spark_rapids_tpu.exec.aqe import TpuAQEJoinExec
    from spark_rapids_tpu.tools.profiling import triage_report
    conf = RapidsConf({
        "spark.rapids.flight.dir": str(tmp_path / "flight"),
        "spark.rapids.eventLog.dir": str(tmp_path / "events")})
    broken = TpuAQEJoinExec(_source())
    with TpuProcessCluster(n_workers=1, conf=conf) as c:
        with pytest.raises(PlanVerificationError):
            c.run_query(broken, conf)
        events = [e["event"] for e in c.last_scheduler.events]
        assert "plan_rejected" in events
        assert c.last_incident_path is not None
        text = triage_report(c.last_incident_path)
    assert "plan_rejected" in text
    assert os.path.exists(str(tmp_path / "flight"))
