"""Dual-run tests for math, datetime, and string expression families."""
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.expr import (
    Sqrt, Cbrt, Exp, Log, Log10, Log2, Log1p, Sin, Cos, Tan, Atan, Tanh,
    Signum, ToDegrees, ToRadians, Floor, Ceil, Rint, Pow, Atan2, Hypot,
    Round, BRound, Year, Month, DayOfMonth, Quarter, DayOfWeek, WeekDay,
    DayOfYear, LastDay, Hour, Minute, Second, DateAdd, DateSub, DateDiff,
    AddMonths, MonthsBetween, TruncDate, UnixTimestamp, FromUnixTime,
    Length, Upper, Lower, Substring, ConcatStrings, StartsWith, EndsWith,
    Contains, Like, StringTrim, StringTrimLeft, StringTrimRight,
    Cast, Literal, UnresolvedColumn as col)

from asserts import assert_tpu_and_cpu_expr_equal as check
from data_gen import (gen_table, IntegerGen, FloatGen, StringGen, DateGen,
                      TimestampGen, DecimalGen, ShortGen)


def dtable(n=256, seed=11):
    return gen_table([FloatGen(dt.FLOAT64), FloatGen(dt.FLOAT64)],
                     n=n, seed=seed, names=["a", "b"])


@pytest.mark.parametrize("op", [Sqrt, Cbrt, Exp, Sin, Cos, Tan, Atan, Tanh,
                                Signum, ToDegrees, ToRadians, Rint],
                         ids=lambda o: o.__name__)
def test_unary_math(op):
    check(op(col("a")), dtable(), approx_float=True)


@pytest.mark.parametrize("op", [Log, Log10, Log2, Log1p],
                         ids=lambda o: o.__name__)
def test_log_null_semantics(op):
    check(op(col("a")), dtable(), approx_float=True)


@pytest.mark.parametrize("op", [Pow, Atan2, Hypot], ids=lambda o: o.__name__)
def test_binary_math(op):
    check(op(col("a"), col("b")), dtable(), approx_float=True)


def test_floor_ceil():
    check(Floor(col("a")), dtable())
    check(Ceil(col("a")), dtable())
    rbd = gen_table([DecimalGen(12, 3)], names=["a"])
    check(Floor(col("a")), rbd)
    check(Ceil(col("a")), rbd)


def test_round():
    rb = gen_table([DecimalGen(12, 4)], names=["a"])
    check(Round(col("a"), 2), rb)
    check(BRound(col("a"), 2), rb)
    check(Round(col("a"), 0), rb)
    rbi = gen_table([IntegerGen()], names=["a"])
    check(Round(col("a"), -2), rbi)


# ---- datetime ------------------------------------------------------------

def date_tab(n=256):
    # n bounded so date +/- n days/months stays inside python date range
    return gen_table([DateGen(), DateGen(),
                      IntegerGen(null_frac=0.05, min_val=-10000,
                                 max_val=10000)],
                     n=n, seed=3, names=["a", "b", "n"])


@pytest.mark.parametrize("op", [Year, Month, DayOfMonth, Quarter, DayOfWeek,
                                WeekDay, DayOfYear, LastDay],
                         ids=lambda o: o.__name__)
def test_date_parts(op):
    check(op(col("a")), date_tab())


@pytest.mark.parametrize("op", [Hour, Minute, Second],
                         ids=lambda o: o.__name__)
def test_time_parts(op):
    rb = gen_table([TimestampGen()], names=["a"])
    check(op(col("a")), rb)


def test_date_arith():
    rb = date_tab()
    check(DateAdd(col("a"), col("n")), rb)
    check(DateSub(col("a"), col("n")), rb)
    check(DateDiff(col("a"), col("b")), rb)
    check(AddMonths(col("a"), col("n")), rb)
    check(MonthsBetween(col("a"), col("b")), rb, approx_float=True)


@pytest.mark.parametrize("fmt", ["YEAR", "MONTH", "QUARTER", "WEEK"])
def test_trunc_date(fmt):
    check(TruncDate(col("a"), fmt), date_tab())


def test_unix_roundtrip():
    rb = gen_table([TimestampGen()], names=["a"])
    check(UnixTimestamp(col("a")), rb)
    rb2 = gen_table([IntegerGen(min_val=0, max_val=2_000_000_000)],
                    names=["a"])
    check(FromUnixTime(Cast(col("a"), dt.INT64)), rb2)


def test_epoch_oracle():
    """Pin a few known dates against hand-computed field values."""
    import pyarrow as pa
    import datetime
    dates = [datetime.date(1970, 1, 1), datetime.date(2000, 2, 29),
             datetime.date(1999, 12, 31), datetime.date(2026, 7, 29),
             datetime.date(1900, 3, 1)]
    rb = pa.record_batch({"a": pa.array(dates, pa.date32())})
    assert check(Year(col("a")), rb).to_pylist() == \
        [1970, 2000, 1999, 2026, 1900]
    assert check(Month(col("a")), rb).to_pylist() == [1, 2, 12, 7, 3]
    assert check(DayOfMonth(col("a")), rb).to_pylist() == [1, 29, 31, 29, 1]
    # 1970-01-01 was a Thursday -> Spark dayofweek=5
    assert check(DayOfWeek(col("a")), rb).to_pylist()[0] == 5
    assert check(LastDay(col("a")), rb).to_pylist() == [
        datetime.date(1970, 1, 31), datetime.date(2000, 2, 29),
        datetime.date(1999, 12, 31), datetime.date(2026, 7, 31),
        datetime.date(1900, 3, 31)]


# ---- strings -------------------------------------------------------------

def stable(n=256, **kw):
    return gen_table([StringGen(**kw), StringGen(**kw)], n=n, seed=5,
                     names=["a", "b"])


def test_length_utf8():
    rb = stable()  # includes unicode specials
    check(Length(col("a")), rb)


def test_upper_lower_ascii():
    rb = stable(ascii_only=True)
    check(Upper(col("a")), rb)
    check(Lower(col("a")), rb)


def test_substring():
    rb = stable(ascii_only=True)
    check(Substring(col("a"), Literal(2, dt.INT32), Literal(3, dt.INT32)),
          rb)
    check(Substring(col("a"), Literal(-4, dt.INT32), Literal(2, dt.INT32)),
          rb)
    check(Substring(col("a"), Literal(1, dt.INT32), Literal(100, dt.INT32)),
          rb)
    check(Substring(col("a"), Literal(0, dt.INT32), Literal(2, dt.INT32)),
          rb)


def test_concat():
    rb = stable()
    check(ConcatStrings(col("a"), col("b")), rb)
    check(ConcatStrings(col("a"), Literal("-", dt.STRING), col("b")), rb)


def test_starts_ends_contains():
    import pyarrow as pa
    rb = pa.record_batch({"a": pa.array(
        ["apple pie", "app", "pie", None, "", "a ap app"])})
    assert check(StartsWith(col("a"), "ap"), rb).to_pylist() == \
        [True, True, False, None, False, False]
    assert check(EndsWith(col("a"), "ie"), rb).to_pylist() == \
        [True, False, True, None, False, False]
    assert check(Contains(col("a"), "pp"), rb).to_pylist() == \
        [True, True, False, None, False, True]
    check(Contains(col("a"), ""), rb)


@pytest.mark.parametrize("pattern", ["abc", "ab%", "%bc", "%b%", "a%c", "%",
                                     ""])
def test_like_simple(pattern):
    import pyarrow as pa
    rb = pa.record_batch({"a": pa.array(
        ["abc", "abxc", "ab", "bc", "", None, "aabcc"])})
    e = Like(col("a"), pattern)
    assert e.tpu_supported() is None
    check(e, rb)


def test_like_complex_host_only():
    # round 4: `_` wildcards transpile to the device regex dialect;
    # only patterns outside it (non-ASCII) stay host-only
    e = Like(col("a"), "a_c")
    assert e.tpu_supported() is None
    # non-ASCII + non-simple: outside both the literal shapes and the
    # device regex dialect
    assert Like(col("a"), "caf\u00e9_x").tpu_supported() is not None
    import pyarrow as pa
    from spark_rapids_tpu.expr.base import bind_expr, EvalCtx
    from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
    rb = pa.record_batch({"a": pa.array(["abc", "ac", "abbc", None])})
    bound = bind_expr(e, engine_schema(rb.schema))
    assert bound.eval_cpu(rb, EvalCtx()).to_pylist() == \
        [True, False, False, None]


def test_trim():
    import pyarrow as pa
    rb = pa.record_batch({"a": pa.array(
        ["  hi  ", "hi", "   ", "", None, " a b "])})
    assert check(StringTrim(col("a")), rb).to_pylist() == \
        ["hi", "hi", "", "", None, "a b"]
    check(StringTrimLeft(col("a")), rb)
    check(StringTrimRight(col("a")), rb)


def test_host_string_ops():
    """Host-fallback expressions still honest against Spark semantics."""
    import pyarrow as pa
    from spark_rapids_tpu.expr import (StringReplace, RegExpLike,
                                       RegExpReplace, RegExpExtract,
                                       StringLocate, StringLpad, StringRpad,
                                       StringRepeat, Reverse)
    from spark_rapids_tpu.expr.base import bind_expr, EvalCtx
    from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
    rb = pa.record_batch({"a": pa.array(["hello world", "abcabc", None,
                                         ""])})
    sch = engine_schema(rb.schema)
    ctx = EvalCtx()

    def run(e):
        return bind_expr(e, sch).eval_cpu(rb, ctx).to_pylist()

    assert run(StringReplace(col("a"), "abc", "x")) == \
        ["hello world", "xx", None, ""]
    assert run(RegExpLike(col("a"), "^h.*d$")) == [True, False, None, False]
    assert run(RegExpReplace(col("a"), "[aeiou]", "_")) == \
        ["h_ll_ w_rld", "_bc_bc", None, ""]
    assert run(RegExpExtract(col("a"), "(\\w+) (\\w+)", 2)) == \
        ["world", "", None, ""]
    assert run(StringLocate("bc", col("a"))) == [0, 2, None, 0]
    assert run(StringLpad(col("a"), 5, "*")) == \
        ["hello", "abcab", None, "*****"]
    assert run(StringRpad(col("a"), 13, "!")) == \
        ["hello world!!", "abcabc!!!!!!!", None, "!!!!!!!!!!!!!"]
    assert run(StringRepeat(col("a"), 2)) == \
        ["hello worldhello world", "abcabcabcabc", None, ""]
    assert run(Reverse(col("a"))) == ["dlrow olleh", "cbacba", None, ""]
