"""Device regex transpiler tests (reference: regex transpiler +
cudf-dialect gating — SURVEY.md:175; dual-run + placement asserts)."""
import re

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.exec import HostBatchSourceExec
from spark_rapids_tpu.exec.basic import TpuProjectExec
from spark_rapids_tpu.expr import UnresolvedColumn as col
from spark_rapids_tpu.expr.base import Alias
from spark_rapids_tpu.expr.strings import Like, RegExpLike
from spark_rapids_tpu.ops.regex import (RegexUnsupported, compile_pattern,
                                        like_to_regex)
from spark_rapids_tpu.planner import TpuOverrides

from asserts import assert_tpu_and_cpu_plan_equal

DIALECT_PATTERNS = [
    "abc", "^abc", "abc$", "^abc$", "a.c", "ab*c", "ab+c", "ab?c",
    "[abc]x", "[^abc]x", "[a-f0-9]+", "\\d+", "\\w+z", "\\s",
    "cat|dog|bird", "^(?:)?".replace("(?:)?", "x*"), "a[b-d]*e$",
    "^\\d\\d-\\d\\d", "x.*y", ".*", "a*", "^$", "colou?r",
    "[A-Z][a-z]+", "end\\.$", "a|", "\\.com$",
]

STRINGS = ["", "abc", "xabc", "abcx", "a c", "abbbc", "ac", "bx", "zx",
           "deadbeef", "12-34x", "x123y", "cat", "hotdog", "birds",
           "color", "colour", "Widget", "a.c", "end.", "foo.com",
           "aaa", "cde", None, "CAT", "42", " ", "ab\ncd"]


def _source():
    return HostBatchSourceExec(
        [pa.record_batch({"s": pa.array(STRINGS, pa.string())})])


@pytest.mark.parametrize("pattern", DIALECT_PATTERNS)
def test_rlike_device_matches_host_re(pattern):
    plan = TpuProjectExec(
        [Alias(RegExpLike(col("s"), pattern), "m")], _source())
    pp = TpuOverrides().apply(plan)
    assert not pp.fallback_nodes(), \
        f"{pattern!r} should be on device: {pp.explain('ALL')}"
    got = pp.collect().column("m").to_pylist()
    want = [None if s is None else bool(re.search(pattern, s))
            for s in STRINGS]
    assert got == want, (pattern, list(zip(STRINGS, got, want)))


@pytest.mark.parametrize("pattern", [
    "(ab)+", "a{2,3}", "(?i)abc", "a(?=b)", "\\bword", "a|b|(cd)",
    "café",
])
def test_rlike_outside_dialect_falls_back(pattern):
    plan = TpuProjectExec(
        [Alias(RegExpLike(col("s"), pattern), "m")], _source())
    pp = TpuOverrides().apply(plan)
    assert pp.fallback_nodes(), f"{pattern!r} must fall back"
    # the planner-placed (host) path still answers like the oracle
    from spark_rapids_tpu.exec.base import collect_arrow_cpu
    got = pp.collect().column("m").to_pylist()
    want = collect_arrow_cpu(plan).column("m").to_pylist()
    assert got == want


def test_rlike_dual_run_generated_strings():
    from data_gen import StringGen, gen_table
    rb = gen_table([StringGen(max_len=12, charset="abc01 .",
                              null_frac=0.15)], 300, seed=9,
                   names=["s"])
    for pattern in ("^a", "b$", "[ab]+c", "\\d\\d", "a.*c", "c|0"):
        plan = TpuProjectExec(
            [Alias(RegExpLike(col("s"), pattern), "m")],
            HostBatchSourceExec([rb]))
        assert_tpu_and_cpu_plan_equal(plan, label=pattern)


def test_like_general_patterns_on_device():
    # beyond the literal shapes: _ wildcards and mixed %_% now device
    from data_gen import StringGen, gen_table
    rb = gen_table([StringGen(max_len=10, charset="abcx_%",
                              null_frac=0.1)], 200, seed=3, names=["s"])
    for pattern in ("a_c", "%a_c%", "a%b%c", "_bc%", "%a%b%"):
        plan = TpuProjectExec(
            [Alias(Like(col("s"), pattern), "m")],
            HostBatchSourceExec([rb]))
        pp = TpuOverrides().apply(plan)
        assert not pp.fallback_nodes(), pattern
        assert_tpu_and_cpu_plan_equal(plan, label=pattern)


def test_like_to_regex_translation():
    assert like_to_regex("a%b_c") == "^a[\\s\\S]*b[\\s\\S]c$"
    assert like_to_regex("100\\%") == "^100%$"
    assert like_to_regex("a.b") == "^a\\.b$"


def test_like_wildcards_match_newlines_on_device():
    # SQL LIKE wildcards cross newlines; regex '.' would not (the bug a
    # review pass caught): device and CPU must agree on \n-bearing rows
    rb = pa.record_batch({"s": pa.array(
        ["a\nb", "a\nb\nc", "axb", "ab", None])})
    for pattern in ("a_b", "a%b%c", "%\n%"):
        plan = TpuProjectExec(
            [Alias(Like(col("s"), pattern), "m")],
            HostBatchSourceExec([rb]))
        pp = TpuOverrides().apply(plan)
        assert not pp.fallback_nodes(), pattern
        assert_tpu_and_cpu_plan_equal(plan, label=pattern)


UNICODE_STRINGS = ["é", "aé", "éa", "日本", "日本語x", "naïve", "𝄞clef",
                   "mixé\nline", "", "plain", "ß", "ﬃ", None, "aßc",
                   "é" * 5, "𝄞", "aα0", "Ωmega"]


def test_byte_sensitive_atoms_utf8_correct_on_device():
    """'é' LIKE '_' must be TRUE on device (one character, two bytes):
    `.`/`_`/negated classes compile to whole-UTF-8-character automata
    (ADVICE r4 medium — the byte-level automaton silently diverged)."""
    rb = pa.record_batch({"s": pa.array(UNICODE_STRINGS, pa.string())})
    for pattern in ("_", "__", "_a", "a_", "%_%", "__%", "_\n_%"):
        plan = TpuProjectExec(
            [Alias(Like(col("s"), pattern), "m")],
            HostBatchSourceExec([rb]))
        pp = TpuOverrides().apply(plan)
        assert not pp.fallback_nodes(), pattern
        assert_tpu_and_cpu_plan_equal(plan, label=f"LIKE {pattern}")


def test_rlike_utf8_data_parity():
    # oracle with re.ASCII: Spark regexes are Java regexes (\w \d \s
    # are ASCII classes); `.`/negated classes still match whole
    # non-ASCII characters
    rb = pa.record_batch({"s": pa.array(UNICODE_STRINGS, pa.string())})
    for pattern in ("^.$", "..", "^[^a]+$", "a.", ".*x$", "^\\w+$",
                    "[^x]*", "\\S+", "^\\W+$", "^[^абв]+$"
                    .replace("абв", "xyz")):
        plan = TpuProjectExec(
            [Alias(RegExpLike(col("s"), pattern), "m")],
            HostBatchSourceExec([rb]))
        pp = TpuOverrides().apply(plan)
        assert not pp.fallback_nodes(), pattern
        got = pp.collect().column("m").to_pylist()
        want = [None if s is None else bool(re.search(pattern, s,
                                                      re.ASCII))
                for s in UNICODE_STRINGS]
        assert got == want, (pattern,
                             list(zip(UNICODE_STRINGS, got, want)))


def test_compile_rejects_and_fuzz_parity():
    for bad in ("(a)", "a{2}", "a**", "[z-a]", "\\q"):
        with pytest.raises(RegexUnsupported):
            compile_pattern(bad)
    # randomized parity sweep on the dialect
    rng = np.random.default_rng(0)
    alphabet = "abc0 ."
    strings = ["".join(rng.choice(list(alphabet),
                                  rng.integers(0, 10)).tolist())
               for _ in range(60)]
    import jax
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    batch = arrow_to_device(
        pa.record_batch({"s": pa.array(strings, pa.string())}))
    from spark_rapids_tpu.ops.regex import regex_match_device
    for pattern in ("a+b", "[ab]c*", "^c|0$", "\\d", "\\s", "a.b",
                    "b?c", "[^a]+$"):
        prog = compile_pattern(pattern)
        got = np.asarray(jax.device_get(
            regex_match_device(batch.column(0), prog)))[:len(strings)]
        want = np.array([bool(re.search(pattern, s)) for s in strings])
        assert (got == want).all(), \
            (pattern, [s for s, g, w in zip(strings, got, want)
                       if g != w])


# --- match positions: regexp_replace / regexp_extract (VERDICT r4 #7) ------

from spark_rapids_tpu.expr.strings import RegExpExtract, RegExpReplace

REPLACE_STRINGS = ["abc123def45", "", "xyz", "a1b2c3", "123", "zz99z",
                   "no digits", None, "7", "mix 42 and 7 end",
                   "aa11bb22cc", "é12é34", "x" * 40 + "9end", "9", "99",
                   "a,b,,c", "  pad  "]


def _rsource():
    return HostBatchSourceExec(
        [pa.record_batch({"s": pa.array(REPLACE_STRINGS, pa.string())})])


def test_regexp_replace_device_matrix():
    cases = [(r"\d+", "#"), (r"\d+", ""), (r"\d", "NUM"),
             (r"[a-z]+", "_"), (r"9$", "!"), (r"^[a-z]+", "<>"),
             (r",+", ";"), (r"\s+", " ")]
    for pattern, repl in cases:
        plan = TpuProjectExec(
            [Alias(RegExpReplace(col("s"), pattern, repl), "r")],
            _rsource())
        pp = TpuOverrides().apply(plan)
        assert not pp.fallback_nodes(), (pattern, pp.explain("ALL"))
        got = pp.collect().column("r").to_pylist()
        want = [None if s is None else re.sub(pattern, repl, s, flags=re.ASCII)
                for s in REPLACE_STRINGS]
        assert got == want, (pattern, repl,
                             [x for x in zip(REPLACE_STRINGS, got, want)
                              if x[1] != x[2]])


def test_regexp_replace_fallback_shapes():
    # alternation (Java leftmost-first), empty-matchable, $group repl
    for pattern, repl in [("a|ab", "X"), ("a*", "X"), ("(a)", "$1")]:
        plan = TpuProjectExec(
            [Alias(RegExpReplace(col("s"), pattern, repl), "r")],
            _rsource())
        pp = TpuOverrides().apply(plan)
        assert pp.fallback_nodes(), pattern
        got = pp.collect().column("r").to_pylist()
        from spark_rapids_tpu.exec.base import collect_arrow_cpu
        want = collect_arrow_cpu(plan).column("r").to_pylist()
        assert got == want, pattern


def test_regexp_extract_device():
    for pattern, group in [(r"\d+", 0), (r"(\d+)", 1), (r"[a-z]+\d", 0),
                           (r"(x+9)", 1)]:
        plan = TpuProjectExec(
            [Alias(RegExpExtract(col("s"), pattern, group), "e")],
            _rsource())
        pp = TpuOverrides().apply(plan)
        assert not pp.fallback_nodes(), (pattern, pp.explain("ALL"))
        got = pp.collect().column("e").to_pylist()
        rx = re.compile(pattern, re.ASCII)

        def oracle(s):
            m = rx.search(s)
            if m is None:
                return ""
            g = m.group(group)
            return g if g is not None else ""
        want = [None if s is None else oracle(s) for s in REPLACE_STRINGS]
        assert got == want, (pattern, list(zip(REPLACE_STRINGS, got,
                                               want)))


def test_regexp_extract_inner_group_falls_back():
    plan = TpuProjectExec(
        [Alias(RegExpExtract(col("s"), r"([a-z])(\d)", 2), "e")],
        _rsource())
    pp = TpuOverrides().apply(plan)
    assert pp.fallback_nodes()
    from spark_rapids_tpu.exec.base import collect_arrow_cpu
    assert pp.collect().column("e").to_pylist() == \
        collect_arrow_cpu(plan).column("e").to_pylist()
