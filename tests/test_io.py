"""File scan + write tests (reference: integration_tests parquet_test.py /
orc_test.py / csv_test.py / *_write_test.py — SURVEY.md §4.1; reader
modes + round-trip shapes from §2.2-B Scans/Writes)."""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from asserts import assert_tpu_and_cpu_plan_equal
from data_gen import (all_basic_gens, gen_table, DateGen, DecimalGen,
                      IntegerGen, LongGen, FloatGen, StringGen)

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow, \
    collect_arrow_cpu
from spark_rapids_tpu.exec.basic import TpuFilterExec, TpuProjectExec
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.expr import (Alias, And, GreaterThanOrEqual, LessThan,
                                   Literal, Multiply,
                                   UnresolvedColumn as col)
from spark_rapids_tpu.expr.aggregates import Sum
from spark_rapids_tpu.io import (FileSplit, TpuFileScanExec,
                                 TpuFileWriteExec, plan_splits)
from spark_rapids_tpu.planner import overrides


def _canon(table):
    """to_pydict with NaN mapped to a comparable token (NaN != NaN)."""
    import math
    return {name: ["NaN" if isinstance(v, float) and math.isnan(v) else v
                   for v in vals]
            for name, vals in table.to_pydict().items()}


def _write_parquet(tmp_path, rb, name="data.parquet", row_group_size=None):
    p = os.path.join(str(tmp_path), name)
    pq.write_table(pa.Table.from_batches([rb]), p,
                   row_group_size=row_group_size)
    return p


def test_parquet_scan_all_basic_types(tmp_path):
    rb = gen_table(all_basic_gens, n=500)
    p = _write_parquet(tmp_path, rb)
    assert_tpu_and_cpu_plan_equal(TpuFileScanExec([p]))


def test_parquet_scan_multi_file_reader_modes(tmp_path):
    paths = []
    for i in range(6):
        rb = gen_table([IntegerGen(), StringGen(), FloatGen(dt.FLOAT64)],
                       n=200 + i, seed=100 + i)
        paths.append(_write_parquet(tmp_path, rb, f"f{i}.parquet"))
    results = {}
    for mode in ("PERFILE", "MULTITHREADED", "COALESCING"):
        conf = RapidsConf({
            "spark.rapids.sql.format.parquet.reader.type": mode})
        scan = TpuFileScanExec(paths, conf=conf)
        results[mode] = assert_tpu_and_cpu_plan_equal(scan, conf=conf)
    # all reader modes agree (same rows, same order: split-ordered)
    assert _canon(results["PERFILE"]) == _canon(results["MULTITHREADED"])
    assert sorted(map(tuple, results["PERFILE"].to_pylist()[0:0])) == []
    assert results["COALESCING"].num_rows == results["PERFILE"].num_rows


def test_parquet_row_group_splits(tmp_path):
    rb = gen_table([LongGen(null_frac=0)], n=4000)
    p = _write_parquet(tmp_path, rb, row_group_size=256)
    splits = plan_splits([p], "parquet", max_partition_bytes=8 << 10)
    assert len(splits) > 1
    covered = [g for s in splits for g in s.row_groups]
    assert covered == sorted(set(covered))  # disjoint + complete
    assert_tpu_and_cpu_plan_equal(TpuFileScanExec([p]))


def test_parquet_column_projection(tmp_path):
    rb = gen_table([IntegerGen(), StringGen(), DateGen()],
                   names=["a", "b", "c"])
    p = _write_parquet(tmp_path, rb)
    scan = TpuFileScanExec([p], columns=["c", "a"])
    assert scan.output_schema.names == ["c", "a"]
    assert_tpu_and_cpu_plan_equal(scan)


def test_parquet_predicate_pushdown_prunes_and_stays_correct(tmp_path):
    # ascending key -> row group stats are tight -> pruning provable
    n = 4096
    key = pa.array(np.arange(n, dtype=np.int64))
    val = pa.array(np.arange(n, dtype=np.float64) * 0.5)
    rb = pa.record_batch({"k": key, "v": val})
    p = _write_parquet(tmp_path, rb, row_group_size=512)
    cond = And(GreaterThanOrEqual(col("k"), Literal(1000, dt.INT64)),
               LessThan(col("k"), Literal(1500, dt.INT64)))
    scan = TpuFileScanExec([p], pushdown=cond)
    plan = TpuFilterExec(cond, scan)
    out = assert_tpu_and_cpu_plan_equal(plan)
    assert out.num_rows == 500
    # pruning really skipped groups: decode only touches 2 of 8
    from spark_rapids_tpu.io.scan import _decode_split, _simple_conjuncts
    rbs = _decode_split(FileSplit(p), "parquet", None, 1 << 20,
                        _simple_conjuncts(cond))
    assert sum(r.num_rows for r in rbs) <= 1024


def test_parquet_device_decode_matrix(tmp_path):
    """Device page decode (VERDICT r4 #1): PLAIN + dictionary/RLE
    bit-packed chunks, nullable and required, across codecs, against
    both the CPU oracle and the host-decode path; dictionary columns
    must cross the link SMALLER than decoded."""
    rng = np.random.default_rng(7)
    n = 30_000
    arrays = {
        "dict_i32": pa.array(rng.integers(0, 9, n).astype(np.int32)),
        "dict_f32": pa.array((rng.integers(0, 7, n) / 8)
                             .astype(np.float32)),
        "plain_f64": pa.array(rng.uniform(0, 1, n)),
        "i64": pa.array(rng.integers(-(1 << 40), 1 << 40, n)),
        "b": pa.array(rng.integers(0, 2, n).astype(bool)),
        "date": pa.array(rng.integers(8000, 9000, n).astype(np.int32))
        .cast(pa.date32()),
        "rle_sorted": pa.array(np.sort(rng.integers(0, 4, n))
                               .astype(np.int64)),
        "null_i32": pa.array(rng.integers(0, 50, n).astype(np.int32),
                             mask=rng.uniform(0, 1, n) < 0.25),
        "all_null": pa.array([None] * n, type=pa.int64()),
        "s": pa.array(["x" + str(i % 13) for i in range(n)]),  # host path
    }
    for codec in ("snappy", "zstd"):
        p = os.path.join(str(tmp_path), f"m_{codec}.parquet")
        pq.write_table(pa.table(arrays), p, row_group_size=8000,
                       compression=codec,
                       dictionary_pagesize_limit=32 << 10,
                       data_page_size=8 << 10)
        scan = TpuFileScanExec([p])
        ctx = ExecCtx()
        got_dev = pa.Table.from_batches(
            [b for b in map(_to_arrow, scan.execute(ctx))])
        want = pa.Table.from_batches(list(scan.execute_cpu(ExecCtx())))
        assert _canon(got_dev) == _canon(want), codec
        m = ctx.metrics[scan.node_label()]
        assert m["encodedBytes"].value > 0
        # dict/RLE savings on this data dominate the PLAIN columns
        assert m["encodedBytes"].value < m["decodedBytes"].value, codec
        # host-decode path (conf off) agrees
        off = RapidsConf({
            "spark.rapids.sql.format.parquet.deviceDecode.enabled":
                "false"})
        got_host = pa.Table.from_batches(
            [b for b in map(_to_arrow,
                            TpuFileScanExec([p]).execute(ExecCtx(off)))])
        assert _canon(got_dev) == _canon(got_host), codec


def _to_arrow(batch):
    from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
    return device_to_arrow(batch)


def test_parquet_device_decode_dict_strings(tmp_path):
    """Dictionary-encoded STRING chunks decode on device: indices cross
    the link at bit-packed width, the device gathers the strings from
    the uploaded dictionary (unicode, nulls, empties included)."""
    rng = np.random.default_rng(9)
    n = 20_000
    cats = ["alpha", "β-unicode", "", "a-much-longer-category-name",
            "x", "日本語"]
    vals = [cats[i] for i in rng.integers(0, len(cats), n)]
    arrays = {
        "s": pa.array(vals, pa.string()),
        "sn": pa.array([None if rng.uniform() < 0.3 else v
                        for v in vals], pa.string()),
        "i": pa.array(rng.integers(0, 5, n).astype(np.int32)),
    }
    p = os.path.join(str(tmp_path), "ds.parquet")
    pq.write_table(pa.table(arrays), p, row_group_size=8000,
                   compression="snappy")
    scan = TpuFileScanExec([p])
    ctx = ExecCtx()
    got = pa.Table.from_batches([_to_arrow(b) for b in scan.execute(ctx)])
    want = pa.Table.from_batches(list(scan.execute_cpu(ExecCtx())))
    assert _canon(got) == _canon(want)
    m = ctx.metrics[scan.node_label()]
    # the string chunks were device-decoded (they count toward encoded)
    assert m["encodedBytes"].value > 0
    # high-cardinality strings overflow the dictionary into PLAIN pages
    # mid-chunk — since the envelope widened, those decode on device too
    many = pa.table({"u": pa.array([f"unique-{i}" * 3
                                    for i in range(n)])})
    p2 = os.path.join(str(tmp_path), "plain.parquet")
    pq.write_table(many, p2, dictionary_pagesize_limit=1024,
                   compression="snappy")
    assert_tpu_and_cpu_plan_equal(TpuFileScanExec([p2]))
    _, dev2, fb2 = _scan_coverage(p2)
    assert fb2 == 0 and dev2 > 0, (dev2, fb2)


def test_parquet_device_decode_coalesced_bit_exact(tmp_path):
    """Quantized-arena/coalesced path vs the per-row-group path vs the
    CPU oracle, across dtype lanes, null patterns, and PLAIN/dict/RLE
    mixes (data_gen generators + crafted encoding-specific columns)."""
    from data_gen import BooleanGen, DoubleGen
    rng = np.random.default_rng(11)
    n = 16_000
    rb = gen_table([IntegerGen(null_frac=0.3), LongGen(null_frac=0),
                    DoubleGen(), BooleanGen(null_frac=0),
                    StringGen(max_len=9, null_frac=0.2), DateGen()],
                   n=n, seed=5, names=["ni", "l", "d", "b", "s", "dt"])
    arrays = {name: rb.column(i) for i, name in enumerate(rb.schema.names)}
    grp = np.arange(n) // 3000
    # heterogeneous dictionaries: each row group's value set is disjoint
    arrays["dict_i32"] = pa.array(
        (rng.integers(0, 7, n) + grp * 1000).astype(np.int32))
    arrays["rle"] = pa.array(np.sort(rng.integers(0, 5, n))
                             .astype(np.int64))
    arrays["plain_f32"] = pa.array(rng.uniform(0, 1, n)
                                   .astype(np.float32))
    p = os.path.join(str(tmp_path), "c.parquet")
    pq.write_table(pa.table(arrays), p, row_group_size=3000,
                   compression="snappy")
    want = pa.Table.from_batches(
        list(TpuFileScanExec([p]).execute_cpu(ExecCtx())))
    n_batches = {}
    for label, target in (("per_group", "0"), ("coalesced", "1g")):
        conf = RapidsConf(
            {"spark.rapids.sql.scan.coalesceTargetBytes": target})
        scan = TpuFileScanExec([p], conf=conf)
        bs = [_to_arrow(b) for b in scan.execute(ExecCtx(conf))]
        n_batches[label] = len(bs)
        assert _canon(pa.Table.from_batches(bs)) == _canon(want), label
    # the coalescer genuinely fused row groups into fewer dispatches
    assert n_batches["per_group"] == 6
    assert n_batches["coalesced"] < n_batches["per_group"]


def test_parquet_device_decode_jit_cache_quantized(tmp_path):
    """Heterogeneous row groups of one schema must NOT compile one
    fused-decode program per row group: the quantized arena collapses
    the JIT cache to a couple of variants per capacity bucket, and a
    re-scan is fully cache-hot."""
    from spark_rapids_tpu.io import parquet_device as pd_
    rng = np.random.default_rng(13)
    n = 36_000
    grp = np.arange(n) // 8000  # 4 full groups + one 4000-row tail
    arrays = {
        "a": pa.array((rng.integers(0, 6, n) + grp * 100)
                      .astype(np.int32)),
        "b": pa.array(rng.integers(0, 50, n).astype(np.int64),
                      mask=rng.uniform(0, 1, n) < 0.15),
        "c": pa.array([f"g{g}x{i % 9}" for i, g in enumerate(grp)]),
    }
    p = os.path.join(str(tmp_path), "h.parquet")
    pq.write_table(pa.table(arrays), p, row_group_size=8000,
                   compression="zstd")
    conf = RapidsConf(
        {"spark.rapids.sql.scan.coalesceTargetBytes": "0"})
    pd_._JIT_CACHE.clear()
    scan = TpuFileScanExec([p], conf=conf)
    got = pa.Table.from_batches(
        [_to_arrow(b) for b in scan.execute(ExecCtx(conf))])
    want = pa.Table.from_batches(
        list(TpuFileScanExec([p]).execute_cpu(ExecCtx())))
    assert _canon(got) == _canon(want)
    keys = [k for k in pd_._JIT_CACHE if k[0] == "rg"]
    caps = {k[1] for k in keys}
    # 5 heterogeneous row groups, 2 capacity buckets (8192 + the tail's
    # 4096): at most a couple of program variants per capacity bucket —
    # the raw-offset cache key compiled one program PER GROUP
    assert len(keys) < 5, keys
    assert len(keys) <= 2 * len(caps), keys
    # second scan: zero new compilations
    before = len(pd_._JIT_CACHE)
    list(TpuFileScanExec([p], conf=conf).execute(ExecCtx(conf)))
    assert len(pd_._JIT_CACHE) == before


def _scan_coverage(path, conf=None):
    """(arrow table via device path, deviceChunks, fallbackChunks)."""
    conf = conf or RapidsConf()
    scan = TpuFileScanExec([path], conf=conf)
    ctx = ExecCtx(conf)
    got = pa.Table.from_batches([_to_arrow(b) for b in scan.execute(ctx)])
    m = ctx.metrics[scan.node_label()]
    return got, int(m["deviceChunks"].value), int(m["fallbackChunks"].value)


def test_parquet_device_decode_fallback_encodings(tmp_path):
    """DELTA_BINARY_PACKED is now INSIDE the device envelope;
    byte-stream-split is still outside — the per-chunk fallback keeps
    results right and the coverage counters tell the two apart."""
    rng = np.random.default_rng(8)
    n = 5000
    tab = pa.table({
        "delta": pa.array(rng.integers(0, 1 << 30, n).astype(np.int64)),
        "bss": pa.array(rng.uniform(0, 1, n).astype(np.float32)),
        "ok": pa.array(rng.integers(0, 5, n).astype(np.int32)),
    })
    p = os.path.join(str(tmp_path), "enc.parquet")
    pq.write_table(tab, p, use_dictionary=False,
                   column_encoding={"delta": "DELTA_BINARY_PACKED",
                                    "bss": "BYTE_STREAM_SPLIT",
                                    "ok": "PLAIN"})
    assert_tpu_and_cpu_plan_equal(TpuFileScanExec([p]))
    got, dev, fb = _scan_coverage(p)
    assert fb == 1, (dev, fb)   # only the BYTE_STREAM_SPLIT chunk
    assert dev == 2, (dev, fb)  # delta + plain decode on device


def test_parquet_device_decode_v2_pages(tmp_path):
    """DATA_PAGE_V2 files decode ON DEVICE now (levels split from the
    data region, no length prefix, nulls from the page header):
    bit-exact vs the CPU oracle, zero fallback chunks."""
    rng = np.random.default_rng(21)
    n = 12_000
    arrays = {
        "i32": pa.array(rng.integers(0, 9, n).astype(np.int32)),
        "ni64": pa.array(rng.integers(0, 60, n).astype(np.int64),
                         mask=rng.uniform(0, 1, n) < 0.3),
        "s": pa.array([None if i % 9 == 0 else f"v{i % 13}"
                       for i in range(n)]),
        "b": pa.array(rng.integers(0, 2, n).astype(bool)),
        "all_null": pa.array([None] * n, type=pa.int32()),
    }
    p = os.path.join(str(tmp_path), "v2.parquet")
    pq.write_table(pa.table(arrays), p, data_page_version="2.0",
                   row_group_size=4000, compression="zstd",
                   data_page_size=4 << 10)
    got, dev, fb = _scan_coverage(p)
    want = pa.Table.from_batches(
        list(TpuFileScanExec([p]).execute_cpu(ExecCtx())))
    assert _canon(got) == _canon(want)
    assert fb == 0 and dev > 0, (dev, fb)


def test_parquet_device_decode_plain_strings_matrix(tmp_path):
    """PLAIN BYTE_ARRAY strings decode on device (host walks the
    length prefixes into the store, device gathers the characters):
    nulls, empty strings, unicode, v1 AND v2 pages, and the coalesced
    path, all bit-exact vs the CPU oracle with zero fallbacks."""
    rng = np.random.default_rng(23)
    n = 12_000
    cats = ["", "alpha", "β-unicode", "a-much-longer-plain-value",
            "日本語テキスト", "x"]
    arrays = {
        "ps": pa.array([None if rng.uniform() < 0.25
                        else cats[i % len(cats)] + str(i % 7)
                        for i in range(n)]),
        "pb": pa.array([None if i % 17 == 0 else b"\x00bin%d" % (i % 5)
                        for i in range(n)], pa.binary()),
        "i": pa.array(rng.integers(0, 1 << 16, n).astype(np.int32)),
    }
    for ver, codec in (("1.0", "snappy"), ("2.0", "zstd")):
        p = os.path.join(str(tmp_path), f"ps_{ver}.parquet")
        pq.write_table(pa.table(arrays), p, use_dictionary=False,
                       data_page_version=ver, compression=codec,
                       row_group_size=3000, data_page_size=8 << 10)
        want = pa.Table.from_batches(
            list(TpuFileScanExec([p]).execute_cpu(ExecCtx())))
        for target in ("0", "1g"):
            conf = RapidsConf(
                {"spark.rapids.sql.scan.coalesceTargetBytes": target})
            got, dev, fb = _scan_coverage(p, conf)
            assert _canon(got) == _canon(want), (ver, target)
            assert fb == 0 and dev > 0, (ver, target, dev, fb)


def test_parquet_device_decode_delta_matrix(tmp_path):
    """DELTA_BINARY_PACKED int32/int64 (negative deltas, nulls,
    multi-page chunks — the device prefix sum restarts per page) and
    DELTA_LENGTH_BYTE_ARRAY strings (nulls, empties): bit-exact vs the
    CPU oracle across per-group and coalesced dispatch, zero
    fallbacks."""
    rng = np.random.default_rng(29)
    n = 16_000
    arrays = {
        "d32": pa.array((rng.integers(-100, 100, n).cumsum()
                         % 1_000_000).astype(np.int32)),
        "d64": pa.array(rng.integers(-1000, 1000, n).cumsum()
                        .astype(np.int64),
                        mask=rng.uniform(0, 1, n) < 0.2),
        "dls": pa.array([None if i % 11 == 0 else
                         ["", f"dl-{i % 53}", "長い" * (i % 4)][i % 3]
                        for i in range(n)]),
    }
    p = os.path.join(str(tmp_path), "delta.parquet")
    pq.write_table(pa.table(arrays), p, use_dictionary=False,
                   compression="snappy", row_group_size=4000,
                   data_page_size=4 << 10,
                   column_encoding={"d32": "DELTA_BINARY_PACKED",
                                    "d64": "DELTA_BINARY_PACKED",
                                    "dls": "DELTA_LENGTH_BYTE_ARRAY"})
    want = pa.Table.from_batches(
        list(TpuFileScanExec([p]).execute_cpu(ExecCtx())))
    for target in ("0", "1g"):
        conf = RapidsConf(
            {"spark.rapids.sql.scan.coalesceTargetBytes": target})
        got, dev, fb = _scan_coverage(p, conf)
        assert _canon(got) == _canon(want), target
        assert fb == 0 and dev > 0, (target, dev, fb)


def test_delta_stream_truncation_classified():
    """A truncated DELTA stream must surface as a classified
    HostFallback(reason='truncated') — never an IndexError escaping the
    per-chunk fallback net (code-review r7)."""
    from spark_rapids_tpu.io.parquet_device import (
        HostFallback, _decode_delta_ints, _plan_delta_page)
    # valid header (block 128, 4 miniblocks, 100 values, first 0) with
    # the block payload cut off
    hdr = b"\x80\x01" + b"\x04" + b"\x64" + b"\x00"
    for trunc in (hdr,                      # cut at min_delta
                  hdr + b"\x02",            # cut inside the widths
                  hdr + b"\x02" + b"\x08" * 4):  # widths, no payload
        with pytest.raises(HostFallback) as ei:
            _decode_delta_ints(trunc, 0)
        assert ei.value.reason == "truncated", trunc
        with pytest.raises(HostFallback) as ei:
            _plan_delta_page(trunc, 0, 100)
        assert ei.value.reason == "truncated", trunc


def test_parquet_device_decode_mixed_dict_plain_strings(tmp_path):
    """A chunk whose dictionary page overflows mid-write (dict pages
    then PLAIN pages in ONE column chunk) decodes on device: dict runs
    index the dictionary slice of the store, identity runs index their
    page's slice — nulls included, coalesced included."""
    rng = np.random.default_rng(31)
    n = 20_000
    tab = pa.table({
        "u": pa.array([None if i % 13 == 0
                       else f"val-{i}-{'pad' * (i % 3)}"
                       for i in range(n)]),
        "k": pa.array(rng.integers(0, 5, n).astype(np.int32)),
    })
    p = os.path.join(str(tmp_path), "mixed.parquet")
    pq.write_table(tab, p, dictionary_pagesize_limit=2048,
                   compression="zstd", row_group_size=5000,
                   data_page_size=4096)
    want = pa.Table.from_batches(
        list(TpuFileScanExec([p]).execute_cpu(ExecCtx())))
    for target in ("0", "1g"):
        conf = RapidsConf(
            {"spark.rapids.sql.scan.coalesceTargetBytes": target})
        got, dev, fb = _scan_coverage(p, conf)
        assert _canon(got) == _canon(want), target
        assert fb == 0 and dev > 0, (target, dev, fb)


def test_parquet_device_decode_string_jit_cache_quantized(tmp_path):
    """String-gather variants share the quantized JIT cache: similar
    heterogeneous PLAIN-string row groups must collapse to a couple of
    fused-program variants per capacity bucket, and a re-scan compiles
    nothing new."""
    from spark_rapids_tpu.io import parquet_device as pd_
    rng = np.random.default_rng(37)
    n = 24_000
    grp = np.arange(n) // 8000
    tab = pa.table({
        "s": pa.array([f"g{g}-{'x' * int(rng.integers(3, 9))}-{i % 11}"
                       for i, g in enumerate(grp)]),
        "i": pa.array((rng.integers(0, 50, n) + grp * 100)
                      .astype(np.int64)),
    })
    p = os.path.join(str(tmp_path), "sq.parquet")
    pq.write_table(tab, p, use_dictionary=False, compression="snappy",
                   row_group_size=8000)
    conf = RapidsConf(
        {"spark.rapids.sql.scan.coalesceTargetBytes": "0"})
    pd_._JIT_CACHE.clear()
    got, dev, fb = _scan_coverage(p, conf)
    assert fb == 0, (dev, fb)
    want = pa.Table.from_batches(
        list(TpuFileScanExec([p]).execute_cpu(ExecCtx())))
    assert _canon(got) == _canon(want)
    keys = [k for k in pd_._JIT_CACHE if k[0] == "rg"]
    caps = {k[1] for k in keys}
    assert len(keys) <= 2 * len(caps), keys
    before = len(pd_._JIT_CACHE)
    list(TpuFileScanExec([p], conf=conf).execute(ExecCtx(conf)))
    assert len(pd_._JIT_CACHE) == before


def test_csv_scan(tmp_path):
    rb = gen_table([IntegerGen(), FloatGen(dt.FLOAT64),
                    StringGen(ascii_only=True,
                              charset="abcdefgh123")], n=300)
    import pyarrow.csv as pcsv
    p = os.path.join(str(tmp_path), "data.csv")
    pcsv.write_csv(pa.Table.from_batches([rb]), p)
    assert_tpu_and_cpu_plan_equal(TpuFileScanExec([p], fmt="csv"))


def test_json_scan(tmp_path):
    rb = gen_table([IntegerGen(), LongGen(), StringGen(ascii_only=True)],
                   n=200)
    p = os.path.join(str(tmp_path), "data.json")
    with open(p, "w") as f:
        for row in pa.Table.from_batches([rb]).to_pylist():
            import json
            f.write(json.dumps(row) + "\n")
    assert_tpu_and_cpu_plan_equal(TpuFileScanExec([p], fmt="json"))


def test_orc_scan(tmp_path):
    from pyarrow import orc
    rb = gen_table([IntegerGen(), LongGen(), FloatGen(dt.FLOAT64),
                    StringGen()], n=300)
    p = os.path.join(str(tmp_path), "data.orc")
    orc.write_table(pa.Table.from_batches([rb]), p)
    assert_tpu_and_cpu_plan_equal(TpuFileScanExec([p], fmt="orc"))


@pytest.mark.parametrize("fmt", ["parquet", "orc", "csv"])
def test_write_round_trip(tmp_path, fmt):
    """BASELINE config-5 shape: write via device path and CPU path, read
    both back, results equal (write dual-run)."""
    gens = [IntegerGen(), LongGen(), FloatGen(dt.FLOAT64)]
    if fmt != "csv":
        gens += [StringGen(), DateGen()]
    rb = gen_table(gens, n=700)
    from spark_rapids_tpu.exec.base import HostBatchSourceExec
    src = HostBatchSourceExec([rb])
    dev_dir = os.path.join(str(tmp_path), "dev")
    cpu_dir = os.path.join(str(tmp_path), "cpu")

    w = TpuFileWriteExec(src, dev_dir, fmt=fmt)
    list(w.execute(ExecCtx()))
    assert w.written_files
    w2 = TpuFileWriteExec(src, cpu_dir, fmt=fmt)
    list(w2.execute_cpu(ExecCtx()))

    back_dev = collect_arrow_cpu(TpuFileScanExec(w.written_files, fmt=fmt))
    back_cpu = collect_arrow_cpu(TpuFileScanExec(w2.written_files, fmt=fmt))
    assert _canon(back_dev) == _canon(back_cpu)
    # and the device-read of what the device wrote matches the source
    again = collect_arrow(TpuFileScanExec(w.written_files, fmt=fmt))
    assert again.num_rows == rb.num_rows


def test_partitioned_write(tmp_path):
    rb = gen_table([IntegerGen(min_val=0, max_val=3, null_frac=0),
                    LongGen(), StringGen()], names=["part", "v", "s"])
    from spark_rapids_tpu.exec.base import HostBatchSourceExec
    src = HostBatchSourceExec([rb])
    out = os.path.join(str(tmp_path), "out")
    w = TpuFileWriteExec(src, out, fmt="parquet", partition_by=["part"])
    list(w.execute(ExecCtx()))
    assert any("part=" in f for f in w.written_files)
    import pyarrow.dataset as pads
    back = pads.dataset(out, format="parquet",
                        partitioning="hive").to_table()
    assert back.num_rows == rb.num_rows
    assert sorted(back.column("v").to_pylist(), key=lambda x: (x is None, x)) \
        == sorted(rb.column(1).to_pylist(), key=lambda x: (x is None, x))


def test_hive_partition_inference_strict(tmp_path):
    """Directory values like 'nan'/'inf'/'1_0' type as STRING, not
    float64/int64 (Python float()/int() accept them; Spark does not —
    ADVICE r4)."""
    from spark_rapids_tpu.io.scan import _hive_partition_values
    base = str(tmp_path)
    paths = [f"{base}/k={v}/f.parquet" for v in ("nan", "inf", "1_0")]
    typed, schema = _hive_partition_values(paths)
    assert schema.fields[0].dtype == dt.STRING
    assert typed[paths[0]]["k"] == "nan"
    # plain ints still infer int64
    paths = [f"{base}/k={v}/f.parquet" for v in ("1", "-2", "+3")]
    typed, schema = _hive_partition_values(paths)
    assert schema.fields[0].dtype == dt.INT64
    assert typed[paths[1]]["k"] == -2
    # decimals/exponents infer float64
    paths = [f"{base}/k={v}/f.parquet" for v in ("1.5", "2e3", ".25")]
    _, schema = _hive_partition_values(paths)
    assert schema.fields[0].dtype == dt.FLOAT64


def test_scan_q6_pipeline_through_planner(tmp_path):
    """Scan -> filter -> project -> agg, planned via TpuOverrides: the full
    BASELINE config-1 pipeline starting at real files."""
    n = 5000
    rng = np.random.default_rng(3)
    rb = pa.record_batch({
        "l_quantity": pa.array(rng.uniform(1, 50, n).astype(np.float32)),
        "l_extendedprice": pa.array(
            rng.uniform(900, 105000, n).astype(np.float32)),
        "l_discount": pa.array(
            (rng.integers(0, 11, n) / 100).astype(np.float32)),
        "l_shipdate": pa.array(
            rng.integers(8000, 10600, n).astype(np.int32)),
    })
    p = _write_parquet(tmp_path, rb)
    d = lambda v: Literal(np.float32(v), dt.FLOAT32)
    cond = And(And(GreaterThanOrEqual(col("l_shipdate"),
                                      Literal(8766, dt.INT32)),
                   LessThan(col("l_shipdate"), Literal(9131, dt.INT32))),
               LessThan(col("l_quantity"), d(24.0)))
    scan = TpuFileScanExec([p], pushdown=cond)
    filt = TpuFilterExec(cond, scan)
    proj = TpuProjectExec([Alias(Multiply(col("l_extendedprice"),
                                          col("l_discount")), "rev")], filt)
    agg = TpuHashAggregateExec([], [Alias(Sum(col("rev")), "revenue")], proj)
    pp = overrides(agg)
    assert pp.fallback_nodes() == []
    got = pp.collect()
    exp = collect_arrow_cpu(agg)
    assert abs(got.column(0)[0].as_py() - exp.column(0)[0].as_py()) \
        <= 1e-6 * abs(exp.column(0)[0].as_py())


def test_scan_falls_back_when_format_disabled(tmp_path):
    rb = gen_table([IntegerGen()], n=50)
    p = _write_parquet(tmp_path, rb)
    conf = RapidsConf({"spark.rapids.sql.exec.FileScanExec": "false"})
    pp = overrides(TpuFileScanExec([p]), conf)
    assert "FileScanExec" in pp.fallback_nodes()
    got = pp.collect()
    assert got.num_rows == 50


def test_hive_text_round_trip(tmp_path):
    """Hive LazySimpleSerDe text (B13): \\x01 delimiters, \\N nulls,
    serde escapes — write + read round-trip incl. hostile strings."""
    import datetime as dtm
    from spark_rapids_tpu.io.write import TpuFileWriteExec
    from spark_rapids_tpu.exec.base import HostBatchSourceExec
    rb = pa.record_batch({
        "i": pa.array([1, None, -3, 400], pa.int64()),
        "f": pa.array([0.5, 2.25, None, -1.0]),
        "b": pa.array([True, False, None, True]),
        "d": pa.array([dtm.date(2021, 3, 5), None,
                       dtm.date(1999, 12, 31), dtm.date(2000, 1, 1)]),
        "s": pa.array(["plain", "with\x01delim", "multi\nline",
                       "back\\slash"]),
    })
    src = HostBatchSourceExec([rb])
    out_dir = os.path.join(str(tmp_path), "ht")
    w = TpuFileWriteExec(src, out_dir, fmt="hivetext")
    list(w.execute(ExecCtx()))
    assert w.written_files
    from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
    scan = TpuFileScanExec(w.written_files, fmt="hivetext",
                           schema=engine_schema(rb.schema))
    back = assert_tpu_and_cpu_plan_equal(scan)
    assert _canon(back) == _canon(
        pa.Table.from_batches([rb]))


def test_hive_text_binary_base64(tmp_path):
    """BINARY columns ride Hive text as Base64 (the serde's encoding) —
    round-trip exact, including delimiter-colliding bytes."""
    from spark_rapids_tpu.io.write import TpuFileWriteExec
    from spark_rapids_tpu.exec.base import HostBatchSourceExec
    from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
    rb = pa.record_batch({
        "k": pa.array([1, 2, 3], pa.int64()),
        "bin": pa.array([b"ab\x01c", None, b"\\x\nraw"], pa.binary()),
    })
    out_dir = os.path.join(str(tmp_path), "htb")
    w = TpuFileWriteExec(HostBatchSourceExec([rb]), out_dir,
                         fmt="hivetext")
    list(w.execute(ExecCtx()))
    scan = TpuFileScanExec(w.written_files, fmt="hivetext",
                           schema=engine_schema(rb.schema))
    back = assert_tpu_and_cpu_plan_equal(scan)
    assert back.column("bin").to_pylist() == [b"ab\x01c", None,
                                              b"\\x\nraw"]


def test_hive_text_cr_decimal_timestamp(tmp_path):
    """\\r in strings must not split rows, and decimal/timestamp
    columns round-trip via their text forms (code-review r5)."""
    import datetime as dtm
    import decimal
    from spark_rapids_tpu.io.write import TpuFileWriteExec
    from spark_rapids_tpu.exec.base import HostBatchSourceExec
    from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
    utc = dtm.timezone.utc
    rb = pa.record_batch({
        "i": pa.array([1, 2], pa.int64()),
        "s": pa.array(["a\rb", "win\r\nline"]),
        "dec": pa.array([decimal.Decimal("1.50"), None],
                        pa.decimal128(10, 2)),
        "ts": pa.array([dtm.datetime(2021, 3, 5, 12, 0, 1, 250000,
                                     tzinfo=utc), None],
                       pa.timestamp("us", tz="UTC")),
    })
    out_dir = os.path.join(str(tmp_path), "htc")
    w = TpuFileWriteExec(HostBatchSourceExec([rb]), out_dir,
                         fmt="hivetext")
    list(w.execute(ExecCtx()))
    scan = TpuFileScanExec(w.written_files, fmt="hivetext",
                           schema=engine_schema(rb.schema))
    back = assert_tpu_and_cpu_plan_equal(scan)
    assert back.num_rows == 2
    assert back.column("s").to_pylist() == ["a\rb", "win\r\nline"]
    assert back.column("dec").to_pylist() == [decimal.Decimal("1.50"),
                                              None]


def test_hive_text_crlf_external_file(tmp_path):
    """CRLF-terminated files (external writers) parse without trailing
    \\r leaking into the last field (code-review r5)."""
    from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
    p = os.path.join(str(tmp_path), "crlf.txt")
    with open(p, "wb") as f:
        f.write(b"1\x01alpha\r\n2\x01beta\r\n\\N\x01\\N\r\n")
    schema = engine_schema(pa.schema([("i", pa.int64()),
                                      ("s", pa.string())]))
    scan = TpuFileScanExec([p], fmt="hivetext", schema=schema)
    back = assert_tpu_and_cpu_plan_equal(scan)
    assert back.column("i").to_pylist() == [1, 2, None]
    assert back.column("s").to_pylist() == ["alpha", "beta", None]
