"""File scan + write tests (reference: integration_tests parquet_test.py /
orc_test.py / csv_test.py / *_write_test.py — SURVEY.md §4.1; reader
modes + round-trip shapes from §2.2-B Scans/Writes)."""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from asserts import assert_tpu_and_cpu_plan_equal
from data_gen import (all_basic_gens, gen_table, DateGen, DecimalGen,
                      IntegerGen, LongGen, FloatGen, StringGen)

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow, \
    collect_arrow_cpu
from spark_rapids_tpu.exec.basic import TpuFilterExec, TpuProjectExec
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.expr import (Alias, And, GreaterThanOrEqual, LessThan,
                                   Literal, Multiply,
                                   UnresolvedColumn as col)
from spark_rapids_tpu.expr.aggregates import Sum
from spark_rapids_tpu.io import (FileSplit, TpuFileScanExec,
                                 TpuFileWriteExec, plan_splits)
from spark_rapids_tpu.planner import overrides


def _canon(table):
    """to_pydict with NaN mapped to a comparable token (NaN != NaN)."""
    import math
    return {name: ["NaN" if isinstance(v, float) and math.isnan(v) else v
                   for v in vals]
            for name, vals in table.to_pydict().items()}


def _write_parquet(tmp_path, rb, name="data.parquet", row_group_size=None):
    p = os.path.join(str(tmp_path), name)
    pq.write_table(pa.Table.from_batches([rb]), p,
                   row_group_size=row_group_size)
    return p


def test_parquet_scan_all_basic_types(tmp_path):
    rb = gen_table(all_basic_gens, n=500)
    p = _write_parquet(tmp_path, rb)
    assert_tpu_and_cpu_plan_equal(TpuFileScanExec([p]))


def test_parquet_scan_multi_file_reader_modes(tmp_path):
    paths = []
    for i in range(6):
        rb = gen_table([IntegerGen(), StringGen(), FloatGen(dt.FLOAT64)],
                       n=200 + i, seed=100 + i)
        paths.append(_write_parquet(tmp_path, rb, f"f{i}.parquet"))
    results = {}
    for mode in ("PERFILE", "MULTITHREADED", "COALESCING"):
        conf = RapidsConf({
            "spark.rapids.sql.format.parquet.reader.type": mode})
        scan = TpuFileScanExec(paths, conf=conf)
        results[mode] = assert_tpu_and_cpu_plan_equal(scan, conf=conf)
    # all reader modes agree (same rows, same order: split-ordered)
    assert _canon(results["PERFILE"]) == _canon(results["MULTITHREADED"])
    assert sorted(map(tuple, results["PERFILE"].to_pylist()[0:0])) == []
    assert results["COALESCING"].num_rows == results["PERFILE"].num_rows


def test_parquet_row_group_splits(tmp_path):
    rb = gen_table([LongGen(null_frac=0)], n=4000)
    p = _write_parquet(tmp_path, rb, row_group_size=256)
    splits = plan_splits([p], "parquet", max_partition_bytes=8 << 10)
    assert len(splits) > 1
    covered = [g for s in splits for g in s.row_groups]
    assert covered == sorted(set(covered))  # disjoint + complete
    assert_tpu_and_cpu_plan_equal(TpuFileScanExec([p]))


def test_parquet_column_projection(tmp_path):
    rb = gen_table([IntegerGen(), StringGen(), DateGen()],
                   names=["a", "b", "c"])
    p = _write_parquet(tmp_path, rb)
    scan = TpuFileScanExec([p], columns=["c", "a"])
    assert scan.output_schema.names == ["c", "a"]
    assert_tpu_and_cpu_plan_equal(scan)


def test_parquet_predicate_pushdown_prunes_and_stays_correct(tmp_path):
    # ascending key -> row group stats are tight -> pruning provable
    n = 4096
    key = pa.array(np.arange(n, dtype=np.int64))
    val = pa.array(np.arange(n, dtype=np.float64) * 0.5)
    rb = pa.record_batch({"k": key, "v": val})
    p = _write_parquet(tmp_path, rb, row_group_size=512)
    cond = And(GreaterThanOrEqual(col("k"), Literal(1000, dt.INT64)),
               LessThan(col("k"), Literal(1500, dt.INT64)))
    scan = TpuFileScanExec([p], pushdown=cond)
    plan = TpuFilterExec(cond, scan)
    out = assert_tpu_and_cpu_plan_equal(plan)
    assert out.num_rows == 500
    # pruning really skipped groups: decode only touches 2 of 8
    from spark_rapids_tpu.io.scan import _decode_split, _simple_conjuncts
    rbs = _decode_split(FileSplit(p), "parquet", None, 1 << 20,
                        _simple_conjuncts(cond))
    assert sum(r.num_rows for r in rbs) <= 1024


def test_csv_scan(tmp_path):
    rb = gen_table([IntegerGen(), FloatGen(dt.FLOAT64),
                    StringGen(ascii_only=True,
                              charset="abcdefgh123")], n=300)
    import pyarrow.csv as pcsv
    p = os.path.join(str(tmp_path), "data.csv")
    pcsv.write_csv(pa.Table.from_batches([rb]), p)
    assert_tpu_and_cpu_plan_equal(TpuFileScanExec([p], fmt="csv"))


def test_json_scan(tmp_path):
    rb = gen_table([IntegerGen(), LongGen(), StringGen(ascii_only=True)],
                   n=200)
    p = os.path.join(str(tmp_path), "data.json")
    with open(p, "w") as f:
        for row in pa.Table.from_batches([rb]).to_pylist():
            import json
            f.write(json.dumps(row) + "\n")
    assert_tpu_and_cpu_plan_equal(TpuFileScanExec([p], fmt="json"))


def test_orc_scan(tmp_path):
    from pyarrow import orc
    rb = gen_table([IntegerGen(), LongGen(), FloatGen(dt.FLOAT64),
                    StringGen()], n=300)
    p = os.path.join(str(tmp_path), "data.orc")
    orc.write_table(pa.Table.from_batches([rb]), p)
    assert_tpu_and_cpu_plan_equal(TpuFileScanExec([p], fmt="orc"))


@pytest.mark.parametrize("fmt", ["parquet", "orc", "csv"])
def test_write_round_trip(tmp_path, fmt):
    """BASELINE config-5 shape: write via device path and CPU path, read
    both back, results equal (write dual-run)."""
    gens = [IntegerGen(), LongGen(), FloatGen(dt.FLOAT64)]
    if fmt != "csv":
        gens += [StringGen(), DateGen()]
    rb = gen_table(gens, n=700)
    from spark_rapids_tpu.exec.base import HostBatchSourceExec
    src = HostBatchSourceExec([rb])
    dev_dir = os.path.join(str(tmp_path), "dev")
    cpu_dir = os.path.join(str(tmp_path), "cpu")

    w = TpuFileWriteExec(src, dev_dir, fmt=fmt)
    list(w.execute(ExecCtx()))
    assert w.written_files
    w2 = TpuFileWriteExec(src, cpu_dir, fmt=fmt)
    list(w2.execute_cpu(ExecCtx()))

    back_dev = collect_arrow_cpu(TpuFileScanExec(w.written_files, fmt=fmt))
    back_cpu = collect_arrow_cpu(TpuFileScanExec(w2.written_files, fmt=fmt))
    assert _canon(back_dev) == _canon(back_cpu)
    # and the device-read of what the device wrote matches the source
    again = collect_arrow(TpuFileScanExec(w.written_files, fmt=fmt))
    assert again.num_rows == rb.num_rows


def test_partitioned_write(tmp_path):
    rb = gen_table([IntegerGen(min_val=0, max_val=3, null_frac=0),
                    LongGen(), StringGen()], names=["part", "v", "s"])
    from spark_rapids_tpu.exec.base import HostBatchSourceExec
    src = HostBatchSourceExec([rb])
    out = os.path.join(str(tmp_path), "out")
    w = TpuFileWriteExec(src, out, fmt="parquet", partition_by=["part"])
    list(w.execute(ExecCtx()))
    assert any("part=" in f for f in w.written_files)
    import pyarrow.dataset as pads
    back = pads.dataset(out, format="parquet",
                        partitioning="hive").to_table()
    assert back.num_rows == rb.num_rows
    assert sorted(back.column("v").to_pylist(), key=lambda x: (x is None, x)) \
        == sorted(rb.column(1).to_pylist(), key=lambda x: (x is None, x))


def test_scan_q6_pipeline_through_planner(tmp_path):
    """Scan -> filter -> project -> agg, planned via TpuOverrides: the full
    BASELINE config-1 pipeline starting at real files."""
    n = 5000
    rng = np.random.default_rng(3)
    rb = pa.record_batch({
        "l_quantity": pa.array(rng.uniform(1, 50, n).astype(np.float32)),
        "l_extendedprice": pa.array(
            rng.uniform(900, 105000, n).astype(np.float32)),
        "l_discount": pa.array(
            (rng.integers(0, 11, n) / 100).astype(np.float32)),
        "l_shipdate": pa.array(
            rng.integers(8000, 10600, n).astype(np.int32)),
    })
    p = _write_parquet(tmp_path, rb)
    d = lambda v: Literal(np.float32(v), dt.FLOAT32)
    cond = And(And(GreaterThanOrEqual(col("l_shipdate"),
                                      Literal(8766, dt.INT32)),
                   LessThan(col("l_shipdate"), Literal(9131, dt.INT32))),
               LessThan(col("l_quantity"), d(24.0)))
    scan = TpuFileScanExec([p], pushdown=cond)
    filt = TpuFilterExec(cond, scan)
    proj = TpuProjectExec([Alias(Multiply(col("l_extendedprice"),
                                          col("l_discount")), "rev")], filt)
    agg = TpuHashAggregateExec([], [Alias(Sum(col("rev")), "revenue")], proj)
    pp = overrides(agg)
    assert pp.fallback_nodes() == []
    got = pp.collect()
    exp = collect_arrow_cpu(agg)
    assert abs(got.column(0)[0].as_py() - exp.column(0)[0].as_py()) \
        <= 1e-6 * abs(exp.column(0)[0].as_py())


def test_scan_falls_back_when_format_disabled(tmp_path):
    rb = gen_table([IntegerGen()], n=50)
    p = _write_parquet(tmp_path, rb)
    conf = RapidsConf({"spark.rapids.sql.exec.FileScanExec": "false"})
    pp = overrides(TpuFileScanExec([p]), conf)
    assert "FileScanExec" in pp.fallback_nodes()
    got = pp.collect()
    assert got.num_rows == 50
