"""Shuffle integrity unit tests (no worker processes): CRC footers,
commit manifests, fetch-failure classification with in-place retry,
the attempt-commit edge cases, the multithreaded writer's sticky
error, and cleanup-safe teardown. The process-cluster recovery paths
these feed live in test_shuffle_recovery.py."""
import json
import os
import time

import pyarrow as pa
import pytest

from data_gen import IntegerGen, LongGen, gen_table

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.scheduler import chaos
from spark_rapids_tpu.shuffle import integrity
from spark_rapids_tpu.shuffle.host import HostShuffleTransport
from spark_rapids_tpu.shuffle.transport import FetchFailure


def _rb(n=50, seed=1):
    return gen_table([IntegerGen(nullable=False), LongGen(nullable=False)],
                     n, seed=seed, names=["k", "v"])


def _transport(tmp_path, threads=0):
    return HostShuffleTransport(RapidsConf(), threads=threads,
                                root=str(tmp_path / "shuffle"))


def _commit_mapout(t, sid=1, key="t0", attempt=0, parts=(0, 1), mid=0,
                   seed=7):
    """Write one partition file per pid into a staging dir and commit."""
    t.register_shuffle(sid, max(parts) + 1 if parts else 1)
    staging = t.begin_task_attempt(sid, key, attempt)
    for pid in parts:
        t._write_rb(sid, mid, pid, _rb(seed=seed + pid), subdir=staging)
    won = t.commit_task_attempt(sid, key, attempt)
    return won, os.path.join(t._sdir(sid), f"{key}.mapout")


# --- footer + classification ------------------------------------------------

def test_footer_roundtrip_and_crc(tmp_path):
    path = str(tmp_path / "b.arrow")
    payload = b"x" * 1000
    size, crc = integrity.write_block(path, payload)
    assert size == 1000 + integrity.FOOTER_LEN
    assert os.path.getsize(path) == size
    got = integrity.read_block(path)
    assert got == payload
    meta_ok = {"size": size, "crc": crc}
    assert integrity.read_block(path, meta_ok) == payload


def test_missing_block_classified(tmp_path):
    with pytest.raises(FetchFailure) as ei:
        integrity.read_block(str(tmp_path / "gone.arrow"),
                             {"task": "t9"}, shuffle_id=3)
    assert ei.value.kind == "missing"
    assert ei.value.map_task == "t9"
    assert ei.value.shuffle_id == 3


def test_torn_footer_classified(tmp_path):
    path = str(tmp_path / "b.arrow")
    integrity.write_block(path, b"y" * 500)
    # crash between write and (dir) rename can leave a short file:
    # truncate through the trailer
    with open(path, "r+b") as f:
        f.truncate(500 + integrity.FOOTER_LEN - 7)
    with pytest.raises(FetchFailure) as ei:
        integrity.read_block(path)
    assert ei.value.kind == "torn"
    # trailing garbage after the trailer is torn too, not corrupt
    path2 = str(tmp_path / "b2.arrow")
    integrity.write_block(path2, b"z" * 100)
    with open(path2, "ab") as f:
        f.write(b"junk")
    with pytest.raises(FetchFailure) as ei:
        integrity.read_block(path2)
    assert ei.value.kind == "torn"


def test_corrupt_payload_classified(tmp_path):
    path = str(tmp_path / "b.arrow")
    integrity.write_block(path, bytes(range(256)) * 10)
    with open(path, "r+b") as f:
        f.seek(100)
        f.write(b"\xde\xad")
    with pytest.raises(FetchFailure) as ei:
        integrity.read_block(path)
    assert ei.value.kind == "corrupt"


def test_manifest_size_mismatch_is_torn(tmp_path):
    path = str(tmp_path / "b.arrow")
    size, crc = integrity.write_block(path, b"p" * 64)
    with pytest.raises(FetchFailure) as ei:
        integrity.read_block(path, {"size": size + 5, "crc": crc})
    assert ei.value.kind == "torn"


# --- transient io: eio sidecar + bounded in-place retry ---------------------

def test_eio_retries_in_place_then_succeeds(tmp_path):
    path = str(tmp_path / "b.arrow")
    integrity.write_block(path, b"q" * 128)
    with open(path + ".eio", "w") as f:
        f.write("2")
    retries = []
    got = integrity.read_block(path, max_retries=3, retry_wait_s=0.001,
                               on_retry=lambda n, e: retries.append(n))
    assert got == b"q" * 128
    assert retries == [1, 2]  # two injected failures burned two retries
    with open(path + ".eio") as f:
        assert f.read().strip() == "0"


def test_eio_beyond_budget_escalates_as_io(tmp_path):
    path = str(tmp_path / "b.arrow")
    integrity.write_block(path, b"q" * 128)
    with open(path + ".eio", "w") as f:
        f.write("50")
    t0 = time.monotonic()
    with pytest.raises(FetchFailure) as ei:
        integrity.read_block(path, max_retries=2, retry_wait_s=0.001)
    assert ei.value.kind == "io"
    assert time.monotonic() - t0 < 5.0


# --- commit protocol + manifest ---------------------------------------------

def test_commit_writes_manifest_and_reads_verify(tmp_path):
    t = _transport(tmp_path)
    won, mapout = _commit_mapout(t, parts=(0, 1, 2))
    assert won
    manifest = integrity.read_manifest(mapout)
    assert manifest["task"] == "t0" and manifest["attempt"] == 0
    assert len(manifest["files"]) == 3
    for name, meta in manifest["files"].items():
        p = os.path.join(mapout, name)
        assert os.path.getsize(p) == meta["size"]
        integrity.read_block(p, meta)  # verifies crc + footer
    blocks = integrity.expected_partition_files(os.path.dirname(mapout),
                                                1, ["t0"])
    assert [os.path.basename(p) for p, _ in blocks] == ["m00000_p1.arrow"]
    assert blocks[0][1]["task"] == "t0"


def test_manifest_detects_missing_block(tmp_path):
    t = _transport(tmp_path)
    _, mapout = _commit_mapout(t, parts=(0, 1))
    victim = os.path.join(mapout, "m00000_p1.arrow")
    os.unlink(victim)
    # enumeration still names the lost block; reading it classifies
    blocks = integrity.expected_partition_files(os.path.dirname(mapout),
                                                1, ["t0"])
    assert len(blocks) == 1
    with pytest.raises(FetchFailure) as ei:
        integrity.read_block(*blocks[0])
    assert ei.value.kind == "missing" and ei.value.map_task == "t0"


def test_expected_mapout_dir_gone_is_missing(tmp_path):
    t = _transport(tmp_path)
    _, mapout = _commit_mapout(t)
    import shutil
    shutil.rmtree(mapout)  # committed-then-lost (executor-loss analog)
    with pytest.raises(FetchFailure) as ei:
        integrity.expected_partition_files(os.path.dirname(mapout), 0,
                                           ["t0"], shuffle_id=1)
    assert ei.value.kind == "missing" and ei.value.map_task == "t0"


def test_torn_manifest_classified(tmp_path):
    t = _transport(tmp_path)
    _, mapout = _commit_mapout(t)
    with open(os.path.join(mapout, integrity.MANIFEST_NAME), "w") as f:
        f.write('{"task": "t0", "files": {')  # torn commit
    with pytest.raises(FetchFailure) as ei:
        integrity.expected_partition_files(os.path.dirname(mapout), 0)
    assert ei.value.kind == "torn"


# --- committed_partition_files edge cases (satellite) ------------------------

def test_staging_dir_invisible_mid_commit(tmp_path):
    t = _transport(tmp_path)
    t.register_shuffle(1, 2)
    staging = t.begin_task_attempt(1, "t0", 0)
    t._write_rb(1, 0, 0, _rb(), subdir=staging)
    sdir = t._sdir(1)
    # before commit: a reader sees NOTHING from the in-flight attempt
    assert HostShuffleTransport.committed_partition_files(sdir, 0) == []
    assert integrity.expected_partition_files(sdir, 0) == []
    t.commit_task_attempt(1, "t0", 0)
    assert len(HostShuffleTransport.committed_partition_files(sdir, 0)) == 1


def test_zombie_commit_after_winner_stays_invisible(tmp_path):
    t = _transport(tmp_path)
    t.register_shuffle(1, 2)
    # the retry (attempt 1) commits first; the zombie attempt 0
    # finishes later and must atomically lose
    s1 = t.begin_task_attempt(1, "t0", 1)
    t._write_rb(1, 0, 0, _rb(seed=10), subdir=s1)
    assert t.commit_task_attempt(1, "t0", 1)
    s0 = t.begin_task_attempt(1, "t0", 0)
    t._write_rb(1, 0, 0, _rb(seed=99), subdir=s0)
    t._write_rb(1, 0, 1, _rb(seed=98), subdir=s0)
    assert not t.commit_task_attempt(1, "t0", 0)  # lost the race
    sdir = t._sdir(1)
    assert not os.path.exists(s0)  # loser's staging discarded
    mapouts = [n for n in os.listdir(sdir) if n.endswith(".mapout")]
    assert mapouts == ["t0.mapout"]
    # the visible output is the WINNER's (attempt 1 wrote only p0)
    manifest = integrity.read_manifest(os.path.join(sdir, "t0.mapout"))
    assert manifest["attempt"] == 1
    assert integrity.expected_partition_files(sdir, 1, ["t0"]) == []


def test_zero_row_map_output_commits_empty_manifest(tmp_path):
    t = _transport(tmp_path)
    t.register_shuffle(1, 4)
    t.begin_task_attempt(1, "t0", 0)
    assert t.commit_task_attempt(1, "t0", 0)  # no partition had rows
    sdir = t._sdir(1)
    manifest = integrity.read_manifest(os.path.join(sdir, "t0.mapout"))
    assert manifest["files"] == {}
    for pid in range(4):
        assert integrity.expected_partition_files(sdir, pid, ["t0"]) == []
        assert HostShuffleTransport.committed_partition_files(sdir,
                                                              pid) == []


def test_torn_block_inside_committed_dir(tmp_path):
    t = _transport(tmp_path)
    _, mapout = _commit_mapout(t, parts=(0,))
    victim = os.path.join(mapout, "m00000_p0.arrow")
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) - 3)
    blocks = integrity.expected_partition_files(os.path.dirname(mapout),
                                                0, ["t0"])
    with pytest.raises(FetchFailure) as ei:
        integrity.read_block(*blocks[0])
    assert ei.value.kind == "torn"


# --- end-to-end read path verifies -------------------------------------------

def test_read_partition_raises_classified_on_corruption(tmp_path):
    t = _transport(tmp_path)
    _, mapout = _commit_mapout(t, parts=(0,))
    victim = os.path.join(mapout, "m00000_p0.arrow")
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\x00\x01\x02\x03")
    with pytest.raises(FetchFailure) as ei:
        list(t.read_partition(1, 0))
    assert ei.value.kind == "corrupt" and ei.value.map_task == "t0"


def test_read_partition_roundtrip_with_footers(tmp_path):
    from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
    t = _transport(tmp_path)
    rb = _rb(80, seed=3)
    _, _ = _commit_mapout(t, parts=(0,), seed=3)
    got = pa.Table.from_batches(
        [device_to_arrow(b) for b in t.read_partition(1, 0)])
    want = pa.Table.from_batches([_rb(seed=3 + 0)])
    assert got.to_pydict() == want.to_pydict()
    del rb


# --- sticky writer error + cleanup-safe teardown (satellites) ---------------

def _boom():
    raise OSError(28, "No space left on device")


def test_drain_error_sticky_across_reads(tmp_path):
    t = _transport(tmp_path, threads=2)
    t.register_shuffle(5, 3)
    t._submit(5, _boom)
    t._submit(5, lambda: None)  # later healthy write still drains
    with pytest.raises(RuntimeError, match="failed async write"):
        list(t.read_partition(5, 0))
    # the error is NOT consumed by the first reader: every subsequent
    # partition read re-raises instead of silently yielding partial data
    with pytest.raises(RuntimeError, match="failed async write"):
        list(t.read_partition(5, 1))
    with pytest.raises(RuntimeError, match="failed async write"):
        t.commit_task_attempt(5, "t0", 0)
    # cleanup still happens, and the error surfaces one last time
    sdir = t._sdir(5)
    with pytest.raises(RuntimeError, match="failed async write"):
        t.unregister_shuffle(5)
    assert not os.path.exists(sdir)
    # after unregister the shuffle is gone for good: fresh state
    t.register_shuffle(5, 3)
    assert list(t.read_partition(5, 0)) == []
    t.close()


def test_close_bounded_behind_wedged_writer(tmp_path):
    # the close() join bound is a registered conf now, not a module
    # literal (spark.rapids.shuffle.close.joinTimeout)
    t = HostShuffleTransport(
        RapidsConf({"spark.rapids.shuffle.close.joinTimeout": "0.2"}),
        threads=1, root=str(tmp_path / "shuffle"))
    t.register_shuffle(1, 1)
    release = []
    t._submit(1, lambda: [time.sleep(0.05)
                          for _ in iter(lambda: not release, False)])
    t0 = time.monotonic()
    t.close()  # must not hang behind the wedged writer
    assert time.monotonic() - t0 < 5.0
    release.append(True)


# --- chaos grammar for the new shuffle-durability modes ----------------------

def test_chaos_parses_durability_modes():
    rules = chaos.parse_fault_spec(
        "corrupt:q1s1m0:0; drop:q1s1m1:*; eio:q1s*:0:5@w1")
    assert [r.mode for r in rules] == ["corrupt", "drop", "eio"]
    assert rules[1].attempt is None
    assert rules[2].seconds == 5.0 and rules[2].worker == 1
    # the pre-run hook must ignore post-commit modes and vice versa
    assert chaos.find_rule("corrupt:q1s1m0:0", 0, "q1s1m0", 0,
                           chaos._PRE_MODES) is None
    assert chaos.find_rule("corrupt:q1s1m0:0", 0, "q1s1m0", 0,
                           chaos._POST_MODES).mode == "corrupt"


def test_chaos_inject_output_modes(tmp_path):
    t = _transport(tmp_path)
    _, mapout = _commit_mapout(t, parts=(0, 1))
    files = sorted(n for n in os.listdir(mapout) if n.endswith(".arrow"))
    chaos.maybe_inject_output("eio:t0:0:4", 0, "t0", 0, mapout)
    for n in files:
        with open(os.path.join(mapout, n + ".eio")) as f:
            assert f.read() == "4"
    chaos.maybe_inject_output("corrupt:t0:0", 0, "t0", 0, mapout)
    with pytest.raises(FetchFailure) as ei:
        integrity.read_block(os.path.join(mapout, files[0]),
                             max_retries=10, retry_wait_s=0.001)
    assert ei.value.kind == "corrupt"  # corrupt, NOT torn: footer intact
    chaos.maybe_inject_output("drop:t0:0", 0, "t0", 0, mapout)
    assert not os.path.exists(mapout)
    # attempt-pinned rules don't fire on other attempts
    _, mapout = _commit_mapout(t, key="t1")
    chaos.maybe_inject_output("drop:t1:3", 0, "t1", 0, mapout)
    assert os.path.exists(mapout)
