"""Join tests via the dual-run harness (reference: join_test.py —
SURVEY.md §4.1)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.exec import HostBatchSourceExec
from spark_rapids_tpu.exec.joins import (TpuCartesianProductExec,
                                         TpuShuffledHashJoinExec)
from spark_rapids_tpu.expr import (GreaterThan, Literal,
                                   UnresolvedColumn as col)

from asserts import assert_tpu_and_cpu_plan_equal
from data_gen import (BooleanGen, DateGen, DecimalGen, DoubleGen, FloatGen,
                      IntegerGen, LongGen, StringGen, TimestampGen,
                      gen_table)

ALL_TYPES = ["inner", "left_outer", "right_outer", "full_outer",
             "left_semi", "left_anti"]


def two_sources(key_gen_l, key_gen_r, nl=150, nr=120, seeds=(11, 22)):
    left = HostBatchSourceExec(
        [gen_table([key_gen_l, LongGen(nullable=False)], nl, seeds[0],
                   names=["lk", "lv"])])
    right = HostBatchSourceExec(
        [gen_table([key_gen_r, LongGen(nullable=False)], nr, seeds[1],
                   names=["rk", "rv"])])
    return left, right


def join_plan(jt, key_gen, **kw):
    left, right = two_sources(key_gen, key_gen, **kw)
    return TpuShuffledHashJoinExec([col("lk")], [col("rk")], jt, left,
                                   right)


@pytest.mark.parametrize("jt", ALL_TYPES)
def test_join_int_keys(jt):
    plan = join_plan(jt, IntegerGen(min_val=0, max_val=40))
    assert_tpu_and_cpu_plan_equal(plan, label=jt)


@pytest.mark.parametrize("jt", ALL_TYPES)
def test_join_null_keys(jt):
    # null keys never match; outer/anti sides still emit them
    plan = join_plan(jt, IntegerGen(min_val=0, max_val=10, null_frac=0.3))
    assert_tpu_and_cpu_plan_equal(plan, label=jt)


@pytest.mark.parametrize("jt", ALL_TYPES)
def test_join_string_keys(jt):
    plan = join_plan(jt, StringGen(max_len=4, charset="abc",
                                   null_frac=0.2))
    assert_tpu_and_cpu_plan_equal(plan, label=jt)


@pytest.mark.parametrize("kg", [LongGen(), DateGen(), TimestampGen(),
                                BooleanGen(), DecimalGen(precision=5),
                                DoubleGen(null_frac=0.2)],
                         ids=lambda g: g.dtype.simple_string())
def test_join_key_types_inner(kg):
    plan = join_plan("inner", kg)
    assert_tpu_and_cpu_plan_equal(plan)


def test_join_float_key_specials():
    # NaN==NaN and -0.0==0.0 for join keys
    left = HostBatchSourceExec([pa.record_batch(
        {"lk": pa.array([float("nan"), 0.0, -0.0, 1.5, None]),
         "lv": pa.array([1, 2, 3, 4, 5], pa.int64())})])
    right = HostBatchSourceExec([pa.record_batch(
        {"rk": pa.array([float("nan"), -0.0, 2.5, None]),
         "rv": pa.array([10, 20, 30, 40], pa.int64())})])
    for jt in ALL_TYPES:
        plan = TpuShuffledHashJoinExec([col("lk")], [col("rk")], jt,
                                       left, right)
        assert_tpu_and_cpu_plan_equal(plan, label=jt)


def test_join_multi_key():
    gens = [IntegerGen(min_val=0, max_val=5), StringGen(max_len=2,
                                                        charset="xy")]
    left = HostBatchSourceExec(
        [gen_table(gens + [LongGen(nullable=False)], 100, 1,
                   names=["k1", "k2", "lv"])])
    right = HostBatchSourceExec(
        [gen_table(gens + [LongGen(nullable=False)], 80, 2,
                   names=["k1", "k2", "rv"])])
    for jt in ("inner", "left_outer", "left_anti"):
        plan = TpuShuffledHashJoinExec(
            [col("k1"), col("k2")], [col("k1"), col("k2")], jt, left,
            right)
        assert_tpu_and_cpu_plan_equal(plan, label=jt)


def test_join_empty_sides():
    empty = HostBatchSourceExec([pa.record_batch(
        {"rk": pa.array([], pa.int32()), "rv": pa.array([], pa.int64())})])
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(), LongGen(nullable=False)], 50, 3,
                   names=["lk", "lv"])])
    for jt in ALL_TYPES:
        plan = TpuShuffledHashJoinExec([col("lk")], [col("rk")], jt, left,
                                       empty)
        assert_tpu_and_cpu_plan_equal(plan, label=f"{jt} empty right")
    empty_l = HostBatchSourceExec([pa.record_batch(
        {"lk": pa.array([], pa.int32()), "lv": pa.array([], pa.int64())})])
    right = HostBatchSourceExec(
        [gen_table([IntegerGen(), LongGen(nullable=False)], 50, 4,
                   names=["rk", "rv"])])
    for jt in ALL_TYPES:
        plan = TpuShuffledHashJoinExec([col("lk")], [col("rk")], jt,
                                       empty_l, right)
        assert_tpu_and_cpu_plan_equal(plan, label=f"{jt} empty left")


def test_join_multi_batch_stream():
    rbs = [gen_table([IntegerGen(min_val=0, max_val=20),
                      LongGen(nullable=False)], n, seed=s,
                     names=["lk", "lv"]) for n, s in [(60, 1), (90, 2)]]
    left = HostBatchSourceExec(rbs)
    right = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=20),
                    LongGen(nullable=False)], 70, 9,
                   names=["rk", "rv"])])
    for jt in ("inner", "left_outer", "full_outer", "left_semi"):
        plan = TpuShuffledHashJoinExec([col("lk")], [col("rk")], jt, left,
                                       right)
        assert_tpu_and_cpu_plan_equal(plan, label=jt)


def test_join_duplicate_heavy_keys():
    plan = join_plan("inner", IntegerGen(min_val=0, max_val=3), nl=100,
                     nr=100)
    assert_tpu_and_cpu_plan_equal(plan)


def test_inner_join_with_condition():
    left, right = two_sources(IntegerGen(min_val=0, max_val=10),
                              IntegerGen(min_val=0, max_val=10))
    plan = TpuShuffledHashJoinExec(
        [col("lk")], [col("rk")], "inner", left, right,
        condition=GreaterThan(col("lv"), col("rv")))
    assert_tpu_and_cpu_plan_equal(plan)


def test_cartesian_product():
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(), LongGen(nullable=False)], 30, 1,
                   names=["a", "b"])])
    right = HostBatchSourceExec(
        [gen_table([StringGen(max_len=3), LongGen(nullable=False)], 20, 2,
                   names=["c", "d"])])
    plan = TpuCartesianProductExec(left, right)
    assert_tpu_and_cpu_plan_equal(plan)


def test_cartesian_with_condition():
    left = HostBatchSourceExec(
        [gen_table([LongGen(nullable=False)], 25, 1, names=["a"])])
    right = HostBatchSourceExec(
        [gen_table([LongGen(nullable=False)], 25, 2, names=["b"])])
    plan = TpuCartesianProductExec(
        left, right, condition=GreaterThan(col("a"), col("b")))
    assert_tpu_and_cpu_plan_equal(plan)


def test_join_strings_payload():
    # string payload columns exercise gather char sizing on both sides
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=8), StringGen()],
                   60, 5, names=["lk", "ls"])])
    right = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=8), StringGen()],
                   50, 6, names=["rk", "rs"])])
    for jt in ("inner", "left_outer", "full_outer"):
        plan = TpuShuffledHashJoinExec([col("lk")], [col("rk")], jt, left,
                                       right)
        assert_tpu_and_cpu_plan_equal(plan, label=jt)


def test_non_equi_condition_rejected_on_non_inner_join():
    """Device execute refuses conditions on join types where post-filtering
    is semantically wrong; the CPU oracle still runs them (advisor
    round-1)."""
    from data_gen import gen_table
    from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow_cpu
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=5), IntegerGen()], 32, 1)])
    right = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=5), IntegerGen()], 32, 2,
                   names=["k", "v"])])
    j = TpuShuffledHashJoinExec([col("c0")], [col("k")], "left_outer",
                                left, right,
                                condition=GreaterThan(col("c1"), col("v")))
    assert j.tpu_supported() is not None
    with pytest.raises(NotImplementedError):
        list(j.execute(ExecCtx()))
    assert collect_arrow_cpu(j).num_rows >= 32  # oracle path works
