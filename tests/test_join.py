"""Join tests via the dual-run harness (reference: join_test.py —
SURVEY.md §4.1)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.exec import HostBatchSourceExec
from spark_rapids_tpu.exec.joins import (TpuCartesianProductExec,
                                         TpuShuffledHashJoinExec)
from spark_rapids_tpu.expr import (GreaterThan, Literal,
                                   UnresolvedColumn as col)

from asserts import assert_tpu_and_cpu_plan_equal
from data_gen import (BooleanGen, DateGen, DecimalGen, DoubleGen, FloatGen,
                      IntegerGen, LongGen, StringGen, TimestampGen,
                      gen_table)

ALL_TYPES = ["inner", "left_outer", "right_outer", "full_outer",
             "left_semi", "left_anti"]


def two_sources(key_gen_l, key_gen_r, nl=150, nr=120, seeds=(11, 22)):
    left = HostBatchSourceExec(
        [gen_table([key_gen_l, LongGen(nullable=False)], nl, seeds[0],
                   names=["lk", "lv"])])
    right = HostBatchSourceExec(
        [gen_table([key_gen_r, LongGen(nullable=False)], nr, seeds[1],
                   names=["rk", "rv"])])
    return left, right


def join_plan(jt, key_gen, **kw):
    left, right = two_sources(key_gen, key_gen, **kw)
    return TpuShuffledHashJoinExec([col("lk")], [col("rk")], jt, left,
                                   right)


@pytest.mark.parametrize("jt", ALL_TYPES)
def test_join_int_keys(jt):
    plan = join_plan(jt, IntegerGen(min_val=0, max_val=40))
    assert_tpu_and_cpu_plan_equal(plan, label=jt)


@pytest.mark.parametrize("jt", ALL_TYPES)
def test_join_null_keys(jt):
    # null keys never match; outer/anti sides still emit them
    plan = join_plan(jt, IntegerGen(min_val=0, max_val=10, null_frac=0.3))
    assert_tpu_and_cpu_plan_equal(plan, label=jt)


@pytest.mark.parametrize("jt", ALL_TYPES)
def test_join_string_keys(jt):
    plan = join_plan(jt, StringGen(max_len=4, charset="abc",
                                   null_frac=0.2))
    assert_tpu_and_cpu_plan_equal(plan, label=jt)


@pytest.mark.parametrize("kg", [LongGen(), DateGen(), TimestampGen(),
                                BooleanGen(), DecimalGen(precision=5),
                                DoubleGen(null_frac=0.2)],
                         ids=lambda g: g.dtype.simple_string())
def test_join_key_types_inner(kg):
    plan = join_plan("inner", kg)
    assert_tpu_and_cpu_plan_equal(plan)


def test_join_float_key_specials():
    # NaN==NaN and -0.0==0.0 for join keys
    left = HostBatchSourceExec([pa.record_batch(
        {"lk": pa.array([float("nan"), 0.0, -0.0, 1.5, None]),
         "lv": pa.array([1, 2, 3, 4, 5], pa.int64())})])
    right = HostBatchSourceExec([pa.record_batch(
        {"rk": pa.array([float("nan"), -0.0, 2.5, None]),
         "rv": pa.array([10, 20, 30, 40], pa.int64())})])
    for jt in ALL_TYPES:
        plan = TpuShuffledHashJoinExec([col("lk")], [col("rk")], jt,
                                       left, right)
        assert_tpu_and_cpu_plan_equal(plan, label=jt)


def test_join_multi_key():
    gens = [IntegerGen(min_val=0, max_val=5), StringGen(max_len=2,
                                                        charset="xy")]
    left = HostBatchSourceExec(
        [gen_table(gens + [LongGen(nullable=False)], 100, 1,
                   names=["k1", "k2", "lv"])])
    right = HostBatchSourceExec(
        [gen_table(gens + [LongGen(nullable=False)], 80, 2,
                   names=["k1", "k2", "rv"])])
    for jt in ("inner", "left_outer", "left_anti"):
        plan = TpuShuffledHashJoinExec(
            [col("k1"), col("k2")], [col("k1"), col("k2")], jt, left,
            right)
        assert_tpu_and_cpu_plan_equal(plan, label=jt)


def test_join_empty_sides():
    empty = HostBatchSourceExec([pa.record_batch(
        {"rk": pa.array([], pa.int32()), "rv": pa.array([], pa.int64())})])
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(), LongGen(nullable=False)], 50, 3,
                   names=["lk", "lv"])])
    for jt in ALL_TYPES:
        plan = TpuShuffledHashJoinExec([col("lk")], [col("rk")], jt, left,
                                       empty)
        assert_tpu_and_cpu_plan_equal(plan, label=f"{jt} empty right")
    empty_l = HostBatchSourceExec([pa.record_batch(
        {"lk": pa.array([], pa.int32()), "lv": pa.array([], pa.int64())})])
    right = HostBatchSourceExec(
        [gen_table([IntegerGen(), LongGen(nullable=False)], 50, 4,
                   names=["rk", "rv"])])
    for jt in ALL_TYPES:
        plan = TpuShuffledHashJoinExec([col("lk")], [col("rk")], jt,
                                       empty_l, right)
        assert_tpu_and_cpu_plan_equal(plan, label=f"{jt} empty left")


def test_join_multi_batch_stream():
    rbs = [gen_table([IntegerGen(min_val=0, max_val=20),
                      LongGen(nullable=False)], n, seed=s,
                     names=["lk", "lv"]) for n, s in [(60, 1), (90, 2)]]
    left = HostBatchSourceExec(rbs)
    right = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=20),
                    LongGen(nullable=False)], 70, 9,
                   names=["rk", "rv"])])
    for jt in ("inner", "left_outer", "full_outer", "left_semi"):
        plan = TpuShuffledHashJoinExec([col("lk")], [col("rk")], jt, left,
                                       right)
        assert_tpu_and_cpu_plan_equal(plan, label=jt)


def test_join_duplicate_heavy_keys():
    plan = join_plan("inner", IntegerGen(min_val=0, max_val=3), nl=100,
                     nr=100)
    assert_tpu_and_cpu_plan_equal(plan)


def test_inner_join_with_condition():
    left, right = two_sources(IntegerGen(min_val=0, max_val=10),
                              IntegerGen(min_val=0, max_val=10))
    plan = TpuShuffledHashJoinExec(
        [col("lk")], [col("rk")], "inner", left, right,
        condition=GreaterThan(col("lv"), col("rv")))
    assert_tpu_and_cpu_plan_equal(plan)


def test_cartesian_product():
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(), LongGen(nullable=False)], 30, 1,
                   names=["a", "b"])])
    right = HostBatchSourceExec(
        [gen_table([StringGen(max_len=3), LongGen(nullable=False)], 20, 2,
                   names=["c", "d"])])
    plan = TpuCartesianProductExec(left, right)
    assert_tpu_and_cpu_plan_equal(plan)


def test_cartesian_with_condition():
    left = HostBatchSourceExec(
        [gen_table([LongGen(nullable=False)], 25, 1, names=["a"])])
    right = HostBatchSourceExec(
        [gen_table([LongGen(nullable=False)], 25, 2, names=["b"])])
    plan = TpuCartesianProductExec(
        left, right, condition=GreaterThan(col("a"), col("b")))
    assert_tpu_and_cpu_plan_equal(plan)


def test_join_strings_payload():
    # string payload columns exercise gather char sizing on both sides
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=8), StringGen()],
                   60, 5, names=["lk", "ls"])])
    right = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=8), StringGen()],
                   50, 6, names=["rk", "rs"])])
    for jt in ("inner", "left_outer", "full_outer"):
        plan = TpuShuffledHashJoinExec([col("lk")], [col("rk")], jt, left,
                                       right)
        assert_tpu_and_cpu_plan_equal(plan, label=jt)


def test_non_equi_condition_rejected_on_non_inner_join():
    """Device execute refuses conditions on join types where post-filtering
    is semantically wrong; the CPU oracle still runs them (advisor
    round-1)."""
    from data_gen import gen_table
    from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow_cpu
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=5), IntegerGen()], 32, 1)])
    right = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=5), IntegerGen()], 32, 2,
                   names=["k", "v"])])
    j = TpuShuffledHashJoinExec([col("c0")], [col("k")], "left_outer",
                                left, right,
                                condition=GreaterThan(col("c1"), col("v")))
    assert j.tpu_supported() is not None
    with pytest.raises(NotImplementedError):
        list(j.execute(ExecCtx()))
    assert collect_arrow_cpu(j).num_rows >= 32  # oracle path works


# --- out-of-core: spillable build side, streamed outer joins ---------------

def _small_budget_conf(budget=1 << 13):
    from spark_rapids_tpu.config import RapidsConf
    return RapidsConf({"spark.rapids.memory.device.budgetBytes": budget})


@pytest.mark.parametrize("jt", ALL_TYPES)
def test_join_data_over_budget_spills(jt):
    """Join at data >> device budget: the build side registers in the
    spill catalog (forced to spill by the tiny budget) and the stream
    side stays streamed; results must still match the oracle and the
    ledger must record spill traffic (VERDICT r2 item 4)."""
    from spark_rapids_tpu.exec.base import ExecCtx
    from spark_rapids_tpu.memory import DeviceMemoryManager
    conf = _small_budget_conf()
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=60,
                               null_frac=0.05),
                    LongGen(nullable=False)], 300, 11 + i,
                   names=["lk", "lv"]) for i in range(4)])
    right = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=60, null_frac=0.05),
                    LongGen(nullable=False)], 250, 91 + i,
                   names=["rk", "rv"]) for i in range(4)])
    # the real shuffled-join plan shape: both sides behind hash
    # exchanges, whose spillable store competes with the pinned build
    # for the tiny budget
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.shuffle.partitioner import HashPartitioning
    plan = TpuShuffledHashJoinExec(
        [col("lk")], [col("rk")], jt,
        TpuShuffleExchangeExec(HashPartitioning([col("lk")], 3), left),
        TpuShuffleExchangeExec(HashPartitioning([col("rk")], 3), right))
    mm = DeviceMemoryManager(conf)
    ctx = ExecCtx(conf)
    ctx.mm = mm
    from spark_rapids_tpu.exec.base import collect_arrow, collect_arrow_cpu
    tpu = collect_arrow(plan, ctx)
    cpu = collect_arrow_cpu(plan, ExecCtx(conf))
    assert mm.spill_bytes > 0, "nothing spilled at data >> budget"
    assert sorted(tpu.to_pylist(), key=repr) == \
        sorted(cpu.to_pylist(), key=repr)


def test_outer_join_streams_build_stays_pinned():
    """full_outer over many stream batches: the chunked-stream path (no
    whole-stream concat) must agree with the oracle."""
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=30, null_frac=0.1),
                    LongGen(nullable=False)], 100, 7 + i,
                   names=["lk", "lv"]) for i in range(5)])
    right = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=30, null_frac=0.1),
                    LongGen(nullable=False)], 80, 77, names=["rk", "rv"])])
    for jt in ("right_outer", "full_outer"):
        plan = TpuShuffledHashJoinExec([col("lk")], [col("rk")], jt, left,
                                       right)
        assert_tpu_and_cpu_plan_equal(plan, ignore_order=True, label=jt)


def test_broadcast_payload_spills_and_reloads():
    """The broadcast exchange registers its payload: under a tiny budget
    it spills when idle and re-uploads on use; the join reuses the same
    catalog handle (no double registration)."""
    from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow, \
        collect_arrow_cpu
    from spark_rapids_tpu.exec.exchange import TpuBroadcastExchangeExec
    from spark_rapids_tpu.memory import DeviceMemoryManager
    conf = _small_budget_conf(1 << 10)  # < payload: spills while idle
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=20),
                    LongGen(nullable=False)], 200, 5,
                   names=["lk", "lv"])])
    right = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=20),
                    LongGen(nullable=False)], 150, 6,
                   names=["rk", "rv"])])
    bcast = TpuBroadcastExchangeExec(right)
    plan = TpuShuffledHashJoinExec([col("lk")], [col("rk")], "inner",
                                   left, bcast)
    mm = DeviceMemoryManager(conf)
    ctx = ExecCtx(conf)
    ctx.mm = mm
    tpu = collect_arrow(plan, ctx)
    cpu = collect_arrow_cpu(plan, ExecCtx(conf))
    assert sorted(tpu.to_pylist(), key=repr) == \
        sorted(cpu.to_pylist(), key=repr)
    # payload registered exactly once (join reused the handle, no
    # double-count), and the tiny budget actually forced spill traffic
    assert bcast._sb is not None
    assert len(mm._catalog) == 1
    assert mm.spill_bytes > 0
    # pin refcount drained: the payload is evictable again when idle
    assert mm._pin_counts.get(id(bcast._sb), 0) == 0


def test_shuffle_store_bytes_in_ledger():
    """Exchange map batches register in the spill catalog: shuffle bytes
    appear in (and spill from) the ledger (VERDICT r2 weak #4)."""
    from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.memory import DeviceMemoryManager
    from spark_rapids_tpu.shuffle.partitioner import HashPartitioning
    conf = _small_budget_conf(1 << 12)
    src = HostBatchSourceExec(
        [gen_table([IntegerGen(), LongGen(nullable=False)], 400, 3 + i)
         for i in range(4)])
    plan = TpuShuffleExchangeExec(HashPartitioning([col("c0")], 4), src)
    mm = DeviceMemoryManager(conf)
    ctx = ExecCtx(conf)
    ctx.mm = mm
    out = collect_arrow(plan, ctx)
    assert out.num_rows == 1600
    assert mm.spill_bytes > 0, "shuffle store never hit the ledger"


# --- broadcast nested loop join (non-equi for every type) ------------------

def _bnlj_plan(jt, nl=60, nr=45):
    from spark_rapids_tpu.exec.joins import TpuBroadcastNestedLoopJoinExec
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=50, null_frac=0.1),
                    LongGen(nullable=False)], nl, 21, names=["lk", "lv"]),
         gen_table([IntegerGen(min_val=0, max_val=50, null_frac=0.1),
                    LongGen(nullable=False)], nl // 2, 22,
                   names=["lk", "lv"])])
    right = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=50, null_frac=0.1),
                    LongGen(nullable=False)], nr, 23,
                   names=["rk", "rv"])])
    cond = GreaterThan(col("lk"), col("rk"))
    return TpuBroadcastNestedLoopJoinExec(jt, left, right, cond)


@pytest.mark.parametrize("jt", ALL_TYPES + ["cross"])
def test_bnlj_non_equi_all_types(jt):
    if jt == "cross":
        plan = _bnlj_plan("cross")
    else:
        plan = _bnlj_plan(jt)
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True, label=jt)


def test_bnlj_condition_with_strings_payload():
    from spark_rapids_tpu.exec.joins import TpuBroadcastNestedLoopJoinExec
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=20),
                    StringGen(max_len=5)], 40, 31, names=["lk", "ls"])])
    right = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=20),
                    StringGen(max_len=5)], 30, 32, names=["rk", "rs"])])
    cond = GreaterThan(col("lk"), col("rk"))
    plan = TpuBroadcastNestedLoopJoinExec("left_outer", left, right, cond)
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_bnlj_empty_sides():
    from spark_rapids_tpu.exec.joins import TpuBroadcastNestedLoopJoinExec
    from spark_rapids_tpu import datatypes as _dt
    schema = _dt.Schema([_dt.StructField("rk", _dt.INT32, True),
                         _dt.StructField("rv", _dt.INT64, False)])
    empty = HostBatchSourceExec([], schema=schema)
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(), LongGen(nullable=False)], 30, 41,
                   names=["lk", "lv"])])
    cond = GreaterThan(col("lk"), col("rk"))
    for jt in ("left_outer", "full_outer", "left_anti"):
        plan = TpuBroadcastNestedLoopJoinExec(jt, left, empty, cond)
        assert_tpu_and_cpu_plan_equal(plan, ignore_order=True, label=jt)


# --- unique-build fast path (sync-free join, VERDICT r3 #1) ---------------

def _unique_right(jt, key_gen, with_str_payload=False, hint=False,
                  nl=150, nr=120):
    """Join whose build keys are unique by construction (seeded gen over
    a wide domain, deduped)."""
    import pyarrow.compute as pc
    right_rb = gen_table(
        [key_gen, LongGen(nullable=False)]
        + ([StringGen(max_len=6)] if with_str_payload else []),
        nr, 22, names=["rk", "rv"] + (["rs"] if with_str_payload else []))
    # dedupe build keys -> the analysis must see max_dup == 1
    tbl = pa.Table.from_batches([right_rb])
    tbl = tbl.group_by("rk", use_threads=False).aggregate(
        [("rv", "min")] + ([("rs", "min")] if with_str_payload else []))
    names = ["rk", "rv"] + (["rs"] if with_str_payload else [])
    right_rb = pa.record_batch(
        [tbl.column(i).combine_chunks() for i in range(tbl.num_columns)],
        names=names)
    left = HostBatchSourceExec(
        [gen_table([key_gen, LongGen(nullable=False)], nl, 11,
                   names=["lk", "lv"])])
    return TpuShuffledHashJoinExec([col("lk")], [col("rk")], jt, left,
                                   HostBatchSourceExec([right_rb]),
                                   build_unique_hint=hint)


@pytest.mark.parametrize("jt", ["inner", "left_outer", "left_semi",
                                "left_anti"])
def test_join_fast_path_unique_build(jt):
    plan = _unique_right(jt, IntegerGen(min_val=0, max_val=1000,
                                        null_frac=0.1))
    from spark_rapids_tpu.exec.base import ExecCtx
    info = plan._fast_build_info(
        next(iter(plan.right.execute(ExecCtx()))), ExecCtx())
    assert info is not None and info["probe"] is not None, \
        "unique int build must take the probe fast path"
    assert_tpu_and_cpu_plan_equal(plan, label=f"fast-{jt}")


@pytest.mark.parametrize("jt", ["inner", "left_outer"])
def test_join_fast_path_string_key_and_payload(jt):
    # string key -> union-lookup fast path; string payload -> static caps
    plan = _unique_right(jt, StringGen(max_len=6, charset="abcdefgh",
                                       null_frac=0.1),
                         with_str_payload=True)
    from spark_rapids_tpu.exec.base import ExecCtx
    info = plan._fast_build_info(
        next(iter(plan.right.execute(ExecCtx()))), ExecCtx())
    assert info is not None and info["probe"] is None
    assert_tpu_and_cpu_plan_equal(plan, label=f"fast-str-{jt}")


def test_join_fast_path_rejects_duplicate_build():
    plan = join_plan("inner", IntegerGen(min_val=0, max_val=5,
                                         nullable=False))
    from spark_rapids_tpu.exec.base import ExecCtx
    info = plan._fast_build_info(
        next(iter(plan.right.execute(ExecCtx()))), ExecCtx())
    assert info is None, "dup build keys must use the staged path"
    assert_tpu_and_cpu_plan_equal(plan, label="dup-staged")


def test_join_unique_hint_skips_analysis_sync():
    plan = _unique_right("inner", IntegerGen(min_val=0, max_val=1000,
                                             nullable=False), hint=True)
    from spark_rapids_tpu.exec.base import ExecCtx
    # with the hint and no build strings, no analysis jit is ever built
    info = plan._fast_build_info(
        next(iter(plan.right.execute(ExecCtx()))), ExecCtx())
    assert info is not None
    assert plan._jit_analysis is None, \
        "hint + string-free build must not pay the analysis readback"
    assert_tpu_and_cpu_plan_equal(plan, label="hint")


def test_join_fast_path_inner_condition():
    plan = _unique_right("inner", IntegerGen(min_val=0, max_val=1000,
                                             null_frac=0.1))
    plan = TpuShuffledHashJoinExec(
        [col("lk")], [col("rk")], "inner", plan.left, plan.right,
        condition=GreaterThan(col("lv"), col("rv")))
    assert_tpu_and_cpu_plan_equal(plan, label="fast-cond")


# --- build_unique hint verification (VERDICT r4 weak #3 / ADVICE #4) -------

def _dup_build_sources():
    import numpy as np
    left = HostBatchSourceExec([pa.record_batch({
        "lk": pa.array(np.arange(50, dtype=np.int32)),
        "lv": pa.array(np.arange(50, dtype=np.int64))})])
    right = HostBatchSourceExec([pa.record_batch({
        "rk": pa.array((np.arange(40, dtype=np.int32) % 20)),  # dups!
        "rv": pa.array(np.arange(40, dtype=np.int64))})])
    return left, right


def test_unique_hint_false_caught_deferred():
    """Zero-readback fast path (no strings): a FALSE hint is caught by
    the device-side probe and raised at the first natural download."""
    from spark_rapids_tpu.exec.base import collect_arrow
    left, right = _dup_build_sources()
    join = TpuShuffledHashJoinExec([col("lk")], [col("rk")], "inner",
                                   left, right, build_unique_hint=True)
    with pytest.raises(RuntimeError, match="build_unique hint violated"):
        collect_arrow(join)


def test_unique_hint_false_multikey_caught_deferred():
    from spark_rapids_tpu.exec.base import collect_arrow
    left, right = _dup_build_sources()
    join = TpuShuffledHashJoinExec([col("lk"), col("lv")],
                                   [col("rk"), col("rv")], "inner",
                                   left, right, build_unique_hint=True)
    # rv is unique so (rk, rv) is unique -> passes; force dups by
    # joining on rk twice
    join = TpuShuffledHashJoinExec([col("lk"), col("lk")],
                                   [col("rk"), col("rk")], "inner",
                                   left, right, build_unique_hint=True)
    with pytest.raises(RuntimeError, match="build_unique hint violated"):
        collect_arrow(join)


def test_unique_hint_false_with_strings_reverts_staged():
    """When the build analysis readback happens anyway (string payload),
    a false hint is validated eagerly for free: warn + fall back to the
    duplicate-correct staged path — results match the oracle."""
    import numpy as np
    left = HostBatchSourceExec([pa.record_batch({
        "lk": pa.array(np.arange(30, dtype=np.int32)),
        "lv": pa.array(np.arange(30, dtype=np.int64))})])
    right = HostBatchSourceExec([pa.record_batch({
        "rk": pa.array((np.arange(24, dtype=np.int32) % 12)),
        "rs": pa.array([f"s{i}" for i in range(24)])})])
    join = TpuShuffledHashJoinExec([col("lk")], [col("rk")], "inner",
                                   left, right, build_unique_hint=True)
    with pytest.warns(RuntimeWarning, match="build_unique hint is FALSE"):
        assert_tpu_and_cpu_plan_equal(join)


def test_unique_hint_true_passes_verification():
    import numpy as np
    from spark_rapids_tpu.exec.base import collect_arrow
    left = HostBatchSourceExec([pa.record_batch({
        "lk": pa.array(np.arange(50, dtype=np.int32) % 25),
        "lv": pa.array(np.arange(50, dtype=np.int64))})])
    right = HostBatchSourceExec([pa.record_batch({
        "rk": pa.array(np.arange(20, dtype=np.int32)),
        "rv": pa.array(np.arange(20, dtype=np.int64))})])
    join = TpuShuffledHashJoinExec([col("lk")], [col("rk")], "inner",
                                   left, right, build_unique_hint=True)
    out = collect_arrow(join)  # deferred check passes
    assert out.num_rows == 40


def test_unique_hint_verify_off_is_unchecked():
    """Conf off: the hint is trusted verbatim (the reference-style
    trust-me escape hatch) — no raise, even though results drop dups."""
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow
    left, right = _dup_build_sources()
    join = TpuShuffledHashJoinExec([col("lk")], [col("rk")], "inner",
                                   left, right, build_unique_hint=True)
    conf = RapidsConf({"spark.rapids.sql.join.verifyUniqueHint": "false"})
    out = collect_arrow(join, ExecCtx(conf))
    assert out.num_rows == 20  # one match per stream row: dropped dups
