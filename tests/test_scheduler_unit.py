"""Scheduler unit tests: chaos-rule parsing, the attempt-suffixed
shuffle commit protocol, and the TaskScheduler retry/blacklist/
speculation state machine driven through a fake worker pool — no OS
processes, no JAX. The process-level recovery paths live in
test_scheduler.py."""
import os
import threading
import time

import pyarrow as pa
import pytest

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.scheduler import TaskScheduler, TaskSpec
from spark_rapids_tpu.scheduler.chaos import (ChaosRule, find_rule,
                                              parse_fault_spec)


# --- chaos rules -----------------------------------------------------------

def test_chaos_parse_basic():
    rules = parse_fault_spec(
        "crash:q1s1m0:0; hang:*m1:*; delay:q2*:1:3.5; crash:t:0@w1")
    # no 4th field -> seconds None (an explicit ':2' must stay
    # distinguishable from "no arg"); mode defaults apply via .arg()
    assert rules[0] == ChaosRule("crash", "q1s1m0", 0, None, None)
    assert rules[0].arg(2.0) == 2.0
    assert rules[1].attempt is None and rules[1].mode == "hang"
    assert rules[2].seconds == 3.5
    assert rules[3].worker == 1


def test_chaos_parse_empty_and_bad():
    assert parse_fault_spec("") == []
    assert parse_fault_spec(None) == []
    # unknown mode: a hard parse error NAMING the mode and the valid
    # set — never a silent no-op (a typo'd chaos spec that injects
    # nothing would green-light the exact test it was meant to fail)
    with pytest.raises(ValueError, match="unknown injectFaults mode "
                                         "'explode'"):
        parse_fault_spec("explode:x:0")
    with pytest.raises(ValueError, match="hang_query"):
        parse_fault_spec("explode:x:0")  # valid modes are listed
    with pytest.raises(ValueError, match="bad injectFaults"):
        parse_fault_spec("crash:x")  # missing attempt


def test_chaos_matching():
    spec = "crash:q1s1m0:0; delay:*m1:*:1.0@w1"
    assert find_rule(spec, 0, "q1s1m0", 0).mode == "crash"
    assert find_rule(spec, 0, "q1s1m0", 1) is None  # retry runs clean
    assert find_rule(spec, 1, "q9s3m1", 7).mode == "delay"
    assert find_rule(spec, 0, "q9s3m1", 7) is None  # wrong worker
    assert find_rule(spec, 0, "other", 0) is None


# --- commit protocol (shuffle/host.py) -------------------------------------

def _rb(vals):
    return pa.record_batch({"x": pa.array(vals, pa.int64())})


def test_commit_first_attempt_wins(tmp_path):
    from spark_rapids_tpu.shuffle.host import HostShuffleTransport
    t = HostShuffleTransport(RapidsConf(), threads=0, root=str(tmp_path))
    t.register_shuffle(1, 2)
    d0 = t.begin_task_attempt(1, "t0", 0)
    t._write_rb(1, 0, 0, _rb([1, 2, 3]), subdir=d0)
    assert t.commit_task_attempt(1, "t0", 0) is True
    # zombie attempt: full output, commits late, must vanish entirely
    d1 = t.begin_task_attempt(1, "t0", 1)
    t._write_rb(1, 0, 0, _rb([9, 9, 9]), subdir=d1)
    t._write_rb(1, 0, 1, _rb([8]), subdir=d1)
    assert t.commit_task_attempt(1, "t0", 1) is False
    assert not os.path.exists(d1)
    files = t.committed_partition_files(t._sdir(1), 0)
    assert len(files) == 1 and "t0.mapout" in files[0]
    # blocks now carry an integrity trailer: read through the verifier
    from spark_rapids_tpu.shuffle import integrity
    got = pa.ipc.open_file(
        pa.BufferReader(integrity.read_block(files[0]))).read_all()
    assert got.column("x").to_pylist() == [1, 2, 3]
    # the loser's partition-1 file must not exist anywhere
    assert t.committed_partition_files(t._sdir(1), 1) == []


def test_empty_output_commit_still_exclusive(tmp_path):
    """rename() succeeds onto an empty dir, so a zero-row map output
    needs the staging sentinel to keep first-commit-wins exclusive."""
    from spark_rapids_tpu.shuffle.host import HostShuffleTransport
    t = HostShuffleTransport(RapidsConf(), threads=0, root=str(tmp_path))
    t.register_shuffle(1, 1)
    t.begin_task_attempt(1, "t0", 0)
    assert t.commit_task_attempt(1, "t0", 0) is True
    t.begin_task_attempt(1, "t0", 1)  # zombie with empty output too
    assert t.commit_task_attempt(1, "t0", 1) is False
    assert t.committed_partition_files(t._sdir(1), 0) == []


def test_writer_map_batch_stages_under_subdir(tmp_path):
    """The real map-task path (writer -> write_unsplit ->
    _write_map_batch) must honor the attempt staging dir end to end —
    a flat write here would let concurrent attempts tear each other's
    partition files."""
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    from spark_rapids_tpu.shuffle.host import HostShuffleTransport
    t = HostShuffleTransport(RapidsConf(), threads=0, root=str(tmp_path))
    t.register_shuffle(1, 2)
    d = t.begin_task_attempt(1, "t0", 0)
    batch = arrow_to_device(_rb([10, 20, 30]))
    pids = jnp.array([0, 1, 0], jnp.int32)
    w = t.writer(1, map_id=0, subdir=d)
    w.write_unsplit(batch, pids)
    # nothing flat, everything staged
    flat = [n for n in os.listdir(t._sdir(1)) if n.endswith(".arrow")]
    assert flat == []
    assert sorted(os.listdir(d)) == [".attempt", "m00000_p0.arrow",
                                     "m00000_p1.arrow"]
    assert t.commit_task_attempt(1, "t0", 0) is True
    assert len(t.committed_partition_files(t._sdir(1), 0)) == 1


def test_staging_invisible_until_commit(tmp_path):
    from spark_rapids_tpu.shuffle.host import HostShuffleTransport
    t = HostShuffleTransport(RapidsConf(), threads=0, root=str(tmp_path))
    t.register_shuffle(1, 1)
    d = t.begin_task_attempt(1, "t0", 0)
    t._write_rb(1, 0, 0, _rb([1]), subdir=d)
    assert t.committed_partition_files(t._sdir(1), 0) == []
    t.abort_task_attempt(1, "t0", 0)
    assert not os.path.exists(d)


def test_process_shuffle_read_sees_only_committed(tmp_path):
    from spark_rapids_tpu import datatypes as dt
    from spark_rapids_tpu.cluster import ProcessShuffleReadExec
    from spark_rapids_tpu.shuffle.host import HostShuffleTransport
    t = HostShuffleTransport(RapidsConf(), threads=0, root=str(tmp_path))
    t.register_shuffle(3, 1)
    d = t.begin_task_attempt(3, "m0", 0)
    t._write_rb(3, 0, 0, _rb([4, 5]), subdir=d)
    t.commit_task_attempt(3, "m0", 0)
    d = t.begin_task_attempt(3, "m1", 0)  # uncommitted straggler
    t._write_rb(3, 100000, 0, _rb([7]), subdir=d)
    schema = dt.Schema([dt.StructField("x", dt.INT64, True)])
    read = ProcessShuffleReadExec(str(tmp_path), 3, [0], schema)
    rows = [v for rb in read.execute_cpu(None)
            for v in rb.column(0).to_pylist()]
    assert rows == [4, 5]


# --- TaskScheduler over a fake pool ----------------------------------------

class FakePool:
    def __init__(self, n):
        self.n = n
        self.dead = set()
        self.respawned = []
        self._ts = time.time()

    def alive(self, w):
        return w not in self.dead

    def exit_info(self, w):
        return 1, "fake worker death"

    def kill(self, w):
        self.dead.add(w)

    def respawn(self, w):
        self.dead.discard(w)
        self.respawned.append(w)

    def heartbeat_age(self, w):
        return 0.0

    def spawn_ts(self, w):
        return self._ts


class Responder:
    """Plays the worker side: polls the tasks dir and answers each new
    attempt file per `script(task_id, attempt, worker) -> 'ok' | 'err'
    | None` (None = leave it running)."""

    def __init__(self, tasks_dir, script):
        self.tasks_dir = tasks_dir
        self.script = script
        self._stop = threading.Event()
        self._seen = set()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.wait(0.01):
            self.poll()

    def poll(self):
        for name in sorted(os.listdir(self.tasks_dir)):
            if not name.endswith(".task") or name in self._seen:
                continue
            stem = name[:-len(".task")]
            tid, a, w = stem.rsplit(".", 2)
            verdict = self.script(tid, int(a[1:]), int(w[1:]))
            if verdict is None:
                continue
            self._seen.add(name)
            path = os.path.join(self.tasks_dir, name)
            with open(path + ".claim", "w") as f:
                f.write("claimed")
            with open(path + "." + verdict, "w") as f:
                f.write("synthetic failure" if verdict == "err" else "ok")

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2)


def _conf(**over):
    base = {
        "spark.rapids.tpu.task.maxAttempts": 2,
        "spark.rapids.tpu.task.timeout": 5.0,
        "spark.rapids.tpu.scheduler.stageTimeout": 10.0,
        "spark.rapids.tpu.scheduler.maxTaskFailuresPerWorker": 2,
        "spark.rapids.tpu.heartbeat.timeout": 100.0,
    }
    base.update(over)
    return RapidsConf(base)


def _specs(*ids):
    return [TaskSpec(t, "noop", {"conf": {}}) for t in ids]


def test_retry_lands_on_other_worker(tmp_path):
    def script(tid, attempt, worker):
        if tid == "t0" and worker == 0:
            return "err"
        return "ok"

    pool = FakePool(2)
    sched = TaskScheduler(pool, str(tmp_path), _conf())
    r = Responder(str(tmp_path), script)
    try:
        sched.run_stage(_specs("t0", "t1"))
    finally:
        r.stop()
    oks = [e for e in sched.events if e["event"] == "task_ok"]
    assert {e["task"] for e in oks} == {"t0", "t1"}
    t0_ok = next(e for e in oks if e["task"] == "t0")
    assert t0_ok["attempt"] == 1 and t0_ok["worker"] == 1
    assert sched.worker_failures.get(0) == 1
    assert sched.summary()["failures"] == 1


def test_blacklist_after_max_failures(tmp_path):
    def script(tid, attempt, worker):
        return "err" if worker == 0 else "ok"

    pool = FakePool(2)
    sched = TaskScheduler(
        pool, str(tmp_path),
        _conf(**{"spark.rapids.tpu.scheduler.maxTaskFailuresPerWorker": 1,
                 "spark.rapids.tpu.task.maxAttempts": 4}))
    r = Responder(str(tmp_path), script)
    try:
        sched.run_stage(_specs("t0", "t1", "t2"))
    finally:
        r.stop()
    assert 0 in sched.blacklist
    assert any(e["event"] == "worker_blacklisted" and e["worker"] == 0
               for e in sched.events)
    # everything after the blacklist landed on worker 1
    oks = [e for e in sched.events if e["event"] == "task_ok"]
    assert len(oks) == 3 and all(e["worker"] == 1 for e in oks)


def test_bounded_retry_exhaustion_raises(tmp_path):
    pool = FakePool(2)
    sched = TaskScheduler(pool, str(tmp_path), _conf())
    r = Responder(str(tmp_path), lambda *a: "err")
    try:
        with pytest.raises(RuntimeError, match="worker task t0 failed "
                                               "after 2 attempts"):
            sched.run_stage(_specs("t0"))
    finally:
        r.stop()


def test_worker_death_respawns_and_retries(tmp_path):
    pool = FakePool(2)
    state = {"killed": False}

    def script(tid, attempt, worker):
        if tid == "t0" and attempt == 0:
            # the dead incarnation must NEVER finish this attempt: a
            # later rescan answering "ok" for attempt 0 raced the
            # scheduler's liveness pass under load and made the stage
            # complete respawn-free (flaky under a loaded full-suite
            # run)
            if not state["killed"]:
                state["killed"] = True
                pool.dead.add(worker)  # process "dies" mid-task
            return None
        return "ok"

    sched = TaskScheduler(pool, str(tmp_path), _conf())
    r = Responder(str(tmp_path), script)
    try:
        sched.run_stage(_specs("t0"))
    finally:
        r.stop()
    assert pool.respawned, "dead worker was not respawned"
    assert any(e["event"] == "worker_respawn" for e in sched.events)
    assert any(e["event"] == "task_ok" and e["task"] == "t0"
               and e["attempt"] == 1 for e in sched.events)


def test_blacklisted_worker_death_still_detected(tmp_path):
    """Blacklisting must not blind the liveness loop: an attempt
    assigned (but never claimed) on a worker that is blacklisted and
    THEN dies has no claim_ts for the task timeout — only the death
    check can recover it before the stage deadline."""
    pool = FakePool(2)

    def script(tid, attempt, worker):
        if worker == 0:
            if tid == "t0":
                return "err"  # one failure -> w0 blacklisted
            # t2 assigned to w0: kill w0 while it sits unclaimed
            pool.dead.add(0)
            return None
        return "ok"

    sched = TaskScheduler(
        pool, str(tmp_path),
        _conf(**{"spark.rapids.tpu.scheduler.maxTaskFailuresPerWorker": 1,
                 "spark.rapids.tpu.task.maxAttempts": 4,
                 "spark.rapids.tpu.scheduler.stageTimeout": 8.0}))
    r = Responder(str(tmp_path), script)
    t0 = time.time()
    try:
        sched.run_stage(_specs("t0", "t1", "t2"))
    finally:
        r.stop()
    wall = time.time() - t0
    oks = {e["task"] for e in sched.events if e["event"] == "task_ok"}
    assert oks == {"t0", "t1", "t2"}
    assert any(e["event"] == "worker_respawn" and e["worker"] == 0
               for e in sched.events)
    assert wall < 5.0, f"recovered only via stage deadline ({wall:.1f}s)"


def test_speculation_duplicates_straggler(tmp_path):
    tasks_dir = str(tmp_path)
    done_b1 = threading.Event()

    def slow_ok_marks():
        return sum(1 for n in os.listdir(tasks_dir)
                   if n.startswith("slow.") and n.endswith(".ok"))

    def script(tid, attempt, worker):
        if tid == "fast":
            return "ok"
        if tid == "slow":
            if attempt == 0:
                # straggles until the speculative sibling is done, then
                # completes as a zombie — exactly one of the two .oks
                # may win, the other must be recorded as lost
                return "ok" if done_b1.is_set() else None
            done_b1.set()
            return "ok"
        # tail holds the stage open until both slow attempts landed so
        # the winner/loser bookkeeping is observable deterministically
        return "ok" if slow_ok_marks() >= 2 else None

    pool = FakePool(2)
    sched = TaskScheduler(
        pool, str(tmp_path),
        _conf(**{"spark.rapids.tpu.speculation": "true",
                 "spark.rapids.tpu.speculation.multiplier": 1.0,
                 "spark.rapids.tpu.speculation.minRuntime": 0.1,
                 "spark.rapids.tpu.task.maxAttempts": 4}))
    r = Responder(str(tmp_path), script)
    try:
        sched.run_stage(_specs("fast", "slow", "tail"))
    finally:
        r.stop()
    assert any(e["event"] == "speculative_attempt" and e["task"] == "slow"
               for e in sched.events)
    oks = [e for e in sched.events
           if e["event"] == "task_ok" and e["task"] == "slow"]
    lost = [e for e in sched.events
            if e["event"] == "attempt_lost" and e["task"] == "slow"]
    assert len(oks) == 1 and len(lost) == 1
    assert {oks[0]["attempt"], lost[0]["attempt"]} == {0, 1}


def test_speculation_win_completes_stage_without_straggler(tmp_path):
    """The point of speculation is the latency win: once the duplicate
    commits, the stage must finish WITHOUT waiting out (or killing) the
    still-running original attempt."""
    def script(tid, attempt, worker):
        if tid == "slow" and attempt == 0:
            return None  # original straggles forever
        return "ok"

    pool = FakePool(2)
    sched = TaskScheduler(
        pool, str(tmp_path),
        _conf(**{"spark.rapids.tpu.speculation": "true",
                 "spark.rapids.tpu.speculation.multiplier": 1.0,
                 "spark.rapids.tpu.speculation.minRuntime": 0.1,
                 "spark.rapids.tpu.task.timeout": 6.0,
                 "spark.rapids.tpu.scheduler.stageTimeout": 10.0}))
    r = Responder(str(tmp_path), script)
    t0 = time.time()
    try:
        sched.run_stage(_specs("fast", "slow"))
    finally:
        r.stop()
    wall = time.time() - t0
    assert wall < 4.0, f"stage blocked on superseded attempt ({wall:.1f}s)"
    assert any(e["event"] == "task_ok" and e["task"] == "slow"
               and e["attempt"] == 1 for e in sched.events)
    # the straggler's worker was neither killed nor blamed
    assert not pool.dead and not pool.respawned
    assert sched.summary()["failures"] == 0


def test_speculation_off_by_default(tmp_path):
    conf = RapidsConf()
    from spark_rapids_tpu.config import SPECULATION
    assert conf.get(SPECULATION) is False
