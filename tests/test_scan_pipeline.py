"""Upload-pipeline robustness: the shared ordered feeder
(`spark_rapids_tpu.pipeline.pipelined_map`) and the device-decode scan
path built on it — feeder exception propagation, early close without
deadlock, and the bounded in-flight device-residency window (the legacy
arrow feeder's guarantees, now for the device-decode tunnel)."""
import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec.base import ExecCtx
from spark_rapids_tpu.io import TpuFileScanExec
from spark_rapids_tpu.pipeline import pipelined_map


# --- pipelined_map unit tests ----------------------------------------------

def test_order_and_results():
    out = list(pipelined_map(lambda x: x * x, range(50), threads=4,
                             window=8))
    assert out == [x * x for x in range(50)]


def test_serial_degrade():
    # threads<=0 or window<=0 is the kill switch: same results, no pool
    for threads, window in ((0, 4), (2, 0)):
        out = list(pipelined_map(lambda x: x + 1, range(5),
                                 threads=threads, window=window))
        assert out == [1, 2, 3, 4, 5]


def test_worker_exception_at_its_position():
    def fn(x):
        if x == 3:
            raise ValueError("boom3")
        return x

    got = []
    with pytest.raises(ValueError, match="boom3"):
        for v in pipelined_map(fn, range(6), threads=3, window=4):
            got.append(v)
    # every result BEFORE the failing item was delivered, in order
    assert got == [0, 1, 2]


def test_source_exception_propagates():
    def src():
        yield 1
        yield 2
        raise RuntimeError("src died")

    gen = pipelined_map(lambda x: x * 10, src(), threads=2, window=2)
    assert next(gen) == 10
    assert next(gen) == 20
    with pytest.raises(RuntimeError, match="src died"):
        next(gen)


def test_early_close_no_deadlock_on_full_window():
    produced = []

    def src():
        for i in range(10_000):
            produced.append(i)
            yield i

    gen = pipelined_map(lambda x: x, src(), threads=1, window=2)
    assert next(gen) == 0
    t0 = time.monotonic()
    gen.close()  # the feeder is parked on a full window right now
    assert time.monotonic() - t0 < 5.0
    with pytest.raises(StopIteration):
        next(gen)
    # the feeder stopped near the window, not after draining the source
    assert len(produced) < 100


def test_bounded_inflight_under_slow_consumer():
    lock = threading.Lock()
    state = {"started": 0, "consumed": 0, "max_excess": 0}

    def fn(x):
        with lock:
            state["started"] += 1
            state["max_excess"] = max(
                state["max_excess"],
                state["started"] - state["consumed"])
        return x

    for _ in pipelined_map(fn, range(30), threads=4, window=3):
        time.sleep(0.002)  # slow consumer
        with lock:
            state["consumed"] += 1
    # at most `window` undelivered results + the one being handed over
    assert state["max_excess"] <= 3 + 1, state


def test_weight_bounded_inflight():
    """With a weigher, the in-flight window is bounded in summed weight
    too: heavy items (the widened envelope's string blobs) must not
    stack up to `window` at once; a single over-budget item still
    admits alone (progress, not deadlock)."""
    lock = threading.Lock()
    state = {"inflight": 0, "max_w": 0}
    weights = [10, 10, 100, 10, 250, 10, 10, 10]  # 250 > max_weight

    def fn(i):
        with lock:
            state["inflight"] += weights[i]
            state["max_w"] = max(state["max_w"], state["inflight"])
        time.sleep(0.003)
        return i

    out = []
    for i in pipelined_map(fn, range(len(weights)), threads=4, window=8,
                           weigher=lambda i: weights[i],
                           max_weight=120):
        with lock:
            state["inflight"] -= weights[i]
        out.append(i)
    assert out == list(range(len(weights)))
    # admitted weight never exceeds budget + one in-hand-over item,
    # except the single over-budget item which runs alone
    assert state["max_w"] <= 250 + 10, state


def test_weigher_exception_is_source_exception():
    def bad_weigher(i):
        if i == 2:
            raise RuntimeError("weigher boom")
        return 1

    got = []
    gen = pipelined_map(lambda x: x, range(5), threads=2, window=2,
                        weigher=bad_weigher, max_weight=10)
    with pytest.raises(RuntimeError, match="weigher boom"):
        for v in gen:
            got.append(v)
    assert got == [0, 1]


# --- device-decode scan pipeline -------------------------------------------

def _write_rg_file(tmp_path, n=8000, rg=2000, name="f.parquet"):
    rng = np.random.default_rng(0)
    t = pa.table({
        "a": pa.array(rng.integers(0, 9, n).astype(np.int32)),
        "b": pa.array(rng.uniform(0, 1, n)),
    })
    p = os.path.join(str(tmp_path), name)
    pq.write_table(t, p, row_group_size=rg)
    return p


def test_device_decode_feeder_exception_propagates(tmp_path, monkeypatch):
    """A planner failure on the feeder side must surface in the
    consumer as the original exception, not a hang or a truncated
    stream."""
    p = _write_rg_file(tmp_path)
    orig = TpuFileScanExec._plan_row_group

    def boom(self, path, g):
        if g >= 2:
            raise OSError("disk gone")
        return orig(self, path, g)

    monkeypatch.setattr(TpuFileScanExec, "_plan_row_group", boom)
    scan = TpuFileScanExec([p])
    with pytest.raises(OSError, match="disk gone"):
        list(scan.execute(ExecCtx()))


def test_device_decode_early_close_no_deadlock(tmp_path):
    """Closing the scan generator with a full in-flight window must not
    deadlock the feeder, and must release every in-flight ledger
    charge."""
    from spark_rapids_tpu.memory import DeviceMemoryManager
    conf = RapidsConf({
        "spark.rapids.sql.scan.coalesceTargetBytes": "0",
        "spark.rapids.sql.scan.inFlightBatches": "1",
    })
    mgr = DeviceMemoryManager.shared(conf)
    p = _write_rg_file(tmp_path, n=16_000, rg=1000)
    scan = TpuFileScanExec([p], conf=conf)
    before = mgr.device_bytes
    gen = scan.execute(ExecCtx(conf))
    batch = next(gen)
    assert batch.num_rows == 1000
    t0 = time.monotonic()
    gen.close()
    assert time.monotonic() - t0 < 10.0
    # stragglers release on their own thread; give them a moment
    for _ in range(100):
        if mgr.device_bytes <= before:
            break
        time.sleep(0.02)
    assert mgr.device_bytes <= before


def test_device_decode_bounded_inflight_and_ledger(tmp_path, monkeypatch):
    """Under a slow consumer the feeder may run at most
    inFlightBatches assembled-but-unconsumed batches ahead, every one
    registered with (and then released from) the device memory
    ledger."""
    from spark_rapids_tpu.memory import DeviceMemoryManager
    conf = RapidsConf({
        "spark.rapids.sql.scan.coalesceTargetBytes": "0",
        "spark.rapids.sql.scan.inFlightBatches": "2",
        "spark.rapids.sql.scan.uploadThreads": "2",
    })
    window = 2
    mgr = DeviceMemoryManager.shared(conf)
    p = _write_rg_file(tmp_path, n=16_000, rg=1000)  # 16 row groups
    lock = threading.Lock()
    state = {"started": 0, "consumed": 0, "max_excess": 0}
    registered = []
    orig_assemble = TpuFileScanExec._assemble_device_batch
    orig_register = DeviceMemoryManager.register

    def counting_assemble(self, *a, **kw):
        with lock:
            state["started"] += 1
            state["max_excess"] = max(
                state["max_excess"],
                state["started"] - state["consumed"])
        return orig_assemble(self, *a, **kw)

    def spy_register(self, batch, pinned=False):
        sb = orig_register(self, batch, pinned=pinned)
        registered.append(sb)
        return sb

    monkeypatch.setattr(TpuFileScanExec, "_assemble_device_batch",
                        counting_assemble)
    monkeypatch.setattr(DeviceMemoryManager, "register", spy_register)
    before = mgr.device_bytes
    scan = TpuFileScanExec([p], conf=conf)
    n_rows = n_batches = 0
    for b in scan.execute(ExecCtx(conf)):
        time.sleep(0.01)  # slow consumer
        with lock:
            state["consumed"] += 1
        n_rows += b.num_rows
        n_batches += 1
    assert n_rows == 16_000
    assert n_batches == 16
    assert len(registered) == 16  # one ledger entry per batch
    assert mgr.device_bytes == before  # all in-flight charges released
    assert state["max_excess"] <= window + 1, state
