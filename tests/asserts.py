"""Dual-run equivalence assertions (reference: integration_tests asserts.py
`assert_gpu_and_cpu_are_equal_collect` — SURVEY.md §4.1; built from
capability description, mount empty).

Expression-level: evaluate the same expression tree on the CPU (pyarrow/
numpy, Spark-semantics oracle) and on the TPU path (device batch), compare.
Plan-level helpers are added with the session API.
"""
from __future__ import annotations

import math

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.columnar import arrow_to_device
from spark_rapids_tpu.columnar.arrow_bridge import device_column_to_arrow
from spark_rapids_tpu.expr.base import EvalCtx, bind_expr
from spark_rapids_tpu.columnar.arrow_bridge import engine_schema


def _norm_nested(v):
    """Recursive NaN-stable normalizer for nested (struct/array/map)
    python values."""
    if isinstance(v, float) and math.isnan(v):
        return "NaN"
    if isinstance(v, dict):
        return {k: _norm_nested(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return tuple(_norm_nested(x) for x in v)
    return v


def _normalize(values, t: dt.DataType, approx_float=False):
    if dt.is_nested(t):
        return [_norm_nested(v) for v in values]
    out = []
    for v in values:
        if v is None:
            out.append(None)
        elif dt.is_floating(t):
            if isinstance(v, float) and math.isnan(v):
                out.append("NaN")
            elif approx_float and isinstance(v, float) and math.isfinite(v):
                out.append(round(v, 10) if abs(v) < 1e100 else v)
            else:
                out.append(v)
        else:
            out.append(v)
    return out


def assert_columns_equal(cpu: pa.Array, tpu: pa.Array, t: dt.DataType,
                         approx_float=False, label=""):
    cl = _normalize(cpu.to_pylist(), t, approx_float)
    tl = _normalize(tpu.to_pylist(), t, approx_float)
    if approx_float and dt.is_floating(t):
        assert len(cl) == len(tl), f"{label}: length {len(cl)} vs {len(tl)}"
        for i, (a, b) in enumerate(zip(cl, tl)):
            if a == b:
                continue
            if isinstance(a, float) and isinstance(b, float):
                assert a == b or abs(a - b) <= 1e-6 * max(1.0, abs(a)), \
                    f"{label} row {i}: cpu={a!r} tpu={b!r}"
            else:
                raise AssertionError(f"{label} row {i}: cpu={a!r} tpu={b!r}")
    else:
        assert cl == tl, (
            f"{label}: mismatch\n cpu={cl[:20]}\n tpu={tl[:20]}"
            + (f"\n (first diff at row "
               f"{next(i for i, (a, b) in enumerate(zip(cl, tl)) if a != b)})"
               if cl != tl and len(cl) == len(tl) else ""))


def assert_tpu_and_cpu_expr_equal(expr, rb: pa.RecordBatch, ansi=False,
                                  approx_float=False, label=""):
    """Evaluate `expr` (with UnresolvedColumn refs) both ways and compare."""
    schema = engine_schema(rb.schema)
    bound = bind_expr(expr, schema)
    ctx = EvalCtx(ansi=ansi)
    cpu = bound.eval_cpu(rb, ctx)
    batch = arrow_to_device(rb, schema)
    tcol = bound.eval_tpu(batch, ctx)
    tpu = device_column_to_arrow(tcol, rb.num_rows)
    assert_columns_equal(cpu, tpu, bound.dtype, approx_float,
                         label or repr(expr))
    return cpu


def _elem_sort_key(v, approx_float):
    """Pairing key for unordered comparison: numeric values compare
    numerically (so -0.0/0.0 and last-ulp approx noise land in the same
    position on both sides), everything else by type+string."""
    if v is None:
        return (3, "", 0.0)
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        x = float(v) if isinstance(v, float) else v
        if isinstance(x, float) and approx_float and x != 0 \
                and math.isfinite(x):
            # quantize to ~6 significant digits so near-equal values tie
            x = round(x, 6 - int(math.floor(math.log10(abs(x)))))
        return (0, "", x)
    return (1, str(type(v)), str(v))


def _sorted_rows(table: pa.Table, types, approx_float):
    cols = [_normalize(c.to_pylist(), t, approx_float)
            for c, t in zip(table.columns, types)]
    rows = list(zip(*cols)) if cols else []
    return sorted(rows, key=lambda r: tuple(
        _elem_sort_key(v, approx_float) for v in r))


def assert_tpu_and_cpu_plan_equal(plan, conf=None, approx_float=False,
                                  ignore_order=False, label=""):
    """Run a physical plan on the TPU path and the CPU oracle path, compare
    full results (the plan-level dual-run harness — SURVEY.md §4.1)."""
    from spark_rapids_tpu.exec.base import (ExecCtx, collect_arrow,
                                            collect_arrow_cpu)
    label = label or plan.describe()
    types = plan.output_schema.types
    tpu = collect_arrow(plan, ExecCtx(conf))
    cpu = collect_arrow_cpu(plan, ExecCtx(conf))
    assert cpu.num_rows == tpu.num_rows, (
        f"{label}: row count cpu={cpu.num_rows} tpu={tpu.num_rows}")
    if ignore_order:
        crows = _sorted_rows(cpu, types, approx_float)
        trows = _sorted_rows(tpu, types, approx_float)
        if approx_float:
            assert len(crows) == len(trows)
            for i, (cr, tr) in enumerate(zip(crows, trows)):
                for a, b in zip(cr, tr):
                    if a == b:
                        continue
                    if isinstance(a, float) and isinstance(b, float) \
                            and abs(a - b) <= 1e-6 * max(1.0, abs(a)):
                        continue
                    raise AssertionError(
                        f"{label} sorted row {i}: cpu={cr!r} tpu={tr!r}")
        else:
            assert crows == trows, (
                f"{label}: mismatch (ignore_order)\n cpu={crows[:10]}\n "
                f"tpu={trows[:10]}")
    else:
        for i, t in enumerate(types):
            assert_columns_equal(cpu.column(i).combine_chunks(),
                                 tpu.column(i).combine_chunks(), t,
                                 approx_float,
                                 f"{label} col {plan.output_schema.names[i]}")
    return cpu
