"""Dual-run equivalence assertions (reference: integration_tests asserts.py
`assert_gpu_and_cpu_are_equal_collect` — SURVEY.md §4.1; built from
capability description, mount empty).

Expression-level: evaluate the same expression tree on the CPU (pyarrow/
numpy, Spark-semantics oracle) and on the TPU path (device batch), compare.
Plan-level helpers are added with the session API.
"""
from __future__ import annotations

import math

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.columnar import arrow_to_device
from spark_rapids_tpu.columnar.arrow_bridge import device_column_to_arrow
from spark_rapids_tpu.expr.base import EvalCtx, bind_expr
from spark_rapids_tpu.columnar.arrow_bridge import engine_schema


def _normalize(values, t: dt.DataType, approx_float=False):
    out = []
    for v in values:
        if v is None:
            out.append(None)
        elif dt.is_floating(t):
            if isinstance(v, float) and math.isnan(v):
                out.append("NaN")
            elif approx_float and isinstance(v, float) and math.isfinite(v):
                out.append(round(v, 10) if abs(v) < 1e100 else v)
            else:
                out.append(v)
        else:
            out.append(v)
    return out


def assert_columns_equal(cpu: pa.Array, tpu: pa.Array, t: dt.DataType,
                         approx_float=False, label=""):
    cl = _normalize(cpu.to_pylist(), t, approx_float)
    tl = _normalize(tpu.to_pylist(), t, approx_float)
    if approx_float and dt.is_floating(t):
        assert len(cl) == len(tl), f"{label}: length {len(cl)} vs {len(tl)}"
        for i, (a, b) in enumerate(zip(cl, tl)):
            if a == b:
                continue
            if isinstance(a, float) and isinstance(b, float):
                assert a == b or abs(a - b) <= 1e-6 * max(1.0, abs(a)), \
                    f"{label} row {i}: cpu={a!r} tpu={b!r}"
            else:
                raise AssertionError(f"{label} row {i}: cpu={a!r} tpu={b!r}")
    else:
        assert cl == tl, (
            f"{label}: mismatch\n cpu={cl[:20]}\n tpu={tl[:20]}"
            + (f"\n (first diff at row "
               f"{next(i for i, (a, b) in enumerate(zip(cl, tl)) if a != b)})"
               if cl != tl and len(cl) == len(tl) else ""))


def assert_tpu_and_cpu_expr_equal(expr, rb: pa.RecordBatch, ansi=False,
                                  approx_float=False, label=""):
    """Evaluate `expr` (with UnresolvedColumn refs) both ways and compare."""
    schema = engine_schema(rb.schema)
    bound = bind_expr(expr, schema)
    ctx = EvalCtx(ansi=ansi)
    cpu = bound.eval_cpu(rb, ctx)
    batch = arrow_to_device(rb, schema)
    tcol = bound.eval_tpu(batch, ctx)
    tpu = device_column_to_arrow(tcol, rb.num_rows)
    assert_columns_equal(cpu, tpu, bound.dtype, approx_float,
                         label or repr(expr))
    return cpu
