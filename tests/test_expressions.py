"""Dual-run tests for the scalar expression library (arithmetic, predicates,
conditionals, cast) — the engine twin of arithmetic_ops_test / cmp_test /
cast_test in the reference's integration suite."""
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.expr import (
    Add, Subtract, Multiply, Divide, IntegralDivide, Remainder, Pmod,
    UnaryMinus, Abs, EqualTo, EqualNullSafe, LessThan, LessThanOrEqual,
    GreaterThan, GreaterThanOrEqual, And, Or, Not, IsNull, IsNotNull,
    IsNaN, In, If, CaseWhen, Coalesce, Least, Greatest, NullIf, Cast,
    Literal, UnresolvedColumn as col)

from asserts import assert_tpu_and_cpu_expr_equal as check
from data_gen import (gen_table, IntegerGen, LongGen, ByteGen, ShortGen,
                      FloatGen, DoubleGen, BooleanGen, StringGen,
                      DecimalGen, DateGen, TimestampGen)


def two_col_table(gen, n=256, seed=7):
    return gen_table([gen, gen], n=n, seed=seed, names=["a", "b"])


INT_GENS = [ByteGen(), ShortGen(), IntegerGen(), LongGen()]
NUM_GENS = INT_GENS + [FloatGen(dt.FLOAT32), FloatGen(dt.FLOAT64)]


@pytest.mark.parametrize("gen", NUM_GENS, ids=lambda g: str(g.dtype))
@pytest.mark.parametrize("op", [Add, Subtract, Multiply])
def test_binary_arithmetic(gen, op):
    rb = two_col_table(gen)
    check(op(col("a"), col("b")), rb)


@pytest.mark.parametrize("gen", [FloatGen(dt.FLOAT32), FloatGen(dt.FLOAT64)],
                         ids=["f32", "f64"])
def test_float_divide(gen):
    rb = two_col_table(gen)
    check(Divide(col("a"), col("b")), rb)


def test_divide_by_zero_null():
    import pyarrow as pa
    rb = pa.record_batch({"a": pa.array([1.0, 2.0, None]),
                          "b": pa.array([0.0, 1.0, 2.0])})
    out = check(Divide(col("a"), col("b")), rb)
    assert out.to_pylist() == [None, 2.0, None]


@pytest.mark.parametrize("gen", INT_GENS, ids=lambda g: str(g.dtype))
def test_integral_divide_remainder(gen):
    rb = two_col_table(gen)
    check(IntegralDivide(col("a"), col("b")), rb)
    check(Remainder(col("a"), col("b")), rb)
    check(Pmod(col("a"), col("b")), rb)


def test_remainder_sign():
    import pyarrow as pa
    rb = pa.record_batch({"a": pa.array([7, -7, 7, -7], pa.int32()),
                          "b": pa.array([3, 3, -3, -3], pa.int32())})
    out = check(Remainder(col("a"), col("b")), rb)
    assert out.to_pylist() == [1, -1, 1, -1]  # Java % semantics
    out = check(Pmod(col("a"), col("b")), rb)
    assert out.to_pylist() == [1, 2, 1, 2]


@pytest.mark.parametrize("gen", NUM_GENS + [DecimalGen()],
                         ids=lambda g: str(g.dtype))
def test_unary(gen):
    rb = two_col_table(gen)
    check(UnaryMinus(col("a")), rb)
    check(Abs(col("a")), rb)


@pytest.mark.parametrize("gen", NUM_GENS + [BooleanGen(), StringGen(),
                                            DateGen(), TimestampGen(),
                                            DecimalGen()],
                         ids=lambda g: str(g.dtype))
@pytest.mark.parametrize("op", [EqualTo, LessThan, LessThanOrEqual,
                                GreaterThan, GreaterThanOrEqual,
                                EqualNullSafe])
def test_comparisons(gen, op):
    rb = two_col_table(gen)
    check(op(col("a"), col("b")), rb)


def test_float_nan_ordering():
    import pyarrow as pa
    nan = float("nan")
    rb = pa.record_batch({"a": pa.array([nan, nan, 1.0, float("inf")]),
                          "b": pa.array([nan, 1.0, nan, nan])})
    assert check(EqualTo(col("a"), col("b")), rb).to_pylist() == \
        [True, False, False, False]
    assert check(GreaterThan(col("a"), col("b")), rb).to_pylist() == \
        [False, True, False, False]
    assert check(LessThan(col("a"), col("b")), rb).to_pylist() == \
        [False, False, True, True]


def test_kleene_logic():
    import pyarrow as pa
    vals = [True, False, None]
    a = [x for x in vals for _ in vals]
    b = vals * 3
    rb = pa.record_batch({"a": pa.array(a), "b": pa.array(b)})
    assert check(And(col("a"), col("b")), rb).to_pylist() == \
        [True, False, None, False, False, False, None, False, None]
    assert check(Or(col("a"), col("b")), rb).to_pylist() == \
        [True, True, True, True, False, None, True, None, None]
    check(Not(col("a")), rb)


@pytest.mark.parametrize("gen", [IntegerGen(), StringGen(), DoubleGen()],
                         ids=["int", "str", "double"])
def test_null_tests(gen):
    rb = two_col_table(gen)
    check(IsNull(col("a")), rb)
    check(IsNotNull(col("a")), rb)


def test_isnan():
    rb = two_col_table(FloatGen(dt.FLOAT64))
    check(IsNaN(col("a")), rb)


def test_in():
    rb = two_col_table(IntegerGen(min_val=0, max_val=10))
    check(In(col("a"), [1, 3, 5]), rb)
    check(In(col("a"), [1, 3, None]), rb)
    srb = two_col_table(StringGen(max_len=3))
    check(In(col("a"), ["a", "Ab", ""]), srb)


@pytest.mark.parametrize("gen", [IntegerGen(), DoubleGen(), StringGen(),
                                 DecimalGen()],
                         ids=["int", "double", "str", "dec"])
def test_if_coalesce(gen):
    rb = gen_table([BooleanGen(), gen, gen], names=["p", "a", "b"])
    check(If(col("p"), col("a"), col("b")), rb)
    check(Coalesce(col("a"), col("b")), rb)
    check(NullIf(col("a"), col("b")), rb)


def test_case_when():
    rb = gen_table([IntegerGen(min_val=-50, max_val=50), IntegerGen()],
                   names=["x", "y"])
    ten = Literal(10, dt.INT32)
    expr = CaseWhen(
        [(LessThan(col("x"), Literal(0, dt.INT32)), UnaryMinus(col("x"))),
         (LessThan(col("x"), ten), Add(col("x"), ten))],
        else_value=col("y"))
    check(expr, rb)


@pytest.mark.parametrize("gen", [IntegerGen(), DoubleGen()],
                         ids=["int", "double"])
def test_least_greatest(gen):
    rb = gen_table([gen, gen, gen], names=["a", "b", "c"])
    check(Least(col("a"), col("b"), col("c")), rb)
    check(Greatest(col("a"), col("b"), col("c")), rb)


# ---- cast matrix ---------------------------------------------------------

NUMERIC_TYPES = [dt.INT8, dt.INT16, dt.INT32, dt.INT64, dt.FLOAT32,
                 dt.FLOAT64]


@pytest.mark.parametrize("to_t", NUMERIC_TYPES, ids=lambda t: str(t))
@pytest.mark.parametrize("gen", NUM_GENS, ids=lambda g: str(g.dtype))
def test_cast_numeric_matrix(gen, to_t):
    rb = two_col_table(gen)
    check(Cast(col("a"), to_t), rb)


@pytest.mark.parametrize("gen", NUM_GENS, ids=lambda g: str(g.dtype))
def test_cast_numeric_to_bool(gen):
    rb = two_col_table(gen)
    check(Cast(col("a"), dt.BOOL), rb)


def test_cast_bool_numeric():
    rb = two_col_table(BooleanGen())
    for t in NUMERIC_TYPES:
        check(Cast(col("a"), t), rb)


def test_cast_int_to_string():
    for gen in INT_GENS:
        rb = two_col_table(gen)
        check(Cast(col("a"), dt.STRING), rb)


def test_cast_bool_to_string():
    check(Cast(col("a"), dt.STRING), two_col_table(BooleanGen()))


def test_cast_date_to_string():
    check(Cast(col("a"), dt.STRING), two_col_table(DateGen()))


def test_cast_decimal_to_string():
    for p, s in [(10, 2), (18, 0), (7, 7), (5, 1)]:
        rb = two_col_table(DecimalGen(p, s))
        check(Cast(col("a"), dt.STRING), rb)


def test_cast_decimal_conversions():
    rb = two_col_table(DecimalGen(10, 2))
    check(Cast(col("a"), dt.DecimalType(12, 4)), rb)
    check(Cast(col("a"), dt.DecimalType(8, 0)), rb)
    check(Cast(col("a"), dt.INT64), rb)
    check(Cast(col("a"), dt.FLOAT64), rb)
    rb2 = two_col_table(IntegerGen(min_val=-10**6, max_val=10**6))
    check(Cast(col("a"), dt.DecimalType(12, 2)), rb2)


def test_cast_date_timestamp():
    rb = two_col_table(DateGen())
    check(Cast(col("a"), dt.TIMESTAMP), rb)
    rb2 = two_col_table(TimestampGen())
    check(Cast(col("a"), dt.DATE), rb2)
    check(Cast(col("a"), dt.INT64), rb2)


def test_cast_string_to_numeric_cpu():
    """String parsing runs on host (fallback per tpu_supported)."""
    import pyarrow as pa
    rb = pa.record_batch({"a": pa.array(
        ["1", " 42 ", "-7", "2.5", "abc", "", None, "99999999999999999999",
         "NaN", "Infinity", "-Infinity", "1e3"])})
    from spark_rapids_tpu.expr.base import bind_expr, EvalCtx
    from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
    bound = bind_expr(Cast(col("a"), dt.INT32), engine_schema(rb.schema))
    assert bound.tpu_supported() is not None  # planner will fall back
    out = bound.eval_cpu(rb, EvalCtx())
    assert out.to_pylist() == [1, 42, -7, 2, None, None, None, None,
                               None, None, None, None]
    d = bind_expr(Cast(col("a"), dt.FLOAT64), engine_schema(rb.schema))
    out = d.eval_cpu(rb, EvalCtx())
    lst = out.to_pylist()
    assert lst[0] == 1.0 and lst[3] == 2.5 and lst[4] is None
    assert str(lst[8]) == "nan" and lst[9] == float("inf")
    assert lst[11] == 1000.0


def test_cast_float_to_string_cpu():
    import pyarrow as pa
    rb = pa.record_batch({"a": pa.array(
        [1.0, -0.5, float("nan"), float("inf"), None, 123456.0])})
    from spark_rapids_tpu.expr.base import bind_expr, EvalCtx
    from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
    bound = bind_expr(Cast(col("a"), dt.STRING), engine_schema(rb.schema))
    out = bound.eval_cpu(rb, EvalCtx())
    assert out.to_pylist() == ["1.0", "-0.5", "NaN", "Infinity", None,
                               "123456.0"]


def test_ansi_div_by_zero_raises():
    import pyarrow as pa
    from spark_rapids_tpu.expr.base import bind_expr, EvalCtx, ExprError
    from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
    rb = pa.record_batch({"a": pa.array([1.0]), "b": pa.array([0.0])})
    bound = bind_expr(Divide(col("a"), col("b")), engine_schema(rb.schema))
    with pytest.raises(ExprError):
        bound.eval_cpu(rb, EvalCtx(ansi=True))


# ---- string kernels ------------------------------------------------------

def test_string_comparisons_detail():
    import pyarrow as pa
    rb = pa.record_batch({
        "a": pa.array(["apple", "b", "", "same", "prefix", "unié"]),
        "b": pa.array(["apricot", "a", "x", "same", "prefixlonger", "uni"])})
    assert check(LessThan(col("a"), col("b")), rb).to_pylist() == \
        [True, False, True, False, True, False]
    assert check(EqualTo(col("a"), col("b")), rb).to_pylist() == \
        [False, False, False, True, False, False]


def test_long_string_comparison():
    import pyarrow as pa
    base = "x" * 200  # crosses several compare windows
    rb = pa.record_batch({"a": pa.array([base + "a", base, base]),
                          "b": pa.array([base + "b", base, base + "q"])})
    assert check(LessThan(col("a"), col("b")), rb).to_pylist() == \
        [True, False, True]


# --- hash expressions -------------------------------------------------------

def test_xxhash64_matches_reference_library():
    """Device & oracle string hashing vs the C xxhash library (the
    external truth for XXH64 with seed 42, which Spark's XxHash64 on
    strings follows)."""
    xxhash = pytest.importorskip("xxhash")
    import numpy as np
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    from spark_rapids_tpu.ops.hash import (xxhash64_columns_device,
                                           xxhash64_columns_numpy)
    import pyarrow as pa
    vals = ["", "a", "abc", "hello world", "x" * 31, "y" * 32,
            "z" * 100, "日本語テキスト", "padding-1234567", None]
    rb = pa.record_batch({"s": pa.array(vals)})
    types = [dt.STRING]
    want = []
    for v in vals:
        if v is None:
            want.append(42)  # null keeps the running seed
        else:
            h = xxhash.xxh64(v.encode(), seed=42).intdigest()
            want.append(h - (1 << 64) if h >= (1 << 63) else h)
    host = xxhash64_columns_numpy([rb.column(0)], types, len(vals))
    assert list(host) == want
    dev = np.asarray(xxhash64_columns_device(
        arrow_to_device(rb).columns))[:len(vals)]
    assert list(dev) == want


@pytest.mark.parametrize("gen", [IntegerGen(), LongGen(), BooleanGen(),
                                 FloatGen(dt.FLOAT32), DoubleGen(),
                                 DateGen(), TimestampGen(),
                                 DecimalGen(precision=12),
                                 StringGen(max_len=40)],
                         ids=lambda g: g.dtype.simple_string())
def test_xxhash64_device_matches_host(gen):
    import numpy as np
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    from spark_rapids_tpu.ops.hash import (xxhash64_columns_device,
                                           xxhash64_columns_numpy)
    rb = gen_table([gen], 200, seed=17)
    host = xxhash64_columns_numpy([rb.column(0)], [gen.dtype],
                                  rb.num_rows)
    dev = np.asarray(xxhash64_columns_device(
        arrow_to_device(rb).columns))[:rb.num_rows]
    assert (host == dev).all(), \
        f"first diff at {np.nonzero(host != dev)[0][:5]}"


def test_hash_expressions_dual_run():
    from spark_rapids_tpu.expr import Murmur3Hash, XxHash64
    rb = gen_table([IntegerGen(null_frac=0.2), StringGen(), DoubleGen()],
                   150, seed=9)
    for expr in (Murmur3Hash(col("c0"), col("c1"), col("c2")),
                 XxHash64(col("c0"), col("c1"), col("c2"))):
        check(expr, rb)
