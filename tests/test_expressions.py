"""Dual-run tests for the scalar expression library (arithmetic, predicates,
conditionals, cast) — the engine twin of arithmetic_ops_test / cmp_test /
cast_test in the reference's integration suite."""
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.expr import (
    Add, Subtract, Multiply, Divide, IntegralDivide, Remainder, Pmod,
    UnaryMinus, Abs, EqualTo, EqualNullSafe, LessThan, LessThanOrEqual,
    GreaterThan, GreaterThanOrEqual, And, Or, Not, IsNull, IsNotNull,
    IsNaN, In, If, CaseWhen, Coalesce, Least, Greatest, NullIf, Cast,
    Literal, UnresolvedColumn as col)

from asserts import assert_tpu_and_cpu_expr_equal as check
from data_gen import (gen_table, IntegerGen, LongGen, ByteGen, ShortGen,
                      FloatGen, DoubleGen, BooleanGen, StringGen,
                      DecimalGen, DateGen, TimestampGen)


def two_col_table(gen, n=256, seed=7):
    return gen_table([gen, gen], n=n, seed=seed, names=["a", "b"])


INT_GENS = [ByteGen(), ShortGen(), IntegerGen(), LongGen()]
NUM_GENS = INT_GENS + [FloatGen(dt.FLOAT32), FloatGen(dt.FLOAT64)]


@pytest.mark.parametrize("gen", NUM_GENS, ids=lambda g: str(g.dtype))
@pytest.mark.parametrize("op", [Add, Subtract, Multiply])
def test_binary_arithmetic(gen, op):
    rb = two_col_table(gen)
    check(op(col("a"), col("b")), rb)


@pytest.mark.parametrize("gen", [FloatGen(dt.FLOAT32), FloatGen(dt.FLOAT64)],
                         ids=["f32", "f64"])
def test_float_divide(gen):
    rb = two_col_table(gen)
    check(Divide(col("a"), col("b")), rb)


def test_divide_by_zero_null():
    import pyarrow as pa
    rb = pa.record_batch({"a": pa.array([1.0, 2.0, None]),
                          "b": pa.array([0.0, 1.0, 2.0])})
    out = check(Divide(col("a"), col("b")), rb)
    assert out.to_pylist() == [None, 2.0, None]


@pytest.mark.parametrize("gen", INT_GENS, ids=lambda g: str(g.dtype))
def test_integral_divide_remainder(gen):
    rb = two_col_table(gen)
    check(IntegralDivide(col("a"), col("b")), rb)
    check(Remainder(col("a"), col("b")), rb)
    check(Pmod(col("a"), col("b")), rb)


def test_remainder_sign():
    import pyarrow as pa
    rb = pa.record_batch({"a": pa.array([7, -7, 7, -7], pa.int32()),
                          "b": pa.array([3, 3, -3, -3], pa.int32())})
    out = check(Remainder(col("a"), col("b")), rb)
    assert out.to_pylist() == [1, -1, 1, -1]  # Java % semantics
    out = check(Pmod(col("a"), col("b")), rb)
    assert out.to_pylist() == [1, 2, 1, 2]


@pytest.mark.parametrize("gen", NUM_GENS + [DecimalGen()],
                         ids=lambda g: str(g.dtype))
def test_unary(gen):
    rb = two_col_table(gen)
    check(UnaryMinus(col("a")), rb)
    check(Abs(col("a")), rb)


@pytest.mark.parametrize("gen", NUM_GENS + [BooleanGen(), StringGen(),
                                            DateGen(), TimestampGen(),
                                            DecimalGen()],
                         ids=lambda g: str(g.dtype))
@pytest.mark.parametrize("op", [EqualTo, LessThan, LessThanOrEqual,
                                GreaterThan, GreaterThanOrEqual,
                                EqualNullSafe])
def test_comparisons(gen, op):
    rb = two_col_table(gen)
    check(op(col("a"), col("b")), rb)


def test_float_nan_ordering():
    import pyarrow as pa
    nan = float("nan")
    rb = pa.record_batch({"a": pa.array([nan, nan, 1.0, float("inf")]),
                          "b": pa.array([nan, 1.0, nan, nan])})
    assert check(EqualTo(col("a"), col("b")), rb).to_pylist() == \
        [True, False, False, False]
    assert check(GreaterThan(col("a"), col("b")), rb).to_pylist() == \
        [False, True, False, False]
    assert check(LessThan(col("a"), col("b")), rb).to_pylist() == \
        [False, False, True, True]


def test_kleene_logic():
    import pyarrow as pa
    vals = [True, False, None]
    a = [x for x in vals for _ in vals]
    b = vals * 3
    rb = pa.record_batch({"a": pa.array(a), "b": pa.array(b)})
    assert check(And(col("a"), col("b")), rb).to_pylist() == \
        [True, False, None, False, False, False, None, False, None]
    assert check(Or(col("a"), col("b")), rb).to_pylist() == \
        [True, True, True, True, False, None, True, None, None]
    check(Not(col("a")), rb)


@pytest.mark.parametrize("gen", [IntegerGen(), StringGen(), DoubleGen()],
                         ids=["int", "str", "double"])
def test_null_tests(gen):
    rb = two_col_table(gen)
    check(IsNull(col("a")), rb)
    check(IsNotNull(col("a")), rb)


def test_isnan():
    rb = two_col_table(FloatGen(dt.FLOAT64))
    check(IsNaN(col("a")), rb)


def test_in():
    rb = two_col_table(IntegerGen(min_val=0, max_val=10))
    check(In(col("a"), [1, 3, 5]), rb)
    check(In(col("a"), [1, 3, None]), rb)
    srb = two_col_table(StringGen(max_len=3))
    check(In(col("a"), ["a", "Ab", ""]), srb)


@pytest.mark.parametrize("gen", [IntegerGen(), DoubleGen(), StringGen(),
                                 DecimalGen()],
                         ids=["int", "double", "str", "dec"])
def test_if_coalesce(gen):
    rb = gen_table([BooleanGen(), gen, gen], names=["p", "a", "b"])
    check(If(col("p"), col("a"), col("b")), rb)
    check(Coalesce(col("a"), col("b")), rb)
    check(NullIf(col("a"), col("b")), rb)


def test_case_when():
    rb = gen_table([IntegerGen(min_val=-50, max_val=50), IntegerGen()],
                   names=["x", "y"])
    ten = Literal(10, dt.INT32)
    expr = CaseWhen(
        [(LessThan(col("x"), Literal(0, dt.INT32)), UnaryMinus(col("x"))),
         (LessThan(col("x"), ten), Add(col("x"), ten))],
        else_value=col("y"))
    check(expr, rb)


@pytest.mark.parametrize("gen", [IntegerGen(), DoubleGen()],
                         ids=["int", "double"])
def test_least_greatest(gen):
    rb = gen_table([gen, gen, gen], names=["a", "b", "c"])
    check(Least(col("a"), col("b"), col("c")), rb)
    check(Greatest(col("a"), col("b"), col("c")), rb)


# ---- cast matrix ---------------------------------------------------------

NUMERIC_TYPES = [dt.INT8, dt.INT16, dt.INT32, dt.INT64, dt.FLOAT32,
                 dt.FLOAT64]


@pytest.mark.parametrize("to_t", NUMERIC_TYPES, ids=lambda t: str(t))
@pytest.mark.parametrize("gen", NUM_GENS, ids=lambda g: str(g.dtype))
def test_cast_numeric_matrix(gen, to_t):
    rb = two_col_table(gen)
    check(Cast(col("a"), to_t), rb)


@pytest.mark.parametrize("gen", NUM_GENS, ids=lambda g: str(g.dtype))
def test_cast_numeric_to_bool(gen):
    rb = two_col_table(gen)
    check(Cast(col("a"), dt.BOOL), rb)


def test_cast_bool_numeric():
    rb = two_col_table(BooleanGen())
    for t in NUMERIC_TYPES:
        check(Cast(col("a"), t), rb)


def test_cast_int_to_string():
    for gen in INT_GENS:
        rb = two_col_table(gen)
        check(Cast(col("a"), dt.STRING), rb)


def test_cast_bool_to_string():
    check(Cast(col("a"), dt.STRING), two_col_table(BooleanGen()))


def test_cast_date_to_string():
    check(Cast(col("a"), dt.STRING), two_col_table(DateGen()))


def test_cast_decimal_to_string():
    for p, s in [(10, 2), (18, 0), (7, 7), (5, 1)]:
        rb = two_col_table(DecimalGen(p, s))
        check(Cast(col("a"), dt.STRING), rb)


def test_cast_decimal_conversions():
    rb = two_col_table(DecimalGen(10, 2))
    check(Cast(col("a"), dt.DecimalType(12, 4)), rb)
    check(Cast(col("a"), dt.DecimalType(8, 0)), rb)
    check(Cast(col("a"), dt.INT64), rb)
    check(Cast(col("a"), dt.FLOAT64), rb)
    rb2 = two_col_table(IntegerGen(min_val=-10**6, max_val=10**6))
    check(Cast(col("a"), dt.DecimalType(12, 2)), rb2)


def test_cast_date_timestamp():
    rb = two_col_table(DateGen())
    check(Cast(col("a"), dt.TIMESTAMP), rb)
    rb2 = two_col_table(TimestampGen())
    check(Cast(col("a"), dt.DATE), rb2)
    check(Cast(col("a"), dt.INT64), rb2)


_STR_NUM_CORPUS = [
    "1", " 42 ", "-7", "+9", "2.5", "2.", ".5", "abc", "", " ", None,
    "99999999999999999999", "9223372036854775807", "-9223372036854775808",
    "9223372036854775808", "-9223372036854775809", "000123", "-000",
    "1 2", "--1", "+", "-", "1.2.3", "127", "-128", "128", "32767",
    "-32768", "32768", "2147483647", "-2147483648", "2147483648",
    "\t13\n", "1_0",
]


def test_cast_string_to_int_device_matrix():
    """string -> integral parses ON DEVICE (round 5 — VERDICT r4 weak
    #4); whole edge corpus dual-runs against the host parser."""
    import pyarrow as pa
    rb = pa.record_batch({"a": pa.array(_STR_NUM_CORPUS, pa.string())})
    from spark_rapids_tpu.expr.base import bind_expr
    from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
    for t in (dt.INT8, dt.INT16, dt.INT32, dt.INT64):
        bound = bind_expr(Cast(col("a"), t), engine_schema(rb.schema))
        assert bound.tpu_supported() is None, t  # on device now
        check(Cast(col("a"), t), rb)


def test_cast_string_to_float_device():
    import pyarrow as pa
    vals = ["1", "2.5", "-0.125", ".5", "5.", "1e3", "1.5E-3", "-2e+2",
            "NaN", "nan", "Infinity", "-Infinity", "+inf", "-inf",
            "abc", "", None, "1e", "e5", "0e999", "1e999", "-1e999",
            " 3.25 ", "1_0", "12345678901234", "+.75",
            # >19 combined mantissa digits must not overflow the device
            # accumulator (code-review r5: int and fraction runs now
            # scale separately in float64)
            "1234567890123456789.123", "1.0000000000000000000005",
            "0.00000000000000000000075"]
    rb = pa.record_batch({"a": pa.array(vals, pa.string())})
    for t in (dt.FLOAT32, dt.FLOAT64):
        from spark_rapids_tpu.expr.base import bind_expr
        from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
        bound = bind_expr(Cast(col("a"), t), engine_schema(rb.schema))
        assert bound.tpu_supported() is None, t
        check(Cast(col("a"), t), rb)


def test_cast_string_to_bool_date_device():
    import pyarrow as pa
    bvals = ["t", "TRUE", "y", "Yes", "1", "f", "false", "N", "no", "0",
             " true ", "tru", "2", "", None]
    rb = pa.record_batch({"a": pa.array(bvals, pa.string())})
    check(Cast(col("a"), dt.BOOL), rb)
    dvals = ["2021-03-05", "2021-3-5", "1999-12-31", "2020-02-29",
             "2021-02-29", "2021-02-30", "2021-13-01", "2021-00-10",
             "2021-01-00", "2021-1-1T12:00:00", "2021-1-1 x", "21-01-01",
             "2021-01-1x", "", None, " 2021-06-15 ", "0001-01-01",
             "9999-12-31"]
    rb = pa.record_batch({"a": pa.array(dvals, pa.string())})
    from spark_rapids_tpu.expr.base import bind_expr
    from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
    bound = bind_expr(Cast(col("a"), dt.DATE), engine_schema(rb.schema))
    assert bound.tpu_supported() is None
    check(Cast(col("a"), dt.DATE), rb)


def test_cast_string_to_int_ansi_raises_on_device():
    import pyarrow as pa
    from spark_rapids_tpu.expr.base import (ExprError, EvalCtx,
                                            bind_expr)
    from spark_rapids_tpu.columnar.arrow_bridge import (arrow_to_device,
                                                        engine_schema)
    rb = pa.record_batch({"a": pa.array(["1", "oops"], pa.string())})
    schema = engine_schema(rb.schema)
    bound = bind_expr(Cast(col("a"), dt.INT32), schema)
    batch = arrow_to_device(rb, schema)
    with pytest.raises(ExprError):
        bound.eval_tpu(batch, EvalCtx(ansi=True))


def test_cast_timestamp_to_string_device():
    import pyarrow as pa
    import datetime as dtm
    utc = dtm.timezone.utc
    vals = [dtm.datetime(2021, 3, 5, 12, 34, 56, tzinfo=utc),
            dtm.datetime(2021, 3, 5, 0, 0, 0, tzinfo=utc),
            dtm.datetime(1999, 12, 31, 23, 59, 59, 123456, tzinfo=utc),
            dtm.datetime(2000, 1, 1, 1, 2, 3, 100000, tzinfo=utc),
            dtm.datetime(1970, 1, 1, tzinfo=utc),
            dtm.datetime(1960, 6, 1, 6, 7, 8, 900, tzinfo=utc),
            None]
    rb = pa.record_batch({"a": pa.array(vals, pa.timestamp("us",
                                                           tz="UTC"))})
    from spark_rapids_tpu.expr.base import bind_expr
    from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
    bound = bind_expr(Cast(col("a"), dt.STRING),
                      engine_schema(rb.schema))
    assert bound.tpu_supported() is None  # on device now
    out = check(Cast(col("a"), dt.STRING), rb)
    assert out.to_pylist()[0] == "2021-03-05 12:34:56"
    assert out.to_pylist()[2] == "1999-12-31 23:59:59.123456"
    assert out.to_pylist()[3] == "2000-01-01 01:02:03.1"


def test_cast_float_to_string_cpu():
    import pyarrow as pa
    rb = pa.record_batch({"a": pa.array(
        [1.0, -0.5, float("nan"), float("inf"), None, 123456.0])})
    from spark_rapids_tpu.expr.base import bind_expr, EvalCtx
    from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
    bound = bind_expr(Cast(col("a"), dt.STRING), engine_schema(rb.schema))
    out = bound.eval_cpu(rb, EvalCtx())
    assert out.to_pylist() == ["1.0", "-0.5", "NaN", "Infinity", None,
                               "123456.0"]


def test_ansi_div_by_zero_raises():
    import pyarrow as pa
    from spark_rapids_tpu.expr.base import bind_expr, EvalCtx, ExprError
    from spark_rapids_tpu.columnar.arrow_bridge import engine_schema
    rb = pa.record_batch({"a": pa.array([1.0]), "b": pa.array([0.0])})
    bound = bind_expr(Divide(col("a"), col("b")), engine_schema(rb.schema))
    with pytest.raises(ExprError):
        bound.eval_cpu(rb, EvalCtx(ansi=True))


# ---- string kernels ------------------------------------------------------

def test_string_comparisons_detail():
    import pyarrow as pa
    rb = pa.record_batch({
        "a": pa.array(["apple", "b", "", "same", "prefix", "unié"]),
        "b": pa.array(["apricot", "a", "x", "same", "prefixlonger", "uni"])})
    assert check(LessThan(col("a"), col("b")), rb).to_pylist() == \
        [True, False, True, False, True, False]
    assert check(EqualTo(col("a"), col("b")), rb).to_pylist() == \
        [False, False, False, True, False, False]


def test_long_string_comparison():
    import pyarrow as pa
    base = "x" * 200  # crosses several compare windows
    rb = pa.record_batch({"a": pa.array([base + "a", base, base]),
                          "b": pa.array([base + "b", base, base + "q"])})
    assert check(LessThan(col("a"), col("b")), rb).to_pylist() == \
        [True, False, True]


# --- hash expressions -------------------------------------------------------

def test_xxhash64_matches_reference_library():
    """Device & oracle string hashing vs the C xxhash library (the
    external truth for XXH64 with seed 42, which Spark's XxHash64 on
    strings follows)."""
    xxhash = pytest.importorskip("xxhash")
    import numpy as np
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    from spark_rapids_tpu.ops.hash import (xxhash64_columns_device,
                                           xxhash64_columns_numpy)
    import pyarrow as pa
    vals = ["", "a", "abc", "hello world", "x" * 31, "y" * 32,
            "z" * 100, "日本語テキスト", "padding-1234567", None]
    rb = pa.record_batch({"s": pa.array(vals)})
    types = [dt.STRING]
    want = []
    for v in vals:
        if v is None:
            want.append(42)  # null keeps the running seed
        else:
            h = xxhash.xxh64(v.encode(), seed=42).intdigest()
            want.append(h - (1 << 64) if h >= (1 << 63) else h)
    host = xxhash64_columns_numpy([rb.column(0)], types, len(vals))
    assert list(host) == want
    dev = np.asarray(xxhash64_columns_device(
        arrow_to_device(rb).columns))[:len(vals)]
    assert list(dev) == want


@pytest.mark.parametrize("gen", [IntegerGen(), LongGen(), BooleanGen(),
                                 FloatGen(dt.FLOAT32), DoubleGen(),
                                 DateGen(), TimestampGen(),
                                 DecimalGen(precision=12),
                                 StringGen(max_len=40)],
                         ids=lambda g: g.dtype.simple_string())
def test_xxhash64_device_matches_host(gen):
    import numpy as np
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    from spark_rapids_tpu.ops.hash import (xxhash64_columns_device,
                                           xxhash64_columns_numpy)
    rb = gen_table([gen], 200, seed=17)
    host = xxhash64_columns_numpy([rb.column(0)], [gen.dtype],
                                  rb.num_rows)
    dev = np.asarray(xxhash64_columns_device(
        arrow_to_device(rb).columns))[:rb.num_rows]
    assert (host == dev).all(), \
        f"first diff at {np.nonzero(host != dev)[0][:5]}"


def test_hash_expressions_dual_run():
    from spark_rapids_tpu.expr import Murmur3Hash, XxHash64
    rb = gen_table([IntegerGen(null_frac=0.2), StringGen(), DoubleGen()],
                   150, seed=9)
    for expr in (Murmur3Hash(col("c0"), col("c1"), col("c2")),
                 XxHash64(col("c0"), col("c1"), col("c2"))):
        check(expr, rb)


def test_cast_string_ansi_filtered_rows_planner_path():
    """ANSI string casts route to HOST at plan time (the raise-on-first-
    invalid check cannot sync inside a traced program); rows a filter
    removed must not trip the check, and the result is right
    (code-review r5 finding)."""
    import pyarrow as pa
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec.base import HostBatchSourceExec
    from spark_rapids_tpu.exec.basic import TpuFilterExec, TpuProjectExec
    from spark_rapids_tpu.expr import Alias
    from spark_rapids_tpu.expr.strings import RegExpLike
    from spark_rapids_tpu.planner import TpuOverrides
    rb = pa.record_batch({"s": pa.array(["12", "abc", "7", "x9y"])})
    src = HostBatchSourceExec([rb])
    filt = TpuFilterExec(RegExpLike(col("s"), "^[0-9]+$"), src)
    proj = TpuProjectExec([Alias(Cast(col("s"), dt.INT32), "i")], filt)
    conf = RapidsConf({"spark.sql.ansi.enabled": "true"})
    pp = TpuOverrides(conf).apply(proj)
    assert pp.fallback_nodes(), "ANSI string cast must plan to host"
    out = pp.collect()
    assert out.column("i").to_pylist() == [12, 7]
    # non-ANSI: same plan stays fully on device
    pp2 = TpuOverrides(RapidsConf()).apply(proj)
    assert not pp2.fallback_nodes()
    assert pp2.collect().column("i").to_pylist() == [12, 7]
