"""Query lifecycle tests (spark_rapids_tpu/lifecycle.py): classified
cancellation (user / deadline / budget / admission), fair per-tenant
admission, the cancel-aware upload pipeline, the memory-pressure
degradation ladder, the query-scoped chaos modes — and the
process-cluster cancel paths, asserting zero ledger/slot leakage after
every cancel. The cluster tests run in CI step 12's
lockwatch-enabled file set, so every path here is also a lock-order
witness."""
import json
import os
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.lifecycle import (CancellationToken,
                                        FairAdmissionController,
                                        QueryCancelled, QueryContext,
                                        read_cancel_marker)
from spark_rapids_tpu.memory import DeviceMemoryManager
from spark_rapids_tpu.pipeline import pipelined_map
from spark_rapids_tpu.session import TpuSession


# --- token ------------------------------------------------------------------

def test_token_first_cancel_wins_and_classifies():
    tok = CancellationToken("q1")
    assert not tok.cancelled and tok.poll() is None
    assert tok.cancel("deadline", "too slow")
    assert not tok.cancel("user", "late loser")  # first wins
    assert tok.reason == "deadline" and tok.detail == "too slow"
    with pytest.raises(QueryCancelled) as ei:
        tok.check()
    assert ei.value.reason == "deadline" and ei.value.query_id == "q1"
    with pytest.raises(ValueError, match="unknown cancel reason"):
        tok.cancel("meteor")


def test_token_deadline_fires():
    tok = CancellationToken("q2", deadline_s=0.01)
    time.sleep(0.03)
    assert tok.poll() == "deadline"
    assert tok.cancelled


def test_cancel_marker_roundtrip(tmp_path):
    p = str(tmp_path / "q.cancel")
    with open(p, "w") as f:
        f.write("budget over the line")
    assert read_cancel_marker(p) == ("budget", "over the line")
    with open(p, "w") as f:
        f.write("garbage-content")
    r, _ = read_cancel_marker(p)
    assert r == "user"  # foreign content degrades, never crashes
    tok = CancellationToken("q3", cancel_file=p)
    with open(p, "w") as f:
        f.write("user bye")
    tok._next_poll = 0.0
    assert tok.poll() == "user"


# --- fair admission ---------------------------------------------------------

def _qc(conf=None, **kw):
    return QueryContext(RapidsConf(conf or {}), **kw)


def test_admission_weighted_grant_order():
    """2 slots, tenants a(weight 3) / b(1) each holding one; on a's
    release the freed slot must go to the waiting a (score 0/3) over
    the earlier-queued b (score 1/1)."""
    ctl = FairAdmissionController(2, RapidsConf({
        "spark.rapids.query.admission.weights": "a:3,b:1"}))
    sa = ctl.slot(_qc(tenant="a"))
    sb = ctl.slot(_qc(tenant="b"))
    got = []

    def waiter(tenant, tag):
        with ctl.slot(_qc(tenant=tenant)):
            got.append(tag)
            time.sleep(0.2)

    tb = threading.Thread(target=waiter, args=("b", "b2"))
    tb.start()
    time.sleep(0.05)  # b2 queues first
    ta = threading.Thread(target=waiter, args=("a", "a2"))
    ta.start()
    time.sleep(0.05)
    assert ctl.snapshot()["queued"] == {"b": 1, "a": 1}
    sa.release()  # freed slot: a2 (0/3) beats b2 (1/1) despite FIFO age
    time.sleep(0.1)
    assert got == ["a2"]
    sb.release()
    ta.join()
    tb.join()
    assert got == ["a2", "b2"]
    assert ctl.snapshot()["in_use"] == 0 and not ctl.snapshot()["tenants"]


def test_admission_queue_full_rejects_classified():
    ctl = FairAdmissionController(1, RapidsConf({
        "spark.rapids.query.admission.maxQueuedPerTenant": "1"}))
    held = ctl.slot(_qc(tenant="t"))
    parked = threading.Thread(
        target=lambda: ctl.slot(_qc(tenant="t")).release())
    parked.start()
    time.sleep(0.05)
    with pytest.raises(QueryCancelled) as ei:
        ctl.slot(_qc(tenant="t"))
    assert ei.value.reason == "admission"
    assert "queue full" in ei.value.detail
    held.release()
    parked.join()


def test_admission_timeout_rejects_classified():
    ctl = FairAdmissionController(1, RapidsConf({
        "spark.rapids.query.admission.timeout": "0.1"}))
    held = ctl.slot(None)
    qx = _qc(tenant="t")
    t0 = time.monotonic()
    with pytest.raises(QueryCancelled) as ei:
        ctl.slot(qx)
    assert ei.value.reason == "admission"
    assert time.monotonic() - t0 < 5.0
    assert qx.token.reason == "admission"  # the token was classified
    held.release()
    assert ctl.snapshot()["in_use"] == 0
    assert not ctl.snapshot()["queued"]  # the loser left no ticket


def test_admission_cancel_while_queued():
    ctl = FairAdmissionController(1, RapidsConf())
    held = ctl.slot(None)
    qx = _qc(tenant="t")
    threading.Timer(0.05, qx.cancel).start()
    with pytest.raises(QueryCancelled) as ei:
        ctl.slot(qx)
    assert ei.value.reason == "user"
    held.release()
    assert ctl.snapshot()["in_use"] == 0


def test_admission_slow_admission_chaos_trips_timeout():
    """slow_admission chaos keys on the QUERY id and delays admission
    deterministically past the queue-time deadline."""
    ctl = FairAdmissionController(2, RapidsConf({
        "spark.rapids.query.admission.timeout": "0.1",
        "spark.rapids.tpu.test.injectFaults": "slow_admission:qslow:*:0.3",
    }))
    with pytest.raises(QueryCancelled) as ei:
        ctl.slot(_qc(query_id="qslow"))
    assert ei.value.reason == "admission"
    # non-matching query ids admit instantly
    ctl.slot(_qc(query_id="qfast")).release()
    assert ctl.snapshot()["in_use"] == 0


def test_exclusive_cleared_at_query_end_even_without_slot():
    """width-1 exclusivity set by a slotless (CPU-island) subtree must
    not outlive its query — clear_exclusive resumes grants."""
    ctl = FairAdmissionController(2, RapidsConf())
    qx = _qc(query_id="qdeg")
    ctl.await_exclusive(qx, timeout=0.01)  # in_use==0: returns at once
    assert ctl.snapshot()["exclusive"] == "qdeg"
    ctl.clear_exclusive("other-query")  # someone else's end: no-op
    assert ctl.snapshot()["exclusive"] == "qdeg"
    ctl.clear_exclusive("qdeg")
    assert ctl.snapshot()["exclusive"] is None
    ctl.slot(None).release()  # grants flow again


# --- cancel-aware pipeline --------------------------------------------------

def test_pipelined_map_cancels_at_consumer_and_unparks_feeder():
    tok = CancellationToken("qp")
    fed = []

    def items():
        for i in range(100):
            fed.append(i)
            yield i

    gen = pipelined_map(lambda x: x, items(), threads=1, window=2,
                        token=tok)
    assert next(gen) == 0
    tok.cancel("user", "enough")
    with pytest.raises(QueryCancelled):
        list(gen)
    time.sleep(0.2)  # feeder must die promptly, not fill the window
    assert len(fed) < 100


def test_pipelined_map_serial_path_checks_token():
    tok = CancellationToken("qs")
    tok.cancel("user")
    with pytest.raises(QueryCancelled):
        list(pipelined_map(lambda x: x, range(5), threads=0, token=tok))


# --- local query paths ------------------------------------------------------

def _frame(session, nbatches=40, rows=200):
    tbl = pa.Table.from_batches([
        pa.RecordBatch.from_arrays(
            [pa.array(np.arange(rows, dtype=np.int64))], names=["a"])
        for _ in range(nbatches)])
    return session.create_dataframe(tbl)


def test_local_user_cancel_releases_everything():
    s = TpuSession()
    qx = s.query_context()
    mm = DeviceMemoryManager.shared(s.conf)
    base_bytes = mm.device_bytes
    threading.Timer(0.05, qx.cancel).start()
    with pytest.raises(QueryCancelled) as ei:
        for _ in range(300):  # keep running queries until the cancel
            _frame(s).select("a").collect(qx)
    assert ei.value.reason == "user"
    assert mm.device_bytes == base_bytes  # zero ledger leakage
    snap = mm.admission.snapshot()
    assert snap["in_use"] == 0 and not snap["queued"]  # zero slot leakage


def test_local_deadline_cancel_classified_with_event_log(tmp_path):
    log_dir = str(tmp_path / "events")
    s = TpuSession({"spark.rapids.query.deadline": "0.0001",
                    "spark.rapids.eventLog.dir": log_dir})
    time.sleep(0.01)
    with pytest.raises(QueryCancelled) as ei:
        _frame(s).select("a").collect()
    assert ei.value.reason == "deadline"
    evs = [json.loads(line)
           for n in os.listdir(log_dir)
           for line in open(os.path.join(log_dir, n))]
    cancels = [e for e in evs if e.get("type") == "query_cancelled"]
    assert len(cancels) == 1 and cancels[0]["reason"] == "deadline"


def test_budget_action_cancel_classifies():
    s = TpuSession({"spark.rapids.query.memoryBudgetBytes": "1",
                    "spark.rapids.query.memoryBudget.action": "cancel"})
    with pytest.raises(QueryCancelled) as ei:
        _frame(s, nbatches=2).select("a").collect()
    assert ei.value.reason == "budget"
    assert "budget exceeded" in ei.value.detail


def test_budget_degrade_exhausts_to_budget_cancel():
    """action=degrade: the unsatisfiable budget walks the ladder and
    terminates as QueryCancelled(budget), not CPU fallback (the user
    asked for the bound, not a slower path around it)."""
    s = TpuSession({"spark.rapids.query.memoryBudgetBytes": "1",
                    "spark.rapids.sql.oomRetry.maxSplits": "1"})
    qx = s.query_context()
    with pytest.raises(QueryCancelled) as ei:
        _frame(s, nbatches=2).select("a").collect(qx)
    assert ei.value.reason == "budget"
    # the walk is visible: halving, then spill and width1 rungs
    assert qx.ladder.counts.get("spill", 0) >= 1
    assert qx.ladder.counts.get("width1", 0) >= 1


def test_oom_storm_walks_all_four_rungs_to_correct_result():
    """ISSUE acceptance: an injected OOM storm exhausts halving, the
    ladder walks spill -> width1 -> cpu, and the query still returns
    the correct answer (via the classified CPU fallback)."""
    s = TpuSession({"spark.rapids.sql.test.injectRetryOOM.storm": "200",
                    "spark.rapids.sql.oomRetry.maxSplits": "2"})
    qx = s.query_context()
    df = _frame(s, nbatches=1, rows=64)
    got = df.select("a").collect(qx)
    assert got.column(0).to_pylist() == list(range(64))
    for rung in ("halve", "spill", "width1", "cpu"):
        assert qx.ladder.counts.get(rung, 0) >= 1, qx.ladder.counts
    pp = df.select("a")._plan()
    pp.collect(qctx=s.query_context())  # plan path reusable afterwards


def test_ladder_metrics_and_query_cancelled_counter():
    from spark_rapids_tpu.lifecycle import QUERY_CANCELLED, QUERY_DEGRADED
    before = QUERY_CANCELLED.labels("user").value
    CancellationToken("qm").cancel("user")
    assert QUERY_CANCELLED.labels("user").value == before + 1
    b2 = QUERY_DEGRADED.labels("spill").value
    qx = _qc()
    qx.ladder.escalate()
    assert QUERY_DEGRADED.labels("spill").value == b2 + 1


# --- chaos grammar (query-scoped modes) -------------------------------------

def test_chaos_conf_overrides_oom_storm():
    from spark_rapids_tpu.scheduler.chaos import conf_overrides
    ov = conf_overrides("oom_storm:q1s1m0:0:6", 0, "q1s1m0", 0)
    assert ov == {"spark.rapids.sql.test.injectRetryOOM.storm": "6"}
    assert conf_overrides("oom_storm:q1s1m0:0:6", 0, "q1s1m0", 1) == {}
    assert conf_overrides("crash:q1s1m0:*", 0, "q1s1m0", 0) == {}


def test_chaos_spill_fault_modes_do_not_silently_collide():
    """spill_corrupt + spill_torn share the one injectSpillFault
    channel a manager has: both matching the same (task, attempt) is a
    contradictory spec and a named hard error (never a silent no-op),
    while disjoint task globs and repeated rules of ONE mode still
    compose / first-match-win."""
    from spark_rapids_tpu.scheduler.chaos import conf_overrides
    with pytest.raises(ValueError,
                       match="spill_corrupt.*spill_torn"):
        conf_overrides("spill_corrupt:q1r0:*;spill_torn:q1r0:*",
                       0, "q1r0", 0)
    spec = "spill_corrupt:q1r0:*;spill_torn:q2r0:*"
    assert conf_overrides(spec, 0, "q1r0", 0) == {
        "spark.rapids.memory.test.injectSpillFault": "corrupt"}
    assert conf_overrides(spec, 0, "q2r0", 0) == {
        "spark.rapids.memory.test.injectSpillFault": "torn"}
    assert conf_overrides("spill_torn:q1r0:*;spill_torn:q1r0:*",
                          0, "q1r0", 0) == {
        "spark.rapids.memory.test.injectSpillFault": "torn"}


def test_chaos_hang_query_returns_after_bound_without_cancel(tmp_path):
    from spark_rapids_tpu.scheduler.chaos import maybe_inject
    t0 = time.monotonic()
    maybe_inject("hang_query:t1:*:0.1", 0, "t1", 0,
                 cancel_path=str(tmp_path / "none.cancel"))
    assert 0.1 <= time.monotonic() - t0 < 2.0


def test_chaos_hang_query_raises_classified_on_marker(tmp_path):
    from spark_rapids_tpu.scheduler.chaos import maybe_inject
    marker = str(tmp_path / "q.cancel")
    with open(marker, "w") as f:
        f.write("deadline driver said so")
    with pytest.raises(QueryCancelled) as ei:
        maybe_inject("hang_query:t1:*:30", 0, "t1", 0,
                     cancel_path=marker)
    assert ei.value.reason == "deadline"


# --- process-cluster cancel paths -------------------------------------------

def _cluster_plan(nparts=3):
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.exec.base import HostBatchSourceExec
    from spark_rapids_tpu.exec.basic import TpuProjectExec
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.expr import Alias, UnresolvedColumn as col
    from spark_rapids_tpu.expr.aggregates import Sum
    from spark_rapids_tpu.expr.base import bind_expr
    from spark_rapids_tpu.shuffle.partitioner import HashPartitioning
    rng = np.random.default_rng(7)
    rb = pa.record_batch({
        "k": pa.array((np.arange(4000) % 7).astype(np.int32)),
        "v": pa.array(rng.integers(0, 100, 4000).astype(np.int64))})
    src = HostBatchSourceExec([rb.slice(0, 2000), rb.slice(2000)])
    # a projection in the map stage so worker-side batches run through
    # the retry scope (budget checks live there)
    proj = TpuProjectExec(
        [bind_expr(col("k"), src.output_schema),
         bind_expr(col("v"), src.output_schema)], src)
    ex = TpuShuffleExchangeExec(HashPartitioning([col("k")], nparts),
                                proj)
    return TpuHashAggregateExec([col("k")],
                                [Alias(Sum(col("v")), "t")], ex)


def _sched_cancel_events(sched):
    return [e for e in sched.events if e["event"] == "query_cancelled"]


def test_cluster_user_cancel_midstage_no_leaks(tmp_path):
    """ISSUE satellite: cancel mid-stage on a 2-worker process
    cluster — zero ledger leakage (worker gauges via the metrics
    rendezvous), zero admission-slot leakage, classified user cancel,
    and a post-cancel query that runs green on the same cluster."""
    from spark_rapids_tpu.cluster import TpuProcessCluster
    from spark_rapids_tpu.obs.metrics import read_worker_metrics
    conf = RapidsConf({
        # hold every final-stage task until the cancel lands
        "spark.rapids.tpu.test.injectFaults": "hang_query:q1r*:*:60",
        "spark.rapids.metrics.enabled": "true",
        "spark.rapids.query.cancel.joinTimeout": "10",
    })
    plan = _cluster_plan()
    with TpuProcessCluster(n_workers=2, conf=conf) as c:
        canceller = threading.Timer(
            1.0, lambda: c.cancel_running("operator hit ctrl-c"))
        canceller.start()
        with pytest.raises(QueryCancelled) as ei:
            c.run_query(plan)
        canceller.cancel()
        assert ei.value.reason == "user"
        sched = c.last_scheduler
        assert _sched_cancel_events(sched)
        # zero admission-slot leakage on the driver
        snap = DeviceMemoryManager.shared(conf).admission.snapshot()
        assert snap["in_use"] == 0 and not snap["queued"]
        # zero ledger leakage in the workers: the error-path metric
        # flush records each worker's ledger AFTER the reap
        time.sleep(1.0)
        for tag, ms in read_worker_metrics(c.root):
            fam = ms.get("rapids_memory_device_bytes_in_use")
            if fam:
                for _, v in fam["samples"].items():
                    assert v == 0, (tag, v)
        # the same cluster is not poisoned: a clean query runs green
        got = c.run_query(plan, conf=RapidsConf({}))
        assert got.num_rows == 7


def test_cluster_deadline_cancel_with_incident(tmp_path):
    """Deadline-exceeded under hang_query: classified deadline cancel,
    exactly one query_cancelled event-log line, and an incident
    bundle."""
    from spark_rapids_tpu.cluster import TpuProcessCluster
    log_dir = str(tmp_path / "events")
    conf = RapidsConf({
        "spark.rapids.query.deadline": "2.0",
        "spark.rapids.tpu.test.injectFaults": "hang_query:q1r*:*:60",
        "spark.rapids.eventLog.dir": log_dir,
        "spark.rapids.flight.dir": str(tmp_path / "flight"),
    })
    plan = _cluster_plan()
    with TpuProcessCluster(n_workers=2, conf=conf) as c:
        with pytest.raises(QueryCancelled) as ei:
            c.run_query(plan)
        assert ei.value.reason == "deadline"
        assert c.last_incident_path \
            and os.path.exists(c.last_incident_path)
        with open(c.last_incident_path) as f:
            bundle = json.load(f)
        assert any(a["kind"] == "query_cancelled"
                   for a in bundle["anomalies"])
    evs = [json.loads(line)
           for n in os.listdir(log_dir)
           for line in open(os.path.join(log_dir, n))]
    cancels = [e for e in evs if e.get("type") == "query_cancelled"]
    assert len(cancels) == 1 and cancels[0]["reason"] == "deadline"


def test_cluster_admission_and_budget_reasons(tmp_path):
    """The remaining two classified reasons on the process cluster:
    slow_admission chaos trips the queue-time deadline (admission),
    and a 1-byte budget with action=cancel classifies from the worker
    through the .qcancel marker (budget)."""
    from spark_rapids_tpu.cluster import TpuProcessCluster
    plan = _cluster_plan()
    adm_conf = RapidsConf({
        "spark.rapids.query.admission.timeout": "0.2",
        "spark.rapids.tpu.test.injectFaults": "slow_admission:q1:*:1.0",
    })
    with TpuProcessCluster(n_workers=2, conf=adm_conf) as c:
        with pytest.raises(QueryCancelled) as ei:
            c.run_query(plan)
        assert ei.value.reason == "admission"
        assert _sched_cancel_events(c.last_scheduler)
        snap = DeviceMemoryManager.shared(adm_conf).admission.snapshot()
        assert snap["in_use"] == 0 and not snap["queued"]
    bud_conf = RapidsConf({
        "spark.rapids.query.memoryBudgetBytes": "1",
        "spark.rapids.query.memoryBudget.action": "cancel",
    })
    with TpuProcessCluster(n_workers=2, conf=bud_conf) as c:
        with pytest.raises(QueryCancelled) as ei:
            c.run_query(plan)
        assert ei.value.reason == "budget"
        ev = _sched_cancel_events(c.last_scheduler)
        assert ev and "[budget]" in ev[0]["reason"]
        snap = DeviceMemoryManager.shared(bud_conf).admission.snapshot()
        assert snap["in_use"] == 0
        # cancelling after the query already finished is a no-op, not
        # phantom cancel evidence
        assert c.cancel_running() is False
    # the DEFAULT budget action (degrade) must also classify on the
    # cluster: workers have no ladder, so budget exhaustion after the
    # halving budget classifies via the .qcancel marker — never an
    # unclassified retry storm that blacklists healthy workers
    deg_conf = RapidsConf({
        "spark.rapids.query.memoryBudgetBytes": "1",
        "spark.rapids.sql.oomRetry.maxSplits": "1",
    })
    with TpuProcessCluster(n_workers=2, conf=deg_conf) as c:
        with pytest.raises(QueryCancelled) as ei:
            c.run_query(plan)
        assert ei.value.reason == "budget"
        sched = c.last_scheduler
        assert _sched_cancel_events(sched)
        assert not sched.blacklist  # cooperative stop blames no worker


# --- registered timeout confs (satellite) -----------------------------------

def test_shuffle_close_join_timeout_is_a_conf():
    from spark_rapids_tpu.config import (SHUFFLE_CLOSE_JOIN_TIMEOUT,
                                         WORKER_EXIT_TIMEOUT)
    assert SHUFFLE_CLOSE_JOIN_TIMEOUT.key == \
        "spark.rapids.shuffle.close.joinTimeout"
    assert RapidsConf({SHUFFLE_CLOSE_JOIN_TIMEOUT.key: "0.25"}).get(
        SHUFFLE_CLOSE_JOIN_TIMEOUT) == 0.25
    assert RapidsConf().get(WORKER_EXIT_TIMEOUT) == 10.0
    # the transport reads the conf (not a literal) at close time
    from spark_rapids_tpu.shuffle.host import HostShuffleTransport
    t = HostShuffleTransport(RapidsConf(
        {SHUFFLE_CLOSE_JOIN_TIMEOUT.key: "0.25"}), threads=2)
    t.close()  # no outstanding writes: returns immediately
