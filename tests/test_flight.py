"""Flight-recorder tier tests: ring bounds, anomaly triggers, memory
timeline, harvest torn-file tolerance, /metrics under concurrent
writers, histogram bisect semantics, trace/event-log retention — plus
the ISSUE acceptance test: with ``spark.rapids.trace.dir`` UNSET, an
injected mid-stage worker crash on ``TpuProcessCluster`` yields exactly
one incident bundle containing the dead worker's preceding ring events,
a memory timeline with a nonzero high-water mark, and straggler/attempt
attribution naming the failed attempt, and ``profiling triage`` renders
it without error."""
import importlib.util
import json
import os
import threading
import time
import urllib.request

import pyarrow as pa
import pytest

from data_gen import IntegerGen, LongGen, gen_table

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.obs.anomaly import (AnomalyDetector,
                                          anomalies_from_scheduler,
                                          build_incident_bundle,
                                          conf_delta,
                                          straggler_attribution)
from spark_rapids_tpu.obs.recorder import (RECORDER, FlightRecorder,
                                           memory_timeline, prune_oldest,
                                           read_flight_dumps,
                                           read_worker_rings)
from spark_rapids_tpu.tools.profiling import triage_report


def _load_checker():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "check_obs_output.py")
    spec = importlib.util.spec_from_file_location("check_obs_fl", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --- ring buffer ------------------------------------------------------------

def test_ring_bounds_events_and_bytes():
    r = FlightRecorder(max_events=5, max_bytes=1 << 20)
    for i in range(9):
        r.record("t", i=i)
    evs = r.snapshot()
    assert len(evs) == 5 and r.dropped == 4
    assert [e["i"] for e in evs] == [4, 5, 6, 7, 8]  # oldest evicted
    # byte bound evicts even under the event bound
    r2 = FlightRecorder(max_events=10_000, max_bytes=2048)
    for i in range(200):
        r2.record("t", payload="x" * 64)
    assert len(r2.snapshot()) < 40 and r2.dropped > 0


def test_ring_disabled_records_nothing():
    r = FlightRecorder()
    r.configure(RapidsConf({"spark.rapids.flight.enabled": "false"}))
    r.record("t", a=1)
    assert r.snapshot() == []
    r.configure(RapidsConf())  # default is ON
    r.record("t", a=2)
    assert len(r.snapshot()) == 1


def test_ring_snapshot_since():
    r = FlightRecorder()
    r.record("old")
    cut = time.time()
    time.sleep(0.01)
    r.record("new")
    evs = r.snapshot(since=cut)
    assert [e["kind"] for e in evs] == ["new"]


def test_span_tap_joins_ring():
    from spark_rapids_tpu.obs.tracer import Tracer
    RECORDER.configure(RapidsConf())
    RECORDER.clear()
    t = Tracer()
    with t.span("op x", cat="op"):
        pass
    spans = [e for e in RECORDER.snapshot() if e["kind"] == "span"]
    assert spans and spans[-1]["name"] == "op x"


# --- memory timeline --------------------------------------------------------

def test_memory_ledger_transitions_recorded_with_high_water():
    from spark_rapids_tpu.columnar.batch import TpuBatch, bucket_rows
    from spark_rapids_tpu.columnar.column import TpuColumnVector
    from spark_rapids_tpu import datatypes as dt
    from spark_rapids_tpu.memory import DeviceMemoryManager
    import numpy as np
    RECORDER.configure(RapidsConf())
    RECORDER.clear()
    t0 = time.time()
    mgr = DeviceMemoryManager(RapidsConf(
        {"spark.rapids.sql.test.injectRetryOOM": 0,
         "spark.rapids.memory.device.budgetBytes": 1 << 30}))
    n = 64
    col = TpuColumnVector.from_numpy(
        dt.INT64, np.arange(n, dtype=np.int64), None, bucket_rows(n))
    schema = dt.Schema([dt.StructField("a", dt.INT64, False)])
    b = TpuBatch([col], schema, n)
    sb = mgr.register(b)
    sb.spill()
    _ = sb.get()
    sb.release()
    tl = memory_timeline(RECORDER.snapshot(since=t0))
    kinds = [e["ev"] for e in tl["events"]]
    for ev in ("budget", "reserve", "spill", "readback", "release"):
        assert ev in kinds, (ev, kinds)
    assert tl["high_water_bytes"] > 0
    assert tl["budget_bytes"] == 1 << 30
    ts = [e["ts"] for e in tl["events"]]
    assert ts == sorted(ts)


def test_oom_retry_recorded_and_triggers_anomaly():
    from spark_rapids_tpu.columnar.batch import TpuBatch, bucket_rows
    from spark_rapids_tpu.columnar.column import TpuColumnVector
    from spark_rapids_tpu import datatypes as dt
    from spark_rapids_tpu.memory import DeviceMemoryManager
    import numpy as np
    RECORDER.configure(RapidsConf())
    RECORDER.clear()
    t0 = time.time()
    mgr = DeviceMemoryManager(RapidsConf(
        {"spark.rapids.sql.test.injectRetryOOM": 1}))
    n = 8
    col = TpuColumnVector.from_numpy(
        dt.INT64, np.arange(n, dtype=np.int64), None, bucket_rows(n))
    schema = dt.Schema([dt.StructField("a", dt.INT64, False)])
    b = TpuBatch([col], schema, n)
    outs = mgr.with_retry(b, lambda bb: bb)
    assert len(outs) == 2  # split once
    evs = RECORDER.snapshot(since=t0)
    assert any(e.get("ev") == "oom_retry" for e in evs)
    trig = AnomalyDetector().check_task(evs, failed=False)
    assert trig is not None and trig[0] == "oom_retry_cascade"


# --- anomaly detector -------------------------------------------------------

def test_detector_task_failure_and_spill_cascade():
    d = AnomalyDetector(spill_cascade_threshold=2)
    assert d.check_task([], failed=True, error="Boom\nValueError: x") \
        == ("task_failure", "ValueError: x")
    spills = [{"kind": "mem", "ev": "spill"} for _ in range(2)]
    kind, reason = d.check_task(spills, failed=False)
    assert kind == "spill_cascade" and "2" in reason
    assert d.check_task(spills[:1], failed=False) is None
    assert d.check_task([], failed=False) is None


def test_anomalies_from_scheduler_filters_benign_events():
    evs = [
        {"event": "task_submitted", "task": "t1"},
        {"event": "task_failed", "task": "t1", "attempt": 0,
         "worker": 1, "ts": 5.0, "reason": "boom"},
        {"event": "attempt_lost", "task": "t1"},  # benign spec loser
        {"event": "worker_respawn", "worker": 1, "ts": 6.0,
         "reason": "died"},
        {"event": "straggler_detected", "task": "t2", "attempt": 0,
         "worker": 0, "ts": 7.0, "reason": "slow"},
    ]
    out = anomalies_from_scheduler(evs)
    assert [a["kind"] for a in out] == [
        "task_failed", "worker_respawn", "straggler_detected"]


def test_straggler_attribution_flags_failed_and_slow():
    evs = [
        {"event": "task_ok", "stage": "map s1", "task": "m0",
         "attempt": 0, "worker": 0, "wall_s": 1.0},
        {"event": "task_ok", "stage": "map s1", "task": "m1",
         "attempt": 0, "worker": 1, "wall_s": 1.2},
        {"event": "task_ok", "stage": "map s1", "task": "m2",
         "attempt": 1, "worker": 0, "wall_s": 9.0},
        {"event": "task_failed", "stage": "map s1", "task": "m2",
         "attempt": 0, "worker": 1, "wall_s": 0.2, "reason": "err"},
    ]
    att = straggler_attribution(evs, factor=4.0)
    st = att["map s1"]
    assert st["median_ok_s"] == pytest.approx(1.2)
    flagged = {(a["task"], a["attempt"]) for a in st["flagged"]}
    assert ("m2", 0) in flagged   # the failed attempt is named
    assert ("m2", 1) in flagged   # 9.0s > 4 x 1.2s median
    assert ("m0", 0) not in flagged


def test_conf_delta_only_non_defaults():
    c = RapidsConf({"spark.rapids.sql.enabled": "true",       # = default
                    "spark.sql.shuffle.partitions": "4",      # changed
                    "some.unregistered.key": "v"})
    d = conf_delta(c)
    assert "spark.rapids.sql.enabled" not in d
    assert d["spark.sql.shuffle.partitions"] == "4"
    assert d["some.unregistered.key"] == "v"


# --- harvest torn-file tolerance (satellite) --------------------------------

def test_harvest_skips_torn_rings_dumps_and_metrics(tmp_path):
    root = str(tmp_path)
    fdir = os.path.join(root, "flight")
    tdir = os.path.join(root, "tasks")
    os.makedirs(fdir)
    os.makedirs(tdir)
    # one good ring, one torn, one alien shape
    with open(os.path.join(fdir, "w0-11.ring.json"), "w") as f:
        json.dump({"proc": "w0", "pid": 11,
                   "events": [{"ts": 1.0, "kind": "task"}]}, f)
    with open(os.path.join(fdir, "w1-12.ring.json"), "w") as f:
        f.write('{"proc": "w1", "events": [{"t')   # torn mid-write
    with open(os.path.join(fdir, "w2-13.ring.json"), "w") as f:
        json.dump({"proc": "w2", "events": "not-a-list"}, f)
    rings = read_worker_rings(root)
    assert [t for t, _ in rings] == ["w0:11"]
    # one good dump, one torn, one for another query
    with open(os.path.join(tdir, "q1s1m0.a0.w1.task.flight.json"),
              "w") as f:
        json.dump({"proc": "w1", "task": "q1s1m0", "attempt": 0,
                   "trigger": "task_failure", "events": []}, f)
    with open(os.path.join(tdir, "q1s1m1.a0.w0.task.flight.json"),
              "w") as f:
        f.write('{"torn":')
    with open(os.path.join(tdir, "q10s1m0.a0.w0.task.flight.json"),
              "w") as f:
        json.dump({"proc": "w0", "task": "q10s1m0", "attempt": 0,
                   "trigger": "task_failure", "events": []}, f)
    dumps = read_flight_dumps(tdir, query_id="q1")
    assert [d["task"] for d in dumps] == ["q1s1m0"]  # q10 NOT matched
    # torn worker metrics snapshots: same guarantee (existing reader)
    from spark_rapids_tpu.obs.metrics import read_worker_metrics
    os.makedirs(os.path.join(root, "metrics"))
    with open(os.path.join(root, "metrics", "w0.json"), "w") as f:
        f.write('{"half":')
    assert read_worker_metrics(root) == []


def test_bundle_assembly_and_schema(tmp_path):
    sched_events = [
        {"event": "task_failed", "stage": "map s1", "task": "m0",
         "attempt": 0, "worker": 1, "ts": 10.0, "wall_s": 0.5,
         "reason": "boom"},
        {"event": "task_ok", "stage": "map s1", "task": "m0",
         "attempt": 1, "worker": 0, "ts": 11.0, "wall_s": 0.4},
    ]
    driver_events = [
        {"ts": 9.0, "kind": "mem", "ev": "budget", "budget": 100,
         "device": 0, "host": 0},
        {"ts": 9.5, "kind": "mem", "ev": "reserve", "bytes": 10,
         "device": 10, "host": 0},
    ]
    bundle = build_incident_bundle(
        query_id="q1", flight_id="abcd", seq=3,
        trigger_anomalies=anomalies_from_scheduler(sched_events),
        driver_events=driver_events,
        worker_rings=[("w0:11", {"events": [
            {"ts": 9.9, "kind": "task", "ev": "claim", "task": "m0",
             "attempt": 0}]})],
        worker_dumps=[], sched_events=sched_events,
        metrics_snapshot={"driver": {}}, conf=RapidsConf(),
        straggler_factor=6.0)
    assert bundle["incident_id"] == "incident-abcd-3"
    assert bundle["memory_timeline"]["high_water_bytes"] == 10
    p = os.path.join(str(tmp_path), "incident-abcd-3.json")
    with open(p, "w") as f:
        json.dump(bundle, f)
    assert _load_checker().check_flight(p) == []
    # the renderer accepts it
    rep = triage_report(bundle)
    assert "task_failed" in rep and "high water" in rep


# --- /metrics endpoint under concurrent writers (satellite) -----------------

def test_http_metrics_endpoint_under_concurrent_updates():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    from spark_rapids_tpu.obs import metrics as M
    srv_before = M._http_server  # restore after: the server is a
    # process singleton and later tests assert on a fresh bind
    conf = RapidsConf({"spark.rapids.metrics.port": port})
    bound = M.maybe_start_http_server(conf)
    if bound is None:
        pytest.skip("metrics http server unavailable (bound elsewhere)")
    checker = _load_checker()
    c = M.REGISTRY.counter("rapids_flight_conc_total", "", ("k",))
    h = M.REGISTRY.histogram("rapids_flight_conc_seconds")
    stop = threading.Event()

    def hammer():
        i = 0
        while not stop.is_set():
            c.labels(f"k{i % 4}").inc()
            h.observe((i % 100) / 1000.0)
            i += 1

    threads = [threading.Thread(target=hammer) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for _ in range(10):
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{bound}/metrics",
                timeout=5).read().decode()
            # every scrape parses and holds the histogram invariants
            # (cumulative buckets, +Inf == _count) mid-hammer
            assert checker.check_prometheus(body) == []
    finally:
        stop.set()
        for t in threads:
            t.join()
        if srv_before is None and M._http_server not in (None, "failed"):
            M._http_server.shutdown()
            M._http_server.server_close()
            M._http_server = None


# --- histogram bisect semantics (satellite) ---------------------------------

def test_histogram_bisect_bucket_edges():
    from spark_rapids_tpu.obs.metrics import MetricsRegistry
    r = MetricsRegistry()
    h = r.histogram("h_seconds", buckets=(0.1, 1.0, float("inf")))
    for v in (0.1, 0.100001, 1.0, 50.0, float("inf"), 0.0):
        h.observe(v)
    snap = r.snapshot()["h_seconds"]["samples"][""]
    # v <= le semantics: 0.1 and 0.0 in bucket 0; 0.100001 and 1.0 in
    # bucket 1; 50.0 and inf in +Inf — cumulative [2, 4, 6]
    assert snap["counts"] == [2, 4, 6]
    assert snap["count"] == 6


def test_transfer_buckets_observe_matches_linear_walk():
    from spark_rapids_tpu.obs.metrics import (TRANSFER_BUCKETS,
                                              MetricsRegistry)
    import random
    rng = random.Random(7)
    r = MetricsRegistry()
    h = r.histogram("t_seconds", buckets=TRANSFER_BUCKETS)
    vals = [rng.uniform(0, 2) for _ in range(500)] \
        + list(TRANSFER_BUCKETS[:-1])
    for v in vals:
        h.observe(v)
    got = r.snapshot()["t_seconds"]["samples"][""]["counts"]
    want = [sum(1 for v in vals if v <= le) for le in TRANSFER_BUCKETS]
    assert got == want


# --- retention (satellite) --------------------------------------------------

def test_trace_dir_retention_prunes_oldest(tmp_path):
    from spark_rapids_tpu.obs.tracer import Tracer
    d = str(tmp_path)
    for i in range(6):
        t = Tracer(trace_id=f"{i:04x}", max_files=4)
        with t.span("q", cat="query"):
            pass
        t.write_chrome(d)
        os.utime(os.path.join(d, f"trace-{i:04x}.json"),
                 (1000 + i, 1000 + i))
    names = sorted(n for n in os.listdir(d) if n.endswith(".json"))
    assert len(names) == 4
    assert "trace-0000.json" not in names  # oldest-first
    assert "trace-0005.json" in names


def test_event_log_retention(tmp_path):
    base = str(tmp_path)
    for i in range(7):
        with open(os.path.join(base, f"app-{i}-1.jsonl"), "w") as f:
            f.write("{}\n")
        os.utime(os.path.join(base, f"app-{i}-1.jsonl"),
                 (2000 + i, 2000 + i))
    assert prune_oldest(base, 3, prefix="app-", suffix=".jsonl") == 4
    left = sorted(os.listdir(base))
    assert left == ["app-4-1.jsonl", "app-5-1.jsonl", "app-6-1.jsonl"]


# --- the acceptance test: crash -> one bundle, tracing DISABLED -------------

def _crash_plan():
    from spark_rapids_tpu.exec.base import HostBatchSourceExec
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.expr import Alias, UnresolvedColumn as col
    from spark_rapids_tpu.expr.aggregates import Sum
    from spark_rapids_tpu.shuffle.partitioner import HashPartitioning
    rbs = [gen_table([IntegerGen(min_val=0, max_val=9, nullable=False),
                      LongGen(nullable=False)], n, seed=s,
                     names=["k", "v"])
           for n, s in [(400, 1), (350, 2)]]
    src = HostBatchSourceExec(rbs)
    exch = TpuShuffleExchangeExec(HashPartitioning([col("k")], 4), src)
    return TpuHashAggregateExec(
        [col("k")], [Alias(Sum(col("v")), "s")], exch)


def test_crash_yields_one_incident_bundle_without_tracing(tmp_path):
    """ISSUE acceptance: spark.rapids.trace.dir UNSET; a mid-stage
    worker crash must leave exactly one incident bundle holding (a) the
    failed task's preceding ring events from the dead worker, (b) a
    memory timeline with a nonzero high-water mark, and (c) attempt
    attribution naming the failed attempt — and triage renders it."""
    from spark_rapids_tpu.cluster import TpuProcessCluster
    from spark_rapids_tpu.exec.base import ExecCtx
    flight_dir = str(tmp_path / "incidents")
    conf = RapidsConf({
        "spark.rapids.tpu.test.injectFaults": "crash:q1s1m0:0",
        "spark.rapids.flight.dir": flight_dir,
    })
    plan = _crash_plan()
    with TpuProcessCluster(n_workers=2, conf=conf) as c:
        got = c.run_query(plan)
        bundle_path = c.last_incident_path
        assert c.last_trace_path is None  # tracing really was off

    # the query still succeeded (scheduler retried the crashed task)
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_schema
    want = pa.Table.from_batches(
        list(plan.execute_cpu(ExecCtx())),
        schema=arrow_schema(plan.output_schema))
    key = lambda t: sorted(t.to_pylist(), key=lambda d: d["k"])
    assert key(got) == key(want)

    # exactly ONE bundle, schema-valid
    assert bundle_path and os.path.dirname(bundle_path) == flight_dir
    assert [n for n in os.listdir(flight_dir)
            if n.endswith(".json")] == [os.path.basename(bundle_path)]
    assert _load_checker().check_flight(bundle_path) == []
    with open(bundle_path) as f:
        bundle = json.load(f)

    # (a) the dead worker's ring contains the crashed attempt's claim
    dead_rings = [
        tag for tag, evs in bundle["rings"].items()
        if any(e.get("kind") == "task" and e.get("ev") == "claim"
               and e.get("task") == "q1s1m0" and e.get("attempt") == 0
               for e in evs)]
    assert dead_rings, bundle["rings"].keys()
    # ... and it is a WORKER ring that survived the respawn (the
    # incarnation-tagged flush at claim time)
    assert all(t.startswith("w") for t in dead_rings)

    # (b) merged memory timeline with a nonzero high-water mark
    mt = bundle["memory_timeline"]
    assert mt["high_water_bytes"] > 0 and mt["events"]

    # (c) attribution names the failed attempt in its stage
    st = bundle["attempts"]["map s1"]
    flagged = {(a["task"], a["attempt"], a["state"])
               for a in st["flagged"]}
    assert ("q1s1m0", 0, "err") in flagged
    # the anomaly list names the same attempt
    assert any(a["kind"] == "task_failed" and a["task"] == "q1s1m0"
               for a in bundle["anomalies"])
    # the crash (worker death) is visible as a respawn anomaly
    assert any(a["kind"] == "worker_respawn"
               for a in bundle["anomalies"])

    # triage renders without error and names the pieces
    rep = triage_report(bundle_path)
    assert "what fired" in rep and "q1s1m0" in rep
    assert "HBM timeline" in rep and "high water" in rep
    assert "straggler / attempt attribution" in rep


def test_straggler_trigger_fires_and_clean_query_leaves_no_bundle(
        tmp_path):
    """A chaos-delayed attempt past stragglerFactor x the stage median
    is recorded and bundled; a clean follow-up query on the same
    cluster leaves no second bundle."""
    from spark_rapids_tpu.cluster import TpuProcessCluster
    flight_dir = str(tmp_path / "incidents")
    conf = RapidsConf({
        # m0 attempt 0 sleeps 6s; its sibling map task sets the median,
        # so m0 trips factor x median while still running (the delay
        # dominates per-task compile noise by construction: firing
        # needs 6 + T > 2T, i.e. sibling time T < 6s)
        "spark.rapids.tpu.test.injectFaults": "delay:q1s1m0:0:6.0",
        "spark.rapids.flight.dir": flight_dir,
        "spark.rapids.flight.stragglerFactor": 2.0,
    })
    plan = _crash_plan()
    with TpuProcessCluster(n_workers=2, conf=conf) as c:
        c.run_query(plan)
        first = c.last_incident_path
        assert first and os.path.exists(first)
        with open(first) as f:
            bundle = json.load(f)
        assert any(a["kind"] == "straggler_detected"
                   and a["task"] == "q1s1m0"
                   for a in bundle["anomalies"]), bundle["anomalies"]
        # the attribution carries the straggler observation too
        st = bundle["attempts"]["map s1"]
        assert any(a["state"] == "straggler" for a in st["attempts"])
        # clean second query on the same cluster: no new bundle (a
        # huge factor rules out timing-noise false stragglers — the
        # point is that NO anomaly means NO bundle)
        c.run_query(_crash_plan(), conf.with_settings(
            {"spark.rapids.tpu.test.injectFaults": "",
             "spark.rapids.flight.stragglerFactor": 1000.0}))
        bundles = [n for n in os.listdir(flight_dir)
                   if n.endswith(".json")]
        assert bundles == [os.path.basename(first)]
