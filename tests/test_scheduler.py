"""Fault-tolerance integration tests: real worker OS processes under
deterministic chaos (spark.rapids.tpu.test.injectFaults). Each test
drives a recovery path end to end — crash mid-map, hang past the
heartbeat, straggler speculation with a zombie commit race — and checks
results against the CPU oracle / a no-fault run, plus the attempt
timeline the scheduler records for the event log. State-machine unit
tests (no processes) live in test_scheduler_unit.py."""
import json
import os

import numpy as np
import pyarrow as pa
import pytest

from data_gen import IntegerGen, LongGen, gen_table

from spark_rapids_tpu.cluster import TpuProcessCluster
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec.base import ExecCtx, HostBatchSourceExec
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
from spark_rapids_tpu.exec.joins import TpuShuffledHashJoinExec
from spark_rapids_tpu.expr import Alias, UnresolvedColumn as col
from spark_rapids_tpu.expr.aggregates import Count, Sum
from spark_rapids_tpu.shuffle.partitioner import HashPartitioning


def _oracle(plan):
    rbs = list(plan.execute_cpu(ExecCtx()))
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_schema
    return pa.Table.from_batches(rbs, schema=arrow_schema(
        plan.output_schema))


def _rows(table):
    return sorted(table.to_pylist(), key=lambda d: tuple(
        (v is None, str(v)) for v in d.values()))


def _join_agg_plan(nparts=3, seed=5):
    """The acceptance query: 2-stage (map shuffles + reduce join/agg)
    fact x dim join, two batches per side so both map stages split
    across workers."""
    rng = np.random.default_rng(seed)
    n_f, n_d = 2000, 64
    fact = pa.record_batch({
        "fk": pa.array(rng.integers(0, n_d, n_f).astype(np.int32)),
        "amt": pa.array(rng.integers(1, 100, n_f).astype(np.int64)),
    })
    dim = pa.record_batch({
        "dk": pa.array(np.arange(n_d, dtype=np.int32)),
        "grp": pa.array((np.arange(n_d) % 7).astype(np.int32)),
    })
    fact_src = HostBatchSourceExec([fact.slice(0, 1100), fact.slice(1100)])
    dim_src = HostBatchSourceExec([dim.slice(0, 40), dim.slice(40)])
    lex = TpuShuffleExchangeExec(HashPartitioning([col("fk")], nparts),
                                 fact_src)
    rex = TpuShuffleExchangeExec(HashPartitioning([col("dk")], nparts),
                                 dim_src)
    join = TpuShuffledHashJoinExec([col("fk")], [col("dk")], "inner",
                                   lex, rex)
    # the agg groups by a NON-join key: distributed execution needs the
    # re-partition exchange Spark would plan here
    gex = TpuShuffleExchangeExec(HashPartitioning([col("grp")], nparts),
                                 join)
    return TpuHashAggregateExec(
        [col("grp")], [Alias(Sum(col("amt")), "total"),
                       Alias(Count(col("amt")), "n")], gex)


def _events(sched, kind, task=None):
    return [e for e in sched.events if e["event"] == kind
            and (task is None or e["task"] == task)]


def test_chaos_crash_midmap_join_completes(tmp_path):
    """ISSUE acceptance: a worker killed during the map stage of a
    2-stage join query; the query completes with correct results, the
    retry is in the event log, and speculation stayed off (default)."""
    log_dir = str(tmp_path / "events")
    conf = RapidsConf({
        "spark.rapids.tpu.test.injectFaults": "crash:q1s1m0:0",
        "spark.rapids.eventLog.dir": log_dir,
    })
    plan = _join_agg_plan()
    with TpuProcessCluster(n_workers=2, conf=conf) as c:
        got = c.run_query(plan)
        sched = c.last_scheduler
    want = _oracle(plan)  # == the no-fault run (see test below)
    assert _rows(got) == _rows(want)
    # the crash was detected as a worker death and retried
    failed = _events(sched, "task_failed", "q1s1m0")
    assert failed and "worker died" in failed[0]["reason"]
    ok = _events(sched, "task_ok", "q1s1m0")
    assert ok and ok[0]["attempt"] >= 1
    assert _events(sched, "worker_respawn")
    # speculation is opt-in; the default run must not duplicate tasks
    assert not _events(sched, "speculative_attempt")
    # ... and the retry made it into the persisted event log
    files = [os.path.join(log_dir, n) for n in os.listdir(log_dir)]
    evs = [json.loads(line) for p in files for line in open(p)]
    sched_evs = [e for e in evs if e.get("type") == "scheduler"]
    assert sched_evs and sched_evs[0]["summary"]["failures"] >= 1
    assert any(a["event"] == "task_ok" and a["task"] == "q1s1m0"
               and a["attempt"] >= 1
               for e in sched_evs for a in e["attempts"])


def test_no_fault_run_matches_oracle_and_is_deterministic():
    """Regression guard: with the scheduler on and no faults, a clean
    run matches the CPU oracle and two runs are byte-identical."""
    plan = _join_agg_plan()
    with TpuProcessCluster(n_workers=2) as c:
        got1 = c.run_query(plan)
        sched = c.last_scheduler
        got2 = c.run_query(plan)
    assert _rows(got1) == _rows(_oracle(plan))
    # byte-identical across runs: same stage split, same commit layout
    sink1, sink2 = pa.BufferOutputStream(), pa.BufferOutputStream()
    for t, sink in ((got1, sink1), (got2, sink2)):
        with pa.ipc.new_stream(sink, t.schema) as w:
            w.write_table(t)
    assert sink1.getvalue().equals(sink2.getvalue())
    # a clean run has no retries, respawns, or speculation
    assert not _events(sched, "task_failed")
    assert not _events(sched, "worker_respawn")
    assert not _events(sched, "speculative_attempt")


def test_chaos_hang_past_heartbeat_recovers():
    """A worker that wedges (heartbeat suspended, task never finishes)
    is detected by heartbeat staleness, killed, respawned, and its task
    retried."""
    conf = RapidsConf({
        "spark.rapids.tpu.test.injectFaults": "hang:q1s1m0:0",
        "spark.rapids.tpu.heartbeat.interval": 0.2,
        "spark.rapids.tpu.heartbeat.timeout": 5.0,
    })
    rbs = [gen_table([IntegerGen(min_val=0, max_val=20, null_frac=0.1),
                      LongGen(nullable=False)], n, seed=s,
                     names=["k", "v"])
           for n, s in [(300, 1), (250, 2)]]
    src = HostBatchSourceExec(rbs)
    exch = TpuShuffleExchangeExec(HashPartitioning([col("k")], 4), src)
    plan = TpuHashAggregateExec(
        [col("k")], [Alias(Sum(col("v")), "s")], exch)
    with TpuProcessCluster(n_workers=2, conf=conf) as c:
        got = c.run_query(plan)
        sched = c.last_scheduler
    assert _rows(got) == _rows(_oracle(plan))
    failed = _events(sched, "task_failed", "q1s1m0")
    assert failed and "heartbeat stale" in failed[0]["reason"]
    assert _events(sched, "worker_respawn")
    assert _events(sched, "task_ok", "q1s1m0")[0]["attempt"] >= 1


def test_chaos_delay_speculation_zombie_commit():
    """Straggler mitigation end to end: a delayed map attempt triggers a
    speculative duplicate; both eventually produce full output, the
    commit protocol keeps exactly one, and the result has no duplicated
    rows."""
    conf = RapidsConf({
        "spark.rapids.tpu.test.injectFaults": "delay:q1s1m0:0:8.0",
        "spark.rapids.tpu.speculation": "true",
        "spark.rapids.tpu.speculation.multiplier": 1.5,
        "spark.rapids.tpu.speculation.minRuntime": 2.0,
    })
    rbs = [gen_table([IntegerGen(min_val=0, max_val=20, null_frac=0.1),
                      LongGen(nullable=False)], n, seed=s,
                     names=["k", "v"])
           for n, s in [(300, 1), (250, 2), (411, 3)]]
    src = HostBatchSourceExec(rbs)
    exch = TpuShuffleExchangeExec(HashPartitioning([col("k")], 4), src)
    plan = TpuHashAggregateExec(
        [col("k")], [Alias(Sum(col("v")), "s"),
                     Alias(Count(col("v")), "c")], exch)
    with TpuProcessCluster(n_workers=2, conf=conf) as c:
        got = c.run_query(plan)
        sched = c.last_scheduler
        shuffle_dir = os.path.join(c.root, "shuffle", "s1")
        committed = [n for n in os.listdir(shuffle_dir)
                     if n.startswith("q1s1m0") and n.endswith(".mapout")]
        staging = [n for n in os.listdir(shuffle_dir)
                   if n.startswith("q1s1m0") and ".staging" in n]
        # duplicate attempts may still be in flight; the visible state
        # must be exactly one committed dir for the task
        assert len(committed) == 1
        assert _rows(got) == _rows(_oracle(plan))
    assert _events(sched, "speculative_attempt", "q1s1m0")
    assert len(_events(sched, "task_ok", "q1s1m0")) == 1
    del staging  # may or may not still exist mid-race; not asserted


def test_persistent_task_failure_exhausts_attempts():
    """A task that fails deterministically on every worker raises after
    maxAttempts with the worker traceback, and the failing workers got
    blacklisted along the way."""
    from spark_rapids_tpu import datatypes as dt
    from spark_rapids_tpu.io.scan import TpuFileScanExec
    conf = RapidsConf({
        "spark.rapids.tpu.task.maxAttempts": 2,
        "spark.rapids.tpu.scheduler.maxTaskFailuresPerWorker": 1,
    })
    schema = dt.Schema([dt.StructField("x", dt.INT64, True)])
    missing = TpuFileScanExec(["/nonexistent/x.parquet"], schema=schema)
    exch = TpuShuffleExchangeExec(HashPartitioning([col("x")], 2),
                                  missing)
    plan = TpuHashAggregateExec([], [Alias(Count(col("x")), "c")], exch)
    with TpuProcessCluster(n_workers=2, conf=conf) as c:
        with pytest.raises(RuntimeError,
                           match="worker task .* failed after 2 attempts"):
            c.run_query(plan)
        sched = c.last_scheduler
    assert len(_events(sched, "task_failed")) == 2
    assert _events(sched, "worker_blacklisted")


def test_aqe_wrapped_plan_runs_on_cluster():
    """ADVICE r5 satellite: planner-built plans (AQE on by default) wrap
    exchanges in TpuAQEShuffleReadExec; run_query must strip them
    instead of dying on ProcessShuffleReadExec.materialize."""
    from spark_rapids_tpu.exec.aqe import TpuAQEShuffleReadExec
    from spark_rapids_tpu.planner import overrides
    rbs = [gen_table([IntegerGen(min_val=0, max_val=9, null_frac=0.0),
                      LongGen(nullable=False)], 200, seed=s,
                     names=["k", "v"]) for s in (1, 2)]
    src = HostBatchSourceExec(rbs)
    exch = TpuShuffleExchangeExec(HashPartitioning([col("k")], 3), src)
    plan = TpuHashAggregateExec(
        [col("k")], [Alias(Sum(col("v")), "s")], exch)
    pp = overrides(plan, RapidsConf())  # AQE defaults on
    assert any(isinstance(n, TpuAQEShuffleReadExec)
               for n in _walk(pp.root)), "planner no longer wraps; " \
        "update this test's premise"
    with TpuProcessCluster(n_workers=2) as c:
        got = c.run_query(pp.root)
    assert _rows(got) == _rows(_oracle(plan))


def test_aqe_topn_over_shuffle_on_cluster():
    """TopN wires an internal pipeline to its child at construction:
    stripping the AQE reader / swapping in ProcessShuffleReadExec must
    go through with_new_children or TopN executes the stale child.
    One reduce partition — a global TopN is only partition-local-safe
    when the final stage is a single task."""
    from spark_rapids_tpu.cluster import _strip_aqe_reads
    from spark_rapids_tpu.exec.aqe import TpuAQEShuffleReadExec
    from spark_rapids_tpu.exec.sort import SortOrder, TpuTopNExec

    def build(nparts):
        rbs = [gen_table([IntegerGen(min_val=0, max_val=999,
                                     null_frac=0.0),
                          LongGen(nullable=False)], 300, seed=s,
                         names=["k", "v"]) for s in (3, 4)]
        src = HostBatchSourceExec(rbs)
        exch = TpuShuffleExchangeExec(
            HashPartitioning([col("k")], nparts), src)
        return exch, TpuTopNExec(
            10, [SortOrder(col("v"), ascending=False)],
            TpuAQEShuffleReadExec(exch))

    # wiring: after the strip, TopN's INTERNAL pipeline (not just
    # .children) must chain down to the exchange, not the AQE reader
    exch, plan = build(3)
    stripped = _strip_aqe_reads(plan)
    internal = list(_walk(stripped._out))
    assert not any(isinstance(n, TpuAQEShuffleReadExec)
                   for n in internal)
    assert any(n is exch for n in internal)

    # end to end: distributed run matches the in-process oracle
    exch1, plan1 = build(1)
    oracle_plan = TpuTopNExec(10, [SortOrder(col("v"), ascending=False)],
                              exch1)
    with TpuProcessCluster(n_workers=2) as c:
        got = c.run_query(plan1)
    assert _rows(got) == _rows(_oracle(oracle_plan))


def _walk(node):
    yield node
    for ch in getattr(node, "children", ()):
        yield from _walk(ch)


# --- spill-tier durability under chaos (PR 12) -----------------------------

@pytest.mark.parametrize("mode,kind", [("spill_corrupt", "corrupt"),
                                       ("spill_torn", "torn")])
def test_chaos_spill_damage_classified_retry_no_blacklist(
        tmp_path, mode, kind):
    """PR 12 acceptance: a worker whose committed spill files rot
    (chaos ``spill_corrupt``) fails its attempt CLASSIFIED — the
    SpillReadError rides a structured ``.spillfail`` marker — and the
    scheduler retries the task WITHOUT blacklisting the reading worker
    (bit rot is not a process fault; re-execution regenerates the
    data). The retry (no injection at attempt 1) goes green, the query
    matches the oracle, the incident bundle carries the
    spill_read_failed anomaly, and no live incarnation spill dir
    leaks files."""
    from spark_rapids_tpu.exec.sort import SortOrder, TpuSortExec
    log_dir = str(tmp_path / "events")
    flight_dir = str(tmp_path / "incidents")
    spill_dir = str(tmp_path / "spill")
    conf = RapidsConf({
        "spark.rapids.tpu.test.injectFaults": f"{mode}:q1r*:0",
        # budgets tiny enough that the reduce task's global sort goes
        # out-of-core: its runs walk device -> host -> sealed disk
        # files and are read back (verified) during the k-way merge
        "spark.rapids.memory.device.budgetBytes": 1 << 14,
        "spark.rapids.memory.host.spillStorageSize": 1 << 12,
        "spark.rapids.memory.spillDir": spill_dir,
        "spark.rapids.eventLog.dir": log_dir,
        "spark.rapids.flight.dir": flight_dir,
    })
    rng = np.random.default_rng(7)
    rbs = [pa.record_batch({
        "k": pa.array(rng.integers(0, 1 << 30, 1200).astype(np.int64)),
        "v": pa.array(rng.integers(0, 1000, 1200).astype(np.int64)),
    }) for _ in range(4)]
    plan = TpuSortExec(
        [SortOrder(col("k"))],
        TpuShuffleExchangeExec(HashPartitioning([col("v")], 1),
                               HostBatchSourceExec(rbs)))
    with TpuProcessCluster(n_workers=2, conf=conf) as c:
        got = c.run_query(plan)
        sched = c.last_scheduler
        bundle = c.last_incident_path
    assert _rows(got) == _rows(_oracle(plan))
    # the loss was classified, not a raw OSError/ArrowInvalid task error
    spill_fails = _events(sched, "spill_read_failed")
    assert spill_fails, "spill_corrupt never bit a reduce task"
    assert f"[spill {kind}]" in spill_fails[0]["reason"]
    # the reading worker is never blamed
    assert not sched.blacklist
    assert not _events(sched, "worker_blacklisted")
    # the task re-ran and went green elsewhere/next attempt
    task = spill_fails[0]["task"]
    ok = _events(sched, "task_ok", task)
    assert ok and ok[0]["attempt"] >= 1
    # forensics: the bundle names the classified anomaly
    assert bundle and os.path.exists(bundle)
    kinds = {a["kind"] for a in json.load(open(bundle))["anomalies"]}
    assert "spill_read_failed" in kinds, kinds
    # no orphan spill files survive in any live incarnation namespace
    leftovers = []
    if os.path.isdir(spill_dir):
        for ns in os.listdir(spill_dir):
            leftovers += [f for f in os.listdir(os.path.join(
                spill_dir, ns)) if f.endswith(".arrow")]
    assert leftovers == [], f"leaked spill files: {leftovers}"
