"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the multi-chip sharding paths are
validated without TPU hardware, mirroring the reference's mock-transport
testing strategy — SURVEY.md §4.3). Must set XLA flags before jax imports.
"""
import importlib.util
import os
import sys

# The axon sitecustomize pins JAX_PLATFORMS=axon (real TPU); tests must run
# on the virtual CPU mesh, so assign (not setdefault) before jax init.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()


def _bootstrap_lockwatch():
    """Install the lock-order watchdog (RAPIDS_TPU_LOCKWATCH=1) BEFORE
    anything imports jax or spark_rapids_tpu: the package creates its
    module-/class-level singleton locks (exchange._SHARED_LOCK_INIT,
    DeviceMemoryManager._shared_lock, flight-recorder/metrics guards,
    _JIT_LOCK) at import time, and they must be watched too. The module
    is loaded by FILE PATH (stdlib-only imports) and pre-registered
    under its canonical name, so the later package import yields the
    SAME module/state."""
    if os.environ.get("RAPIDS_TPU_LOCKWATCH", "") in ("", "0", "false"):
        return
    name = "spark_rapids_tpu.analysis.lockwatch"
    if name in sys.modules:
        sys.modules[name].install()
        return
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "spark_rapids_tpu", "analysis",
                        "lockwatch.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    mod.install()


_bootstrap_lockwatch()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from spark_rapids_tpu.analysis import lockwatch  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running acceptance sweeps excluded from the tier-1 "
        "gate (which runs -m 'not slow')")
    # fallback install (the module-level bootstrap above normally ran
    # first, before the package's import-time locks were created);
    # cluster worker processes install their own watchdog via
    # cluster._main (env is inherited)
    if lockwatch.env_enabled() and not lockwatch.installed():
        lockwatch.install()


def pytest_sessionfinish(session, exitstatus):
    if not lockwatch.installed():
        return
    path = lockwatch.write_report()
    rep = lockwatch.report()
    if rep["inversions"]:
        tr = session.config.pluginmanager.get_plugin("terminalreporter")
        if tr is not None:
            tr.write_line(
                f"lock-order watchdog: "
                f"{len(rep['inversions'])} inversion(s)"
                + (f" — report at {path}" if path else ""), red=True)
            for inv in rep["inversions"][:20]:
                tr.write_line(f"  {inv['why']} at "
                              f"{inv['acquiring_site']}", red=True)
        session.exitstatus = 3


@pytest.fixture
def rng():
    return np.random.default_rng(42)
