"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the multi-chip sharding paths are
validated without TPU hardware, mirroring the reference's mock-transport
testing strategy — SURVEY.md §4.3). Must set XLA flags before jax imports.
"""
import os

# The axon sitecustomize pins JAX_PLATFORMS=axon (real TPU); tests must run
# on the virtual CPU mesh, so assign (not setdefault) before jax init.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
