"""tpu-lint: the package must be clean (zero unallowlisted,
unbaselined violations), and every rule must fire on a seeded specimen
of its bug class (analysis/lint.py; ISSUE 6 + ISSUE 10 — the dataflow
engine's own specimens live in tests/test_dataflow.py)."""
import json
import subprocess
import sys

import pytest

from spark_rapids_tpu.analysis.lint import (LINT_SCHEMA,
                                            conf_key_report,
                                            default_baseline_path,
                                            finding_fingerprint,
                                            lint_package, lint_paths,
                                            load_baseline, package_dir,
                                            registered_conf_keys)


def _lint_snippet(tmp_path, src, name="cluster.py"):
    """Lint one synthetic module; `name` controls module-scoped rules
    (cluster.py is inside the thread-heavy set)."""
    p = tmp_path / name
    p.write_text(src)
    return lint_paths([str(p)])


def _rules(out, allowlisted=False):
    return sorted({f["rule"] for f in out["findings"]
                   if f["allowlisted"] == allowlisted})


# --- the gate ---------------------------------------------------------------

@pytest.fixture(scope="module")
def package_report():
    """ONE full-package lint shared by the gate tests (a package run
    costs ~10s; the baseline is applied per-test from the raw
    fingerprints, so sharing loses nothing)."""
    return lint_package()


def test_package_is_lint_clean(package_report):
    """Zero violations with the checked-in baseline applied: every
    remaining finding is either inline-allowlisted (with a reason) or
    fingerprinted in tools/tpu_lint_baseline.json."""
    base = load_baseline()
    offenders = []
    for f in package_report["findings"]:
        if f["allowlisted"]:
            continue
        if base.get(f["fingerprint"], 0) > 0:
            base[f["fingerprint"]] -= 1
            continue
        offenders.append(f)
    assert offenders == []
    assert package_report["schema"] == LINT_SCHEMA
    # the allowlist surface stays auditable: every suppression carries
    # a reason
    for f in package_report["findings"]:
        if f["allowlisted"]:
            assert f["allow_reason"], f


def test_checked_in_baseline_is_tight(package_report):
    """The baseline must not hoard headroom: every fingerprint in it
    corresponds to a live finding (a stale entry would let a future
    regression with the same fingerprint slip in unnoticed). An EMPTY
    baseline is the ideal end state and trivially tight."""
    base = load_baseline()
    live = {}
    for f in package_report["findings"]:
        if not f["allowlisted"]:
            live[f["fingerprint"]] = live.get(f["fingerprint"], 0) + 1
    for fp, count in base.items():
        assert live.get(fp, 0) >= count, \
            f"stale baseline entry {fp} (accepted {count}, live " \
            f"{live.get(fp, 0)}) — regenerate with --write-baseline"


def test_conf_registry_is_clean():
    rep = conf_key_report()
    assert len(rep["checked"]) > 70
    assert rep["unused"] == [], rep["unused"]
    assert rep["unregistered_reads"] == [], rep["unregistered_reads"]


def test_validate_configs_delegates_to_ast_rule():
    from spark_rapids_tpu.tools.api_validation import validate_configs
    out = validate_configs()
    assert out["unused"] == []
    assert out["unregistered_reads"] == []
    assert len(out["checked"]) > 70


# --- per-rule specimens -----------------------------------------------------

def test_rule_wallclock_duration(tmp_path):
    out = _lint_snippet(tmp_path, (
        "import time\n"
        "def f():\n"
        "    t0 = time.time()\n"
        "    work()\n"
        "    return time.time() - t0\n"))
    assert _rules(out) == ["wallclock-duration"]
    # a bare wall stamp (no subtraction) is NOT a violation
    out = _lint_snippet(tmp_path, (
        "import time\n"
        "def f():\n"
        "    return {'ts': time.time()}\n"))
    assert out["findings"] == []


def test_rule_unregistered_conf_key(tmp_path):
    out = _lint_snippet(tmp_path, (
        "def f(conf):\n"
        "    return conf.get('spark.rapids.sql.noSuchKnob')\n"))
    assert _rules(out) == ["unregistered-conf-key"]
    # registered keys pass (pulled from the live package registry)
    keys = registered_conf_keys()
    assert "spark.rapids.sql.verifyPlan" in keys
    out = _lint_snippet(tmp_path, (
        "def f(conf):\n"
        "    return conf.get('spark.rapids.sql.verifyPlan')\n"))
    assert out["findings"] == []


def test_rule_blocking_call_scoped_to_thread_modules(tmp_path):
    src = ("import time\n"
           "def worker(fut, th):\n"
           "    time.sleep(5)\n"
           "    fut.result()\n"
           "    th.join()\n"
           "    th.join(10.0)\n"       # bounded: fine
           "    ','.join(['a'])\n")    # string join has args: fine
    out = _lint_snippet(tmp_path, src, name="cluster.py")
    flagged = [f["line"] for f in out["findings"]]
    assert flagged == [3, 4, 5]
    # the same source outside the thread-heavy module set is untouched
    out = _lint_snippet(tmp_path, src, name="other.py")
    assert out["findings"] == []


def test_rule_host_sync_in_jit(tmp_path):
    src = ("import jax\n"
           "import numpy as np\n"
           "def decode(blob):\n"
           "    return np.asarray(blob) + 1\n"
           "fn = jax.jit(decode)\n"
           "def host_helper(x):\n"      # NOT jitted: np.asarray fine
           "    return np.asarray(x)\n")
    out = _lint_snippet(tmp_path, src, name="parquet_device.py")
    assert _rules(out) == ["host-sync-in-jit"]
    assert [f["line"] for f in out["findings"]] == [4]
    # tpu-lint 2.0: taint is package-wide — the old two-module
    # file-list scoping is gone, any module is checked
    out = _lint_snippet(tmp_path, src, name="some_module.py")
    assert _rules(out) == ["host-sync-in-jit"]
    assert [f["line"] for f in out["findings"]] == [4]


def test_rule_unlocked_shared_mutation(tmp_path):
    src = ("import threading\n"
           "class Store:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self.total = 0\n"
           "    def add(self, n):\n"
           "        with self._lock:\n"
           "            self.total += n\n"
           "    def reset(self):\n"
           "        self.total = 0\n")  # outside the lock: violation
    out = _lint_snippet(tmp_path, src, name="whatever.py")
    assert _rules(out) == ["unlocked-shared-mutation"]
    assert [f["line"] for f in out["findings"]] == [10]


def test_rule_unlocked_shared_mutation_acquire_style(tmp_path):
    """The PR 6 false negative (ISSUE 10 satellite): acquire()-style
    critical sections guarded nothing, so an augmented assignment
    outside the lock was invisible. The dataflow port flags it."""
    src = ("import threading\n"
           "class Store:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self.x = 0\n"
           "    def f(self):\n"
           "        self._lock.acquire()\n"
           "        self.x += 1\n"
           "        self._lock.release()\n"
           "    def g(self):\n"
           "        self.x += 1\n")
    out = _lint_snippet(tmp_path, src, name="whatever.py")
    assert _rules(out) == ["unlocked-shared-mutation"]
    assert [f["line"] for f in out["findings"]] == [11]


def test_rule_exit_without_flush(tmp_path):
    out = _lint_snippet(tmp_path, (
        "import os\n"
        "def die():\n"
        "    os._exit(3)\n"), name="anything.py")
    assert _rules(out) == ["exit-without-flush"]
    out = _lint_snippet(tmp_path, (
        "import os\n"
        "def die(ring):\n"
        "    flush_worker_ring(ring)\n"
        "    os._exit(3)\n"), name="anything.py")
    assert out["findings"] == []


# --- allowlist syntax -------------------------------------------------------

def test_allowlist_same_line_and_line_above(tmp_path):
    src = ("import time\n"
           "def f(th, fut):\n"
           "    time.sleep(1)  # tpu-lint: allow[blocking-call-in-thread] poll loop\n"
           "    # tpu-lint: allow[blocking-call-in-thread] must drain\n"
           "    fut.result()\n"
           "    th.join()\n")
    out = _lint_snippet(tmp_path, src, name="pipeline.py")
    allowed = [f for f in out["findings"] if f["allowlisted"]]
    hard = [f for f in out["findings"] if not f["allowlisted"]]
    assert [f["line"] for f in allowed] == [3, 5]
    assert [f["allow_reason"] for f in allowed] == ["poll loop",
                                                    "must drain"]
    assert [f["line"] for f in hard] == [6]
    assert out["violations"] == 1


def test_allowlist_does_not_bleed_to_next_line(tmp_path):
    """A trailing allow on line N blesses line N only — a new violation
    directly below an allowlisted site must still fail the gate."""
    src = ("import time\n"
           "def f():\n"
           "    time.sleep(1)  # tpu-lint: allow[blocking-call-in-thread] poll\n"
           "    time.sleep(2)\n")
    out = _lint_snippet(tmp_path, src, name="cluster.py")
    assert out["violations"] == 1
    hard = [f for f in out["findings"] if not f["allowlisted"]]
    assert [f["line"] for f in hard] == [4]


def test_allowlist_requires_reason_and_matching_rule(tmp_path):
    src = ("import time\n"
           "def f():\n"
           "    time.sleep(1)  # tpu-lint: allow[blocking-call-in-thread]\n"
           "    time.sleep(2)  # tpu-lint: allow[wallclock-duration] wrong rule\n")
    out = _lint_snippet(tmp_path, src, name="cluster.py")
    assert out["violations"] == 2  # empty reason + wrong rule: both fatal


# --- baseline ratchet -------------------------------------------------------

def test_baseline_marks_known_findings_and_fails_new(tmp_path):
    src = ("import time\n"
           "def f():\n"
           "    time.sleep(1)\n")
    p = tmp_path / "cluster.py"
    p.write_text(src)
    out = lint_paths([str(p)])
    assert out["violations"] == 1
    fp = out["findings"][0]["fingerprint"]
    assert fp == finding_fingerprint(
        out["findings"][0]["rule"], out["findings"][0]["path"],
        out["findings"][0]["message"])
    # baselined: the same finding no longer counts
    out = lint_paths([str(p)], baseline={fp: 1})
    assert out["violations"] == 0 and out["baselined"] == 1
    assert out["findings"][0]["baselined"] is True
    # a NEW finding (second sleep) exceeds the accepted count and fails
    p.write_text(src + "    time.sleep(2)\n")
    out = lint_paths([str(p)], baseline={fp: 1})
    assert out["violations"] == 1 and out["baselined"] == 1


def test_baseline_fingerprint_survives_line_drift(tmp_path):
    p = tmp_path / "cluster.py"
    p.write_text("import time\ndef f():\n    time.sleep(1)\n")
    fp1 = lint_paths([str(p)])["findings"][0]["fingerprint"]
    # shift the finding down 40 lines: same fingerprint
    p.write_text("import time\n" + "# pad\n" * 40
                 + "def f():\n    time.sleep(1)\n")
    fp2 = lint_paths([str(p)])["findings"][0]["fingerprint"]
    assert fp1 == fp2


def test_baseline_does_not_cover_allowlisted_or_other_rules(tmp_path):
    p = tmp_path / "cluster.py"
    p.write_text("import time\n"
                 "def f(th):\n"
                 "    th.join()\n")
    out = lint_paths([str(p)])
    fp = out["findings"][0]["fingerprint"]
    # a different rule's fingerprint never matches
    other = finding_fingerprint("wallclock-duration",
                                out["findings"][0]["path"], "x - y")
    out = lint_paths([str(p)], baseline={other: 5})
    assert out["violations"] == 1 and out["baselined"] == 0
    out = lint_paths([str(p)], baseline={fp: 1})
    assert out["violations"] == 0


# --- CLI --------------------------------------------------------------------

def test_cli_json_schema_and_exit_codes(tmp_path):
    import os
    root = os.path.dirname(package_dir())
    cli = os.path.join(root, "tools", "tpu_lint.py")
    r = subprocess.run([sys.executable, cli, "--json", "--baseline",
                        os.path.join(root, "tools",
                                     "tpu_lint_baseline.json")],
                       capture_output=True, text=True, cwd=root)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["schema"] == LINT_SCHEMA
    assert doc["violations"] == 0
    assert doc["allowlisted"] >= 1
    # every accepted fingerprint is spent exactly once (0 when the
    # baseline reaches the ideal empty state)
    assert doc["baselined"] == sum(load_baseline().values())
    assert set(doc["rules"]) >= {"lock-order-cycle", "ledger-leak-path",
                                 "blocking-under-lock",
                                 "host-sync-in-jit"}
    for f in doc["findings"]:
        assert f["fingerprint"]
    bad = tmp_path / "cluster.py"
    bad.write_text("import time\n"
                   "def f(th):\n"
                   "    th.join()\n")
    r = subprocess.run([sys.executable, cli, str(bad)],
                       capture_output=True, text=True, cwd=root)
    assert r.returncode == 1
    assert "blocking-call-in-thread" in r.stdout


def test_cli_write_baseline_roundtrip(tmp_path):
    import os
    root = os.path.dirname(package_dir())
    cli = os.path.join(root, "tools", "tpu_lint.py")
    out = tmp_path / "base.json"
    r = subprocess.run([sys.executable, cli, "--write-baseline",
                        str(out)],
                       capture_output=True, text=True, cwd=root)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(out.read_text())
    assert doc["schema"] == LINT_SCHEMA
    # the written baseline immediately yields a clean run
    r = subprocess.run([sys.executable, cli, "--baseline", str(out)],
                       capture_output=True, text=True, cwd=root)
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_lock_graph(tmp_path):
    import os
    root = os.path.dirname(package_dir())
    cli = os.path.join(root, "tools", "tpu_lint.py")
    r = subprocess.run([sys.executable, cli, "--lock-graph"],
                       capture_output=True, text=True, cwd=root)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["cycles"] == []
    assert "DeviceMemoryManager._lock" in doc["locks"]
    assert any(e["from"] == "SpillableBatch._state_lock"
               and e["to"] == "DeviceMemoryManager._lock"
               for e in doc["edges"])


def test_cli_check_docs():
    import os
    root = os.path.dirname(package_dir())
    cli = os.path.join(root, "tools", "tpu_lint.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, cli, "--check-docs"],
                       capture_output=True, text=True, cwd=root, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "in sync" in r.stdout
