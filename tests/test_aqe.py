"""AQE tests: stats-driven partition coalescing and skew splitting at
the materialized shuffle stage boundary (reference: AQE integration +
GpuShuffleCoalesceExec / skew join handling — SURVEY.md:161, 228)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec import HostBatchSourceExec
from spark_rapids_tpu.exec.aqe import (TpuAQEShuffleReadExec,
                                       plan_partition_groups)
from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow_cpu
from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
from spark_rapids_tpu.expr import UnresolvedColumn as col
from spark_rapids_tpu.planner import TpuOverrides
from spark_rapids_tpu.shuffle import HashPartitioning

from data_gen import IntegerGen, LongGen, StringGen, gen_table


# --- pure planning --------------------------------------------------------

def test_plan_groups_coalesces_small_runs():
    stats = [10, 10, 10, 100, 10, 10]
    groups = plan_partition_groups(stats, advisory=35, skew_factor=50,
                                   skew_threshold=1 << 40, coalesce=True)
    flat = [p for _, ms in groups for p in ms]
    assert flat == list(range(6))  # order preserved, nothing dropped
    assert ("coalesced", [0, 1, 2]) in groups
    assert ("coalesced", [4, 5]) in groups


def test_plan_groups_detects_skew():
    stats = [10, 10, 1000, 10]
    groups = plan_partition_groups(stats, advisory=50, skew_factor=5,
                                   skew_threshold=100, coalesce=True)
    kinds = {tuple(ms): k for k, ms in groups}
    assert kinds[(2,)] == "skewed"
    flat = [p for _, ms in groups for p in ms]
    assert flat == [0, 1, 2, 3]


def test_plan_groups_no_coalesce_flag():
    groups = plan_partition_groups([1, 1, 1], advisory=100, skew_factor=5,
                                   skew_threshold=1 << 40, coalesce=False)
    assert all(k == "plain" and len(ms) == 1 for k, ms in groups)


def test_plan_groups_empty_and_zero():
    assert plan_partition_groups([], 10, 5, 100, True) == []
    groups = plan_partition_groups([0, 0], 10, 5, 100, True)
    assert [p for _, ms in groups for p in ms] == [0, 1]


# --- end-to-end through the planner ---------------------------------------

def _skewed_source(n=4000, hot_frac=0.8, seed=7):
    """90% of rows share one key -> one hot partition."""
    rng = np.random.default_rng(seed)
    hot = rng.random(n) < hot_frac
    keys = np.where(hot, 3, rng.integers(0, 64, n)).astype(np.int32)
    vals = rng.integers(0, 10**6, n).astype(np.int64)
    rb = pa.record_batch({"k": pa.array(keys), "v": pa.array(vals)})
    return HostBatchSourceExec([rb])


def _aqe_conf(**extra):
    base = {
        "spark.sql.adaptive.enabled": "true",
        # tests run untunneled: let the local transport sync for stats
        "spark.rapids.sql.adaptive.freeStatsOnly": "false",
        # tiny thresholds so test-sized data triggers both paths
        "spark.sql.adaptive.advisoryPartitionSizeInBytes": "4096",
        "spark.sql.adaptive.skewJoin.skewedPartitionThresholdInBytes":
            "4096",
    }
    base.update(extra)
    return RapidsConf(base)


def test_aqe_inserted_by_planner_and_results_correct():
    conf = _aqe_conf()
    ex = TpuShuffleExchangeExec(HashPartitioning([col("k")], 8),
                                _skewed_source())
    from spark_rapids_tpu.exec.basic import TpuProjectExec
    from spark_rapids_tpu.expr import Alias, Add, Literal
    from spark_rapids_tpu import datatypes as dt
    top = TpuProjectExec([Alias(Add(col("v"), Literal(1, dt.INT64)),
                                "v1")], ex)
    plan = TpuOverrides(conf).apply(top)
    reader = plan.root.children[0]
    assert isinstance(reader, TpuAQEShuffleReadExec), plan.root
    got = plan.collect().to_pandas().sort_values("v1").reset_index(
        drop=True)
    want = collect_arrow_cpu(top).to_pandas().sort_values(
        "v1").reset_index(drop=True)
    import pandas.testing as pdt
    pdt.assert_frame_equal(got, want, check_dtype=False)
    kinds = [k for k, _ in reader.last_groups]
    assert "skewed" in kinds, reader.last_groups
    assert "coalesced" in kinds, reader.last_groups


def test_aqe_skew_split_bounds_batch_bytes():
    conf = _aqe_conf()
    ex = TpuShuffleExchangeExec(HashPartitioning([col("k")], 8),
                                _skewed_source())
    reader = TpuAQEShuffleReadExec(ex)
    ctx = ExecCtx(conf)
    batches = list(reader.execute(ctx))
    advisory = 4096
    skew = ctx.metrics[reader.node_label()]["numSkewSplits"].value
    assert skew > 0
    # skewed pieces were capacity-halved under the advisory byte bound
    # (plain/coalesced views keep the shared map-batch capacity)
    assert min(b.device_size_bytes() for b in batches) <= advisory
    # no rows lost or duplicated across the split/coalesce reshaping
    from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
    got = sorted(v for b in batches
                 for v in device_to_arrow(b).column("v").to_pylist())
    want = sorted(v for rb in collect_arrow_cpu(ex).to_batches()
                  for v in rb.column(1).to_pylist())
    assert got == want


def test_aqe_disabled_no_reader():
    ex = TpuShuffleExchangeExec(HashPartitioning([col("k")], 4),
                                _skewed_source(500))
    from spark_rapids_tpu.exec.basic import TpuFilterExec
    from spark_rapids_tpu.expr import GreaterThan, Literal
    from spark_rapids_tpu import datatypes as dt
    top = TpuFilterExec(GreaterThan(col("v"), Literal(0, dt.INT64)), ex)
    plan = TpuOverrides(RapidsConf(
        {"spark.sql.adaptive.enabled": "false"})).apply(top)
    assert not isinstance(plan.root.children[0], TpuAQEShuffleReadExec)


def test_aqe_default_on_free_stats_engage_local():
    """AQE defaults ON and the local transport now records writer-side
    partition stats during the map phase, so the adaptive reader
    ENGAGES on the default path under freeStatsOnly (ROADMAP item 4:
    adaptivity on the default path with zero read-side syncs)."""
    ex = TpuShuffleExchangeExec(HashPartitioning([col("k")], 4),
                                _skewed_source(500))
    from spark_rapids_tpu.exec.basic import TpuFilterExec
    from spark_rapids_tpu.expr import GreaterThan, Literal
    from spark_rapids_tpu import datatypes as dt
    top = TpuFilterExec(GreaterThan(col("v"), Literal(-1, dt.INT64)), ex)
    plan = TpuOverrides(RapidsConf()).apply(top)
    reader = plan.root.children[0]
    assert isinstance(reader, TpuAQEShuffleReadExec)
    got = plan.collect()
    # writer-side stats were served: the reader planned groups
    assert reader.last_groups is not None
    assert [p for _, ms in reader.last_groups for p in ms] == [0, 1, 2, 3]
    want = collect_arrow_cpu(top)
    assert sorted(got.column("v").to_pylist()) == \
        sorted(want.column("v").to_pylist())


def test_aqe_local_free_stats_skew_and_coalesce():
    """The skewed source through the LOCAL transport with tiny
    thresholds: writer-side stats alone (freeStatsOnly left at the
    default TRUE) must be enough for both skew split and coalesce to
    fire."""
    conf = _aqe_conf()
    conf.set("spark.rapids.sql.adaptive.freeStatsOnly", "true")
    ex = TpuShuffleExchangeExec(HashPartitioning([col("k")], 8),
                                _skewed_source())
    reader = TpuAQEShuffleReadExec(ex)
    ctx = ExecCtx(conf)
    batches = list(reader.execute(ctx))
    kinds = {k for k, _ in reader.last_groups}
    assert "skewed" in kinds and "coalesced" in kinds, reader.last_groups
    from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
    got = sorted(v for b in batches
                 for v in device_to_arrow(b).column("v").to_pylist())
    want = sorted(v for rb in collect_arrow_cpu(ex).to_batches()
                  for v in rb.column(1).to_pylist())
    assert got == want


def test_aqe_local_stats_off_without_adaptive():
    """With AQE disabled the exchange never enables writer-side
    recording, so a later free-stats probe reports None (no silent
    write-path overhead when nobody will read the stats)."""
    conf = RapidsConf({"spark.sql.adaptive.enabled": "false"})
    ex = TpuShuffleExchangeExec(HashPartitioning([col("k")], 4),
                                _skewed_source(400))
    ctx = ExecCtx(conf)
    handle = ex.materialize(ctx)
    try:
        assert handle.partition_stats(free_only=True) is None
    finally:
        handle.close()


def test_aqe_host_transport_free_stats_no_device_touch(monkeypatch):
    """The host transport's writer-side byte counts serve
    partition_stats(free_only=True) WITHOUT touching device memory or
    syncing: assert by making every device readback explode during the
    stats call, then check coalesce/skew planning over those stats."""
    import jax
    from spark_rapids_tpu.shuffle.host import HostShuffleTransport
    conf = _aqe_conf()
    t = HostShuffleTransport(conf, threads=0)
    try:
        ex = TpuShuffleExchangeExec(HashPartitioning([col("k")], 8),
                                    _skewed_source(), transport=t)
        ctx = ExecCtx(conf)
        handle = ex.materialize(ctx)

        def boom(*a, **k):
            raise AssertionError("free stats touched the device")
        monkeypatch.setattr(jax, "device_get", boom)
        monkeypatch.setattr(jax, "block_until_ready", boom)
        stats = handle.partition_stats(free_only=True)
        monkeypatch.undo()
        assert stats is not None and len(stats) == 8
        assert sum(stats) > 0
        # the hot partition dominates: planning over these stats splits
        groups = plan_partition_groups(stats, advisory=4096,
                                       skew_factor=5,
                                       skew_threshold=4096,
                                       coalesce=True)
        assert any(k == "skewed" for k, _ in groups), (stats, groups)
        handle.close()
    finally:
        t.close()


def test_aqe_host_transport_stats_via_reader():
    """End to end: exchange on the HOST transport + adaptive reader
    under default freeStatsOnly — stats engage, rows exact."""
    from spark_rapids_tpu.shuffle.host import HostShuffleTransport
    conf = _aqe_conf()
    conf.set("spark.rapids.sql.adaptive.freeStatsOnly", "true")
    t = HostShuffleTransport(conf, threads=0)
    try:
        ex = TpuShuffleExchangeExec(HashPartitioning([col("k")], 8),
                                    _skewed_source(), transport=t)
        reader = TpuAQEShuffleReadExec(ex)
        ctx = ExecCtx(conf)
        batches = list(reader.execute(ctx))
        kinds = {k for k, _ in reader.last_groups}
        assert "skewed" in kinds, reader.last_groups
        from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
        got = sorted(v for b in batches
                     for v in device_to_arrow(b).column("v").to_pylist())
        want = sorted(v for rb in collect_arrow_cpu(ex).to_batches()
                      for v in rb.column(1).to_pylist())
        assert got == want
    finally:
        t.close()


# --- runtime join-strategy switch (VERDICT r4 #4) --------------------------

def _join_with_exchanges(n_stream=3000, n_build=50, nparts=4,
                         two_batches=False):
    from spark_rapids_tpu.exec.joins import TpuShuffledHashJoinExec
    rng = np.random.default_rng(3)
    fact = pa.record_batch({
        "fk": pa.array(rng.integers(0, n_build, n_stream)
                       .astype(np.int32)),
        "amt": pa.array(rng.integers(0, 1000, n_stream)
                        .astype(np.int64))})
    dim = pa.record_batch({
        "dk": pa.array(np.arange(n_build, dtype=np.int32)),
        "dv": pa.array(np.arange(n_build, dtype=np.int64) * 7)})
    fsrc = HostBatchSourceExec([fact.slice(0, n_stream // 2),
                                fact.slice(n_stream // 2)]
                               if two_batches else [fact])
    dsrc = HostBatchSourceExec([dim])
    lex = TpuShuffleExchangeExec(HashPartitioning([col("fk")], nparts),
                                 fsrc)
    rex = TpuShuffleExchangeExec(HashPartitioning([col("dk")], nparts),
                                 dsrc)
    return TpuShuffledHashJoinExec([col("fk")], [col("dk")], "inner",
                                   lex, rex)


def test_aqe_join_demotes_to_broadcast():
    """Small build side -> the shuffled join re-plans to broadcast at
    runtime: the stream-side exchange is skipped, results unchanged."""
    from spark_rapids_tpu.exec.aqe import TpuAQEJoinExec
    join = _join_with_exchanges()
    plan = TpuOverrides(RapidsConf()).apply(join)
    assert isinstance(plan.root, TpuAQEJoinExec), plan.root
    got = plan.collect()
    assert plan.root.last_strategy == "broadcast"
    m = plan.last_ctx.metrics[plan.root.node_label()]
    assert m["numBroadcastDemotions"].value == 1
    want = collect_arrow_cpu(join)
    assert sorted(tuple(d.values()) for d in got.to_pylist()) == \
        sorted(tuple(d.values()) for d in want.to_pylist())


def test_aqe_join_keeps_shuffled_over_threshold():
    from spark_rapids_tpu.exec.aqe import TpuAQEJoinExec
    join = _join_with_exchanges()
    conf = RapidsConf({"spark.sql.autoBroadcastJoinThreshold": "1"})
    plan = TpuOverrides(conf).apply(join)
    assert isinstance(plan.root, TpuAQEJoinExec)
    got = plan.collect()
    assert plan.root.last_strategy == "shuffled"
    want = collect_arrow_cpu(join)
    assert sorted(tuple(d.values()) for d in got.to_pylist()) == \
        sorted(tuple(d.values()) for d in want.to_pylist())


def test_aqe_exchange_reuse_self_join():
    """The SAME exchange instance consumed by both join sides
    materializes once (ReusedExchangeExec analog): the transport sees
    one shuffle id; results match the oracle."""
    from spark_rapids_tpu.exec.joins import TpuShuffledHashJoinExec
    rng = np.random.default_rng(4)
    rb = pa.record_batch({
        "k": pa.array(np.arange(40, dtype=np.int32)),
        "v": pa.array(rng.integers(0, 100, 40).astype(np.int64))})
    src = HostBatchSourceExec([rb])
    ex = TpuShuffleExchangeExec(HashPartitioning([col("k")], 4), src)
    join = TpuShuffledHashJoinExec([col("k")], [col("k")], "inner",
                                   ex, ex)
    plan = TpuOverrides(RapidsConf()).apply(join)
    assert ex.shared, "planner must flag the doubly-consumed exchange"
    calls = []
    orig = TpuShuffleExchangeExec.materialize

    def counting(self, ctx):
        calls.append(1)
        return orig(self, ctx)
    TpuShuffleExchangeExec.materialize = counting
    try:
        got = plan.collect()
    finally:
        TpuShuffleExchangeExec.materialize = orig
    assert len(calls) == 1, "shared exchange must materialize once"
    want = collect_arrow_cpu(join)
    assert sorted(tuple(d.values()) for d in got.to_pylist()) == \
        sorted(tuple(d.values()) for d in want.to_pylist())


def test_aqe_passthrough_without_stats():
    class NoStatsExchange(TpuShuffleExchangeExec):
        def materialize(self, ctx):
            h = super().materialize(ctx)
            h.transport = _NoStats(h.transport)
            return h

    class _NoStats:
        def __init__(self, inner):
            self._inner = inner

        def read_partition(self, sid, p):
            return self._inner.read_partition(sid, p)

        def unregister_shuffle(self, sid):
            return self._inner.unregister_shuffle(sid)

    ex = NoStatsExchange(HashPartitioning([col("k")], 4),
                         _skewed_source(600))
    reader = TpuAQEShuffleReadExec(ex)
    ctx = ExecCtx(_aqe_conf())
    from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
    got = sorted(v for b in reader.execute(ctx)
                 for v in device_to_arrow(b).column("v").to_pylist())
    want = sorted(v for rb in collect_arrow_cpu(ex).to_batches()
                  for v in rb.column(1).to_pylist())
    assert got == want
    assert reader.last_groups is None  # passthrough path
