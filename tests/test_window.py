"""Window operator tests via the dual-run harness (reference:
window_function_test.py — SURVEY.md §4.1; capability-built, mount empty).

Covers ranking functions, running/rolling/whole-partition frames, rows vs
range semantics (peers), lag/lead, first/last, nulls in order keys, empty
frames, multi-batch inputs, and the planner fallback for frames the
device does not support (range literal offsets, stddev over window)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.exec import HostBatchSourceExec
from spark_rapids_tpu.exec.sort import SortOrder
from spark_rapids_tpu.exec.window import TpuWindowExec
from spark_rapids_tpu.expr import (Alias, DenseRank, Lag, Lead, Literal,
                                   NTile, PercentRank, Rank, RowNumber,
                                   UnresolvedColumn as col, WindowExpression,
                                   WindowFrame)
from spark_rapids_tpu.expr.aggregates import (Average, Count, First, Last,
                                              Max, Min, StddevSamp, Sum)

from asserts import assert_tpu_and_cpu_plan_equal
from data_gen import (DecimalGen, DoubleGen, IntegerGen, LongGen,
                      StringGen, gen_table)


def source(gens, n=256, seed=1234, names=None, n_batches=1):
    return HostBatchSourceExec(
        [gen_table(gens, n, seed + i, names) for i in range(n_batches)])


def part_order_source(n=200, seed=1234, **kw):
    """3 columns: c0 partition key (small card), c1 order key (with ties
    + nulls), c2 values (with nulls)."""
    return source([IntegerGen(min_val=0, max_val=4, null_frac=0.1),
                   IntegerGen(min_val=0, max_val=20, null_frac=0.15),
                   LongGen(min_val=-1000, max_val=1000, null_frac=0.2)],
                  n=n, seed=seed, **kw)


def win(func, frame=None, partition=("c0",), order=("c1",)):
    return Alias(WindowExpression(
        func, [col(c) for c in partition],
        [SortOrder(col(c)) for c in order], frame), "w")


RANKING = [RowNumber(), Rank(), DenseRank(), PercentRank(), NTile(3),
           NTile(7)]


@pytest.mark.parametrize("func", RANKING,
                         ids=lambda f: f.pretty_name().lower())
def test_ranking(func):
    plan = TpuWindowExec([win(func)], part_order_source())
    assert_tpu_and_cpu_plan_equal(plan)


def test_ranking_no_partition():
    plan = TpuWindowExec(
        [win(RowNumber(), partition=()), ],
        part_order_source())
    assert_tpu_and_cpu_plan_equal(plan)


def test_rank_order_desc_nulls_last():
    we = Alias(WindowExpression(
        Rank(), [col("c0")],
        [SortOrder(col("c1"), ascending=False, nulls_first=False)]), "w")
    plan = TpuWindowExec([we], part_order_source())
    assert_tpu_and_cpu_plan_equal(plan)


AGG_FRAMES = [
    None,                              # default RANGE UNBOUNDED..CURRENT
    WindowFrame("rows", None, 0),      # running
    WindowFrame("rows", None, None),   # whole partition
    WindowFrame("rows", -2, 0),
    WindowFrame("rows", -1, 1),
    WindowFrame("rows", 0, None),
    WindowFrame("rows", 2, 4),         # empty near partition end
    WindowFrame("range", None, None),
    WindowFrame("range", 0, None),
    WindowFrame("range", 0, 0),        # peer group
]


@pytest.mark.parametrize("frame", AGG_FRAMES,
                         ids=lambda f: "default" if f is None
                         else f.describe().lower().replace(" ", "_"))
@pytest.mark.parametrize("func_cls", [Sum, Count, Min, Max, Average],
                         ids=lambda c: c.__name__.lower())
def test_agg_window_frames(func_cls, frame):
    plan = TpuWindowExec([win(func_cls(col("c2")), frame)],
                         part_order_source())
    assert_tpu_and_cpu_plan_equal(plan, approx_float=True)


def test_count_star_window():
    from spark_rapids_tpu.expr.aggregates import Count as C
    plan = TpuWindowExec([win(C(), WindowFrame("rows", -3, 3))],
                         part_order_source())
    assert_tpu_and_cpu_plan_equal(plan)


def test_sum_window_double_and_decimal():
    src = source([IntegerGen(min_val=0, max_val=3, null_frac=0.0),
                  IntegerGen(min_val=0, max_val=9, null_frac=0.0),
                  DoubleGen(null_frac=0.2), DecimalGen(null_frac=0.2)],
                 n=150)
    for c in ("c2", "c3"):
        plan = TpuWindowExec([win(Sum(col(c)))], src)
        assert_tpu_and_cpu_plan_equal(plan, approx_float=True)


def test_multiple_window_exprs_one_spec():
    plan = TpuWindowExec(
        [Alias(WindowExpression(RowNumber(), [col("c0")],
                                [SortOrder(col("c1"))]), "rn"),
         Alias(WindowExpression(Sum(col("c2")), [col("c0")],
                                [SortOrder(col("c1"))]), "s"),
         Alias(WindowExpression(Min(col("c2")), [col("c0")],
                                [SortOrder(col("c1"))],
                                WindowFrame("rows", -3, 0)), "m")],
        part_order_source())
    assert_tpu_and_cpu_plan_equal(plan)


def test_mixed_specs_rejected():
    with pytest.raises(ValueError):
        TpuWindowExec(
            [Alias(WindowExpression(RowNumber(), [col("c0")],
                                    [SortOrder(col("c1"))]), "a"),
             Alias(WindowExpression(RowNumber(), [col("c1")],
                                    [SortOrder(col("c0"))]), "b")],
            part_order_source())


@pytest.mark.parametrize("fn", ["lag", "lead"])
def test_lag_lead(fn):
    cls = Lag if fn == "lag" else Lead
    for f in (cls(col("c2"), 1), cls(col("c2"), 3),
              cls(col("c2"), 2, Literal(-99, dt.INT64))):
        plan = TpuWindowExec([win(f)], part_order_source())
        assert_tpu_and_cpu_plan_equal(plan)


def test_lag_strings():
    src = source([IntegerGen(min_val=0, max_val=3, null_frac=0.0),
                  LongGen(nullable=False), StringGen(max_len=6)],
                 n=120)
    plan = TpuWindowExec([win(Lag(col("c2"), 1))], src)
    assert_tpu_and_cpu_plan_equal(plan)


@pytest.mark.parametrize("ignore_nulls", [False, True])
@pytest.mark.parametrize("cls", [First, Last],
                         ids=["first", "last"])
def test_first_last_window(cls, ignore_nulls):
    for frame in (None, WindowFrame("rows", -2, 2),
                  WindowFrame("rows", 0, None)):
        plan = TpuWindowExec(
            [win(cls(col("c2"), ignore_nulls=ignore_nulls), frame)],
            part_order_source())
        assert_tpu_and_cpu_plan_equal(plan)


def test_window_multi_batch():
    plan = TpuWindowExec([win(Sum(col("c2")))],
                         part_order_source(n=100, n_batches=3))
    assert_tpu_and_cpu_plan_equal(plan)


def test_window_float_order_keys():
    # NaN / -0.0 / nulls in the order key: peers must match the oracle
    src = source([IntegerGen(min_val=0, max_val=2, null_frac=0.0),
                  DoubleGen(null_frac=0.2),
                  LongGen(min_val=0, max_val=100, null_frac=0.1)], n=150)
    plan = TpuWindowExec([win(Rank())], src)
    assert_tpu_and_cpu_plan_equal(plan)
    plan = TpuWindowExec([win(Sum(col("c2")))], src)
    assert_tpu_and_cpu_plan_equal(plan)


def test_window_string_partition_keys():
    src = source([StringGen(max_len=4, null_frac=0.1),
                  IntegerGen(min_val=0, max_val=9, null_frac=0.0),
                  LongGen(min_val=-50, max_val=50, null_frac=0.1)], n=150)
    plan = TpuWindowExec([win(RowNumber())], src)
    assert_tpu_and_cpu_plan_equal(plan)
    plan = TpuWindowExec([win(Average(col("c2")))], src)
    assert_tpu_and_cpu_plan_equal(plan, approx_float=True)


# --- planner integration / fallback ---------------------------------------

def _planner_dual_run(plan, expect_fallback):
    from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow_cpu
    from spark_rapids_tpu.planner import overrides
    pp = overrides(plan)
    fb = pp.fallback_nodes()
    if expect_fallback:
        assert "WindowExec" in fb, fb
    else:
        assert "WindowExec" not in fb, fb
    got = pp.collect()
    want = collect_arrow_cpu(plan, ExecCtx())
    assert got.to_pylist() == want.to_pylist()


def test_planner_window_on_device():
    plan = TpuWindowExec([win(Sum(col("c2")))], part_order_source(n=80))
    _planner_dual_run(plan, expect_fallback=False)


def test_planner_range_offset_on_device_now():
    # RANGE with literal offsets runs on device since round 5 (the
    # compound-searchsorted bounds); the old CPU-only gate is gone
    plan = TpuWindowExec(
        [win(Sum(col("c2")), WindowFrame("range", -5, 5))],
        part_order_source(n=80))
    _planner_dual_run(plan, expect_fallback=False)


def test_planner_range_offset_64bit_key_falls_back():
    # ...but a 64-bit order key exceeds the 32-bit compound lane
    plan = TpuWindowExec(
        [win(Sum(col("c1")), WindowFrame("range", -5, 5),
             order=("c2",))],
        part_order_source(n=80))
    _planner_dual_run(plan, expect_fallback=True)


def test_planner_stddev_window_on_device_now():
    # stddev/variance over windows run on device since round 5; the
    # sum-of-squares path differs from the two-pass oracle by ulps,
    # so compare approximately (like all float window aggregates)
    import numpy as np
    from spark_rapids_tpu.planner import TpuOverrides
    from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow_cpu
    plan = TpuWindowExec([win(StddevSamp(col("c2")))],
                         part_order_source(n=80))
    pp = TpuOverrides().apply(plan)
    assert not pp.fallback_nodes(), pp.explain("NOT_ON_GPU")
    got = pp.collect().to_pandas()
    want = collect_arrow_cpu(plan, ExecCtx()).to_pandas()
    for c in got.columns:
        g, w = got[c].to_numpy(), want[c].to_numpy()
        if np.issubdtype(np.asarray(w).dtype, np.floating):
            assert np.allclose(g.astype(float), w.astype(float),
                               rtol=1e-9, equal_nan=True), c
        else:
            gn, wn = got[c].isna().to_numpy(), want[c].isna().to_numpy()
            assert (gn == wn).all(), c
            assert (g[~gn] == w[~wn]).all(), c


def test_stddev_variance_window_frames():
    from spark_rapids_tpu.expr.aggregates import (StddevPop, StddevSamp,
                                                  VariancePop,
                                                  VarianceSamp)
    for frame in (None, WindowFrame("rows", -3, 3),
                  WindowFrame("rows", None, 0),
                  WindowFrame("rows", 2, 5),   # empty near segment end
                  WindowFrame("range", 0, 0)):
        for cls in (StddevSamp, StddevPop, VarianceSamp, VariancePop):
            plan = TpuWindowExec([win(cls(col("c2")), frame)],
                                 part_order_source(n=160))
            assert_tpu_and_cpu_plan_equal(plan, approx_float=True,
                                          label=f"{cls.__name__}")


def test_window_out_of_core_bucketed():
    """Window at data >> budget: the bucketed (hash partition -> spill ->
    per-bucket window) path must match the oracle."""
    from spark_rapids_tpu.config import RapidsConf
    conf = RapidsConf({"spark.rapids.memory.device.budgetBytes": 1 << 13})
    plan = TpuWindowExec(
        [win(Sum(col("c2"))), win(RowNumber())],
        part_order_source(n=400, n_batches=4))
    assert_tpu_and_cpu_plan_equal(plan, conf=conf, ignore_order=True)


def test_window_sentinel_extremes():
    """Min over all-Long.MaxValue / Max over all-Long.MinValue frames must
    not collide with the argmin sentinel (code-review finding)."""
    imax, imin = (1 << 63) - 1, -(1 << 63)
    rb = pa.record_batch({
        "c0": pa.array([0, 0, 1, 1], pa.int32()),
        "c1": pa.array([1, 2, 1, 2], pa.int32()),
        "c2": pa.array([imax, imax, imin, imin], pa.int64())})
    src = HostBatchSourceExec([rb])
    for f in (Min(col("c2")), Max(col("c2"))):
        plan = TpuWindowExec([win(f)], src)
        assert_tpu_and_cpu_plan_equal(plan)


def test_wide_bounded_minmax_frame_on_device():
    """Bounded rows frames wider than the gather cap now run on device
    via the sparse-table range-argmin (VERDICT r4 weak #8: they used to
    fall back to CPU)."""
    from spark_rapids_tpu.expr.window import (MAX_GATHER_FRAME,
                                              WindowExpression,
                                              WindowFrame)
    from spark_rapids_tpu.expr.aggregates import Max, Min
    w = MAX_GATHER_FRAME * 2 + 7
    rbs = [gen_table([IntegerGen(min_val=0, max_val=3, null_frac=0),
                      LongGen(null_frac=0.1), IntegerGen(null_frac=0)],
                     4000, seed=21, names=["p", "v", "o"])]
    frame = WindowFrame("rows", -w // 2, w // 2)
    exprs = [
        Alias(WindowExpression(Min(col("v")), [col("p")],
                               [SortOrder(col("o")), SortOrder(col("v"))],
                               frame), "mn"),
        Alias(WindowExpression(Max(col("v")), [col("p")],
                               [SortOrder(col("o")), SortOrder(col("v"))],
                               frame), "mx"),
    ]
    plan = TpuWindowExec(exprs, HostBatchSourceExec(rbs))
    from spark_rapids_tpu.planner import TpuOverrides
    pp = TpuOverrides().apply(plan)
    assert not pp.fallback_nodes(), pp.explain("NOT_ON_GPU")
    assert_tpu_and_cpu_plan_equal(plan, ignore_order=True)


def test_range_frame_literal_offsets_on_device():
    """RANGE BETWEEN x PRECEDING AND y FOLLOWING over a numeric order
    key runs on device now (compound searchsorted bounds + sparse-table
    argmin): sum/count/min/max dual-run vs the oracle."""
    from spark_rapids_tpu.expr.window import (WindowExpression,
                                              WindowFrame)
    from spark_rapids_tpu.expr.aggregates import Count, Max, Min, Sum
    rbs = [gen_table([IntegerGen(min_val=0, max_val=3, nullable=False),
                      IntegerGen(min_val=0, max_val=500,
                                 null_frac=0.08),  # null order keys:
                      # a null row's frame is its null peers (Spark)
                      LongGen(null_frac=0.1)],
                     1200, seed=31, names=["p", "o", "v"])]
    for lo, hi in [(-25, 25), (-100, 0), (0, 40), (-7, -2), (3, 9),
                   (None, 30), (-30, None)]:
        frame = WindowFrame("range", lo, hi)
        exprs = [
            Alias(WindowExpression(Sum(col("v")), [col("p")],
                                   [SortOrder(col("o"))], frame), "s"),
            Alias(WindowExpression(Count(col("v")), [col("p")],
                                   [SortOrder(col("o"))], frame), "c"),
            Alias(WindowExpression(Min(col("v")), [col("p")],
                                   [SortOrder(col("o"))], frame), "mn"),
            Alias(WindowExpression(Max(col("v")), [col("p")],
                                   [SortOrder(col("o"))], frame), "mx"),
        ]
        plan = TpuWindowExec(exprs, HostBatchSourceExec(rbs))
        from spark_rapids_tpu.planner import TpuOverrides
        pp = TpuOverrides().apply(plan)
        assert not pp.fallback_nodes(), (lo, hi,
                                         pp.explain("NOT_ON_GPU"))
        assert_tpu_and_cpu_plan_equal(plan, ignore_order=True,
                                      label=f"range[{lo},{hi}]")


def test_range_frame_literal_offsets_gates():
    """Unsupported shapes (descending/nullable/64-bit keys) fall back
    with reasons and stay correct via the oracle."""
    from spark_rapids_tpu.expr.window import (WindowExpression,
                                              WindowFrame)
    from spark_rapids_tpu.expr.aggregates import Sum
    from spark_rapids_tpu.planner import TpuOverrides
    frame = WindowFrame("range", -5, 5)
    rbs = [gen_table([IntegerGen(min_val=0, max_val=2, null_frac=0),
                      LongGen(null_frac=0), LongGen(null_frac=0.1)],
                     300, seed=5, names=["p", "o64", "v"])]
    plan = TpuWindowExec(
        [Alias(WindowExpression(Sum(col("v")), [col("p")],
                                [SortOrder(col("o64"))], frame), "s")],
        HostBatchSourceExec(rbs))
    pp = TpuOverrides().apply(plan)
    assert pp.fallback_nodes()
    # the planner-placed (CPU) execution still answers like the oracle
    from spark_rapids_tpu.exec.base import collect_arrow_cpu
    got = pp.collect().to_pydict()
    want = collect_arrow_cpu(plan).to_pydict()
    assert got == want
