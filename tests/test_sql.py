"""SQL frontend unit tests: dialect edges, error model, EXPLAIN.

Covers the satellite checklist explicitly: quoted/keyword-colliding
identifiers, operator precedence (NOT/AND/OR, unary minus), NULL-
literal typing, CTE shadowing, ambiguous-column and unknown-function
negatives asserting the named error slugs, caret-annotated parse
errors, and event-log evidence for failures."""
import json
import os

import pyarrow as pa
import pytest

from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.sql import SqlAnalysisError, SqlParseError


@pytest.fixture
def s():
    sess = TpuSession()
    sess.register_table("t", pa.table({
        "k": pa.array([1, 1, 2, 2, 3], pa.int32()),
        "v": pa.array([10, 20, 30, 40, None], pa.int64()),
        "x": pa.array([1.5, -2.5, 3.5, None, 5.5], pa.float64()),
        "name": pa.array(["apple", "banana", "cherry", "apricot",
                          None]),
    }))
    sess.register_table("d", pa.table({
        "k": pa.array([1, 2, 3], pa.int32()),
        "label": pa.array(["one", "two", "three"]),
    }))
    # a table whose column names collide with keywords
    sess.register_table("kw", pa.table({
        "order": pa.array([3, 1, 2], pa.int32()),
        "select": pa.array(["a", "b", "c"]),
    }))
    return sess


def rows(df):
    return df.collect().to_pylist()


# --- dialect edges --------------------------------------------------------

def test_quoted_keyword_identifiers(s):
    got = rows(s.sql('SELECT "order", `select` FROM kw '
                     'ORDER BY "order"'))
    assert got == [{"order": 1, "select": "b"},
                   {"order": 2, "select": "c"},
                   {"order": 3, "select": "a"}]


def test_reserved_word_unquoted_is_parse_error(s):
    with pytest.raises(SqlParseError):
        s.sql("SELECT order FROM kw")


def test_not_and_or_precedence(s):
    # NOT binds tighter than AND, AND tighter than OR:
    # a OR b AND NOT c == a OR (b AND (NOT c))
    got = rows(s.sql(
        "SELECT k FROM t WHERE k = 3 OR k = 1 AND NOT v = 20 "
        "ORDER BY k, v"))
    assert [r["k"] for r in got] == [1, 3]


def test_unary_minus_precedence(s):
    got = rows(s.sql("SELECT -2 + 3 AS a, 2 * -3 AS b, -(1 + 2) AS c"))
    assert got == [{"a": 1, "b": -6, "c": -3}]


def test_comparison_chain_and_between(s):
    got = rows(s.sql(
        "SELECT v FROM t WHERE v BETWEEN 15 AND 35 ORDER BY v"))
    assert [r["v"] for r in got] == [20, 30]
    got = rows(s.sql(
        "SELECT v FROM t WHERE v NOT BETWEEN 15 AND 35 ORDER BY v"))
    assert [r["v"] for r in got] == [10, 40]


def test_null_literal_typing(s):
    # NULL adopts the branch/sibling type instead of staying NullType
    got = s.sql("SELECT CASE WHEN v > 25 THEN NULL ELSE name END AS n, "
                "coalesce(v, NULL, -1) AS c FROM t ORDER BY k, v") \
        .collect()
    assert got.schema.field("n").type == pa.string()
    assert got.schema.field("c").type == pa.int64()
    assert got.to_pylist()[4]["c"] == -1  # v NULL -> -1


def test_null_comparisons_and_in(s):
    got = rows(s.sql("SELECT k FROM t WHERE v IS NULL"))
    assert [r["k"] for r in got] == [3]
    got = rows(s.sql(
        "SELECT k, v IN (10, 40, NULL) AS m FROM t ORDER BY k, v"))
    # null-in-list semantics: non-match -> NULL, match -> TRUE
    assert [r["m"] for r in got] == [True, None, None, True, None]


def test_cte_shadowing(s):
    # a CTE named like a catalog table shadows it...
    got = rows(s.sql(
        "WITH t AS (SELECT k + 100 AS k FROM d) "
        "SELECT k FROM t ORDER BY k"))
    assert [r["k"] for r in got] == [101, 102, 103]
    # ...and an inner WITH shadows an outer CTE of the same name
    got = rows(s.sql(
        "WITH c AS (SELECT 1 AS a), "
        "outerq AS (WITH c AS (SELECT 2 AS a) SELECT a FROM c) "
        "SELECT a FROM outerq"))
    assert got == [{"a": 2}]


def test_cte_multi_reference_and_chaining(s):
    got = rows(s.sql(
        "WITH base AS (SELECT k, v FROM t WHERE v IS NOT NULL), "
        "agg AS (SELECT k, SUM(v) AS sv FROM base GROUP BY k) "
        "SELECT a.k, a.sv, b.sv AS other "
        "FROM agg a JOIN agg b ON a.k = b.k ORDER BY a.k"))
    assert [r["sv"] for r in got] == [30, 70]
    assert [r["other"] for r in got] == [30, 70]


def test_string_ops_and_concat(s):
    got = rows(s.sql(
        "SELECT upper(name) || '!' AS u FROM t "
        "WHERE name LIKE 'ap%' ORDER BY name"))
    assert [r["u"] for r in got] == ["APPLE!", "APRICOT!"]


def test_distinct(s):
    got = rows(s.sql("SELECT DISTINCT k FROM t ORDER BY k"))
    assert [r["k"] for r in got] == [1, 2, 3]


def test_join_family(s):
    # left outer: unmatched right side is NULL
    got = rows(s.sql(
        "SELECT t.k, label FROM t LEFT JOIN d ON t.k = d.k AND "
        "d.k < 3 ORDER BY t.k, v"))
    assert [r["label"] for r in got] == ["one", "one", "two", "two",
                                        None]
    got = rows(s.sql(
        "SELECT k FROM d LEFT ANTI JOIN t ON d.k = t.k AND v >= 30 "
        "ORDER BY k"))
    assert [r["k"] for r in got] == [1, 3]


def test_order_by_expression_not_in_select(s):
    # sort key outside the output plans the sort under the projection
    got = rows(s.sql("SELECT name FROM t WHERE v IS NOT NULL "
                     "ORDER BY v DESC LIMIT 2"))
    assert [r["name"] for r in got] == ["apricot", "cherry"]


def test_group_by_position_and_alias(s):
    got = rows(s.sql("SELECT k * 10 AS kk, COUNT(*) AS n FROM t "
                     "GROUP BY 1 ORDER BY kk"))
    assert got == [{"kk": 10, "n": 2}, {"kk": 20, "n": 2},
                   {"kk": 30, "n": 1}]
    got2 = rows(s.sql("SELECT k * 10 AS kk, COUNT(*) AS n FROM t "
                      "GROUP BY kk ORDER BY kk"))
    assert got2 == got


def test_window_frame_rows(s):
    got = rows(s.sql(
        "SELECT k, v, SUM(v) OVER (ORDER BY k, v ROWS BETWEEN "
        "1 PRECEDING AND CURRENT ROW) AS rsum FROM t "
        "WHERE v IS NOT NULL ORDER BY k, v"))
    assert [r["rsum"] for r in got] == [10, 30, 50, 70]


def test_date_literal(s):
    got = rows(s.sql("SELECT DATE '2001-03-04' AS d"))
    import datetime
    assert got == [{"d": datetime.date(2001, 3, 4)}]


# --- negatives: named slugs -----------------------------------------------

def test_ambiguous_column_negative(s):
    with pytest.raises(SqlAnalysisError) as ei:
        s.sql("SELECT k FROM t JOIN d ON t.k = d.k")
    assert ei.value.slug == "sql_analysis_error"
    assert ei.value.detail == "ambiguous_column"
    assert ei.value.line > 0 and ei.value.col > 0


def test_unknown_function_negative(s):
    with pytest.raises(SqlAnalysisError) as ei:
        s.sql("SELECT frobnicate(k) FROM t")
    assert ei.value.slug == "sql_analysis_error"
    assert ei.value.detail == "unknown_function"


def test_unknown_column_negative(s):
    with pytest.raises(SqlAnalysisError) as ei:
        s.sql("SELECT nope FROM t")
    assert ei.value.detail == "unknown_column"


def test_unknown_table_negative(s):
    with pytest.raises(SqlAnalysisError) as ei:
        s.sql("SELECT 1 FROM missing_table")
    assert ei.value.detail == "unknown_table"


def test_missing_aggregation_negative(s):
    with pytest.raises(SqlAnalysisError) as ei:
        s.sql("SELECT v, COUNT(*) FROM t GROUP BY k")
    assert ei.value.detail == "missing_aggregation"


def test_aggregate_in_where_negative(s):
    with pytest.raises(SqlAnalysisError) as ei:
        s.sql("SELECT k FROM t WHERE SUM(v) > 10")
    assert ei.value.detail == "misplaced_aggregate"


def test_count_distinct_unsupported(s):
    with pytest.raises(SqlAnalysisError) as ei:
        s.sql("SELECT COUNT(DISTINCT k) FROM t")
    assert ei.value.detail == "unsupported_feature"


def test_join_without_on_is_parse_error(s):
    # a forgotten ON must not silently become a cartesian product
    for q in ("SELECT t.k, label FROM t JOIN d",
              "SELECT t.k FROM t LEFT JOIN d",
              "SELECT t.k FROM t LEFT SEMI JOIN d"):
        with pytest.raises(SqlParseError, match="ON clause"):
            s.sql(q)
    # explicit cartesian product still available
    assert s.sql("SELECT t.k FROM t CROSS JOIN d").count() == 15


def test_malformed_hint_anchored_to_statement(s):
    with pytest.raises(SqlParseError) as ei:
        s.sql("SELECT /*+ UNIQUE(;) */ k\nFROM t")
    # location points at the hint token in the REAL statement, not
    # into the hint-body substring
    assert ei.value.line == 1 and ei.value.col == 8
    assert "malformed hint" in str(ei.value)


def test_parse_error_carries_caret_snippet(s):
    with pytest.raises(SqlParseError) as ei:
        s.sql("SELECT k\nFROM t\nWHERE k >")
    e = ei.value
    assert e.slug == "sql_parse_error"
    assert e.line == 3
    assert "^" in str(e) and "WHERE k >" in str(e)


def test_type_error_has_location(s):
    with pytest.raises(SqlAnalysisError) as ei:
        s.sql("SELECT k FROM t WHERE name > 5")
    assert ei.value.detail == "type_error"


# --- error evidence + EXPLAIN ---------------------------------------------

def test_sql_errors_logged_to_event_log(tmp_path):
    sess = TpuSession(conf={"spark.rapids.eventLog.dir": str(tmp_path)})
    sess.register_table("t", pa.table({"a": pa.array([1])}))
    with pytest.raises(SqlParseError):
        sess.sql("SELEKT 1")
    with pytest.raises(SqlAnalysisError):
        sess.sql("SELECT missing FROM t")
    events = []
    for fn in os.listdir(tmp_path):
        with open(os.path.join(tmp_path, fn)) as f:
            events += [json.loads(ln) for ln in f if ln.strip()]
    kinds = sorted(e["type"] for e in events)
    assert kinds == ["sql_analysis_error", "sql_parse_error"]
    ana = next(e for e in events if e["type"] == "sql_analysis_error")
    assert ana["detail"] == "unknown_column"
    assert ana["line"] == 1 and ana["col"] > 0
    assert "^" in ana["snippet"]
    assert "missing" in ana["sql"]


def test_explain_returns_plan_text_without_executing(s):
    text = s.sql("EXPLAIN SELECT k, SUM(v) AS sv FROM t GROUP BY k")
    assert isinstance(text, str)
    assert "will run on TPU" in text
    assert "HashAggregateExec" in text
    fmt = s.sql("EXPLAIN FORMATTED SELECT k FROM t ORDER BY k")
    assert isinstance(fmt, str)
    assert "SortExec" in fmt and "ProjectExec" in fmt


def test_sql_plans_flow_through_verifier(s):
    # SQL-originated plans hit the same pre-execution contract pass
    from spark_rapids_tpu.planner import TpuOverrides
    df = s.sql("SELECT t.k, label, SUM(v) AS sv FROM t "
               "JOIN d ON t.k = d.k GROUP BY t.k, label")
    pp = TpuOverrides(s.conf).apply(df._node)
    assert not pp.fallback_nodes()


def test_union_type_widening(s):
    got = s.sql("SELECT k FROM t UNION ALL SELECT v FROM t "
                "WHERE v IS NOT NULL ORDER BY 1").collect()
    assert got.schema.field("k").type == pa.int64()
    assert len(got) == 9


def test_hints_parse_and_are_inert_when_unknown(s):
    got = rows(s.sql("SELECT /*+ BROADCAST(d) */ t.k, label FROM t "
                     "JOIN d ON t.k = d.k WHERE v = 10"))
    assert got == [{"k": 1, "label": "one"}]
