"""Complex-type tests: struct/array/map bridge round-trips, access
expressions, explode, and planner fallbacks for nested-unsupported ops
(reference: struct_test.py / array_test.py / map_test.py /
generate_expr_test.py — SURVEY.md §4.1; capability-built, mount empty)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.columnar.arrow_bridge import (arrow_to_device,
                                                    device_to_arrow)
from spark_rapids_tpu.exec import HostBatchSourceExec, TpuGenerateExec, \
    TpuFilterExec, TpuProjectExec
from spark_rapids_tpu.expr import (Alias, CreateNamedStruct, GetArrayItem,
                                   GetStructField, GreaterThan, Literal,
                                   MapKeys, MapValues, Size,
                                   UnresolvedColumn as col)

from asserts import assert_tpu_and_cpu_plan_equal
from data_gen import (ArrayGen, DoubleGen, IntegerGen, LongGen, MapGen,
                      StringGen, StructGen, gen_table)


def source(gens, n=200, seed=77, names=None, n_batches=1):
    return HostBatchSourceExec(
        [gen_table(gens, n, seed + i, names) for i in range(n_batches)])


NESTED_GENS = [
    StructGen([("a", IntegerGen()), ("b", StringGen(max_len=6))]),
    ArrayGen(LongGen()),
    ArrayGen(StringGen(max_len=5)),
    ArrayGen(ArrayGen(IntegerGen(), max_len=3)),
    MapGen(StringGen(max_len=4, nullable=False), LongGen()),
    StructGen([("in", StructGen([("x", DoubleGen())]))]),
]


from asserts import _norm_nested as _norm


@pytest.mark.parametrize("gen", NESTED_GENS,
                         ids=lambda g: g.dtype.simple_string()[:40])
def test_nested_roundtrip(gen):
    rb = gen_table([gen, IntegerGen()], 300, seed=5)
    out = device_to_arrow(arrow_to_device(rb))
    assert _norm(out.to_pylist()) == _norm(rb.to_pylist())


@pytest.mark.parametrize("gen", NESTED_GENS,
                         ids=lambda g: g.dtype.simple_string()[:40])
def test_nested_filter_compaction(gen):
    """Filter over a batch with nested columns: the compaction gather
    must reorder struct children / array elements correctly."""
    plan = TpuFilterExec(
        GreaterThan(col("c1"), Literal(0, dt.INT32)),
        source([gen, IntegerGen(null_frac=0.0)], n=250))
    assert_tpu_and_cpu_plan_equal(plan)


def test_get_struct_field():
    g = StructGen([("a", IntegerGen()), ("b", StringGen(max_len=6)),
                   ("c", DoubleGen())])
    plan = TpuProjectExec(
        [Alias(GetStructField(col("c0"), "a"), "a"),
         Alias(GetStructField(col("c0"), "b"), "b"),
         Alias(GetStructField(col("c0"), "c"), "c")],
        source([g]))
    assert_tpu_and_cpu_plan_equal(plan)


def test_get_struct_field_nested():
    g = StructGen([("in", StructGen([("x", DoubleGen())]))])
    plan = TpuProjectExec(
        [Alias(GetStructField(GetStructField(col("c0"), "in"), "x"), "x")],
        source([g]))
    assert_tpu_and_cpu_plan_equal(plan)


@pytest.mark.parametrize("elem_gen", [LongGen(), StringGen(max_len=5),
                                      DoubleGen()],
                         ids=["long", "string", "double"])
def test_get_array_item(elem_gen):
    plan = TpuProjectExec(
        [Alias(GetArrayItem(col("c0"), Literal(0, dt.INT32)), "first"),
         Alias(GetArrayItem(col("c0"), Literal(2, dt.INT32)), "third"),
         Alias(GetArrayItem(col("c0"), col("c1")), "dyn")],
        source([ArrayGen(elem_gen), IntegerGen(min_val=-1, max_val=5,
                                               null_frac=0.1)]))
    assert_tpu_and_cpu_plan_equal(plan)


def test_create_named_struct():
    plan = TpuProjectExec(
        [Alias(CreateNamedStruct(["x", "y"], [col("c0"), col("c1")]),
               "s")],
        source([IntegerGen(), StringGen(max_len=5)]))
    assert_tpu_and_cpu_plan_equal(plan)


def test_size_and_map_projections():
    plan = TpuProjectExec(
        [Alias(Size(col("c0")), "asz"), Alias(Size(col("c1")), "msz"),
         Alias(MapKeys(col("c1")), "ks"),
         Alias(MapValues(col("c1")), "vs")],
        source([ArrayGen(LongGen()),
                MapGen(StringGen(max_len=4, nullable=False), LongGen())]))
    assert_tpu_and_cpu_plan_equal(plan)


# --- explode ---------------------------------------------------------------

@pytest.mark.parametrize("outer", [False, True], ids=["inner", "outer"])
@pytest.mark.parametrize("position", [False, True], ids=["explode",
                                                         "posexplode"])
def test_explode_array(outer, position):
    plan = TpuGenerateExec(col("c0"),
                           source([ArrayGen(LongGen()), IntegerGen(),
                                   StringGen(max_len=6)]),
                           outer=outer, position=position)
    assert_tpu_and_cpu_plan_equal(plan)


def test_explode_string_elements():
    plan = TpuGenerateExec(col("c0"),
                           source([ArrayGen(StringGen(max_len=8)),
                                   LongGen()]))
    assert_tpu_and_cpu_plan_equal(plan)


def test_explode_map():
    plan = TpuGenerateExec(
        col("c0"),
        source([MapGen(StringGen(max_len=4, nullable=False), LongGen()),
                IntegerGen()]),
        outer=True)
    assert_tpu_and_cpu_plan_equal(plan)


def test_explode_multi_batch():
    plan = TpuGenerateExec(col("c0"),
                           source([ArrayGen(IntegerGen()), LongGen()],
                                  n=120, n_batches=3))
    assert_tpu_and_cpu_plan_equal(plan)


def test_explode_then_filter_then_explode():
    """Nested pipeline: explode -> filter -> project (array access)."""
    src = source([ArrayGen(LongGen(), max_len=5), IntegerGen()])
    g = TpuGenerateExec(col("c0"), src)
    f = TpuFilterExec(GreaterThan(col("col"), Literal(0, dt.INT64)), g)
    plan = TpuProjectExec([Alias(col("col"), "v"), Alias(col("c1"), "k")],
                          f)
    assert_tpu_and_cpu_plan_equal(plan)


# --- planner fallbacks for nested-unsupported ops --------------------------

def test_nested_sort_falls_back():
    from spark_rapids_tpu.exec.sort import SortOrder, TpuSortExec
    from spark_rapids_tpu.planner import overrides
    plan = TpuSortExec([SortOrder(col("c0"))],
                       source([StructGen([("a", IntegerGen())]),
                               LongGen()], n=60))
    pp = overrides(plan)
    assert "SortExec" in pp.fallback_nodes()
    from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow_cpu
    got = pp.collect()
    want = collect_arrow_cpu(plan, ExecCtx())
    assert got.to_pylist() == want.to_pylist()


def test_nested_groupby_falls_back():
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.expr.aggregates import Count
    from spark_rapids_tpu.planner import overrides
    plan = TpuHashAggregateExec(
        [col("c0")], [Alias(Count(), "n")],
        source([StructGen([("a", IntegerGen(min_val=0, max_val=3))],
                          null_frac=0.0), LongGen()], n=60))
    pp = overrides(plan)
    assert "HashAggregateExec" in pp.fallback_nodes()


def test_explode_nested_passthrough_falls_back():
    from spark_rapids_tpu.planner import overrides
    plan = TpuGenerateExec(
        col("c0"),
        source([ArrayGen(LongGen()), ArrayGen(IntegerGen())], n=60))
    pp = overrides(plan)
    assert "GenerateExec" in pp.fallback_nodes()
    from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow_cpu
    got = pp.collect()
    want = collect_arrow_cpu(plan, ExecCtx())
    assert got.to_pylist() == want.to_pylist()


# --- round 4: nested types ride the engine (VERDICT r3 item 6) ------------

def test_device_concat_arrays_and_structs():
    from spark_rapids_tpu.exec.exchange import TpuCoalesceBatchesExec
    from data_gen import (ArrayGen, IntegerGen, LongGen, StringGen,
                          StructGen, gen_table)
    rbs = [gen_table([ArrayGen(IntegerGen(null_frac=0.2), null_frac=0.2),
                      StructGen([("a", LongGen()),
                                 ("b", StringGen(max_len=6))]),
                      StringGen(max_len=5)], 60, seed=30 + i)
           for i in range(4)]
    plan = TpuCoalesceBatchesExec(HostBatchSourceExec(rbs),
                                  target_rows=150)
    assert_tpu_and_cpu_plan_equal(plan)


def test_broadcast_of_array_column():
    from spark_rapids_tpu.exec.exchange import TpuBroadcastExchangeExec
    from data_gen import ArrayGen, DoubleGen, IntegerGen, gen_table
    rbs = [gen_table([IntegerGen(), ArrayGen(DoubleGen(null_frac=0.1))],
                     40, seed=60 + i) for i in range(3)]
    plan = TpuBroadcastExchangeExec(HostBatchSourceExec(rbs))
    assert_tpu_and_cpu_plan_equal(plan)


def test_ici_exchange_nested_lanes():
    import jax
    import numpy as np
    from jax.sharding import Mesh
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.shuffle import HashPartitioning
    from spark_rapids_tpu.shuffle.ici import IciShuffleTransport
    from data_gen import (ArrayGen, IntegerGen, LongGen, StringGen,
                          StructGen, gen_table)
    mesh = Mesh(np.array(jax.devices()[:8]), ("x",))
    rbs = [gen_table([IntegerGen(nullable=False),
                      ArrayGen(LongGen(null_frac=0.2), null_frac=0.15),
                      StructGen([("p", IntegerGen()),
                                 ("q", StringGen(max_len=7))])],
                     30, seed=80 + i) for i in range(8)]
    plan = TpuShuffleExchangeExec(
        HashPartitioning([col("c0")], 8), HostBatchSourceExec(rbs),
        transport=IciShuffleTransport(mesh))
    assert_tpu_and_cpu_plan_equal(plan)


def test_explode_shuffle_agg_over_mesh():
    # THE done-criterion shape: array column scans to device, explodes,
    # rides the ICI exchange, aggregates — through the planner on the
    # mesh (SURVEY.md:179)
    import jax
    import numpy as np
    from jax.sharding import Mesh
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.exec.generate import TpuGenerateExec
    from spark_rapids_tpu.expr.aggregates import Count, Sum
    from spark_rapids_tpu.expr.base import Alias
    from spark_rapids_tpu.shuffle import HashPartitioning
    from spark_rapids_tpu.shuffle.ici import IciShuffleTransport
    from spark_rapids_tpu.planner import TpuOverrides
    from spark_rapids_tpu.exec.base import collect_arrow_cpu
    from data_gen import ArrayGen, IntegerGen, gen_table
    mesh = Mesh(np.array(jax.devices()[:8]), ("x",))
    rbs = [gen_table([ArrayGen(IntegerGen(min_val=0, max_val=12,
                                          null_frac=0.1),
                               null_frac=0.1)], 40, seed=90 + i,
                     names=["xs"]) for i in range(8)]
    gen = TpuGenerateExec(col("xs"), HostBatchSourceExec(rbs),
                          outer=False, element_name="x")
    ex = TpuShuffleExchangeExec(HashPartitioning([col("x")], 8), gen,
                                transport=IciShuffleTransport(mesh))
    agg = TpuHashAggregateExec([col("x")], [Alias(Count(), "n")], ex)
    plan = TpuOverrides().apply(agg)
    assert not plan.fallback_nodes(), plan.explain("ALL")
    got = plan.collect().to_pandas().sort_values("x").reset_index(
        drop=True)
    want = collect_arrow_cpu(agg).to_pandas().sort_values(
        "x").reset_index(drop=True)
    import pandas.testing as pdt
    pdt.assert_frame_equal(got, want, check_dtype=False)


def test_hive_partition_values_on_read(tmp_path):
    import pyarrow as pa
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession()
    tbl = pa.table({
        "k": pa.array([1, 2, 3, 4, 5, 6], pa.int64()),
        "region": pa.array(["eu", "us", "eu", "us", "eu", "us"]),
        "yr": pa.array([2023, 2023, 2024, 2024, 2023, 2024]),
    })
    df = s.create_dataframe(tbl)
    paths = df.write(str(tmp_path / "t"), partition_by=["region", "yr"])
    back = s.read_parquet(paths)
    got = back.collect().to_pandas().sort_values("k").reset_index(
        drop=True)
    assert sorted(got.columns) == ["k", "region", "yr"]
    want = tbl.to_pandas().sort_values("k").reset_index(drop=True)
    import pandas.testing as pdt
    pdt.assert_frame_equal(got[["k", "region", "yr"]],
                           want[["k", "region", "yr"]],
                           check_dtype=False)
    # partition type inference: yr came back integral
    assert str(got["yr"].dtype).startswith("int")
