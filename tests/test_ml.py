"""ML bridge + Mortgage ETL tests (reference: ColumnarRdd /
InternalColumnarRddConverter + Mortgage->XGBoost — SURVEY.md §3.5,
§2.2-F, BASELINE config 4)."""
import numpy as np
import pyarrow as pa

from spark_rapids_tpu.ml import columnar_rdd, to_feature_matrix, to_torch
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.tools.mortgage import (gen_mortgage,
                                             mortgage_features,
                                             train_logreg_jax)


def _session():
    return TpuSession(conf={"spark.sql.shuffle.partitions": "2"})


def test_columnar_rdd_exposes_device_columns():
    s = _session()
    df = s.create_dataframe(pa.table({
        "a": pa.array([1, 2, None, 4], pa.int64()),
        "b": pa.array([0.5, 1.5, 2.5, 3.5])}))
    batches = list(columnar_rdd(df))
    assert batches
    import jax
    b0 = batches[0]
    assert isinstance(b0["a"], jax.Array)  # device handle, no rows
    valid = np.asarray(jax.device_get(b0["a__valid"]))
    assert valid[:4].tolist() == [True, True, False, True]


def test_mortgage_etl_places_on_device_and_trains():
    s = _session()
    tables = gen_mortgage(n_loans=800, seed=3)
    feats, feature_cols = mortgage_features(s, tables)
    # the ETL itself is fully accelerated (joins/aggs/casts/hash)
    pp = feats._plan()
    assert pp.fallback_nodes() == [], pp.explain("NOT_ON_GPU")
    X, y, live = to_feature_matrix(feats, feature_cols, "label")
    assert X.shape[1] == len(feature_cols)
    import jax
    n_live = int(np.asarray(jax.device_get(live)).sum())
    assert n_live == 800
    w, b, losses = train_logreg_jax(X, y, live, steps=40)
    # learning happened on the device-resident features
    assert losses[-1] < losses[0] * 0.97, losses[::10]
    # the learned model beats the base rate (signal is dti/score-driven)
    yl = np.asarray(jax.device_get(y))[
        np.asarray(jax.device_get(live))]
    base = max(yl.mean(), 1 - yl.mean())
    import jax.numpy as jnp
    n_live_f = jnp.maximum(jnp.sum(live.astype(jnp.float32)), 1.0)
    mu = jnp.sum(jnp.where(live[:, None], X, 0), axis=0) / n_live_f
    sd = jnp.sqrt(jnp.sum(jnp.where(live[:, None], (X - mu) ** 2, 0),
                          axis=0) / n_live_f) + 1e-6
    p = jax.nn.sigmoid(((X - mu) / sd) @ w + b)
    pred = np.asarray(jax.device_get(p)) >= 0.5
    acc = (pred[np.asarray(jax.device_get(live))] == (yl >= 0.5)).mean()
    assert acc >= base - 0.02, (acc, base)


def test_to_torch_handoff():
    s = _session()
    tables = gen_mortgage(n_loans=200, seed=5)
    feats, feature_cols = mortgage_features(s, tables)
    Xt, yt = to_torch(feats, feature_cols, "label")
    import torch
    assert isinstance(Xt, torch.Tensor)
    assert Xt.shape == (200, len(feature_cols))
    assert yt.shape == (200,)
    assert torch.isfinite(Xt).all()
