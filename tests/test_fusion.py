"""Scan-rooted whole-stage fusion (ISSUE 15).

The fused-decode scan splices the downstream device_fn chain
(filter -> project -> partial-agg tail) into ITS OWN XLA program, so a
from-files pipeline pays ONE dispatch per coalesced row-group batch —
counter-verified via the scan's ``fusedDispatches``/``scanPrograms``
metrics — with results bit-exact against the unfused (stageFusion off)
path and a JIT cache bounded across heterogeneous row groups by the
quantized-arena x chain-content key.
"""
import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.exec.base import (ExecCtx, collect_arrow,
                                        collect_arrow_cpu)
from spark_rapids_tpu.exec.basic import TpuFilterExec, TpuProjectExec
from spark_rapids_tpu.expr import (Alias, And, GreaterThanOrEqual,
                                   LessThan, Literal, Multiply)
from spark_rapids_tpu.expr import UnresolvedColumn as col
from spark_rapids_tpu.expr.aggregates import Sum
from spark_rapids_tpu.io import TpuFileScanExec


def _q6_file(tmp_path, n=6000, row_group_size=700, seed=0):
    rng = np.random.default_rng(seed)
    t = pa.table({
        "q": pa.array(rng.integers(1, 51, n).astype(np.float32)),
        "p": pa.array(rng.uniform(900, 105000, n).astype(np.float32)),
        "d": pa.array((rng.integers(0, 11, n) / 100.0)
                      .astype(np.float32)),
        "s": pa.array(rng.integers(8000, 10600, n).astype(np.int32)),
        "k": pa.array(rng.integers(0, 5, n).astype(np.int64)),
    })
    path = str(tmp_path / "fusion.parquet")
    pq.write_table(t, path, row_group_size=row_group_size,
                   compression="snappy")
    return path


def _q6_plan(path, conf):
    scan = TpuFileScanExec([path], conf=conf)
    f32 = lambda v: Literal(np.float32(v), dt.FLOAT32)  # noqa: E731
    cond = And(And(GreaterThanOrEqual(col("s"), Literal(8766, dt.INT32)),
                   LessThan(col("s"), Literal(9131, dt.INT32))),
               LessThan(col("q"), f32(24.0)))
    proj = TpuProjectExec(
        [Alias(Multiply(col("p"), col("d")), "rev"),
         Alias(col("k"), "k")], TpuFilterExec(cond, scan))
    agg = TpuHashAggregateExec([col("k")],
                               [Alias(Sum(col("rev")), "revenue")], proj)
    return scan, agg


def test_fused_scan_one_program_per_coalesced_batch(tmp_path):
    """The dispatch-granularity claim, counter-verified: every
    coalesced batch runs decode+filter+project+partial-agg as ONE
    spliced program (fusedDispatches == scanPrograms, >= 2 batches so
    the per-batch claim is real), rows match the oracle, and the same
    plan with stageFusion off is bit-exact."""
    path = _q6_file(tmp_path)
    conf = RapidsConf(
        {"spark.rapids.sql.scan.coalesceTargetBytes": str(16 << 10)})
    scan, agg = _q6_plan(path, conf)
    ctx = ExecCtx(conf)
    got = collect_arrow(agg, ctx).sort_by("k")
    want = collect_arrow_cpu(_q6_plan(path, conf)[1]).sort_by("k")
    gd, wd = got.to_pydict(), want.to_pydict()
    assert gd["k"] == wd["k"]
    assert np.allclose(gd["revenue"], wd["revenue"], rtol=1e-4)
    m = ctx.metrics[scan.node_label()]
    fused = int(m["fusedDispatches"].value)
    programs = int(m["scanPrograms"].value)
    assert fused >= 2
    assert fused == programs, (fused, programs)
    assert int(m["fallbackChunks"].value) == 0
    conf_off = RapidsConf(
        {"spark.rapids.sql.scan.coalesceTargetBytes": str(16 << 10),
         "spark.rapids.sql.stageFusion.enabled": "false"})
    off = collect_arrow(_q6_plan(path, conf_off)[1],
                        ExecCtx(conf_off)).sort_by("k")
    assert off.to_pydict() == gd  # bit-exact, not merely close


def test_fusion_membership_visible_to_explain_analyze(tmp_path):
    """Every operator that executed inside the spliced program records
    fusedInto (the consumer's stable program id), and render_analyzed
    shows the membership instead of a bare not-executed marker."""
    from spark_rapids_tpu.obs.opmetrics import (assign_op_ids, fold_ctx,
                                                render_analyzed)
    path = _q6_file(tmp_path)
    conf = RapidsConf()
    scan, agg = _q6_plan(path, conf)
    assign_op_ids(agg)
    ctx = ExecCtx(conf)
    collect_arrow(agg, ctx)
    fused_nodes = [lbl for lbl, ms in ctx.metrics.items()
                   if "fusedInto" in ms]
    for want_op in ("FileScanExec", "FilterExec", "ProjectExec"):
        assert any(lbl.startswith(want_op) for lbl in fused_nodes), \
            (want_op, fused_nodes)
    text = render_analyzed(agg, fold_ctx(ctx))
    assert "fused into op" in text
    # honest fused-stage timing: opTime is stamped by the completion
    # watcher (time from batch handover to output readiness) — present
    # and positive after the query's natural sync drained the watcher.
    # dispatchTime exists but stays 0 on the SPLICED path (the launch
    # happened on the scan's feeder, accounted under the scan's
    # uploadTime, not re-counted on the consumer).
    am = ctx.metrics[agg.node_label()]
    assert am["opTime"].value > 0
    assert "dispatchTime" in am


def test_fused_scan_jit_variants_bounded_heterogeneous_groups(tmp_path):
    """>= 5 heterogeneous row groups (odd sizes): the fused scan-chain
    JIT cache stays at a handful of variants — keyed on the quantized
    arena key x chain content key, NOT raw offsets — and a re-scan is
    fully cache-hot."""
    from spark_rapids_tpu.io import parquet_device as pd_
    rng = np.random.default_rng(3)
    # heterogeneous the way real files are: several full-size groups
    # with DIFFERENT data (different dictionaries/values — these must
    # COLLAPSE onto shared programs via the quantized arena) plus
    # genuinely different-sized stragglers (each its own capacity/fine
    # bucket, still bounded)
    sizes = [1000, 1000, 1000, 1000, 229, 1789]
    parts = []
    for i, sz in enumerate(sizes):
        parts.append(pa.table({
            "q": pa.array(rng.integers(1, 51, sz).astype(np.float32)),
            "p": pa.array(rng.uniform(900, 105000, sz)
                          .astype(np.float32)),
            "d": pa.array((rng.integers(0, 11, sz) / 100.0)
                          .astype(np.float32)),
            "s": pa.array(rng.integers(8000, 10600, sz)
                          .astype(np.int32)),
            "k": pa.array(rng.integers(0, 5, sz).astype(np.int64)),
        }))
    path = str(tmp_path / "hetero.parquet")
    with pq.ParquetWriter(path, parts[0].schema,
                          compression="snappy") as w:
        for p in parts:
            w.write_table(p, row_group_size=len(p))
    assert pq.ParquetFile(path).metadata.num_row_groups >= 5
    # coalesceTargetBytes=0: one fused dispatch PER ROW GROUP, so the
    # heterogeneity actually reaches the jit cache key
    conf = RapidsConf(
        {"spark.rapids.sql.scan.coalesceTargetBytes": "0"})
    pd_._JIT_CACHE.clear()
    scan, agg = _q6_plan(path, conf)
    ctx = ExecCtx(conf)
    got = collect_arrow(agg, ctx).sort_by("k")
    m = ctx.metrics[scan.node_label()]
    assert int(m["fusedDispatches"].value) >= 5
    keys = [k for k in pd_._JIT_CACHE if k[0] == "rgc"]
    assert keys, "no fused scan-chain programs were compiled"
    # bounded variants (same quantization contract as the plain "rg"
    # decode cache, test_io.py): the four near-target groups collapse
    # onto shared programs via the quantized arena key x chain key —
    # the raw-offset key would compile one program PER GROUP (6)
    assert len(keys) <= 4, \
        (f"{len(keys)} fused variants for {len(sizes)} heterogeneous "
         f"row groups — quantization regressed")
    # re-scan: fully cache-hot (zero new compiles)
    before = len(pd_._JIT_CACHE)
    got2 = collect_arrow(_q6_plan(path, conf)[1],
                         ExecCtx(conf)).sort_by("k")
    assert len(pd_._JIT_CACHE) == before
    assert got2.to_pydict() == got.to_pydict()
    want = collect_arrow_cpu(_q6_plan(path, conf)[1]).sort_by("k")
    assert got.to_pydict()["k"] == want.to_pydict()["k"]
    assert np.allclose(got.to_pydict()["revenue"],
                       want.to_pydict()["revenue"], rtol=1e-4)


def test_expand_device_fn_fuses_and_matches_oracle():
    """TpuExpandExec's device_fn (all projections as one traced
    concat): a partial aggregate above an expand fuses expand+partial
    and still matches the CPU oracle — including a string column."""
    from spark_rapids_tpu.exec.base import HostBatchSourceExec
    from spark_rapids_tpu.exec.misc import TpuExpandExec
    rng = np.random.default_rng(5)
    n = 500
    rb = pa.record_batch({
        "g": pa.array(rng.integers(0, 4, n).astype(np.int64)),
        "v": pa.array(rng.uniform(0, 100, n)),
        "name": pa.array([f"n{i % 7}" for i in range(n)]),
    })
    src = HostBatchSourceExec([rb])
    null_i64 = Literal(None, dt.INT64)
    null_str = Literal(None, dt.STRING)
    expand = TpuExpandExec(
        [[col("g"), col("name"), col("v"), Literal(0, dt.INT64)],
         [col("g"), null_str, col("v"), Literal(1, dt.INT64)],
         [null_i64, col("name"), col("v"), Literal(3, dt.INT64)]],
        ["g", "name", "v", "gid"], src)
    assert expand.device_fn() is not None
    agg = TpuHashAggregateExec(
        [col("g"), col("name"), col("gid")],
        [Alias(Sum(col("v")), "total")], expand)
    ctx = ExecCtx()
    got = collect_arrow(agg, ctx).sort_by(
        [("gid", "ascending"), ("g", "ascending"),
         ("name", "ascending")])
    want = collect_arrow_cpu(agg).sort_by(
        [("gid", "ascending"), ("g", "ascending"),
         ("name", "ascending")])
    gd, wd = got.to_pydict(), want.to_pydict()
    assert gd["g"] == wd["g"]
    assert gd["name"] == wd["name"]
    assert gd["gid"] == wd["gid"]
    assert np.allclose(gd["total"], wd["total"], rtol=1e-9)
    # the expand fused into the aggregate's program: it never executed
    # directly (no batches of its own), but recorded its membership
    exp_metrics = ctx.metrics.get(expand.node_label(), {})
    assert "fusedInto" in exp_metrics


def test_exchange_fused_split_matches_oracle(tmp_path):
    """The exchange writer's partition-key computation fuses as the
    chain tail — scan-rooted: decode -> project -> partition-ids in
    one program — and the shuffled rows match the CPU path."""
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.shuffle.partitioner import HashPartitioning
    path = _q6_file(tmp_path, n=2000, row_group_size=600)
    conf = RapidsConf()
    scan = TpuFileScanExec([path], conf=conf)
    proj = TpuProjectExec([Alias(col("k"), "k"),
                           Alias(Multiply(col("p"), col("d")), "rev")],
                          scan)
    ex = TpuShuffleExchangeExec(HashPartitioning([col("k")], 4), proj)
    ctx = ExecCtx(conf)
    got = collect_arrow(ex, ctx).sort_by(
        [("k", "ascending"), ("rev", "ascending")])
    want = collect_arrow_cpu(ex).sort_by(
        [("k", "ascending"), ("rev", "ascending")])
    assert got.to_pydict()["k"] == want.to_pydict()["k"]
    assert np.allclose(got.to_pydict()["rev"],
                       want.to_pydict()["rev"], rtol=1e-6)
    m = ctx.metrics[scan.node_label()]
    assert int(m["fusedDispatches"].value) >= 1


# --- fused-vs-unfused bit-exactness sweep over the SQL corpus -------------

def _corpus_session(tmp_path_factory, fusion: bool):
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.tools.nds import gen_tables, register_frames
    tables = gen_tables(n_sales=1 << 12)
    base = tmp_path_factory.mktemp(
        "nds_fusion_" + ("on" if fusion else "off"))
    conf = {"spark.sql.shuffle.partitions": "1"}
    if not fusion:
        conf["spark.rapids.sql.stageFusion.enabled"] = "false"
    s = TpuSession(conf=conf)
    frames = {}
    for name, cols in tables.items():
        p = str(base / f"{name}.parquet")
        pq.write_table(pa.table(cols), p, row_group_size=1 << 10,
                       compression="snappy")
        frames[name] = s.read_parquet(p)
    register_frames(s, frames)
    s._nds_frames = (tables, frames)
    return s, tables


def _sweep(names, tmp_path_factory):
    from spark_rapids_tpu.tools.nds import build_query_sql
    s_on, tables = _corpus_session(tmp_path_factory, fusion=True)
    s_off, _ = _corpus_session(tmp_path_factory, fusion=False)
    for name in names:
        on = build_query_sql(name, s_on, tables).collect()
        off = build_query_sql(name, s_off, tables).collect()
        assert on.schema == off.schema, name
        for ci, field in enumerate(on.schema):
            g = on.column(ci).to_numpy(zero_copy_only=False)
            w = off.column(ci).to_numpy(zero_copy_only=False)
            if np.issubdtype(np.asarray(w).dtype, np.floating):
                # bit-exact: fusion must not reassociate — equal_nan
                # only tolerates NaN==NaN, not value drift
                assert np.array_equal(g.astype(float),
                                      w.astype(float),
                                      equal_nan=True), (name, field)
            else:
                assert (np.asarray(g) == np.asarray(w)).all(), \
                    (name, field)


def test_fused_vs_unfused_bitexact_subset(tmp_path_factory):
    """Fast representative slice of the corpus sweep (agg, join,
    strings, window, top-n shapes) — tier-1 sized; the full 22-query
    sweep runs under the slow marker."""
    _sweep(["q3", "q55", "q96", "q_like", "q_topn", "q_price_band"],
           tmp_path_factory)


@pytest.mark.slow
def test_fused_vs_unfused_bitexact_full_corpus(tmp_path_factory):
    """The acceptance sweep: EVERY SQL corpus query from parquet files,
    stageFusion on vs off, bit-exact column for column."""
    from spark_rapids_tpu.tools.nds import SQL_QUERIES
    _sweep(sorted(SQL_QUERIES), tmp_path_factory)
