"""Session / DataFrame facade tests — the product surface over the
planner + exec pipeline (SURVEY.md §2.2-A plugin analog)."""
import os

import pyarrow as pa
import pytest

from spark_rapids_tpu import TpuSession, datatypes as dt
from spark_rapids_tpu.expr import (Alias, GreaterThan, Literal,
                                   UnresolvedColumn as col)
from spark_rapids_tpu.expr.aggregates import Count, Sum


@pytest.fixture
def spark():
    return TpuSession()


def _df(spark, n=100):
    return spark.create_dataframe({
        "k": [i % 7 for i in range(n)],
        "v": list(range(n)),
        "s": [f"row{i % 5}" for i in range(n)],
    })


def test_select_filter_collect(spark):
    out = (_df(spark)
           .filter(GreaterThan(col("v"), Literal(50)))
           .select("k", "v")
           .collect())
    assert out.num_rows == 49
    assert out.column("v").to_pylist() == list(range(51, 100))


def test_with_column_and_count(spark):
    from spark_rapids_tpu.expr import Multiply
    df = _df(spark).with_column("v2", Multiply(col("v"), Literal(2)))
    assert "v2" in df.columns
    assert df.count() == 100
    got = df.collect()
    assert got.column("v2").to_pylist()[:3] == [0, 2, 4]


def test_group_by_agg_uses_shuffle_partitions(spark):
    df = (_df(spark)
          .group_by("k")
          .agg(Alias(Sum(col("v")), "total"), Alias(Count(), "n")))
    # plan shape: aggregate over a shuffle exchange with the conf's
    # partition count (spark.sql.shuffle.partitions consumption)
    assert "ShuffleExchangeExec" in df.explain("ALL") or \
        "ShuffleExchange" in repr(df._node)
    rows = {r["k"]: r for r in df.to_pylist()}
    assert rows[0]["n"] == 15  # 0,7,...,98
    assert rows[0]["total"] == sum(range(0, 100, 7))


def test_join_orderby_limit(spark):
    left = _df(spark)
    right = spark.create_dataframe({
        "k": list(range(7)), "name": [f"g{i}" for i in range(7)]})
    out = (left.join(right, on="k")
           .order_by("v", ascending=False)
           .limit(3)
           .collect())
    assert out.column("v").to_pylist() == [99, 98, 97]
    assert out.column("name").to_pylist() == ["g1", "g0", "g6"]


def test_condition_only_join_routes_to_nlj(spark):
    left = spark.create_dataframe({"a": [1, 5, 9]})
    right = spark.create_dataframe({"b": [3, 7]})
    df = left.join(right, how="inner",
                   condition=GreaterThan(col("a"), col("b")))
    assert "NestedLoop" in type(df._node).__name__
    got = sorted((r["a"], r["b"]) for r in df.to_pylist())
    assert got == [(5, 3), (9, 3), (9, 7)]


def test_union_sample_cache(spark):
    df = _df(spark, 50).union(_df(spark, 50))
    assert df.count() == 100
    cached = df.cache()
    a = cached.collect()
    b = cached.collect()  # replays from the cache exec
    assert a.to_pylist() == b.to_pylist()
    from spark_rapids_tpu.session import TpuCacheExec
    assert isinstance(cached._node, TpuCacheExec)
    assert cached._node._entries is not None  # materialized once


def test_explode(spark):
    df = spark.create_dataframe(pa.table({
        "id": pa.array([1, 2], pa.int32()),
        "xs": pa.array([[10, 20], [30]], pa.list_(pa.int64()))}))
    out = df.explode("xs").collect()
    assert out.column("col").to_pylist() == [10, 20, 30]
    assert out.column("id").to_pylist() == [1, 1, 2]


def test_case_sensitivity_conf(spark):
    df = _df(spark)
    # default: case-insensitive resolution (spark.sql.caseSensitive)
    assert df.select("K").collect().num_rows == 100
    strict = TpuSession({"spark.sql.caseSensitive": True})
    df2 = _df(strict)
    with pytest.raises(Exception):
        df2.select("K").collect()


def test_read_write_roundtrip(spark, tmp_path):
    df = _df(spark)
    files = df.write_parquet(str(tmp_path / "out"))
    assert files and all(os.path.exists(f) for f in files)
    back = spark.read_parquet(files)
    got = back.collect().sort_by("v")
    assert got.column("v").to_pylist() == list(range(100))


def test_range(spark):
    assert spark.range(10).collect().column("id").to_pylist() == \
        list(range(10))


def test_explain_renders(spark):
    text = _df(spark).filter(GreaterThan(col("v"), Literal(1))).explain()
    assert "will run on TPU" in text


# --- pivot (Spark's conditional-aggregate rewrite) ------------------------

def _pivot_frame(session):
    import numpy as np
    import pyarrow as pa
    rng = np.random.default_rng(8)
    n = 400
    return session.create_dataframe(pa.table({
        "dept": pa.array(rng.choice(["eng", "ops", "fin"], n).tolist()),
        "year": pa.array(rng.choice([2023, 2024], n)),
        "pay": pa.array(rng.integers(50, 200, n).astype("int64")),
    }))


def test_pivot_explicit_values():
    from spark_rapids_tpu.expr.aggregates import Sum
    from spark_rapids_tpu.expr import UnresolvedColumn as col
    from spark_rapids_tpu.expr.base import Alias
    s = TpuSession()
    df = _pivot_frame(s)
    got = df.group_by("dept").pivot("year", [2023, 2024]).agg(
        Alias(Sum(col("pay")), "s")).collect().to_pandas() \
        .sort_values("dept").reset_index(drop=True)
    pdf = df.collect().to_pandas()
    want = pdf.pivot_table(index="dept", columns="year", values="pay",
                           aggfunc="sum").reset_index()
    want.columns = ["dept", "2023", "2024"]
    want = want.sort_values("dept").reset_index(drop=True)
    import pandas.testing as pdt
    pdt.assert_frame_equal(got, want, check_dtype=False,
                           check_names=False)


def test_pivot_inferred_values_and_multi_agg():
    from spark_rapids_tpu.expr.aggregates import Count, Max
    from spark_rapids_tpu.expr import UnresolvedColumn as col
    from spark_rapids_tpu.expr.base import Alias
    s = TpuSession()
    df = _pivot_frame(s)
    got = df.group_by("year").pivot("dept").agg(
        Alias(Count(), "n"), Alias(Max(col("pay")), "m")).collect()
    assert sorted(got.column_names) == sorted(
        ["year", "eng_n", "eng_m", "ops_n", "ops_m", "fin_n", "fin_m"])
    pdf = df.collect().to_pandas()
    g = got.to_pandas().sort_values("year").reset_index(drop=True)
    for dept in ("eng", "ops", "fin"):
        sub = pdf[pdf.dept == dept].groupby("year").agg(
            n=("pay", "size"), m=("pay", "max")).reset_index() \
            .sort_values("year").reset_index(drop=True)
        assert (g["year"] == sub["year"]).all()
        assert (g[f"{dept}_n"] == sub["n"]).all()
        assert (g[f"{dept}_m"] == sub["m"]).all()
