"""NDS corpus from SQL text: every SQL_QUERIES entry compiles through
``session.sql`` and dual-runs row-for-row equal to its hand-built
Python plan (the acceptance bar for the SQL frontend: the corpus stops
being a transcription and becomes the real thing)."""
import numpy as np
import pytest

from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.tools.nds import (QUERIES, SQL_QUERIES,
                                        build_query, build_query_sql,
                                        gen_tables)

TABLES = gen_tables(n_sales=1 << 14)


def _assert_frames_equal(got, want, name):
    assert list(got.columns) == list(want.columns), \
        (name, got.columns, want.columns)
    assert len(got) == len(want), (name, len(got), len(want))
    for c in got.columns:
        g = got[c].to_numpy()
        w = want[c].to_numpy()
        if np.issubdtype(np.asarray(w).dtype, np.floating):
            # device float aggregation across differing plan shapes can
            # reassociate; row ORDER must still match exactly
            assert np.allclose(g.astype(float), w.astype(float),
                               rtol=1e-9, atol=1e-9, equal_nan=True), \
                (name, c)
        else:
            assert (g == w).all(), (name, c)


def test_sql_corpus_is_complete():
    # every hand-built corpus query has a SQL text and vice versa, and
    # the corpus satisfies the >= 20-query acceptance bar
    assert set(SQL_QUERIES) == set(QUERIES)
    assert len(SQL_QUERIES) >= 20


@pytest.mark.parametrize("name", sorted(SQL_QUERIES))
def test_sql_dual_runs_hand_built(name):
    s = TpuSession()
    hand = build_query(name, s, TABLES).collect().to_pandas()
    sql = build_query_sql(name, s, TABLES).collect().to_pandas()
    _assert_frames_equal(sql.reset_index(drop=True),
                         hand.reset_index(drop=True), name)


@pytest.mark.parametrize("name", sorted(SQL_QUERIES))
def test_sql_corpus_plans_fully_on_device(name):
    # zero unexpected fallbacks: SQL-originated plans place every
    # operator on TPU exactly like the hand-built ones
    from spark_rapids_tpu.planner import TpuOverrides
    s = TpuSession()
    df = build_query_sql(name, s, TABLES)
    pp = TpuOverrides(s.conf).apply(df._node)
    assert not pp.fallback_nodes(), \
        f"{name}: {pp.explain('NOT_ON_GPU')}"


def test_sql_corpus_explains():
    # EXPLAIN over a corpus text returns plan text without executing
    s = TpuSession()
    from spark_rapids_tpu.tools import nds as _nds
    _nds._frames(s, TABLES)
    text = s.sql("EXPLAIN " + SQL_QUERIES["q3"])
    assert "will run on TPU" in text
