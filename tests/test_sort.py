"""Sort / TopN / limit operator tests via the dual-run harness
(reference: sort_test.py, limit_test.py — SURVEY.md §4.1)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.exec import (HostBatchSourceExec, TpuProjectExec)
from spark_rapids_tpu.exec.sort import (SortOrder, TpuGlobalLimitExec,
                                        TpuLocalLimitExec, TpuSortExec,
                                        TpuTopNExec)
from spark_rapids_tpu.expr import Alias, UnresolvedColumn as col

from asserts import assert_tpu_and_cpu_plan_equal
from data_gen import (BooleanGen, ByteGen, DateGen, DecimalGen, DoubleGen,
                      FloatGen, IntegerGen, LongGen, ShortGen, StringGen,
                      TimestampGen, gen_table)


def source(gens, n=256, seed=1234, names=None):
    return HostBatchSourceExec([gen_table(gens, n, seed, names)])


sortable_gens = [ByteGen(), ShortGen(), IntegerGen(), LongGen(),
                 FloatGen(dt.FLOAT32), DoubleGen(), BooleanGen(),
                 StringGen(), DateGen(), TimestampGen(), DecimalGen()]


@pytest.mark.parametrize("gen", sortable_gens,
                         ids=lambda g: g.dtype.simple_string())
@pytest.mark.parametrize("asc", [True, False])
def test_sort_single_key(gen, asc):
    # c1 tie-break makes the expected order total (stability-independent).
    plan = TpuSortExec(
        [SortOrder(col("c0"), ascending=asc),
         SortOrder(col("c1"))],
        source([gen, LongGen(nullable=False)]))
    assert_tpu_and_cpu_plan_equal(plan)


@pytest.mark.parametrize("nulls_first", [True, False])
def test_sort_null_placement(nulls_first):
    plan = TpuSortExec(
        [SortOrder(col("c0"), ascending=True, nulls_first=nulls_first),
         SortOrder(col("c1"))],
        source([IntegerGen(null_frac=0.3), LongGen(nullable=False)]))
    assert_tpu_and_cpu_plan_equal(plan)


def test_sort_multi_key_mixed_directions():
    plan = TpuSortExec(
        [SortOrder(col("c0"), ascending=False),
         SortOrder(col("c1"), ascending=True, nulls_first=False),
         SortOrder(col("c2"))],
        source([IntegerGen(min_val=0, max_val=5), StringGen(max_len=4),
                LongGen(nullable=False)]))
    assert_tpu_and_cpu_plan_equal(plan)


def test_sort_strings_long():
    # strings longer than one 7-byte refinement window, with shared prefixes
    plan = TpuSortExec(
        [SortOrder(col("c0")), SortOrder(col("c1"))],
        source([StringGen(max_len=40, charset="ab"),
                LongGen(nullable=False)]))
    assert_tpu_and_cpu_plan_equal(plan)


def test_sort_float_specials():
    # NaN sorts largest; -0.0 ties 0.0 (broken by c1)
    plan = TpuSortExec(
        [SortOrder(col("c0")), SortOrder(col("c1"))],
        source([DoubleGen(null_frac=0.2), LongGen(nullable=False)]))
    assert_tpu_and_cpu_plan_equal(plan)
    plan = TpuSortExec(
        [SortOrder(col("c0"), ascending=False), SortOrder(col("c1"))],
        source([DoubleGen(null_frac=0.2), LongGen(nullable=False)]))
    assert_tpu_and_cpu_plan_equal(plan)


def test_sort_global_multi_batch():
    rbs = [gen_table([IntegerGen(), LongGen(nullable=False)], n, seed=s)
           for n, s in [(100, 1), (57, 2), (300, 3)]]
    plan = TpuSortExec(
        [SortOrder(col("c0")), SortOrder(col("c1"))],
        HostBatchSourceExec(rbs))
    assert_tpu_and_cpu_plan_equal(plan)


def test_sort_local_per_batch():
    rbs = [gen_table([IntegerGen(nullable=False),
                      LongGen(nullable=False)], n, seed=s)
           for n, s in [(64, 1), (32, 2)]]
    plan = TpuSortExec([SortOrder(col("c0")), SortOrder(col("c1"))],
                       HostBatchSourceExec(rbs), global_sort=False)
    assert_tpu_and_cpu_plan_equal(plan)


def test_sort_strings_multi_batch_concat():
    rbs = [gen_table([StringGen(max_len=12), LongGen(nullable=False)],
                     n, seed=s) for n, s in [(80, 4), (120, 5)]]
    plan = TpuSortExec([SortOrder(col("c0")), SortOrder(col("c1"))],
                       HostBatchSourceExec(rbs))
    assert_tpu_and_cpu_plan_equal(plan)


def test_local_limit():
    rbs = [gen_table([IntegerGen(), StringGen()], n, seed=s)
           for n, s in [(100, 1), (100, 2), (100, 3)]]
    for lim in (0, 50, 100, 150, 299, 300, 500):
        plan = TpuLocalLimitExec(lim, HostBatchSourceExec(rbs))
        assert_tpu_and_cpu_plan_equal(plan, label=f"limit {lim}")


def test_local_limit_early_exit():
    """LIMIT n over a long stream stops pulling the child after the
    periodic counter sync confirms the limit is reached (ADVICE r4: the
    sync-free path did O(input) work)."""
    from spark_rapids_tpu.exec.base import ExecCtx

    rbs = [gen_table([IntegerGen(nullable=False)], 100, seed=s)
           for s in range(64)]
    pulled = []

    class CountingSource(HostBatchSourceExec):
        def execute(self, ctx):
            for i, b in enumerate(super().execute(ctx)):
                pulled.append(i)
                yield b

    plan = TpuLocalLimitExec(50, CountingSource(rbs))
    list(plan.execute(ExecCtx()))
    # limit hit in batch 0; the every-8-batches sync must break the
    # stream well before all 64 batches are decoded/uploaded
    assert len(pulled) <= TpuLocalLimitExec._SYNC_EVERY
    pulled.clear()
    assert_tpu_and_cpu_plan_equal(
        TpuLocalLimitExec(50, CountingSource(rbs)), label="early-exit")


def test_topn():
    plan = TpuTopNExec(
        10, [SortOrder(col("c0"), ascending=False), SortOrder(col("c2"))],
        source([IntegerGen(), StringGen(), LongGen(nullable=False)]))
    assert_tpu_and_cpu_plan_equal(plan)


def test_topn_with_project():
    plan = TpuTopNExec(
        7, [SortOrder(col("c0")), SortOrder(col("c2"))],
        source([IntegerGen(), StringGen(), LongGen(nullable=False)]),
        project=[col("c1"), Alias(col("c0"), "k")])
    assert_tpu_and_cpu_plan_equal(plan)


def test_limit_after_sort():
    plan = TpuGlobalLimitExec(
        25, TpuSortExec([SortOrder(col("c0")), SortOrder(col("c1"))],
                        source([DateGen(), LongGen(nullable=False)])))
    assert_tpu_and_cpu_plan_equal(plan)


def test_sort_computed_key_with_nulls():
    # Regression: computed keys leave garbage in null rows' data lane;
    # null ordering must not depend on it.
    from spark_rapids_tpu.expr import Add
    plan = TpuSortExec(
        [SortOrder(Add(col("c0"), col("c1"))), SortOrder(col("c2"))],
        source([IntegerGen(null_frac=0.4), IntegerGen(null_frac=0.4),
                LongGen(nullable=False)]))
    assert_tpu_and_cpu_plan_equal(plan)
