"""Query telemetry warehouse tests (spark_rapids_tpu/obs/warehouse.py +
obs/attribution.py): sealed-segment durability (torn tails salvage,
crash-safe appends), one-row-per-query emission across every outcome
class (completed / cancelled / degraded / failed), per-operator and
per-transport cost attribution — including the exchange write-side row
fix (the BENCH_r07 ``ShuffleExchangeExec rows: 0`` bug) — the drift
sentinel's structural-regression rc semantics, and the /status JSON
endpoint."""
import glob
import json
import os
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from data_gen import IntegerGen, LongGen, gen_table

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec import HostBatchSourceExec
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
from spark_rapids_tpu.exec.joins import TpuShuffledHashJoinExec
from spark_rapids_tpu.expr import Alias, UnresolvedColumn as col
from spark_rapids_tpu.expr.aggregates import Count, Sum
from spark_rapids_tpu.lifecycle import QueryCancelled
from spark_rapids_tpu.obs.warehouse import (append_row, drift_report,
                                            read_rows, render_warehouse,
                                            tail_rows, warehouse_dir)
from spark_rapids_tpu.planner import overrides
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.shuffle.partitioner import HashPartitioning


def _conf(d, **extra):
    base = {"spark.rapids.warehouse.dir": str(d)}
    base.update({k: str(v) for k, v in extra.items()})
    return RapidsConf(base)


def _row(**kw):
    r = {"query_id": "q1", "tenant": "default", "outcome": "completed",
         "device_kind": "cpu", "fingerprint": "fp0", "wall_s": 1.0,
         "fusion": {"fused_dispatches": 4, "jit_variants": 2,
                    "scan_programs": 4},
         "scan": {"device_chunks": 6, "fallback_chunks": 0},
         "bytes": {"host_written": 1000}, "spill": {}}
    r.update(kw)
    return r


# --- writer / reader durability ---------------------------------------------

def test_warehouse_dir_gating(tmp_path):
    assert warehouse_dir(RapidsConf()) is None  # no dir configured
    assert warehouse_dir(_conf(tmp_path)) == str(tmp_path)
    off = _conf(tmp_path, **{"spark.rapids.warehouse.enabled": "false"})
    assert warehouse_dir(off) is None  # kill switch wins over dir


def test_append_read_roundtrip_sealed(tmp_path):
    conf = _conf(tmp_path)
    for i in range(3):
        p = append_row(conf, _row(query_id=f"q{i}", ts=float(i)))
        assert p is not None
    rows = read_rows(str(tmp_path))
    assert [r["query_id"] for r in rows] == ["q0", "q1", "q2"]
    assert all(r["version"] == 1 for r in rows)
    # segments really carry the CRC32C seal: the verified read succeeds
    from spark_rapids_tpu.shuffle.integrity import read_sealed_file
    segs = glob.glob(os.path.join(str(tmp_path), "wh-*.jsonl"))
    assert segs
    for s in segs:
        read_sealed_file(s, lambda k, d: AssertionError(f"{k}: {d}"))


def test_segment_roll_and_retention(tmp_path):
    conf = _conf(tmp_path,
                 **{"spark.rapids.warehouse.segment.maxRows": "1",
                    "spark.rapids.warehouse.maxFiles": "2"})
    for i in range(5):
        append_row(conf, _row(query_id=f"q{i}", ts=float(i)))
    segs = glob.glob(os.path.join(str(tmp_path), "wh-*.jsonl"))
    assert len(segs) == 2  # oldest pruned at write time
    assert [r["query_id"] for r in read_rows(str(tmp_path))] == \
        ["q3", "q4"]


def test_torn_tail_salvaged(tmp_path):
    conf = _conf(tmp_path)
    for i in range(3):
        append_row(conf, _row(query_id=f"q{i}", ts=float(i)))
    (seg,) = glob.glob(os.path.join(str(tmp_path), "wh-*.jsonl"))
    raw = open(seg, "rb").read()
    # crash mid-write of a FUTURE append: sealed payload + torn tail
    with open(seg, "wb") as f:
        f.write(raw + b'{"query_id": "q3", "torn')
    rows = read_rows(str(tmp_path))
    # the seal no longer verifies -> line salvage recovers the intact
    # prefix rows and skips the torn line + binary footer
    assert [r["query_id"] for r in rows] == ["q0", "q1", "q2"]
    # a fully garbage segment contributes nothing but doesn't raise
    with open(os.path.join(str(tmp_path), "wh-0-0.jsonl"), "wb") as f:
        f.write(b"\x00\xff\x01garbage")
    assert len(read_rows(str(tmp_path))) == 3


def test_append_row_disabled_is_noop(tmp_path):
    off = _conf(tmp_path, **{"spark.rapids.warehouse.enabled": "false"})
    assert append_row(off, _row()) is None
    assert not glob.glob(os.path.join(str(tmp_path), "wh-*"))


# --- one row per query, every outcome class ---------------------------------

def _frame(session, nbatches=2, rows=200):
    tbl = pa.Table.from_batches([
        pa.RecordBatch.from_arrays(
            [pa.array(np.arange(rows, dtype=np.int64))], names=["a"])
        for _ in range(nbatches)])
    return session.create_dataframe(tbl)


def test_completed_row_attribution_consistent(tmp_path):
    conf = _conf(tmp_path)
    rb = gen_table([IntegerGen(min_val=0, max_val=4, null_frac=0.0),
                    LongGen(nullable=False)], 300, seed=1,
                   names=["k", "v"])
    src = HostBatchSourceExec([rb])
    exch = TpuShuffleExchangeExec(HashPartitioning([col("k")], 4), src)
    plan = TpuHashAggregateExec([col("k")],
                                [Alias(Sum(col("v")), "s")], exch)
    overrides(plan, conf).collect()
    (row,) = read_rows(str(tmp_path))
    assert row["outcome"] == "completed" and row["cancel"] is None
    assert row["source"] == "plan" and row["query_id"]
    assert row["fingerprint"] and row["device_kind"]
    # internal consistency: op time fits inside the wall, ops carry the
    # oracle row counts
    assert 0 < row["wall_s"]
    assert row["split"]["op_time_s"] <= row["wall_s"] * 1.5
    by_label = {op["label"].split("#")[0]: op
                for op in row["ops"].values()}
    assert by_label["HostBatchSourceExec"]["rows"] == 300
    assert by_label["HashAggregateExec"]["rows"] == 5
    assert set(row["bytes"]) == {"host_written", "host_fetched",
                                 "ici_written", "ici_fetched",
                                 "process_fetched", "gang_dcn",
                                 "gang_epochs"}
    assert set(row["spill"]) == {"write_bytes", "disk_write_bytes",
                                 "read_bytes"}


def test_exchange_write_side_rows_attributed(tmp_path):
    """BENCH_r07 regression: the AQE reader drives the exchange through
    materialize() (never execute()), so without write-side counting the
    exchange showed rows=0 while its consumers saw the full stream."""
    conf = _conf(tmp_path)
    rb = gen_table([IntegerGen(min_val=0, max_val=9, null_frac=0.0),
                    LongGen(nullable=False)], 400, seed=2,
                   names=["k", "v"])
    src = HostBatchSourceExec([rb])
    exch = TpuShuffleExchangeExec(HashPartitioning([col("k")], 4), src)
    plan = TpuHashAggregateExec([col("k")],
                                [Alias(Count(col("v")), "c")], exch)
    overrides(plan, conf).collect()  # AQE on by default
    (row,) = read_rows(str(tmp_path))
    by_label = {op["label"].split("#")[0]: op["rows"]
                for op in row["ops"].values()}
    # the exchange counts every row it partitions — exactly its input,
    # not zero and not double-counted with the reader's read side
    assert by_label["ShuffleExchangeExec"] == 400
    assert by_label.get("AQEShuffleReadExec", 400) == 400


def test_cancelled_row_classified(tmp_path):
    s = TpuSession({"spark.rapids.warehouse.dir": str(tmp_path),
                    "spark.rapids.query.memoryBudgetBytes": "1",
                    "spark.rapids.query.memoryBudget.action": "cancel"})
    with pytest.raises(QueryCancelled):
        _frame(s).select("a").collect()
    (row,) = read_rows(str(tmp_path))
    assert row["outcome"] == "cancelled"
    assert row["cancel"]["reason"] == "budget"
    assert "budget exceeded" in row["cancel"]["detail"]
    assert "error" not in row  # cancelled, not failed


def test_degraded_row_carries_ladder_and_reasons(tmp_path):
    s = TpuSession({"spark.rapids.warehouse.dir": str(tmp_path),
                    "spark.rapids.sql.test.injectRetryOOM.storm": "200",
                    "spark.rapids.sql.oomRetry.maxSplits": "2"})
    qx = s.query_context()
    got = _frame(s, nbatches=1, rows=64).select("a").collect(qx)
    assert got.column(0).to_pylist() == list(range(64))
    (row,) = read_rows(str(tmp_path))
    assert row["outcome"] == "degraded"
    for rung in ("halve", "spill", "width1", "cpu"):
        assert row["ladder"].get(rung, 0) >= 1, row["ladder"]
    assert any(r.startswith("ladder_cpu_fallback:")
               for r in row["fallback_reasons"])


def test_failed_row_carries_error(tmp_path):
    conf = _conf(tmp_path)
    schema = dt.Schema([dt.StructField("x", dt.INT64, True)])
    from spark_rapids_tpu.io.scan import TpuFileScanExec
    plan = TpuFileScanExec(["/nonexistent/wh.parquet"], schema=schema)
    with pytest.raises(Exception):
        overrides(plan, conf).collect()
    (row,) = read_rows(str(tmp_path))
    assert row["outcome"] == "failed"
    assert row["error"]  # classified exception text rides the row


# --- drift sentinel ---------------------------------------------------------

def test_drift_silent_on_identical_runs(tmp_path):
    conf = _conf(tmp_path)
    append_row(conf, _row(ts=1.0))
    append_row(conf, _row(ts=2.0))
    rep, rc = drift_report(str(tmp_path))
    assert rc == 0
    assert "drift: clean" in rep


def test_drift_flags_seeded_dispatch_regression_once(tmp_path):
    conf = _conf(tmp_path)
    append_row(conf, _row(ts=1.0))
    seeded = _row(ts=2.0)
    seeded["fusion"] = dict(seeded["fusion"], fused_dispatches=5)
    append_row(conf, seeded)
    rep, rc = drift_report(str(tmp_path))
    assert rc == 1
    # flagged exactly once, naming the offending counter and the delta
    assert rep.count("REGRESSION") == 1
    assert "fusedDispatches: 4 -> 5 (+1)" in rep


def test_drift_flags_fallback_variants_and_bytes(tmp_path):
    conf = _conf(tmp_path)
    append_row(conf, _row(ts=1.0))
    bad = _row(ts=2.0)
    bad["scan"] = {"device_chunks": 5, "fallback_chunks": 1}
    bad["fusion"] = dict(bad["fusion"], jit_variants=99)
    bad["bytes"] = {"host_written": 10000}  # 10x > 25% tolerance
    append_row(conf, bad)
    rep, rc = drift_report(str(tmp_path))
    assert rc == 1
    assert "fallbackChunks: 0 -> 1" in rep
    assert "jitVariants: 99 exceeds bound 8" in rep
    assert "bytesMoved: 1000 -> 10000" in rep
    # knobs loosen the sentinel
    rep2, rc2 = drift_report(str(tmp_path), bytes_tolerance=100.0,
                             variant_bound=1000)
    assert "jitVariants" not in rep2 and "bytesMoved" not in rep2


def test_drift_refuses_cross_device_kind_rc3(tmp_path):
    conf = _conf(tmp_path)
    append_row(conf, _row(ts=1.0, device_kind="cpu"))
    append_row(conf, _row(ts=2.0, device_kind="TPU v4"))
    rep, rc = drift_report(str(tmp_path))
    assert rc == 3
    assert rep.startswith("=== drift REFUSED: device_kind mismatch ===")
    assert "'TPU v4'" in rep and "'cpu'" in rep
    # explicit opt-out downgrades to a warning and compares anyway
    rep2, rc2 = drift_report(str(tmp_path), allow_cross_device=True)
    assert rc2 == 0
    assert "WARNING" in rep2


def test_drift_same_device_baseline_preferred_over_cross(tmp_path):
    """A same-device_kind prior exists further back: compare against
    IT, not the interleaved foreign-device run."""
    conf = _conf(tmp_path)
    append_row(conf, _row(ts=1.0, device_kind="cpu"))
    append_row(conf, _row(ts=2.0, device_kind="TPU v4"))
    append_row(conf, _row(ts=3.0, device_kind="cpu"))
    rep, rc = drift_report(str(tmp_path))
    assert rc == 0, rep


def test_profiling_cli_warehouse_and_drift(tmp_path, capsys):
    from spark_rapids_tpu.tools.profiling import _main as main
    conf = _conf(tmp_path)
    append_row(conf, _row(ts=1.0, tenant="etl"))
    seeded = _row(ts=2.0, tenant="etl")
    seeded["fusion"] = dict(seeded["fusion"], fused_dispatches=7)
    append_row(conf, seeded)
    assert main(["warehouse", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "telemetry warehouse" in out and "etl" in out
    assert main(["drift", str(tmp_path)]) == 1  # seeded regression
    assert "fusedDispatches" in capsys.readouterr().out
    assert main(["drift", str(tmp_path),
                 "--variant-bound", "1"]) == 1
    # cross-device history refuses with rc 3
    append_row(conf, _row(ts=3.0, device_kind="TPU v4",
                          fingerprint="fpX"))
    append_row(conf, _row(ts=2.5, device_kind="cpu",
                          fingerprint="fpX"))
    assert main(["drift", str(tmp_path)]) == 3
    assert main(["drift", str(tmp_path), "--allow-cross-device"]) == 1


def test_render_warehouse_rollups(tmp_path):
    conf = _conf(tmp_path)
    append_row(conf, _row(ts=1.0, tenant="etl"))
    append_row(conf, _row(ts=2.0, tenant="adhoc",
                          outcome="cancelled"))
    out = render_warehouse(str(tmp_path))
    assert "rows: 2" in out
    assert "etl" in out and "adhoc" in out
    assert "cancelled=1" in out
    assert "fp0" in out  # per-fingerprint structural summary


# --- /status endpoint -------------------------------------------------------

def test_render_status_document_shape(tmp_path):
    from spark_rapids_tpu.obs.metrics import (clear_status_provider,
                                              render_status,
                                              set_status_provider)
    doc = render_status()
    assert doc["pid"] == os.getpid()
    assert "device_bytes_in_use" in doc["memory"]
    assert "in_use" in doc["admission"]
    sentinel = {"in_flight": [{"query_id": "q9", "phase": "running"}]}
    set_status_provider(lambda: sentinel)
    try:
        doc = render_status()
        assert doc["in_flight"][0]["query_id"] == "q9"
        # the whole document is JSON-serializable
        json.loads(json.dumps(doc))
    finally:
        clear_status_provider()
    assert "in_flight" not in render_status()


def test_status_provider_stale_clear_does_not_clobber():
    from spark_rapids_tpu.obs import metrics as M
    old = lambda: {"gen": 1}  # noqa: E731
    new = lambda: {"gen": 2}  # noqa: E731
    M.set_status_provider(old)
    M.set_status_provider(new)
    M.clear_status_provider(old)  # stale shutdown: must be a no-op
    try:
        assert M.render_status()["gen"] == 2
    finally:
        M.clear_status_provider()


def test_http_status_endpoint(tmp_path):
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    from spark_rapids_tpu.obs import metrics as M
    conf = RapidsConf({"spark.rapids.metrics.port": port})
    bound = M.maybe_start_http_server(conf)
    if bound is None:
        pytest.skip("metrics port raced away")
    M.set_status_provider(lambda: {"probe": "alive"})
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{bound}/status", timeout=5) as resp:
            assert resp.headers["Content-Type"] == "application/json"
            doc = json.load(resp)
        assert doc["probe"] == "alive"
        assert "memory" in doc and "admission" in doc
        # /metrics still serves prometheus text beside it
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{bound}/metrics", timeout=5).read()
        assert b"# TYPE" in body
    finally:
        M.clear_status_provider()


def test_tail_rows_compacts_for_status(tmp_path):
    conf = _conf(tmp_path)
    for i in range(7):
        append_row(conf, _row(ts=float(i), query_id=f"q{i}"))
    tail = tail_rows(str(tmp_path), 3)
    assert [t["query_id"] for t in tail] == ["q4", "q5", "q6"]
    assert set(tail[0]) == {"query_id", "tenant", "outcome", "wall_s",
                            "device_kind", "fingerprint"}


# --- process cluster: folded attribution + failed-query rows ----------------

@pytest.fixture(scope="module")
def wh_cluster(tmp_path_factory):
    from spark_rapids_tpu.cluster import TpuProcessCluster
    d = str(tmp_path_factory.mktemp("wh"))
    conf = RapidsConf({"spark.rapids.warehouse.dir": d,
                       "spark.rapids.metrics.enabled": "true"})
    with TpuProcessCluster(n_workers=2, conf=conf) as c:
        yield c, d


def _join_plan(n_fact=400, n_dim=10):
    rng = np.random.default_rng(7)
    fact = pa.record_batch({
        "fk": pa.array(rng.integers(0, n_dim, n_fact)
                       .astype(np.int32)),
        "amt": pa.array(rng.integers(1, 100, n_fact).astype(np.int64)),
    })
    dim = pa.record_batch({
        "dk": pa.array(np.arange(n_dim, dtype=np.int32)),
        "grp": pa.array((np.arange(n_dim) % 3).astype(np.int32)),
    })
    lex = TpuShuffleExchangeExec(
        HashPartitioning([col("fk")], 3),
        HostBatchSourceExec([fact.slice(0, 250), fact.slice(250)]))
    rex = TpuShuffleExchangeExec(
        HashPartitioning([col("dk")], 3), HostBatchSourceExec([dim]))
    join = TpuShuffledHashJoinExec([col("fk")], [col("dk")], "inner",
                                   lex, rex)
    gex = TpuShuffleExchangeExec(HashPartitioning([col("grp")], 3),
                                 join)
    return TpuHashAggregateExec(
        [col("grp")], [Alias(Sum(col("amt")), "total")], gex), n_fact


def test_cluster_exchange_rows_match_consumer_input(wh_cluster):
    """Satellite regression: on a 2-worker join, every exchange's row
    count equals what its consumer read — never 0, never doubled."""
    c, d = wh_cluster
    plan, n_fact = _join_plan()
    before = len(read_rows(d))
    out = c.run_query(plan)
    assert out.num_rows == 3
    rows = read_rows(d)
    assert len(rows) == before + 1  # exactly ONE row for the query
    row = rows[-1]
    assert row["outcome"] == "completed"
    assert row["cluster"] == {"kind": "process", "n_workers": 2,
                              "mesh_incarnation": 0}
    # the cluster replaces each exchange with a ProcessShuffleReadExec
    # carrying the exchange's stable op id, so its read rows fold under
    # the exchange node
    exch_rows = sorted(
        op["rows"] for op in row["ops"].values()
        if op["label"].startswith(("ShuffleExchangeExec",
                                   "ProcessShuffleReadExec")))
    join_rows = sum(op["rows"] for op in row["ops"].values()
                    if op["label"].startswith("ShuffledHashJoinExec"))
    # lex carries the fact side (400), rex the dim side (10), gex the
    # join output — each exactly its consumer's input
    assert exch_rows == sorted([10, n_fact, join_rows])
    assert join_rows == n_fact  # every fact row hits one dim row
    # transport attribution: the workers really moved shuffle bytes
    # through host files, and the row saw the worker-side deltas
    assert row["bytes"]["host_written"] > 0
    assert row["bytes"]["gang_dcn"] == 0  # no mesh in this cluster


def test_cluster_failed_query_row_partial_attribution(wh_cluster):
    """A query that dies mid-flight still leaves ONE row —
    outcome=failed, with whatever attribution the .opm harvest
    recovered from completed stages."""
    c, d = wh_cluster
    from spark_rapids_tpu.io.scan import TpuFileScanExec
    rb = gen_table([IntegerGen(min_val=0, max_val=4, null_frac=0.0),
                    LongGen(nullable=False)], 300, seed=3,
                   names=["k", "v"])
    good = TpuShuffleExchangeExec(
        HashPartitioning([col("k")], 2), HostBatchSourceExec([rb]))
    schema = dt.Schema([dt.StructField("k", dt.INT32, True),
                        dt.StructField("v", dt.INT64, True)])
    bad = TpuShuffleExchangeExec(
        HashPartitioning([col("k")], 2),
        TpuFileScanExec(["/nonexistent/wh-fail.parquet"],
                        schema=schema))
    join = TpuShuffledHashJoinExec([col("k")], [col("k")], "inner",
                                   good, bad)
    plan = TpuHashAggregateExec([col("k")],
                                [Alias(Sum(col("v")), "s")], join)
    before = len(read_rows(d))
    with pytest.raises(Exception):
        c.run_query(plan)
    rows = read_rows(d)
    assert len(rows) == before + 1
    row = rows[-1]
    assert row["outcome"] == "failed" and row["error"]
    # the good map stage ran before the bad one killed the query: its
    # flushed .opm snapshots give the row partial attribution
    src_rows = sum(op["rows"] for op in row["ops"].values()
                   if op["label"].startswith("HostBatchSourceExec"))
    assert src_rows == 300


def test_cluster_status_doc_shape(wh_cluster):
    """The cluster's /status provider: worker census, mesh health, and
    the warehouse tail (in_flight is exercised end-to-end by CI step
    17's hang_query probe)."""
    c, d = wh_cluster
    doc = c._status_doc()
    json.loads(json.dumps(doc))  # serializable as served
    assert doc["cluster"]["n_workers"] == 2
    assert doc["in_flight"] == []  # nothing running right now
    assert doc["mesh"]["enabled"] is False
    tail = doc["warehouse_tail"]
    assert tail and set(tail[0]) == {"query_id", "tenant", "outcome",
                                     "wall_s", "device_kind",
                                     "fingerprint"}
