"""Multi-process execution tests (VERDICT r4 #2): real OS worker
processes exchanging Arrow-IPC shuffle files through a filesystem
rendezvous — rung 1 of the blueprint ladder (SURVEY.md:524-527, §3.4).
The whole point is the process boundary: each worker has its own JAX
runtime and nothing is shared but files."""
import os

import numpy as np
import pyarrow as pa
import pytest

from data_gen import IntegerGen, LongGen, StringGen, gen_table

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.cluster import TpuProcessCluster
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec.base import ExecCtx, HostBatchSourceExec
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
from spark_rapids_tpu.exec.joins import TpuShuffledHashJoinExec
from spark_rapids_tpu.expr import (Alias, Multiply,
                                   UnresolvedColumn as col)
from spark_rapids_tpu.expr.aggregates import Count, Sum
from spark_rapids_tpu.shuffle.partitioner import HashPartitioning


@pytest.fixture(scope="module")
def cluster():
    with TpuProcessCluster(n_workers=2) as c:
        yield c


def _canon_rows(table: pa.Table, sort_by):
    return sorted(map(tuple, pa.Table.from_arrays(
        [table.column(i) for i in range(table.num_columns)],
        names=table.column_names).to_pylist()), key=lambda r: tuple(
            (v is None, v) for v in r))


def _oracle(plan):
    rbs = list(plan.execute_cpu(ExecCtx()))
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_schema
    return pa.Table.from_batches(rbs, schema=arrow_schema(
        plan.output_schema))


def _rows(table):
    return sorted(
        map(tuple, table.to_pylist()),
        key=lambda r: tuple((v is None, str(v)) for v in r.values())) \
        if isinstance(table, dict) else sorted(
            table.to_pylist(), key=lambda d: tuple(
                (v is None, str(v)) for v in d.values()))


def test_process_shuffle_groupby(cluster):
    """shuffle -> final agg across two worker processes == CPU oracle."""
    rbs = [gen_table([IntegerGen(min_val=0, max_val=20, null_frac=0.1),
                      LongGen(nullable=False)], n, seed=s,
                     names=["k", "v"])
           for n, s in [(300, 1), (250, 2), (411, 3), (128, 4)]]
    src = HostBatchSourceExec(rbs)
    exch = TpuShuffleExchangeExec(HashPartitioning([col("k")], 4), src)
    plan = TpuHashAggregateExec(
        [col("k")], [Alias(Sum(col("v")), "s"),
                     Alias(Count(col("v")), "c")], exch)
    got = cluster.run_query(plan)
    want = _oracle(plan)
    assert _rows(got) == _rows(want)


def test_process_shuffle_join_agg(cluster):
    """The verdict's named bar: shuffle + join + agg dual-run across OS
    processes."""
    rng = np.random.default_rng(5)
    n_f, n_d = 2000, 64
    fact = pa.record_batch({
        "fk": pa.array(rng.integers(0, n_d, n_f).astype(np.int32)),
        "amt": pa.array(rng.integers(1, 100, n_f).astype(np.int64)),
    })
    dim = pa.record_batch({
        "dk": pa.array(np.arange(n_d, dtype=np.int32)),
        "grp": pa.array((np.arange(n_d) % 7).astype(np.int32)),
    })
    # two batches per side so both map stages have real splits
    fact_src = HostBatchSourceExec([fact.slice(0, 1100),
                                    fact.slice(1100)])
    dim_src = HostBatchSourceExec([dim.slice(0, 40), dim.slice(40)])
    nparts = 3
    lex = TpuShuffleExchangeExec(HashPartitioning([col("fk")], nparts),
                                 fact_src)
    rex = TpuShuffleExchangeExec(HashPartitioning([col("dk")], nparts),
                                 dim_src)
    join = TpuShuffledHashJoinExec([col("fk")], [col("dk")], "inner",
                                   lex, rex)
    # the agg groups by a NON-join key, so distributed execution needs
    # the re-partition exchange Spark would plan here; the cluster runs
    # this as three stages (two leaf maps, a join map, a reduce)
    gex = TpuShuffleExchangeExec(HashPartitioning([col("grp")], nparts),
                                 join)
    plan = TpuHashAggregateExec(
        [col("grp")], [Alias(Sum(col("amt")), "total"),
                       Alias(Count(col("amt")), "n")], gex)
    got = cluster.run_query(plan)
    want = _oracle(plan)
    assert _rows(got) == _rows(want)


def test_process_cluster_worker_error_surfaces(cluster):
    """A failing task raises on the driver with the worker traceback."""
    class Boom(HostBatchSourceExec):
        def execute(self, ctx):
            raise RuntimeError("boom-from-worker")
    # Boom is a local class: pickling it fails at submit OR raises in
    # the worker; either way the driver must not hang. Use a picklable
    # failure instead: scan of a missing file.
    from spark_rapids_tpu.io.scan import TpuFileScanExec
    schema = dt.Schema([dt.StructField("x", dt.INT64, True)])
    missing = TpuFileScanExec(["/nonexistent/x.parquet"], schema=schema)
    exch = TpuShuffleExchangeExec(HashPartitioning([col("x")], 2),
                                  missing)
    plan = TpuHashAggregateExec([], [Alias(Count(col("x")), "c")], exch)
    with pytest.raises(RuntimeError, match="worker task"):
        cluster.run_query(plan)


def test_multichild_leaf_stage_splits_into_per_child_tasks(cluster):
    """A join directly over two batch sources below ONE exchange used
    to collapse to a single map task; the stage must split over the
    side with the most input pieces (the other side rides whole in
    every task) and still match the oracle."""
    from spark_rapids_tpu.cluster import _split_leaf_input
    rng = np.random.default_rng(9)
    n_f, n_d = 800, 32
    fact = pa.record_batch({
        "fk": pa.array(rng.integers(0, n_d, n_f).astype(np.int32)),
        "amt": pa.array(rng.integers(1, 50, n_f).astype(np.int64)),
    })
    dim = pa.record_batch({
        "dk": pa.array(np.arange(n_d, dtype=np.int32)),
        "grp": pa.array((np.arange(n_d) % 5).astype(np.int32)),
    })
    fact_src = HostBatchSourceExec([fact.slice(i * 200, 200)
                                    for i in range(4)])
    dim_src = HostBatchSourceExec([dim.slice(0, 16), dim.slice(16)])
    join = TpuShuffledHashJoinExec([col("fk")], [col("dk")], "inner",
                                   fact_src, dim_src)
    gex = TpuShuffleExchangeExec(HashPartitioning([col("grp")], 3),
                                 join)
    plan = TpuHashAggregateExec(
        [col("grp")], [Alias(Sum(col("amt")), "total")], gex)
    # unit: the stage splits into n tasks, fact sliced, dim replicated
    slices = _split_leaf_input(join, 2)
    assert len(slices) == 2
    for s in slices:
        f, d = s.children
        assert len(f.batches) == 2 and len(d.batches) == 2
    # aliased self-join leaves must never slice (both sides would)
    self_join = TpuShuffledHashJoinExec([col("fk")], [col("fk")],
                                        "inner", fact_src, fact_src)
    assert _split_leaf_input(self_join, 2) == [self_join]
    # end to end: two map tasks for the join stage, result == oracle
    got = cluster.run_query(plan)
    qid = cluster._query_seq
    join_maps = {e["task"] for e in cluster.last_scheduler.events
                 if e["event"] == "task_ok"
                 and e["task"].startswith(f"q{qid}s")
                 and "m" in e["task"]}
    assert len({t for t in join_maps if t.endswith(("m0", "m1"))}) >= 2
    assert _rows(got) == _rows(_oracle(plan))
