"""Multi-host mesh runtime (ISSUE 16): one logical (dcn, ici) device
mesh spanning two real OS worker processes, gang-scheduled SPMD queries
whose shuffle exchanges cross the process boundary as XLA collectives,
and the failure ladder around them — cooperative cancel with zero
orphaned processes, gang-member death -> remesh -> retry, and the
single-process fallback. The whole point is the process boundary:
`jax.distributed` spans real processes, nothing is shared but the
rendezvous filesystem and the coordinator socket."""
import os
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.cluster import (TpuProcessCluster,
                                      _mesh_ineligible,
                                      _slice_for_member)
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.exec.base import ExecCtx, HostBatchSourceExec
from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
from spark_rapids_tpu.exec.joins import TpuShuffledHashJoinExec
from spark_rapids_tpu.expr import Alias, UnresolvedColumn as col
from spark_rapids_tpu.expr.aggregates import Count, Sum
from spark_rapids_tpu.lifecycle import QueryCancelled
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.shuffle.partitioner import (HashPartitioning,
                                                  SinglePartitioning)

MESH_CONF = {"spark.rapids.tpu.mesh.enabled": "true"}


@pytest.fixture(scope="module")
def mesh_cluster():
    with TpuProcessCluster(n_workers=2,
                           conf=RapidsConf(MESH_CONF)) as c:
        yield c


def _oracle(plan):
    rbs = list(plan.execute_cpu(ExecCtx()))
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_schema
    return pa.Table.from_batches(rbs, schema=arrow_schema(
        plan.output_schema))


def _rows(table):
    return sorted(table.to_pylist(), key=lambda d: tuple(
        (v is None, str(v)) for v in d.values()))


def _events(cluster, name):
    return [e for e in cluster.last_scheduler.events
            if e["event"] == name]


def _fact_dim(n_f=1200, n_d=48, seed=11):
    rng = np.random.default_rng(seed)
    fact = pa.record_batch({
        "fk": pa.array(rng.integers(0, n_d, n_f).astype(np.int32)),
        "amt": pa.array(rng.integers(1, 100, n_f).astype(np.int64)),
    })
    dim = pa.record_batch({
        "dk": pa.array(np.arange(n_d, dtype=np.int32)),
        "grp": pa.array((np.arange(n_d) % 5).astype(np.int32)),
    })
    return fact, dim


def _join_agg_plan(nparts=3, n_fact_batches=4):
    """shuffle(fact) >< shuffle(dim) -> regroup exchange -> agg: three
    exchanges, every leaf below one, the smoke-proven gang shape."""
    fact, dim = _fact_dim()
    step = fact.num_rows // n_fact_batches
    fact_src = HostBatchSourceExec(
        [fact.slice(i * step, step if i < n_fact_batches - 1 else None)
         for i in range(n_fact_batches)])
    dim_src = HostBatchSourceExec([dim.slice(0, 30), dim.slice(30)])
    lex = TpuShuffleExchangeExec(HashPartitioning([col("fk")], nparts),
                                 fact_src)
    rex = TpuShuffleExchangeExec(HashPartitioning([col("dk")], nparts),
                                 dim_src)
    join = TpuShuffledHashJoinExec([col("fk")], [col("dk")], "inner",
                                   lex, rex)
    gex = TpuShuffleExchangeExec(HashPartitioning([col("grp")], nparts),
                                 join)
    return TpuHashAggregateExec(
        [col("grp")], [Alias(Sum(col("amt")), "total"),
                       Alias(Count(col("amt")), "n")], gex)


def _assert_gang_ran(cluster, gen=0):
    """The query rode the mesh gang path: no fallback, one task_ok per
    member with the gang task-id shape."""
    assert not _events(cluster, "mesh_fallback"), \
        _events(cluster, "mesh_fallback")
    oks = [e["task"] for e in _events(cluster, "task_ok")]
    gang = [t for t in oks if f"g{gen}w" in t]
    assert len(gang) == cluster.n_workers, (oks, gang)


# --- the gang path ---------------------------------------------------------

@pytest.mark.slow  # covered in tier 1 by the SQL-text variant below,
# which runs the same gang join+agg shape to the same oracle
def test_mesh_gang_join_agg_matches_oracle(mesh_cluster):
    """Join + regroup + agg as ONE SPMD program over a mesh spanning
    two worker processes; every exchange is a cross-process collective,
    result identical to the in-process CPU oracle."""
    plan = _join_agg_plan()
    got = mesh_cluster.run_query(plan)
    _assert_gang_ran(mesh_cluster)
    assert _rows(got) == _rows(_oracle(plan))


def test_mesh_sql_join_explain_analyze(mesh_cluster):
    """The acceptance bar: a join query from SQL TEXT runs over ICI
    spanning two processes, and EXPLAIN ANALYZE folds operator metrics
    across both (tasks=2 on the operators every member executed)."""
    fact, dim = _fact_dim(seed=23)
    s = TpuSession(conf={"spark.sql.autoBroadcastJoinThreshold": "-1",
                         "spark.sql.shuffle.partitions": "4"})
    # four fact batches so the gang has real per-member slices
    fact_t = pa.Table.from_batches([fact])
    s.register_table("fact", pa.Table.from_batches(
        [b for i in range(4)
         for b in fact_t.slice(i * 300, 300).to_batches()]))
    s.register_table("dim", pa.Table.from_batches([dim]))
    s.set_cluster(mesh_cluster)
    sql = ("SELECT d.grp, SUM(f.amt) AS total, COUNT(*) AS n "
           "FROM fact f JOIN dim d ON f.fk = d.dk GROUP BY d.grp")
    analyzed = s.sql("EXPLAIN ANALYZE " + sql)
    _assert_gang_ran(mesh_cluster)
    assert "tasks=2" in analyzed, analyzed
    # correctness against a numpy oracle (dk == arange, so grp and the
    # per-group sums are direct indexing)
    fk = fact.column("fk").to_numpy()
    amt = fact.column("amt").to_numpy()
    grp_of = dim.column("grp").to_numpy()[fk]
    want = sorted((int(g), int(amt[grp_of == g].sum()),
                   int((grp_of == g).sum()))
                  for g in np.unique(grp_of))
    got_t = mesh_cluster.run_query(s.sql(sql)._plan().root)
    got = sorted((r["grp"], r["total"], r["n"])
                 for r in got_t.to_pylist())
    assert got == want


@pytest.mark.slow  # boots its own 1-worker cluster; the local-mesh
# bootstrap path it exercises also runs in every dryrun/ci-smoke
def test_mesh_single_process_fallback():
    """n_workers=1 with mesh on: the runtime bootstraps the local
    (1, L) mesh — no coordinator — and gang queries still run and
    match the oracle."""
    from spark_rapids_tpu.distributed.runtime import read_mesh_markers
    plan = _join_agg_plan(nparts=2, n_fact_batches=2)
    with TpuProcessCluster(n_workers=1,
                           conf=RapidsConf(MESH_CONF)) as c:
        got = c.run_query(plan)
        _assert_gang_ran(c)
        docs = read_mesh_markers(c.root, 1, 0)
        assert docs and docs[0]["ok"] \
            and docs[0]["distributed"] is False
    assert _rows(got) == _rows(_oracle(plan))


# --- the failure ladder ----------------------------------------------------

def test_mesh_cancel_no_orphans(mesh_cluster):
    """Cancel mid-gang while every member stalls inside the stage:
    exactly one classified QueryCancelled, the whole incarnation is
    torn down (no orphaned worker processes, no wedged collectives),
    and the next mesh query on the same cluster runs green."""
    old_pids = [p.pid for p in mesh_cluster.pool._procs]
    plan = _join_agg_plan()
    conf = RapidsConf(dict(
        MESH_CONF, **{
            "spark.rapids.tpu.test.injectFaults":
                "hang_query:q*g*w*:*:60",
            "spark.rapids.query.cancel.joinTimeout": "10"}))
    canceller = threading.Timer(
        2.0, lambda: mesh_cluster.cancel_running("operator ctrl-c"))
    canceller.start()
    with pytest.raises(QueryCancelled) as ei:
        mesh_cluster.run_query(plan, conf)
    canceller.cancel()
    assert ei.value.reason == "user"
    assert len(_events(mesh_cluster, "query_cancelled")) == 1
    # cancel remeshed the fleet: every member of the cancelled gang's
    # incarnation is dead (waitpid-verified via the pool), none leaked
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        gone = []
        for pid in old_pids:
            try:
                os.kill(pid, 0)
                gone.append(False)
            except ProcessLookupError:
                gone.append(True)
        if all(gone):
            break
        time.sleep(0.1)
    assert all(gone), (old_pids, gone)
    assert all(mesh_cluster.pool.alive(w)
               for w in range(mesh_cluster.n_workers))
    # the cluster is not poisoned: the fresh incarnation runs a gang
    got = mesh_cluster.run_query(plan)
    _assert_gang_ran(mesh_cluster)
    assert _rows(got) == _rows(_oracle(plan))


def test_mesh_gang_member_crash_remesh_retry(mesh_cluster):
    """One member dies mid-gang: the WHOLE gang fails (never half a
    collective), the fleet remeshes under a new incarnation, and the
    retry generation completes on the gang path with a correct
    result."""
    plan = _join_agg_plan()
    conf = RapidsConf(dict(
        MESH_CONF, **{"spark.rapids.tpu.test.injectFaults":
                      "crash:q*g0w1:*"}))
    got = mesh_cluster.run_query(plan, conf)
    assert _events(mesh_cluster, "gang_failed")
    assert any("remesh" in e.get("reason", "")
               for e in _events(mesh_cluster, "worker_respawn"))
    _assert_gang_ran(mesh_cluster, gen=1)
    assert _rows(got) == _rows(_oracle(plan))


# --- plan gating and slicing (no cluster) ----------------------------------

def _mini_src(nbatch=2, name="k"):
    rb = pa.record_batch({name: pa.array([1, 2, 3], pa.int32()),
                          "v": pa.array([10, 20, 30], pa.int64())})
    return HostBatchSourceExec([rb] * nbatch)


def test_mesh_ineligible_reasons():
    src = _mini_src()
    assert "no shuffle exchange" in _mesh_ineligible(
        TpuHashAggregateExec([col("k")],
                             [Alias(Sum(col("v")), "s")], src))
    # a leaf above every exchange replays once per member
    ex = TpuShuffleExchangeExec(HashPartitioning([col("k")], 2), src)
    join = TpuShuffledHashJoinExec([col("k")], [col("k")], "inner",
                                   ex, _mini_src())
    assert "above every exchange" in _mesh_ineligible(join)
    # a stage mixing a deeper exchange with a raw leaf beside it
    outer = TpuShuffleExchangeExec(HashPartitioning([col("k")], 2),
                                   join)
    assert "mixes exchange input" in _mesh_ineligible(outer)
    # non-hash exchange
    single = TpuShuffleExchangeExec(SinglePartitioning(), src)
    assert "exchange" in _mesh_ineligible(single)


def test_slice_for_member_one_distribution_source_per_stage():
    """Join directly over two raw leaves below ONE exchange: exactly
    one side is sliced per member (the other replicates whole), so the
    member contributions stay a disjoint cover of the true join."""
    fact_src = _mini_src(nbatch=4, name="fk")
    dim_src = _mini_src(nbatch=2, name="dk")
    join = TpuShuffledHashJoinExec([col("fk")], [col("dk")], "inner",
                                   fact_src, dim_src)
    ex = TpuShuffleExchangeExec(HashPartitioning([col("fk")], 2), join)
    plan = TpuHashAggregateExec([col("fk")],
                                [Alias(Sum(col("v")), "s")], ex)
    assert _mesh_ineligible(plan) is None
    seen = []
    for k in range(2):
        m = _slice_for_member(plan, k, 2)
        f, d = m.child.child.children
        assert len(f.batches) == 2, "fact side carries the k::n slice"
        assert len(d.batches) == 2, "dim side replicates whole"
        seen.append(len(f.batches))
    assert sum(seen) == 4


def test_slice_for_member_aliased_leaf_runs_on_member0():
    """A self-join sharing ONE source object cannot slice either side
    (the slice would apply to both); the stage runs whole on member 0
    and empty elsewhere — still a disjoint cover."""
    src = _mini_src(nbatch=4)
    join = TpuShuffledHashJoinExec([col("k")], [col("k")], "inner",
                                   src, src)
    ex = TpuShuffleExchangeExec(HashPartitioning([col("k")], 2), join)
    plan = TpuHashAggregateExec([col("k")],
                                [Alias(Count(col("v")), "c")], ex)
    m0 = _slice_for_member(plan, 0, 2)
    m1 = _slice_for_member(plan, 1, 2)
    assert all(len(c.batches) == 4
               for c in m0.child.child.children)
    assert all(len(c.batches) == 0
               for c in m1.child.child.children)
