"""Per-operator metrics layer tests (obs/opmetrics.py): stable
operator-instance ids, always-on row/batch accounting, cross-worker
folding, EXPLAIN ANALYZE, query-profile history and regression
comparison.

The acceptance shape from the issue: per-operator totals match oracle
row counts on a process-cluster join query; a worker crash leaves
partial snapshots harvested (not a crashed fold); EXPLAIN ANALYZE text
carries every operator id exactly once; `profiling compare` flags a
seeded 2x regression.
"""
import copy
import json
import os
import pickle
import re

import pyarrow as pa
import pytest

from spark_rapids_tpu.cluster import TpuProcessCluster
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.exec.base import HostBatchSourceExec
from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
from spark_rapids_tpu.expr import Alias, UnresolvedColumn as col
from spark_rapids_tpu.expr.aggregates import Sum
from spark_rapids_tpu.obs.opmetrics import (assign_op_ids, fold_ctx,
                                            fold_snapshots, plan_source,
                                            render_analyzed)
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.shuffle.partitioner import HashPartitioning

OPID_RE = re.compile(r"\(op(\d+)\)")


def _session(extra=None):
    conf = {"spark.sql.shuffle.partitions": "2"}
    conf.update(extra or {})
    return TpuSession(conf)


def _join_agg_df(s, n_left=400, n_dim=10):
    left = s.create_dataframe({
        "k": [i % n_dim for i in range(n_left)],
        "v": list(range(n_left))})
    dim = s.create_dataframe({
        "k": list(range(n_dim)),
        "name": [f"d{i}" for i in range(n_dim)]})
    return left.join(dim, on="k").group_by("name").agg(
        Alias(Sum(col("v")), "sv"))


def _ops_by_name(folded, name):
    return [st for st in folded.values()
            if st["label"].split("#", 1)[0] == name]


def _rows_total(folded, name):
    return sum(int(st["metrics"].get("rows", 0))
               for st in _ops_by_name(folded, name))


# --- stable ids --------------------------------------------------------------

def test_op_ids_unique_and_survive_pickle_and_deepcopy():
    s = _session()
    pp = _join_agg_df(s)._plan()
    labels = []

    def walk(n, seen):
        if id(n) in seen:
            return
        seen.add(id(n))
        labels.append(n.node_label())
        for c in n.children:
            walk(c, seen)

    walk(pp.root, set())
    assert all("#op" in lb for lb in labels), labels
    assert len(labels) == len(set(labels)), labels
    # ids ride the task pickle and deep copies unchanged — that is what
    # lets worker snapshots fold back under the driver's labels
    for clone in (pickle.loads(pickle.dumps(pp.root)),
                  copy.deepcopy(pp.root)):
        c_labels = []

        def walk2(n, seen):
            if id(n) in seen:
                return
            seen.add(id(n))
            c_labels.append(n.node_label())
            for c in n.children:
                walk2(c, seen)

        walk2(clone, set())
        assert c_labels == labels


def test_assign_op_ids_shares_aliased_subtrees():
    src = HostBatchSourceExec([pa.record_batch({"k": [1, 2]})])
    agg = TpuHashAggregateExec([col("k")], [Alias(Sum(col("k")), "s")],
                               src)
    # the same exchange object under two parents (self-join shape)
    exch = TpuShuffleExchangeExec(HashPartitioning([col("k")], 2), agg)
    from spark_rapids_tpu.exec.misc import TpuUnionExec
    root = TpuUnionExec([exch, exch])
    assign_op_ids(root, force=True)
    assert root.children[0] is root.children[1]
    assert root.children[0]._op_id == root.children[1]._op_id


# --- local EXPLAIN ANALYZE ---------------------------------------------------

def test_explain_analyze_local_ids_unique_and_rows():
    s = _session()
    s.register_table("t", {"k": [i % 3 for i in range(90)],
                           "v": list(range(90))})
    text = s.sql("EXPLAIN ANALYZE SELECT k, SUM(v) AS sv FROM t "
                 "GROUP BY k ORDER BY k")
    ids = OPID_RE.findall(text)
    assert ids, text
    assert len(ids) == len(set(ids)), f"duplicate op ids: {text}"
    # the source and the aggregate both report their true row counts
    src_line = next(ln for ln in text.splitlines()
                    if "HostBatchSourceExec" in ln)
    assert "rows=90" in src_line, src_line
    agg_line = next(ln for ln in text.splitlines()
                    if "HashAggregateExec" in ln)
    assert "rows=3" in agg_line, agg_line
    # FORMATTED renders the full metric set
    full = s.sql("EXPLAIN ANALYZE FORMATTED SELECT k, SUM(v) AS sv "
                 "FROM t GROUP BY k ORDER BY k")
    assert "outputBytes=" in full, full


def test_explain_analyze_marks_fused_and_sql_source():
    s = _session()
    s.register_table("t", {"k": [1, 2, 3, 4], "v": [1.0, 2.0, 3.0, 4.0]})
    df = s.sql("SELECT k + 1 AS k1 FROM t WHERE v > 1.5")
    assert plan_source(df._node) == "sql"
    pp = df._plan()
    pp.collect()
    text = pp.explain_analyze()
    # project/filter chains fuse into one XLA program below their
    # consumer: the fused node is marked with the program it joined
    # ("fused into opN's program"; nodes with no metrics at all still
    # get the generic parent-stage marker) — never silently zeroed
    assert "fused into" in text, text


# --- process cluster: fold across workers ------------------------------------

def test_cluster_join_totals_match_oracle_rows():
    s = _session()
    df = _join_agg_df(s, n_left=400, n_dim=10)
    with TpuProcessCluster(n_workers=2) as c:
        out = c.run_query(df._plan().root)
        folded = c.last_opmetrics
        analyzed = c.last_analyzed()
    assert out.num_rows == 10
    assert sorted(r["sv"] for r in out.to_pylist()) == sorted(
        sum(v for v in range(400) if v % 10 == k) for k in range(10))
    # per-operator totals match the oracle row counts exactly
    assert _rows_total(folded, "HostBatchSourceExec") == 400 + 10
    assert _rows_total(folded, "ShuffledHashJoinExec") == 400
    assert _rows_total(folded, "HashAggregateExec") == 10
    # the exchange folds with its reduce-side read: output rows = what
    # the reducers consumed = the join's 400 output rows
    exch_line = next(ln for ln in analyzed.splitlines()
                     if "ShuffleExchangeExec" in ln)
    assert "rows=400" in exch_line, analyzed
    # cross-worker aggregation is visible: the reduce ops ran as 2 tasks
    agg_st = _ops_by_name(folded, "HashAggregateExec")[0]
    assert agg_st["tasks"] == 2, agg_st
    assert agg_st["skew"] >= 1.0


def test_cluster_worker_crash_partial_snapshots_harvested():
    rbs = [pa.record_batch({"k": [i % 5 for i in range(300)],
                            "v": list(range(300))}),
           pa.record_batch({"k": [i % 5 for i in range(300, 600)],
                            "v": list(range(300, 600))})]
    src = HostBatchSourceExec(rbs)
    plan = TpuHashAggregateExec(
        [col("k")], [Alias(Sum(col("v")), "s")],
        TpuShuffleExchangeExec(HashPartitioning([col("k")], 4), src))
    conf = RapidsConf({
        "spark.rapids.tpu.test.injectFaults": "crash:q1s1m0:0"})
    with TpuProcessCluster(n_workers=2, conf=conf) as c:
        out = c.run_query(plan)
        folded = c.last_opmetrics
        sched = c.last_scheduler
    assert out.num_rows == 5
    # the crash really happened and was retried
    assert any(e["event"] == "task_failed" for e in sched.events)
    # fold survives the crashed attempt's missing/partial snapshot and
    # counts ONLY winning attempts: source rows are exact, not doubled
    assert _rows_total(folded, "HostBatchSourceExec") == 600
    assert _rows_total(folded, "HashAggregateExec") == 5


def test_fold_tolerates_torn_snapshot(tmp_path):
    # a torn .opm.json (crash mid-write) is skipped, never fatal
    from spark_rapids_tpu.obs.opmetrics import read_task_opmetrics
    good = tmp_path / "t1.a0.w0.task.opm.json"
    good.write_text(json.dumps(
        {"task": "t1", "attempt": 0,
         "ops": {"FooExec#op1": {"rows": 7, "opTime": 0.1}}}))
    torn = tmp_path / "t2.a1.w1.task.opm.json"
    torn.write_text('{"task": "t2", "ops": {"FooExec#')
    snaps = read_task_opmetrics(str(tmp_path),
                                [("t1", 0, 0), ("t2", 1, 1),
                                 ("t3", 0, 0)])
    assert len(snaps) == 1 and snaps[0]["task"] == "t1"
    folded = fold_snapshots(snaps)
    assert folded["op1"]["metrics"]["rows"] == 7


# --- profiles + history + compare --------------------------------------------

def test_profile_written_and_history_renders(tmp_path):
    hist = str(tmp_path / "hist")
    s = _session({"spark.rapids.history.dir": hist})
    df = _join_agg_df(s)
    pp = df._plan()
    pp.collect()
    assert pp.last_profile_path and os.path.exists(pp.last_profile_path)
    doc = json.load(open(pp.last_profile_path))
    assert doc["cluster"] == "local" and doc["source"] == "plan"
    assert doc["ops"] and doc["nodes"]
    from spark_rapids_tpu.tools.profiling import history_report
    listing = history_report(hist)
    assert doc["profile_id"] in listing
    inspect = history_report(hist, doc["profile_id"])
    assert "HashAggregateExec" in inspect and "rows=" in inspect


def test_profiling_compare_flags_seeded_2x_regression(tmp_path):
    hist = str(tmp_path / "hist")
    s = _session({"spark.rapids.history.dir": hist})
    df = _join_agg_df(s)
    pp = df._plan()
    pp.collect()
    a_path = pp.last_profile_path
    pp2 = df._plan()
    pp2.collect()
    b_path = pp2.last_profile_path
    assert a_path != b_path
    # seed a 2x opTime regression into run B's hottest operator
    a = json.load(open(a_path))
    b = json.load(open(b_path))
    key = max(a["ops"], key=lambda k: a["ops"][k]["metrics"]
              .get("opTime", 0.0))
    seeded = a["ops"][key]["metrics"]["opTime"] * 2.0 + 0.01
    b["ops"][key]["metrics"]["opTime"] = seeded
    b["ops"][key]["max"]["opTime"] = seeded
    with open(b_path, "w") as f:
        json.dump(b, f)
    from spark_rapids_tpu.tools.profiling import compare_report
    rep = compare_report(a_path, b_path, threshold=1.5)
    flagged = [ln for ln in rep.splitlines() if "REGRESSED" in ln]
    assert len(flagged) == 1, rep
    assert a["ops"][key]["label"] in flagged[0], rep
    # and an identical pair flags nothing
    rep_same = compare_report(a_path, a_path, threshold=1.5)
    assert "REGRESSED" not in rep_same
    assert "0 regression(s)" in rep_same


def test_compare_accepts_bench_json(tmp_path):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps({"parsed": {"value": 30.0, "frac": 0.2}}))
    b.write_text(json.dumps({"parsed": {"value": 10.0, "frac": 0.21}}))
    from spark_rapids_tpu.tools.profiling import compare_report
    rep = compare_report(str(a), str(b), threshold=1.5)
    assert "bench compare" in rep
    assert "CHANGED" in rep and "value" in rep


def test_compare_refuses_cross_device_kind(tmp_path):
    """Comparability guard: profiles/benches measured on different
    hardware REFUSE to diff (a CPU-backend run read against a TPU run
    is a ~1000x fake regression, not a result) unless the cross-device
    diff is explicitly forced — then the report leads with a warning."""
    from spark_rapids_tpu.tools.profiling import compare_report
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(
        {"parsed": {"value": 30.0, "device_kind": "TPU v5 lite"}}))
    b.write_text(json.dumps(
        {"parsed": {"value": 0.02, "device_kind": "cpu"}}))
    rep = compare_report(str(a), str(b), threshold=1.5)
    assert rep.startswith("=== compare REFUSED"), rep
    assert "device_kind" in rep and "cpu" in rep
    forced = compare_report(str(a), str(b), threshold=1.5,
                            allow_cross_device=True)
    assert "WARNING" in forced.splitlines()[0]
    assert "bench compare" in forced
    # same-kind docs still compare cleanly
    c = tmp_path / "c.json"
    c.write_text(json.dumps(
        {"parsed": {"value": 29.0, "device_kind": "TPU v5 lite"}}))
    rep_ok = compare_report(str(a), str(c), threshold=1.5)
    assert "REFUSED" not in rep_ok and "bench compare" in rep_ok
    # profile docs carry device_kind too (build_profile records it)
    pa_ = tmp_path / "pa.json"
    pb_ = tmp_path / "pb.json"
    ops = {"op1": {"label": "ProjectExec#op1",
                   "metrics": {"opTime": 0.1, "rows": 10},
                   "max": {"opTime": 0.1}, "tasks": 1, "skew": 1.0}}
    pa_.write_text(json.dumps({"profile_id": "profile-a", "ops": ops,
                               "wall_s": 0.2,
                               "device_kind": "TPU v5 lite"}))
    pb_.write_text(json.dumps({"profile_id": "profile-b", "ops": ops,
                               "wall_s": 0.2, "device_kind": "cpu"}))
    assert compare_report(str(pa_), str(pb_)).startswith(
        "=== compare REFUSED")


# --- event log + duration histogram satellites -------------------------------

def test_event_log_embeds_top_op_sinks(tmp_path):
    log_dir = str(tmp_path / "events")
    s = _session({"spark.rapids.eventLog.dir": log_dir})
    _join_agg_df(s).collect()
    from spark_rapids_tpu.tools.event_log import read_event_logs
    evs = [e for e in read_event_logs(log_dir) if "op_sinks" in e]
    assert evs, "no query event with op_sinks"
    sinks = evs[-1]["op_sinks"]
    assert 1 <= len(sinks) <= 3
    times = [sk["time_s"] for sk in sinks]
    assert times == sorted(times, reverse=True)
    assert all("#" in sk["op"] and sk["rows"] >= 0 for sk in sinks)


def test_query_duration_histogram_observed():
    from spark_rapids_tpu.obs.metrics import REGISTRY
    s = _session()
    _join_agg_df(s).collect()
    snap = REGISTRY.snapshot()["rapids_query_duration_seconds"]
    assert snap["kind"] == "histogram"
    assert snap["labelnames"] == ["source", "cluster"]
    key = "plan\tlocal"
    assert key in snap["samples"], snap["samples"].keys()
    assert snap["samples"][key]["count"] >= 1


def test_no_double_count_on_super_delegating_execute():
    # TpuBroadcastNestedLoopJoinExec.execute delegates to the wrapped
    # _BaseJoinExec.execute via super() for conditionless cross joins:
    # both shims fire, but the re-entrancy guard must count each batch
    # exactly once
    s = _session()
    left = s.create_dataframe({"a": [1, 2, 3]})
    right = s.create_dataframe({"b": [10, 20]})
    df = left.join(right, on=None)  # cross join, no condition
    pp = df._plan()
    out = pp.collect()
    assert out.num_rows == 6
    folded = fold_ctx(pp.last_ctx)
    join = _ops_by_name(folded, "BroadcastNestedLoopJoinExec")[0]
    assert join["metrics"]["rows"] == 6, join
    assert join["metrics"]["batches"] == 1, join


def test_render_analyzed_direct():
    # render over a raw (unplanned) tree falls back to per-instance
    # labels and never throws on empty folds
    src = HostBatchSourceExec([pa.record_batch({"k": [1, 2, 3]})])
    assign_op_ids(src, force=True)
    text = render_analyzed(src, {}, cluster="local")
    assert "HostBatchSourceExec" in text
    text2 = render_analyzed(
        src, fold_snapshots([{"ops": {src.node_label():
                                      {"rows": 3, "batches": 1}}}]))
    assert "rows=3" in text2
