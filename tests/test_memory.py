"""Memory runtime tests: budget ledger, spill, semaphore, split-retry,
out-of-core sort and aggregate merge (reference:
RapidsDeviceMemoryStoreSuite / RmmSparkRetrySuiteBase / out-of-core sort —
SURVEY.md §4.2, §5.3, §5.7)."""
import threading
import time

import pyarrow as pa
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
from spark_rapids_tpu.exec import HostBatchSourceExec
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow, \
    collect_arrow_cpu
from spark_rapids_tpu.exec.sort import SortOrder, TpuSortExec
from spark_rapids_tpu.expr import Alias, UnresolvedColumn as col
from spark_rapids_tpu.expr.aggregates import Count, Max, Min, Sum
from spark_rapids_tpu.memory import (DeviceMemoryManager, TpuRetryOOM,
                                     split_batch)

from data_gen import (DoubleGen, IntegerGen, LongGen, StringGen, gen_table)


def _rb(n, seed=1, gens=None, names=None):
    gens = gens or [IntegerGen(min_val=0, max_val=50), LongGen()]
    return gen_table(gens, n, seed, names)


def _norm(table):
    """NaN-safe pydict for exact-order comparison."""
    import math
    out = {}
    for name, colvals in table.to_pydict().items():
        out[name] = ["NaN" if isinstance(v, float) and math.isnan(v) else v
                     for v in colvals]
    return out


def _sorted_rows(table):
    rows = zip(*[table.column(i).to_pylist()
                 for i in range(table.num_columns)])
    return sorted(rows, key=lambda r: tuple(
        (v is None, str(type(v)), v if v is not None else 0) for v in r))


# --- ledger / spill -------------------------------------------------------

def test_catalog_spills_lru_under_budget():
    conf = RapidsConf({"spark.rapids.memory.device.budgetBytes": 1 << 14})
    mm = DeviceMemoryManager(conf)
    sbs = []
    for i in range(8):
        b = arrow_to_device(_rb(256, seed=i))
        sbs.append(mm.register(b))
    assert mm.device_bytes <= mm.budget
    assert any(not sb.on_device for sb in sbs)  # older ones spilled
    assert mm.spill_bytes > 0
    # spilled batch round-trips through host Arrow intact
    spilled = next(sb for sb in sbs if not sb.on_device)
    again = spilled.get()
    assert again.num_rows == 256
    for sb in sbs:
        sb.release()
    assert mm.device_bytes == 0


def test_spillable_roundtrip_preserves_strings():
    conf = RapidsConf({"spark.rapids.memory.device.budgetBytes": 1})
    mm = DeviceMemoryManager(conf)
    rb = _rb(64, gens=[StringGen(max_len=10), IntegerGen()])
    sb = mm.register(arrow_to_device(rb))
    assert not sb.on_device or mm.device_bytes > mm.budget
    mm._evict_to_fit()
    from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
    assert device_to_arrow(sb.get()).equals(rb)


# --- semaphore ------------------------------------------------------------

def test_semaphore_limits_concurrency():
    conf = RapidsConf({"spark.rapids.sql.concurrentGpuTasks": 1})
    mm = DeviceMemoryManager(conf)
    active = []
    peak = []

    def task():
        with mm.task_slot():
            active.append(1)
            peak.append(len(active))
            time.sleep(0.02)
            active.pop()

    threads = [threading.Thread(target=task) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert max(peak) == 1


# --- split-and-retry ------------------------------------------------------

def test_split_batch_halves_rows():
    rb = _rb(300, gens=[IntegerGen(), StringGen(max_len=6)])
    b = arrow_to_device(rb)
    b1, b2 = split_batch(b)
    from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
    t = pa.Table.from_batches([device_to_arrow(b1), device_to_arrow(b2)])
    assert t.to_pydict() == pa.Table.from_batches([rb]).to_pydict()


def test_injected_oom_split_retry_aggregate():
    """spark.rapids.sql.test.injectRetryOOM forces an OOM inside the fused
    stage; split-and-retry halves the batch and the result is unchanged."""
    rb = _rb(512, seed=3)
    plan = TpuHashAggregateExec(
        [col("c0")], [Alias(Sum(col("c1")), "s"), Alias(Count(), "n")],
        HostBatchSourceExec([rb]))
    want = _sorted_rows(collect_arrow_cpu(plan))
    ctx = ExecCtx(RapidsConf({"spark.rapids.sql.test.injectRetryOOM": 1}))
    got = _sorted_rows(collect_arrow(plan, ctx))
    assert got == want


def test_injected_oom_exhausts_splits():
    conf = RapidsConf({"spark.rapids.sql.oomRetry.enabled": False})
    mm = DeviceMemoryManager(conf)

    def boom(_):
        raise TpuRetryOOM("RESOURCE_EXHAUSTED: fake")

    b = arrow_to_device(_rb(64))
    with pytest.raises(TpuRetryOOM):
        mm.with_retry(b, boom)


def test_non_oom_errors_not_retried():
    mm = DeviceMemoryManager(RapidsConf())
    calls = []

    def boom(_):
        calls.append(1)
        raise ValueError("not an oom")

    b = arrow_to_device(_rb(64))
    with pytest.raises(ValueError):
        mm.with_retry(b, boom)
    assert len(calls) == 1


# --- out-of-core sort and aggregate --------------------------------------

@pytest.mark.parametrize("gens,names", [
    ([LongGen(), DoubleGen(null_frac=0.1)], None),
    ([StringGen(max_len=8), IntegerGen(null_frac=0.1)], None),
])
def test_out_of_core_sort_forced_spill(gens, names):
    """Sort at data size >> device budget: external merge with host spill
    produces exactly the oracle's ordering."""
    rbs = [_rb(500, seed=s, gens=gens, names=names) for s in range(6)]
    plan = TpuSortExec([SortOrder(col("c0")), SortOrder(col("c1"))],
                       HostBatchSourceExec(rbs))
    conf = RapidsConf({"spark.rapids.memory.device.budgetBytes": 1 << 13})
    ctx = ExecCtx(conf)
    got = collect_arrow(plan, ctx)
    want = collect_arrow_cpu(plan)
    assert _norm(got) == _norm(want)
    assert ctx.mm.spill_bytes > 0  # really went out-of-core


def test_out_of_core_aggregate_bounded_merge():
    rbs = [_rb(400, seed=s) for s in range(8)]
    plan = TpuHashAggregateExec(
        [col("c0")],
        [Alias(Sum(col("c1")), "s"), Alias(Min(col("c1")), "lo"),
         Alias(Max(col("c1")), "hi"), Alias(Count(), "n")],
        HostBatchSourceExec(rbs))
    conf = RapidsConf({"spark.rapids.memory.device.budgetBytes": 1 << 13})
    got = _sorted_rows(collect_arrow(plan, ExecCtx(conf)))
    want = _sorted_rows(collect_arrow_cpu(plan))
    assert got == want


def test_sort_small_input_stays_in_core():
    rbs = [_rb(100, seed=s) for s in range(2)]
    plan = TpuSortExec([SortOrder(col("c1"))], HostBatchSourceExec(rbs))
    ctx = ExecCtx()
    got = collect_arrow(plan, ctx)
    want = collect_arrow_cpu(plan)
    assert _norm(got) == _norm(want)
    assert ctx.mm.spill_bytes == 0


# --- disk spill tier + debug surfaces --------------------------------------

def test_host_tier_cascades_to_disk(tmp_path):
    """Host-tier pressure tiers spilled batches to Arrow IPC files and
    reads them back on access (SURVEY.md:143 device/host/disk ladder)."""
    import pyarrow as pa
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    conf = RapidsConf({
        "spark.rapids.memory.device.budgetBytes": 1 << 12,
        "spark.rapids.memory.host.spillStorageSize": 1 << 12,
        "spark.rapids.memory.spillDir": str(tmp_path)})
    mm = DeviceMemoryManager(conf)
    import numpy as np
    rng = np.random.default_rng(0)
    sbs = []
    for i in range(6):
        rb = pa.record_batch({"v": pa.array(
            rng.integers(0, 1000, 512), pa.int64())})
        sbs.append(mm.register(arrow_to_device(rb)))
    # device budget forced host spills; host limit forced disk spills
    assert mm.spill_bytes > 0
    assert mm.disk_spill_bytes > 0
    assert mm.disk_in_use_bytes > 0  # live residency tracked
    assert any(sb.on_disk for sb in sbs)
    import os
    # files land in this process's incarnation namespace, not the root
    assert os.path.dirname(mm.spill_dir) == str(tmp_path)
    assert os.listdir(mm.spill_dir)
    # read-back restores values through all tiers
    for sb in sbs:
        host = sb.get_host()
        assert host.num_rows == 512
    for sb in sbs:
        sb.release()
    # disk files cleaned on release; live residency back to zero
    assert os.listdir(mm.spill_dir) == []
    assert mm.disk_in_use_bytes == 0


# --- spill durability: sealed files, classified read-back, disk budget -----

def _disk_mgr(tmp_path, extra=None):
    conf = {"spark.rapids.memory.device.budgetBytes": 1 << 22,
            "spark.rapids.memory.spillDir": str(tmp_path)}
    conf.update(extra or {})
    return DeviceMemoryManager(RapidsConf(conf))


def _spill_to_disk(mm, n=256, seed=1):
    """One batch walked device -> host -> committed sealed disk file."""
    rb = _rb(n, seed=seed)
    sb = mm.register(arrow_to_device(rb))
    sb.spill(cascade=False)
    assert sb.spill_to_disk(), "spill file did not commit"
    assert sb.on_disk and sb._host is None
    return sb, rb


def test_spill_file_is_sealed_and_verified_roundtrip(tmp_path):
    """The committed spill file carries the shuffle tier's CRC32C+length
    trailer and read-back verifies it (same sealed format — PR 12)."""
    from spark_rapids_tpu.shuffle.integrity import read_sealed_file
    mm = _disk_mgr(tmp_path)
    sb, rb = _spill_to_disk(mm)
    # independently verifiable with the shuffle-side reader
    payload = read_sealed_file(sb._disk_path, RuntimeError)
    assert len(payload) == sb._disk_size - 16  # FOOTER_LEN
    host = sb.get_host()  # verified read-back
    assert pa.Table.from_batches([host]).to_pydict() \
        == pa.Table.from_batches([rb]).to_pydict()
    assert not sb.on_disk and mm.disk_in_use_bytes == 0
    sb.release()


@pytest.mark.parametrize("damage,kind", [
    ("torn", "torn"), ("corrupt", "corrupt"), ("missing", "missing")])
def test_spill_read_failure_classified(tmp_path, damage, kind):
    """Torn trailer / flipped payload bytes / deleted file each classify
    as SpillReadError(kind=...) — never a raw OSError/ArrowInvalid."""
    import os
    from spark_rapids_tpu.memory import SpillReadError
    mm = _disk_mgr(tmp_path)
    sb, _ = _spill_to_disk(mm)
    path = sb._disk_path
    if damage == "torn":
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 8)
    elif damage == "corrupt":
        with open(path, "r+b") as f:
            f.seek(os.path.getsize(path) // 2)
            chunk = f.read(4)
            f.seek(os.path.getsize(path) // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))
    else:
        os.unlink(path)
    with pytest.raises(SpillReadError) as ei:
        sb.get_host()
    assert ei.value.kind == kind
    # tier state untouched: a later consumer sees the SAME classified
    # state, and release still cleans the ledger
    assert sb.on_disk
    sb.release()
    assert mm.disk_in_use_bytes == 0


def test_spill_write_side_chaos_injections(tmp_path):
    """spark.rapids.memory.test.injectSpillFault damages the COMMITTED
    file exactly like the chaos modes spill_corrupt/spill_torn do."""
    from spark_rapids_tpu.memory import SpillReadError
    for fault, kind in (("corrupt", "corrupt"), ("torn", "torn")):
        mm = _disk_mgr(tmp_path / fault, {
            "spark.rapids.memory.test.injectSpillFault": fault})
        sb, _ = _spill_to_disk(mm)
        with pytest.raises(SpillReadError) as ei:
            sb.get_host()
        assert ei.value.kind == kind
        sb.release()


def test_spill_read_eio_retries_in_place(tmp_path):
    """A transient EIO (countdown sidecar — the shuffle tier's chaos
    grammar) is retried in place and the read succeeds."""
    mm = _disk_mgr(tmp_path, {
        "spark.rapids.memory.disk.readRetryWaitMs": 1})
    sb, rb = _spill_to_disk(mm)
    with open(sb._disk_path + ".eio", "w") as f:
        f.write("2")  # first two reads fail transiently
    host = sb.get_host()
    assert host.num_rows == rb.num_rows
    sb.release()


def test_spill_read_eio_exhausted_classifies_io(tmp_path):
    from spark_rapids_tpu.memory import SpillReadError
    mm = _disk_mgr(tmp_path, {
        "spark.rapids.memory.disk.readRetries": 1,
        "spark.rapids.memory.disk.readRetryWaitMs": 1})
    sb, _ = _spill_to_disk(mm)
    with open(sb._disk_path + ".eio", "w") as f:
        f.write("99")  # more failures than the retry budget
    with pytest.raises(SpillReadError) as ei:
        sb.get_host()
    assert ei.value.kind == "io"
    sb.release()


def test_zero_row_batch_spill_roundtrip(tmp_path):
    """A 0-live-row batch survives the full device->host->disk->host
    walk (0-row Arrow IPC tables yield no batches on read — the
    read-back must rebuild an empty RecordBatch, not crash)."""
    mm = _disk_mgr(tmp_path)
    rb = pa.record_batch({"a": pa.array([], pa.int64()),
                          "b": pa.array([], pa.string())})
    sb = mm.register(arrow_to_device(rb))
    sb.spill(cascade=False)
    assert sb.spill_to_disk()
    host = sb.get_host()
    assert host.num_rows == 0
    assert host.schema.names == ["a", "b"]
    sb.release()


def test_enospc_mid_write_classified_and_no_partial_file(tmp_path):
    """Injected ENOSPC mid-write (after payload, before commit): the
    partial tmp is unlinked, the batch stays host-resident, the refusal
    is classified disk pressure — no OSError escapes, nothing leaks."""
    import os
    from spark_rapids_tpu.memory import _SPILL_WRITE_FAILURES
    before = _SPILL_WRITE_FAILURES.labels("enospc").value
    mm = _disk_mgr(tmp_path, {
        "spark.rapids.memory.test.injectDiskFull": 2})  # both attempts
    rb = _rb(256)
    sb = mm.register(arrow_to_device(rb))
    sb.spill(cascade=False)
    assert sb.spill_to_disk() is False  # refused, not raised
    assert sb._host is not None and not sb.on_disk  # data survives
    assert mm.disk_pressure_active()
    assert _SPILL_WRITE_FAILURES.labels("enospc").value == before + 1
    leftovers = os.listdir(mm.spill_dir) if os.path.isdir(mm.spill_dir) \
        else []
    assert leftovers == [], f"partial files leaked: {leftovers}"
    # countdown spent: the next attempt commits and clears the pressure
    assert sb.spill_to_disk() is True
    assert not mm.disk_pressure_active()
    sb.release()
    assert mm.disk_in_use_bytes == 0


def test_io_write_failure_is_evidence_not_pressure(tmp_path, monkeypatch):
    """A transient non-ENOSPC write error classifies as spill_write_failed
    evidence (metric + flight ring) but does NOT open the sticky
    disk-pressure window: one flaky EIO must not pause host->disk
    eviction or flip the ladder's terminal rung to a budget cancel for
    a disk that has room and is healthy again."""
    import errno
    from spark_rapids_tpu.memory import _SPILL_WRITE_FAILURES
    from spark_rapids_tpu.shuffle import integrity
    mm = _disk_mgr(tmp_path)
    rb = _rb(256)
    sb = mm.register(arrow_to_device(rb))
    sb.spill(cascade=False)
    before = _SPILL_WRITE_FAILURES.labels("io").value

    def flaky(path, payload, fail_hook=None):
        raise OSError(errno.EIO, "flaky disk")

    monkeypatch.setattr(integrity, "write_sealed_file", flaky)
    from spark_rapids_tpu.obs.recorder import RECORDER
    ring_before = len(RECORDER.snapshot())
    assert sb.spill_to_disk() is False  # refused, not raised
    assert sb._host is not None and not sb.on_disk  # data survives
    assert _SPILL_WRITE_FAILURES.labels("io").value == before + 1
    assert not mm.disk_pressure_active()  # evidence, not pressure
    # the flight event matches: spill_write_failed (spill_failure
    # anomaly), NOT disk_pressure (which would emit a disk-pressure
    # incident bundle for one flaky EIO)
    new = [e for e in RECORDER.snapshot()[ring_before:]
           if e.get("kind") == "mem" and e.get("fail_kind") == "io"]
    assert [e["ev"] for e in new] == ["spill_write_failed"]
    monkeypatch.undo()
    assert sb.spill_to_disk() is True  # healthy again: commits
    sb.release()
    assert mm.disk_in_use_bytes == 0


def test_slow_disk_injection_gets_fresh_manager(tmp_path):
    """spark.rapids.memory.test.injectSlowDisk bypasses the shared()
    cache like every other spill/disk fault injection: the delay must
    neither silently no-op (default-conf manager built first, then
    shared by the injected task) nor bleed into later non-injected
    tasks that hash to the same key."""
    base = {"spark.rapids.memory.device.budgetBytes": 1 << 22,
            "spark.rapids.memory.spillDir": str(tmp_path)}
    plain = DeviceMemoryManager.shared(RapidsConf(base))
    slow = DeviceMemoryManager.shared(RapidsConf(
        {**base, "spark.rapids.memory.test.injectSlowDisk": 50}))
    assert slow is not plain
    assert slow._slow_disk_s > 0 and plain._slow_disk_s == 0
    # and a second default-conf resolve still shares the plain one
    assert DeviceMemoryManager.shared(RapidsConf(base)) is plain


def test_disk_read_policy_confs_fragment_shared_cache(tmp_path):
    """The disk read-retry/orphan-TTL knobs are part of the shared()
    cache key: a query setting readRetries=0 for fail-fast reads must
    get a manager that honors it, not the cached default-policy one
    (DISK_SPILL_LIMIT already fragments the cache; these ride the same
    rule)."""
    base = {"spark.rapids.memory.device.budgetBytes": 1 << 22,
            "spark.rapids.memory.spillDir": str(tmp_path)}
    plain = DeviceMemoryManager.shared(RapidsConf(base))
    fast = DeviceMemoryManager.shared(RapidsConf(
        {**base, "spark.rapids.memory.disk.readRetries": 0,
         "spark.rapids.memory.disk.readRetryWaitMs": 500}))
    assert fast is not plain
    assert fast.disk_read_retries == 0 and plain.disk_read_retries == 3
    assert DeviceMemoryManager.shared(RapidsConf(base)) is plain


def test_budget_eviction_skips_terminally_bad_victim(tmp_path):
    """A victim whose read-back fails terminally (corrupt) is skipped by
    later budget-eviction passes: its classified failure is counted once
    for the eviction probe, not once per over-budget spill, and the bad
    file stays referenced for the real consumer to classify."""
    from spark_rapids_tpu.memory import SpillReadError, \
        _SPILL_READ_FAILURES
    mm = _disk_mgr(tmp_path)
    sb1, _ = _spill_to_disk(mm, seed=1)
    with open(sb1._disk_path, "r+b") as f:
        f.seek(3)
        f.write(b"\xff")
    mm.disk_limit = sb1._disk_size  # any further spill is over budget
    before = _SPILL_READ_FAILURES.labels("corrupt").value
    spills = []
    for seed in (2, 3, 4):  # three eviction passes over the bad victim
        sb = mm.register(arrow_to_device(_rb(256, seed=seed)))
        sb.spill(cascade=False)
        assert sb.spill_to_disk() is False  # budget refusal, classified
        spills.append(sb)
    assert _SPILL_READ_FAILURES.labels("corrupt").value == before + 1
    assert sb1.on_disk  # never silently dropped
    with pytest.raises(SpillReadError) as ei:  # consumer still classifies
        sb1.get_host()
    assert ei.value.kind == "corrupt"
    for sb in (sb1, *spills):
        sb.release()
    assert mm.disk_in_use_bytes == 0


def test_budget_eviction_skips_persistent_eio_victim(tmp_path):
    """A victim whose read-back exhausts the EIO retry budget (kind=io)
    is marked bad exactly like corrupt/torn victims: later
    budget-eviction passes must neither re-sleep the full retry ladder
    under another batch's spill nor re-count the classified failure
    once per over-budget write."""
    from spark_rapids_tpu.memory import _SPILL_READ_FAILURES
    mm = _disk_mgr(tmp_path, {
        "spark.rapids.memory.disk.readRetries": 1,
        "spark.rapids.memory.disk.readRetryWaitMs": 1})
    sb1, _ = _spill_to_disk(mm, seed=1)
    with open(sb1._disk_path + ".eio", "w") as f:
        f.write("9999")  # persistent: every read attempt fails
    mm.disk_limit = sb1._disk_size  # any further spill is over budget
    before = _SPILL_READ_FAILURES.labels("io").value
    spills = []
    for seed in (2, 3, 4):  # three eviction passes over the bad victim
        sb = mm.register(arrow_to_device(_rb(256, seed=seed)))
        sb.spill(cascade=False)
        assert sb.spill_to_disk() is False  # budget refusal, classified
        spills.append(sb)
    assert _SPILL_READ_FAILURES.labels("io").value == before + 1
    assert sb1.on_disk  # never silently dropped
    for sb in (sb1, *spills):
        sb.release()
    assert mm.disk_in_use_bytes == 0


def test_disk_budget_admission_reserves_not_check_then_act(tmp_path):
    """Admission RESERVES the file size in disk_in_use_bytes under the
    ledger lock: two concurrent spills that each fit alone must not
    both pass the check and breach spark.rapids.memory.disk.limit
    together — the second admit sees the first's reservation and
    refuses classified."""
    from spark_rapids_tpu.memory import _SPILL_WRITE_FAILURES
    mm = _disk_mgr(tmp_path)
    mm.disk_limit = 100
    before = _SPILL_WRITE_FAILURES.labels("budget").value
    assert mm._disk_budget_admit(60) is True
    assert mm.disk_in_use_bytes == 60  # reserved before the write lands
    # check-then-act would admit this too (60 <= 100); the reservation
    # makes it see 120 > 100 with nothing on disk to evict
    assert mm._disk_budget_admit(60) is False
    assert mm.disk_in_use_bytes == 60  # a refusal reserves nothing
    assert _SPILL_WRITE_FAILURES.labels("budget").value == before + 1
    assert mm.disk_pressure_active()
    with mm._lock:  # the caller's non-commit path releases its hold
        mm.disk_in_use_bytes -= 60
    assert mm.disk_in_use_bytes == 0


def test_unlink_failure_after_verified_read_not_classified(tmp_path,
                                                           monkeypatch):
    """An unlink that fails AFTER the verified read succeeded (EACCES,
    ro-remount) must not escape as an unclassified OSError that
    discards the table and blames the reading worker: the data is
    returned, the residency ledger drops the bytes, and the stale file
    is a bounded leak the next incarnation's orphan sweep reclaims."""
    import errno
    import os
    mm = _disk_mgr(tmp_path)
    sb, rb = _spill_to_disk(mm)
    path = sb._disk_path
    real_unlink = os.unlink

    def ro_unlink(p, *a, **k):
        if p == path:
            raise OSError(errno.EACCES, "read-only remount")
        return real_unlink(p, *a, **k)

    monkeypatch.setattr(os, "unlink", ro_unlink)
    host = sb.get_host()  # returns the data, does not raise
    assert host.num_rows == rb.num_rows
    assert not sb.on_disk
    assert mm.disk_in_use_bytes == 0
    assert os.path.exists(path)  # the bounded leak, swept next boot
    monkeypatch.undo()
    sb.release()


def test_stale_pressure_window_does_not_abort_eviction_pass(tmp_path):
    """_evict_host_to_disk stops a pass only on a FRESH disk refusal
    (every refusal restamps the sticky window, so a fresh one strictly
    advances it) — a victim losing its try-acquire or sitting behind
    the anti-churn bar while a stale 30s window from a healed ENOSPC
    is still open must not strand the rest of the host tier over its
    limit for the remainder of the window."""
    mm = _disk_mgr(tmp_path)
    sb1 = mm.register(arrow_to_device(_rb(256, seed=1)))
    sb1.spill(cascade=False)
    sb2 = mm.register(arrow_to_device(_rb(256, seed=2)))
    sb2.spill(cascade=False)
    sb1._no_disk_until = time.monotonic() + 60  # anti-churn: False,
    # without restamping the window
    mm._disk_pressure_until = time.monotonic() + 60  # stale (healed)
    mm.host_limit = 0
    mm._evict_host_to_disk()
    assert sb2.on_disk, "stale window aborted the pass at first False"
    assert not sb1.on_disk
    for sb in (sb1, sb2):
        sb.release()
    assert mm.disk_in_use_bytes == 0


def test_get_charge_unwind_on_failed_reupload(tmp_path, monkeypatch):
    """Regression (PR 12 satellite): a re-upload that raises after
    _charge must not strand device_bytes on a batch whose _device stays
    None — the charge unwinds and a later get() still works."""
    import spark_rapids_tpu.columnar.arrow_bridge as bridge
    mm = _disk_mgr(tmp_path)
    rb = _rb(128)
    sb = mm.register(arrow_to_device(rb))
    sb.spill(cascade=False)
    baseline = mm.device_bytes
    real = bridge.arrow_to_device

    def boom(*a, **k):
        raise RuntimeError("upload exploded")

    monkeypatch.setattr(bridge, "arrow_to_device", boom)
    with pytest.raises(RuntimeError):
        sb.get()
    assert mm.device_bytes == baseline, "stranded device charge"
    assert sb._host is not None and sb._device is None  # still retryable
    monkeypatch.setattr(bridge, "arrow_to_device", real)
    assert sb.get().num_rows == 128  # the retry succeeds
    sb.release()


def test_disk_budget_evicts_oldest_then_refuses_classified(tmp_path):
    """spark.rapids.memory.disk.limit: an over-budget spill first
    promotes the oldest unpinned disk entry back to host; if the budget
    STILL can't fit (victims pinned), the write is refused classified
    as budget pressure."""
    from spark_rapids_tpu.memory import _SPILL_WRITE_FAILURES
    mm = _disk_mgr(tmp_path)
    sb1, _ = _spill_to_disk(mm, seed=1)
    size = sb1._disk_size
    mm.disk_limit = int(size * 1.5)  # room for one file, not two
    sb2, _ = _spill_to_disk(mm, seed=2)  # evicts sb1 to make room
    assert sb2.on_disk
    assert not sb1.on_disk and sb1._host is not None  # promoted back
    assert mm.disk_in_use_bytes <= mm.disk_limit
    # pinned disk entries are not eviction victims: now the budget is
    # genuinely unsatisfiable and the refusal classifies as 'budget'
    sb2.pin()
    before = _SPILL_WRITE_FAILURES.labels("budget").value
    sb3 = mm.register(arrow_to_device(_rb(256, seed=3)))
    sb3.spill(cascade=False)
    sb1._no_disk_until = 0.0  # not the victim under test
    assert sb3.spill_to_disk() is False
    assert _SPILL_WRITE_FAILURES.labels("budget").value == before + 1
    assert mm.disk_pressure_active()
    sb2.unpin()
    for sb in (sb1, sb2, sb3):
        sb.release()
    assert mm.disk_in_use_bytes == 0


def test_disk_pressure_feeds_ladder_terminal_as_budget_cancel(tmp_path):
    """A query OOMing while the disk tier refuses writes walks the
    ladder and terminates QueryCancelled(reason=budget) — CPU fallback
    cannot spill either when the disk is full."""
    from spark_rapids_tpu.lifecycle import QueryCancelled, QueryContext
    mm = _disk_mgr(tmp_path, {"spark.rapids.sql.oomRetry.maxSplits": 0,
                              "spark.rapids.query.admission.timeout": 1})
    mm._disk_pressure_until = time.monotonic() + 60  # sticky pressure
    qctx = QueryContext(mm.conf, query_id="qdisk")

    def boom(_):
        raise TpuRetryOOM("RESOURCE_EXHAUSTED: fake")

    b = arrow_to_device(_rb(64))
    with pytest.raises(QueryCancelled) as ei:
        mm.with_retry(b, boom, qctx=qctx)
    assert ei.value.reason == "budget"
    assert "disk spill tier" in ei.value.detail


def test_orphan_sweep_reclaims_dead_incarnations(tmp_path):
    """Namespaces whose same-host owner pid is dead are reclaimed
    immediately; foreign-host dirs only via the age fallback; the live
    process's own namespace is never touched."""
    import os
    import subprocess
    from spark_rapids_tpu.memory import (_hostname, spill_namespace,
                                         sweep_orphan_spill_dirs)
    base = str(tmp_path)
    host = _hostname()
    p = subprocess.Popen(["true"])
    p.wait()  # reaped: the pid is provably dead
    dead = os.path.join(base, f"{host}-{p.pid}-{'a' * 8}")
    os.makedirs(dead)
    open(os.path.join(dead, "spill-x.arrow"), "w").close()
    old_foreign = os.path.join(base, f"elsewhere-4242-{'b' * 8}")
    os.makedirs(old_foreign)
    os.utime(old_foreign, (1.0, 1.0))  # ancient
    young_foreign = os.path.join(base, f"elsewhere-4243-{'c' * 8}")
    os.makedirs(young_foreign)
    own = spill_namespace(base)
    os.makedirs(own)
    removed = sweep_orphan_spill_dirs(base, ttl_s=3600.0, force=True)
    assert dead in removed and old_foreign in removed
    assert not os.path.exists(dead) and not os.path.exists(old_foreign)
    assert os.path.exists(young_foreign)  # can't prove abandonment yet
    assert os.path.exists(own)  # never sweep the live namespace


def test_manager_construction_sweeps_once(tmp_path):
    """Manager construction runs the orphan sweep for its root (and a
    dead namespace planted there is gone before the first spill)."""
    import os
    import subprocess
    from spark_rapids_tpu.memory import _hostname
    p = subprocess.Popen(["true"])
    p.wait()
    dead = os.path.join(str(tmp_path), f"{_hostname()}-{p.pid}-{'d' * 8}")
    os.makedirs(dead)
    # force=False path is once-per-root-per-process; force guarantees
    # this test is order-independent under pytest
    from spark_rapids_tpu.memory import sweep_orphan_spill_dirs
    sweep_orphan_spill_dirs(str(tmp_path), force=True)
    assert not os.path.exists(dead)
    mm = _disk_mgr(tmp_path)
    sb, _ = _spill_to_disk(mm)
    sb.release()


def test_leak_report(tmp_path):
    import pyarrow as pa
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    conf = RapidsConf({"spark.rapids.refcount.debug": True,
                       "spark.rapids.memory.device.budgetBytes": 1 << 20,
                       "spark.rapids.memory.spillDir": str(tmp_path)})
    mm = DeviceMemoryManager(conf)
    rb = pa.record_batch({"v": pa.array([1, 2, 3], pa.int64())})
    sb = mm.register(arrow_to_device(rb))
    rep = mm.leak_report()
    assert "never released" in rep
    assert "test_memory" in rep  # the alloc site traceback names us
    sb.release()
    assert mm.leak_report() == "no leaked catalog entries"
