"""Memory runtime tests: budget ledger, spill, semaphore, split-retry,
out-of-core sort and aggregate merge (reference:
RapidsDeviceMemoryStoreSuite / RmmSparkRetrySuiteBase / out-of-core sort —
SURVEY.md §4.2, §5.3, §5.7)."""
import threading
import time

import pyarrow as pa
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
from spark_rapids_tpu.exec import HostBatchSourceExec
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow, \
    collect_arrow_cpu
from spark_rapids_tpu.exec.sort import SortOrder, TpuSortExec
from spark_rapids_tpu.expr import Alias, UnresolvedColumn as col
from spark_rapids_tpu.expr.aggregates import Count, Max, Min, Sum
from spark_rapids_tpu.memory import (DeviceMemoryManager, TpuRetryOOM,
                                     split_batch)

from data_gen import (DoubleGen, IntegerGen, LongGen, StringGen, gen_table)


def _rb(n, seed=1, gens=None, names=None):
    gens = gens or [IntegerGen(min_val=0, max_val=50), LongGen()]
    return gen_table(gens, n, seed, names)


def _norm(table):
    """NaN-safe pydict for exact-order comparison."""
    import math
    out = {}
    for name, colvals in table.to_pydict().items():
        out[name] = ["NaN" if isinstance(v, float) and math.isnan(v) else v
                     for v in colvals]
    return out


def _sorted_rows(table):
    rows = zip(*[table.column(i).to_pylist()
                 for i in range(table.num_columns)])
    return sorted(rows, key=lambda r: tuple(
        (v is None, str(type(v)), v if v is not None else 0) for v in r))


# --- ledger / spill -------------------------------------------------------

def test_catalog_spills_lru_under_budget():
    conf = RapidsConf({"spark.rapids.memory.device.budgetBytes": 1 << 14})
    mm = DeviceMemoryManager(conf)
    sbs = []
    for i in range(8):
        b = arrow_to_device(_rb(256, seed=i))
        sbs.append(mm.register(b))
    assert mm.device_bytes <= mm.budget
    assert any(not sb.on_device for sb in sbs)  # older ones spilled
    assert mm.spill_bytes > 0
    # spilled batch round-trips through host Arrow intact
    spilled = next(sb for sb in sbs if not sb.on_device)
    again = spilled.get()
    assert again.num_rows == 256
    for sb in sbs:
        sb.release()
    assert mm.device_bytes == 0


def test_spillable_roundtrip_preserves_strings():
    conf = RapidsConf({"spark.rapids.memory.device.budgetBytes": 1})
    mm = DeviceMemoryManager(conf)
    rb = _rb(64, gens=[StringGen(max_len=10), IntegerGen()])
    sb = mm.register(arrow_to_device(rb))
    assert not sb.on_device or mm.device_bytes > mm.budget
    mm._evict_to_fit()
    from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
    assert device_to_arrow(sb.get()).equals(rb)


# --- semaphore ------------------------------------------------------------

def test_semaphore_limits_concurrency():
    conf = RapidsConf({"spark.rapids.sql.concurrentGpuTasks": 1})
    mm = DeviceMemoryManager(conf)
    active = []
    peak = []

    def task():
        with mm.task_slot():
            active.append(1)
            peak.append(len(active))
            time.sleep(0.02)
            active.pop()

    threads = [threading.Thread(target=task) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert max(peak) == 1


# --- split-and-retry ------------------------------------------------------

def test_split_batch_halves_rows():
    rb = _rb(300, gens=[IntegerGen(), StringGen(max_len=6)])
    b = arrow_to_device(rb)
    b1, b2 = split_batch(b)
    from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
    t = pa.Table.from_batches([device_to_arrow(b1), device_to_arrow(b2)])
    assert t.to_pydict() == pa.Table.from_batches([rb]).to_pydict()


def test_injected_oom_split_retry_aggregate():
    """spark.rapids.sql.test.injectRetryOOM forces an OOM inside the fused
    stage; split-and-retry halves the batch and the result is unchanged."""
    rb = _rb(512, seed=3)
    plan = TpuHashAggregateExec(
        [col("c0")], [Alias(Sum(col("c1")), "s"), Alias(Count(), "n")],
        HostBatchSourceExec([rb]))
    want = _sorted_rows(collect_arrow_cpu(plan))
    ctx = ExecCtx(RapidsConf({"spark.rapids.sql.test.injectRetryOOM": 1}))
    got = _sorted_rows(collect_arrow(plan, ctx))
    assert got == want


def test_injected_oom_exhausts_splits():
    conf = RapidsConf({"spark.rapids.sql.oomRetry.enabled": False})
    mm = DeviceMemoryManager(conf)

    def boom(_):
        raise TpuRetryOOM("RESOURCE_EXHAUSTED: fake")

    b = arrow_to_device(_rb(64))
    with pytest.raises(TpuRetryOOM):
        mm.with_retry(b, boom)


def test_non_oom_errors_not_retried():
    mm = DeviceMemoryManager(RapidsConf())
    calls = []

    def boom(_):
        calls.append(1)
        raise ValueError("not an oom")

    b = arrow_to_device(_rb(64))
    with pytest.raises(ValueError):
        mm.with_retry(b, boom)
    assert len(calls) == 1


# --- out-of-core sort and aggregate --------------------------------------

@pytest.mark.parametrize("gens,names", [
    ([LongGen(), DoubleGen(null_frac=0.1)], None),
    ([StringGen(max_len=8), IntegerGen(null_frac=0.1)], None),
])
def test_out_of_core_sort_forced_spill(gens, names):
    """Sort at data size >> device budget: external merge with host spill
    produces exactly the oracle's ordering."""
    rbs = [_rb(500, seed=s, gens=gens, names=names) for s in range(6)]
    plan = TpuSortExec([SortOrder(col("c0")), SortOrder(col("c1"))],
                       HostBatchSourceExec(rbs))
    conf = RapidsConf({"spark.rapids.memory.device.budgetBytes": 1 << 13})
    ctx = ExecCtx(conf)
    got = collect_arrow(plan, ctx)
    want = collect_arrow_cpu(plan)
    assert _norm(got) == _norm(want)
    assert ctx.mm.spill_bytes > 0  # really went out-of-core


def test_out_of_core_aggregate_bounded_merge():
    rbs = [_rb(400, seed=s) for s in range(8)]
    plan = TpuHashAggregateExec(
        [col("c0")],
        [Alias(Sum(col("c1")), "s"), Alias(Min(col("c1")), "lo"),
         Alias(Max(col("c1")), "hi"), Alias(Count(), "n")],
        HostBatchSourceExec(rbs))
    conf = RapidsConf({"spark.rapids.memory.device.budgetBytes": 1 << 13})
    got = _sorted_rows(collect_arrow(plan, ExecCtx(conf)))
    want = _sorted_rows(collect_arrow_cpu(plan))
    assert got == want


def test_sort_small_input_stays_in_core():
    rbs = [_rb(100, seed=s) for s in range(2)]
    plan = TpuSortExec([SortOrder(col("c1"))], HostBatchSourceExec(rbs))
    ctx = ExecCtx()
    got = collect_arrow(plan, ctx)
    want = collect_arrow_cpu(plan)
    assert _norm(got) == _norm(want)
    assert ctx.mm.spill_bytes == 0


# --- disk spill tier + debug surfaces --------------------------------------

def test_host_tier_cascades_to_disk(tmp_path):
    """Host-tier pressure tiers spilled batches to Arrow IPC files and
    reads them back on access (SURVEY.md:143 device/host/disk ladder)."""
    import pyarrow as pa
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    conf = RapidsConf({
        "spark.rapids.memory.device.budgetBytes": 1 << 12,
        "spark.rapids.memory.host.spillStorageSize": 1 << 12,
        "spark.rapids.memory.spillDir": str(tmp_path)})
    mm = DeviceMemoryManager(conf)
    import numpy as np
    rng = np.random.default_rng(0)
    sbs = []
    for i in range(6):
        rb = pa.record_batch({"v": pa.array(
            rng.integers(0, 1000, 512), pa.int64())})
        sbs.append(mm.register(arrow_to_device(rb)))
    # device budget forced host spills; host limit forced disk spills
    assert mm.spill_bytes > 0
    assert mm.disk_spill_bytes > 0
    assert any(sb.on_disk for sb in sbs)
    import os
    assert os.listdir(tmp_path)
    # read-back restores values through all tiers
    for sb in sbs:
        host = sb.get_host()
        assert host.num_rows == 512
    for sb in sbs:
        sb.release()
    assert os.listdir(tmp_path) == []  # disk files cleaned on release


def test_leak_report(tmp_path):
    import pyarrow as pa
    from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
    conf = RapidsConf({"spark.rapids.refcount.debug": True,
                       "spark.rapids.memory.device.budgetBytes": 1 << 20,
                       "spark.rapids.memory.spillDir": str(tmp_path)})
    mm = DeviceMemoryManager(conf)
    rb = pa.record_batch({"v": pa.array([1, 2, 3], pa.int64())})
    sb = mm.register(arrow_to_device(rb))
    rep = mm.leak_report()
    assert "never released" in rep
    assert "test_memory" in rep  # the alloc site traceback names us
    sb.release()
    assert mm.leak_report() == "no leaked catalog entries"
