"""Planner / override-engine tests (reference: GpuOverrides +
assert_gpu_fallback_collect — SURVEY.md §2.2-A, §3.2, §4.1)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu import datatypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec import HostBatchSourceExec
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
from spark_rapids_tpu.exec.base import ExecCtx, collect_arrow_cpu
from spark_rapids_tpu.exec.basic import TpuFilterExec, TpuProjectExec
from spark_rapids_tpu.exec.joins import TpuShuffledHashJoinExec
from spark_rapids_tpu.exec.sort import SortOrder, TpuSortExec
from spark_rapids_tpu.exec.transitions import (DeviceToHostExec,
                                               HostToDeviceExec)
from spark_rapids_tpu.expr import (Alias, GreaterThan, Literal, Multiply,
                                   UnresolvedColumn as col)
from spark_rapids_tpu.expr.aggregates import Count, Sum
from spark_rapids_tpu.planner import overrides

from data_gen import IntegerGen, LongGen, StringGen, gen_table


def _source(n=300, seed=5):
    return HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=9), LongGen()], n, seed)])


def _pipeline(src=None):
    src = src or _source()
    f = TpuFilterExec(GreaterThan(col("c1"), Literal(0, dt.INT64)), src)
    p = TpuProjectExec([Alias(col("c0"), "k"),
                        Alias(Multiply(col("c1"), Literal(3, dt.INT64)),
                              "v")], f)
    return TpuHashAggregateExec([col("k")],
                                [Alias(Sum(col("v")), "s"),
                                 Alias(Count(), "c")], p)


def _sorted_rows(table):
    rows = zip(*[table.column(i).to_pylist()
                 for i in range(table.num_columns)])
    return sorted(rows, key=lambda r: tuple(
        (v is None, str(type(v)), v if v is not None else 0) for v in r))


def assert_planner_matches_cpu(plan, conf=None, expect_fallback=()):
    """Dual-run through the planner: collect() vs the pure-CPU oracle,
    plus fallback assertions (assert_gpu_fallback_collect analog)."""
    pp = overrides(plan, conf)
    got = pp.fallback_nodes()
    for name in expect_fallback:
        assert name in got, f"expected {name} to fall back, got {got}"
    result = pp.collect()
    oracle = collect_arrow_cpu(plan)
    assert _sorted_rows(result) == _sorted_rows(oracle)
    return pp


def test_all_device_plan_no_fallback():
    pp = assert_planner_matches_cpu(_pipeline())
    assert pp.fallback_nodes() == []
    assert pp.root_on_device
    text = pp.explain("ALL")
    assert "HashAggregateExec" in text and "will run on TPU" in text
    assert pp.explain("NOT_ON_GPU") == ""


def test_exec_kill_switch_falls_back():
    conf = RapidsConf({"spark.rapids.sql.exec.FilterExec": "false"})
    pp = assert_planner_matches_cpu(_pipeline(), conf,
                                    expect_fallback=["FilterExec"])
    assert pp.fallback_nodes() == ["FilterExec"]
    # transitions around the CPU island
    agg = pp.root
    proj = agg.children[0]
    h2d = proj.children[0]
    assert isinstance(h2d, HostToDeviceExec)
    filt = h2d.children[0]
    assert isinstance(filt, TpuFilterExec)
    assert isinstance(filt.children[0], DeviceToHostExec)
    text = pp.explain("NOT_ON_GPU")
    assert "FilterExec" in text and "disabled" in text


def test_expression_kill_switch_falls_back():
    conf = RapidsConf({"spark.rapids.sql.expression.Multiply": "false"})
    pp = assert_planner_matches_cpu(_pipeline(), conf,
                                    expect_fallback=["ProjectExec"])
    assert "Multiply" in pp.explain("NOT_ON_GPU")


def test_master_kill_switch_everything_cpu():
    conf = RapidsConf({"spark.rapids.sql.enabled": "false"})
    pp = assert_planner_matches_cpu(
        _pipeline(), conf,
        expect_fallback=["HashAggregateExec", "ProjectExec", "FilterExec"])
    assert not pp.root_on_device


def test_tpu_supported_auto_fallback_conditional_outer_join():
    """The planner honors tpu_supported(): a non-equi left_outer join runs
    through the CPU path automatically (no exec-level raise)."""
    left = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=5), IntegerGen()], 64, 1)])
    right = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=5), IntegerGen()], 64, 2,
                   names=["k", "v"])])
    j = TpuShuffledHashJoinExec([col("c0")], [col("k")], "left_outer",
                                left, right,
                                condition=GreaterThan(col("c1"), col("v")))
    pp = assert_planner_matches_cpu(
        j, expect_fallback=["ShuffledHashJoinExec"])
    assert "non-equi condition" in pp.explain("NOT_ON_GPU")


def test_mixed_islands_roundtrip():
    """device source -> CPU filter -> device sort: two transitions."""
    conf = RapidsConf({"spark.rapids.sql.exec.FilterExec": "false"})
    src = _source()
    f = TpuFilterExec(GreaterThan(col("c1"), Literal(0, dt.INT64)), src)
    s = TpuSortExec([SortOrder(col("c1"))], f)
    pp = assert_planner_matches_cpu(s, conf,
                                    expect_fallback=["FilterExec"])
    # sort is batch-size sensitive: coalesce inserted above the upload
    from spark_rapids_tpu.exec.exchange import TpuCoalesceBatchesExec
    coal = pp.root.children[0]
    assert isinstance(coal, TpuCoalesceBatchesExec)
    assert isinstance(coal.children[0], HostToDeviceExec)


def test_string_plan_through_planner():
    src = HostBatchSourceExec(
        [gen_table([StringGen(max_len=8), IntegerGen()], 128, 3)])
    agg = TpuHashAggregateExec([col("c0")], [Alias(Count(), "n")], src)
    assert_planner_matches_cpu(agg)


def test_metrics_report():
    """metrics_report renders per-op metrics from the last collect
    (VERDICT r2 item 10): DEBUG level gives device-time opTime."""
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.expr import Alias
    from spark_rapids_tpu.expr.aggregates import Sum
    from spark_rapids_tpu.planner import overrides
    conf = RapidsConf({"spark.rapids.sql.metrics.level": "DEBUG"})
    src = HostBatchSourceExec(
        [gen_table([IntegerGen(min_val=0, max_val=5), LongGen()], 300, 5)])
    plan = TpuHashAggregateExec([col("c0")],
                                [Alias(Sum(col("c1")), "s")], src)
    pp = overrides(plan, conf)
    pp.collect()
    report = pp.metrics_report()
    assert "HashAggregateExec" in report
    assert "opTime" in report
    # numOutputRows flows from the source
    assert "numOutputRows" in report


def test_profiler_trace_written(tmp_path):
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.planner import overrides
    import os
    conf = RapidsConf({"spark.rapids.profile.path": str(tmp_path)})
    plan = TpuProjectExec(
        [Alias(col("c0"), "x")],
        HostBatchSourceExec([gen_table([IntegerGen()], 100, 7)]))
    pp = overrides(plan, conf)
    pp.collect()
    # jax profiler writes a plugins/profile/<ts>/ tree
    found = [p for p, _, files in os.walk(tmp_path) for f in files]
    assert found, "no profiler output written"
