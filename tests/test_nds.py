"""NDS subset dual-run: every corpus query through the planner-built
device path vs its pandas oracle (reference: integration_tests NDS job
definitions — SURVEY.md §6)."""
import numpy as np
import pandas.testing as pdt
import pytest

from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.tools.nds import (QUERIES, build_query, gen_tables,
                                        pandas_oracle)

TABLES = gen_tables(n_sales=1 << 14)


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_nds_query_matches_pandas(name):
    s = TpuSession()
    df = build_query(name, s, TABLES)
    got = df.collect().to_pandas().reset_index(drop=True)
    want = pandas_oracle(name, TABLES).reset_index(drop=True)
    want.columns = [str(c) for c in want.columns]
    assert list(got.columns) == list(want.columns), \
        (got.columns, want.columns)
    # numeric tolerance: device float aggregation order differs
    for c in got.columns:
        if np.issubdtype(np.asarray(want[c]).dtype, np.floating):
            assert np.allclose(got[c].to_numpy(dtype=float),
                               want[c].to_numpy(dtype=float),
                               rtol=1e-6, atol=1e-6), c
        else:
            pdt.assert_series_equal(got[c], want[c], check_dtype=False,
                                    check_names=False)


def test_nds_plans_fully_on_device():
    # every corpus query must place every operator on the TPU: any
    # fallback is a coverage regression the suite should catch
    from spark_rapids_tpu.planner import TpuOverrides
    s = TpuSession()
    for name in sorted(QUERIES):
        df = build_query(name, s, TABLES)
        pp = TpuOverrides(s.conf).apply(df._plan()._node
                                        if hasattr(df._plan(), "_node")
                                        else df._node)
        assert not pp.fallback_nodes(), \
            f"{name}: {pp.explain('NOT_ON_GPU')}"
