"""Device batch concatenation.

TPU replacement for cudf's table concat (used by GpuCoalesceBatches, sort,
aggregate merge — SURVEY.md §2.2-A; reference mount empty). Batches carry
padding after row_count, so concatenation is a masked scatter of each
input's live rows (and live chars) at running offsets. Capacities are
static per input; output capacity is chosen by the host caller.
"""
from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.batch import TpuBatch, bucket_bytes, bucket_rows, row_mask
from ..columnar.column import TpuColumnVector

__all__ = ["concat_batches", "concat_device", "device_concat_supported"]


def device_concat_supported(t) -> bool:
    """Whether concat_device can handle a column of this type: planner
    guards (sort's global merge, coalesce, broadcast) consult this so
    unsupported plans fall back instead of raising mid-execute."""
    from .. import datatypes as dt
    if isinstance(t, (dt.ArrayType, dt.MapType)):
        return False
    if isinstance(t, dt.StructType):
        # struct children recurse through build() but nested char/element
        # sizing is per-top-level-column only
        return all(f.dtype.np_dtype is not None
                   and not dt.is_nested(f.dtype) for f in t.fields)
    return True


def concat_device(batches: Sequence[TpuBatch], out_capacity: int,
                  out_char_caps: Sequence[int]) -> TpuBatch:
    """Traced concat, all gathers (arbitrary scatters serialize on TPU):
    output row j finds its source batch by searchsorted over the running
    row counts, then gathers from the statically-concatenated inputs.
    out_char_caps has one entry per column (unused for fixed-width)."""
    schema = batches[0].schema
    ncols = len(schema)
    nb = len(batches)
    rcs = jnp.stack([b.row_count.astype(jnp.int32) for b in batches])
    cum_rc = jnp.cumsum(rcs)           # inclusive; nb is small
    total = cum_rc[-1]
    row_base = jnp.concatenate([jnp.zeros((1,), jnp.int32), cum_rc[:-1]])
    # static bases into the axis-concatenated input arrays
    caps = [b.capacity for b in batches]
    cap_base = np.concatenate([[0], np.cumsum(caps)[:-1]]).astype(np.int32)

    j = jnp.arange(out_capacity, dtype=jnp.int32)
    src_b = jnp.searchsorted(cum_rc, j, side="right").astype(jnp.int32)
    src_b = jnp.clip(src_b, 0, nb - 1)
    local = j - row_base[src_b]
    src_row = jnp.asarray(cap_base)[src_b] + local
    out_live = j < total
    max_row = sum(caps) - 1
    src_row = jnp.clip(src_row, 0, max_row)

    cols = []
    def build(cols_in, ccap):
        """Concat one (possibly nested) column across the batches via the
        shared row mapping. Structs recurse (children align with parent
        rows); array/map columns have no device concat yet — plans that
        need one (sort/coalesce over arrays) fall back via planner
        guards."""
        first = cols_in[0]
        dtype = first.dtype
        validity_all = jnp.concatenate([c.validity for c in cols_in])
        validity = validity_all[src_row] & out_live
        if first.is_string_like:
            # per-batch live char counts and bases
            nchars = jnp.stack([
                c.offsets[b.row_count.astype(jnp.int32)]
                for c, b in zip(cols_in, batches)])
            cum_ch = jnp.cumsum(nchars)
            ch_base = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                       cum_ch[:-1]])
            char_caps_in = [c.chars.shape[0] for c in cols_in]
            ch_cap_base = np.concatenate(
                [[0], np.cumsum(char_caps_in)[:-1]]).astype(np.int32)
            chars_all = jnp.concatenate([c.chars for c in cols_in]) \
                if sum(char_caps_in) else jnp.zeros((0,), jnp.uint8)
            offsets_all = jnp.concatenate(
                [c.offsets[:-1] for c in cols_in])
            # output offsets: source row's offset rebased into the packed
            # char space; rows past total pin to the final byte count
            o = offsets_all[src_row] + ch_base[src_b]
            o = jnp.where(out_live, o, cum_ch[-1])
            offsets = jnp.concatenate(
                [o, cum_ch[-1:].astype(jnp.int32)])
            # chars: position c -> source batch by char count, then byte
            cpos = jnp.arange(ccap, dtype=jnp.int32)
            cb = jnp.searchsorted(cum_ch, cpos, side="right") \
                .astype(jnp.int32)
            cb = jnp.clip(cb, 0, nb - 1)
            within = cpos - ch_base[cb]
            csrc = jnp.asarray(ch_cap_base)[cb] + within
            cvalid = cpos < cum_ch[-1]
            if sum(char_caps_in):
                chars = jnp.where(
                    cvalid,
                    chars_all[jnp.clip(csrc, 0, sum(char_caps_in) - 1)],
                    jnp.uint8(0))
            else:
                chars = jnp.zeros((ccap,), jnp.uint8)
            return TpuColumnVector(dtype, validity=validity,
                                   offsets=offsets, chars=chars)
        if first.offsets is not None and first.children is not None:
            raise NotImplementedError(
                "device concat of array/map columns not yet supported")
        if first.children is not None:  # struct
            if any(ch.is_string_like or ch.children is not None
                   for ch in first.children):
                # nested char/element sizing is per-top-level-column only
                raise NotImplementedError(
                    "device concat of structs with var-width or nested "
                    "children not yet supported")
            children = [build([c.children[k] for c in cols_in], ccap)
                        for k in range(len(first.children))]
            return TpuColumnVector(dtype, validity=validity,
                                   children=children)
        if first.data is None:  # NullType
            return TpuColumnVector(dtype, validity=validity)
        data_all = jnp.concatenate([c.data for c in cols_in])
        return TpuColumnVector(dtype, data=data_all[src_row],
                               validity=validity)

    for ci in range(ncols):
        cols.append(build([b.columns[ci] for b in batches],
                          out_char_caps[ci]))
    return TpuBatch(cols, schema, total)


_concat_jit_cache = {}
_size_jit_cache = {}


def concat_batches_bounded(batches: List[TpuBatch]) -> TpuBatch:
    """Sync-free concat: output capacity is the bucketed SUM OF INPUT
    CAPACITIES (a static upper bound), so no device->host size transfer is
    needed — one RPC saved per merge, at the cost of up to 2x padding.
    Use when capacities are already tight (e.g. shrunk aggregate
    partials); use concat_batches when exact sizing matters."""
    from .gather import ensure_compacted
    batches = [ensure_compacted(b) for b in batches]
    if len(batches) == 1:
        return batches[0]
    ncols = len(batches[0].schema)
    out_cap = bucket_rows(sum(b.capacity for b in batches))
    char_caps = []
    for ci in range(ncols):
        c = batches[0].columns[ci]
        if c.is_string_like:
            char_caps.append(bucket_bytes(sum(
                b.columns[ci].chars.shape[0] for b in batches)))
        else:
            char_caps.append(0)
    key = ("bounded", tuple(b.capacity for b in batches), out_cap,
           tuple(char_caps), id(batches[0].schema))
    fn = _concat_jit_cache.get(key)
    if fn is None:
        fn = jax.jit(lambda bs: concat_device(bs, out_cap, char_caps))
        _concat_jit_cache[key] = fn
    return fn(batches)


def concat_batches(batches: List[TpuBatch]) -> TpuBatch:
    """Host wrapper: sync row counts, size the output, run the jitted
    concat. One compiled program per (input capacities, output capacity)
    combination — bounded by the power-of-two bucketing."""
    from .gather import ensure_compacted
    batches = [ensure_compacted(b) for b in batches]
    if len(batches) == 1:
        return batches[0]
    ncols = len(batches[0].schema)
    str_cols = [ci for ci in range(ncols)
                if batches[0].columns[ci].is_string_like]
    # one jitted call + one device->host transfer for all row counts and
    # string byte counts (eager ops pay a dispatch round-trip each)
    key_sizes = (tuple(b.capacity for b in batches), tuple(str_cols))
    fn = _size_jit_cache.get(key_sizes)
    if fn is None:
        def _sizes(bs):
            out = [b.row_count.astype(jnp.int64) for b in bs]
            for ci in str_cols:
                out.extend(b.columns[ci].offsets[
                    b.row_count.astype(jnp.int32)].astype(jnp.int64)
                    for b in bs)
            return jnp.stack(out)
        fn = jax.jit(_sizes)
        _size_jit_cache[key_sizes] = fn
    host = [int(v) for v in jax.device_get(fn(batches))]
    nb = len(batches)
    for b, rc in zip(batches, host[:nb]):
        if b._num_rows_cache is None:
            b._num_rows_cache = rc
    total = sum(host[:nb])
    out_cap = bucket_rows(total)
    char_caps = [0] * ncols
    for si, ci in enumerate(str_cols):
        nbytes = sum(host[nb * (si + 1): nb * (si + 2)])
        char_caps[ci] = bucket_bytes(nbytes)
    key = (tuple(b.capacity for b in batches), out_cap, tuple(char_caps),
           id(batches[0].schema))
    fn = _concat_jit_cache.get(key)
    if fn is None:
        fn = jax.jit(lambda bs: concat_device(bs, out_cap,
                                              char_caps))
        _concat_jit_cache[key] = fn
    return fn(batches)
