"""Device batch concatenation.

TPU replacement for cudf's table concat (used by GpuCoalesceBatches, sort,
aggregate merge — SURVEY.md §2.2-A; reference mount empty). Batches carry
padding after row_count, so concatenation is a masked scatter of each
input's live rows (and live chars) at running offsets. Capacities are
static per input; output capacity is chosen by the host caller.
"""
from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp

from ..columnar.batch import TpuBatch, bucket_bytes, bucket_rows, row_mask
from ..columnar.column import TpuColumnVector

__all__ = ["concat_batches", "concat_device"]


def _scatter_fixed(out, src, dst_idx, keep, out_cap):
    dst = jnp.where(keep, dst_idx, out_cap)
    return out.at[dst].set(src, mode="drop")


def concat_device(batches: Sequence[TpuBatch], out_capacity: int,
                  out_char_caps: Sequence[int]) -> TpuBatch:
    """Traced concat: scatter live rows of each batch at running offsets.
    out_char_caps has one entry per column (unused for fixed-width)."""
    schema = batches[0].schema
    ncols = len(schema)
    total = jnp.int32(0)
    row_offs = []
    for b in batches:
        row_offs.append(total)
        total = total + b.row_count.astype(jnp.int32)

    cols = []
    for ci in range(ncols):
        dtype = batches[0].columns[ci].dtype
        first = batches[0].columns[ci]
        validity = jnp.zeros((out_capacity,), jnp.bool_)
        if first.is_string_like:
            ccap = out_char_caps[ci]
            offsets = jnp.zeros((out_capacity + 1,), jnp.int32)
            chars = jnp.zeros((ccap,), jnp.uint8)
            char_off = jnp.int32(0)
            for b, roff in zip(batches, row_offs):
                c = b.columns[ci]
                cap = c.capacity
                rc = b.row_count.astype(jnp.int32)
                live = row_mask(cap, rc)
                pos = jnp.arange(cap, dtype=jnp.int32)
                validity = _scatter_fixed(validity, c.validity, roff + pos,
                                          live, out_capacity)
                # offsets: positions 0..rc inclusive, rebased by char_off
                opos = jnp.arange(cap + 1, dtype=jnp.int32)
                okeep = opos <= rc
                offsets = _scatter_fixed(offsets, c.offsets + char_off,
                                         roff + opos, okeep,
                                         out_capacity + 1)
                # chars: live bytes are [0, offsets[rc])
                nchars = c.offsets[rc]
                cpos = jnp.arange(c.chars.shape[0], dtype=jnp.int32)
                chars = _scatter_fixed(chars, c.chars, char_off + cpos,
                                       cpos < nchars, ccap)
                char_off = char_off + nchars
            # keep offsets monotone through trailing padding
            opos = jnp.arange(out_capacity + 1, dtype=jnp.int32)
            offsets = jnp.where(opos > total, char_off, offsets)
            cols.append(TpuColumnVector(dtype, validity=validity,
                                        offsets=offsets, chars=chars))
        elif first.data is None:  # NullType
            for b, roff in zip(batches, row_offs):
                c = b.columns[ci]
                cap = c.capacity
                live = row_mask(cap, b.row_count)
                pos = jnp.arange(cap, dtype=jnp.int32)
                validity = _scatter_fixed(validity, c.validity, roff + pos,
                                          live, out_capacity)
            cols.append(TpuColumnVector(dtype, validity=validity))
        else:
            data = jnp.zeros((out_capacity,), first.data.dtype)
            for b, roff in zip(batches, row_offs):
                c = b.columns[ci]
                cap = c.capacity
                live = row_mask(cap, b.row_count)
                pos = jnp.arange(cap, dtype=jnp.int32)
                data = _scatter_fixed(data, c.data, roff + pos, live,
                                      out_capacity)
                validity = _scatter_fixed(validity, c.validity, roff + pos,
                                          live, out_capacity)
            cols.append(TpuColumnVector(dtype, data=data, validity=validity))
    return TpuBatch(cols, schema, total)


_concat_jit_cache = {}


def concat_batches(batches: List[TpuBatch]) -> TpuBatch:
    """Host wrapper: sync row counts, size the output, run the jitted
    concat. One compiled program per (input capacities, output capacity)
    combination — bounded by the power-of-two bucketing."""
    if len(batches) == 1:
        return batches[0]
    ncols = len(batches[0].schema)
    str_cols = [ci for ci in range(ncols)
                if batches[0].columns[ci].is_string_like]
    # one device->host transfer for all row counts + string byte counts
    scalars = [b.row_count for b in batches]
    for ci in str_cols:
        scalars.extend(b.columns[ci].offsets[b.row_count] for b in batches)
    host = [int(v) for v in jax.device_get(jnp.stack(
        [jnp.asarray(s, jnp.int64) for s in scalars]))]
    nb = len(batches)
    for b, rc in zip(batches, host[:nb]):
        if b._num_rows_cache is None:
            b._num_rows_cache = rc
    total = sum(host[:nb])
    out_cap = bucket_rows(total)
    char_caps = [0] * ncols
    for si, ci in enumerate(str_cols):
        nbytes = sum(host[nb * (si + 1): nb * (si + 2)])
        char_caps[ci] = bucket_bytes(nbytes)
    key = (tuple(b.capacity for b in batches), out_cap, tuple(char_caps),
           id(batches[0].schema))
    fn = _concat_jit_cache.get(key)
    if fn is None:
        fn = jax.jit(lambda bs: concat_device(bs, out_cap,
                                              char_caps))
        _concat_jit_cache[key] = fn
    return fn(batches)
