"""Device batch concatenation.

TPU replacement for cudf's table concat (used by GpuCoalesceBatches, sort,
aggregate merge — SURVEY.md §2.2-A; reference mount empty). Batches carry
padding after row_count, so concatenation is a masked scatter of each
input's live rows (and live chars) at running offsets. Capacities are
static per input; output capacity is chosen by the host caller.
"""
from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.batch import TpuBatch, bucket_bytes, bucket_rows, row_mask
from ..columnar.column import TpuColumnVector

__all__ = ["concat_batches", "concat_device", "device_concat_supported"]


def device_concat_supported(t) -> bool:
    """Whether concat_device can handle a column of this type: planner
    guards (sort's global merge, coalesce, broadcast) consult this so
    unsupported plans fall back instead of raising mid-execute. Round 4:
    the recursive unit-mapping build covers arrays/maps/structs at any
    depth (VERDICT r3 item 6), so everything concats."""
    return True


def concat_device(batches: Sequence[TpuBatch], out_capacity: int,
                  out_char_caps: Sequence[int]) -> TpuBatch:
    """Traced concat, all gathers (arbitrary scatters serialize on TPU):
    output row j finds its source batch by searchsorted over the running
    row counts, then gathers from the statically-concatenated inputs.

    Nesting recurses through a UNIT MAPPING at each level: rows map to
    (source batch, source row); an array/string level turns per-batch
    live unit counts (offsets[live parent units]) into the next level's
    (source batch, source unit) mapping, identically for chars, array
    elements, and map entries — one algorithm at every depth
    (SURVEY.md:179). out_char_caps has one entry per TOP-LEVEL string
    column (exact sizing from the host wrapper); nested levels size by
    the capacity-sum bound, which needs no readback."""
    schema = batches[0].schema
    ncols = len(schema)
    nb = len(batches)
    rcs = jnp.stack([b.row_count.astype(jnp.int32) for b in batches])
    cum_rc = jnp.cumsum(rcs)           # inclusive; nb is small
    total = cum_rc[-1]
    row_base = jnp.concatenate([jnp.zeros((1,), jnp.int32), cum_rc[:-1]])
    caps = [b.capacity for b in batches]

    def unit_mapping(unit_counts, caps_in, out_cap):
        """Per-level mapping: unit_counts (nb,) device live-unit counts,
        caps_in static per-batch capacities -> (src batch, packed source
        index, live mask, cum counts, bases) over out_cap positions."""
        cum = jnp.cumsum(unit_counts.astype(jnp.int32))
        base = jnp.concatenate([jnp.zeros((1,), jnp.int32), cum[:-1]])
        cap_base = np.concatenate(
            [[0], np.cumsum(caps_in)[:-1]]).astype(np.int32)
        pos = jnp.arange(out_cap, dtype=jnp.int32)
        ub = jnp.clip(jnp.searchsorted(cum, pos, side="right"),
                      0, nb - 1).astype(jnp.int32)
        within = pos - base[ub]
        src = jnp.clip(jnp.asarray(cap_base)[ub] + within, 0,
                       max(sum(caps_in) - 1, 0))
        live = pos < cum[-1]
        return ub, src, live, cum, base

    src_b, src_row, out_live, _, _ = unit_mapping(
        rcs, caps, out_capacity)

    def build(cols_in, live_units, s_b, s_idx, o_live, ccap_hint):
        """One column at one nesting level. live_units: per-batch device
        count of live units at THIS level; (s_b, s_idx, o_live): this
        level's unit mapping."""
        first = cols_in[0]
        dtype = first.dtype
        validity_all = jnp.concatenate([c.validity for c in cols_in])
        validity = validity_all[s_idx] & o_live

        if first.offsets is not None:  # string / array / map
            child_counts = jnp.stack([
                c.offsets[jnp.clip(lu, 0, c.offsets.shape[0] - 1)]
                for c, lu in zip(cols_in, live_units)])
            if first.is_string_like:
                caps_in = [c.chars.shape[0] for c in cols_in]
            else:
                caps_in = [c.children[0].capacity for c in cols_in]
            if ccap_hint is not None:
                ecap = ccap_hint
            elif first.is_string_like:
                ecap = bucket_bytes(max(sum(caps_in), 1))
            else:
                ecap = bucket_rows(max(sum(caps_in), 1))
            eb, esrc, elive, cum_e, e_base = unit_mapping(
                child_counts, caps_in, ecap)
            offsets_all = jnp.concatenate(
                [c.offsets[:-1] for c in cols_in])
            o = offsets_all[s_idx] + e_base[s_b]
            o = jnp.where(o_live, o, cum_e[-1])
            offsets = jnp.concatenate([o, cum_e[-1:].astype(jnp.int32)])
            if first.is_string_like:
                chars_all = jnp.concatenate([c.chars for c in cols_in]) \
                    if sum(caps_in) else jnp.zeros((0,), jnp.uint8)
                if sum(caps_in):
                    chars = jnp.where(elive, chars_all[esrc],
                                      jnp.uint8(0))
                else:
                    chars = jnp.zeros((ecap,), jnp.uint8)
                return TpuColumnVector(dtype, validity=validity,
                                       offsets=offsets, chars=chars)
            children = [build([c.children[k] for c in cols_in],
                              [child_counts[i] for i in range(nb)],
                              eb, esrc, elive, None)
                        for k in range(len(first.children))]
            return TpuColumnVector(dtype, validity=validity,
                                   offsets=offsets, children=children)
        if first.children is not None:  # struct: same row mapping
            children = [build([c.children[k] for c in cols_in],
                              live_units, s_b, s_idx, o_live, None)
                        for k in range(len(first.children))]
            return TpuColumnVector(dtype, validity=validity,
                                   children=children)
        if first.data is None:  # NullType
            return TpuColumnVector(dtype, validity=validity)
        data_all = jnp.concatenate([c.data for c in cols_in])
        return TpuColumnVector(dtype, data=data_all[s_idx],
                               validity=validity)

    live_rows = [b.row_count.astype(jnp.int32) for b in batches]
    cols = []
    for ci in range(ncols):
        hint = out_char_caps[ci] if out_char_caps[ci] else None
        if not batches[0].columns[ci].is_string_like:
            hint = None
        cols.append(build([b.columns[ci] for b in batches], live_rows,
                          src_b, src_row, out_live, hint))
    return TpuBatch(cols, schema, total)


_concat_jit_cache = {}
_size_jit_cache = {}


def concat_batches_bounded(batches: List[TpuBatch]) -> TpuBatch:
    """Sync-free concat: output capacity is the bucketed SUM OF INPUT
    CAPACITIES (a static upper bound), so no device->host size transfer is
    needed — one RPC saved per merge, at the cost of up to 2x padding.
    Use when capacities are already tight (e.g. shrunk aggregate
    partials); use concat_batches when exact sizing matters."""
    from .gather import ensure_compacted
    batches = [ensure_compacted(b) for b in batches]
    if len(batches) == 1:
        return batches[0]
    ncols = len(batches[0].schema)
    out_cap = bucket_rows(sum(b.capacity for b in batches))
    char_caps = []
    for ci in range(ncols):
        c = batches[0].columns[ci]
        if c.is_string_like:
            char_caps.append(bucket_bytes(sum(
                b.columns[ci].chars.shape[0] for b in batches)))
        else:
            char_caps.append(0)
    key = ("bounded", tuple(b.capacity for b in batches), out_cap,
           tuple(char_caps), id(batches[0].schema))
    fn = _concat_jit_cache.get(key)
    if fn is None:
        fn = jax.jit(lambda bs: concat_device(bs, out_cap, char_caps))
        _concat_jit_cache[key] = fn
    return fn(batches)


def concat_batches(batches: List[TpuBatch]) -> TpuBatch:
    """Host wrapper: sync row counts, size the output, run the jitted
    concat. One compiled program per (input capacities, output capacity)
    combination — bounded by the power-of-two bucketing."""
    from .gather import ensure_compacted
    batches = [ensure_compacted(b) for b in batches]
    if len(batches) == 1:
        return batches[0]
    ncols = len(batches[0].schema)
    str_cols = [ci for ci in range(ncols)
                if batches[0].columns[ci].is_string_like]
    # one jitted call + one device->host transfer for all row counts and
    # string byte counts (eager ops pay a dispatch round-trip each)
    key_sizes = (tuple(b.capacity for b in batches), tuple(str_cols))
    fn = _size_jit_cache.get(key_sizes)
    if fn is None:
        def _sizes(bs):
            out = [b.row_count.astype(jnp.int64) for b in bs]
            for ci in str_cols:
                out.extend(b.columns[ci].offsets[
                    b.row_count.astype(jnp.int32)].astype(jnp.int64)
                    for b in bs)
            return jnp.stack(out)
        fn = jax.jit(_sizes)
        _size_jit_cache[key_sizes] = fn
    host = [int(v) for v in jax.device_get(fn(batches))]
    nb = len(batches)
    for b, rc in zip(batches, host[:nb]):
        if b._num_rows_cache is None:
            b._num_rows_cache = rc
    total = sum(host[:nb])
    out_cap = bucket_rows(total)
    char_caps = [0] * ncols
    for si, ci in enumerate(str_cols):
        nbytes = sum(host[nb * (si + 1): nb * (si + 2)])
        char_caps[ci] = bucket_bytes(nbytes)
    key = (tuple(b.capacity for b in batches), out_cap, tuple(char_caps),
           id(batches[0].schema))
    fn = _concat_jit_cache.get(key)
    if fn is None:
        fn = jax.jit(lambda bs: concat_device(bs, out_cap,
                                              char_caps))
        _concat_jit_cache[key] = fn
    return fn(batches)
