"""Device-side string -> numeric/bool/date parsing kernels.

TPU analog of the cast edge-case kernels the reference keeps in
libcudf/spark-rapids-jni (`GpuCast.scala` string-source casts —
SURVEY.md §2.2-C Cast, §2.2-E; reference mount empty). Round 4 left
string->numeric on host (VERDICT r4 weak #4); these kernels are the
inverse of ops/numeric_format.py's digit generation: vectorized segment
reductions over the flat (offsets, chars) lanes — no Python per row, no
host round-trip.

Accepted forms mirror the engine's host parser (`expr/cast.py
_parse_string`), which follows Spark's UTF8String semantics:
whitespace-trimmed, optional sign, plain decimal digits (integrals
accept a trailing ".ddd" fraction, truncated), float adds exponent
notation and the nan/inf/infinity specials, date is
YYYY-M-D[T/space ...]. Invalid rows are NULL (ANSI raise happens at the
expression layer via the validity delta).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import datatypes as dt

__all__ = ["parse_int_tpu", "parse_float_tpu", "parse_bool_tpu",
           "parse_date_tpu", "days_from_civil"]

_WS = (0x09, 0x0A, 0x0B, 0x0C, 0x0D, 0x20)  # str.strip() whitespace


def _row_ids(offsets, flat_cap, n):
    i = jnp.arange(flat_cap, dtype=jnp.int32)
    return jnp.clip(jnp.searchsorted(offsets, i, side="right") - 1,
                    0, n - 1), i


def _bounds(col):
    """Per-row [start, end) in the flat chars lane plus the machinery
    every parser shares: row ids per flat position and the whitespace-
    trimmed [ts, te) window."""
    offs = col.offsets
    n = offs.shape[0] - 1
    chars = col.chars if col.chars.shape[0] else jnp.zeros((1,), jnp.uint8)
    flat_cap = chars.shape[0]
    rid, i = _row_ids(offs, flat_cap, n)
    s = offs[:-1].astype(jnp.int32)
    e = offs[1:].astype(jnp.int32)
    in_row = (i >= s[rid]) & (i < e[rid])
    c = chars
    is_ws = jnp.zeros_like(in_row)
    for w in _WS:
        is_ws = is_ws | (c == w)
    nonws = in_row & ~is_ws
    big = jnp.int32(flat_cap + 1)
    ts = jax.ops.segment_min(jnp.where(nonws, i, big), rid,
                             num_segments=n)
    te_last = jax.ops.segment_max(jnp.where(nonws, i, jnp.int32(-1)),
                                  rid, num_segments=n)
    ts = jnp.where(ts > te_last, e, ts)     # all-whitespace/empty row
    te = jnp.where(te_last < 0, e, te_last + 1)
    return n, c, rid, i, ts, te


def _first_pos(pred, rid, i, lo, hi, n, default):
    """Per row: min position in [lo, hi) where pred, else default."""
    big = jnp.int32(1 << 30)
    inside = (i >= lo[rid]) & (i < hi[rid])
    pos = jax.ops.segment_min(jnp.where(pred & inside, i, big), rid,
                              num_segments=n)
    return jnp.where(pos >= big, default, pos)


def _all_in(pred, rid, i, lo, hi, n):
    """Per row: every position in [lo, hi) satisfies pred (vacuously
    true for empty ranges)."""
    inside = (i >= lo[rid]) & (i < hi[rid])
    bad = jax.ops.segment_max((inside & ~pred).astype(jnp.int32), rid,
                              num_segments=n)
    return bad == 0


_POW10_U64 = np.array([10 ** k for k in range(20)], np.uint64)


def _digits_value(c, rid, i, lo, hi, n):
    """Per row: uint64 value of the digit run [lo, hi) (caller has
    verified all-digits), plus the significant digit count (sans leading
    zeros). Values with > 19 significant digits are flagged."""
    inside = (i >= lo[rid]) & (i < hi[rid])
    d = (c - ord("0")).astype(jnp.uint64)
    nonzero = inside & (c != ord("0"))
    big = jnp.int32(1 << 30)
    first_sig = jax.ops.segment_min(jnp.where(nonzero, i, big), rid,
                                    num_segments=n)
    first_sig = jnp.where(first_sig >= big, hi, first_sig)
    sig = hi - first_sig
    ok_width = sig <= 19
    exp = (hi[rid] - 1 - i).astype(jnp.int32)
    term = d * jnp.asarray(_POW10_U64)[jnp.clip(exp, 0, 19)]
    term = jnp.where(inside & (exp < 20), term, jnp.uint64(0))
    total = jax.ops.segment_sum(term, rid, num_segments=n)
    return total, sig, ok_width


def parse_int_tpu(col, target: dt.DataType):
    """(values int64, parsed_ok bool) for string -> integral casts:
    [ws] [+-] digits [. digits] [ws]; fraction truncated (Spark 3.x
    cast semantics, matching the host parser)."""
    n, c, rid, i, ts, te = _bounds(col)
    is_digit = (c >= ord("0")) & (c <= ord("9"))
    at_ts = c[jnp.clip(ts, 0, c.shape[0] - 1)]
    has_sign = (at_ts == ord("+")) | (at_ts == ord("-"))
    neg = at_ts == ord("-")
    ds = ts + has_sign.astype(jnp.int32)
    dot = _first_pos(c == ord("."), rid, i, ds, te, n, te)
    ok = (te > ts)
    ok = ok & (dot > ds)  # at least one integer digit
    ok = ok & _all_in(is_digit, rid, i, ds, dot, n)
    frac_lo = jnp.minimum(dot + 1, te)
    ok = ok & _all_in(is_digit, rid, i, frac_lo, te, n)
    val, _, ok_width = _digits_value(c, rid, i, ds, dot, n)
    ok = ok & ok_width
    i64max = jnp.uint64(0x7FFFFFFFFFFFFFFF)
    limit = i64max + neg.astype(jnp.uint64)
    ok = ok & (val <= limit)
    sv = val.astype(jnp.int64)
    v = jnp.where(neg, -sv, sv)  # -(2^63) wraps to INT64_MIN correctly
    if not isinstance(target, dt.LongType):
        info = np.iinfo(target.np_dtype)
        ok = ok & (v >= info.min) & (v <= info.max)
    return v, ok


_F_POW10 = np.zeros(701, np.float64)
for _k in range(-350, 351):
    _F_POW10[_k + 350] = float(10.0 ** _k) if abs(_k) < 309 else \
        (np.inf if _k > 0 else 0.0)


def _match_literal(c, rid, i, ts, te, n, lit: bytes, offset=0):
    """Per row: the trimmed window starting at ts+offset equals `lit`
    case-insensitively and ends exactly at te."""
    m = jnp.ones((n,), jnp.bool_)
    lower = jnp.where((c >= ord("A")) & (c <= ord("Z")), c + 32, c)
    cap = c.shape[0] - 1
    for k, ch in enumerate(lit):
        pos = jnp.clip(ts + offset + k, 0, cap)
        m = m & (lower[pos] == ch) & (ts + offset + k < te)
    m = m & (te == ts + offset + len(lit))
    return m


def parse_float_tpu(col, target: dt.DataType):
    """(values, parsed_ok) for string -> float/double: mantissa with
    optional fraction and exponent, plus the nan/inf/infinity specials.
    Value = mantissa_digits x 10^(exp - frac_len) in float64 — exact for
    <= 15 significant digits and moderate exponents (the fast-path
    guarantee); longer literals can differ from the host strtod by an
    ulp, the same caveat the reference documents for its string->float
    kernels."""
    n, c, rid, i, ts, te = _bounds(col)
    is_digit = (c >= ord("0")) & (c <= ord("9"))
    cap = c.shape[0] - 1
    at_ts = c[jnp.clip(ts, 0, cap)]
    has_sign = (at_ts == ord("+")) | (at_ts == ord("-"))
    neg = at_ts == ord("-")
    ds = ts + has_sign.astype(jnp.int32)

    is_e = (c == ord("e")) | (c == ord("E"))
    epos = _first_pos(is_e, rid, i, ds, te, n, te)
    dot = _first_pos(c == ord("."), rid, i, ds, epos, n, epos)
    int_len = dot - ds
    frac_lo = jnp.minimum(dot + 1, epos)
    frac_len = jnp.maximum(epos - frac_lo, 0)
    ok = (te > ts)
    ok = ok & ((int_len + frac_len) > 0)  # at least one mantissa digit
    ok = ok & _all_in(is_digit, rid, i, ds, dot, n)
    ok = ok & _all_in(is_digit, rid, i, frac_lo, epos, n)
    # mantissa: integer and fraction digit runs scaled SEPARATELY in
    # float64 — combining them in uint64 (int*10^frac_len + frac)
    # overflows past 19 total digits and silently produced garbage
    # (code-review r5). Each run is individually gated to <= 19
    # significant digits by _digits_value; the separate scaling costs
    # at most one extra rounding (the documented ulp caveat).
    iv, _, ok_i = _digits_value(c, rid, i, ds, dot, n)
    fv, _, ok_f = _digits_value(c, rid, i, frac_lo, epos, n)
    ok = ok & ok_i & ok_f
    # exponent
    e_ds = epos + 1
    at_e = c[jnp.clip(e_ds, 0, cap)]
    e_sign = (at_e == ord("+")) | (at_e == ord("-"))
    e_neg = at_e == ord("-")
    e_lo = e_ds + e_sign.astype(jnp.int32)
    has_exp = epos < te
    ok = ok & (~has_exp | (te > e_lo))  # exponent needs a digit
    ok = ok & _all_in(is_digit, rid, i, e_lo, te, n)
    ev, _, _ = _digits_value(c, rid, i, e_lo, te, n)
    ev = jnp.clip(ev, jnp.uint64(0), jnp.uint64(400)).astype(jnp.int32)
    exp = jnp.where(has_exp, jnp.where(e_neg, -ev, ev), 0)
    POW = jnp.asarray(_F_POW10)
    int_scale = jnp.clip(exp, -350, 350)
    frac_scale = jnp.clip(exp - frac_len, -350, 350)
    int_mag = jnp.where(iv == 0, 0.0,
                        iv.astype(jnp.float64) * POW[int_scale + 350])
    frac_mag = jnp.where(fv == 0, 0.0,
                         fv.astype(jnp.float64) * POW[frac_scale + 350])
    val = jnp.where(neg, -(int_mag + frac_mag), int_mag + frac_mag)
    # specials (trimmed, case-insensitive)
    nan_m = _match_literal(c, rid, i, ts, te, n, b"nan")
    sgn = has_sign.astype(jnp.int32)
    inf_m = _match_literal(c, rid, i, ts, te, n, b"inf", offset=0) \
        | _match_literal(c, rid, i, ts, te, n, b"infinity", offset=0)
    inf_s = (_match_literal(c, rid, i, ts, te, n, b"inf", offset=1)
             | _match_literal(c, rid, i, ts, te, n, b"infinity",
                              offset=1)) & has_sign
    # the host parser accepts nan without sign, inf with optional sign
    special = nan_m | inf_m | inf_s
    sval = jnp.where(nan_m, jnp.float64(jnp.nan),
                     jnp.where(neg, -jnp.inf, jnp.inf))
    out = jnp.where(special, sval, val)
    ok = ok | special
    return out.astype(target.np_dtype), ok


_BOOL_TRUE = (b"t", b"true", b"y", b"yes", b"1")
_BOOL_FALSE = (b"f", b"false", b"n", b"no", b"0")


def parse_bool_tpu(col):
    n, c, rid, i, ts, te = _bounds(col)
    t = jnp.zeros((n,), jnp.bool_)
    f = jnp.zeros((n,), jnp.bool_)
    for lit in _BOOL_TRUE:
        t = t | _match_literal(c, rid, i, ts, te, n, lit)
    for lit in _BOOL_FALSE:
        f = f | _match_literal(c, rid, i, ts, te, n, lit)
    return t, t | f


def days_from_civil(y, m, d):
    """(year, month, day) -> days since epoch; the exact inverse of
    numeric_format._civil_from_days (Hinnant's public-domain civil
    calendar algorithm)."""
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def parse_date_tpu(col):
    """(days int32, parsed_ok) for string -> date:
    YYYY-M-D with optional '[T ]<anything>' tail (the host parser's
    regex). Invalid calendar dates (2021-02-30) round-trip-fail."""
    from .numeric_format import _civil_from_days
    n, c, rid, i, ts, te = _bounds(col)
    is_digit = (c >= ord("0")) & (c <= ord("9"))
    dash = c == ord("-")
    d1 = _first_pos(dash, rid, i, ts + 1, te, n, te)
    d2 = _first_pos(dash, rid, i, d1 + 1, te, n, te)
    tail = _first_pos((c == ord("T")) | (c == ord(" ")), rid, i,
                      d2 + 1, te, n, te)
    ok = (d1 == ts + 4) & (d2 > d1 + 1) & (d2 <= d1 + 3) \
        & (tail > d2 + 1) & (tail <= d2 + 3)
    ok = ok & _all_in(is_digit, rid, i, ts, d1, n)
    ok = ok & _all_in(is_digit, rid, i, d1 + 1, d2, n)
    ok = ok & _all_in(is_digit, rid, i, d2 + 1, tail, n)
    yv, _, _ = _digits_value(c, rid, i, ts, d1, n)
    mv, _, _ = _digits_value(c, rid, i, d1 + 1, d2, n)
    dv, _, _ = _digits_value(c, rid, i, d2 + 1, tail, n)
    y = yv.astype(jnp.int64)
    m = mv.astype(jnp.int64)
    d = dv.astype(jnp.int64)
    ok = ok & (m >= 1) & (m <= 12) & (d >= 1) & (d <= 31)
    days = days_from_civil(y, jnp.clip(m, 1, 12), jnp.clip(d, 1, 31))
    ry, rm, rd = _civil_from_days(days.astype(jnp.int32))
    ok = ok & (ry == y) & (rm == m) & (rd == d)
    return days.astype(jnp.int32), ok
