"""Equi-join kernels.

TPU replacement for libcudf's hash-join (SURVEY.md §2.2-E; reference mount
empty), built sort-based as §7.1.3 prescribes: both sides' key columns are
reduced to shared dense group ids (joint string ranks / orderable int
lanes over the virtual union), the build side is ordered by group, and
per-stream-row match ranges come from per-group counts + offsets — no
device hash table, every step a sort/scan/gather.

SQL semantics: rows with any null key never match (but are emitted by
outer/anti sides); NaN==NaN and -0.0==0.0 for keys (Spark normalization).

Output sizing is data-dependent, so a join is staged (SURVEY.md §7.3.1):
  stage A (jit)  — group ids, match counts, total output rows
  host sync      — choose static output capacity bucket
  stage B (jit)  — build output row indices + string byte counts
  host sync      — choose char capacities (string outputs only)
  stage C (jit)  — gather both sides into the output batch
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import datatypes as dt
from ..columnar.batch import TpuBatch, row_mask
from ..columnar.column import TpuColumnVector
from .sort_keys import (normalize_float_key_col, orderable_int,
                        string_order_ranks_multi)

__all__ = ["JOIN_TYPES", "union_group_ids", "JoinPlanA", "join_counts",
           "join_total", "join_indices", "join_gather",
           "join_output_bytes", "unique_build_analysis",
           "unique_build_probe", "probe_unique", "unique_union_lookup"]

JOIN_TYPES = ("inner", "left_outer", "right_outer", "full_outer",
              "left_semi", "left_anti", "cross")


_norm_key_col = normalize_float_key_col


def union_group_ids(left_keys: Sequence[TpuColumnVector],
                    right_keys: Sequence[TpuColumnVector],
                    live_l: jax.Array, live_r: jax.Array):
    """Dense group ids shared across sides: g_l[i] == g_r[j] iff the key
    tuples are equal (null==null at this layer; null-key *matching* policy
    is applied by the caller via the valid-key masks)."""
    nl, nr = live_l.shape[0], live_r.shape[0]
    n = nl + nr
    live = jnp.concatenate([live_l, live_r])
    lanes: List[jax.Array] = [jnp.where(live, jnp.int8(0), jnp.int8(1))]
    for lk, rk in zip(left_keys, right_keys):
        lk, rk = _norm_key_col(lk), _norm_key_col(rk)
        validity = jnp.concatenate([lk.validity, rk.validity])
        lanes.append(jnp.where(validity, jnp.int8(1), jnp.int8(0)))
        if lk.is_string_like:
            vals = string_order_ranks_multi(
                [lk, rk], [live_l & lk.validity, live_r & rk.validity])
        elif lk.data is None:
            vals = jnp.zeros((n,), jnp.int8)
        else:
            v_l = orderable_int(lk)
            v_r = orderable_int(rk)
            if v_l.dtype != v_r.dtype:
                tgt = jnp.promote_types(v_l.dtype, v_r.dtype)
                v_l, v_r = v_l.astype(tgt), v_r.astype(tgt)
            vals = jnp.concatenate([v_l, v_r])
            vals = jnp.where(validity, vals, jnp.zeros_like(vals))
        lanes.append(vals)
    idx = jnp.arange(n, dtype=jnp.int32)
    sorted_all = jax.lax.sort(tuple(lanes) + (idx,),
                              num_keys=len(lanes) + 1)
    sorted_lanes, perm = sorted_all[:-1], sorted_all[-1]
    boundary = jnp.zeros((n,), jnp.bool_).at[0].set(True)
    for lane in sorted_lanes:
        boundary = boundary | jnp.concatenate(
            [jnp.zeros((1,), jnp.bool_), lane[1:] != lane[:-1]])
    from .gather import inclusive_int_cumsum
    seg = inclusive_int_cumsum(boundary) - 1
    from .gather import invert_permutation
    g = invert_permutation(perm, seg)
    return g[:nl], g[nl:]


def _any_null_key(keys: Sequence[TpuColumnVector], cap: int) -> jax.Array:
    if not keys:
        return jnp.zeros((cap,), jnp.bool_)
    bad = ~keys[0].validity
    for k in keys[1:]:
        bad = bad | ~k.validity
    return bad


class JoinPlanA:
    """Results of stage A, a pytree of device arrays + static shapes."""

    def __init__(self, g_l, g_r, matches, starts_g, perm_r, eligible_l,
                 eligible_r, matched_r, live_l, live_r, times_r):
        self.g_l = g_l
        self.g_r = g_r
        self.matches = matches          # per left row, 0 for null-key/dead
        self.starts_g = starts_g        # per group: start in perm_r order
        self.perm_r = perm_r            # right rows sorted by (group, idx)
        self.eligible_l = eligible_l    # live & no null key
        self.eligible_r = eligible_r
        self.matched_r = matched_r      # right rows with >=1 left match
        self.live_l = live_l
        self.live_r = live_r
        self.times_r = times_r          # per right row: # left pair matches

    def tree_flatten(self):
        return ((self.g_l, self.g_r, self.matches, self.starts_g,
                 self.perm_r, self.eligible_l, self.eligible_r,
                 self.matched_r, self.live_l, self.live_r,
                 self.times_r), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    JoinPlanA, lambda p: p.tree_flatten(),
    lambda aux, ch: JoinPlanA.tree_unflatten(aux, ch))


def join_counts(left_keys, right_keys, live_l, live_r,
                cross: bool = False) -> JoinPlanA:
    """Stage A: shared group ids, right-side ordering, per-left-row match
    counts. For cross joins every live pair matches."""
    nl, nr = live_l.shape[0], live_r.shape[0]
    gcap = nl + nr
    if cross:
        g_l = jnp.zeros((nl,), jnp.int32)
        g_r = jnp.zeros((nr,), jnp.int32)
        eligible_l, eligible_r = live_l, live_r
    else:
        g_l, g_r = union_group_ids(left_keys, right_keys, live_l, live_r)
        eligible_l = live_l & ~_any_null_key(left_keys, nl)
        eligible_r = live_r & ~_any_null_key(right_keys, nr)
    # order right rows by (group, original idx); ineligible go last
    g_r_sort = jnp.where(eligible_r, g_r, gcap)
    idx_r = jnp.arange(nr, dtype=jnp.int32)
    _, perm_r = jax.lax.sort((g_r_sort, idx_r), num_keys=2)
    counts = jax.ops.segment_sum(eligible_r.astype(jnp.int32),
                                 jnp.where(eligible_r, g_r, gcap - 1),
                                 num_segments=gcap)
    # exclusive prefix: start of each group's run in perm_r order
    from .gather import exclusive_cumsum
    starts_g = exclusive_cumsum(counts)
    matches = jnp.where(eligible_l, counts[g_l], 0)
    counts_l = jax.ops.segment_sum(eligible_l.astype(jnp.int32),
                                   jnp.where(eligible_l, g_l, gcap - 1),
                                   num_segments=gcap)
    matched_r = eligible_r & (counts_l[g_r] > 0)
    times_r = jnp.where(eligible_r, counts_l[g_r], 0)
    return JoinPlanA(g_l, g_r, matches, starts_g, perm_r, eligible_l,
                     eligible_r, matched_r, live_l, live_r, times_r)


def join_total(plan: JoinPlanA, join_type: str) -> jax.Array:
    """Total output rows (device scalar) for the given join type."""
    m = plan.matches
    if join_type in ("inner", "cross"):
        return jnp.sum(m)
    if join_type == "left_outer":
        return jnp.sum(jnp.where(plan.live_l, jnp.maximum(m, 1), 0))
    if join_type == "right_outer":
        unmatched = plan.live_r & ~plan.matched_r
        return jnp.sum(m) + jnp.sum(unmatched.astype(jnp.int32))
    if join_type == "full_outer":
        unmatched = plan.live_r & ~plan.matched_r
        return jnp.sum(jnp.where(plan.live_l, jnp.maximum(m, 1), 0)) \
            + jnp.sum(unmatched.astype(jnp.int32))
    if join_type == "left_semi":
        return jnp.sum((plan.live_l & (m > 0)).astype(jnp.int32))
    if join_type == "left_anti":
        return jnp.sum((plan.live_l & (m == 0)).astype(jnp.int32))
    raise ValueError(join_type)


def join_output_bytes(plan: JoinPlanA, left: TpuBatch, right: TpuBatch,
                      join_type: str) -> jax.Array:
    """Per-string-column output byte counts from stage-A algebra alone —
    no output indices needed, so sizing folds into the stage-A program
    and the whole staged join pays ONE host sync per batch instead of
    two (VERDICT r3 #1). Column order: left string cols then (except
    semi/anti) right string cols — matching the char-cap order the
    gather stage consumes."""
    m = plan.matches
    if join_type in ("inner", "cross", "right_outer"):
        emit_l = m
    elif join_type in ("left_outer", "full_outer"):
        emit_l = jnp.where(plan.live_l, jnp.maximum(m, 1), 0)
    elif join_type == "left_semi":
        emit_l = (plan.live_l & (m > 0)).astype(jnp.int32)
    else:  # left_anti
        emit_l = (plan.live_l & (m == 0)).astype(jnp.int32)
    # int64 accumulation: a join emitting >2 GiB of string payload would
    # wrap an int32 sum negative and silently truncate strings via an
    # undersized char cap (ADVICE r4)
    counts = []
    for c in left.columns:
        if c.is_string_like:
            lens = c.offsets[1:] - c.offsets[:-1]
            counts.append(jnp.sum(emit_l.astype(jnp.int64)
                                  * lens.astype(jnp.int64)))
    if join_type not in ("left_semi", "left_anti"):
        times = plan.times_r
        if join_type in ("right_outer", "full_outer"):
            times = times + (plan.live_r
                             & ~plan.matched_r).astype(jnp.int32)
        for c in right.columns:
            if c.is_string_like:
                lens = c.offsets[1:] - c.offsets[:-1]
                counts.append(jnp.sum(times.astype(jnp.int64)
                                      * lens.astype(jnp.int64)))
    return jnp.stack(counts) if counts else jnp.zeros((0,), jnp.int64)


def unique_build_analysis(right_keys: Sequence[TpuColumnVector],
                          live_r: jax.Array,
                          payload: Sequence[TpuColumnVector]) -> jax.Array:
    """Build-side facts for the sync-free fast path, ONE small device
    vector (a single host readback per build, not per stream batch):
    [max_dup, max_live_len(payload string col 0), ...]. max_dup <= 1
    means every key appears at most once among eligible build rows, so
    a stream batch of capacity N joins into capacity N — a static bound
    with no per-batch size sync (SURVEY.md §7.3.1 applied at build
    granularity)."""
    from .sort_keys import segment_ids_for_keys
    cap = live_r.shape[0]
    eligible = live_r & ~_any_null_key(right_keys, cap)
    keys = [_norm_key_col(k) for k in right_keys]
    perm, seg, _ = segment_ids_for_keys(keys, eligible)
    live_sorted = eligible[perm]
    counts = jax.ops.segment_sum(live_sorted.astype(jnp.int32), seg,
                                 num_segments=cap)
    parts = [jnp.max(counts, initial=0)]
    for c in payload:
        if c.is_string_like:
            lens = c.offsets[1:] - c.offsets[:-1]
            parts.append(jnp.max(jnp.where(live_r, lens, 0), initial=0))
    return jnp.stack(parts)


def unique_build_probe(rkey: TpuColumnVector, live_r: jax.Array):
    """Presort a single fixed-width build key ONCE per build:
    (rk_sorted, perm, n_eligible, dup_flag). Stream batches then probe
    by searchsorted — no per-batch sort of the build side, no union sort
    at all (the TPU answer to a reusable hash table: a reusable sorted
    array). `dup_flag` is a device bool scalar: some eligible key
    appears more than once — free to compute here (the array is already
    sorted) and the verification the build_unique hint needs
    (VERDICT r4 weak #3): a false hint would silently drop matches."""
    rk = _norm_key_col(rkey)
    eligible = live_r & rk.validity
    v = orderable_int(rk)
    # ineligible rows take the dtype's max BEFORE the sort so the WHOLE
    # sorted array is ascending (searchsorted requires global order, not
    # just an ordered prefix); a real key equal to the max still matches
    # because the probe guards with pos < n_eligible and eligible rows
    # sort before sentinels via the eligibility lane
    v = jnp.where(eligible, v, jnp.array(jnp.iinfo(v.dtype).max, v.dtype))
    elig_lane = jnp.where(eligible, jnp.int8(0), jnp.int8(1))
    idx = jnp.arange(v.shape[0], dtype=jnp.int32)
    _, rk_sorted, perm = jax.lax.sort((elig_lane, v, idx), num_keys=3)
    n_elig = jnp.sum(eligible.astype(jnp.int32))
    pos1 = jnp.arange(1, v.shape[0], dtype=jnp.int32)
    dup = jnp.any((rk_sorted[1:] == rk_sorted[:-1]) & (pos1 < n_elig))
    return rk_sorted, perm, n_elig, dup


def build_dup_flag(right_keys: Sequence[TpuColumnVector],
                   live_r: jax.Array) -> jax.Array:
    """Device bool scalar: some eligible multi-column/string build key
    is duplicated (the union-lookup fast path's hint verification)."""
    from .sort_keys import segment_ids_for_keys
    cap = live_r.shape[0]
    eligible = live_r & ~_any_null_key(right_keys, cap)
    keys = [_norm_key_col(k) for k in right_keys]
    perm, seg, _ = segment_ids_for_keys(keys, eligible)
    live_sorted = eligible[perm]
    counts = jax.ops.segment_sum(live_sorted.astype(jnp.int32), seg,
                                 num_segments=cap)
    return jnp.max(counts, initial=0) > 1


def probe_unique(lkey: TpuColumnVector, eligible_l: jax.Array,
                 rk_sorted: jax.Array, perm_r: jax.Array,
                 n_elig: jax.Array):
    """(ridx, matched) for a unique build via binary search into the
    presorted key array. O(N log M) gathers, fully vectorized."""
    v = orderable_int(_norm_key_col(lkey))
    if v.dtype != rk_sorted.dtype:
        tgt = jnp.promote_types(v.dtype, rk_sorted.dtype)
        v = v.astype(tgt)
        rk_sorted = rk_sorted.astype(tgt)
    pos = jnp.searchsorted(rk_sorted, v, side="left").astype(jnp.int32)
    pos_c = jnp.clip(pos, 0, rk_sorted.shape[0] - 1)
    matched = eligible_l & (pos < n_elig) & (rk_sorted[pos_c] == v)
    return perm_r[pos_c], matched


def unique_union_lookup(left_keys, right_keys, live_l, live_r,
                        eligible_l, eligible_r):
    """(ridx, matched) for a unique build with multi-column or string
    keys: the shared-group-id machinery, but with <=1 right row per
    group the first (only) member IS the match — no output expansion,
    no size sync."""
    nl, nr = live_l.shape[0], live_r.shape[0]
    gcap = nl + nr
    g_l, g_r = union_group_ids(left_keys, right_keys, live_l, live_r)
    g_r_sort = jnp.where(eligible_r, g_r, gcap)
    idx_r = jnp.arange(nr, dtype=jnp.int32)
    _, perm_r = jax.lax.sort((g_r_sort, idx_r), num_keys=2)
    counts = jax.ops.segment_sum(eligible_r.astype(jnp.int32),
                                 jnp.where(eligible_r, g_r, gcap - 1),
                                 num_segments=gcap)
    from .gather import exclusive_cumsum
    starts_g = exclusive_cumsum(counts)
    matched = eligible_l & (counts[g_l] > 0)
    ridx = perm_r[jnp.clip(starts_g[g_l], 0, nr - 1)]
    return ridx, matched


def join_indices(plan: JoinPlanA, join_type: str, out_cap: int):
    """Stage B: per-output-row (left_idx, right_idx, left_valid,
    right_valid) with static out_cap; rows >= total are padding."""
    nl = plan.live_l.shape[0]
    nr = plan.live_r.shape[0]
    j = jnp.arange(out_cap, dtype=jnp.int32)

    if join_type in ("left_semi", "left_anti"):
        keep = plan.live_l & ((plan.matches > 0) if join_type == "left_semi"
                              else (plan.matches == 0))
        from .gather import compaction_indices
        lidx, count = compaction_indices(keep)
        lidx = lidx[:out_cap] if out_cap <= nl else jnp.concatenate(
            [lidx, jnp.zeros((out_cap - nl,), jnp.int32)])
        live_out = j < count
        ridx = jnp.zeros((out_cap,), jnp.int32)
        return lidx, ridx, live_out, jnp.zeros((out_cap,), jnp.bool_), count

    emit = plan.matches
    if join_type in ("left_outer", "full_outer"):
        emit = jnp.where(plan.live_l, jnp.maximum(plan.matches, 1), 0)
    # exclusive cumsum of per-left-row output counts
    from .gather import exclusive_cumsum
    out_start = exclusive_cumsum(emit)
    pairs_total = jnp.sum(emit)
    # map output row -> left row: last i with out_start[i] <= j, restricted
    # to emitting rows (emit>0). searchsorted over the cumsum works because
    # non-emitting rows collapse to zero-width intervals.
    ends = out_start + emit  # exclusive end per left row
    lidx = jnp.searchsorted(ends, j, side="right").astype(jnp.int32)
    lidx = jnp.clip(lidx, 0, nl - 1)
    k = j - out_start[lidx]
    is_pair = k < plan.matches[lidx]
    g = plan.g_l[lidx]
    rpos = jnp.clip(plan.starts_g[g] + k, 0, nr - 1)
    ridx = plan.perm_r[rpos]
    left_valid = j < pairs_total
    right_valid = left_valid & is_pair

    total = pairs_total
    if join_type in ("right_outer", "full_outer"):
        unmatched = plan.live_r & ~plan.matched_r
        from .gather import compaction_indices
        uidx, ucount = compaction_indices(unmatched)
        total = pairs_total + ucount
        in_extra = (j >= pairs_total) & (j < total)
        epos = jnp.clip(j - pairs_total, 0, nr - 1)
        extra_r = uidx[jnp.clip(epos, 0, uidx.shape[0] - 1)]
        ridx = jnp.where(in_extra, extra_r, ridx)
        right_valid = right_valid | in_extra
        left_valid = left_valid & (j < pairs_total)
    live_out = j < total
    return lidx, ridx, left_valid & live_out, right_valid & live_out, total


def join_gather(left: TpuBatch, right: TpuBatch, lidx, ridx, lvalid,
                rvalid, total, out_schema,
                char_caps: Sequence[int]) -> TpuBatch:
    """Stage C: gather both sides into the output batch. lvalid/rvalid
    mask whole sides (outer-join nulls)."""
    from .gather import gather_column
    cols = []
    ci = 0
    for c in left.columns:
        cols.append(gather_column(c, lidx, lvalid, char_caps[ci]
                                  if c.is_string_like else None))
        ci += 1
    for c in right.columns:
        cols.append(gather_column(c, ridx, rvalid, char_caps[ci]
                                  if c.is_string_like else None))
        ci += 1
    return TpuBatch(cols, out_schema, total)
