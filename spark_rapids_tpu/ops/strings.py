"""Device string kernels over (offsets:int32, chars:uint8) columns.

TPU replacement for libcudf's strings kernels (SURVEY.md §2.2-E; mount
empty). Strings are Arrow-layout byte arrays; kernels are vectorized
gathers/compares over fixed-size byte windows so shapes stay static —
variable-length work is bounded by a while_loop with early exit, not
per-row dynamic control flow.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.column import TpuColumnVector

__all__ = ["string_lengths", "string_compare_tpu", "gather_window",
           "substring_tpu", "upper_ascii_tpu", "lower_ascii_tpu",
           "concat_strings_tpu", "starts_with_tpu", "ends_with_tpu",
           "contains_tpu", "gather_strings"]

_WINDOW = 64  # bytes compared per loop step


def string_lengths(col: TpuColumnVector) -> jax.Array:
    """Byte length per row (int32)."""
    return col.offsets[1:] - col.offsets[:-1]


def gather_window(offsets, chars, chunk, window=_WINDOW):
    """(n, window) int16 byte matrix for window #chunk of each string.
    Past-end positions are -1 (sorts below any real byte)."""
    n = offsets.shape[0] - 1
    starts = offsets[:-1]
    lens = offsets[1:] - starts
    pos = chunk * window + jnp.arange(window, dtype=jnp.int32)[None, :]
    idx = starts[:, None] + pos
    in_range = pos < lens[:, None]
    limit = max(chars.shape[0] - 1, 0)
    idx = jnp.clip(idx, 0, limit)
    if chars.shape[0] == 0:
        b = jnp.zeros((n, window), jnp.int16)
    else:
        b = chars[idx].astype(jnp.int16)
    return jnp.where(in_range, b, jnp.int16(-1))


def string_compare_tpu(a: TpuColumnVector, b: TpuColumnVector) -> jax.Array:
    """Row-wise lexicographic compare (unsigned bytes): int8 -1/0/1."""
    max_len = jnp.maximum(
        jnp.max(string_lengths(a), initial=0),
        jnp.max(string_lengths(b), initial=0))

    def body(state):
        chunk, result, done = state
        wa = gather_window(a.offsets, a.chars, chunk)
        wb = gather_window(b.offsets, b.chars, chunk)
        diff = wa != wb
        any_diff = jnp.any(diff, axis=1)
        first = jnp.argmax(diff, axis=1)
        sa = jnp.take_along_axis(wa, first[:, None], axis=1)[:, 0]
        sb = jnp.take_along_axis(wb, first[:, None], axis=1)[:, 0]
        cmp = jnp.where(sa < sb, jnp.int8(-1), jnp.int8(1))
        new_result = jnp.where(done, result,
                               jnp.where(any_diff, cmp, jnp.int8(0)))
        # a row is finished if bytes differed, or both strings ended
        ended = (chunk + 1) * _WINDOW >= max_len
        new_done = done | any_diff | ended
        return chunk + 1, new_result, new_done

    def cond(state):
        chunk, _, done = state
        return ~jnp.all(done)

    n = a.offsets.shape[0] - 1
    init = (jnp.int32(0), jnp.zeros((n,), jnp.int8),
            jnp.zeros((n,), jnp.bool_))
    _, result, _ = jax.lax.while_loop(cond, body, init)
    return result


def gather_strings(col: TpuColumnVector, indices: jax.Array,
                   char_capacity: int, out_live=None) -> TpuColumnVector:
    """Reorder a string column by row indices, all gathers (no scatter —
    arbitrary scatters serialize on TPU, gathers don't).

    Output offsets = cumulative gathered lengths (log-depth int32
    associative_scan: serial int cumsum and 24-bit-exact f64-as-f32
    cumsum both lose on TPU). For each output char position, the
    owning row comes from one searchsorted over the offsets, then the byte
    is a single gather from the source. out_live (if given) zeroes the
    lengths of dead output rows so padding can't inflate the offsets."""
    n = indices.shape[0]
    lens = string_lengths(col)
    new_lens = lens[indices]
    if out_live is not None:
        new_lens = jnp.where(out_live, new_lens, 0)
    from .gather import inclusive_int_cumsum
    new_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), inclusive_int_cumsum(new_lens)])
    src_starts = col.offsets[:-1][indices]

    c = jnp.arange(char_capacity, dtype=jnp.int32)
    row = jnp.searchsorted(new_offsets[1:], c, side="right")
    row = jnp.clip(row, 0, n - 1).astype(jnp.int32)
    within = c - new_offsets[row]
    src = src_starts[row] + within
    total = new_offsets[-1]
    valid_pos = c < total
    if col.chars.shape[0]:
        limit = col.chars.shape[0] - 1
        out = jnp.where(valid_pos,
                        col.chars[jnp.clip(src, 0, limit)],
                        jnp.uint8(0))
    else:
        out = jnp.zeros((char_capacity,), jnp.uint8)
    validity = col.validity[indices]
    return TpuColumnVector(col.dtype, validity=validity,
                           offsets=new_offsets, chars=out)


def substring_tpu(col: TpuColumnVector, start: jax.Array, length: jax.Array,
                  char_capacity: int) -> TpuColumnVector:
    """Byte-substring (Spark SUBSTRING is char-based; exact for ASCII —
    the planner falls back for non-ASCII batches when configured)."""
    lens = string_lengths(col)
    # Spark 1-based start; negative counts from end; clamp like Spark.
    s = jnp.where(start > 0, start - 1,
                  jnp.where(start < 0, jnp.maximum(lens + start, 0), 0))
    s = jnp.minimum(s, lens)
    ln = jnp.clip(length, 0)
    e = jnp.minimum(s + ln, lens)
    new_lens = (e - s).astype(jnp.int32)
    new_offsets = jnp.concatenate([
        jnp.zeros((1,), jnp.int32),
        jnp.cumsum(new_lens, dtype=jnp.int32)])
    src_starts = col.offsets[:-1] + s.astype(jnp.int32)
    n = lens.shape[0]

    def loop_body(state):
        chunk, out = state
        pos = chunk * _WINDOW + jnp.arange(_WINDOW, dtype=jnp.int32)[None, :]
        in_range = pos < new_lens[:, None]
        src_idx = jnp.clip(src_starts[:, None] + pos, 0,
                           max(col.chars.shape[0] - 1, 0))
        vals = col.chars[src_idx] if col.chars.shape[0] else \
            jnp.zeros((n, _WINDOW), jnp.uint8)
        dst_idx = jnp.where(in_range, new_offsets[:-1][:, None] + pos,
                            char_capacity)
        out = out.at[dst_idx.reshape(-1)].set(vals.reshape(-1), mode="drop")
        return chunk + 1, out

    max_chunks = jnp.int32(-(-jnp.max(new_lens, initial=0) // _WINDOW))
    out = jnp.zeros((char_capacity,), jnp.uint8)
    _, out = jax.lax.while_loop(lambda st: st[0] < max_chunks, loop_body,
                                (jnp.int32(0), out))
    return TpuColumnVector(col.dtype, validity=col.validity,
                           offsets=new_offsets, chars=out)


def _case_map_ascii(chars: jax.Array, to_upper: bool) -> jax.Array:
    if to_upper:
        is_lower = (chars >= ord("a")) & (chars <= ord("z"))
        return jnp.where(is_lower, chars - 32, chars)
    is_upper = (chars >= ord("A")) & (chars <= ord("Z"))
    return jnp.where(is_upper, chars + 32, chars)


def upper_ascii_tpu(col: TpuColumnVector) -> TpuColumnVector:
    return col.with_arrays(chars=_case_map_ascii(col.chars, True))


def lower_ascii_tpu(col: TpuColumnVector) -> TpuColumnVector:
    return col.with_arrays(chars=_case_map_ascii(col.chars, False))


def concat_strings_tpu(cols, char_capacity: int,
                       validity=None) -> TpuColumnVector:
    """Row-wise CONCAT of string columns (null if any input null — Spark)."""
    n = cols[0].offsets.shape[0] - 1
    lens = [string_lengths(c) for c in cols]
    total = sum(lens)
    new_offsets = jnp.concatenate([
        jnp.zeros((1,), jnp.int32),
        jnp.cumsum(total, dtype=jnp.int32)])
    out = jnp.zeros((char_capacity,), jnp.uint8)
    base = new_offsets[:-1]
    for c, ln in zip(cols, lens):
        src_starts = c.offsets[:-1]

        def loop_body(state, c=c, ln=ln, base=base, src_starts=src_starts):
            chunk, acc = state
            pos = chunk * _WINDOW + \
                jnp.arange(_WINDOW, dtype=jnp.int32)[None, :]
            in_range = pos < ln[:, None]
            src_idx = jnp.clip(src_starts[:, None] + pos, 0,
                               max(c.chars.shape[0] - 1, 0))
            vals = c.chars[src_idx] if c.chars.shape[0] else \
                jnp.zeros((n, _WINDOW), jnp.uint8)
            dst_idx = jnp.where(in_range, base[:, None] + pos, char_capacity)
            acc = acc.at[dst_idx.reshape(-1)].set(vals.reshape(-1),
                                                  mode="drop")
            return chunk + 1, acc

        max_chunks = jnp.int32(-(-jnp.max(ln, initial=0) // _WINDOW))
        _, out = jax.lax.while_loop(lambda st: st[0] < max_chunks, loop_body,
                                    (jnp.int32(0), out))
        base = base + ln
    if validity is None:
        validity = cols[0].validity
        for c in cols[1:]:
            validity = validity & c.validity
    return TpuColumnVector(cols[0].dtype, validity=validity,
                           offsets=new_offsets, chars=out)


def _match_at(col: TpuColumnVector, pat: np.ndarray, starts) -> jax.Array:
    """True where pat matches at byte offset `starts` (per-row)."""
    k = len(pat)
    if k == 0:
        return jnp.ones((col.offsets.shape[0] - 1,), jnp.bool_)
    idx = starts[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
    idx = jnp.clip(idx, 0, max(col.chars.shape[0] - 1, 0))
    b = col.chars[idx] if col.chars.shape[0] else \
        jnp.zeros((col.offsets.shape[0] - 1, k), jnp.uint8)
    return jnp.all(b == jnp.asarray(pat)[None, :], axis=1)


def starts_with_tpu(col: TpuColumnVector, pattern: bytes) -> jax.Array:
    pat = np.frombuffer(pattern, np.uint8)
    lens = string_lengths(col)
    ok = lens >= len(pat)
    return ok & _match_at(col, pat, col.offsets[:-1])


def ends_with_tpu(col: TpuColumnVector, pattern: bytes) -> jax.Array:
    pat = np.frombuffer(pattern, np.uint8)
    lens = string_lengths(col)
    ok = lens >= len(pat)
    starts = col.offsets[:-1] + lens - len(pat)
    return ok & _match_at(col, pat, jnp.maximum(starts, 0))


def contains_tpu(col: TpuColumnVector, pattern: bytes) -> jax.Array:
    """Substring search: slide the pattern over every position (bounded by
    max row length via while_loop)."""
    pat = np.frombuffer(pattern, np.uint8)
    n = col.offsets.shape[0] - 1
    lens = string_lengths(col)
    if len(pat) == 0:
        return jnp.ones((n,), jnp.bool_)
    max_start = jnp.max(lens, initial=0) - len(pat)

    def loop_body(state):
        i, found = state
        starts = col.offsets[:-1] + i
        in_range = i <= lens - len(pat)
        m = _match_at(col, pat, starts) & in_range
        return i + 1, found | m

    _, found = jax.lax.while_loop(
        lambda st: (st[0] <= max_start) & ~jnp.all(st[1]),
        loop_body, (jnp.int32(0), jnp.zeros((n,), jnp.bool_)))
    return found
