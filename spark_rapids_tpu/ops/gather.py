"""Row gather / stream-compaction kernels.

TPU replacement for libcudf's stream compaction (apply_boolean_mask,
gather/scatter — SURVEY.md §2.2-E; reference mount empty). Filter output
size is data-dependent, which XLA can't express as a shape — so compaction
is prefix-sum + scatter into the SAME static capacity, with the live count
threaded alongside (SURVEY.md §7.3.1).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar.batch import TpuBatch, row_mask
from ..columnar.column import TpuColumnVector
from .strings import gather_strings

__all__ = ["compaction_indices", "gather_column", "gather_batch",
           "compact_batch"]


def compaction_indices(keep: jax.Array):
    """(indices, count): indices[j] = source row of the j-th kept row, for
    j < count; rows >= count point at row 0 (padding garbage).

    keep must already exclude padding rows (AND with the batch live mask).
    """
    cap = keep.shape[0]
    positions = jnp.cumsum(keep.astype(jnp.int32)) - 1
    count = positions[-1] + 1 if cap else jnp.int32(0)
    dst = jnp.where(keep, positions, cap)
    indices = jnp.zeros((cap,), jnp.int32).at[dst].set(
        jnp.arange(cap, dtype=jnp.int32), mode="drop")
    return indices, count


def gather_column(col: TpuColumnVector, indices: jax.Array,
                  out_live: jax.Array,
                  char_capacity: int = None) -> TpuColumnVector:
    """Reorder a column by row indices; out_live masks validity of padding
    rows in the output so downstream null-aware kernels see them as null."""
    validity = col.validity[indices] & out_live
    if col.is_string_like:
        cap = char_capacity if char_capacity is not None \
            else col.chars.shape[0]
        out = gather_strings(col, indices, cap)
        return out.with_arrays(validity=validity)
    if col.data is None:  # NullType
        return col.with_arrays(validity=validity)
    return col.with_arrays(data=col.data[indices], validity=validity)


def gather_batch(batch: TpuBatch, indices: jax.Array, count,
                 char_capacities=None) -> TpuBatch:
    """Reorder/compact a whole batch by row indices (count = live rows)."""
    out_live = row_mask(indices.shape[0], count)
    cols = []
    for i, c in enumerate(batch.columns):
        cc = None if char_capacities is None else char_capacities[i]
        cols.append(gather_column(c, indices, out_live, cc))
    return TpuBatch(cols, batch.schema, count)


def compact_batch(batch: TpuBatch, keep: jax.Array) -> TpuBatch:
    """Stream compaction: keep rows where `keep` (padding excluded here)."""
    keep = keep & batch.live_mask()
    indices, count = compaction_indices(keep)
    return gather_batch(batch, indices, count)
