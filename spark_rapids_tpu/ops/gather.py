"""Row gather / stream-compaction kernels.

TPU replacement for libcudf's stream compaction (apply_boolean_mask,
gather/scatter — SURVEY.md §2.2-E; reference mount empty). Filter output
size is data-dependent, which XLA can't express as a shape — so compaction
is prefix-sum + scatter into the SAME static capacity, with the live count
threaded alongside (SURVEY.md §7.3.1).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar.batch import TpuBatch, row_mask
from ..columnar.column import TpuColumnVector
from .strings import gather_strings

__all__ = ["compaction_indices", "exclusive_cumsum", "invert_permutation",
           "gather_column", "gather_batch", "gather_columns",
           "compact_batch", "ensure_compacted", "shrink_batch"]


def inclusive_int_cumsum(x: jax.Array) -> jax.Array:
    """Inclusive int32 prefix sum via the native cumulative-sum HLO.
    Measured on the v5e (2M elements): 0.08 ms run / ~7 s compile — the
    previous `lax.associative_scan` network ran equally fast but cost
    200+ s of XLA compile per program on the axon backend. Int cumsum is
    exact to 2^31 (f64 would be f32 on TPU — only 2^24)."""
    return jnp.cumsum(x.astype(jnp.int32))


def exclusive_cumsum(x: jax.Array) -> jax.Array:
    """Exclusive int32 prefix sum (see inclusive_int_cumsum)."""
    x = x.astype(jnp.int32)
    return inclusive_int_cumsum(x) - x


def invert_permutation(perm: jax.Array, values: jax.Array) -> jax.Array:
    """out[perm[i]] = values[i] without a scatter: sorting (perm, values)
    by perm reorders values back to original positions. lax.sort is fast
    on TPU where arbitrary scatters serialize."""
    _, out = jax.lax.sort((perm, values), num_keys=1)
    return out


def compaction_indices(keep: jax.Array):
    """(indices, count): indices[j] = source row of the j-th kept row, for
    j < count; rows >= count hold the non-kept rows (gather of them is
    masked by the caller's out_live).

    Sort-based: one stable 2-key sort, no scatter, no int cumsum (both
    serialize on TPU). keep must already exclude padding rows.
    """
    cap = keep.shape[0]
    key = jnp.where(keep, jnp.int8(0), jnp.int8(1))
    idx = jnp.arange(cap, dtype=jnp.int32)
    _, indices = jax.lax.sort((key, idx), num_keys=2)
    count = jnp.sum(keep.astype(jnp.int32))
    return indices, count


def gather_list(col: TpuColumnVector, indices: jax.Array,
                out_live: jax.Array) -> TpuColumnVector:
    """Reorder an array/map column by row indices: new offsets are the
    prefix sum of gathered lengths; each output ELEMENT position finds
    its row by searchsorted, and the element columns gather recursively
    by the resulting source-element indices (strings work the same way
    one level down — gather_strings is this kernel with uint8 chars).
    The element capacity stays the child's static capacity (each source
    element appears at most once per gathered row set; duplicates from
    repeated indices are bounded by the caller's semantics)."""
    n = indices.shape[0]
    lens = col.offsets[1:] - col.offsets[:-1]
    new_lens = lens[indices]
    if out_live is not None:
        new_lens = jnp.where(out_live, new_lens, 0)
    new_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), inclusive_int_cumsum(new_lens)])
    validity = col.validity[indices]
    if out_live is not None:
        validity = validity & out_live
    ecap = col.children[0].capacity
    if ecap == 0:
        return col.with_arrays(validity=validity,
                               offsets=jnp.zeros((n + 1,), jnp.int32))
    src_starts = col.offsets[:-1][indices]
    e = jnp.arange(ecap, dtype=jnp.int32)
    row = jnp.searchsorted(new_offsets[1:], e, side="right")
    row = jnp.clip(row, 0, n - 1).astype(jnp.int32)
    within = e - new_offsets[row]
    src = jnp.clip(src_starts[row] + within, 0, ecap - 1)
    elem_live = e < new_offsets[-1]
    children = [gather_column(ch, src, elem_live) for ch in col.children]
    return col.with_arrays(validity=validity, offsets=new_offsets,
                           children=children)


def gather_column(col: TpuColumnVector, indices: jax.Array,
                  out_live: jax.Array,
                  char_capacity: int = None) -> TpuColumnVector:
    """Reorder a column by row indices; out_live masks validity of padding
    rows in the output so downstream null-aware kernels see them as null."""
    validity = col.validity[indices] & out_live
    if col.is_string_like:
        cap = char_capacity if char_capacity is not None \
            else col.chars.shape[0]
        out = gather_strings(col, indices, cap, out_live=out_live)
        return out.with_arrays(validity=validity)
    if col.offsets is not None and col.children is not None:  # array/map
        return gather_list(col, indices, out_live)
    if col.children is not None:  # struct
        children = [gather_column(ch, indices, out_live)
                    for ch in col.children]
        return col.with_arrays(validity=validity, children=children)
    if col.data is None:  # NullType
        return col.with_arrays(validity=validity)
    return col.with_arrays(data=col.data[indices], validity=validity)


def gather_batch(batch: TpuBatch, indices: jax.Array, count,
                 char_capacities=None) -> TpuBatch:
    """Reorder/compact a whole batch by row indices (count = live rows),
    prefix layout. See gather_columns for the packed-gather mechanics."""
    out_live = row_mask(indices.shape[0], count)
    cols = gather_columns(batch.columns, indices, out_live,
                          char_capacities)
    return TpuBatch(cols, batch.schema, count)


def gather_columns(columns, indices: jax.Array, out_live: jax.Array,
                   char_capacities=None):
    """Reorder a list of columns by row indices with an arbitrary
    live-output mask (need not be a prefix — the join fast path gathers
    build rows into match positions).

    All fixed-width data lanes are bitcast to int32 words and packed —
    together with the validity bits (one int32 bitfield lane per 32
    columns) — into a single (rows, words) matrix, so the whole set
    moves in ONE row gather: N separate 1-D gathers cost ~30ms each on
    TPU, a packed 2-D row gather is ~free."""
    n = columns[0].capacity if columns else 0  # input rows (packing side)

    lanes = []          # (n, w) int32 blocks to pack
    col_lanes = []      # per column: (kind, lane_offset, width)
    off = 0
    for c in columns:
        if c.is_string_like or c.data is None or c.children is not None:
            col_lanes.append(("special", 0, 0))
            continue
        if c.data.dtype == jnp.float64:
            # TPU has no native f64 (stored/computed as f32) and its X64
            # rewriter cannot implement bitcast f64<->s64; gather the
            # lane directly instead of packing it
            col_lanes.append(("direct", 0, 0))
            continue
        d = c.data
        if d.dtype == jnp.bool_:
            w = d.astype(jnp.int32)[:, None]
        elif d.dtype.itemsize < 4:
            w = d.astype(jnp.int32)[:, None]
        elif d.dtype.itemsize == 4:
            w = jax.lax.bitcast_convert_type(d, jnp.int32)[:, None]
        else:  # 8-byte lanes -> two int32 words: (n,) i64 -> (n, 2) i32
            w = jax.lax.bitcast_convert_type(
                jax.lax.bitcast_convert_type(d, jnp.int64), jnp.int32)
        lanes.append(w)
        col_lanes.append(("packed", off, w.shape[1]))
        off += w.shape[1]
    # validity bitfields: 32 columns per int32 lane
    ncols = len(columns)
    vwords = []
    for base in range(0, ncols, 32):
        word = jnp.zeros((n,), jnp.int32)
        for bit, c in enumerate(columns[base: base + 32]):
            word = word | (c.validity.astype(jnp.int32) << bit)
        vwords.append(word[:, None])
    vbase = off
    lanes.extend(vwords)
    off += len(vwords)

    packed = jnp.concatenate(lanes, axis=1) if lanes else None
    gathered = packed[indices] if packed is not None else None

    cols = []
    for i, c in enumerate(columns):
        word = gathered[:, vbase + i // 32]
        validity = (((word >> (i % 32)) & 1) != 0) & out_live
        kind, loff, width = col_lanes[i]
        if kind == "direct":
            cols.append(c.with_arrays(data=c.data[indices],
                                      validity=validity))
            continue
        if kind == "special":
            if c.is_string_like:
                cc = char_capacities[i] if char_capacities is not None \
                    else c.chars.shape[0]
                out = gather_strings(c, indices, cc, out_live=out_live)
                cols.append(out.with_arrays(validity=validity))
            elif c.children is not None:  # struct / array / map
                out = gather_column(c, indices, out_live)
                cols.append(out.with_arrays(validity=validity))
            else:  # NullType
                cols.append(c.with_arrays(validity=validity))
            continue
        d = c.data
        g = gathered[:, loff: loff + width]
        if d.dtype == jnp.bool_:
            data = g[:, 0] != 0
        elif d.dtype.itemsize < 4:
            data = g[:, 0].astype(d.dtype)
        elif d.dtype.itemsize == 4:
            data = jax.lax.bitcast_convert_type(g[:, 0], d.dtype)
        else:
            i64 = jax.lax.bitcast_convert_type(g, jnp.int64)  # (n_out,)
            data = i64 if d.dtype == jnp.int64 else \
                jax.lax.bitcast_convert_type(i64, d.dtype)
        cols.append(c.with_arrays(data=data, validity=validity))
    return cols


def compact_batch(batch: TpuBatch, keep: jax.Array) -> TpuBatch:
    """Stream compaction: keep rows where `keep` (padding excluded here)."""
    keep = keep & batch.live_mask()
    indices, count = compaction_indices(keep)
    return gather_batch(batch, indices, count)  # prefix layout, no selection


@jax.jit
def _compact_selection(batch: TpuBatch) -> TpuBatch:
    return compact_batch(batch, batch.live_mask())


def _shrink_col(c: TpuColumnVector, new_cap: int) -> TpuColumnVector:
    if c.data is not None:
        return c.with_arrays(data=c.data[:new_cap],
                             validity=c.validity[:new_cap])
    if c.offsets is not None:  # strings / arrays: payload stays shared
        return c.with_arrays(offsets=c.offsets[:new_cap + 1],
                             validity=c.validity[:new_cap])
    if c.children is not None:  # struct: children align with rows
        return c.with_arrays(validity=c.validity[:new_cap],
                             children=[_shrink_col(ch, new_cap)
                                       for ch in c.children])
    return c.with_arrays(validity=c.validity[:new_cap])


def shrink_batch(batch: TpuBatch, new_cap: int) -> TpuBatch:
    """Slice a prefix-layout batch down to a smaller static capacity
    (row_count must be <= new_cap). Fixed-width lanes are static slices;
    string chars / array elements stay shared (offsets are absolute)."""
    assert batch.selection is None, "compact before shrinking"
    if new_cap >= batch.capacity:
        return batch
    cols = [_shrink_col(c, new_cap) for c in batch.columns]
    return TpuBatch(cols, batch.schema, batch.row_count)


def ensure_compacted(batch: TpuBatch) -> TpuBatch:
    """Materialize a lazy selection mask (TpuBatch docstring) into prefix
    layout; no-op (and no dispatch) when the batch has no selection.
    Callable from host code or inside traced code (the selection check is
    static; nested jit inlines)."""
    if batch.selection is None:
        return batch
    return _compact_selection(batch)
