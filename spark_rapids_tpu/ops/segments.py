"""Sorted-segment reductions without scatters.

TPU replacement for the scatter-shaped `jax.ops.segment_*` family on the
aggregate path (SURVEY.md §7.1.3; reference mount empty). XLA lowers
`segment_sum`/`min`/`max` to scatter-adds that serialize on TPU
(~100 ms per 2M rows, measured); but the engine's sort-based group-by
always presents SORTED segment ids, where the same reductions are
scan/sort/gather shaped:

- **sum**: native `jnp.cumsum` (a dedicated cumulative HLO — measured
  0.1 ms / 2M rows, ~8 s compile; `lax.associative_scan` computes the
  same thing but costs 200 s+ of compile on the axon backend), then per
  segment the difference of prefix values at its edges, found by
  `searchsorted` over the sorted ids. Exact for ints; for floats the
  rounding matches a running left-to-right sum (the order-variance the
  engine already declares via variableFloatAgg).
- **min/max**: one stable 2-lane sort by (segment, value) puts each
  segment's extreme at its edge — a gather, no scan at all.

Empty segments (ids past the live groups) read the op identity, matching
`jax.ops.segment_*` semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["seg_reduce_sorted", "segment_starts_sorted"]


def _identity(kind: str, dtype):
    if kind == "sum":
        return jnp.zeros((), dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        v = jnp.inf if kind == "min" else -jnp.inf
    else:
        info = jnp.iinfo(dtype)
        v = info.max if kind == "min" else info.min
    return jnp.array(v, dtype)


def seg_reduce_sorted(vals: jax.Array, seg: jax.Array, cap: int,
                      kind: str) -> jax.Array:
    """Reduce `vals` per segment for SORTED (non-decreasing) `seg` ids,
    output length `cap` indexed by segment id. kind: sum|min|max."""
    n = seg.shape[0]
    g = jnp.arange(cap, dtype=seg.dtype)
    right = jnp.searchsorted(seg, g, side="right").astype(jnp.int32)
    left = jnp.searchsorted(seg, g, side="left").astype(jnp.int32)
    empty = right == left

    def prefix_diff(v):
        # exact for ints (the only users): one global cumsum, edge diffs
        ps = jnp.cumsum(v)
        hi = ps[jnp.clip(right - 1, 0, n - 1)]
        lo = jnp.where(left > 0, ps[jnp.clip(left - 1, 0, n - 1)],
                       jnp.zeros((), ps.dtype))
        return hi - lo

    def blocked_float_sum(v):
        """Float segment sums from BLOCK-LOCAL prefixes: a plain global
        prefix-diff inherits the absolute rounding error of the whole
        running total, zeroing small segments that sit after a large
        prefix (observed: one 1.0-row segment after 16K rows of 2000.0
        read back as 0.0 in f32 — and TPU f64 IS f32). Here prefixes
        reset every K rows, so an in-block segment's error scales with
        its own block; only segments spanning >= K rows touch the
        block-total prefix, whose error is small relative to any
        segment that large."""
        K = min(1024, n)
        nb = -(-n // K)
        vp = jnp.pad(v, (0, nb * K - n))
        p2 = jnp.cumsum(vp.reshape(nb, K), axis=1)
        pflat = p2.reshape(-1)
        t = p2[:, -1]                       # per-block totals
        bt = jnp.cumsum(t)                  # block-total prefix
        l = jnp.clip(left, 0, n - 1)
        r_ = jnp.clip(right - 1, 0, n - 1)  # inclusive last row
        bl, br = l // K, r_ // K
        p_last = pflat[r_]
        p_before = jnp.where(l % K == 0, jnp.zeros((), pflat.dtype),
                             pflat[jnp.clip(l - 1, 0, n - 1)])
        same = bl == br
        head = t[bl] - p_before
        mid = jnp.where(br - bl >= 2,
                        bt[jnp.clip(br - 1, 0, nb - 1)] - bt[bl],
                        jnp.zeros((), bt.dtype))
        return jnp.where(same, p_last - p_before, head + mid + p_last)

    if kind == "sum":
        if jnp.issubdtype(vals.dtype, jnp.floating):
            # non-finite values would poison prefix differences for
            # every later segment (inf-inf = NaN); count them per
            # segment with exact int prefixes and recompose IEEE
            # semantics on top of the finite part
            finite = jnp.isfinite(vals)
            base = blocked_float_sum(jnp.where(finite, vals,
                                               jnp.zeros((), vals.dtype)))
            nan_c = prefix_diff(jnp.isnan(vals).astype(jnp.int32))
            pos_c = prefix_diff((vals == jnp.inf).astype(jnp.int32))
            neg_c = prefix_diff((vals == -jnp.inf).astype(jnp.int32))
            out = jnp.where(
                (nan_c > 0) | ((pos_c > 0) & (neg_c > 0)),
                jnp.array(jnp.nan, vals.dtype),
                jnp.where(pos_c > 0, jnp.array(jnp.inf, vals.dtype),
                          jnp.where(neg_c > 0,
                                    jnp.array(-jnp.inf, vals.dtype),
                                    base.astype(vals.dtype))))
        else:
            out = prefix_diff(vals).astype(vals.dtype)
    else:
        if vals.dtype == jnp.bool_:
            raise TypeError("sort-based min/max needs an orderable lane")
        _, sval = jax.lax.sort((seg, vals), num_keys=2)
        edge = left if kind == "min" else jnp.clip(right - 1, 0, n - 1)
        out = sval[jnp.clip(edge, 0, n - 1)]
    return jnp.where(empty, _identity(kind, vals.dtype), out)


def segment_starts_sorted(seg: jax.Array, cap: int) -> jax.Array:
    """starts[g] = first position of segment g in the sorted order (cap
    entries; empty/out-of-range segments clamp into [0, n-1]). A
    searchsorted, not a sort — the previous compaction-based
    implementation paid a full 2-lane sort per aggregate batch."""
    g = jnp.arange(cap, dtype=seg.dtype)
    n = seg.shape[0]
    return jnp.clip(jnp.searchsorted(seg, g, side="left"), 0,
                    n - 1).astype(jnp.int32)
