"""Pallas TPU kernels — the hand-written tier below XLA.

SURVEY.md §7.1.3 left open whether hand-written Pallas kernels beat
XLA's fusion for this engine's hot loops (the reference's answer is
libcudf CUDA kernels for everything; the TPU bet was that XLA fusion
covers most of it). This module carries the measured answer
(VERDICT r3 item 10): `masked_product_sum` is the q6 inner loop —
filter conjuncts + product + reduction in ONE pass over VMEM tiles —
implemented with explicit Pallas tiling, A/B-benchmarked against the
identical jnp/XLA formulation in bench.py (`pallas_ab`).

The kernel grids over row tiles reshaped to (rows/128, 128) lanes; each
step reduces its tile into a (1, 1) accumulator ref (sequential grid
steps on TPU make the += safe). `interpret=True` keeps it runnable on
the CPU test mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["masked_product_sum_pallas", "masked_product_sum_xla",
           "gather_pallas", "gather_xla", "sort_pallas", "sort_xla",
           "fused_filter_agg_pallas", "fused_filter_agg_xla",
           "FUSED_AGG_GROUPS"]

_TILE_ROWS = 2048
_LANES = 128


def masked_product_sum_xla(quantity, price, discount, shipdate):
    """The q6 inner loop as XLA sees it (what the engine's fused
    filter->project->agg pipeline lowers to)."""
    mask = ((shipdate >= 8766) & (shipdate < 9131)
            & (discount >= 0.05) & (discount <= 0.07)
            & (quantity < 24.0))
    return jnp.sum(jnp.where(mask, price * discount, 0.0),
                   dtype=jnp.float32)


def _kernel(q_ref, p_ref, d_ref, s_ref, out_ref):
    q = q_ref[...]
    p = p_ref[...]
    d = d_ref[...]
    s = s_ref[...]
    mask = ((s >= 8766) & (s < 9131) & (d >= 0.05) & (d <= 0.07)
            & (q < 24.0))
    vals = jnp.where(mask, p * d, 0.0)
    # reduce the (TILE_ROWS, 128) tile to a min-tile (8, 128) partial —
    # a (1, 1) accumulator is below the f32 tile floor and fails Mosaic
    out_ref[...] = jnp.sum(vals.reshape(-1, 8, _LANES), axis=0,
                           dtype=jnp.float32)


@functools.partial(jax.jit, static_argnums=(4,))
def masked_product_sum_pallas(quantity, price, discount, shipdate,
                              interpret: bool = False):
    """Pallas edition: one grid-free kernel invocation per VMEM-sized
    chunk (the axon remote compiler 500s on any GRIDDED Mosaic kernel —
    bisected empirically — so chunking happens at the XLA level:
    several pallas_call ops composed under one jit, partial (8, 128)
    tiles summed outside). Row count must be a multiple of
    _TILE_ROWS*_LANES (the bench pads; engine batches are power-of-two
    capacities anyway)."""
    from jax.experimental import pallas as pl
    n = quantity.shape[0]
    rows = n // _LANES
    chunks = rows // _TILE_ROWS
    call = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((8, _LANES), jnp.float32),
        interpret=interpret)
    parts = []
    for c in range(chunks):
        lo = c * _TILE_ROWS * _LANES
        hi = lo + _TILE_ROWS * _LANES
        shape2d = (_TILE_ROWS, _LANES)
        parts.append(call(quantity[lo:hi].reshape(shape2d),
                          price[lo:hi].reshape(shape2d),
                          discount[lo:hi].reshape(shape2d),
                          shipdate[lo:hi].reshape(shape2d)))
    return jnp.sum(jnp.stack(parts), dtype=jnp.float32)


# --- gather A/B: the HARD candidate (VERDICT r4 weak #10) -------------------
# The round-4 A/B measured only the kernel XLA was always going to win
# (fused elementwise+reduce at the memory roofline). The shapes where a
# hand kernel could plausibly pay are GATHER-bound: the join's
# build-side probe gather and _ragged_to_matrix. This pair measures a
# representative random gather (out[i] = table[idx[i]]) both ways; if
# the Mosaic compiler rejects the dynamic-index kernel (the axon remote
# compiler already rejects all gridded kernels), bench.py records that
# as the documented unmeasurable case rather than implying a no-win.

_G_ROWS = 1024


def gather_xla(table, idx):
    return table[idx]


def _gather_kernel(t_ref, i_ref, o_ref):
    table = t_ref[...]                      # (T/128, 128)
    idx = i_ref[...]                        # (R, 128) int32 (flat)
    # the natural formulation; Mosaic (this jax/libtpu vintage) rejects
    # 1-D dynamic gathers ("Only 2D gather is supported") and the 2-D
    # row-gather alternative blows the tracer up — bench.py records the
    # rejection verbatim so the A/B stays falsifiable, not silently
    # skipped (VERDICT r4 weak #10)
    o_ref[...] = jnp.take(table.reshape(-1), idx, axis=0)


# --- sort A/B: the remaining open kernel question ---------------------------
# BENCH_r05 settled the gather shape (pallas_gather_ab: Mosaic rejects
# the dynamic gather on this vintage) but the SORT shape was never
# measured — and it is NOT gather-blocked: a bitonic network is pure
# compare-exchange over statically-shaped reshapes, exactly the op mix
# Mosaic lowers. Sort backs the engine's sort exec, the range
# partitioner's bounds, and the local shuffle's stats kernel, so a win
# here would be load-bearing. bench.py A/Bs `sort_pallas` against
# jax.lax.sort as `pallas_sort_ab`, recording a mosaic-rejected status
# verbatim if lowering fails (same falsifiability contract as the
# gather A/B).

def sort_xla(keys):
    return jax.lax.sort(keys)


def _sort_kernel(k_ref, o_ref):
    x = k_ref[...].reshape(-1)
    n = x.shape[0]
    # bitonic sort network: static log^2(n) compare-exchange stages.
    # Pairs at distance `stride` sit in lanes [:, 0, :] / [:, 1, :] of
    # a (n/2s, 2, s) reshape; the merge direction alternates per
    # `size`-block, derived from a broadcasted iota (no dynamic
    # indexing anywhere — the shape Mosaic should accept).
    size = 2
    while size <= n:
        stride = size // 2
        while stride >= 1:
            y = x.reshape(-1, 2, stride)
            a, b = y[:, 0, :], y[:, 1, :]
            blk = jax.lax.broadcasted_iota(jnp.int32, (y.shape[0], 1), 0)
            asc = ((blk * (2 * stride)) // size) % 2 == 0
            lo = jnp.where(asc, jnp.minimum(a, b), jnp.maximum(a, b))
            hi = jnp.where(asc, jnp.maximum(a, b), jnp.minimum(a, b))
            x = jnp.stack([lo, hi], axis=1).reshape(-1)
            stride //= 2
        size *= 2
    o_ref[...] = x.reshape(k_ref.shape)


@functools.partial(jax.jit, static_argnums=(1,))
def sort_pallas(keys, interpret: bool = False):
    """Grid-free Pallas bitonic sort: the whole key array resident in
    VMEM (the caller bounds it — 2^16 f32 keys is 256KB), length must
    be a power of two >= 256 (the bench pads; engine batches are
    power-of-two capacities anyway)."""
    from jax.experimental import pallas as pl
    n = keys.shape[0]
    if n < 256 or n & (n - 1):
        raise ValueError(f"sort_pallas needs a power-of-two length "
                         f">= 256, got {n}")
    k2 = keys.reshape(-1, _LANES)
    call = pl.pallas_call(
        _sort_kernel,
        out_shape=jax.ShapeDtypeStruct(k2.shape, keys.dtype),
        interpret=interpret)
    return call(k2).reshape(-1)


# --- fused filter+partial-agg A/B: the whole-stage-fusion shape -------------
# PR "scan-rooted whole-stage fusion" moved the from-files hot loop to ONE
# XLA program per batch doing decode -> filter -> project -> partial-agg.
# The open Pallas question AT THE FUSED LEVEL (ISSUE 15c): does a hand
# kernel beat the fused XLA chain on the chain's own shape — filter
# conjuncts + product + GROUPED partial reduction in one VMEM pass —
# rather than the global reduction masked_product_sum already measured?
# bench.py A/Bs `pallas_fused_agg_ab` beside `pallas_sort_ab`, with the
# same falsifiability contract: only a compile/lowering failure may claim
# "mosaic-rejected"; a successful compile with wrong values must surface
# as WRONG-RESULT, never as a no-win.

FUSED_AGG_GROUPS = 8  # static group count: a partial-agg keyspace slice


def fused_filter_agg_xla(key, quantity, price, discount, shipdate):
    """The fused chain as the engine's XLA path sees it: q6's filter
    conjuncts, the price*discount projection, and a grouped partial sum
    over a small static keyspace (the segment-reduce shape the
    partial-agg tail lowers to; static one-hot per group — no scatter,
    matching the engine's gather/sort-only idiom). Returns float32
    per-group sums of shape (FUSED_AGG_GROUPS,)."""
    mask = ((shipdate >= 8766) & (shipdate < 9131)
            & (discount >= 0.05) & (discount <= 0.07)
            & (quantity < 24.0))
    vals = jnp.where(mask, price * discount, 0.0)
    return jnp.stack([
        jnp.sum(jnp.where(key == g, vals, 0.0), dtype=jnp.float32)
        for g in range(FUSED_AGG_GROUPS)])


def _fused_agg_kernel(k_ref, q_ref, p_ref, d_ref, s_ref, o_ref):
    k = k_ref[...]
    q = q_ref[...]
    p = p_ref[...]
    d = d_ref[...]
    s = s_ref[...]
    mask = ((s >= 8766) & (s < 9131) & (d >= 0.05) & (d <= 0.07)
            & (q < 24.0))
    vals = jnp.where(mask, p * d, 0.0)
    parts = []
    for g in range(FUSED_AGG_GROUPS):  # static keyspace: unrolled
        vg = jnp.where(k == g, vals, 0.0)
        # per-group (8, 128) min-tile partial — a (1, 1) accumulator is
        # below the f32 tile floor and fails Mosaic (see _kernel above)
        parts.append(jnp.sum(vg.reshape(-1, 8, _LANES), axis=0,
                             dtype=jnp.float32))
    o_ref[...] = jnp.concatenate(parts, axis=0)  # (GROUPS*8, 128)


@functools.partial(jax.jit, static_argnums=(5,))
def fused_filter_agg_pallas(key, quantity, price, discount, shipdate,
                            interpret: bool = False):
    """Pallas edition of the fused filter+partial-agg chain: grid-free
    chunked pallas_call like ``masked_product_sum_pallas`` (the remote
    compiler rejects gridded Mosaic kernels), each chunk emitting one
    (GROUPS*8, 128) partial block reduced outside. Row count must be a
    multiple of _TILE_ROWS*_LANES (the bench pads). Returns float32
    per-group sums of shape (FUSED_AGG_GROUPS,)."""
    from jax.experimental import pallas as pl
    n = quantity.shape[0]
    rows = n // _LANES
    chunks = rows // _TILE_ROWS
    call = pl.pallas_call(
        _fused_agg_kernel,
        out_shape=jax.ShapeDtypeStruct((FUSED_AGG_GROUPS * 8, _LANES),
                                       jnp.float32),
        interpret=interpret)
    parts = []
    shape2d = (_TILE_ROWS, _LANES)
    for c in range(chunks):
        lo = c * _TILE_ROWS * _LANES
        hi = lo + _TILE_ROWS * _LANES
        parts.append(call(key[lo:hi].reshape(shape2d),
                          quantity[lo:hi].reshape(shape2d),
                          price[lo:hi].reshape(shape2d),
                          discount[lo:hi].reshape(shape2d),
                          shipdate[lo:hi].reshape(shape2d)))
    stacked = jnp.stack(parts)  # (chunks, GROUPS*8, 128)
    return jnp.sum(
        stacked.reshape(len(parts), FUSED_AGG_GROUPS, 8, _LANES),
        axis=(0, 2, 3), dtype=jnp.float32)


@functools.partial(jax.jit, static_argnums=(2,))
def gather_pallas(table, idx, interpret: bool = False):
    """Grid-free Pallas gather: the whole table resident in VMEM (the
    caller bounds it), indices in (R, 128) chunks. idx length must be a
    multiple of _G_ROWS*_LANES (the A/B caller pads; a silent truncation
    here would corrupt any future engine use)."""
    from jax.experimental import pallas as pl
    n = idx.shape[0]
    chunk = _G_ROWS * _LANES
    if n == 0 or n % chunk:
        raise ValueError(
            f"gather_pallas needs len(idx) % {chunk} == 0, got {n}")
    chunks = n // chunk
    t2 = table.reshape(-1, _LANES)
    call = pl.pallas_call(
        _gather_kernel,
        out_shape=jax.ShapeDtypeStruct((_G_ROWS, _LANES), table.dtype),
        interpret=interpret)
    parts = []
    for c in range(chunks):
        part = call(t2, idx[c * chunk:(c + 1) * chunk]
                    .reshape(_G_ROWS, _LANES).astype(jnp.int32))
        parts.append(part.reshape(-1))
    return jnp.concatenate(parts)
