"""Device regular expressions: a restricted-dialect transpiler.

TPU analog of the reference's regex transpiler (CUDA regex via cudf +
a Java->cudf dialect translator, SURVEY.md:175, 613-614; reference mount
empty). The supported dialect — literals, escapes (\\d \\w \\s and
upper-case negations), character classes with ranges/negation, `.`,
anchors `^`/`$`, quantifiers `* + ?` on single atoms, and top-level
alternation — covers the pattern shapes NDS-style queries use; anything
else reports unsupported and the expression stays on host (the same
partial-support contract the reference ships).

Compilation (host, per expression): each alternation branch of
single-char atoms becomes a Glushkov position automaton — position i's
character class, the follow relation (which positions may consume the
next byte), first sets (positions legal at a match start) and last sets
(positions completing a match). Branch automata union into one table
set, <= _MAX_STATES positions.

Simulation (device, per batch): byte-parallel over all rows in
lockstep — a `lax.while_loop` steps j through byte positions up to the
LIVE maximum length (dynamic trip count, static shapes — the
string-rank machinery's trick), each step doing an (n, S) x (S, S)
masked transition product (MXU-shaped) plus accept tests. Unanchored
search re-injects floating first-positions every step; `$`-anchored
accepts fire only at each row's last byte.

Byte semantics: matching is over UTF-8 BYTES. Patterns must be ASCII
(enforced); `.` matches any byte except \\n, so on non-ASCII input a
multi-byte character counts as several `.` positions — the documented
device-dialect divergence (the reference's cudf regex has analogous
incompat caveats).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["RegexUnsupported", "compile_pattern", "regex_match_device",
           "like_to_regex"]

_MAX_STATES = 48


class RegexUnsupported(Exception):
    """Pattern outside the device dialect — caller falls back to host."""


def _class_for_escape(ch: str) -> np.ndarray:
    m = np.zeros(256, bool)
    if ch == "d":
        m[ord("0"):ord("9") + 1] = True
    elif ch == "w":
        m[ord("0"):ord("9") + 1] = True
        m[ord("a"):ord("z") + 1] = True
        m[ord("A"):ord("Z") + 1] = True
        m[ord("_")] = True
    elif ch == "s":
        for c in " \t\n\r\f\v":
            m[ord(c)] = True
    elif ch in "DWS":
        m = ~_class_for_escape(ch.lower())
    elif ch == "n":
        m[ord("\n")] = True
    elif ch == "t":
        m[ord("\t")] = True
    elif ch == "r":
        m[ord("\r")] = True
    elif ch in ".^$*+?()[]{}|\\/-":
        m[ord(ch)] = True
    else:
        raise RegexUnsupported(f"escape \\{ch} not in device dialect")
    return m


def _parse_class(p: str, i: int) -> Tuple[np.ndarray, int]:
    """[...] starting at p[i] == '['; returns (256-bool mask, next i)."""
    i += 1
    neg = i < len(p) and p[i] == "^"
    if neg:
        i += 1
    m = np.zeros(256, bool)
    first = True
    while i < len(p) and (p[i] != "]" or first):
        first = False
        if p[i] == "\\":
            if i + 1 >= len(p):
                raise RegexUnsupported("dangling escape in class")
            sub = _class_for_escape(p[i + 1])
            m |= sub
            i += 2
            continue
        lo = p[i]
        if i + 2 < len(p) and p[i + 1] == "-" and p[i + 2] != "]":
            hi = p[i + 2]
            if ord(lo) > ord(hi):
                raise RegexUnsupported(f"bad range {lo}-{hi}")
            m[ord(lo):ord(hi) + 1] = True
            i += 3
        else:
            m[ord(lo)] = True
            i += 1
    if i >= len(p):
        raise RegexUnsupported("unterminated character class")
    i += 1  # skip ]
    return (~m if neg else m), i


def _parse_branch(branch: str):
    """-> (anchored_start, anchored_end, [(class, quant)]).
    quant in '1?*+'."""
    i = 0
    anchored_start = branch.startswith("^")
    if anchored_start:
        i = 1
    anchored_end = branch.endswith("$") and not branch.endswith("\\$")
    end = len(branch) - 1 if anchored_end else len(branch)
    atoms: List[Tuple[np.ndarray, str]] = []
    while i < end:
        c = branch[i]
        if c in "(){":
            raise RegexUnsupported(f"'{c}' (groups/bounded repeats) not "
                                   "in device dialect")
        if c in "^$":
            raise RegexUnsupported("mid-pattern anchor")
        if c == "[":
            m, i = _parse_class(branch, i)
        elif c == "\\":
            if i + 1 >= end:
                raise RegexUnsupported("dangling escape")
            m = _class_for_escape(branch[i + 1])
            i += 2
        elif c == ".":
            m = np.ones(256, bool)
            m[ord("\n")] = False  # Java default: . excludes newline
            i += 1
        elif c in "*+?":
            raise RegexUnsupported("quantifier without atom")
        else:
            if ord(c) > 127:
                raise RegexUnsupported("non-ASCII pattern byte")
            m = np.zeros(256, bool)
            m[ord(c)] = True
            i += 1
        quant = "1"
        if i < end and branch[i] in "*+?":
            quant = branch[i]
            i += 1
            if i < end and branch[i] in "*+?":
                raise RegexUnsupported("stacked quantifiers")
        atoms.append((m, quant))
    return anchored_start, anchored_end, atoms


class RegexProgram:
    """Compiled position-automaton tables (numpy, embedded as constants
    into the device program)."""

    __slots__ = ("acc", "follow", "first_anchored", "first_floating",
                 "accept_any", "accept_end", "always_match",
                 "empty_only_match", "n_states")

    def __init__(self):
        self.n_states = 0
        self.acc = np.zeros((256, 0), bool)
        self.follow = np.zeros((0, 0), bool)
        self.first_anchored = np.zeros(0, bool)
        self.first_floating = np.zeros(0, bool)
        self.accept_any = np.zeros(0, bool)
        self.accept_end = np.zeros(0, bool)
        self.always_match = False     # matches every (non-null) string
        self.empty_only_match = False  # ^$-style: matches len==0 rows


def _split_alternation(p: str) -> List[str]:
    out, cur, i = [], [], 0
    depth = 0
    while i < len(p):
        c = p[i]
        if c == "\\":
            cur.append(p[i:i + 2])
            i += 2
            continue
        if c == "[":
            j = i + 1
            if j < len(p) and p[j] == "^":
                j += 1
            if j < len(p) and p[j] == "]":
                j += 1
            while j < len(p) and p[j] != "]":
                j += 2 if p[j] == "\\" else 1
            cur.append(p[i:j + 1])
            i = j + 1
            continue
        if c == "|" and depth == 0:
            out.append("".join(cur))
            cur = []
            i += 1
            continue
        cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def compile_pattern(pattern: str) -> RegexProgram:
    """Compile, or raise RegexUnsupported."""
    if any(ord(c) > 127 for c in pattern):
        raise RegexUnsupported("non-ASCII pattern")
    prog = RegexProgram()
    branches = [_parse_branch(b) for b in _split_alternation(pattern)]
    n = sum(len(atoms) for _, _, atoms in branches)
    if n > _MAX_STATES:
        raise RegexUnsupported(f"{n} positions > {_MAX_STATES}")
    prog.n_states = n
    prog.acc = np.zeros((256, n), bool)
    prog.follow = np.zeros((n, n), bool)
    prog.first_anchored = np.zeros(n, bool)
    prog.first_floating = np.zeros(n, bool)
    prog.accept_any = np.zeros(n, bool)
    prog.accept_end = np.zeros(n, bool)

    base = 0
    for a_start, a_end, atoms in branches:
        k = len(atoms)
        nullable = [q in "*?" for _, q in atoms]
        if k == 0 or all(nullable):
            # empty-matchable branch: unanchored/half-anchored search
            # always finds the empty match; fully anchored matches only
            # empty strings
            if a_start and a_end:
                prog.empty_only_match = True
            else:
                prog.always_match = True
        for i, (m, q) in enumerate(atoms):
            s = base + i
            prog.acc[:, s] = m
            # firsts: everything before i nullable
            if all(nullable[:i]):
                (prog.first_anchored if a_start
                 else prog.first_floating)[s] = True
            # lasts: everything after i nullable
            if all(nullable[i + 1:]):
                (prog.accept_end if a_end else prog.accept_any)[s] = True
            # follow: self-loop for * and +
            if q in "*+":
                prog.follow[s, s] = True
            # follow: j > i with the gap nullable
            for j in range(i + 1, k):
                if all(nullable[i + 1:j]):
                    prog.follow[s, base + j] = True
                if not nullable[j]:
                    break
        base += k
    return prog


def like_to_regex(pattern: str, escape: str = "\\") -> str:
    """SQL LIKE -> the device regex dialect, fully anchored. LIKE
    wildcards match ANY character including newlines (unlike regex `.`,
    which follows Java's no-DOTALL default), so % and _ translate to
    the all-bytes class [\\s\\S], not dot. Raises RegexUnsupported for
    non-ASCII."""
    out = ["^"]
    i = 0
    specials = ".^$*+?()[]{}|\\/"
    while i < len(pattern):
        c = pattern[i]
        if c == escape and i + 1 < len(pattern):
            nxt = pattern[i + 1]
            out.append("\\" + nxt if nxt in specials else nxt)
            i += 2
            continue
        if c == "%":
            out.append("[\\s\\S]*")
        elif c == "_":
            out.append("[\\s\\S]")
        elif c in specials:
            out.append("\\" + c)
        else:
            if ord(c) > 127:
                raise RegexUnsupported("non-ASCII LIKE pattern")
            out.append(c)
        i += 1
    out.append("$")
    return "".join(out)


def regex_match_device(col, prog: RegexProgram):
    """(n,) bool: does the compiled pattern match (search semantics)
    each row's bytes. Caller masks validity."""
    import jax
    import jax.numpy as jnp
    offs = col.offsets
    lens = (offs[1:] - offs[:-1]).astype(jnp.int32)
    n = lens.shape[0]
    ccap = max(col.chars.shape[0], 1)
    chars = col.chars if col.chars.shape[0] else jnp.zeros((1,), jnp.uint8)
    live_lens = jnp.where(col.validity, lens, 0)
    max_len = jnp.max(live_lens, initial=0)

    acc = jnp.asarray(prog.acc)                    # (256, S)
    follow = jnp.asarray(prog.follow, jnp.float32)  # (S, S) for the MXU
    first_a = jnp.asarray(prog.first_anchored)
    first_f = jnp.asarray(prog.first_floating)
    accept_any = jnp.asarray(prog.accept_any)
    accept_end = jnp.asarray(prog.accept_end)

    matched0 = jnp.full((n,), bool(prog.always_match))
    if prog.empty_only_match:
        matched0 = matched0 | (lens == 0)
    active0 = jnp.broadcast_to(first_a | first_f,
                               (n, prog.n_states))

    def cond(state):
        j, active, matched = state
        # stop at the live max length, when no position can fire again
        # (fully-anchored patterns drain), or when every row matched
        return (j < max_len) & jnp.any(active) & jnp.any(~matched)

    def body(state):
        j, active, matched = state
        c = chars[jnp.clip(offs[:-1] + j, 0, ccap - 1)]
        in_row = j < live_lens
        fired = active & acc[c] & in_row[:, None]
        matched = matched | jnp.any(fired & accept_any, axis=1)
        at_end = (j == live_lens - 1)
        matched = matched | (jnp.any(fired & accept_end, axis=1)
                             & at_end)
        nxt = (fired.astype(jnp.float32) @ follow) > 0
        nxt = nxt | first_f[None, :]
        return j + 1, nxt, matched

    _, _, matched = jax.lax.while_loop(
        cond, body, (jnp.int32(0), active0, matched0))
    return matched
