"""Device regular expressions: a restricted-dialect transpiler.

TPU analog of the reference's regex transpiler (CUDA regex via cudf +
a Java->cudf dialect translator, SURVEY.md:175, 613-614; reference mount
empty). The supported dialect — literals, escapes (\\d \\w \\s and
upper-case negations), character classes with ranges/negation, `.`,
anchors `^`/`$`, quantifiers `* + ?` on single atoms, and top-level
alternation — covers the pattern shapes NDS-style queries use; anything
else reports unsupported and the expression stays on host (the same
partial-support contract the reference ships).

Compilation (host, per expression): each alternation branch of
single-char atoms becomes a Glushkov position automaton — position i's
character class, the follow relation (which positions may consume the
next byte), first sets (positions legal at a match start) and last sets
(positions completing a match). Branch automata union into one table
set, <= _MAX_STATES positions.

Simulation (device, per batch): byte-parallel over all rows in
lockstep — a `lax.while_loop` steps j through byte positions up to the
LIVE maximum length (dynamic trip count, static shapes — the
string-rank machinery's trick), each step doing an (n, S) x (S, S)
masked transition product (MXU-shaped) plus accept tests. Unanchored
search re-injects floating first-positions every step; `$`-anchored
accepts fire only at each row's last byte.

UTF-8 correctness (ADVICE r4 medium): patterns must be ASCII
(enforced), but DATA may be any UTF-8. Atoms that can match non-ASCII
characters — `.`, negated classes, negated escapes (\\D \\W \\S),
`[\\s\\S]` — compile into multi-position sub-automata matching one
WHOLE UTF-8 character (lead byte class + continuation chain for 2-, 3-
and 4-byte sequences), so 'é' LIKE '_' is true on device exactly as in
Spark. ASCII-only atoms stay single positions; the lockstep simulation
is unchanged (its cost scales with total positions).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["RegexUnsupported", "compile_pattern", "regex_match_device",
           "regex_find_spans_device", "compile_replace_pattern",
           "replace_program_supported", "like_to_regex"]

_MAX_STATES = 64


class RegexUnsupported(Exception):
    """Pattern outside the device dialect — caller falls back to host."""


def _class_for_escape(ch: str) -> np.ndarray:
    m = np.zeros(256, bool)
    if ch == "d":
        m[ord("0"):ord("9") + 1] = True
    elif ch == "w":
        m[ord("0"):ord("9") + 1] = True
        m[ord("a"):ord("z") + 1] = True
        m[ord("A"):ord("Z") + 1] = True
        m[ord("_")] = True
    elif ch == "s":
        for c in " \t\n\r\f\v":
            m[ord(c)] = True
    elif ch in "DWS":
        m = ~_class_for_escape(ch.lower())
    elif ch == "n":
        m[ord("\n")] = True
    elif ch == "t":
        m[ord("\t")] = True
    elif ch == "r":
        m[ord("\r")] = True
    elif ch in ".^$*+?()[]{}|\\/-":
        m[ord(ch)] = True
    else:
        raise RegexUnsupported(f"escape \\{ch} not in device dialect")
    return m


def _parse_class(p: str, i: int) -> Tuple[np.ndarray, int]:
    """[...] starting at p[i] == '['; returns (256-bool mask, next i)."""
    i += 1
    neg = i < len(p) and p[i] == "^"
    if neg:
        i += 1
    m = np.zeros(256, bool)
    first = True
    while i < len(p) and (p[i] != "]" or first):
        first = False
        if p[i] == "\\":
            if i + 1 >= len(p):
                raise RegexUnsupported("dangling escape in class")
            sub = _class_for_escape(p[i + 1])
            m |= sub
            i += 2
            continue
        lo = p[i]
        if i + 2 < len(p) and p[i + 1] == "-" and p[i + 2] != "]":
            hi = p[i + 2]
            if ord(lo) > ord(hi):
                raise RegexUnsupported(f"bad range {lo}-{hi}")
            m[ord(lo):ord(hi) + 1] = True
            i += 3
        else:
            m[ord(lo)] = True
            i += 1
    if i >= len(p):
        raise RegexUnsupported("unterminated character class")
    i += 1  # skip ]
    return (~m if neg else m), i


def _parse_branch(branch: str):
    """-> (anchored_start, anchored_end, [(class, quant)]).
    quant in '1?*+'."""
    i = 0
    anchored_start = branch.startswith("^")
    if anchored_start:
        i = 1
    anchored_end = branch.endswith("$") and not branch.endswith("\\$")
    end = len(branch) - 1 if anchored_end else len(branch)
    atoms: List[Tuple[np.ndarray, str]] = []
    while i < end:
        c = branch[i]
        if c in "(){":
            raise RegexUnsupported(f"'{c}' (groups/bounded repeats) not "
                                   "in device dialect")
        if c in "^$":
            raise RegexUnsupported("mid-pattern anchor")
        if c == "[":
            m, i = _parse_class(branch, i)
        elif c == "\\":
            if i + 1 >= end:
                raise RegexUnsupported("dangling escape")
            m = _class_for_escape(branch[i + 1])
            i += 2
        elif c == ".":
            m = np.ones(256, bool)
            m[ord("\n")] = False  # Java default: . excludes newline
            i += 1
        elif c in "*+?":
            raise RegexUnsupported("quantifier without atom")
        else:
            if ord(c) > 127:
                raise RegexUnsupported("non-ASCII pattern byte")
            m = np.zeros(256, bool)
            m[ord(c)] = True
            i += 1
        quant = "1"
        if i < end and branch[i] in "*+?":
            quant = branch[i]
            i += 1
            if i < end and branch[i] in "*+?":
                raise RegexUnsupported("stacked quantifiers")
        atoms.append((m, quant))
    return anchored_start, anchored_end, atoms


class RegexProgram:
    """Compiled position-automaton tables (numpy, embedded as constants
    into the device program)."""

    __slots__ = ("acc", "follow", "first_anchored", "first_floating",
                 "accept_any", "accept_end", "always_match",
                 "empty_only_match", "n_states", "min_len")

    def __init__(self):
        self.n_states = 0
        self.acc = np.zeros((256, 0), bool)
        self.follow = np.zeros((0, 0), bool)
        self.first_anchored = np.zeros(0, bool)
        self.first_floating = np.zeros(0, bool)
        self.accept_any = np.zeros(0, bool)
        self.accept_end = np.zeros(0, bool)
        self.always_match = False     # matches every (non-null) string
        self.empty_only_match = False  # ^$-style: matches len==0 rows
        self.min_len = 1  # minimal match width in bytes (replace sizing)


def _split_alternation(p: str) -> List[str]:
    out, cur, i = [], [], 0
    depth = 0
    while i < len(p):
        c = p[i]
        if c == "\\":
            cur.append(p[i:i + 2])
            i += 2
            continue
        if c == "[":
            j = i + 1
            if j < len(p) and p[j] == "^":
                j += 1
            if j < len(p) and p[j] == "]":
                j += 1
            while j < len(p) and p[j] != "]":
                j += 2 if p[j] == "\\" else 1
            cur.append(p[i:j + 1])
            i = j + 1
            continue
        if c == "|" and depth == 0:
            out.append("".join(cur))
            cur = []
            i += 1
            continue
        cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


class _Fragment:
    """One atom's position-automaton fragment. ASCII-only atoms are a
    single position; atoms that can match non-ASCII characters expand
    into a UTF-8 character automaton (one position per byte of each
    encoding length), so device matching is per CHARACTER, not per byte
    (ADVICE r4 medium)."""

    __slots__ = ("masks", "first", "last", "follow")

    def __init__(self, masks, first, last, follow):
        self.masks = masks      # List[np.ndarray(256, bool)] per position
        self.first = first      # local position indices legal at start
        self.last = last        # local position indices completing it
        self.follow = follow    # local (i, j) internal byte transitions


_CONT = np.zeros(256, bool)
_CONT[0x80:0xC0] = True
_LEAD2 = np.zeros(256, bool)
_LEAD2[0xC2:0xE0] = True
_LEAD3 = np.zeros(256, bool)
_LEAD3[0xE0:0xF0] = True
_LEAD4 = np.zeros(256, bool)
_LEAD4[0xF0:0xF5] = True


def _atom_fragment(m: np.ndarray) -> _Fragment:
    """Class mask -> fragment. A mask with any char >= 0x80 means the
    atom matches non-ASCII CHARACTERS (an ASCII pattern can only say
    "all of them" — via `.`, negation, \\D \\W \\S or [\\s\\S]), so the
    multi-byte branches join the automaton."""
    if not m[128:].any():
        return _Fragment([m], [0], [0], [])
    ascii_m = m.copy()
    ascii_m[128:] = False
    masks, first, last, follow = [], [], [], []
    if ascii_m.any():
        masks.append(ascii_m)
        first.append(0)
        last.append(0)
    b = len(masks)
    masks += [_LEAD2, _CONT]                    # 2-byte sequence
    first.append(b)
    last.append(b + 1)
    follow.append((b, b + 1))
    b = len(masks)
    masks += [_LEAD3, _CONT, _CONT]             # 3-byte sequence
    first.append(b)
    last.append(b + 2)
    follow += [(b, b + 1), (b + 1, b + 2)]
    b = len(masks)
    masks += [_LEAD4, _CONT, _CONT, _CONT]      # 4-byte sequence
    first.append(b)
    last.append(b + 3)
    follow += [(b, b + 1), (b + 1, b + 2), (b + 2, b + 3)]
    return _Fragment(masks, first, last, follow)


def compile_pattern(pattern: str) -> RegexProgram:
    """Compile, or raise RegexUnsupported."""
    if any(ord(c) > 127 for c in pattern):
        raise RegexUnsupported("non-ASCII pattern")
    prog = RegexProgram()
    branches = [_parse_branch(b) for b in _split_alternation(pattern)]
    frag_branches = []
    n = 0
    for a_start, a_end, atoms in branches:
        frags = [( _atom_fragment(m), q) for m, q in atoms]
        n += sum(len(f.masks) for f, _ in frags)
        frag_branches.append((a_start, a_end, frags))
    if n > _MAX_STATES:
        raise RegexUnsupported(f"{n} positions > {_MAX_STATES}")
    prog.n_states = n
    prog.acc = np.zeros((256, n), bool)
    prog.follow = np.zeros((n, n), bool)
    prog.first_anchored = np.zeros(n, bool)
    prog.first_floating = np.zeros(n, bool)
    prog.accept_any = np.zeros(n, bool)
    prog.accept_end = np.zeros(n, bool)

    base = 0
    branch_min = []
    for a_start, a_end, frags in frag_branches:
        branch_min.append(sum(1 for _, q in frags if q not in "*?"))
        k = len(frags)
        nullable = [q in "*?" for _, q in frags]
        if k == 0 or all(nullable):
            # empty-matchable branch: unanchored/half-anchored search
            # always finds the empty match; fully anchored matches only
            # empty strings
            if a_start and a_end:
                prog.empty_only_match = True
            else:
                prog.always_match = True
        # global position index of each fragment's start
        starts = []
        b = base
        for f, _ in frags:
            starts.append(b)
            b += len(f.masks)
        for i, (f, q) in enumerate(frags):
            s0 = starts[i]
            for p, m in enumerate(f.masks):
                prog.acc[:, s0 + p] = m
            for (p, r) in f.follow:
                prog.follow[s0 + p, s0 + r] = True
            # firsts: everything before i nullable
            if all(nullable[:i]):
                tgt = prog.first_anchored if a_start \
                    else prog.first_floating
                for p in f.first:
                    tgt[s0 + p] = True
            # lasts: everything after i nullable
            if all(nullable[i + 1:]):
                tgt = prog.accept_end if a_end else prog.accept_any
                for p in f.last:
                    tgt[s0 + p] = True
            # repetition: * and + loop last -> first
            if q in "*+":
                for p in f.last:
                    for r in f.first:
                        prog.follow[s0 + p, s0 + r] = True
            # cross-fragment follow: j > i with the gap nullable
            for j in range(i + 1, k):
                if all(nullable[i + 1:j]):
                    fj = frags[j][0]
                    for p in f.last:
                        for r in fj.first:
                            prog.follow[s0 + p, starts[j] + r] = True
                if not nullable[j]:
                    break
        base = b
    prog.min_len = max(1, min(branch_min) if branch_min else 1)
    return prog


def like_to_regex(pattern: str, escape: str = "\\") -> str:
    """SQL LIKE -> the device regex dialect, fully anchored. LIKE
    wildcards match ANY character including newlines (unlike regex `.`,
    which follows Java's no-DOTALL default), so % and _ translate to
    the all-bytes class [\\s\\S], not dot. Raises RegexUnsupported for
    non-ASCII."""
    out = ["^"]
    i = 0
    specials = ".^$*+?()[]{}|\\/"
    while i < len(pattern):
        c = pattern[i]
        if c == escape and i + 1 < len(pattern):
            nxt = pattern[i + 1]
            out.append("\\" + nxt if nxt in specials else nxt)
            i += 2
            continue
        if c == "%":
            out.append("[\\s\\S]*")
        elif c == "_":
            out.append("[\\s\\S]")
        elif c in specials:
            out.append("\\" + c)
        else:
            if ord(c) > 127:
                raise RegexUnsupported("non-ASCII LIKE pattern")
            out.append(c)
        i += 1
    out.append("$")
    return "".join(out)


def regex_match_device(col, prog: RegexProgram):
    """(n,) bool: does the compiled pattern match (search semantics)
    each row's bytes. Caller masks validity."""
    import jax
    import jax.numpy as jnp
    offs = col.offsets
    lens = (offs[1:] - offs[:-1]).astype(jnp.int32)
    n = lens.shape[0]
    ccap = max(col.chars.shape[0], 1)
    chars = col.chars if col.chars.shape[0] else jnp.zeros((1,), jnp.uint8)
    live_lens = jnp.where(col.validity, lens, 0)
    max_len = jnp.max(live_lens, initial=0)

    acc = jnp.asarray(prog.acc)                    # (256, S)
    follow = jnp.asarray(prog.follow, jnp.float32)  # (S, S) for the MXU
    first_a = jnp.asarray(prog.first_anchored)
    first_f = jnp.asarray(prog.first_floating)
    accept_any = jnp.asarray(prog.accept_any)
    accept_end = jnp.asarray(prog.accept_end)

    matched0 = jnp.full((n,), bool(prog.always_match))
    if prog.empty_only_match:
        matched0 = matched0 | (lens == 0)
    active0 = jnp.broadcast_to(first_a | first_f,
                               (n, prog.n_states))

    def cond(state):
        j, active, matched = state
        # stop at the live max length, when no position can fire again
        # (fully-anchored patterns drain), or when every row matched
        return (j < max_len) & jnp.any(active) & jnp.any(~matched)

    def body(state):
        j, active, matched = state
        c = chars[jnp.clip(offs[:-1] + j, 0, ccap - 1)]
        in_row = j < live_lens
        fired = active & acc[c] & in_row[:, None]
        matched = matched | jnp.any(fired & accept_any, axis=1)
        at_end = (j == live_lens - 1)
        matched = matched | (jnp.any(fired & accept_end, axis=1)
                             & at_end)
        nxt = (fired.astype(jnp.float32) @ follow) > 0
        nxt = nxt | first_f[None, :]
        return j + 1, nxt, matched

    _, _, matched = jax.lax.while_loop(
        cond, body, (jnp.int32(0), active0, matched0))
    return matched


# --- match POSITIONS: spans for regexp_replace / regexp_extract ------------
#
# VERDICT r4 #7: the automaton above answers accept/reject; replace and
# extract need WHERE. Two phases, both lockstep over all rows:
#
#   1. a BACKWARD boolean pass of the automaton against the follow
#      relation transposed marks, per byte position i, whether some
#      match STARTS at i (reachability of an accept reading s[i..]) —
#      an (n, S) x (S, S) matmul per byte, the same MXU shape as the
#      forward matcher;
#   2. ONE forward walk advances every row's cursor a byte per step:
#      scanning rows look for the next marked start (greedy leftmost),
#      matching rows run the ANCHORED automaton recording the last
#      accept (greedy longest); when a row's active set dies its span
#      [start, last_accept) is committed and the cursor rewinds to the
#      span end (non-overlapping, Java's continue-after-match).
#
# Leftmost-LONGEST equals Java's leftmost-greedy for the supported
# dialect RESTRICTED to a single branch (alternation is leftmost-FIRST
# in Java — 'a|ab' on "ab" picks 'a' — so multi-branch patterns fall
# back to host). Patterns that can match empty also fall back (Java
# emits empty matches at every position; the span machinery assumes
# width >= 1).


def compile_replace_pattern(pattern: str):
    """(program, None) when find-spans semantics are exact for this
    pattern, else (None, reason) — one compilation, reused by the
    caller."""
    try:
        prog = compile_pattern(pattern)
    except RegexUnsupported as e:
        return None, str(e)
    if len(_split_alternation(pattern)) > 1:
        return None, ("alternation is leftmost-first in Java but "
                      "leftmost-longest on device; runs on host")
    if prog.always_match or prog.empty_only_match:
        return None, "pattern can match the empty string; runs on host"
    return prog, None


def replace_program_supported(pattern: str) -> Optional[str]:
    """None when find-spans semantics are exact for this pattern, else
    the fallback reason."""
    return compile_replace_pattern(pattern)[1]


def regex_find_spans_device(col, prog: RegexProgram,
                            first_only: bool = False):
    """Per-row non-overlapping leftmost-longest match spans.

    Returns (in_match, match_start, n_matches, first_s, first_e): flat
    bool masks over the chars lane (byte is inside a span / starts a
    span), the per-row span count, and each row's FIRST span as
    row-relative [first_s, first_e) (-1/-1 when none — regexp_extract's
    answer). With first_only, each row stops after its first span."""
    import jax
    import jax.numpy as jnp
    offs = col.offsets
    n = offs.shape[0] - 1
    lens = (offs[1:] - offs[:-1]).astype(jnp.int32)
    live_lens = jnp.where(col.validity, lens, 0)
    ccap = max(col.chars.shape[0], 1)
    chars = col.chars if col.chars.shape[0] else jnp.zeros((1,), jnp.uint8)
    max_len = jnp.max(live_lens, initial=0)
    S = prog.n_states

    acc = jnp.asarray(prog.acc)                          # (256, S)
    follow_t = jnp.asarray(prog.follow.T, jnp.float32)   # backward
    follow = jnp.asarray(prog.follow, jnp.float32)
    first = jnp.asarray(prog.first_anchored | prog.first_floating)
    anchored_start = bool(prog.first_anchored.any()) \
        and not prog.first_floating.any()
    accept_any = jnp.asarray(prog.accept_any)
    accept_end = jnp.asarray(prog.accept_end)

    # ---- phase 1: backward start-reachability ---------------------------
    # R[j] = states that, consuming s[j], can begin a suffix reaching an
    # accept. A match starts at j iff first ∩ R[j] != 0.
    def bcond(state):
        j, _, _ = state
        return j >= 0

    # start marks live on the FLAT chars lane (starts_flat[offs[r]+j]):
    # a (n, max_len) matrix would be dynamically shaped
    starts_flat = jnp.zeros((ccap,), jnp.bool_)

    def bbody_flat(state):
        j, R_next, starts_flat = state
        pos = jnp.clip(offs[:-1] + j, 0, ccap - 1)
        c = chars[pos]
        in_row = j < live_lens
        at_last = j == live_lens - 1
        acc_here = (accept_any[None, :]
                    | (accept_end[None, :] & at_last[:, None]))
        can_continue = (R_next.astype(jnp.float32) @ follow_t) > 0
        R = acc[c] & in_row[:, None] & (acc_here | can_continue)
        hit = jnp.any(R & first[None, :], axis=1) & in_row
        if anchored_start:
            hit = hit & (j == 0)
        # inactive rows scatter to the drop sentinel, NOT a stale
        # write-back of the old value: duplicate flat indices (empty
        # rows share pos with their neighbor) are implementation-
        # defined order on TPU and the stale False could win
        starts_flat = starts_flat.at[
            jnp.where(in_row & hit, pos, ccap)].set(True, mode="drop")
        return j - 1, R, starts_flat

    _, _, starts_flat = jax.lax.while_loop(
        bcond, bbody_flat,
        (max_len - 1, jnp.zeros((n, S), jnp.bool_), starts_flat))

    # ---- phase 2: greedy forward span walk ------------------------------

    def fcond(state):
        j = state[0]
        return jnp.any(j < live_lens)

    def fbody(state):
        (j, matching, mstart, last_end, active, in_match, match_start,
         nmatches, done, first_s, first_e) = state
        pos = jnp.clip(offs[:-1] + j, 0, ccap - 1)
        c = chars[pos]
        in_row = (j < live_lens) & ~done
        start_here = starts_flat[pos] & in_row & ~matching
        # begin a span: anchored automaton from this byte
        active = jnp.where(start_here[:, None], first[None, :], active)
        matching2 = matching | start_here
        mstart = jnp.where(start_here, j, mstart)
        last_end = jnp.where(start_here, -1, last_end)
        # consume byte j for matching rows
        fired = active & acc[c] & (matching2 & in_row)[:, None]
        at_last = j == live_lens - 1
        accepts = fired & (accept_any[None, :]
                           | (accept_end[None, :] & at_last[:, None]))
        acc_fired = jnp.any(accepts, axis=1)
        last_end = jnp.where(matching2 & acc_fired, j + 1, last_end)
        nxt = (fired.astype(jnp.float32) @ follow) > 0
        alive = jnp.any(nxt, axis=1) & (j + 1 < live_lens)
        # a span commits when the thread dies (or the row ends)
        commit = matching2 & in_row & ~alive
        have = commit & (last_end > mstart)
        # mark the span's bytes [mstart, last_end) — bounded per-step
        # work: one segment write via the cumulative trick below, done
        # lazily by recording span edges in the masks
        span_pos = jnp.clip(offs[:-1] + mstart, 0, ccap - 1)
        match_start = match_start.at[
            jnp.where(have, span_pos, ccap)].set(True, mode="drop")
        end_pos = jnp.clip(offs[:-1] + last_end, 0, ccap - 1)
        # record end edge into in_match as a +1/-1 prefix encoding:
        # in_match here is an int8 DELTA lane, decoded after the loop
        in_match = in_match.at[span_pos].add(
            jnp.where(have, 1, 0).astype(jnp.int8))
        in_match = in_match.at[end_pos].add(
            jnp.where(have & (last_end < lens), -1, 0).astype(jnp.int8))
        # row-end deltas for spans touching the last byte are implicit:
        # the prefix decode is segmented per row, so no -1 is needed
        # when end == len
        is_first = have & (nmatches == 0)
        first_s = jnp.where(is_first, mstart, first_s)
        first_e = jnp.where(is_first, last_end, first_e)
        nmatches = nmatches + have.astype(jnp.int32)
        done = done | (first_only & have)
        # advance: matching rows that committed rewind to the span end
        # (or +1 past a failed start); everything else one byte forward
        j_next = jnp.where(
            in_row & commit, jnp.where(have, last_end, mstart + 1),
            j + 1)
        matching3 = matching2 & ~commit
        return (j_next.astype(jnp.int32), matching3, mstart, last_end,
                nxt, in_match, match_start, nmatches, done, first_s,
                first_e)

    j0 = jnp.zeros((n,), jnp.int32)
    state = (j0, jnp.zeros((n,), jnp.bool_), jnp.zeros((n,), jnp.int32),
             jnp.full((n,), -1, jnp.int32), jnp.zeros((n, S), jnp.bool_),
             jnp.zeros((ccap,), jnp.int8), jnp.zeros((ccap,), jnp.bool_),
             jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.bool_),
             jnp.full((n,), -1, jnp.int32), jnp.full((n,), -1, jnp.int32))
    state = jax.lax.while_loop(fcond, fbody, state)
    delta, match_start, nmatches = state[5], state[6], state[7]
    first_s, first_e = state[9], state[10]
    # segmented prefix decode of the +1/-1 edges -> in-span mask
    cum = jnp.cumsum(delta.astype(jnp.int32))
    row_of = jnp.clip(jnp.searchsorted(offs, jnp.arange(ccap,
                                                        dtype=jnp.int32),
                                       side="right") - 1, 0, n - 1)
    row_base = cum[jnp.clip(offs[:-1][row_of], 0, ccap - 1)] \
        - delta.astype(jnp.int32)[jnp.clip(offs[:-1][row_of], 0,
                                           ccap - 1)]
    in_match = (cum - row_base) > 0
    return in_match, match_start, nmatches, first_s, first_e


def regex_replace_device(col, prog: RegexProgram, repl: bytes,
                         char_cap: int):
    """replaceAll: every non-overlapping leftmost-longest span replaced
    by the literal `repl`. Returns a string TpuColumnVector with
    char capacity `char_cap` (caller sizes via replace_char_cap)."""
    import jax.numpy as jnp
    from ..columnar.column import TpuColumnVector
    in_match, mstart, _, _, _ = regex_find_spans_device(col, prog)
    offs = col.offsets
    n = offs.shape[0] - 1
    ccap = max(col.chars.shape[0], 1)
    chars = col.chars if col.chars.shape[0] else jnp.zeros((1,), jnp.uint8)
    Lr = len(repl)
    contrib = jnp.where(~in_match, 1,
                        jnp.where(mstart, Lr, 0)).astype(jnp.int32)
    # clamp contributions to live bytes
    i = jnp.arange(ccap, dtype=jnp.int32)
    row_of = jnp.clip(jnp.searchsorted(offs, i, side="right") - 1,
                      0, n - 1)
    in_any_row = (i >= offs[:-1][row_of]) & (i < offs[1:][row_of])
    contrib = jnp.where(in_any_row, contrib, 0)
    out_off = jnp.cumsum(contrib) - contrib  # exclusive
    total = jnp.sum(contrib)
    # per-row output offsets: exclusive cumsum at row starts + total.
    # A row whose start offset EQUALS the chars capacity (total chars
    # landed exactly on the bucket boundary) must map to `total`, not
    # to the clipped last slot (which would steal the preceding row's
    # final output byte — code-review r5)
    row_start_out = jnp.where(
        offs[:-1] >= ccap, total,
        out_off[jnp.clip(offs[:-1], 0, ccap - 1)])
    new_offsets = jnp.concatenate(
        [row_start_out.astype(jnp.int32), total[None].astype(jnp.int32)])
    out = jnp.zeros((char_cap,), jnp.uint8)
    keep = ~in_match & in_any_row
    dst = jnp.where(keep, out_off, char_cap)
    out = out.at[dst].set(chars, mode="drop")
    if Lr:
        rep = jnp.asarray(np.frombuffer(repl, np.uint8))
        start_dst = jnp.where(mstart & in_any_row, out_off, char_cap)
        for k in range(Lr):
            out = out.at[jnp.where(start_dst < char_cap, start_dst + k,
                                   char_cap)].set(rep[k], mode="drop")
    return TpuColumnVector(col.dtype, validity=col.validity,
                           offsets=new_offsets, chars=out)


def replace_char_cap(col, prog: RegexProgram, repl_len: int) -> int:
    """Static output char bound for replace: unmatched bytes plus
    repl_len per match, matches bounded by chars/min_len."""
    from ..columnar.batch import bucket_bytes
    ccap = max(int(col.chars.shape[0]), 1)
    bound = ccap + (ccap // max(prog.min_len, 1)) * repl_len + 16
    return bucket_bytes(bound)


def regex_extract_device(col, prog: RegexProgram):
    """regexp_extract group-0: each row's FIRST span as a string column
    ('' when no match, null propagates)."""
    import jax.numpy as jnp
    from ..columnar.column import TpuColumnVector
    _, _, _, first_s, first_e = regex_find_spans_device(col, prog,
                                                        first_only=True)
    offs = col.offsets
    n = offs.shape[0] - 1
    ccap = max(col.chars.shape[0], 1)
    chars = col.chars if col.chars.shape[0] else jnp.zeros((1,), jnp.uint8)
    have = first_e > first_s
    out_len = jnp.where(have, first_e - first_s, 0).astype(jnp.int32)
    new_offsets = jnp.concatenate([
        jnp.zeros((1,), jnp.int32), jnp.cumsum(out_len)]).astype(jnp.int32)
    char_cap = ccap  # extraction never grows
    i = jnp.arange(char_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_offsets, i, side="right") - 1,
                   0, n - 1)
    src = offs[:-1][row] + first_s[row] + (i - new_offsets[:-1][row])
    live = i < new_offsets[-1]
    out = jnp.where(live, chars[jnp.clip(src, 0, ccap - 1)], 0)
    return TpuColumnVector(col.dtype, validity=col.validity,
                           offsets=new_offsets,
                           chars=out.astype(jnp.uint8))
