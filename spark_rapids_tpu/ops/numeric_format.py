"""Device-side numeric -> string formatting kernels.

cudf has dedicated kernels for this (SURVEY.md §2.2-E); on TPU we generate
digit bytes with vectorized integer arithmetic into fixed-width per-row
windows, then compact to ragged Arrow layout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import datatypes as dt
from ..columnar.column import TpuColumnVector

__all__ = ["int_to_string_tpu", "bool_to_string_tpu", "date_to_string_tpu",
           "timestamp_to_string_tpu", "decimal_to_string_tpu",
           "ragged_from_fixed"]

_MAX_I64_DIGITS = 19


def ragged_from_fixed(bytes_mat: jax.Array, lens: jax.Array,
                      validity: jax.Array,
                      dtype=dt.STRING) -> TpuColumnVector:
    """(n, W) byte matrix + per-row lengths -> ragged string column.

    Rows are left-aligned in the window. Char capacity = n*W (static)."""
    n, w = bytes_mat.shape
    lens = lens.astype(jnp.int32)
    offsets = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               jnp.cumsum(lens, dtype=jnp.int32)])
    char_cap = n * w
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    in_range = pos < lens[:, None]
    dst = jnp.where(in_range, offsets[:-1][:, None] + pos, char_cap)
    out = jnp.zeros((char_cap,), jnp.uint8)
    out = out.at[dst.reshape(-1)].set(bytes_mat.reshape(-1).astype(jnp.uint8),
                                      mode="drop")
    return TpuColumnVector(dtype, validity=validity, offsets=offsets,
                           chars=out)


def _digits_mat(absval: jax.Array, width: int):
    """(n, width) digit matrix, most significant first, and digit count."""
    powers = jnp.asarray([10 ** (width - 1 - i) for i in range(width)],
                         dtype=jnp.int64)[None, :]
    v = absval.astype(jnp.int64)[:, None]
    digs = (v // powers) % 10
    # exact digit count via integer thresholds (float log10 is unsafe on
    # TPU where f64 computes as f32)
    thresholds = jnp.asarray([10 ** k for k in range(1, width)],
                             dtype=jnp.int64)[None, :]
    ndig = 1 + jnp.sum(absval.astype(jnp.int64)[:, None] >= thresholds,
                       axis=1).astype(jnp.int32)
    return digs, ndig


def int_to_string_tpu(col: TpuColumnVector) -> TpuColumnVector:
    """Java Long.toString for any integral lane."""
    v = col.data.astype(jnp.int64)
    neg = v < 0
    # abs(INT64_MIN) overflows int64; compute |v| as (|v|-1)+1 for negatives
    # and special-case INT64_MIN with its literal below.
    absv = jnp.where(neg, -(v + 1), v)  # = |v|-1 for negatives, no overflow
    adj = jnp.where(neg, 1, 0)
    # digits of absv+adj without overflow: absv <= i64max-1 so +1 safe? only
    # for min: -(min+1) = max, +1 overflows. Special-case min below.
    is_min = v == jnp.int64(-(2**63))
    safe_abs = jnp.where(is_min, 0, absv + adj)
    width = _MAX_I64_DIGITS
    digs, ndig = _digits_mat(safe_abs, width)
    lens = ndig + neg.astype(jnp.int32)
    total_w = width + 1  # sign slot
    # layout: optional '-', then digits with leading zeros trimmed.
    posj = jnp.arange(total_w, dtype=jnp.int32)[None, :]
    digit_pos = posj - neg[:, None].astype(jnp.int32)  # 0..ndig-1
    src_idx = width - ndig[:, None] + digit_pos
    src_idx_c = jnp.clip(src_idx, 0, width - 1)
    dvals = jnp.take_along_axis(digs, src_idx_c.astype(jnp.int32), axis=1)
    bytes_ = (dvals + ord("0")).astype(jnp.uint8)
    bytes_ = jnp.where((posj == 0) & neg[:, None], ord("-"), bytes_)
    # INT64_MIN literal
    min_lit = np.frombuffer(b"-9223372036854775808", np.uint8)
    min_mat = jnp.zeros((total_w,), jnp.uint8).at[:20].set(
        jnp.asarray(min_lit))
    bytes_ = jnp.where(is_min[:, None], min_mat[None, :], bytes_)
    lens = jnp.where(is_min, 20, lens)
    return ragged_from_fixed(bytes_, lens, col.validity)


def bool_to_string_tpu(col: TpuColumnVector) -> TpuColumnVector:
    t = np.frombuffer(b"true\x00", np.uint8)
    f = np.frombuffer(b"false", np.uint8)
    mat = jnp.where(col.data[:, None],
                    jnp.asarray(t)[None, :], jnp.asarray(f)[None, :])
    lens = jnp.where(col.data, 4, 5).astype(jnp.int32)
    return ragged_from_fixed(mat, lens, col.validity)


def _civil_from_days(z):
    """Days-since-epoch -> (year, month, day). Hinnant's algorithm,
    branch-free integer ops (public-domain well-known algorithm)."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def date_to_string_tpu(col: TpuColumnVector) -> TpuColumnVector:
    """YYYY-MM-DD (Spark format for positive 4-digit years)."""
    y, m, d = _civil_from_days(col.data)
    n = col.data.shape[0]

    def dig(v, p):
        return ((v // p) % 10 + ord("0")).astype(jnp.uint8)

    cols = [dig(y, 1000), dig(y, 100), dig(y, 10), dig(y, 1),
            jnp.full((n,), ord("-"), jnp.uint8),
            dig(m, 10), dig(m, 1),
            jnp.full((n,), ord("-"), jnp.uint8),
            dig(d, 10), dig(d, 1)]
    mat = jnp.stack(cols, axis=1)
    lens = jnp.full((n,), 10, jnp.int32)
    return ragged_from_fixed(mat, lens, col.validity)


def timestamp_to_string_tpu(col: TpuColumnVector) -> TpuColumnVector:
    """us-since-epoch -> 'YYYY-MM-DD HH:MM:SS[.ffffff]' (UTC, Spark's
    cast format: fractional part only when nonzero, trailing zeros
    trimmed) — closes the last hot-path to-string hole on device
    (VERDICT r4 weak #4). Years are formatted with exactly four digits:
    values outside [1, 9999] wrap modulo 10000 — the same bound as the
    host path's civil formatter (Python datetime cannot represent them
    either), out of scope for both paths."""
    us_per_day = 86400 * 1_000_000
    v = col.data.astype(jnp.int64)
    days = jnp.floor_divide(v, us_per_day)
    us_of_day = v - days * us_per_day
    y, m, d = _civil_from_days(days.astype(jnp.int32))
    secs = us_of_day // 1_000_000
    frac = (us_of_day % 1_000_000).astype(jnp.int64)
    hh = secs // 3600
    mm = (secs // 60) % 60
    ss = secs % 60
    n = v.shape[0]

    def dig(x, p):
        return ((x // p) % 10 + ord("0")).astype(jnp.uint8)

    dash = jnp.full((n,), ord("-"), jnp.uint8)
    colon = jnp.full((n,), ord(":"), jnp.uint8)
    cols = [dig(y, 1000), dig(y, 100), dig(y, 10), dig(y, 1), dash,
            dig(m, 10), dig(m, 1), dash, dig(d, 10), dig(d, 1),
            jnp.full((n,), ord(" "), jnp.uint8),
            dig(hh, 10), dig(hh, 1), colon, dig(mm, 10), dig(mm, 1),
            colon, dig(ss, 10), dig(ss, 1),
            jnp.full((n,), ord("."), jnp.uint8),
            dig(frac, 100000), dig(frac, 10000), dig(frac, 1000),
            dig(frac, 100), dig(frac, 10), dig(frac, 1)]
    mat = jnp.stack(cols, axis=1)
    # fraction length: 6 minus trailing zeros; zero fraction drops the
    # dot entirely (Spark cast format)
    tz = jnp.where(frac % 10 != 0, 0,
                   jnp.where(frac % 100 != 0, 1,
                             jnp.where(frac % 1000 != 0, 2,
                                       jnp.where(frac % 10000 != 0, 3,
                                                 jnp.where(frac % 100000
                                                           != 0, 4, 5)))))
    lens = jnp.where(frac == 0, 19, 26 - tz).astype(jnp.int32)
    return ragged_from_fixed(mat, lens, col.validity)


def decimal_to_string_tpu(col: TpuColumnVector, scale: int) \
        -> TpuColumnVector:
    """Unscaled int64 -> decimal string like Java BigDecimal.toString
    (plain notation for our scale ranges)."""
    v = col.data.astype(jnp.int64)
    neg = v < 0
    absv = jnp.where(neg, -v, v)  # (abs of int64-min decimal unlikely: cap)
    width = _MAX_I64_DIGITS
    digs, ndig = _digits_mat(absv, width)
    n = v.shape[0]
    if scale == 0:
        posj = jnp.arange(width + 1, dtype=jnp.int32)[None, :]
        digit_pos = posj - neg[:, None].astype(jnp.int32)
        src = jnp.clip(width - ndig[:, None] + digit_pos, 0, width - 1)
        bytes_ = (jnp.take_along_axis(digs, src, axis=1)
                  + ord("0")).astype(jnp.uint8)
        bytes_ = jnp.where((posj == 0) & neg[:, None], ord("-"), bytes_)
        return ragged_from_fixed(bytes_, ndig + neg, col.validity)
    # with scale: int part digits = max(ndig - scale, 1), then '.', then
    # `scale` fraction digits (zero-padded)
    int_digits = jnp.maximum(ndig - scale, 1)
    total_w = width + 3  # sign + dot + possible leading 0
    lens = neg.astype(jnp.int32) + int_digits + 1 + scale
    posj = jnp.arange(total_w, dtype=jnp.int32)[None, :]
    p = posj - neg[:, None].astype(jnp.int32)  # position ignoring sign
    intd = int_digits[:, None]
    is_dot = p == intd
    # digit index within the full (int+frac) digit string:
    dpos = jnp.where(p < intd, p, p - 1)  # skip dot
    total_digits = intd + scale
    src = jnp.clip(width - total_digits + dpos, 0, width - 1)
    dvals = jnp.take_along_axis(digs, src, axis=1)
    # positions before (width - total_digits) are leading zeros -> digit 0
    bytes_ = (dvals + ord("0")).astype(jnp.uint8)
    bytes_ = jnp.where(is_dot, ord("."), bytes_)
    bytes_ = jnp.where((posj == 0) & neg[:, None], ord("-"), bytes_)
    return ragged_from_fixed(bytes_, lens, col.validity)
