"""Sort-key normalization and multi-key permutation kernels.

TPU replacement for libcudf's radix/merge sort (SURVEY.md §2.2-E, §7.1.3;
reference mount empty): every key column is normalized to one orderable
integer lane (floats via IEEE total-order bit tricks with Spark's NaN/-0.0
semantics; strings via iterative rank refinement), then `jax.lax.sort`
does one lexicographic sort over the lanes with the row index as the final
tiebreak key (= stable). The same machinery yields group-ids for the
sort-based aggregate.
"""
from __future__ import annotations

import dataclasses
from typing import List, Sequence

import jax
import jax.numpy as jnp

from .. import datatypes as dt
from ..columnar.column import TpuColumnVector
from .strings import gather_window

__all__ = ["SortSpec", "orderable_int", "canonicalize_floats",
           "string_order_ranks", "string_order_ranks_multi",
           "sort_permutation", "segment_ids_for_keys", "key_lanes",
           "lex_leq", "lex_min_tuple"]

_RANK_WINDOW = 7  # bytes per refinement pass: 7 x 9 bits = 63 bits / int64


@dataclasses.dataclass(frozen=True)
class SortSpec:
    """Per-key direction/null placement (GpuSortOrder analog).
    Spark defaults: ascending nulls-first; descending nulls-last."""
    ascending: bool = True
    nulls_first: bool = True


def canonicalize_floats(d: jax.Array) -> jax.Array:
    """-0.0 -> 0.0, any NaN -> the canonical positive NaN (Spark's
    NormalizeFloatingNumbers semantics, shared by sort keys, group keys
    and min/max)."""
    d = jnp.where(d == 0, jnp.zeros_like(d), d)
    return jnp.where(jnp.isnan(d), jnp.full_like(d, jnp.nan), d)


def normalize_float_key_col(col: TpuColumnVector) -> TpuColumnVector:
    """Column-level float key normalization (Spark's
    NormalizeFloatingNumbers): shared by group-by keys, join keys and any
    other place key *values* are emitted, not just compared."""
    from .. import datatypes as _dt
    if not _dt.is_floating(col.dtype):
        return col
    return col.with_arrays(data=canonicalize_floats(col.data))


def orderable_int(col: TpuColumnVector) -> jax.Array:
    """Map a fixed-width column's data lane to a signed integer lane whose
    ascending order is Spark's ascending order (nulls excluded — handled by
    a separate rank lane). Floats: -0.0 == 0.0, all NaNs equal and largest."""
    t = col.dtype
    d = col.data
    if isinstance(t, dt.BooleanType):
        return d.astype(jnp.int8)
    if dt.is_floating(t):
        bits_t = jnp.int32 if t.np_dtype == jnp.float32 else jnp.int64
        d = canonicalize_floats(d)
        if t.np_dtype == jnp.float64 and jax.default_backend() != "cpu":
            # the TPU stores f64 as f32 (no f64 hardware) and its X64
            # rewriter cannot bitcast f64<->s64: order via the f32 bits
            # (a physical no-op for the stored values)
            d = d.astype(jnp.float32)
            bits_t = jnp.int32
        bits = jax.lax.bitcast_convert_type(d, bits_t)
        # Signed total-order map: positives (incl. +0, +inf, NaN) keep their
        # bits (already ascending); negatives map to ~bits + INT_MIN, a
        # wrapping add that lands them ascending in the negative int range
        # (-inf lowest, -0.0 -> -1 just below +0.0 -> 0).
        min_int = jnp.array(jnp.iinfo(bits_t).min, bits_t)
        return jnp.where(bits < 0, ~bits + min_int, bits)
    # ints / date / timestamp / decimal already compare as ints
    return d


def string_order_ranks_multi(cols: Sequence[TpuColumnVector],
                             lives: Sequence[jax.Array]) -> jax.Array:
    """Dense order ranks over the virtual concatenation of several string
    columns: rank[i] < rank[j] iff bytes(i) < bytes(j) lexicographically
    (unsigned); equal strings share a rank — also across columns, which is
    what makes this the join-key equality kernel. Non-live rows get
    INT32_MAX so they sort last. Returns one rank vector of length
    sum(capacities) in column order.

    Iterative refinement: stable-sort by (current-rank, next-7-byte window)
    and split ties; loops until the longest string is consumed or all ranks
    are distinct (dynamic trip count, static shapes per pass —
    SURVEY.md §7.3.1).
    """
    live = jnp.concatenate([jnp.asarray(lv) for lv in lives])
    n = live.shape[0]
    lens = jnp.concatenate([c.offsets[1:] - c.offsets[:-1] for c in cols])
    live_lens = jnp.where(live, lens, 0)
    max_len = jnp.max(live_lens, initial=0)
    num_live = jnp.sum(live.astype(jnp.int32))
    idx = jnp.arange(n, dtype=jnp.int32)

    def window_key(chunk):
        # pack 7 bytes into one int64, 9 bits each: past-end (-1) -> 0,
        # real bytes -> 1..256, so shorter strings sort first.
        parts = []
        for c in cols:
            w = gather_window(c.offsets, c.chars, chunk,
                              window=_RANK_WINDOW)
            parts.append((w + 1).astype(jnp.int64))
        w = jnp.concatenate(parts)
        key = jnp.zeros((n,), jnp.int64)
        for b in range(_RANK_WINDOW):
            key = (key << 9) | w[:, b]
        return key

    rank0 = jnp.where(live, jnp.int32(0), jnp.int32(1))

    def cond(state):
        chunk, rank, distinct = state
        return (chunk * _RANK_WINDOW < max_len) & (distinct < num_live)

    def body(state):
        chunk, rank, _ = state
        key = window_key(chunk)
        # idx as trailing sort key = stable within (rank, key) ties
        srank, skey, sidx = jax.lax.sort((rank, key, idx), num_keys=3)
        boundary = jnp.concatenate([
            jnp.ones((1,), jnp.bool_),
            (srank[1:] != srank[:-1]) | (skey[1:] != skey[:-1])])
        # log-depth int prefix + sort-based inversion: serial cumsum and
        # scatters both lose on TPU
        from .gather import inclusive_int_cumsum, invert_permutation
        new_rank_sorted = inclusive_int_cumsum(boundary) - 1
        new_rank = invert_permutation(sidx, new_rank_sorted)
        distinct = jnp.max(jnp.where(live, new_rank, -1), initial=-1) + 1
        return chunk + 1, new_rank, distinct

    _, rank, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), rank0, jnp.int32(0)))
    return jnp.where(live, rank, jnp.int32(2**31 - 1))


def string_order_ranks(col: TpuColumnVector, live: jax.Array) -> jax.Array:
    """Single-column case of string_order_ranks_multi."""
    return string_order_ranks_multi([col], [live])


def _null_rank_lane(validity: jax.Array, spec: SortSpec) -> jax.Array:
    """Null placement is independent of direction: the value lane handles
    direction, this lane handles where nulls land."""
    if spec.nulls_first:
        return jnp.where(validity, jnp.int8(1), jnp.int8(0))
    return jnp.where(validity, jnp.int8(0), jnp.int8(1))


def _key_lanes(key_cols: Sequence[TpuColumnVector],
               specs: Sequence[SortSpec],
               live: jax.Array) -> List[jax.Array]:
    """Orderable lanes, most-significant first: a live-rank lane (padding
    always last), then per key a null-placement lane and a value lane."""
    lanes: List[jax.Array] = [jnp.where(live, jnp.int8(0), jnp.int8(1))]
    for col, spec in zip(key_cols, specs):
        if col.is_string_like:
            vals = string_order_ranks(col, live & col.validity)
        elif col.data is None:  # NullType: all rows equal
            vals = jnp.zeros((live.shape[0],), jnp.int8)
        else:
            # neutralize the lane under nulls: computed expressions leave
            # garbage in the data lane of null rows, and null==null must
            # hold for both ordering and grouping
            vals = orderable_int(col)
            vals = jnp.where(col.validity, vals, jnp.zeros_like(vals))
        if not spec.ascending:
            vals = ~vals  # total reversal of the signed int order
        lanes.append(_null_rank_lane(col.validity, spec))
        lanes.append(vals)
    return lanes


def key_lanes_vs_bounds(col: TpuColumnVector, bcol: TpuColumnVector,
                        spec: SortSpec):
    """((null_lane, value_lane) for rows, same for bounds) in ONE shared
    orderable space with the exact _key_lanes semantics — the single
    source of truth for direction/null/NaN placement, consumed by the
    range partitioner's row-vs-bound lexicographic compare. Strings rank
    jointly over the virtual concat; equal nulls share the rank space's
    top sentinel on both sides."""
    n = col.capacity
    if col.is_string_like:
        ranks = string_order_ranks_multi(
            [col, bcol], [col.validity, bcol.validity])
        vr = ranks[:n].astype(jnp.int64)
        vb = ranks[n:].astype(jnp.int64)
    elif col.data is None:  # NullType: all rows equal
        vr = jnp.zeros((n,), jnp.int64)
        vb = jnp.zeros((bcol.capacity,), jnp.int64)
    else:
        vr = jnp.where(col.validity, orderable_int(col).astype(jnp.int64),
                       jnp.int64(0))
        vb = jnp.where(bcol.validity,
                       orderable_int(bcol).astype(jnp.int64), jnp.int64(0))
    if not spec.ascending:
        vr, vb = ~vr, ~vb
    return ((_null_rank_lane(col.validity, spec), vr),
            (_null_rank_lane(bcol.validity, spec), vb))


def key_lanes(key_cols, specs, live):
    """Public name for the orderable lane stack (out-of-core merge uses it
    to compare rows against run boundaries in the same rank space)."""
    return _key_lanes(key_cols, specs, live)


def lex_leq(lanes: Sequence[jax.Array],
            boundary: Sequence[jax.Array]) -> jax.Array:
    """Per-row mask: lane tuple <= boundary scalar tuple, lexicographic in
    lane order (= the sort order, since lanes encode direction and null
    placement)."""
    n = lanes[0].shape[0]
    lt = jnp.zeros((n,), jnp.bool_)
    eq = jnp.ones((n,), jnp.bool_)
    for lane, b in zip(lanes, boundary):
        lt = lt | (eq & (lane < b))
        eq = eq & (lane == b)
    return lt | eq


def lex_min_tuple(blanes: Sequence[jax.Array], bvalid: jax.Array):
    """Lexicographic minimum among k boundary tuples (blanes: each lane is
    shape (k,)); invalid entries never win. k is static and small."""
    k = bvalid.shape[0]
    best = [lane[0] for lane in blanes]
    best_valid = bvalid[0]
    for i in range(1, k):
        cand = [lane[i] for lane in blanes]
        lt = jnp.asarray(False)
        eq = jnp.asarray(True)
        for c, b in zip(cand, best):
            lt = lt | (eq & (c < b))
            eq = eq & (c == b)
        take = bvalid[i] & (lt | ~best_valid)
        best = [jnp.where(take, c, b) for c, b in zip(cand, best)]
        best_valid = best_valid | bvalid[i]
    return best


def sort_permutation(key_cols: Sequence[TpuColumnVector],
                     specs: Sequence[SortSpec],
                     live: jax.Array) -> jax.Array:
    """Stable permutation ordering rows by the keys, padding rows last."""
    n = live.shape[0]
    lanes = _key_lanes(key_cols, specs, live)
    idx = jnp.arange(n, dtype=jnp.int32)
    # idx participates as the least-significant key -> stable
    out = jax.lax.sort(tuple(lanes) + (idx,), num_keys=len(lanes) + 1)
    return out[-1]


def segment_ids_for_keys(key_cols: Sequence[TpuColumnVector],
                         live: jax.Array):
    """(perm, seg_ids_sorted, num_groups): rows permuted so equal keys are
    adjacent (live rows first), seg ids over the sorted order, and the
    group count among live rows. Grouping equality is Spark's: null==null,
    NaN==NaN, -0.0==0.0."""
    n = live.shape[0]
    specs = [SortSpec()] * len(key_cols)
    lanes = _key_lanes(key_cols, specs, live)
    idx = jnp.arange(n, dtype=jnp.int32)
    sorted_all = jax.lax.sort(tuple(lanes) + (idx,),
                              num_keys=len(lanes) + 1)
    sorted_lanes, perm = sorted_all[:-1], sorted_all[-1]
    boundary = jnp.zeros((n,), jnp.bool_).at[0].set(True)
    for lane in sorted_lanes:
        boundary = boundary | jnp.concatenate(
            [jnp.zeros((1,), jnp.bool_), lane[1:] != lane[:-1]])
    from .gather import inclusive_int_cumsum
    seg = inclusive_int_cumsum(boundary) - 1
    live_sorted = live[perm]
    num_groups = jnp.max(jnp.where(live_sorted, seg + 1, 0), initial=0)
    return perm, seg, num_groups
