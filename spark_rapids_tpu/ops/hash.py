"""Spark-compatible Murmur3 hash kernels.

TPU replacement for the reference's hash partitioning / GpuMurmur3Hash
(`HashFunctions.scala`, `GpuHashPartitioningBase` — SURVEY.md §2.2-C/D;
reference mount empty). Spark uses Murmur3_x86_32 with seed 42 for
`hash()` and shuffle partitioning; matching it bit-for-bit keeps partition
placement identical to CPU Spark (important for AQE stats parity and for
the dual-run harness's exchange tests).

Written against an array-module parameter `xp` so the SAME code runs as a
jnp device kernel and as the numpy host oracle; all arithmetic in uint32
(wrapping).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import datatypes as dt
from ..columnar.column import TpuColumnVector

__all__ = ["murmur3_int32", "murmur3_int64",
           "murmur3_bytes_device_seeded", "hash_column_device",
           "hash_columns_device", "hash_columns_numpy", "pmod"]

_C1 = np.uint32(0xcc9e2d51)
_C2 = np.uint32(0x1b873593)
SEED = np.uint32(42)


def _rotl(x, r, xp):
    r32 = np.uint32(32 - r)
    return (x << np.uint32(r)) | (x >> r32)


def _mix_k1(k1, xp):
    k1 = k1 * _C1
    k1 = _rotl(k1, 15, xp)
    return k1 * _C2


def _mix_h1(h1, k1, xp):
    h1 = h1 ^ k1
    h1 = _rotl(h1, 13, xp)
    return h1 * np.uint32(5) + np.uint32(0xe6546b64)


def _fmix(h1, length, xp):
    h1 = h1 ^ np.uint32(length)
    h1 = h1 ^ (h1 >> np.uint32(16))
    h1 = h1 * np.uint32(0x85ebca6b)
    h1 = h1 ^ (h1 >> np.uint32(13))
    h1 = h1 * np.uint32(0xc2b2ae35)
    return h1 ^ (h1 >> np.uint32(16))


def murmur3_int32(v, seed, xp):
    """v: uint32 array (the 4-byte value), seed: uint32 array/scalar."""
    h = _mix_h1(seed, _mix_k1(v, xp), xp)
    return _fmix(h, 4, xp)


def murmur3_int64(v, seed, xp):
    """v: uint64-ish as two uint32 lanes (lo, hi) — Spark hashes the low
    word then the high word."""
    lo, hi = v
    h = _mix_h1(seed, _mix_k1(lo, xp), xp)
    h = _mix_h1(h, _mix_k1(hi, xp), xp)
    return _fmix(h, 8, xp)


def _split64(v64, xp):
    u = v64.astype(xp.uint64) if xp is np else \
        jax.lax.bitcast_convert_type(v64, jnp.uint64)
    lo = (u & xp.uint64(0xffffffff)).astype(xp.uint32)
    hi = (u >> xp.uint64(32)).astype(xp.uint32)
    return lo, hi


def _hash_fixed(values, t: dt.DataType, seed, xp):
    """Hash one fixed-width column's dense values with Spark semantics."""
    if isinstance(t, dt.BooleanType):
        v = values.astype(xp.uint32) if xp is np else \
            values.astype(jnp.uint32)
        return murmur3_int32(v, seed, xp)
    if isinstance(t, (dt.ByteType, dt.ShortType, dt.IntegerType,
                      dt.DateType)):
        v = values.astype(xp.int32)
        v = v.view(xp.uint32) if xp is np else \
            jax.lax.bitcast_convert_type(v, jnp.uint32)
        return murmur3_int32(v, seed, xp)
    if isinstance(t, (dt.LongType, dt.TimestampType, dt.DecimalType)):
        return murmur3_int64(_split64(values.astype(xp.int64), xp), seed,
                             xp)
    if isinstance(t, dt.FloatType):
        v = values
        v = xp.where(v == 0, xp.zeros_like(v), v)  # -0.0 -> 0.0
        nan_bits = np.float32(np.nan)
        v = xp.where(xp.isnan(v), xp.full_like(v, nan_bits), v)
        bits = v.view(xp.uint32) if xp is np else \
            jax.lax.bitcast_convert_type(v, jnp.uint32)
        return murmur3_int32(bits, seed, xp)
    if isinstance(t, dt.DoubleType):
        v = values
        v = xp.where(v == 0, xp.zeros_like(v), v)
        v = xp.where(xp.isnan(v), xp.full_like(v, np.nan), v)
        bits = v.view(xp.int64) if xp is np else v  # split64 bitcasts
        if xp is np:
            return murmur3_int64(_split64_np_bits(bits), seed, xp)
        return murmur3_int64(_split64_f64_device(v), seed, xp)
    raise NotImplementedError(f"hash of {t.simple_string()}")


def _split64_np_bits(bits):
    u = bits.view(np.uint64)
    return ((u & np.uint64(0xffffffff)).astype(np.uint32),
            (u >> np.uint64(32)).astype(np.uint32))


def _split64_f64_device(v):
    u = jax.lax.bitcast_convert_type(v, jnp.uint64)
    return ((u & jnp.uint64(0xffffffff)).astype(jnp.uint32),
            (u >> jnp.uint64(32)).astype(jnp.uint32))


def _fmix_len(h1, lens):
    h1 = h1 ^ lens.astype(jnp.uint32)
    h1 = h1 ^ (h1 >> jnp.uint32(16))
    h1 = h1 * jnp.uint32(0x85ebca6b)
    h1 = h1 ^ (h1 >> jnp.uint32(13))
    h1 = h1 * jnp.uint32(0xc2b2ae35)
    return h1 ^ (h1 >> jnp.uint32(16))


def hash_column_device(col: TpuColumnVector, seed) -> jax.Array:
    """One column's contribution: null rows keep the incoming seed
    (Spark semantics: null doesn't change the running hash)."""
    if col.is_string_like:
        h = murmur3_bytes_device_seeded(col.offsets, col.chars, seed)
    elif col.data is None:
        return seed
    else:
        h = _hash_fixed(col.data, col.dtype, seed, jnp)
    return jnp.where(col.validity, h, seed)


def murmur3_bytes_device_seeded(offsets, chars, seed):
    """Like murmur3_bytes_device but threading a per-row seed array."""
    n = offsets.shape[0] - 1
    starts = offsets[:-1]
    lens = offsets[1:] - starts
    nblocks = lens // 4
    max_blocks = jnp.max(nblocks, initial=0)
    limit = max(chars.shape[0] - 1, 0)

    def get_byte(pos):
        idx = jnp.clip(pos, 0, limit)
        return (chars[idx] if chars.shape[0] else
                jnp.zeros_like(idx, jnp.uint8)).astype(jnp.uint32)

    def block_word(b):
        base = starts + b * 4
        w = get_byte(base)
        w = w | (get_byte(base + 1) << 8)
        w = w | (get_byte(base + 2) << 16)
        w = w | (get_byte(base + 3) << 24)
        return w

    def body(state):
        b, h = state
        active = b < nblocks
        w = block_word(b)
        h2 = _mix_h1(h, _mix_k1(w, jnp), jnp)
        return b + 1, jnp.where(active, h2, h)

    h = seed * jnp.ones((n,), jnp.uint32)
    _, h = jax.lax.while_loop(lambda s: s[0] < max_blocks, body,
                              (jnp.int32(0), h))
    for tpos in range(3):
        pos = nblocks * 4 + tpos
        active = pos < lens
        byte = get_byte(starts + pos)
        sbyte = jnp.where(byte >= 128, byte.astype(jnp.int32) - 256,
                          byte.astype(jnp.int32))
        k = jax.lax.bitcast_convert_type(sbyte, jnp.uint32)
        h2 = _mix_h1(h, _mix_k1(k, jnp), jnp)
        h = jnp.where(active, h2, h)
    return _fmix_len(h, lens)


def hash_columns_device(cols: Sequence[TpuColumnVector]) -> jax.Array:
    """Spark hash(cols...): running seed threaded through columns."""
    n = cols[0].capacity if cols else 0
    h = jnp.full((n,), SEED, jnp.uint32)
    for c in cols:
        h = hash_column_device(c, h)
    return jax.lax.bitcast_convert_type(h, jnp.int32)


def hash_columns_numpy(arrays, types: Sequence[dt.DataType],
                       n: int) -> np.ndarray:
    """Host oracle: same running-seed scheme over pyarrow arrays."""
    np_err = np.seterr(over="ignore")  # uint32 wraparound is intended
    h = np.full(n, SEED, np.uint32)
    for arr, t in zip(arrays, types):
        valid = np.ones(n, bool) if arr.null_count == 0 else \
            np.array([v is not None for v in arr.to_pylist()])
        if isinstance(t, (dt.StringType, dt.BinaryType)):
            vals = arr.to_pylist()
            for i in range(n):
                if not valid[i]:
                    continue
                b = vals[i].encode() if isinstance(vals[i], str) else \
                    bytes(vals[i])
                h[i] = _hash_bytes_seeded_np(b, h[i])
        else:
            vals = arr.to_pylist()
            for i in range(n):
                if not valid[i]:
                    continue
                h[i] = _hash_scalar_np(vals[i], t, h[i])
    np.seterr(**np_err)
    return h.view(np.int32)


def _hash_scalar_np(v, t: dt.DataType, seed: np.uint32) -> np.uint32:
    import decimal as _dec
    import datetime as _dtm
    if isinstance(t, dt.BooleanType):
        return murmur3_int32(np.uint32(1 if v else 0), seed, np)
    if isinstance(t, (dt.ByteType, dt.ShortType, dt.IntegerType)):
        return murmur3_int32(np.uint32(int(v) & 0xffffffff), seed, np)
    if isinstance(t, dt.DateType):
        days = (v - _dtm.date(1970, 1, 1)).days if isinstance(v, _dtm.date) \
            else int(v)
        return murmur3_int32(np.uint32(days & 0xffffffff), seed, np)
    if isinstance(t, (dt.LongType, dt.TimestampType, dt.DecimalType)):
        if isinstance(t, dt.TimestampType) and isinstance(v, _dtm.datetime):
            if v.tzinfo is None:
                v = v.replace(tzinfo=_dtm.timezone.utc)
            epoch = _dtm.datetime(1970, 1, 1, tzinfo=_dtm.timezone.utc)
            v = (v - epoch) // _dtm.timedelta(microseconds=1)
        elif isinstance(t, dt.DecimalType):
            v = int(_dec.Decimal(v).scaleb(t.scale))
        u = int(v) & 0xffffffffffffffff
        return murmur3_int64((np.uint32(u & 0xffffffff),
                              np.uint32(u >> 32)), seed, np)
    if isinstance(t, dt.FloatType):
        f = np.float32(v)
        if f == 0:
            f = np.float32(0.0)
        if np.isnan(f):
            f = np.float32(np.nan)
        return murmur3_int32(f.view(np.uint32), seed, np)
    if isinstance(t, dt.DoubleType):
        f = np.float64(v)
        if f == 0:
            f = np.float64(0.0)
        if np.isnan(f):
            f = np.float64(np.nan)
        u = f.view(np.uint64)
        return murmur3_int64((np.uint32(u & np.uint64(0xffffffff)),
                              np.uint32(u >> np.uint64(32))), seed, np)
    raise NotImplementedError(t.simple_string())


def _hash_bytes_seeded_np(b: bytes, seed: np.uint32) -> np.uint32:
    h = seed
    nb = len(b) // 4
    for blk in range(nb):
        w = np.uint32(int.from_bytes(b[blk * 4: blk * 4 + 4], "little"))
        h = _mix_h1(h, _mix_k1(w, np), np)
    for t in range(nb * 4, len(b)):
        sb = b[t]
        if sb >= 128:
            sb -= 256
        k = np.uint32(sb & 0xffffffff)
        h = _mix_h1(h, _mix_k1(k, np), np)
    return _fmix(h, len(b), np)


def pmod(hash_vals, n: int, xp=jnp):
    """Spark's positive modulo for partition ids."""
    r = hash_vals % xp.int32(n)
    return xp.where(r < 0, r + n, r)
