"""Spark-compatible Murmur3 hash kernels.

TPU replacement for the reference's hash partitioning / GpuMurmur3Hash
(`HashFunctions.scala`, `GpuHashPartitioningBase` — SURVEY.md §2.2-C/D;
reference mount empty). Spark uses Murmur3_x86_32 with seed 42 for
`hash()` and shuffle partitioning; matching it bit-for-bit keeps partition
placement identical to CPU Spark (important for AQE stats parity and for
the dual-run harness's exchange tests).

Written against an array-module parameter `xp` so the SAME code runs as a
jnp device kernel and as the numpy host oracle; all arithmetic in uint32
(wrapping).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import datatypes as dt
from ..columnar.column import TpuColumnVector

__all__ = ["murmur3_int32", "murmur3_int64",
           "murmur3_bytes_device_seeded", "hash_column_device",
           "hash_columns_device", "hash_columns_numpy", "pmod"]

_C1 = np.uint32(0xcc9e2d51)
_C2 = np.uint32(0x1b873593)
SEED = np.uint32(42)


def _rotl(x, r, xp):
    r32 = np.uint32(32 - r)
    return (x << np.uint32(r)) | (x >> r32)


def _mix_k1(k1, xp):
    k1 = k1 * _C1
    k1 = _rotl(k1, 15, xp)
    return k1 * _C2


def _mix_h1(h1, k1, xp):
    h1 = h1 ^ k1
    h1 = _rotl(h1, 13, xp)
    return h1 * np.uint32(5) + np.uint32(0xe6546b64)


def _fmix(h1, length, xp):
    h1 = h1 ^ np.uint32(length)
    h1 = h1 ^ (h1 >> np.uint32(16))
    h1 = h1 * np.uint32(0x85ebca6b)
    h1 = h1 ^ (h1 >> np.uint32(13))
    h1 = h1 * np.uint32(0xc2b2ae35)
    return h1 ^ (h1 >> np.uint32(16))


def murmur3_int32(v, seed, xp):
    """v: uint32 array (the 4-byte value), seed: uint32 array/scalar."""
    h = _mix_h1(seed, _mix_k1(v, xp), xp)
    return _fmix(h, 4, xp)


def murmur3_int64(v, seed, xp):
    """v: uint64-ish as two uint32 lanes (lo, hi) — Spark hashes the low
    word then the high word."""
    lo, hi = v
    h = _mix_h1(seed, _mix_k1(lo, xp), xp)
    h = _mix_h1(h, _mix_k1(hi, xp), xp)
    return _fmix(h, 8, xp)


def _split64(v64, xp):
    u = v64.astype(xp.uint64) if xp is np else \
        jax.lax.bitcast_convert_type(v64, jnp.uint64)
    lo = (u & xp.uint64(0xffffffff)).astype(xp.uint32)
    hi = (u >> xp.uint64(32)).astype(xp.uint32)
    return lo, hi


def _hash_fixed(values, t: dt.DataType, seed, xp):
    """Hash one fixed-width column's dense values with Spark semantics."""
    if isinstance(t, dt.BooleanType):
        v = values.astype(xp.uint32) if xp is np else \
            values.astype(jnp.uint32)
        return murmur3_int32(v, seed, xp)
    if isinstance(t, (dt.ByteType, dt.ShortType, dt.IntegerType,
                      dt.DateType)):
        v = values.astype(xp.int32)
        v = v.view(xp.uint32) if xp is np else \
            jax.lax.bitcast_convert_type(v, jnp.uint32)
        return murmur3_int32(v, seed, xp)
    if isinstance(t, (dt.LongType, dt.TimestampType, dt.DecimalType)):
        return murmur3_int64(_split64(values.astype(xp.int64), xp), seed,
                             xp)
    if isinstance(t, dt.FloatType):
        v = values
        v = xp.where(v == 0, xp.zeros_like(v), v)  # -0.0 -> 0.0
        nan_bits = np.float32(np.nan)
        v = xp.where(xp.isnan(v), xp.full_like(v, nan_bits), v)
        bits = v.view(xp.uint32) if xp is np else \
            jax.lax.bitcast_convert_type(v, jnp.uint32)
        return murmur3_int32(bits, seed, xp)
    if isinstance(t, dt.DoubleType):
        v = values
        v = xp.where(v == 0, xp.zeros_like(v), v)
        v = xp.where(xp.isnan(v), xp.full_like(v, np.nan), v)
        bits = v.view(xp.int64) if xp is np else v  # split64 bitcasts
        if xp is np:
            return murmur3_int64(_split64_np_bits(bits), seed, xp)
        return murmur3_int64(_split64_f64_device(v), seed, xp)
    raise NotImplementedError(f"hash of {t.simple_string()}")


def _split64_np_bits(bits):
    u = bits.view(np.uint64)
    return ((u & np.uint64(0xffffffff)).astype(np.uint32),
            (u >> np.uint64(32)).astype(np.uint32))


def _split64_f64_device(v):
    u = jax.lax.bitcast_convert_type(v, jnp.uint64)
    return ((u & jnp.uint64(0xffffffff)).astype(jnp.uint32),
            (u >> jnp.uint64(32)).astype(jnp.uint32))


def _fmix_len(h1, lens):
    h1 = h1 ^ lens.astype(jnp.uint32)
    h1 = h1 ^ (h1 >> jnp.uint32(16))
    h1 = h1 * jnp.uint32(0x85ebca6b)
    h1 = h1 ^ (h1 >> jnp.uint32(13))
    h1 = h1 * jnp.uint32(0xc2b2ae35)
    return h1 ^ (h1 >> jnp.uint32(16))


def hash_column_device(col: TpuColumnVector, seed) -> jax.Array:
    """One column's contribution: null rows keep the incoming seed
    (Spark semantics: null doesn't change the running hash)."""
    if col.is_string_like:
        h = murmur3_bytes_device_seeded(col.offsets, col.chars, seed)
    elif col.data is None:
        return seed
    else:
        h = _hash_fixed(col.data, col.dtype, seed, jnp)
    return jnp.where(col.validity, h, seed)


def murmur3_bytes_device_seeded(offsets, chars, seed):
    """Like murmur3_bytes_device but threading a per-row seed array."""
    n = offsets.shape[0] - 1
    starts = offsets[:-1]
    lens = offsets[1:] - starts
    nblocks = lens // 4
    max_blocks = jnp.max(nblocks, initial=0)
    limit = max(chars.shape[0] - 1, 0)

    def get_byte(pos):
        idx = jnp.clip(pos, 0, limit)
        return (chars[idx] if chars.shape[0] else
                jnp.zeros_like(idx, jnp.uint8)).astype(jnp.uint32)

    def block_word(b):
        base = starts + b * 4
        w = get_byte(base)
        w = w | (get_byte(base + 1) << 8)
        w = w | (get_byte(base + 2) << 16)
        w = w | (get_byte(base + 3) << 24)
        return w

    def body(state):
        b, h = state
        active = b < nblocks
        w = block_word(b)
        h2 = _mix_h1(h, _mix_k1(w, jnp), jnp)
        return b + 1, jnp.where(active, h2, h)

    h = seed * jnp.ones((n,), jnp.uint32)
    _, h = jax.lax.while_loop(lambda s: s[0] < max_blocks, body,
                              (jnp.int32(0), h))
    for tpos in range(3):
        pos = nblocks * 4 + tpos
        active = pos < lens
        byte = get_byte(starts + pos)
        sbyte = jnp.where(byte >= 128, byte.astype(jnp.int32) - 256,
                          byte.astype(jnp.int32))
        k = jax.lax.bitcast_convert_type(sbyte, jnp.uint32)
        h2 = _mix_h1(h, _mix_k1(k, jnp), jnp)
        h = jnp.where(active, h2, h)
    return _fmix_len(h, lens)


def hash_columns_device(cols: Sequence[TpuColumnVector]) -> jax.Array:
    """Spark hash(cols...): running seed threaded through columns."""
    n = cols[0].capacity if cols else 0
    h = jnp.full((n,), SEED, jnp.uint32)
    for c in cols:
        h = hash_column_device(c, h)
    return jax.lax.bitcast_convert_type(h, jnp.int32)


def hash_columns_numpy(arrays, types: Sequence[dt.DataType],
                       n: int) -> np.ndarray:
    """Host oracle: same running-seed scheme over pyarrow arrays."""
    np_err = np.seterr(over="ignore")  # uint32 wraparound is intended
    h = np.full(n, SEED, np.uint32)
    for arr, t in zip(arrays, types):
        valid = np.ones(n, bool) if arr.null_count == 0 else \
            np.array([v is not None for v in arr.to_pylist()])
        if isinstance(t, (dt.StringType, dt.BinaryType)):
            vals = arr.to_pylist()
            for i in range(n):
                if not valid[i]:
                    continue
                b = vals[i].encode() if isinstance(vals[i], str) else \
                    bytes(vals[i])
                h[i] = _hash_bytes_seeded_np(b, h[i])
        else:
            vals = arr.to_pylist()
            for i in range(n):
                if not valid[i]:
                    continue
                h[i] = _hash_scalar_np(vals[i], t, h[i])
    np.seterr(**np_err)
    return h.view(np.int32)


def _hash_scalar_np(v, t: dt.DataType, seed: np.uint32) -> np.uint32:
    import decimal as _dec
    import datetime as _dtm
    if isinstance(t, dt.BooleanType):
        return murmur3_int32(np.uint32(1 if v else 0), seed, np)
    if isinstance(t, (dt.ByteType, dt.ShortType, dt.IntegerType)):
        return murmur3_int32(np.uint32(int(v) & 0xffffffff), seed, np)
    if isinstance(t, dt.DateType):
        days = (v - _dtm.date(1970, 1, 1)).days if isinstance(v, _dtm.date) \
            else int(v)
        return murmur3_int32(np.uint32(days & 0xffffffff), seed, np)
    if isinstance(t, (dt.LongType, dt.TimestampType, dt.DecimalType)):
        if isinstance(t, dt.TimestampType) and isinstance(v, _dtm.datetime):
            if v.tzinfo is None:
                v = v.replace(tzinfo=_dtm.timezone.utc)
            epoch = _dtm.datetime(1970, 1, 1, tzinfo=_dtm.timezone.utc)
            v = (v - epoch) // _dtm.timedelta(microseconds=1)
        elif isinstance(t, dt.DecimalType):
            v = int(_dec.Decimal(v).scaleb(t.scale))
        u = int(v) & 0xffffffffffffffff
        return murmur3_int64((np.uint32(u & 0xffffffff),
                              np.uint32(u >> 32)), seed, np)
    if isinstance(t, dt.FloatType):
        f = np.float32(v)
        if f == 0:
            f = np.float32(0.0)
        if np.isnan(f):
            f = np.float32(np.nan)
        return murmur3_int32(f.view(np.uint32), seed, np)
    if isinstance(t, dt.DoubleType):
        f = np.float64(v)
        if f == 0:
            f = np.float64(0.0)
        if np.isnan(f):
            f = np.float64(np.nan)
        u = f.view(np.uint64)
        return murmur3_int64((np.uint32(u & np.uint64(0xffffffff)),
                              np.uint32(u >> np.uint64(32))), seed, np)
    raise NotImplementedError(t.simple_string())


def _hash_bytes_seeded_np(b: bytes, seed: np.uint32) -> np.uint32:
    h = seed
    nb = len(b) // 4
    for blk in range(nb):
        w = np.uint32(int.from_bytes(b[blk * 4: blk * 4 + 4], "little"))
        h = _mix_h1(h, _mix_k1(w, np), np)
    for t in range(nb * 4, len(b)):
        sb = b[t]
        if sb >= 128:
            sb -= 256
        k = np.uint32(sb & 0xffffffff)
        h = _mix_h1(h, _mix_k1(k, np), np)
    return _fmix(h, len(b), np)


def pmod(hash_vals, n: int, xp=jnp):
    """Spark's positive modulo for partition ids."""
    r = hash_vals % xp.int32(n)
    return xp.where(r < 0, r + n, r)


# --- xxhash64 (Spark XxHash64, seed 42) ------------------------------------
# 64-bit XXH64 exactly as Spark's catalyst XXH64.java defines it: fixed
# types hash their int/long form, strings hash their bytes (4-lane
# accumulator path for >= 32 bytes). All arithmetic wraps in uint64 (the
# TPU X64 rewriter emulates u64 as 32-bit pairs).

_XP1 = 0x9E3779B185EBCA87
_XP2 = 0xC2B2AE3D27D4EB4F
_XP3 = 0x165667B19E3779F9
_XP4 = 0x85EBCA77C2B2AE63
_XP5 = 0x27D4EB2F165667C5
XXSEED = 42


def _u64(x, xp):
    return xp.uint64(x)


def _rotl64(x, r, xp):
    return (x << _u64(r, xp)) | (x >> _u64(64 - r, xp))


def _xx_avalanche(h, xp):
    h = h ^ (h >> _u64(33, xp))
    h = h * _u64(_XP2, xp)
    h = h ^ (h >> _u64(29, xp))
    h = h * _u64(_XP3, xp)
    return h ^ (h >> _u64(32, xp))


def xxhash64_long(v_u64, seed_u64, xp):
    h = seed_u64 + _u64(_XP5, xp) + _u64(8, xp)
    h = h ^ (_rotl64(v_u64 * _u64(_XP2, xp), 31, xp) * _u64(_XP1, xp))
    h = _rotl64(h, 27, xp) * _u64(_XP1, xp) + _u64(_XP4, xp)
    return _xx_avalanche(h, xp)


def xxhash64_int(v_u32_as_u64, seed_u64, xp):
    """Spark hashInt: the 4-byte value zero-extended into the u64 mix."""
    h = seed_u64 + _u64(_XP5, xp) + _u64(4, xp)
    h = h ^ (v_u32_as_u64 * _u64(_XP1, xp))
    h = _rotl64(h, 23, xp) * _u64(_XP2, xp) + _u64(_XP3, xp)
    return _xx_avalanche(h, xp)


def _xx_fixed(values, t: dt.DataType, seed, xp=jnp):
    """One fixed-width column's dense DEVICE values -> xxhash64
    contribution (the host oracle goes through _xx_scalar_np)."""
    def bits64(a):
        return jax.lax.bitcast_convert_type(a, jnp.uint64)

    def bits32(a):
        return jax.lax.bitcast_convert_type(a, jnp.uint32) \
            .astype(jnp.uint64)
    if isinstance(t, dt.BooleanType):
        return xxhash64_int(values.astype(xp.uint64), seed, xp)
    if isinstance(t, (dt.ByteType, dt.ShortType, dt.IntegerType,
                      dt.DateType)):
        return xxhash64_int(bits32(values.astype(xp.int32)), seed, xp)
    if isinstance(t, (dt.LongType, dt.TimestampType, dt.DecimalType)):
        return xxhash64_long(bits64(values.astype(xp.int64)), seed, xp)
    if isinstance(t, dt.FloatType):
        v = xp.where(values == 0, xp.zeros_like(values), values)
        v = xp.where(xp.isnan(v), xp.full_like(v, np.nan), v)
        return xxhash64_int(bits32(v), seed, xp)
    if isinstance(t, dt.DoubleType):
        v = xp.where(values == 0, xp.zeros_like(values), values)
        v = xp.where(xp.isnan(v), xp.full_like(v, np.nan), v)
        return xxhash64_long(bits64(v), seed, xp)
    raise NotImplementedError(f"xxhash64 of {t.simple_string()}")


def xxhash64_bytes_device_seeded(offsets, chars, seed):
    """Per-row XXH64 over variable-length byte strings, per-row seeds.
    Rows >= 32 bytes use the 4-accumulator stripe path, shorter rows the
    small path — both computed with masked loops over static shapes."""
    n = offsets.shape[0] - 1
    starts = offsets[:-1]
    lens = (offsets[1:] - starts).astype(jnp.uint64)
    limit = max(chars.shape[0] - 1, 0)

    def get_byte(pos):
        idx = jnp.clip(pos, 0, limit)
        return (chars[idx] if chars.shape[0] else
                jnp.zeros_like(idx, jnp.uint8)).astype(jnp.uint64)

    def word64(base):
        w = jnp.zeros((n,), jnp.uint64)
        for i in range(8):
            w = w | (get_byte(base + i) << jnp.uint64(8 * i))
        return w

    def word32(base):
        w = jnp.zeros((n,), jnp.uint64)
        for i in range(4):
            w = w | (get_byte(base + i) << jnp.uint64(8 * i))
        return w

    u = lambda c: jnp.uint64(c)
    nstripes = (lens >> u(5)).astype(jnp.int32)
    max_stripes = jnp.max(nstripes, initial=0)

    def stripe_body(state):
        s, a1, a2, a3, a4 = state
        active = s < nstripes
        base = starts + s * 32

        def rnd(acc, off):
            acc2 = acc + word64(base + off) * u(_XP2)
            return _rotl64(acc2, 31, jnp) * u(_XP1)
        b1, b2, b3, b4 = (rnd(a1, 0), rnd(a2, 8), rnd(a3, 16),
                          rnd(a4, 24))
        return (s + 1, jnp.where(active, b1, a1),
                jnp.where(active, b2, a2), jnp.where(active, b3, a3),
                jnp.where(active, b4, a4))

    sd = seed * jnp.ones((n,), jnp.uint64)
    a1 = sd + u(_XP1) + u(_XP2)
    a2 = sd + u(_XP2)
    a3 = sd
    a4 = sd - u(_XP1)
    _, a1, a2, a3, a4 = jax.lax.while_loop(
        lambda st: st[0] < max_stripes, stripe_body,
        (jnp.int32(0), a1, a2, a3, a4))

    merged = (_rotl64(a1, 1, jnp) + _rotl64(a2, 7, jnp)
              + _rotl64(a3, 12, jnp) + _rotl64(a4, 18, jnp))

    def merge_round(h, acc):
        k = _rotl64(acc * u(_XP2), 31, jnp) * u(_XP1)
        return (h ^ k) * u(_XP1) + u(_XP4)
    for acc in (a1, a2, a3, a4):
        merged = merge_round(merged, acc)

    h = jnp.where(lens >= u(32), merged, sd + u(_XP5))
    h = h + lens
    # remaining (< 32) bytes: up to 3x 8-byte, one 4-byte, up to 3 bytes
    pos = (nstripes.astype(jnp.uint64) << u(5))
    for _ in range(3):
        active = pos + u(8) <= lens
        k = word64(starts + pos.astype(jnp.int32))
        h2 = _rotl64(h ^ (_rotl64(k * u(_XP2), 31, jnp) * u(_XP1)),
                     27, jnp) * u(_XP1) + u(_XP4)
        h = jnp.where(active, h2, h)
        pos = jnp.where(active, pos + u(8), pos)
    active = pos + u(4) <= lens
    k = word32(starts + pos.astype(jnp.int32))
    h2 = _rotl64(h ^ (k * u(_XP1)), 23, jnp) * u(_XP2) + u(_XP3)
    h = jnp.where(active, h2, h)
    pos = jnp.where(active, pos + u(4), pos)
    for _ in range(3):
        active = pos < lens
        b = get_byte(starts + pos.astype(jnp.int32))
        h2 = _rotl64(h ^ (b * u(_XP5)), 11, jnp) * u(_XP1)
        h = jnp.where(active, h2, h)
        pos = jnp.where(active, pos + u(1), pos)
    return _xx_avalanche(h, jnp)


def xxhash64_column_device(col: TpuColumnVector, seed) -> jax.Array:
    """One column's contribution; null rows keep the incoming seed."""
    if col.is_string_like:
        h = xxhash64_bytes_device_seeded(col.offsets, col.chars, seed)
    elif col.data is None:
        return seed
    else:
        h = _xx_fixed(col.data, col.dtype, seed, jnp)
    return jnp.where(col.validity, h, seed)


def xxhash64_columns_device(cols: Sequence[TpuColumnVector]) -> jax.Array:
    n = cols[0].capacity if cols else 0
    h = jnp.full((n,), XXSEED, jnp.uint64)
    for c in cols:
        h = xxhash64_column_device(c, h)
    return jax.lax.bitcast_convert_type(h, jnp.int64)


def _xx_bytes_np(b: bytes, seed: int) -> int:
    M = (1 << 64) - 1

    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & M
    ln = len(b)
    if ln >= 32:
        a1 = (seed + _XP1 + _XP2) & M
        a2 = (seed + _XP2) & M
        a3 = seed & M
        a4 = (seed - _XP1) & M
        s = 0
        while s + 32 <= ln:
            for i, acc in enumerate((a1, a2, a3, a4)):
                k = int.from_bytes(b[s + 8 * i: s + 8 * i + 8], "little")
                acc = (acc + k * _XP2) & M
                acc = (rotl(acc, 31) * _XP1) & M
                if i == 0:
                    a1 = acc
                elif i == 1:
                    a2 = acc
                elif i == 2:
                    a3 = acc
                else:
                    a4 = acc
            s += 32
        h = (rotl(a1, 1) + rotl(a2, 7) + rotl(a3, 12) + rotl(a4, 18)) & M
        for acc in (a1, a2, a3, a4):
            k = (rotl((acc * _XP2) & M, 31) * _XP1) & M
            h = (((h ^ k) * _XP1) + _XP4) & M
        pos = (ln // 32) * 32
    else:
        h = (seed + _XP5) & M
        pos = 0
    h = (h + ln) & M
    while pos + 8 <= ln:
        k = int.from_bytes(b[pos: pos + 8], "little")
        h = (rotl(h ^ ((rotl((k * _XP2) & M, 31) * _XP1) & M), 27)
             * _XP1 + _XP4) & M
        pos += 8
    if pos + 4 <= ln:
        k = int.from_bytes(b[pos: pos + 4], "little")
        h = ((rotl(h ^ ((k * _XP1) & M), 23) * _XP2) + _XP3) & M
        pos += 4
    while pos < ln:
        h = (rotl(h ^ ((b[pos] * _XP5) & M), 11) * _XP1) & M
        pos += 1
    h ^= h >> 33
    h = (h * _XP2) & M
    h ^= h >> 29
    h = (h * _XP3) & M
    h ^= h >> 32
    return h


def _xx_scalar_np(v, t: dt.DataType, seed: int) -> int:
    import datetime as _dtm
    import decimal as _dec
    M = (1 << 64) - 1

    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & M

    def avalanche(h):
        h ^= h >> 33
        h = (h * _XP2) & M
        h ^= h >> 29
        h = (h * _XP3) & M
        return h ^ (h >> 32)

    def hash_int(i32):
        h = (seed + _XP5 + 4) & M
        h = h ^ ((i32 & 0xFFFFFFFF) * _XP1) & M
        h = ((rotl(h, 23) * _XP2) + _XP3) & M
        return avalanche(h)

    def hash_long(l64):
        l64 &= M
        h = (seed + _XP5 + 8) & M
        h = h ^ ((rotl((l64 * _XP2) & M, 31) * _XP1) & M)
        h = ((rotl(h, 27) * _XP1) + _XP4) & M
        return avalanche(h)

    if isinstance(t, dt.BooleanType):
        return hash_int(1 if v else 0)
    if isinstance(t, (dt.ByteType, dt.ShortType, dt.IntegerType)):
        return hash_int(int(v) & 0xFFFFFFFF)
    if isinstance(t, dt.DateType):
        days = (v - _dtm.date(1970, 1, 1)).days \
            if isinstance(v, _dtm.date) else int(v)
        return hash_int(days & 0xFFFFFFFF)
    if isinstance(t, (dt.LongType, dt.TimestampType, dt.DecimalType)):
        if isinstance(t, dt.TimestampType) and isinstance(v, _dtm.datetime):
            if v.tzinfo is None:
                v = v.replace(tzinfo=_dtm.timezone.utc)
            epoch = _dtm.datetime(1970, 1, 1, tzinfo=_dtm.timezone.utc)
            v = (v - epoch) // _dtm.timedelta(microseconds=1)
        elif isinstance(t, dt.DecimalType):
            v = int(_dec.Decimal(v).scaleb(t.scale))
        return hash_long(int(v))
    if isinstance(t, dt.FloatType):
        f = np.float32(0.0) if v == 0 else np.float32(v)
        if np.isnan(f):
            f = np.float32(np.nan)
        return hash_int(int(f.view(np.uint32)))
    if isinstance(t, dt.DoubleType):
        f = np.float64(0.0) if v == 0 else np.float64(v)
        if np.isnan(f):
            f = np.float64(np.nan)
        return hash_long(int(f.view(np.uint64)))
    raise NotImplementedError(t.simple_string())


def xxhash64_columns_numpy(arrays, types: Sequence[dt.DataType],
                           n: int) -> np.ndarray:
    """Host oracle: running-seed xxhash64 over pyarrow arrays."""
    h = [XXSEED] * n
    for arr, t in zip(arrays, types):
        vals = arr.to_pylist()
        for i in range(n):
            v = vals[i]
            if v is None:
                continue
            if isinstance(t, (dt.StringType, dt.BinaryType)):
                b = v.encode() if isinstance(v, str) else bytes(v)
                h[i] = _xx_bytes_np(b, h[i])
            else:
                h[i] = _xx_scalar_np(v, t, h[i])
    out = np.array([x & ((1 << 64) - 1) for x in h], np.uint64)
    return out.view(np.int64)
