"""Device kernel library (Pallas/XLA) — the TPU replacement for libcudf's
CUDA kernels (SURVEY.md §2.2-E)."""
