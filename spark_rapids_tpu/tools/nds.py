"""NDS subset: TPC-DS-shaped query corpus over a generated star schema.

Reference: the integration_tests NDS/TPC-DS job definitions + the
NDS SF3K benchmark suite (SURVEY.md §6, :215; reference mount empty).
A full NDS run needs a SQL frontend; this subset re-expresses twelve
representative query SHAPES — date-dim filter joins over store_sales
(q3/q42/q52/q55), multi-join averages (q7), count-distinct-ish multi
filters (q96), cross-period customer semi/anti (q97 flavor), string
LIKE category scans, percentile and pivot reports — through the
`TpuSession` DataFrame API, each paired with a pandas oracle that is
also the HOST BASELINE the driver-facing geomean compares against
(pandas merge/groupby is the strongest commonly-available single-node
host engine for these shapes).

Used by tests (dual-run correctness, tests/test_nds.py) and bench.py
(`nds_subset_geomean_vs_host`).
"""
from __future__ import annotations

import numpy as np
import pyarrow as pa

__all__ = ["gen_tables", "QUERIES", "SQL_QUERIES", "build_query",
           "build_query_sql", "pandas_oracle", "register_frames"]


def gen_tables(n_sales: int = 1 << 15, seed: int = 42):
    """Star schema as pyarrow tables (deterministic)."""
    rng = np.random.default_rng(seed)
    n_dates = 730  # two years
    n_items = max(200, n_sales // 128)
    n_cust = max(500, n_sales // 64)
    n_stores = 25

    date_dim = pa.table({
        "d_date_sk": pa.array(np.arange(n_dates, dtype=np.int64)),
        "d_year": pa.array((2000 + np.arange(n_dates) // 365)
                           .astype(np.int32)),
        "d_moy": pa.array(((np.arange(n_dates) % 365) // 31 + 1)
                          .clip(1, 12).astype(np.int32)),
        "d_qoy": pa.array((((np.arange(n_dates) % 365) // 92) + 1)
                          .clip(1, 4).astype(np.int32)),
    })
    item = pa.table({
        "i_item_sk": pa.array(np.arange(n_items, dtype=np.int64)),
        "i_brand_id": pa.array(rng.integers(1, 60, n_items)
                               .astype(np.int32)),
        "i_category_id": pa.array(rng.integers(1, 11, n_items)
                                  .astype(np.int32)),
        "i_manufact_id": pa.array(rng.integers(1, 100, n_items)
                                  .astype(np.int32)),
        "i_category": pa.array(rng.choice(
            ["Electronics", "Home", "Sports", "Books", "Music",
             "Jewelry"], n_items).tolist()),
        "i_current_price": pa.array(rng.uniform(0.5, 300, n_items)
                                    .astype(np.float64)),
    })
    store = pa.table({
        "s_store_sk": pa.array(np.arange(n_stores, dtype=np.int64)),
        "s_state": pa.array(rng.choice(["CA", "TX", "NY", "WA", "TN"],
                                       n_stores).tolist()),
    })
    customer = pa.table({
        "c_customer_sk": pa.array(np.arange(n_cust, dtype=np.int64)),
        "c_birth_year": pa.array(rng.integers(1930, 2005, n_cust)
                                 .astype(np.int32)),
    })
    qty = rng.integers(1, 100, n_sales).astype(np.int32)
    price = rng.uniform(1, 200, n_sales).astype(np.float64)
    store_sales = pa.table({
        "ss_sold_date_sk": pa.array(rng.integers(0, n_dates, n_sales)
                                    .astype(np.int64)),
        "ss_item_sk": pa.array(rng.integers(0, n_items, n_sales)
                               .astype(np.int64)),
        "ss_customer_sk": pa.array(rng.integers(0, n_cust, n_sales)
                                   .astype(np.int64)),
        "ss_store_sk": pa.array(rng.integers(0, n_stores, n_sales)
                                .astype(np.int64)),
        "ss_quantity": pa.array(qty),
        "ss_sales_price": pa.array(price),
        "ss_ext_sales_price": pa.array((qty * price).astype(np.float64)),
        "ss_net_profit": pa.array(rng.normal(5, 40, n_sales)
                                  .astype(np.float64)),
    })
    return {"store_sales": store_sales, "date_dim": date_dim,
            "item": item, "store": store, "customer": customer}


# --- query builders (session DataFrames) ----------------------------------

def register_frames(session, frames):
    """Expose corpus frames as SQL temp views (session catalog) — the
    SQL texts in SQL_QUERIES resolve table names through these. Bench
    harnesses that re-wrap frames (e.g. .cache()) re-register so the
    SQL path sees the same cached inputs the hand-built path does."""
    for k, df in frames.items():
        session.register_table(k, df)


def _frames(session, tables):
    """Session-memoized DataFrames for the corpus tables: repeated
    query builds share one frame per table, so bench harnesses can
    .cache() them once (device-resident inputs, matching the pandas
    baseline's in-memory tables)."""
    memo = getattr(session, "_nds_frames", None)
    if memo is not None and memo[0] is tables:
        return memo[1]
    f = {k: session.create_dataframe(t) for k, t in tables.items()}
    session._nds_frames = (tables, f)
    register_frames(session, f)
    return f


def _col(name):
    from ..expr import UnresolvedColumn
    return UnresolvedColumn(name)


def _alias(e, n):
    from ..expr.base import Alias
    return Alias(e, n)


def _lit(v):
    from ..expr.base import Literal
    from .. import datatypes as dt_
    if isinstance(v, bool):
        return Literal(v, dt_.BOOL)
    if isinstance(v, (int, np.integer)):
        return Literal(int(v), dt_.INT32)
    if isinstance(v, float):
        return Literal(v, dt_.FLOAT64)
    return Literal(v, dt_.STRING)


def _cmp(kind, name, v):
    from ..expr.predicates import (EqualTo, GreaterThan,
                                   GreaterThanOrEqual, LessThan,
                                   LessThanOrEqual)
    ops = {"==": EqualTo, ">": GreaterThan, ">=": GreaterThanOrEqual,
           "<": LessThan, "<=": LessThanOrEqual}
    return ops[kind](_col(name), _lit(v))


def q3(session, t):
    """q3 shape: brand revenue in November by year."""
    from ..expr.aggregates import Sum
    f = _frames(session, t)
    dd = f["date_dim"].filter(_cmp("==", "d_moy", 11)) \
        .select(_col("d_date_sk"), _col("d_year"))
    it = f["item"].select(_col("i_item_sk"), _col("i_brand_id"))
    df = (f["store_sales"]
          .select(_col("ss_sold_date_sk"), _col("ss_item_sk"),
                  _col("ss_ext_sales_price"))
          .join(dd, on=[("ss_sold_date_sk", "d_date_sk")], build_unique=True)
          .join(it, on=[("ss_item_sk", "i_item_sk")], build_unique=True)
          .group_by("d_year", "i_brand_id")
          .agg(_alias(Sum(_col("ss_ext_sales_price")), "sum_agg"))
          .order_by("d_year", "sum_agg", "i_brand_id",
                    ascending=[True, False, True])
          .limit(10))
    return df


def q3_pd(pd, t):
    ss, dd, it = t["store_sales"], t["date_dim"], t["item"]
    j = ss.merge(dd[dd.d_moy == 11], left_on="ss_sold_date_sk",
                 right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["d_year", "i_brand_id"], as_index=False) \
        .agg(sum_agg=("ss_ext_sales_price", "sum"))
    return g.sort_values(["d_year", "sum_agg", "i_brand_id"],
                         ascending=[True, False, True]).head(10)


def q42(session, t):
    """q42 shape: category revenue for one month of one year."""
    from ..expr.aggregates import Sum
    from ..expr.predicates import And
    f = _frames(session, t)
    dd = f["date_dim"].filter(And(_cmp("==", "d_moy", 12),
                                  _cmp("==", "d_year", 2000)))
    df = (f["store_sales"]
          .join(dd.select(_col("d_date_sk")),
                on=[("ss_sold_date_sk", "d_date_sk")], build_unique=True)
          .join(f["item"].select(_col("i_item_sk"), _col("i_category_id")),
                on=[("ss_item_sk", "i_item_sk")], build_unique=True)
          .group_by("i_category_id")
          .agg(_alias(Sum(_col("ss_ext_sales_price")), "s"))
          .order_by("s", "i_category_id", ascending=[False, True]))
    return df


def q42_pd(pd, t):
    ss, dd, it = t["store_sales"], t["date_dim"], t["item"]
    d = dd[(dd.d_moy == 12) & (dd.d_year == 2000)]
    j = ss.merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk") \
        .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby("i_category_id", as_index=False) \
        .agg(s=("ss_ext_sales_price", "sum"))
    return g.sort_values(["s", "i_category_id"],
                         ascending=[False, True])


def q55(session, t):
    """q55 shape: brand revenue for a manufacturer band."""
    from ..expr.aggregates import Sum
    from ..expr.predicates import And
    f = _frames(session, t)
    it = f["item"].filter(And(_cmp(">=", "i_manufact_id", 20),
                              _cmp("<", "i_manufact_id", 40))) \
        .select(_col("i_item_sk"), _col("i_brand_id"))
    df = (f["store_sales"]
          .select(_col("ss_item_sk"), _col("ss_ext_sales_price"))
          .join(it, on=[("ss_item_sk", "i_item_sk")], build_unique=True)
          .group_by("i_brand_id")
          .agg(_alias(Sum(_col("ss_ext_sales_price")), "rev"))
          .order_by("rev", "i_brand_id", ascending=[False, True])
          .limit(20))
    return df


def q55_pd(pd, t):
    ss, it = t["store_sales"], t["item"]
    i = it[(it.i_manufact_id >= 20) & (it.i_manufact_id < 40)]
    j = ss.merge(i, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby("i_brand_id", as_index=False) \
        .agg(rev=("ss_ext_sales_price", "sum"))
    return g.sort_values(["rev", "i_brand_id"],
                         ascending=[False, True]).head(20)


def q7(session, t):
    """q7 shape: per-item averages across joins."""
    from ..expr.aggregates import Average
    f = _frames(session, t)
    dd = f["date_dim"].filter(_cmp("==", "d_year", 2001))
    df = (f["store_sales"]
          .join(dd.select(_col("d_date_sk")),
                on=[("ss_sold_date_sk", "d_date_sk")], build_unique=True)
          .join(f["item"].select(_col("i_item_sk"), _col("i_category_id")),
                on=[("ss_item_sk", "i_item_sk")], build_unique=True)
          .group_by("i_category_id")
          .agg(_alias(Average(_col("ss_quantity")), "avg_q"),
               _alias(Average(_col("ss_sales_price")), "avg_p"))
          .order_by("i_category_id"))
    return df


def q7_pd(pd, t):
    ss, dd, it = t["store_sales"], t["date_dim"], t["item"]
    j = ss.merge(dd[dd.d_year == 2001], left_on="ss_sold_date_sk",
                 right_on="d_date_sk") \
        .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby("i_category_id", as_index=False).agg(
        avg_q=("ss_quantity", "mean"), avg_p=("ss_sales_price", "mean"))
    return g.sort_values("i_category_id")


def q96(session, t):
    """q96 shape: selective count through two dimension joins."""
    from ..expr.aggregates import Count
    from ..expr.predicates import And
    f = _frames(session, t)
    df = (f["store_sales"]
          .filter(And(_cmp(">=", "ss_quantity", 40),
                      _cmp("<=", "ss_quantity", 60)))
          .join(f["store"].select(_col("s_store_sk")),
                on=[("ss_store_sk", "s_store_sk")], build_unique=True)
          .join(f["date_dim"].filter(_cmp("==", "d_qoy", 2))
                .select(_col("d_date_sk")),
                on=[("ss_sold_date_sk", "d_date_sk")], build_unique=True)
          .group_by()
          .agg(_alias(Count(), "cnt")))
    return df


def q96_pd(pd, t):
    ss, st, dd = t["store_sales"], t["store"], t["date_dim"]
    j = ss[(ss.ss_quantity >= 40) & (ss.ss_quantity <= 60)]
    j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(dd[dd.d_qoy == 2], left_on="ss_sold_date_sk",
                right_on="d_date_sk")
    return pd.DataFrame({"cnt": [np.int64(len(j))]})


def q97(session, t):
    """q97 flavor: customers buying in H1, H2, both (semi/anti joins)."""
    from ..expr.aggregates import Count
    f = _frames(session, t)
    dd = f["date_dim"]
    h1 = f["store_sales"].join(dd.filter(_cmp("<=", "d_moy", 6)),
                               on=[("ss_sold_date_sk", "d_date_sk")], build_unique=True) \
        .select(_col("ss_customer_sk"))
    h2 = f["store_sales"].join(dd.filter(_cmp(">", "d_moy", 6)),
                               on=[("ss_sold_date_sk", "d_date_sk")], build_unique=True) \
        .select(_alias(_col("ss_customer_sk"), "c2"))
    both = h1.join(h2, on=[("ss_customer_sk", "c2")], how="semi") \
        .group_by().agg(_alias(Count(), "n_pairs"))
    return both


def q97_pd(pd, t):
    ss, dd = t["store_sales"], t["date_dim"]
    h1 = ss.merge(dd[dd.d_moy <= 6], left_on="ss_sold_date_sk",
                  right_on="d_date_sk")["ss_customer_sk"]
    h2 = set(ss.merge(dd[dd.d_moy > 6], left_on="ss_sold_date_sk",
                      right_on="d_date_sk")["ss_customer_sk"])
    n = int((h1.isin(h2)).sum())
    return pd.DataFrame({"n_pairs": [np.int64(n)]})


def q_like(session, t):
    """String-scan shape: LIKE over a category, revenue by state
    (exercises the device regex/LIKE path)."""
    from ..expr.aggregates import Sum
    from ..expr.strings import Like
    f = _frames(session, t)
    it = f["item"].filter(Like(_col("i_category"), "%o%s%"))
    df = (f["store_sales"]
          .join(it, on=[("ss_item_sk", "i_item_sk")], build_unique=True)
          .join(f["store"], on=[("ss_store_sk", "s_store_sk")], build_unique=True)
          .group_by("s_state")
          .agg(_alias(Sum(_col("ss_net_profit")), "profit"))
          .order_by("s_state"))
    return df


def q_like_pd(pd, t):
    ss, it, st = t["store_sales"], t["item"], t["store"]
    i = it[it.i_category.str.match(".*o.*s.*")]
    j = ss.merge(i, left_on="ss_item_sk", right_on="i_item_sk") \
        .merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    g = j.groupby("s_state", as_index=False) \
        .agg(profit=("ss_net_profit", "sum"))
    return g.sort_values("s_state")


def q_percentile(session, t):
    """Quantile-report shape: price percentiles per state."""
    from ..expr.aggregates import ApproxPercentile
    f = _frames(session, t)
    df = (f["store_sales"]
          .join(f["store"], on=[("ss_store_sk", "s_store_sk")], build_unique=True)
          .group_by("s_state")
          .agg(_alias(ApproxPercentile(_col("ss_sales_price"), 0.5),
                      "p50"))
          .order_by("s_state"))
    return df


def q_percentile_pd(pd, t):
    import math
    ss, st = t["store_sales"], t["store"]
    j = ss.merge(st, left_on="ss_store_sk", right_on="s_store_sk")

    def p50(v):
        v = np.sort(v.to_numpy())
        return v[min(max(math.ceil(0.5 * len(v)) - 1, 0), len(v) - 1)]
    g = j.groupby("s_state", as_index=False) \
        .agg(p50=("ss_sales_price", p50))
    return g.sort_values("s_state")


def q_pivot(session, t):
    """Pivot-report shape: yearly revenue by quarter columns."""
    from ..expr.aggregates import Sum
    f = _frames(session, t)
    df = (f["store_sales"]
          .join(f["date_dim"], on=[("ss_sold_date_sk", "d_date_sk")], build_unique=True)
          .group_by("d_year").pivot("d_qoy", [1, 2, 3, 4])
          .agg(_alias(Sum(_col("ss_ext_sales_price")), "s"))
          .order_by("d_year"))
    return df


def q_pivot_pd(pd, t):
    ss, dd = t["store_sales"], t["date_dim"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    g = j.pivot_table(index="d_year", columns="d_qoy",
                      values="ss_ext_sales_price", aggfunc="sum")
    g = g.reindex(columns=[1, 2, 3, 4])
    g.columns = ["1", "2", "3", "4"]
    return g.reset_index().sort_values("d_year")


def q_customer_age(session, t):
    """Demographic-join shape: profit by buyer birth decade."""
    from ..expr.aggregates import Count, Sum
    from ..expr.arithmetic import IntegralDivide, Multiply
    from .. import datatypes as dt_
    from ..expr.base import Literal
    from ..expr import Cast
    f = _frames(session, t)
    decade = _alias(Multiply(
        IntegralDivide(Cast(_col("c_birth_year"), dt_.INT64),
                       Literal(10, dt_.INT64)),
        Literal(10, dt_.INT64)), "decade")
    cust = f["customer"].select(_col("c_customer_sk"), decade)
    df = (f["store_sales"]
          .join(cust, on=[("ss_customer_sk", "c_customer_sk")], build_unique=True)
          .group_by("decade")
          .agg(_alias(Sum(_col("ss_net_profit")), "profit"),
               _alias(Count(), "n"))
          .order_by("decade"))
    return df


def q_customer_age_pd(pd, t):
    ss, c = t["store_sales"], t["customer"]
    c = c.assign(decade=(c.c_birth_year.astype("int64") // 10) * 10)
    j = ss.merge(c, left_on="ss_customer_sk", right_on="c_customer_sk")
    g = j.groupby("decade", as_index=False).agg(
        profit=("ss_net_profit", "sum"), n=("ss_net_profit", "size"))
    return g.sort_values("decade")


def q_topn_profit(session, t):
    """TopN shape: most profitable items in a quarter."""
    from ..expr.aggregates import Sum
    f = _frames(session, t)
    df = (f["store_sales"]
          .join(f["date_dim"].filter(_cmp("==", "d_qoy", 4))
                .select(_col("d_date_sk")),
                on=[("ss_sold_date_sk", "d_date_sk")], build_unique=True)
          .group_by("ss_item_sk")
          .agg(_alias(Sum(_col("ss_net_profit")), "profit"))
          .order_by("profit", "ss_item_sk", ascending=[False, True])
          .limit(25))
    return df


def q_topn_profit_pd(pd, t):
    ss, dd = t["store_sales"], t["date_dim"]
    j = ss.merge(dd[dd.d_qoy == 4], left_on="ss_sold_date_sk",
                 right_on="d_date_sk")
    g = j.groupby("ss_item_sk", as_index=False) \
        .agg(profit=("ss_net_profit", "sum"))
    return g.sort_values(["profit", "ss_item_sk"],
                         ascending=[False, True]).head(25)


def q_price_band(session, t):
    """Case/filter shape: revenue by current-price band."""
    from ..expr.aggregates import Sum
    from ..expr.conditional import CaseWhen
    from ..expr.base import Literal
    from .. import datatypes as dt_
    f = _frames(session, t)
    band = _alias(CaseWhen(
        [(_cmp("<", "i_current_price", 10.0), Literal("low", dt_.STRING)),
         (_cmp("<", "i_current_price", 100.0), Literal("mid", dt_.STRING))],
        Literal("high", dt_.STRING)), "band")
    it = f["item"].select(_col("i_item_sk"), _col("i_current_price"))
    df = (f["store_sales"]
          .select(_col("ss_item_sk"), _col("ss_ext_sales_price"))
          .join(it, on=[("ss_item_sk", "i_item_sk")], build_unique=True)
          .select(_col("ss_ext_sales_price"), band)
          .group_by("band")
          .agg(_alias(Sum(_col("ss_ext_sales_price")), "rev"))
          .order_by("band"))
    return df


def q_price_band_pd(pd, t):
    ss, it = t["store_sales"], t["item"]
    band = np.where(it.i_current_price < 10.0, "low",
                    np.where(it.i_current_price < 100.0, "mid", "high"))
    i = it.assign(band=band)
    j = ss.merge(i, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby("band", as_index=False) \
        .agg(rev=("ss_ext_sales_price", "sum"))
    return g.sort_values("band")


def q_rank_in_category(session, t):
    """Windowed-rank shape (q67-like): top-3 brands per category by
    revenue — group-by -> RANK() OVER (PARTITION BY category ORDER BY
    revenue DESC) -> filter rank <= 3 (exercises the device window
    machine inside a corpus query)."""
    from ..exec.sort import SortOrder
    from ..exec.window import TpuWindowExec
    from ..expr import Rank, WindowExpression
    from ..expr.aggregates import Sum
    from ..expr.predicates import LessThanOrEqual
    from ..expr.base import Literal
    from ..session import DataFrame
    from .. import datatypes as dt
    f = _frames(session, t)
    base = (f["store_sales"]
            .join(f["item"], on=[("ss_item_sk", "i_item_sk")],
                  build_unique=True)
            .group_by("i_category", "i_brand_id")
            .agg(_alias(Sum(_col("ss_ext_sales_price")), "rev")))
    win = TpuWindowExec(
        [_alias(WindowExpression(
            Rank(), [_col("i_category")],
            [SortOrder(_col("rev"), ascending=False),
             SortOrder(_col("i_brand_id"))]), "rk")],
        base._node)
    return (DataFrame(win, session)
            .filter(LessThanOrEqual(_col("rk"), Literal(3, dt.INT32)))
            .order_by("i_category", "rk", "i_brand_id"))


def q_rank_in_category_pd(pd, t):
    ss, it = t["store_sales"], t["item"]
    j = ss.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["i_category", "i_brand_id"], as_index=False) \
        .agg(rev=("ss_ext_sales_price", "sum"))
    g = g.sort_values(["i_category", "rev", "i_brand_id"],
                      ascending=[True, False, True])
    # the engine ranks over the compound (rev DESC, brand ASC) key,
    # and (category, brand) is the group key, so ranks are distinct:
    # cumcount matches exactly even under revenue ties
    g["rk"] = (g.groupby("i_category").cumcount() + 1).astype("int32")
    g = g[g["rk"] <= 3]
    return g.sort_values(["i_category", "rk", "i_brand_id"]).reset_index(
        drop=True)


def q_rolling_revenue(session, t):
    """Rolling-window shape: per-store daily revenue with a trailing
    7-day RANGE average (exercises the round-5 literal-offset range
    frames inside a corpus query)."""
    from ..exec.sort import SortOrder
    from ..exec.window import TpuWindowExec
    from ..expr import WindowExpression, WindowFrame
    from ..expr.aggregates import Average, Sum
    from ..session import DataFrame
    from ..expr import Cast
    from .. import datatypes as dt
    f = _frames(session, t)
    daily = (f["store_sales"]
             .group_by("ss_store_sk", "ss_sold_date_sk")
             .agg(_alias(Sum(_col("ss_ext_sales_price")), "rev"))
             # the device range-frame path wants a <= 32-bit order
             # lane; date surrogate keys fit int32
             .with_column("d32", Cast(_col("ss_sold_date_sk"),
                                      dt.INT32)))
    win = TpuWindowExec(
        [_alias(WindowExpression(
            Average(_col("rev")), [_col("ss_store_sk")],
            [SortOrder(_col("d32"))],
            WindowFrame("range", -6, 0)), "avg7")],
        daily._node)
    return (DataFrame(win, session)
            .select(_col("ss_store_sk"), _col("ss_sold_date_sk"),
                    _col("rev"), _col("avg7"))
            .order_by("ss_store_sk", "ss_sold_date_sk"))


def q_rolling_revenue_pd(pd, t):
    ss = t["store_sales"]
    g = ss.groupby(["ss_store_sk", "ss_sold_date_sk"],
                   as_index=False).agg(rev=("ss_ext_sales_price", "sum"))

    def roll(sub):
        sub = sub.sort_values("ss_sold_date_sk").reset_index(drop=True)
        d = sub["ss_sold_date_sk"].to_numpy()
        r = sub["rev"].to_numpy()
        out = [r[(d >= d[i] - 6) & (d <= d[i])].mean()
               for i in range(len(sub))]
        sub["avg7"] = out
        return sub
    g = g.groupby("ss_store_sk", group_keys=False)[
        ["ss_store_sk", "ss_sold_date_sk", "rev"]].apply(roll)
    return g.sort_values(["ss_store_sk", "ss_sold_date_sk"]) \
        .reset_index(drop=True)


def q52(session, t):
    """q52 shape: brand revenue for one December (q3 cousin)."""
    from ..expr.aggregates import Sum
    from ..expr.predicates import And
    f = _frames(session, t)
    dd = f["date_dim"].filter(And(_cmp("==", "d_moy", 12),
                                  _cmp("==", "d_year", 2001))) \
        .select(_col("d_date_sk"), _col("d_year"))
    it = f["item"].select(_col("i_item_sk"), _col("i_brand_id"))
    df = (f["store_sales"]
          .join(dd, on=[("ss_sold_date_sk", "d_date_sk")], build_unique=True)
          .join(it, on=[("ss_item_sk", "i_item_sk")], build_unique=True)
          .group_by("d_year", "i_brand_id")
          .agg(_alias(Sum(_col("ss_ext_sales_price")), "ext_price"))
          .order_by("d_year", "ext_price", "i_brand_id",
                    ascending=[True, False, True])
          .limit(10))
    return df


def q52_pd(pd, t):
    ss, dd, it = t["store_sales"], t["date_dim"], t["item"]
    d = dd[(dd.d_moy == 12) & (dd.d_year == 2001)]
    j = ss.merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk") \
        .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["d_year", "i_brand_id"], as_index=False) \
        .agg(ext_price=("ss_ext_sales_price", "sum"))
    return g.sort_values(["d_year", "ext_price", "i_brand_id"],
                         ascending=[True, False, True]).head(10)


def q_cte(session, t):
    """CTE shape: year-over-year revenue via a twice-referenced
    year_rev CTE (expression join key d_year = prev + 1)."""
    from .. import datatypes as dt_
    from ..expr.aggregates import Sum
    from ..expr.arithmetic import Add
    from ..expr.base import Literal
    f = _frames(session, t)
    yr = (f["store_sales"]
          .join(f["date_dim"], on=[("ss_sold_date_sk", "d_date_sk")],
                build_unique=True)
          .group_by("d_year")
          .agg(_alias(Sum(_col("ss_ext_sales_price")), "rev")))
    prev = yr.select(_alias(_col("d_year"), "py"),
                     _alias(_col("rev"), "prev_rev"))
    df = (yr.join(prev, on=[(_col("d_year"),
                             Add(_col("py"), Literal(1, dt_.INT32)))])
          .select(_col("d_year"), _col("rev"), _col("prev_rev"))
          .order_by("d_year"))
    return df


def q_cte_pd(pd, t):
    ss, dd = t["store_sales"], t["date_dim"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    g = j.groupby("d_year", as_index=False) \
        .agg(rev=("ss_ext_sales_price", "sum"))
    p = g.rename(columns={"rev": "prev_rev"}).copy()
    p["jk"] = p["d_year"] + 1
    m = g.merge(p[["jk", "prev_rev"]], left_on="d_year", right_on="jk")
    return m[["d_year", "rev", "prev_rev"]].sort_values("d_year")


def q_union(session, t):
    """UNION ALL shape: per-state profit for two quarters stacked."""
    from ..expr.aggregates import Sum
    f = _frames(session, t)

    def half(q):
        return (f["store_sales"]
                .join(f["date_dim"].filter(_cmp("==", "d_qoy", q))
                      .select(_col("d_date_sk")),
                      on=[("ss_sold_date_sk", "d_date_sk")],
                      build_unique=True)
                .join(f["store"], on=[("ss_store_sk", "s_store_sk")],
                      build_unique=True)
                .group_by("s_state")
                .agg(_alias(Sum(_col("ss_net_profit")), "profit"))
                .select(_alias(_lit(q), "qtr"), _col("s_state"),
                        _col("profit")))

    return half(1).union(half(2)).order_by("qtr", "s_state")


def q_union_pd(pd, t):
    ss, dd, st = t["store_sales"], t["date_dim"], t["store"]

    def half(q):
        j = ss.merge(dd[dd.d_qoy == q], left_on="ss_sold_date_sk",
                     right_on="d_date_sk") \
            .merge(st, left_on="ss_store_sk", right_on="s_store_sk")
        g = j.groupby("s_state", as_index=False) \
            .agg(profit=("ss_net_profit", "sum"))
        g.insert(0, "qtr", np.int32(q))
        return g

    out = pd.concat([half(1), half(2)], ignore_index=True)
    return out.sort_values(["qtr", "s_state"])


def q_having(session, t):
    """HAVING shape: busy brands only (post-aggregation filter)."""
    from .. import datatypes as dt_
    from ..expr.aggregates import Count, Sum
    from ..expr.base import Literal
    from ..expr.predicates import GreaterThan
    f = _frames(session, t)
    it = f["item"].select(_col("i_item_sk"), _col("i_brand_id"))
    df = (f["store_sales"]
          .join(it, on=[("ss_item_sk", "i_item_sk")], build_unique=True)
          .group_by("i_brand_id")
          .agg(_alias(Count(), "n"),
               _alias(Sum(_col("ss_ext_sales_price")), "rev"))
          .filter(GreaterThan(_col("n"), Literal(250, dt_.INT64)))
          .order_by("i_brand_id"))
    return df


def q_having_pd(pd, t):
    ss, it = t["store_sales"], t["item"]
    j = ss.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby("i_brand_id", as_index=False).agg(
        n=("ss_ext_sales_price", "size"),
        rev=("ss_ext_sales_price", "sum"))
    g = g[g.n > 250]
    return g.sort_values("i_brand_id")


def q_in_between(session, t):
    """IN + BETWEEN shape: category revenue for a quantity band."""
    from ..expr.aggregates import Sum
    from ..expr.predicates import And, In
    f = _frames(session, t)
    it = f["item"].filter(In(_col("i_category"),
                             ("Books", "Music", "Sports")))
    df = (f["store_sales"]
          .filter(And(_cmp(">=", "ss_quantity", 20),
                      _cmp("<=", "ss_quantity", 40)))
          .join(f["date_dim"].filter(_cmp("==", "d_year", 2000))
                .select(_col("d_date_sk")),
                on=[("ss_sold_date_sk", "d_date_sk")], build_unique=True)
          .join(it, on=[("ss_item_sk", "i_item_sk")], build_unique=True)
          .group_by("i_category")
          .agg(_alias(Sum(_col("ss_ext_sales_price")), "rev"))
          .order_by("i_category"))
    return df


def q_in_between_pd(pd, t):
    ss, dd, it = t["store_sales"], t["date_dim"], t["item"]
    s = ss[(ss.ss_quantity >= 20) & (ss.ss_quantity <= 40)]
    j = s.merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk",
                right_on="d_date_sk")
    i = it[it.i_category.isin(["Books", "Music", "Sports"])]
    j = j.merge(i, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby("i_category", as_index=False) \
        .agg(rev=("ss_ext_sales_price", "sum"))
    return g.sort_values("i_category")


def q_agg_expr(session, t):
    """Expression-over-aggregates shape: bulk-order revenue share per
    state (sum(case)/sum)."""
    from .. import datatypes as dt_
    from ..expr.aggregates import Sum
    from ..expr.arithmetic import Divide
    from ..expr.base import Literal
    from ..expr.conditional import If
    from ..expr.predicates import GreaterThanOrEqual
    f = _frames(session, t)
    bulk = Sum(If(GreaterThanOrEqual(_col("ss_quantity"),
                                     Literal(50, dt_.INT32)),
                  _col("ss_ext_sales_price"),
                  Literal(0.0, dt_.FLOAT64)))
    df = (f["store_sales"]
          .join(f["store"], on=[("ss_store_sk", "s_store_sk")],
                build_unique=True)
          .group_by("s_state")
          .agg(_alias(bulk, "__b"),
               _alias(Sum(_col("ss_ext_sales_price")), "__t"))
          .select(_col("s_state"),
                  _alias(Divide(_col("__b"), _col("__t")), "bulk_share"))
          .order_by("s_state"))
    return df


def q_agg_expr_pd(pd, t):
    ss, st = t["store_sales"], t["store"]
    j = ss.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.assign(bulk=np.where(j.ss_quantity >= 50,
                               j.ss_ext_sales_price, 0.0))
    g = j.groupby("s_state", as_index=False).agg(
        b=("bulk", "sum"), tt=("ss_ext_sales_price", "sum"))
    g["bulk_share"] = g.b / g.tt
    return g[["s_state", "bulk_share"]].sort_values("s_state")


def q_rownum(session, t):
    """ROW_NUMBER shape: single best-selling item per category."""
    from .. import datatypes as dt_
    from ..exec.sort import SortOrder
    from ..exec.window import TpuWindowExec
    from ..expr import RowNumber, WindowExpression
    from ..expr.aggregates import Sum
    from ..expr.base import Literal
    from ..expr.predicates import EqualTo
    from ..session import DataFrame
    f = _frames(session, t)
    base = (f["store_sales"]
            .join(f["item"], on=[("ss_item_sk", "i_item_sk")],
                  build_unique=True)
            .group_by("i_category", "i_item_sk")
            .agg(_alias(Sum(_col("ss_ext_sales_price")), "rev")))
    win = TpuWindowExec(
        [_alias(WindowExpression(
            RowNumber(), [_col("i_category")],
            [SortOrder(_col("rev"), ascending=False),
             SortOrder(_col("i_item_sk"))]), "rn")],
        base._node)
    return (DataFrame(win, session)
            .filter(EqualTo(_col("rn"), Literal(1, dt_.INT32)))
            .select(_col("i_category"), _col("i_item_sk"), _col("rev"))
            .order_by("i_category"))


def q_rownum_pd(pd, t):
    ss, it = t["store_sales"], t["item"]
    j = ss.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["i_category", "i_item_sk"], as_index=False) \
        .agg(rev=("ss_ext_sales_price", "sum"))
    g = g.sort_values(["i_category", "rev", "i_item_sk"],
                      ascending=[True, False, True])
    top = g.groupby("i_category", group_keys=False).head(1)
    return top[["i_category", "i_item_sk", "rev"]] \
        .sort_values("i_category")


def q_not_or(session, t):
    """Precedence shape: NOT/OR month exclusion + profit filter."""
    from ..expr.aggregates import Count
    from ..expr.predicates import Not, Or
    f = _frames(session, t)
    dd = f["date_dim"].filter(Not(Or(_cmp("==", "d_moy", 1),
                                     _cmp("==", "d_moy", 12)))) \
        .select(_col("d_date_sk"), _col("d_year"))
    df = (f["store_sales"]
          .filter(_cmp(">", "ss_net_profit", 0.0))
          .join(dd, on=[("ss_sold_date_sk", "d_date_sk")],
                build_unique=True)
          .group_by("d_year")
          .agg(_alias(Count(), "n"))
          .order_by("d_year"))
    return df


def q_not_or_pd(pd, t):
    ss, dd = t["store_sales"], t["date_dim"]
    d = dd[~((dd.d_moy == 1) | (dd.d_moy == 12))]
    s = ss[ss.ss_net_profit > 0.0]
    j = s.merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk")
    g = j.groupby("d_year", as_index=False) \
        .agg(n=("d_date_sk", "size"))
    return g.sort_values("d_year")


QUERIES = {
    "q3": (q3, q3_pd), "q42": (q42, q42_pd), "q55": (q55, q55_pd),
    "q7": (q7, q7_pd), "q96": (q96, q96_pd), "q97": (q97, q97_pd),
    "q_like": (q_like, q_like_pd),
    "q_percentile": (q_percentile, q_percentile_pd),
    "q_pivot": (q_pivot, q_pivot_pd),
    "q_customer_age": (q_customer_age, q_customer_age_pd),
    "q_topn": (q_topn_profit, q_topn_profit_pd),
    "q_price_band": (q_price_band, q_price_band_pd),
    "q_rank": (q_rank_in_category, q_rank_in_category_pd),
    "q_rolling": (q_rolling_revenue, q_rolling_revenue_pd),
    "q52": (q52, q52_pd),
    "q_cte": (q_cte, q_cte_pd),
    "q_union": (q_union, q_union_pd),
    "q_having": (q_having, q_having_pd),
    "q_in_between": (q_in_between, q_in_between_pd),
    "q_agg_expr": (q_agg_expr, q_agg_expr_pd),
    "q_rownum": (q_rownum, q_rownum_pd),
    "q_not_or": (q_not_or, q_not_or_pd),
}


# --- SQL corpus ------------------------------------------------------------
# Every query re-expressed as REAL NDS-style SQL text (comma FROM
# lists, WHERE-clause join predicates, /*+ UNIQUE(...) */ hints where
# the hand-built plan passes build_unique=True). tests/test_sql_nds.py
# dual-runs each against its hand-built plan row-for-row; bench.py
# drives the corpus from these texts by default.

SQL_QUERIES = {
    "q3": """
SELECT /*+ UNIQUE(dt, item) */ dt.d_year, item.i_brand_id,
       SUM(ss_ext_sales_price) AS sum_agg
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = ss_sold_date_sk
  AND ss_item_sk = item.i_item_sk
  AND dt.d_moy = 11
GROUP BY dt.d_year, item.i_brand_id
ORDER BY dt.d_year, sum_agg DESC, i_brand_id
LIMIT 10
""",
    "q42": """
SELECT /*+ UNIQUE(dt, item) */ i_category_id,
       SUM(ss_ext_sales_price) AS s
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND dt.d_moy = 12 AND dt.d_year = 2000
GROUP BY i_category_id
ORDER BY s DESC, i_category_id
""",
    "q55": """
SELECT /*+ UNIQUE(item) */ i_brand_id,
       SUM(ss_ext_sales_price) AS rev
FROM store_sales, item
WHERE ss_item_sk = i_item_sk
  AND i_manufact_id >= 20 AND i_manufact_id < 40
GROUP BY i_brand_id
ORDER BY rev DESC, i_brand_id
LIMIT 20
""",
    "q7": """
SELECT /*+ UNIQUE(dt, item) */ i_category_id,
       AVG(ss_quantity) AS avg_q, AVG(ss_sales_price) AS avg_p
FROM store_sales, date_dim dt, item
WHERE ss_sold_date_sk = dt.d_date_sk
  AND ss_item_sk = i_item_sk
  AND dt.d_year = 2001
GROUP BY i_category_id
ORDER BY i_category_id
""",
    "q96": """
SELECT /*+ UNIQUE(store, date_dim) */ COUNT(*) AS cnt
FROM store_sales, store, date_dim
WHERE ss_quantity BETWEEN 40 AND 60
  AND ss_store_sk = s_store_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_qoy = 2
""",
    "q97": """
SELECT COUNT(*) AS n_pairs
FROM (SELECT /*+ UNIQUE(date_dim) */ ss_customer_sk
      FROM store_sales, date_dim
      WHERE ss_sold_date_sk = d_date_sk AND d_moy <= 6) h1
LEFT SEMI JOIN
     (SELECT /*+ UNIQUE(date_dim) */ ss_customer_sk AS c2
      FROM store_sales, date_dim
      WHERE ss_sold_date_sk = d_date_sk AND d_moy > 6) h2
ON h1.ss_customer_sk = h2.c2
""",
    "q_like": """
SELECT /*+ UNIQUE(item, store) */ s_state,
       SUM(ss_net_profit) AS profit
FROM store_sales, item, store
WHERE ss_item_sk = i_item_sk
  AND ss_store_sk = s_store_sk
  AND i_category LIKE '%o%s%'
GROUP BY s_state
ORDER BY s_state
""",
    "q_percentile": """
SELECT /*+ UNIQUE(store) */ s_state,
       APPROX_PERCENTILE(ss_sales_price, 0.5) AS p50
FROM store_sales, store
WHERE ss_store_sk = s_store_sk
GROUP BY s_state
ORDER BY s_state
""",
    "q_pivot": """
SELECT /*+ UNIQUE(date_dim) */ d_year,
       SUM(CASE WHEN d_qoy = 1 THEN ss_ext_sales_price END) AS "1",
       SUM(CASE WHEN d_qoy = 2 THEN ss_ext_sales_price END) AS "2",
       SUM(CASE WHEN d_qoy = 3 THEN ss_ext_sales_price END) AS "3",
       SUM(CASE WHEN d_qoy = 4 THEN ss_ext_sales_price END) AS "4"
FROM store_sales, date_dim
WHERE ss_sold_date_sk = d_date_sk
GROUP BY d_year
ORDER BY d_year
""",
    "q_customer_age": """
SELECT /*+ UNIQUE(cust) */ decade, SUM(ss_net_profit) AS profit,
       COUNT(*) AS n
FROM store_sales,
     (SELECT c_customer_sk,
             CAST(c_birth_year AS BIGINT) DIV 10 * 10 AS decade
      FROM customer) cust
WHERE ss_customer_sk = c_customer_sk
GROUP BY decade
ORDER BY decade
""",
    "q_topn": """
SELECT /*+ UNIQUE(date_dim) */ ss_item_sk,
       SUM(ss_net_profit) AS profit
FROM store_sales, date_dim
WHERE ss_sold_date_sk = d_date_sk AND d_qoy = 4
GROUP BY ss_item_sk
ORDER BY profit DESC, ss_item_sk
LIMIT 25
""",
    "q_price_band": """
SELECT /*+ UNIQUE(item) */
       CASE WHEN i_current_price < 10.0 THEN 'low'
            WHEN i_current_price < 100.0 THEN 'mid'
            ELSE 'high' END AS band,
       SUM(ss_ext_sales_price) AS rev
FROM store_sales, item
WHERE ss_item_sk = i_item_sk
GROUP BY band
ORDER BY band
""",
    "q_rank": """
SELECT i_category, i_brand_id, rev, rk
FROM (SELECT i_category, i_brand_id, rev,
             RANK() OVER (PARTITION BY i_category
                          ORDER BY rev DESC, i_brand_id) AS rk
      FROM (SELECT /*+ UNIQUE(item) */ i_category, i_brand_id,
                   SUM(ss_ext_sales_price) AS rev
            FROM store_sales, item
            WHERE ss_item_sk = i_item_sk
            GROUP BY i_category, i_brand_id) brand_rev) ranked
WHERE rk <= 3
ORDER BY i_category, rk, i_brand_id
""",
    "q_rolling": """
SELECT ss_store_sk, ss_sold_date_sk, rev,
       AVG(rev) OVER (PARTITION BY ss_store_sk ORDER BY d32
                      RANGE BETWEEN 6 PRECEDING AND CURRENT ROW)
       AS avg7
FROM (SELECT ss_store_sk, ss_sold_date_sk,
             SUM(ss_ext_sales_price) AS rev,
             CAST(ss_sold_date_sk AS INT) AS d32
      FROM store_sales
      GROUP BY ss_store_sk, ss_sold_date_sk) daily
ORDER BY ss_store_sk, ss_sold_date_sk
""",
    "q52": """
SELECT /*+ UNIQUE(dt, item) */ dt.d_year, item.i_brand_id,
       SUM(ss_ext_sales_price) AS ext_price
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = ss_sold_date_sk
  AND ss_item_sk = item.i_item_sk
  AND dt.d_moy = 12 AND dt.d_year = 2001
GROUP BY dt.d_year, item.i_brand_id
ORDER BY dt.d_year, ext_price DESC, i_brand_id
LIMIT 10
""",
    "q_cte": """
WITH year_rev AS (
  SELECT /*+ UNIQUE(date_dim) */ d_year,
         SUM(ss_ext_sales_price) AS rev
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk
  GROUP BY d_year)
SELECT a.d_year, a.rev, b.rev AS prev_rev
FROM year_rev a JOIN year_rev b ON a.d_year = b.d_year + 1
ORDER BY a.d_year
""",
    "q_union": """
SELECT /*+ UNIQUE(date_dim, store) */ 1 AS qtr, s_state,
       SUM(ss_net_profit) AS profit
FROM store_sales, date_dim, store
WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
  AND d_qoy = 1
GROUP BY s_state
UNION ALL
SELECT /*+ UNIQUE(date_dim, store) */ 2 AS qtr, s_state,
       SUM(ss_net_profit) AS profit
FROM store_sales, date_dim, store
WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
  AND d_qoy = 2
GROUP BY s_state
ORDER BY qtr, s_state
""",
    "q_having": """
SELECT /*+ UNIQUE(item) */ i_brand_id, COUNT(*) AS n,
       SUM(ss_ext_sales_price) AS rev
FROM store_sales, item
WHERE ss_item_sk = i_item_sk
GROUP BY i_brand_id
HAVING COUNT(*) > 250
ORDER BY i_brand_id
""",
    "q_in_between": """
SELECT /*+ UNIQUE(date_dim, item) */ i_category,
       SUM(ss_ext_sales_price) AS rev
FROM store_sales, date_dim, item
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_quantity BETWEEN 20 AND 40
  AND i_category IN ('Books', 'Music', 'Sports')
  AND d_year = 2000
GROUP BY i_category
ORDER BY i_category
""",
    "q_agg_expr": """
SELECT /*+ UNIQUE(store) */ s_state,
       SUM(CASE WHEN ss_quantity >= 50 THEN ss_ext_sales_price
                ELSE 0.0 END) / SUM(ss_ext_sales_price)
       AS bulk_share
FROM store_sales, store
WHERE ss_store_sk = s_store_sk
GROUP BY s_state
ORDER BY s_state
""",
    "q_rownum": """
SELECT i_category, i_item_sk, rev
FROM (SELECT i_category, i_item_sk, rev,
             ROW_NUMBER() OVER (PARTITION BY i_category
                                ORDER BY rev DESC, i_item_sk) AS rn
      FROM (SELECT /*+ UNIQUE(item) */ i_category, i_item_sk,
                   SUM(ss_ext_sales_price) AS rev
            FROM store_sales, item
            WHERE ss_item_sk = i_item_sk
            GROUP BY i_category, i_item_sk) t) ranked
WHERE rn = 1
ORDER BY i_category
""",
    "q_not_or": """
SELECT /*+ UNIQUE(date_dim) */ d_year, COUNT(*) AS n
FROM store_sales, date_dim
WHERE ss_sold_date_sk = d_date_sk
  AND NOT (d_moy = 1 OR d_moy = 12) AND ss_net_profit > 0.0
GROUP BY d_year
ORDER BY d_year
""",
}


def build_query(name: str, session, tables):
    return QUERIES[name][0](session, tables)


def build_query_sql(name: str, session, tables):
    """The SQL-text route to the same query: registers the corpus
    frames as temp views and compiles SQL_QUERIES[name] through
    ``session.sql`` — the path bench.py drives by default."""
    _frames(session, tables)
    return session.sql(SQL_QUERIES[name])


def pandas_frames(tables):
    """One-time arrow->pandas conversion (bench harnesses hoist this
    out of timed regions: the device side's cached frames paid their
    upload once too)."""
    return {k: v.to_pandas() for k, v in tables.items()}


def pandas_oracle(name: str, tables, pdt=None):
    import pandas as pd
    if pdt is None:
        pdt = pandas_frames(tables)
    return QUERIES[name][1](pd, pdt)
