"""UDF compiler: Python lambdas -> engine expression trees.

TPU analog of the reference's udf-compiler (JVM bytecode ->
Catalyst expressions — SURVEY.md §2.2-F; mount empty,
capability-built), built the Python-native way: instead of decompiling
bytecode, the UDF is traced SYMBOLICALLY — it runs once over operator-
overloading column proxies, and the operations it performs materialize
as the engine's own Expression nodes, which then run on the device like
any built-in expression (no per-row Python, no host fallback).

Covers the same UDF subset the reference's compiler targets: arithmetic
(+ - * / % **), comparisons, boolean logic (& | ~), conditionals via
`where(cond, a, b)`, abs/min/max, and math functions exposed on the
trace module. UDFs that branch on data (`if col > 0:`) or call
unsupported functions raise TypeError during tracing and the caller
falls back to a host UDF (spark.rapids.sql.udfCompiler.enabled).
"""
from __future__ import annotations

import math
from typing import Callable, List, Optional, Sequence

from .. import datatypes as dt
from ..expr import (Abs, Add, And, Divide, EqualTo, GreaterThan,
                    GreaterThanOrEqual, Greatest, If, IsNull, Least,
                    LessThan, LessThanOrEqual, Literal, Multiply, Not,
                    Or, Pmod, Pow, Remainder, Subtract, UnaryMinus)
from ..expr.base import Expression

__all__ = ["compile_udf", "TpuCompiledUDF", "trace_math"]


def _lift(v) -> Expression:
    if isinstance(v, SymbolicColumn):
        return v.expr
    if isinstance(v, Expression):
        return v
    return Literal(v)


def _coerce_pair(a: Expression, b: Expression, fractional=False):
    """Numeric widening so binary ops see equal types (the engine's
    expressions are constructed type-resolved, like post-analysis
    Catalyst). Unbound inputs (no dtype yet) pass through — bind the
    inputs via compile_udf's `schema` to get analyzer-grade casts."""
    from ..expr import Cast
    try:
        ta, tb = a.dtype, b.dtype
    except TypeError:
        return a, b
    t = dt.common_type(ta, tb)
    if fractional and dt.is_integral(t):
        t = dt.FLOAT64  # Spark `/` casts integral operands to double
    if ta != t:
        a = Cast(a, t)
    if tb != t:
        b = Cast(b, t)
    return a, b


class SymbolicColumn:
    """Operator-overloading proxy: applying Python operators builds the
    equivalent engine expression."""

    def __init__(self, expr: Expression):
        self.expr = expr

    # -- arithmetic --------------------------------------------------------
    def _bin(self, other, cls, swap=False):
        from ..expr import Divide
        a, b = self.expr, _lift(other)
        if swap:
            a, b = b, a
        a, b = _coerce_pair(a, b, fractional=cls is Divide)
        return SymbolicColumn(cls(a, b))

    def __add__(self, o):
        return self._bin(o, Add)

    def __radd__(self, o):
        return self._bin(o, Add, swap=True)

    def __sub__(self, o):
        return self._bin(o, Subtract)

    def __rsub__(self, o):
        return self._bin(o, Subtract, swap=True)

    def __mul__(self, o):
        return self._bin(o, Multiply)

    def __rmul__(self, o):
        return self._bin(o, Multiply, swap=True)

    def __truediv__(self, o):
        return self._bin(o, Divide)

    def __rtruediv__(self, o):
        return self._bin(o, Divide, swap=True)

    def __mod__(self, o):
        return self._bin(o, Pmod)

    def __pow__(self, o):
        return self._bin(o, Pow)

    def __neg__(self):
        return SymbolicColumn(UnaryMinus(self.expr))

    def __abs__(self):
        return SymbolicColumn(Abs(self.expr))

    # -- comparisons -------------------------------------------------------
    def __lt__(self, o):
        return self._bin(o, LessThan)

    def __le__(self, o):
        return self._bin(o, LessThanOrEqual)

    def __gt__(self, o):
        return self._bin(o, GreaterThan)

    def __ge__(self, o):
        return self._bin(o, GreaterThanOrEqual)

    def __eq__(self, o):  # noqa: D105 — symbolic, intentionally
        return self._bin(o, EqualTo)

    def __ne__(self, o):
        return SymbolicColumn(Not(self._bin(o, EqualTo).expr))

    # -- boolean -----------------------------------------------------------
    def __and__(self, o):
        return self._bin(o, And)

    def __rand__(self, o):
        return self._bin(o, And, swap=True)

    def __or__(self, o):
        return self._bin(o, Or)

    def __ror__(self, o):
        return self._bin(o, Or, swap=True)

    def __invert__(self):
        return SymbolicColumn(Not(self.expr))

    def is_null(self):
        return SymbolicColumn(IsNull(self.expr))

    # -- tracing guards ----------------------------------------------------
    def __bool__(self):
        raise TypeError(
            "data-dependent Python control flow (`if col:`) cannot be "
            "compiled; use trace_math.where(cond, a, b)")

    def __iter__(self):
        raise TypeError("cannot iterate a column inside a compiled UDF")

    def __hash__(self):
        return id(self)


class _TraceMath:
    """Math surface available inside compiled UDFs (`from
    spark_rapids_tpu.tools.udf_compiler import trace_math as m`)."""

    @staticmethod
    def where(cond, a, b):
        ae, be = _coerce_pair(_lift(a), _lift(b))
        return SymbolicColumn(If(_lift(cond), ae, be))

    @staticmethod
    def minimum(a, b):
        ae, be = _coerce_pair(_lift(a), _lift(b))
        return SymbolicColumn(Least(ae, be))

    @staticmethod
    def maximum(a, b):
        ae, be = _coerce_pair(_lift(a), _lift(b))
        return SymbolicColumn(Greatest(ae, be))

    def __getattr__(self, name):
        from .. import expr as E
        cls = {"sqrt": E.Sqrt, "exp": E.Exp, "log": E.Log,
               "log10": E.Log10, "log2": E.Log2, "sin": E.Sin,
               "cos": E.Cos, "tan": E.Tan, "floor": E.Floor,
               "ceil": E.Ceil, "abs": E.Abs}.get(name)
        if cls is None:
            raise TypeError(f"math function {name!r} not compilable")

        def apply(v):
            return SymbolicColumn(cls(_lift(v)))
        return apply


trace_math = _TraceMath()


class TpuCompiledUDF:
    """Result of a successful compile: the expression tree plus the
    original callable (kept for the CPU oracle / debugging)."""

    def __init__(self, expr: Expression, fn: Callable):
        self.expr = expr
        self.fn = fn

    def __repr__(self):
        return f"TpuCompiledUDF({self.expr!r})"


def compile_udf(fn: Callable, inputs: Sequence[Expression],
                schema: Optional[dt.Schema] = None,
                conf=None) -> Optional[TpuCompiledUDF]:
    """Trace `fn` over symbolic columns built from `inputs`. With a
    `schema`, inputs bind first so the trace inserts analyzer-grade
    numeric casts. Returns None when the UDF is not compilable
    (data-dependent branches, unsupported calls) — the caller keeps the
    host fallback, matching the reference compiler's opt-out."""
    from ..config import UDF_COMPILER_ENABLED, RapidsConf
    if not (conf or RapidsConf()).get(UDF_COMPILER_ENABLED):
        return None
    from ..expr.base import bind_expr
    if schema is not None:
        inputs = [bind_expr(e, schema) for e in inputs]
    args = [SymbolicColumn(e) for e in inputs]
    try:
        out = fn(*args)
    except TypeError:
        return None
    except Exception:
        return None
    if isinstance(out, SymbolicColumn):
        return TpuCompiledUDF(out.expr, fn)
    if isinstance(out, (int, float, bool, str)):
        return TpuCompiledUDF(Literal(out), fn)
    return None
