"""Profiling tool: post-run analysis of an executed plan.

TPU analog of the reference's profiling tool (SURVEY.md §2.2-F: mines
event logs for per-op times and tuning recommendations; mount empty,
capability-built). Here it mines the metrics the engine itself
accumulated during collect() — run with
spark.rapids.sql.metrics.level=DEBUG for real device times — and emits
the annotated plan plus ranked hotspots and recommendations.
"""
from __future__ import annotations

from typing import List, Optional

__all__ = ["profile_report", "profile_event_logs"]


def profile_report(pp, ctx=None) -> str:
    """`pp` is a PhysicalPlan whose collect() already ran (or pass the
    ExecCtx used)."""
    ctx = ctx or pp.last_ctx
    lines = ["=== TPU profile ===", pp.metrics_report(ctx)]
    if ctx is None:
        lines.append("(no metrics: run collect() first)")
        return "\n".join(lines)

    # ranked hotspots by opTime
    hot = []
    for label, ms in ctx.metrics.items():
        t = ms.get("opTime")
        if t is not None and t.value:
            hot.append((t.value, label))
    hot.sort(reverse=True)
    if hot:
        lines.append("hotspots:")
        total = sum(t for t, _ in hot) or 1.0
        for t, label in hot[:5]:
            lines.append(f"  {label:<28} {t * 1e3:9.2f}ms "
                         f"({t / total:.0%})")

    recs: List[str] = []
    if not ctx.sync_metrics:
        recs.append("set spark.rapids.sql.metrics.level=DEBUG for "
                    "device-time opTime (timings above are dispatch "
                    "cost only)")
    for label, ms in ctx.metrics.items():
        sp = ms.get("spillTime")
        if sp is not None and sp.value > 0.05:
            recs.append(f"{label}: {sp.value * 1e3:.0f}ms spilling — "
                        "raise spark.rapids.memory.device.budgetBytes "
                        "or reduce concurrency")
        up = ms.get("uploadTime")
        if up is not None and up.value > 0.5:
            recs.append(f"{label}: {up.value * 1e3:.0f}ms uploading — "
                        "keep data device-resident between stages")
    fb = pp.fallback_nodes()
    if fb:
        recs.append("CPU fallbacks present: " + ", ".join(sorted(set(fb)))
                    + " (see explain NOT_ON_GPU)")
    if recs:
        lines.append("recommendations:")
        lines.extend(f"  - {r}" for r in recs)
    return "\n".join(lines)


# --- event-log profiling (the reference tool's actual mode) ----------------
# The reference's ProfileMain mines event logs of ACCELERATED runs:
# op coverage, metric rollups, cross-run comparison, config
# recommendations (SURVEY.md:212). Same here over the engine's JSONL
# query events.

def profile_event_logs(path: str) -> str:
    import collections

    from .event_log import read_event_logs
    all_events = list(read_event_logs(path))
    sched_events = [ev for ev in all_events
                    if ev.get("type") == "scheduler"]
    events = [ev for ev in all_events if ev.get("type") != "scheduler"]
    lines = ["=== TPU profile (event logs) ===",
             f"events: {len(events)} query, {len(sched_events)} scheduler"]
    if not all_events:
        return "\n".join(lines + ["(no events under the given path)"])

    # op coverage across every logged plan
    op_total = collections.Counter()
    op_dev = collections.Counter()
    reason_count = collections.Counter()
    for ev in events:
        for n in ev.get("nodes", []):
            op_total[n["op"]] += 1
            if n["on_device"]:
                op_dev[n["op"]] += 1
            for r in n.get("reasons", []):
                reason_count[r] += 1
    lines.append("operator coverage:")
    for op, tot in op_total.most_common():
        lines.append(f"  {op:<28} {op_dev[op]}/{tot} on device")

    # metric rollups (opTime / spillTime / upload) by operator class
    roll = collections.defaultdict(float)
    for ev in events:
        for label, ms in ev.get("metrics", {}).items():
            op = label.split("#")[0]
            for mname in ("opTime", "spillTime", "uploadTime",
                          "scanTime"):
                v = ms.get(mname)
                if isinstance(v, (int, float)):
                    roll[(op, mname)] += float(v)
    hot = sorted(((v, k) for k, v in roll.items() if v > 0),
                 reverse=True)
    if hot:
        lines.append("metric rollups (summed across runs):")
        for v, (op, mname) in hot[:10]:
            lines.append(f"  {op:<28} {mname:<12} {v * 1e3:9.1f}ms")

    # cross-run regression: same plan fingerprint, wall-time spread
    by_fp = collections.defaultdict(list)
    for ev in events:
        by_fp[ev.get("fingerprint", "?")].append(ev.get("wall_s", 0.0))
    regressions = []
    for fp, walls in by_fp.items():
        if len(walls) >= 2 and min(walls) > 0 \
                and max(walls) / min(walls) > 1.5:
            regressions.append((max(walls) / min(walls), fp, walls))
    if regressions:
        regressions.sort(reverse=True)
        lines.append("wall-time spread across runs of the same query "
                     "(>1.5x):")
        for ratio, fp, walls in regressions[:5]:
            lines.append(
                f"  {fp}  {min(walls) * 1e3:.1f}ms .. "
                f"{max(walls) * 1e3:.1f}ms  ({ratio:.1f}x)")

    # scheduler rollup: retry overhead next to the hotspots it hides in
    recs = []
    if sched_events:
        tot = collections.Counter()
        retry_overhead = 0.0
        cluster_wall = 0.0
        for ev in sched_events:
            s = ev.get("summary", {})
            for k in ("tasks_ok", "failures", "speculative_launched",
                      "speculative_lost", "workers_respawned",
                      "workers_blacklisted"):
                tot[k] += int(s.get(k, 0))
            retry_overhead += float(s.get("retry_overhead_s", 0.0))
            cluster_wall += float(ev.get("wall_s", 0.0))
        lines.append("scheduler (cluster queries):")
        lines.append(f"  tasks ok {tot['tasks_ok']}, failed attempts "
                     f"{tot['failures']}, speculative launched "
                     f"{tot['speculative_launched']} "
                     f"(lost {tot['speculative_lost']})")
        lines.append(f"  workers respawned {tot['workers_respawned']}, "
                     f"blacklisted {tot['workers_blacklisted']}")
        lines.append(f"  retry overhead {retry_overhead * 1e3:.1f}ms "
                     f"of {cluster_wall * 1e3:.1f}ms cluster wall")
        if cluster_wall > 0 and retry_overhead > 0.1 * cluster_wall:
            recs.append(
                f"{retry_overhead / max(cluster_wall, 1e-9):.0%} of "
                "cluster wall went to failed/duplicate attempts — "
                "check worker stability before tuning kernels")
    spill_total = sum(v for (op, m), v in roll.items()
                      if m == "spillTime")
    if spill_total > 0.1:
        recs.append(f"{spill_total * 1e3:.0f}ms total spill — raise "
                    "the device memory budget or lower concurrency")
    if reason_count:
        top = reason_count.most_common(1)[0]
        recs.append(f"most common fallback ({top[1]}x): {top[0]}")
    if recs:
        lines.append("recommendations:")
        lines.extend(f"  - {r}" for r in recs)
    return "\n".join(lines)


def _main(argv):
    import sys
    if not argv:
        print("usage: python -m spark_rapids_tpu.tools.profiling "
              "<event-log dir>", file=sys.stderr)
        return 2
    print(profile_event_logs(argv[0]))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(_main(sys.argv[1:]))
