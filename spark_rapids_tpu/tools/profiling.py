"""Profiling tool: post-run analysis of an executed plan.

TPU analog of the reference's profiling tool (SURVEY.md §2.2-F: mines
event logs for per-op times and tuning recommendations; mount empty,
capability-built). Here it mines the metrics the engine itself
accumulated during collect() — run with
spark.rapids.sql.metrics.level=DEBUG for real device times — and emits
the annotated plan plus ranked hotspots and recommendations.
"""
from __future__ import annotations

from typing import List, Optional

__all__ = ["profile_report"]


def profile_report(pp, ctx=None) -> str:
    """`pp` is a PhysicalPlan whose collect() already ran (or pass the
    ExecCtx used)."""
    ctx = ctx or pp.last_ctx
    lines = ["=== TPU profile ===", pp.metrics_report(ctx)]
    if ctx is None:
        lines.append("(no metrics: run collect() first)")
        return "\n".join(lines)

    # ranked hotspots by opTime
    hot = []
    for label, ms in ctx.metrics.items():
        t = ms.get("opTime")
        if t is not None and t.value:
            hot.append((t.value, label))
    hot.sort(reverse=True)
    if hot:
        lines.append("hotspots:")
        total = sum(t for t, _ in hot) or 1.0
        for t, label in hot[:5]:
            lines.append(f"  {label:<28} {t * 1e3:9.2f}ms "
                         f"({t / total:.0%})")

    recs: List[str] = []
    if not ctx.sync_metrics:
        recs.append("set spark.rapids.sql.metrics.level=DEBUG for "
                    "device-time opTime (timings above are dispatch "
                    "cost only)")
    for label, ms in ctx.metrics.items():
        sp = ms.get("spillTime")
        if sp is not None and sp.value > 0.05:
            recs.append(f"{label}: {sp.value * 1e3:.0f}ms spilling — "
                        "raise spark.rapids.memory.device.budgetBytes "
                        "or reduce concurrency")
        up = ms.get("uploadTime")
        if up is not None and up.value > 0.5:
            recs.append(f"{label}: {up.value * 1e3:.0f}ms uploading — "
                        "keep data device-resident between stages")
    fb = pp.fallback_nodes()
    if fb:
        recs.append("CPU fallbacks present: " + ", ".join(sorted(set(fb)))
                    + " (see explain NOT_ON_GPU)")
    if recs:
        lines.append("recommendations:")
        lines.extend(f"  - {r}" for r in recs)
    return "\n".join(lines)
