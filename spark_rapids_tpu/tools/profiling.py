"""Profiling tool: post-run analysis of an executed plan.

TPU analog of the reference's profiling tool (SURVEY.md §2.2-F: mines
event logs for per-op times and tuning recommendations; mount empty,
capability-built). Here it mines the metrics the engine itself
accumulated during collect() — run with
spark.rapids.sql.metrics.level=DEBUG for real device times — and emits
the annotated plan plus ranked hotspots and recommendations.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

__all__ = ["profile_report", "profile_event_logs", "critical_path",
           "profile_trace", "triage_report", "history_report",
           "compare_report"]


def profile_report(pp, ctx=None) -> str:
    """`pp` is a PhysicalPlan whose collect() already ran (or pass the
    ExecCtx used)."""
    ctx = ctx or pp.last_ctx
    lines = ["=== TPU profile ===", pp.metrics_report(ctx)]
    if ctx is None:
        lines.append("(no metrics: run collect() first)")
        return "\n".join(lines)

    # ranked hotspots by opTime, keyed on the stable operator-INSTANCE
    # id the planner stamps (obs/opmetrics.assign_op_ids): AQE
    # re-planning deep-copies reused sub-plans WITH their ids, so
    # duplicated instances accumulate into one metric row at the store
    # itself — the old name-based dedup across fresh #ids is gone, and
    # two distinct instances of the same operator class now rank
    # separately (per-instance attribution, like the reference UI)
    from ..obs.opmetrics import fold_snapshots
    folded = fold_snapshots([{"ops": {
        label: {name: m.value for name, m in ms.items()}
        for label, ms in ctx.metrics.items()}}])
    hot = sorted(((st["metrics"]["opTime"], st["label"])
                  for st in folded.values()
                  if st["metrics"].get("opTime")), reverse=True)
    if hot:
        lines.append("hotspots:")
        total = sum(t for t, _ in hot) or 1.0
        for t, label in hot[:5]:
            lines.append(f"  {label:<28} {t * 1e3:9.2f}ms "
                         f"({t / total:.0%})")

    recs: List[str] = []
    if not ctx.sync_metrics:
        recs.append("set spark.rapids.sql.metrics.level=DEBUG for "
                    "device-time opTime (timings above are dispatch "
                    "cost only)")
    for label, ms in ctx.metrics.items():
        sp = ms.get("spillTime")
        if sp is not None and sp.value > 0.05:
            recs.append(f"{label}: {sp.value * 1e3:.0f}ms spilling — "
                        "raise spark.rapids.memory.device.budgetBytes "
                        "or reduce concurrency")
        asm = ms.get("assembleTime")
        if asm is not None and asm.value > 0.5:
            recs.append(f"{label}: {asm.value * 1e3:.0f}ms assembling "
                        "host blobs — raise "
                        "spark.rapids.sql.scan.uploadThreads or the "
                        "reader pool size")
        up = ms.get("uploadTime")
        if up is not None and up.value > 0.5:
            wait = ms.get("uploadWaitTime")
            scan_v = ms.get("scanTime")
            scan_v = scan_v.value if scan_v is not None else 0.0
            if wait is not None and up.value > 0:
                # uploadWaitTime is ALL consumer blocking on the next
                # batch — when planning (scanTime) outweighs uploadTime
                # the feeder was starved by the reader pool, not the
                # tunnel, and uploadThreads is the wrong lever
                hidden = max(0.0, 1.0 - wait.value / up.value)
                if hidden >= 0.5:
                    lever = "keep data device-resident between stages"
                elif scan_v > up.value:
                    lever = ("the wait is planning-bound — raise the "
                             "parquet multiThreadedRead.numThreads "
                             "reader pool, not uploadThreads")
                else:
                    lever = ("raise spark.rapids.sql.scan.uploadThreads"
                             " / inFlightBatches to overlap more of it")
                recs.append(
                    f"{label}: {up.value * 1e3:.0f}ms uploading, "
                    f"~{hidden:.0%} hidden behind compute — " + lever)
            else:
                recs.append(f"{label}: {up.value * 1e3:.0f}ms uploading "
                            "— keep data device-resident between stages")
    fb = pp.fallback_nodes()
    if fb:
        recs.append("CPU fallbacks present: " + ", ".join(sorted(set(fb)))
                    + " (see explain NOT_ON_GPU)")
    if recs:
        lines.append("recommendations:")
        lines.extend(f"  - {r}" for r in recs)
    return "\n".join(lines)


# --- event-log profiling (the reference tool's actual mode) ----------------
# The reference's ProfileMain mines event logs of ACCELERATED runs:
# op coverage, metric rollups, cross-run comparison, config
# recommendations (SURVEY.md:212). Same here over the engine's JSONL
# query events.

def profile_event_logs(path: str) -> str:
    import collections

    from .event_log import read_event_logs
    all_events = list(read_event_logs(path))
    sched_events = [ev for ev in all_events
                    if ev.get("type") == "scheduler"]
    events = [ev for ev in all_events if ev.get("type") != "scheduler"]
    lines = ["=== TPU profile (event logs) ===",
             f"events: {len(events)} query, {len(sched_events)} scheduler"]
    if not all_events:
        return "\n".join(lines + ["(no events under the given path)"])

    # op coverage across every logged plan
    op_total = collections.Counter()
    op_dev = collections.Counter()
    reason_count = collections.Counter()
    for ev in events:
        for n in ev.get("nodes", []):
            op_total[n["op"]] += 1
            if n["on_device"]:
                op_dev[n["op"]] += 1
            for r in n.get("reasons", []):
                reason_count[r] += 1
    lines.append("operator coverage:")
    for op, tot in op_total.most_common():
        lines.append(f"  {op:<28} {op_dev[op]}/{tot} on device")

    # metric rollups (opTime / spillTime / upload) by operator class
    roll = collections.defaultdict(float)
    for ev in events:
        for label, ms in ev.get("metrics", {}).items():
            op = label.split("#")[0]
            for mname in ("opTime", "spillTime", "uploadTime",
                          "assembleTime", "uploadWaitTime", "scanTime"):
                v = ms.get(mname)
                if isinstance(v, (int, float)):
                    roll[(op, mname)] += float(v)
    hot = sorted(((v, k) for k, v in roll.items() if v > 0),
                 reverse=True)
    if hot:
        lines.append("metric rollups (summed across runs):")
        for v, (op, mname) in hot[:10]:
            lines.append(f"  {op:<28} {mname:<12} {v * 1e3:9.1f}ms")

    # cross-run regression: same plan fingerprint, wall-time spread
    by_fp = collections.defaultdict(list)
    for ev in events:
        by_fp[ev.get("fingerprint", "?")].append(ev.get("wall_s", 0.0))
    regressions = []
    for fp, walls in by_fp.items():
        if len(walls) >= 2 and min(walls) > 0 \
                and max(walls) / min(walls) > 1.5:
            regressions.append((max(walls) / min(walls), fp, walls))
    if regressions:
        regressions.sort(reverse=True)
        lines.append("wall-time spread across runs of the same query "
                     "(>1.5x):")
        for ratio, fp, walls in regressions[:5]:
            lines.append(
                f"  {fp}  {min(walls) * 1e3:.1f}ms .. "
                f"{max(walls) * 1e3:.1f}ms  ({ratio:.1f}x)")

    # scheduler rollup: retry overhead next to the hotspots it hides in
    recs = []
    if sched_events:
        tot = collections.Counter()
        retry_overhead = 0.0
        cluster_wall = 0.0
        for ev in sched_events:
            s = ev.get("summary", {})
            for k in ("tasks_ok", "failures", "speculative_launched",
                      "speculative_lost", "workers_respawned",
                      "workers_blacklisted", "fetch_failures",
                      "stage_reruns"):
                tot[k] += int(s.get(k, 0))
            retry_overhead += float(s.get("retry_overhead_s", 0.0))
            cluster_wall += float(ev.get("wall_s", 0.0))
        lines.append("scheduler (cluster queries):")
        lines.append(f"  tasks ok {tot['tasks_ok']}, failed attempts "
                     f"{tot['failures']}, speculative launched "
                     f"{tot['speculative_launched']} "
                     f"(lost {tot['speculative_lost']})")
        lines.append(f"  workers respawned {tot['workers_respawned']}, "
                     f"blacklisted {tot['workers_blacklisted']}")
        if tot["fetch_failures"] or tot["stage_reruns"]:
            lines.append(
                f"  shuffle fetch failures {tot['fetch_failures']}, "
                f"map-stage reruns {tot['stage_reruns']}")
        lines.append(f"  retry overhead {retry_overhead * 1e3:.1f}ms "
                     f"of {cluster_wall * 1e3:.1f}ms cluster wall")
        if cluster_wall > 0 and retry_overhead > 0.1 * cluster_wall:
            recs.append(
                f"{retry_overhead / max(cluster_wall, 1e-9):.0%} of "
                "cluster wall went to failed/duplicate attempts — "
                "check worker stability before tuning kernels")
        if tot["stage_reruns"]:
            recs.append(
                f"{tot['stage_reruns']} map-stage rerun(s) recovered "
                "lost/corrupt shuffle output — check the shuffle "
                "storage (disk, NFS) feeding the cluster root; "
                "`profiling triage <incident>` names the bad blocks")
    # trace rollups from embedded span summaries (queries that ran with
    # spark.rapids.trace.dir set; the full timeline is in the trace
    # JSON — `profiling <trace.json>` mines its critical path)
    tr_cats = collections.defaultdict(lambda: [0, 0.0])
    for ev in all_events:
        for cat, c in (ev.get("trace", {}).get("by_cat") or {}).items():
            tr_cats[cat][0] += int(c.get("spans", 0))
            tr_cats[cat][1] += float(c.get("total_s", 0.0))
    if tr_cats:
        lines.append("trace span rollup (by category):")
        for cat, (n, tot) in sorted(tr_cats.items(),
                                    key=lambda kv: -kv[1][1]):
            lines.append(f"  {cat:<12} {n:5d} spans {tot * 1e3:9.1f}ms")

    spill_total = sum(v for (op, m), v in roll.items()
                      if m == "spillTime")
    if spill_total > 0.1:
        recs.append(f"{spill_total * 1e3:.0f}ms total spill — raise "
                    "the device memory budget or lower concurrency")
    if reason_count:
        top = reason_count.most_common(1)[0]
        recs.append(f"most common fallback ({top[1]}x): {top[0]}")
    if recs:
        lines.append("recommendations:")
        lines.extend(f"  - {r}" for r in recs)
    return "\n".join(lines)


# --- critical-path analysis over a stitched trace ---------------------------
# The hotspot table answers "which operator burned the most device
# time"; the critical path answers the question a timeline viewer
# answers visually — WHAT was the wall time actually spent on, across
# processes: "62% of wall time is shuffle fetch wait on stage 2", or
# "the retry of q1s1m0 added 1.8s".

def critical_path(spans: List[dict]) -> List[dict]:
    """The longest parent->child chain through a span forest (dicts as
    produced by Tracer.drain / load_chrome_trace). Starting from the
    root span with the largest duration, descend into the child
    covering the most time, to a leaf. Each step reports its span
    fields plus ``self_s`` (duration not covered by the next step) and
    ``frac`` (self_s / root duration)."""
    children: Dict[str, List[dict]] = {}
    by_id = {}
    for s in spans:
        if s.get("span_id") is not None:
            by_id[s["span_id"]] = s
    for s in spans:
        p = s.get("parent_id")
        if p is not None and p in by_id:
            children.setdefault(p, []).append(s)
    roots = [s for s in spans
             if s.get("parent_id") not in by_id]
    if not roots:
        return []
    root = max(roots, key=lambda s: s.get("dur", 0.0))
    total = max(root.get("dur", 0.0), 1e-12)
    path = []
    node = root
    while node is not None:
        kids = children.get(node.get("span_id"), [])
        nxt = max(kids, key=lambda s: s.get("dur", 0.0)) if kids else None
        self_s = node.get("dur", 0.0) - (nxt.get("dur", 0.0) if nxt else 0)
        path.append(dict(node, self_s=max(self_s, 0.0),
                         frac=max(self_s, 0.0) / total))
        node = nxt
    return path


def format_critical_path(spans: List[dict]) -> List[str]:
    """Render the critical path plus the retry overhead it names."""
    path = critical_path(spans)
    if not path:
        return ["(no spans)"]
    total = max(path[0].get("dur", 0.0), 1e-12)
    lines = [f"critical path ({total * 1e3:.1f}ms wall):"]
    for depth, step in enumerate(path):
        where = "driver" if step.get("pid", 0) == 0 \
            else f"worker {step['pid'] - 1}"
        lines.append(
            f"  {'  ' * depth}{step['name']} [{step.get('cat', '?')}, "
            f"{where}]  {step['dur'] * 1e3:9.1f}ms  "
            f"self {step['self_s'] * 1e3:.1f}ms ({step['frac']:.0%})")
    top = max(path, key=lambda s: s["self_s"])
    lines.append(
        f"  => {top['frac']:.0%} of wall time is {top['name']} "
        f"({top.get('cat', '?')})")
    # name the retry overhead: attempt spans that ended err/lost are
    # pure waste the timeline hides inside stage spans
    wasted = [s for s in spans if s.get("cat") == "attempt"
              and (s.get("args") or {}).get("state") in ("err", "lost")]
    if wasted:
        w = sum(s.get("dur", 0.0) for s in wasted)
        names = sorted({s["name"] for s in wasted})
        lines.append(
            f"  retry overhead: {w * 1e3:.1f}ms "
            f"({w / total:.0%} of wall) across {len(wasted)} "
            f"failed/duplicate attempts: {', '.join(names[:5])}"
            + (" ..." if len(names) > 5 else ""))
    return lines


def profile_trace(path: str) -> str:
    """Mine one Chrome trace JSON (spark.rapids.trace.dir output):
    per-category rollup + the critical path."""
    import collections

    from ..obs.tracer import load_chrome_trace
    spans = load_chrome_trace(path)
    lines = [f"=== TPU trace profile ({path}) ===",
             f"spans: {len(spans)}"]
    if not spans:
        return "\n".join(lines)
    by_cat = collections.defaultdict(float)
    for s in spans:
        by_cat[s.get("cat", "?")] += s.get("dur", 0.0)
    lines.append("time by category (overlapping spans sum):")
    for cat, tot in sorted(by_cat.items(), key=lambda kv: -kv[1]):
        lines.append(f"  {cat:<12} {tot * 1e3:9.1f}ms")
    lines.extend(format_critical_path(spans))
    return "\n".join(lines)


# --- incident-bundle triage --------------------------------------------------
# The flight recorder (obs/recorder.py) dumps incident bundles when an
# anomaly fires; triage renders one for a human: what fired, the 30s of
# ring events preceding it per process, the HBM high-water curve, and
# per-stage straggler/attempt attribution.

_TRIAGE_WINDOW_S = 30.0


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TB"


def _fmt_ring_event(e: dict) -> str:
    kind = e.get("kind", "?")
    if kind == "sched":
        return (f"sched {e.get('event', '?')} {e.get('task', '')} "
                f"a{e.get('attempt', '?')} w{e.get('worker', '?')} "
                f"{e.get('reason', '')}").rstrip()
    if kind == "mem":
        ev = e.get("ev", "?")
        if ev in ("disk_pressure", "spill_read_failed",
                  "spill_write_failed"):
            return (f"mem SPILL-{ev.upper()} "
                    f"[{e.get('fail_kind', '?')}] "
                    f"{os.path.basename(e.get('path') or '')} "
                    f"{e.get('detail', '')}").rstrip()
        if ev == "spill_read_retry":
            return (f"mem spill-read-retry #{e.get('n', '?')} "
                    f"{e.get('error', '')}").rstrip()
        return (f"mem {ev} {_fmt_bytes(e.get('bytes', 0))} "
                f"(device {_fmt_bytes(e.get('device', 0))}, "
                f"host {_fmt_bytes(e.get('host', 0))})")
    if kind == "task":
        extra = e.get("error", "")
        return (f"task {e.get('ev', '?')} {e.get('task', '')} "
                f"a{e.get('attempt', '?')} {extra}").rstrip()
    if kind == "shuffle":
        ev = e.get("ev", "?")
        if ev == "fetch_failure":
            return (f"shuffle FETCH-FAILURE [{e.get('fail_kind', '?')}] "
                    f"s{e.get('sid', '?')} p{e.get('part', '?')} "
                    f"map {e.get('map', '?')} {e.get('path', '')}")
        if ev == "fetch_retry":
            return (f"shuffle fetch-retry #{e.get('n', '?')} "
                    f"s{e.get('sid', '?')} p{e.get('part', '?')} "
                    f"{e.get('error', '')}")
        return (f"shuffle {ev} s{e.get('sid', '?')} "
                f"p{e.get('part', '?')} wait "
                f"{e.get('wait_s', 0) * 1e3:.1f}ms")
    if kind == "span":
        return (f"span {e.get('name', '?')} [{e.get('cat', '?')}] "
                f"{e.get('dur', 0) * 1e3:.1f}ms")
    if kind == "plan":
        return (f"plan {e.get('n_fallbacks', 0)} CPU fallbacks "
                f"{e.get('fallbacks', '')}").rstrip()
    return f"{kind} {e}"


def _memory_curve(timeline: dict, width: int = 24) -> List[str]:
    """Text rendering of the HBM timeline: in-use device bytes after
    each transition, bar-scaled to the high-water mark. Every cluster
    process owns its own device runtime, so rows are labeled by
    process — occupancy values from different processes are separate
    series, not one curve."""
    evs = timeline.get("events") or []
    high = max(int(timeline.get("high_water_bytes", 0) or 0), 1)
    budget = int(timeline.get("budget_bytes", 0) or 0)
    lines = [f"  high water {_fmt_bytes(timeline.get('high_water_bytes', 0))}"
             + (f" of {_fmt_bytes(budget)} budget" if budget else "")
             + " (worst single process)"]
    for proc, p in sorted((timeline.get("per_proc") or {}).items()):
        if proc:
            lines.append(f"    {proc}: high water "
                         f"{_fmt_bytes(p.get('high_water_bytes', 0))}")
    if not evs:
        return lines + ["  (no memory-ledger transitions recorded)"]
    t_origin = evs[0].get("ts", 0.0)
    shown = evs if len(evs) <= 40 else evs[-40:]
    if len(evs) > 40:
        lines.append(f"  (last 40 of {len(evs)} transitions)")
    for e in shown:
        dev = int(e.get("device", 0) or 0)
        bar = "#" * max(0, round(width * dev / high))
        proc = e.get("proc", "")
        lines.append(
            f"  t+{e.get('ts', 0.0) - t_origin:7.3f}s "
            f"{(proc[:12] if proc else '-'):<12} "
            f"{_fmt_bytes(dev):>10} {e.get('ev', '?'):<10} {bar}")
    return lines


def triage_report(bundle) -> str:
    """Render one incident bundle (path or loaded dict) into a human
    report — the `triage` mode of this tool."""
    import json
    if isinstance(bundle, str):
        with open(bundle) as f:
            bundle = json.load(f)
    lines = [f"=== flight-recorder triage "
             f"({bundle.get('incident_id', '?')}) ===",
             f"query {bundle.get('query', '?')}"]

    anomalies = bundle.get("anomalies") or []
    lines.append(f"what fired ({len(anomalies)} anomal"
                 f"{'y' if len(anomalies) == 1 else 'ies'}):")
    for a in anomalies:
        where = a.get("proc", "?")
        w = a.get("worker", -1)
        if isinstance(w, int) and w >= 0:
            where += f" (worker {w})"
        lines.append(
            f"  [{a.get('kind', '?')}] {a.get('task', '')} "
            f"a{a.get('attempt', '?')} on {where}: "
            f"{(a.get('detail') or '').strip()[:160]}")
    if not anomalies:
        lines.append("  (none recorded — bundle written by hand?)")

    # the N seconds of ring events preceding the first trigger, per
    # process — the black-box playback
    t_fire = min((a.get("ts", 0.0) for a in anomalies),
                 default=bundle.get("ts", 0.0)) or bundle.get("ts", 0.0)
    lines.append(f"last {_TRIAGE_WINDOW_S:.0f}s before the first "
                 "trigger, per process:")
    for proc in sorted(bundle.get("rings") or {}):
        evs = [e for e in bundle["rings"][proc]
               if t_fire - _TRIAGE_WINDOW_S <= e.get("ts", 0.0)
               <= t_fire + 1.0]
        lines.append(f"  [{proc}] {len(evs)} events")
        for e in evs[-15:]:
            lines.append(f"    t{e.get('ts', 0.0) - t_fire:+8.3f}s "
                         + _fmt_ring_event(e))

    lines.append("HBM timeline:")
    lines.extend(_memory_curve(bundle.get("memory_timeline") or {}))

    lines.append("straggler / attempt attribution:")
    for stage, st in sorted((bundle.get("attempts") or {}).items()):
        lines.append(f"  stage {stage}: median ok "
                     f"{st.get('median_ok_s', 0.0) * 1e3:.1f}ms, "
                     f"straggler cut "
                     f"{st.get('straggler_cut_s', 0.0) * 1e3:.1f}ms")
        for a in st.get("attempts", []):
            mark = " <-- " + a["state"].upper() \
                if a in (st.get("flagged") or []) else ""
            lines.append(
                f"    {a.get('task', '?')} a{a.get('attempt', '?')} "
                f"w{a.get('worker', '?')} {a.get('state', '?'):<9} "
                f"{a.get('runtime_s', 0.0) * 1e3:9.1f}ms"
                f"{mark} {a.get('reason', '')[:80]}".rstrip())

    fbs = bundle.get("plan_fallbacks") or []
    if any(f.get("n_fallbacks") for f in fbs):
        lines.append("plan fallbacks:")
        for f in fbs:
            if f.get("n_fallbacks"):
                lines.append(f"  {f.get('fallbacks', '')[:200]}")
    delta = bundle.get("conf_delta") or {}
    if delta:
        lines.append("non-default conf:")
        for k in sorted(delta):
            lines.append(f"  {k} = {delta[k]}")
    return "\n".join(lines)


# --- query-profile history + cross-run comparison ----------------------------
# The persisted profile-<id>.json files (spark.rapids.history.dir,
# written by PhysicalPlan.collect and TpuProcessCluster.run_query via
# obs/opmetrics.py) are the offline record of per-operator runtime:
# `history` lists/inspects them, `compare` diffs two runs per OPERATOR
# so a BENCH-level regression (one opaque number) decomposes into
# "which node ate it". `compare` also accepts two BENCH_r0x.json files.

def history_report(path: str, profile_id: Optional[str] = None) -> str:
    """List the profiles under a history dir, or inspect one (by
    profile id, filename, or unique prefix): the annotated plan plus
    the per-operator aggregate table."""
    from ..obs.opmetrics import read_profiles
    profs = read_profiles(path)
    if not profs:
        return f"(no query profiles under {path})"
    if profile_id:
        matches = [(fp, doc) for fp, doc in profs
                   if profile_id in (doc.get("profile_id", ""),
                                     os.path.basename(fp))
                   or doc.get("profile_id", "").startswith(profile_id)]
        if not matches:
            return f"(no profile matching {profile_id!r} under {path})"
        return "\n\n".join(_render_profile(doc) for _, doc in matches)
    lines = [f"=== query-profile history ({path}) ===",
             f"{len(profs)} profiles (oldest first):"]
    for fp, doc in profs:
        sinks = sorted(doc.get("ops", {}).values(),
                       key=lambda st: -st.get("metrics", {})
                       .get("opTime", 0.0))
        top = "-"
        if sinks and sinks[0].get("metrics", {}).get("opTime"):
            top = sinks[0].get("label", "?")
        lines.append(
            f"  {doc.get('profile_id', os.path.basename(fp)):<28} "
            f"{doc.get('query', '') or '-':<6} "
            f"{doc.get('cluster', '?'):<8} {doc.get('source', '?'):<5} "
            f"{doc.get('wall_s', 0.0) * 1e3:9.1f}ms  top: {top}")
    return "\n".join(lines)


def _render_profile(doc: dict) -> str:
    lines = [f"=== {doc.get('profile_id', '?')} "
             f"(query {doc.get('query', '') or '-'}, "
             f"{doc.get('cluster', '?')}/{doc.get('source', '?')}, "
             f"{doc.get('wall_s', 0.0) * 1e3:.1f}ms, "
             f"fingerprint {doc.get('fingerprint', '?')}) ==="]
    from ..obs.opmetrics import _fold_key
    ops = doc.get("ops", {})
    by_label = {st.get("label", k): (k, st) for k, st in ops.items()}
    for n in doc.get("nodes", []):
        pad = "  " * int(n.get("depth", 0))
        st = by_label.get(n.get("label"), (None, None))[1]
        if st is None:
            st = ops.get(_fold_key(n.get("label", "")))
        ann = ""
        if st:
            m = st.get("metrics", {})
            bits = [f"rows={int(m.get('rows', 0))}",
                    f"opTime={m.get('opTime', 0.0) * 1e3:.2f}ms"]
            if st.get("tasks", 1) > 1:
                bits.append(f"tasks={st['tasks']} "
                            f"skew={st.get('skew', 1.0)}")
            ann = "  [" + ", ".join(bits) + "]"
        lines.append(f"{pad}{n.get('describe', n.get('op', '?'))}{ann}")
    return "\n".join(lines)


def _load_compare_doc(path: str) -> dict:
    import json
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        return doc["parsed"]  # BENCH_r0x.json wrapper
    if isinstance(doc, dict) and "tail" in doc and "cmd" in doc:
        # wrapper whose parsed field was never filled: recover the
        # bench's one JSON line from the tail so the round still
        # carries its metrics AND its device_kind into the
        # comparability gate (a CPU-run round diffed against a TPU run
        # must REFUSE, not report a ~1000x fake regression)
        for line in reversed(str(doc.get("tail", "")).splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    parsed = json.loads(line)
                except ValueError:
                    break
                if isinstance(parsed, dict):
                    return parsed
                break
    return doc if isinstance(doc, dict) else {}


def _device_kind_guard(a: dict, b: dict, a_path: str, b_path: str,
                       allow_cross_device: bool):
    """Comparability gate: numbers measured on different hardware are
    not comparable — a CPU-backend bench read against a TPU bench looks
    like a ~1000x 'regression' that is really a backend swap (exactly
    what a naive BENCH_r06-vs-r05 diff would report). Returns
    ``(refusal_or_None, warning_or_None)``; docs without a recorded
    device_kind (pre-guard profiles/benches) pass — absence of evidence
    is not a mismatch."""
    ka, kb = a.get("device_kind"), b.get("device_kind")
    if ka is None or kb is None or ka == kb:
        return None, None  # same device, or a pre-guard doc
    if allow_cross_device:
        return None, ("=== WARNING: device_kind mismatch "
                      f"({ka!r} vs {kb!r}) — cross-device diff "
                      "forced ===")
    return "\n".join([
        "=== compare REFUSED: device_kind mismatch ===",
        f"  A ({a_path}): device_kind={ka!r}",
        f"  B ({b_path}): device_kind={kb!r}",
        "  Numbers measured on different hardware are not "
        "comparable — a backend swap reads as a giant fake "
        "regression (or win).",
        "  Re-run both on the same device_kind, or pass "
        "--allow-cross-device to diff anyway."]), None


def compare_report(a_path: str, b_path: str,
                   threshold: float = 1.5,
                   allow_cross_device: bool = False) -> str:
    """Per-operator time/rows deltas between two query profiles (A =
    baseline, B = candidate); operators whose opTime grew by at least
    ``threshold``x (above a 1ms floor) are flagged REGRESSED. Two
    BENCH json files compare their shared scalar metrics instead.
    Comparisons across differing ``device_kind`` are REFUSED unless
    ``allow_cross_device`` (then the report leads with a warning)."""
    a, b = _load_compare_doc(a_path), _load_compare_doc(b_path)
    guard, warning = _device_kind_guard(a, b, a_path, b_path,
                                        allow_cross_device)
    warn = warning + "\n" if warning else ""
    if guard is not None:
        return guard
    if not (isinstance(a.get("ops"), dict)
            and isinstance(b.get("ops"), dict)):
        return warn + _compare_bench(a, b, a_path, b_path, threshold)
    lines = ([warn.rstrip()] if warn else []) + [
        f"=== profile compare (A={a.get('profile_id', a_path)}, "
        f"B={b.get('profile_id', b_path)}, "
        f"threshold {threshold}x) ==="]
    wa, wb = a.get("wall_s", 0.0), b.get("wall_s", 0.0)
    ratio = f"{wb / wa:.2f}x" if wa > 0 else "n/a"
    lines.append(f"wall: {wa * 1e3:.1f}ms -> {wb * 1e3:.1f}ms ({ratio})")
    if a.get("fingerprint") != b.get("fingerprint"):
        lines.append("NOTE: plan fingerprints differ — operator ids "
                     "may not describe the same plan shape")
    aops, bops = a["ops"], b["ops"]
    rows_out = []
    regressions = 0
    for key in sorted(set(aops) | set(bops),
                      key=lambda k: -(bops.get(k, aops.get(k, {}))
                                      .get("metrics", {})
                                      .get("opTime", 0.0))):
        sa, sb = aops.get(key), bops.get(key)
        label = (sb or sa).get("label", key)
        if sa is None:
            rows_out.append(f"  {label:<36} only in B")
            continue
        if sb is None:
            rows_out.append(f"  {label:<36} only in A")
            continue
        ta = sa.get("metrics", {}).get("opTime", 0.0)
        tb = sb.get("metrics", {}).get("opTime", 0.0)
        ra = int(sa.get("metrics", {}).get("rows", 0))
        rb = int(sb.get("metrics", {}).get("rows", 0))
        flag = ""
        if tb > max(ta * threshold, ta + 1e-3):
            flag = f"  <-- REGRESSED ({tb / ta:.1f}x)" if ta > 0 \
                else "  <-- REGRESSED (new time)"
            regressions += 1
        drows = f" rows {ra}->{rb}" if ra != rb else f" rows {ra}"
        rows_out.append(f"  {label:<36} {ta * 1e3:9.2f}ms -> "
                        f"{tb * 1e3:9.2f}ms{drows}{flag}")
    lines.append(f"per-operator opTime (A -> B), {regressions} "
                 f"regression(s):")
    lines.extend(rows_out)
    return "\n".join(lines)


def _compare_bench(a: dict, b: dict, a_path: str, b_path: str,
                   threshold: float) -> str:
    """Scalar diff of two BENCH json documents (shared numeric keys,
    ratio-sorted); changes beyond the threshold in either direction
    are flagged."""
    lines = [f"=== bench compare (A={a_path}, B={b_path}) ==="]
    keys = [k for k in a if k in b
            and isinstance(a[k], (int, float))
            and isinstance(b[k], (int, float))
            and not isinstance(a[k], bool)]
    if not keys:
        return "\n".join(lines + ["(no shared numeric metrics)"])

    def _ratio(k):
        return (b[k] / a[k]) if a[k] else float("inf")
    import math
    keys.sort(key=lambda k: -abs(math.log(max(_ratio(k), 1e-12)))
              if _ratio(k) not in (0, float("inf")) else float("-inf"))
    for k in keys:
        r = _ratio(k)
        flag = ""
        if r and r != float("inf") \
                and (r >= threshold or r <= 1.0 / threshold):
            flag = f"  <-- CHANGED ({r:.2f}x)"
        rtxt = f"{r:.3f}x" if r not in (0, float("inf")) else "n/a"
        lines.append(f"  {k:<40} {a[k]:>12} -> {b[k]:>12}  "
                     f"{rtxt}{flag}")
    return "\n".join(lines)


def _main(argv):
    import sys
    usage = ("usage: python -m spark_rapids_tpu.tools.profiling "
             "<event-log dir | trace-*.json | triage <incident.json> | "
             "history <dir> [profile-id] | "
             "compare <a.json> <b.json> [--threshold X] | "
             "warehouse <dir> | "
             "drift <dir> [--bytes-tolerance X] [--variant-bound N] "
             "[--allow-cross-device]>")
    if not argv:
        print(usage, file=sys.stderr)
        return 2
    if argv[0] == "triage":
        if len(argv) < 2:
            print("usage: profiling triage <incident-*.json>",
                  file=sys.stderr)
            return 2
        print(triage_report(argv[1]))
    elif argv[0] == "history":
        if len(argv) < 2:
            print("usage: profiling history <dir> [profile-id]",
                  file=sys.stderr)
            return 2
        print(history_report(argv[1],
                             argv[2] if len(argv) > 2 else None))
    elif argv[0] == "compare":
        rest = [a for a in argv[1:] if not a.startswith("--")]
        threshold = 1.5
        allow_cross = "--allow-cross-device" in argv
        for i, a in enumerate(argv):
            if a == "--threshold" and i + 1 < len(argv):
                threshold = float(argv[i + 1])
                rest = [x for x in rest if x != argv[i + 1]]
            elif a.startswith("--threshold="):
                threshold = float(a.split("=", 1)[1])
        if len(rest) != 2:
            print("usage: profiling compare <a.json> <b.json> "
                  "[--threshold X] [--allow-cross-device]",
                  file=sys.stderr)
            return 2
        report = compare_report(rest[0], rest[1], threshold=threshold,
                                allow_cross_device=allow_cross)
        print(report)
        if report.startswith("=== compare REFUSED"):
            return 3  # comparability gate tripped — not a diff result
    elif argv[0] == "warehouse":
        if len(argv) < 2:
            print("usage: profiling warehouse <dir>", file=sys.stderr)
            return 2
        from ..obs.warehouse import render_warehouse
        print(render_warehouse(argv[1]))
    elif argv[0] == "drift":
        rest = [a for a in argv[1:] if not a.startswith("--")]
        bytes_tol = None
        variant_bound = None
        allow_cross = "--allow-cross-device" in argv
        for i, a in enumerate(argv):
            if a == "--bytes-tolerance" and i + 1 < len(argv):
                bytes_tol = float(argv[i + 1])
                rest = [x for x in rest if x != argv[i + 1]]
            elif a.startswith("--bytes-tolerance="):
                bytes_tol = float(a.split("=", 1)[1])
            elif a == "--variant-bound" and i + 1 < len(argv):
                variant_bound = int(argv[i + 1])
                rest = [x for x in rest if x != argv[i + 1]]
            elif a.startswith("--variant-bound="):
                variant_bound = int(a.split("=", 1)[1])
        if len(rest) != 1:
            print("usage: profiling drift <dir> [--bytes-tolerance X] "
                  "[--variant-bound N] [--allow-cross-device]",
                  file=sys.stderr)
            return 2
        from ..obs.warehouse import drift_report
        report, rc = drift_report(rest[0], bytes_tolerance=bytes_tol,
                                  variant_bound=variant_bound,
                                  allow_cross_device=allow_cross)
        print(report)
        # rc 3 = cross-device_kind refusal (same gate as compare);
        # rc 1 = structural regressions flagged; rc 0 = clean
        return rc
    elif argv[0].endswith(".json"):
        print(profile_trace(argv[0]))
    else:
        print(profile_event_logs(argv[0]))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(_main(sys.argv[1:]))
