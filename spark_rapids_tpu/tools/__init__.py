from .udf_compiler import compile_udf, TpuCompiledUDF
from .qualification import qualify
from .profiling import profile_report
from .api_validation import generate_supported_ops
