"""Supported-ops documentation generator + API surface validation.

TPU analog of the reference's api_validation tool and generated
supported-ops docs (SURVEY.md §2.2-F; mount empty, capability-built):
introspects the live exec/expression registries — the same classes the
planner consults — so the doc can never drift from the code, and
validates that every registered config key is consumed somewhere in the
package (the dead-conf check VERDICT r1/r2 asked for).
"""
from __future__ import annotations

import inspect
import os
from typing import Dict, List, Tuple

__all__ = ["generate_supported_ops", "validate_configs"]


def _exec_classes():
    from ..exec import aggregate, basic, exchange, generate, joins, misc, \
        sort, window
    from ..exec.base import TpuExec
    from ..io import scan, write
    out = []
    for mod in (basic, aggregate, sort, joins, exchange, window, generate,
                misc, scan, write):
        for name, cls in vars(mod).items():
            if (inspect.isclass(cls) and issubclass(cls, TpuExec)
                    and name.startswith("Tpu")
                    and cls.__module__ == mod.__name__):
                out.append(cls)
    return out


def _expr_classes():
    from .. import expr as E
    from ..expr.base import Expression
    out = []
    for name in dir(E):
        cls = getattr(E, name)
        if (inspect.isclass(cls) and issubclass(cls, Expression)
                and not name.startswith("_")
                and cls is not Expression):
            out.append(cls)
    return out


def _first_line(doc) -> str:
    if not doc:
        return ""
    return doc.strip().splitlines()[0]


def generate_supported_ops() -> str:
    """Markdown tables of every physical operator and expression the
    engine registers, with their device-support caveats (the classes'
    own tpu_supported hooks are the runtime truth; the static notes here
    come from their docs)."""
    lines = ["# Supported operators and expressions",
             "",
             "Generated from the live registry by "
             "`spark_rapids_tpu.tools.generate_supported_ops()`; "
             "per-instance eligibility is decided at plan time by each "
             "node's `tpu_supported()` and the "
             "`spark.rapids.sql.exec.<Name>` / `.expression.<Name>` "
             "kill switches.",
             "", "## Physical operators", "",
             "| Operator | Notes |", "|---|---|"]
    for cls in sorted(_exec_classes(), key=lambda c: c.__name__):
        note = _first_line(cls.__doc__)
        lines.append(f"| {cls.__name__} | {note} |")
    lines += ["", "## Expressions", "", "| Expression | Notes |",
              "|---|---|"]
    for cls in sorted(_expr_classes(), key=lambda c: c.__name__):
        note = _first_line(cls.__doc__)
        lines.append(f"| {cls.__name__} | {note} |")
    lines += [
        "", "## Format notes", "",
        "- Parquet device decode "
        "(`spark.rapids.sql.format.parquet.deviceDecode.enabled`): the "
        "supported envelope is unchanged by the overlapped/coalesced "
        "upload tunnel — v1 data pages of flat int32/int64/float/"
        "double/boolean in PLAIN / PLAIN_DICTIONARY / RLE_DICTIONARY "
        "encodings (plus dictionary-encoded strings), snappy/zstd/gzip/"
        "brotli codecs, definition depth <= 1. Everything else "
        "(nested, v2 pages, DELTA_*, LZ4, PLAIN strings) still decodes "
        "on host per column chunk, and pipelining/coalescing never "
        "widens that envelope: coalesced row groups merge only when "
        "every column takes the same (device or host) route.",
    ]
    return "\n".join(lines)


def validate_configs() -> Dict[str, List[str]]:
    """{'unused': [conf keys registered but never read outside
    config.py], 'count': ...} — the honesty check for dead config
    surface (VERDICT r2 weak #6)."""
    from .. import config as C
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sources = []
    config_src = ""
    for root, _, files in os.walk(pkg_dir):
        for f in files:
            if f.endswith(".py") and f != "config.py":
                with open(os.path.join(root, f)) as fh:
                    sources.append(fh.read())
            elif f == "config.py":
                with open(os.path.join(root, f)) as fh:
                    config_src = fh.read()
    blob = "\n".join(sources)
    # confs consumed via derived properties INSIDE config.py (e.g.
    # RapidsConf.ansi reads ANSI_ENABLED) count as consumed
    for line in config_src.splitlines():
        if ".get(" in line or "self._settings" in line:
            blob += "\n" + line
    unused: List[str] = []
    names: List[Tuple[str, str]] = []
    for attr in dir(C):
        entry = getattr(C, attr)
        key = getattr(entry, "key", None)
        if key is None and isinstance(entry, str) \
                and entry.startswith("spark."):
            key, entry = entry, None
        if isinstance(key, str) and key.startswith("spark."):
            names.append((attr, key))
    for attr, key in names:
        # consumed if the ConfEntry attribute or the literal key appears
        # anywhere outside config.py
        if attr not in blob and key not in blob:
            unused.append(key)
    return {"checked": [k for _, k in names], "unused": unused}
