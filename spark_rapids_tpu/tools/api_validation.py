"""Supported-ops documentation generator + API surface validation.

TPU analog of the reference's api_validation tool and generated
supported-ops docs (SURVEY.md §2.2-F; mount empty, capability-built):
introspects the live exec/expression registries — the same classes the
planner consults — so the doc can never drift from the code, and
validates that every registered config key is consumed somewhere in the
package (the dead-conf check VERDICT r1/r2 asked for).
"""
from __future__ import annotations

import inspect
from typing import Dict, List

__all__ = ["generate_supported_ops", "validate_configs"]


def _exec_classes():
    from ..exec import aggregate, basic, exchange, generate, joins, misc, \
        sort, window
    from ..exec.base import TpuExec
    from ..io import scan, write
    out = []
    for mod in (basic, aggregate, sort, joins, exchange, window, generate,
                misc, scan, write):
        for name, cls in vars(mod).items():
            if (inspect.isclass(cls) and issubclass(cls, TpuExec)
                    and name.startswith("Tpu")
                    and cls.__module__ == mod.__name__):
                out.append(cls)
    return out


def _expr_classes():
    from .. import expr as E
    from ..expr.base import Expression
    out = []
    for name in dir(E):
        cls = getattr(E, name)
        if (inspect.isclass(cls) and issubclass(cls, Expression)
                and not name.startswith("_")
                and cls is not Expression):
            out.append(cls)
    return out


def _first_line(doc) -> str:
    if not doc:
        return ""
    return doc.strip().splitlines()[0]


def _contract_cell(cls) -> str:
    """Render the class's OpContract (the same object the static plan
    verifier enforces) for the doc table."""
    try:
        c = cls.contract()
    except Exception:  # noqa: BLE001 — doc generation must not fail
        return ""
    flags = c.doc_flags()
    if c.notes:
        flags = f"{flags}; {c.notes}" if flags else c.notes
    return flags


def generate_supported_ops() -> str:
    """Markdown tables of every physical operator and expression the
    engine registers, with their device-support caveats (the classes'
    own tpu_supported hooks are the runtime truth; the static notes here
    come from their docs) and their declared operator contracts (the
    same `OpContract` objects the pre-execution plan verifier
    enforces)."""
    lines = ["# Supported operators and expressions",
             "",
             "Generated from the live registry by "
             "`spark_rapids_tpu.tools.generate_supported_ops()`; "
             "per-instance eligibility is decided at plan time by each "
             "node's `tpu_supported()` and the "
             "`spark.rapids.sql.exec.<Name>` / `.expression.<Name>` "
             "kill switches. The Contract column is rendered from each "
             "operator's declared `OpContract` — the SAME source of "
             "truth the static plan verifier "
             "(`spark_rapids_tpu/analysis/plan_verifier.py`, "
             "`spark.rapids.sql.verifyPlan`) checks before execution, "
             "so this doc and the verifier cannot drift apart.",
             "", "## Physical operators", "",
             "| Operator | Notes | Contract |", "|---|---|---|"]
    for cls in sorted(_exec_classes(), key=lambda c: c.__name__):
        note = _first_line(cls.__doc__)
        lines.append(f"| {cls.__name__} | {note} | "
                     f"{_contract_cell(cls)} |")
    lines += ["", "## Expressions", "", "| Expression | Notes |",
              "|---|---|"]
    for cls in sorted(_expr_classes(), key=lambda c: c.__name__):
        note = _first_line(cls.__doc__)
        lines.append(f"| {cls.__name__} | {note} |")
    lines += ["", "## Stage fusion", "",
              "Whole-stage fusion (`spark.rapids.sql.stageFusion."
              "enabled`) composes chains of row-wise-map operators — "
              "each one's live `device_fn` — into ONE XLA program per "
              "batch; scan-rooted chains splice into the parquet "
              "fused-decode program (`spark.rapids.sql.stageFusion."
              "scan.enabled`), one dispatch per coalesced row-group "
              "batch. This table is the row-wise-map AUDIT, generated "
              "from the live `device_fn` registry plus each barrier's "
              "declared `FUSION_NOTE` — drift-checked by `tpu-lint "
              "--check-docs`.", "",
              "| Operator | Fusion |", "|---|---|"]
    from ..exec.base import (DeviceBatchSourceExec, HostBatchSourceExec,
                             TpuExec as _TpuExec)
    from ..exec.transitions import DeviceToHostExec, HostToDeviceExec
    # the audit covers the non-Tpu-prefixed participants too: the
    # planner-inserted transitions and the source leaves all carry
    # their own chain-root/barrier notes
    audit_classes = _exec_classes() + [
        DeviceToHostExec, HostToDeviceExec, HostBatchSourceExec,
        DeviceBatchSourceExec]
    for cls in sorted(audit_classes, key=lambda c: c.__name__):
        if cls.__dict__.get("device_fn") is not None:
            cell = "fusable: row-wise map (`device_fn`)"
            note = cls.FUSION_NOTE
            if note is not _TpuExec.FUSION_NOTE:
                cell += f" — {note}"
        else:
            cell = cls.FUSION_NOTE
        lines.append(f"| {cls.__name__} | {cell} |")
    lines += [
        "", "## Format notes", "",
        "- Parquet device decode "
        "(`spark.rapids.sql.format.parquet.deviceDecode.enabled`): the "
        "envelope covers v1 AND v2 (DATA_PAGE_V2) data pages of flat "
        "int32/int64/float/double/boolean/string columns in PLAIN "
        "(including PLAIN BYTE_ARRAY strings — length prefixes walked "
        "host-side, characters gathered on device), PLAIN_DICTIONARY /"
        " RLE_DICTIONARY (dictionary-then-PLAIN mixed chunks "
        "included), DELTA_BINARY_PACKED (device prefix-sum "
        "reconstruction; miniblock widths <= 32 bits) and "
        "DELTA_LENGTH_BYTE_ARRAY encodings, under snappy/zstd/gzip/"
        "brotli codecs, definition depth <= 1. Chunks still outside "
        "it (nested, FIXED_LEN_BYTE_ARRAY, DELTA_BYTE_ARRAY, "
        "BYTE_STREAM_SPLIT, LZ4) decode on host per column chunk, "
        "counted per bounded reason in "
        "`rapids_scan_fallback_chunks_total` and the scan's "
        "`deviceChunks`/`fallbackChunks` metrics; coalesced row "
        "groups merge only when every column takes the same (device "
        "or host) route.",
    ]
    lines += [
        "", "## Shuffle transports", "",
        "`TpuShuffleExchangeExec` is transport-agnostic. In-process "
        "collects materialize it through `IciShuffleTransport` "
        "(`shuffle/ici.py`): the all-to-all repartition runs as one "
        "XLA collective over the local device mesh. On a "
        "`TpuProcessCluster` the default is the file-based HOST "
        "transport (Arrow IPC map outputs through the filesystem "
        "rendezvous, CRC-footed, lineage-recoverable); with "
        "`spark.rapids.tpu.mesh.enabled` the exchange instead rides "
        "`GangIciShuffleTransport` (`distributed/gang.py`) — the same "
        "collective spanning every worker process over one "
        "`(dcn, ici)` mesh. Either way a bad exchange read surfaces "
        "as a classified `FetchFailure` "
        "(`missing|corrupt|torn|io`) with the same metric labels "
        "(`rapids_shuffle_fetch_failures_total{kind,transport}`): "
        "host-transport failures recover a single map task from "
        "lineage; ICI/gang failures fail the gang and remesh (see "
        "README §Multi-host mesh).",
    ]
    from ..sql import dialect_note
    lines += [
        "", "## SQL frontend", "",
        "`TpuSession.sql(text)` compiles the dialect below through "
        "the same planner path DataFrames use (section generated from "
        "the live `spark_rapids_tpu/sql` registries).",
        "",
        dialect_note(),
    ]
    return "\n".join(lines)


def validate_configs() -> Dict[str, List[str]]:
    """Dead/unregistered conf audit — delegates to the AST-exact rule
    in `analysis/lint.py::conf_key_report` (the old substring scan
    counted a key mentioned in a docstring as consumed; the AST form
    counts only real name references and call-argument literals).
    Returns {'checked': [keys], 'unused': [keys],
    'unregistered_reads': [{key,path,line}]}."""
    from ..analysis.lint import conf_key_report
    return conf_key_report()
