"""Mortgage ETL -> training features (BASELINE config 4's ETL half).

TPU analog of the reference's Mortgage pipeline (SURVEY.md §3.5,
§2.2-F "XGBoost integration"; mount empty): raw acquisition +
performance tables -> joins/aggregations/casts/categorical features ->
a feature DataFrame handed to a trainer through the ml.py bridge
(`ColumnarRdd` analog) WITHOUT row conversion. The reference trains
XGBoost4J-Spark from GPU column handles; here `train_logreg_jax`
consumes the device feature matrix directly in HBM (zero host
round-trip), and `ml.to_torch` serves host-side trainer libraries.
"""
from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

__all__ = ["gen_mortgage", "mortgage_features", "train_logreg_jax"]


def gen_mortgage(n_loans: int = 2000, perf_per_loan: int = 6,
                 seed: int = 0) -> Dict[str, dict]:
    """Mortgage-shaped raw tables: `acquisition` (loan origination
    facts) and `performance` (monthly servicing rows incl. delinquency
    status) — the two inputs of the reference's ETL."""
    rng = np.random.default_rng(seed)
    states = np.array(["CA", "TX", "NY", "FL", "WA", "IL", "OH", "GA"])
    purposes = np.array(["P", "C", "R", "U"])
    acquisition = {
        "loan_id": np.arange(n_loans, dtype=np.int64),
        "orig_interest_rate": rng.uniform(2.5, 7.5, n_loans)
        .astype(np.float32),
        "orig_upb": rng.integers(50_000, 800_000, n_loans)
        .astype(np.int64),
        "orig_loan_term": rng.choice([180, 240, 360], n_loans)
        .astype(np.int32),
        "oltv": rng.uniform(40, 97, n_loans).astype(np.float32),
        "dti": rng.uniform(10, 50, n_loans).astype(np.float32),
        "borrower_credit_score": rng.integers(580, 840, n_loans)
        .astype(np.int32),
        "property_state": states[rng.integers(0, len(states), n_loans)]
        .tolist(),
        "loan_purpose": purposes[rng.integers(0, len(purposes),
                                              n_loans)].tolist(),
    }
    n_perf = n_loans * perf_per_loan
    loan = np.repeat(np.arange(n_loans, dtype=np.int64), perf_per_loan)
    # delinquency risk increases with dti and decreases with score
    risk = (acquisition["dti"][loan] / 50.0
            + (760 - acquisition["borrower_credit_score"][loan]) / 400.0)
    delinq = (rng.uniform(0, 1, n_perf) < np.clip(risk * 0.18, 0, 0.9)) \
        .astype(np.int32) * rng.integers(1, 4, n_perf).astype(np.int32)
    performance = {
        "loan_id": loan,
        "period": (18_000 + np.tile(np.arange(perf_per_loan) * 30,
                                    n_loans)).astype(np.int32),
        "current_upb": (acquisition["orig_upb"][loan]
                        * rng.uniform(0.5, 1.0, n_perf)).astype(
                            np.float32),
        "delinquency_status": delinq,
    }
    return {"acquisition": acquisition, "performance": performance}


def mortgage_features(session, tables=None, n_loans: int = 2000):
    """The ETL: per-loan performance aggregation -> join with
    acquisition -> categorical hash features + casts. Returns the
    feature DataFrame (one row per loan) and the feature column list —
    the reference pipeline's shape (§3.5) through this engine's planner
    (joins, group-by, casts, hash all on device)."""
    import pyarrow as pa

    from .. import datatypes as dt
    from ..expr import (Alias, Cast, GreaterThanOrEqual, Literal,
                        UnresolvedColumn as col)
    from ..expr.aggregates import Average, Count, Max, Min, Sum
    from ..expr.hashes import Murmur3Hash
    if tables is None:
        tables = gen_mortgage(n_loans)
    acq = session.create_dataframe(pa.table(tables["acquisition"]))
    perf = session.create_dataframe(pa.table(tables["performance"]))

    perf_agg = perf.group_by("loan_id").agg(
        Alias(Max(col("delinquency_status")), "max_delinq"),
        Alias(Average(col("current_upb")), "avg_upb"),
        Alias(Min(col("current_upb")), "min_upb"),
        Alias(Count(col("period")), "n_periods"))

    joined = acq.join(perf_agg, on="loan_id", how="inner")
    feats = (
        joined
        .with_column("state_bucket",
                     Cast(Murmur3Hash(col("property_state")),
                          dt.FLOAT32))
        .with_column("purpose_bucket",
                     Cast(Murmur3Hash(col("loan_purpose")),
                          dt.FLOAT32))
        .with_column("score_f",
                     Cast(col("borrower_credit_score"), dt.FLOAT32))
        .with_column("term_f", Cast(col("orig_loan_term"), dt.FLOAT32))
        .with_column("upb_f", Cast(col("orig_upb"), dt.FLOAT32))
        .with_column("label",
                     Cast(GreaterThanOrEqual(col("max_delinq"),
                                             Literal(1, dt.INT32)),
                          dt.FLOAT32)))
    feature_cols = ["orig_interest_rate", "oltv", "dti", "score_f",
                    "term_f", "upb_f", "avg_upb", "min_upb",
                    "state_bucket", "purpose_bucket"]
    return feats, feature_cols


def train_logreg_jax(X, y, live, steps: int = 60, lr: float = 0.3):
    """Logistic regression trained entirely ON DEVICE from the bridge's
    feature matrix (the XGBoost-from-GPU-handles analog: features never
    leave HBM). Returns (weights, bias, loss_history)."""
    import jax
    import jax.numpy as jnp

    n_live = jnp.maximum(jnp.sum(live.astype(jnp.float32)), 1.0)
    # standardize over LIVE rows only: a full-capacity mean would be
    # biased toward 0 by the padding rows (code-review r5)
    mu = jnp.sum(jnp.where(live[:, None], X, 0), axis=0) / n_live
    sd = jnp.sqrt(jnp.sum(jnp.where(live[:, None], (X - mu) ** 2, 0),
                          axis=0) / n_live) + 1e-6
    Xn = (X - mu) / sd
    w = jnp.zeros((X.shape[1],), jnp.float32)
    b = jnp.float32(0.0)

    def loss_fn(params):
        w_, b_ = params
        z = Xn @ w_ + b_
        p = jax.nn.sigmoid(z)
        eps = 1e-6
        ll = y * jnp.log(p + eps) + (1 - y) * jnp.log(1 - p + eps)
        return -jnp.sum(jnp.where(live, ll, 0)) / n_live

    grad = jax.jit(jax.value_and_grad(loss_fn))
    losses = []
    params = (w, b)
    for _ in range(steps):
        val, g = grad(params)
        params = (params[0] - lr * g[0], params[1] - lr * g[1])
        losses.append(float(val))
    return params[0], params[1], losses
