"""Qualification tool: how much of a workload would run on TPU?

TPU analog of the reference's qualification tool (SURVEY.md §2.2-F:
offline analysis of which plans/operators accelerate; mount empty,
capability-built). Instead of parsing event logs, it runs the REAL
override pass over a plan tree in dry-run and scores the outcome.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

from ..config import RapidsConf
from ..exec.base import TpuExec

__all__ = ["qualify", "QualificationReport"]


@dataclasses.dataclass
class QualificationReport:
    total_ops: int
    on_device_ops: int
    fallback_reasons: List[str]
    score: float          # fraction of operators that accelerate

    def render(self) -> str:
        lines = [
            "=== TPU qualification report ===",
            f"operators on device : {self.on_device_ops}/{self.total_ops}"
            f"  (score {self.score:.0%})",
        ]
        if self.fallback_reasons:
            lines.append("not accelerated:")
            lines.extend(f"  - {r}" for r in self.fallback_reasons)
        else:
            lines.append("fully accelerated: every operator runs on TPU")
        rec = ("RECOMMENDED: this workload accelerates well"
               if self.score >= 0.75 else
               "PARTIAL: review the fallback reasons before migrating"
               if self.score >= 0.3 else
               "NOT RECOMMENDED: most operators fall back to CPU")
        lines.append(rec)
        return "\n".join(lines)


def qualify(plan: TpuExec,
            conf: Optional[RapidsConf] = None) -> QualificationReport:
    """Dry-run the override pass (wrap + tag only — no execution, no
    transition rewrite) and score device placement."""
    from ..planner import TpuOverrides
    ov = TpuOverrides(conf or RapidsConf())
    meta = ov._wrap(plan)
    ov._tag(meta)

    total = 0
    on_dev = 0
    reasons: List[str] = []

    def rec(m):
        nonlocal total, on_dev
        total += 1
        if m.on_device:
            on_dev += 1
        else:
            reasons.append(
                f"{m.node.pretty_name()}: {'; '.join(m.reasons)}")
        for c in m.children:
            rec(c)

    rec(meta)
    return QualificationReport(total, on_dev, reasons,
                               on_dev / max(total, 1))
