"""Qualification tool: how much of a workload would run on TPU?

TPU analog of the reference's qualification tool (SURVEY.md §2.2-F:
offline analysis of which plans/operators accelerate; mount empty,
capability-built). Instead of parsing event logs, it runs the REAL
override pass over a plan tree in dry-run and scores the outcome.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

from ..config import RapidsConf
from ..exec.base import TpuExec

__all__ = ["qualify", "QualificationReport", "qualify_event_logs",
           "AppQualification"]


@dataclasses.dataclass
class QualificationReport:
    total_ops: int
    on_device_ops: int
    fallback_reasons: List[str]
    score: float          # fraction of operators that accelerate

    def render(self) -> str:
        lines = [
            "=== TPU qualification report ===",
            f"operators on device : {self.on_device_ops}/{self.total_ops}"
            f"  (score {self.score:.0%})",
        ]
        if self.fallback_reasons:
            lines.append("not accelerated:")
            lines.extend(f"  - {r}" for r in self.fallback_reasons)
        else:
            lines.append("fully accelerated: every operator runs on TPU")
        rec = ("RECOMMENDED: this workload accelerates well"
               if self.score >= 0.75 else
               "PARTIAL: review the fallback reasons before migrating"
               if self.score >= 0.3 else
               "NOT RECOMMENDED: most operators fall back to CPU")
        lines.append(rec)
        return "\n".join(lines)


def qualify(plan: TpuExec,
            conf: Optional[RapidsConf] = None) -> QualificationReport:
    """Dry-run the override pass (wrap + tag only — no execution, no
    transition rewrite) and score device placement."""
    from ..planner import TpuOverrides
    ov = TpuOverrides(conf or RapidsConf())
    meta = ov._wrap(plan)
    ov._tag(meta)

    total = 0
    on_dev = 0
    reasons: List[str] = []

    def rec(m):
        nonlocal total, on_dev
        total += 1
        if m.on_device:
            on_dev += 1
        else:
            reasons.append(
                f"{m.node.pretty_name()}: {'; '.join(m.reasons)}")
        for c in m.children:
            rec(c)

    rec(meta)
    return QualificationReport(total, on_dev, reasons,
                               on_dev / max(total, 1))


# --- event-log qualification (the reference tool's actual mode) ------------
# The reference's QualificationMain parses event logs of CPU runs and
# estimates per-app speedup (SURVEY.md:211). Same here: feed it the
# JSONL logs of runs executed with spark.rapids.sql.enabled=false — the
# planner still tags what WOULD place on device — and it models the
# speedup per query with Amdahl over per-operator acceleration factors
# measured on this engine's own benchmarks.

# conservative per-op speedup factors (device vs host) from bench.py /
# NDS measurements; unknown ops use DEFAULT_FACTOR
_OP_FACTORS = {
    "HashAggregateExec": 40.0, "ShuffledHashJoinExec": 80.0,
    "BroadcastHashJoinExec": 80.0, "SortExec": 25.0,
    "WindowExec": 25.0, "FilterExec": 50.0, "ProjectExec": 50.0,
    "FileScanExec": 1.3, "ShuffleExchangeExec": 10.0,
    "TopNExec": 25.0, "ExpandExec": 30.0, "GenerateExec": 20.0,
}
_DEFAULT_FACTOR = 10.0


@dataclasses.dataclass
class AppQualification:
    queries: int
    total_wall_s: float
    est_speedup: float           # Amdahl-modelled app-level speedup
    per_query: List[dict]        # fingerprint, wall_s, eligible, est
    top_blockers: List[str]

    def render(self) -> str:
        lines = [
            "=== TPU qualification (event logs) ===",
            f"queries analyzed    : {self.queries}",
            f"total wall time     : {self.total_wall_s:.2f}s",
            f"estimated speedup   : {self.est_speedup:.1f}x",
        ]
        worst = sorted(self.per_query, key=lambda q: q["est_speedup"])
        lines.append("slowest-accelerating queries:")
        for q in worst[:5]:
            lines.append(
                f"  {q['fingerprint']}  wall {q['wall_s'] * 1e3:7.1f}ms"
                f"  eligible {q['eligible']:.0%}"
                f"  est {q['est_speedup']:.1f}x")
        if self.top_blockers:
            lines.append("top fallback reasons:")
            lines.extend(f"  - {r}" for r in self.top_blockers[:8])
        rec = ("RECOMMENDED" if self.est_speedup >= 3 else
               "PARTIAL" if self.est_speedup >= 1.5 else
               "NOT RECOMMENDED")
        lines.append(f"{rec}: modelled from per-op factors measured on "
                     "this engine's benchmarks")
        return "\n".join(lines)


def qualify_event_logs(path: str) -> AppQualification:
    """Analyze the JSONL query events under `path` (a CPU run's logs:
    placement tags recorded at plan time, wall times measured)."""
    import collections

    from .event_log import read_event_logs
    per_query: List[dict] = []
    blockers = collections.Counter()
    for ev in read_event_logs(path):
        nodes = ev.get("nodes", [])
        if not nodes:
            continue
        n_dev = sum(1 for n in nodes if n["on_device"])
        eligible = n_dev / len(nodes)
        # Amdahl with per-op factors: each node carries equal weight of
        # the query's wall time (event logs carry no per-op CPU times)
        inv = 0.0
        for n in nodes:
            f = _OP_FACTORS.get(n["op"], _DEFAULT_FACTOR) \
                if n["on_device"] else 1.0
            inv += (1.0 / len(nodes)) / f
            for r in n.get("reasons", []):
                blockers[r] += 1
        est = 1.0 / max(inv, 1e-9)
        per_query.append({"fingerprint": ev.get("fingerprint", "?"),
                          "wall_s": ev.get("wall_s", 0.0),
                          "eligible": eligible,
                          "est_speedup": round(est, 2)})
    total_wall = sum(q["wall_s"] for q in per_query)
    if total_wall > 0:
        accel_wall = sum(q["wall_s"] / q["est_speedup"]
                         for q in per_query)
        app_speedup = total_wall / max(accel_wall, 1e-9)
    else:
        app_speedup = 1.0
    return AppQualification(
        queries=len(per_query), total_wall_s=total_wall,
        est_speedup=round(app_speedup, 2), per_query=per_query,
        top_blockers=[r for r, _ in blockers.most_common(8)])


def _main(argv):
    import sys
    if not argv:
        print("usage: python -m spark_rapids_tpu.tools.qualification "
              "<event-log dir>", file=sys.stderr)
        return 2
    print(qualify_event_logs(argv[0]).render())
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(_main(sys.argv[1:]))
