"""Query event logs: the persisted run record the offline tools mine.

TPU analog of Spark's event-log files as the reference's qualification/
profiling tools consume them (SURVEY.md §2.2-F, :211-212 — both
reference tools are event-log parsers; mount empty, capability-built).
With `spark.rapids.eventLog.dir` set, every `PhysicalPlan.collect()`
appends ONE JSON line describing the query: the plan tree, per-node
device placement + fallback reasons, per-operator metrics, wall time,
and the non-default conf — enough for

- qualification of a CPU run (`spark.rapids.sql.enabled=false` logs
  still record what WOULD have placed on device), and
- profiling/regression comparison across accelerated runs.

One file per process (`app-<pid>-<start>.jsonl`), append-only, crash
tolerant (a torn last line is skipped by the readers).
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterator, List, Optional

__all__ = ["EVENT_LOG_DIR", "log_query_event", "log_scheduler_events",
           "log_plan_rejected", "log_sql_error", "log_query_cancelled",
           "log_spill_event", "read_event_logs", "plan_fingerprint"]

from ..config import register

EVENT_LOG_DIR = register(
    "spark.rapids.eventLog.dir", "",
    "When set, every collect() appends a JSON-line query event "
    "(plan, placement, metrics, wall time) under this directory — the "
    "input to the offline qualification and profiling tools.")

_APP_FILE: Dict[tuple, str] = {}


def _app_path(base: str) -> str:
    key = (os.getpid(), os.path.abspath(base))
    if key not in _APP_FILE:
        os.makedirs(base, exist_ok=True)
        _APP_FILE[key] = os.path.join(
            base, f"app-{key[0]}-{int(time.time() * 1000)}.jsonl")
    return _APP_FILE[key]


def _prune_event_logs(conf, base: str) -> None:
    """Write-time retention (spark.rapids.trace.maxFiles, shared with
    the trace dir): oldest app-*.jsonl beyond the bound are unlinked —
    safe for the file just appended, which is the newest by mtime."""
    from ..obs.recorder import prune_oldest
    from ..obs.tracer import TRACE_MAX_FILES
    prune_oldest(base, conf.get(TRACE_MAX_FILES), prefix="app-",
                 suffix=".jsonl")


def plan_fingerprint(root) -> str:
    """Stable id for 'the same query shape' across runs: a hash of the
    operator tree with per-instance labels stripped."""
    import hashlib
    import re
    text = re.sub(r"#\d+", "", root.tree_string())
    return hashlib.sha1(text.encode()).hexdigest()[:16]


def log_query_event(pp, ctx, wall_s: float) -> None:
    """Append one query event; pp is the PhysicalPlan, ctx the ExecCtx
    collect() used. No-op unless spark.rapids.eventLog.dir is set."""
    base = pp.conf.get(EVENT_LOG_DIR)
    if not base:
        return
    nodes = []

    def rec(meta, depth):
        nodes.append({
            "op": meta.node.pretty_name(),
            "depth": depth,
            "on_device": meta.on_device,
            "reasons": meta.reasons,
        })
        for c in meta.children:
            rec(c, depth + 1)

    rec(pp.meta, 0)
    # fold deferred device row counts in before snapshotting: every
    # caller of this function sits at (or after) the query's natural
    # sync point, and some (ml.py) don't finalize themselves
    opm = getattr(ctx, "opm", None) if ctx is not None else None
    if opm is not None:
        opm.finalize()
    metrics = {
        label: {name: m.value for name, m in ms.items()}
        for label, ms in (ctx.metrics if ctx else {}).items()}
    # top per-operator time sinks ride the event line itself, so the
    # qualification/profiling tools get operator attribution without
    # opening the query's profile file
    op_sinks = []
    if metrics:
        from ..obs.opmetrics import fold_snapshots, top_op_sinks
        op_sinks = top_op_sinks(fold_snapshots([{"ops": metrics}]))
    event = {
        "ts": time.time(),
        "fingerprint": plan_fingerprint(pp.root),
        "wall_s": round(wall_s, 6),
        "sql_enabled": pp.conf.sql_enabled,
        "nodes": nodes,
        "metrics": metrics,
        "op_sinks": op_sinks,
        "conf": {k: str(v) for k, v in pp.conf.items().items()},
        "plan": pp.root.tree_string(),
    }
    tr = getattr(ctx, "tracer", None) if ctx is not None else None
    if tr is not None and getattr(tr, "enabled", False):
        # span rollup (counts + seconds per category, trace_id) so the
        # profiler can tie this event to its Chrome trace file
        event["trace"] = tr.summary()
    with open(_app_path(base), "a") as f:
        f.write(json.dumps(event) + "\n")
    _prune_event_logs(pp.conf, base)


def log_plan_rejected(conf, report, root, query_id: str = "") -> None:
    """Append one plan_rejected event: the static verifier refused to
    run this plan — the record `profiling` mines to answer "why did my
    query never start". No-op unless spark.rapids.eventLog.dir is
    set."""
    base = conf.get(EVENT_LOG_DIR)
    if not base:
        return
    event = {
        "type": "plan_rejected",
        "ts": time.time(),
        "query": query_id,
        "fingerprint": plan_fingerprint(root),
        "report": report.to_dict(),
        "plan": root.tree_string(),
    }
    with open(_app_path(base), "a") as f:
        f.write(json.dumps(event) + "\n")
    _prune_event_logs(conf, base)


def log_sql_error(conf, err, sql_text: str) -> None:
    """Append one SQL frontend failure event (type = the error's
    stable slug, ``sql_parse_error`` / ``sql_analysis_error``) with
    line/col, detail code, and caret snippet — the "why didn't my SQL
    run" record, mirroring ``plan_rejected``. No-op unless
    spark.rapids.eventLog.dir is set."""
    base = conf.get(EVENT_LOG_DIR)
    if not base:
        return
    event = dict(err.to_dict())
    event["ts"] = time.time()
    event["sql"] = sql_text[:4000]
    with open(_app_path(base), "a") as f:
        f.write(json.dumps(event) + "\n")
    _prune_event_logs(conf, base)


def log_query_cancelled(conf, err, wall_s: float,
                        source: str = "plan",
                        cluster: str = "local") -> None:
    """Append one query_cancelled event: the lifecycle layer stopped
    this query — classified (user | deadline | budget | admission),
    mirroring ``plan_rejected`` as the "why didn't my query finish"
    record. ``err`` is the QueryCancelled. No-op unless
    spark.rapids.eventLog.dir is set."""
    base = conf.get(EVENT_LOG_DIR)
    if not base:
        return
    event = {
        "type": "query_cancelled",
        "ts": time.time(),
        "query": getattr(err, "query_id", ""),
        "reason": getattr(err, "reason", "user"),
        "detail": getattr(err, "detail", "")[:500],
        "wall_s": round(wall_s, 6),
        "source": source,
        "cluster": cluster,
    }
    with open(_app_path(base), "a") as f:
        f.write(json.dumps(event) + "\n")
    _prune_event_logs(conf, base)


def log_spill_event(conf, type_: str, **fields) -> None:
    """Append one spill-tier durability event — ``spill_write_failed``
    (a non-ENOSPC OSError refused a spill write), ``spill_read_failed``
    (a committed spill file failed its verified read-back, classified
    missing|corrupt|torn|io), or ``disk_pressure`` (ENOSPC or the live
    disk-residency budget refused the write; the batch stayed
    host-resident) — mirroring the shuffle tier's ``fetch_failed``
    evidence. No-op unless spark.rapids.eventLog.dir is set."""
    base = conf.get(EVENT_LOG_DIR)
    if not base:
        return
    event = {"type": type_, "ts": time.time()}
    event.update({k: v for k, v in fields.items() if v is not None})
    with open(_app_path(base), "a") as f:
        f.write(json.dumps(event) + "\n")
    _prune_event_logs(conf, base)


def log_scheduler_events(conf, query_id: str, sched, wall_s: float,
                         op_sinks: Optional[List[Dict]] = None) -> None:
    """Append one scheduler event per cluster query: the attempt
    timeline (submit/ok/failed/lost/speculative, worker deaths,
    respawns, blacklists) plus a rollup — what the profiler mines for
    retry overhead — and the query's top per-operator time sinks
    (cross-worker folded opmetrics). No-op unless
    spark.rapids.eventLog.dir is set."""
    base = conf.get(EVENT_LOG_DIR)
    if not base:
        return
    event = {
        "type": "scheduler",
        "ts": time.time(),
        "query": query_id,
        "wall_s": round(wall_s, 6),
        "summary": sched.summary(),
        "attempts": sched.events,
        "op_sinks": op_sinks or [],
    }
    tr = getattr(sched, "tracer", None)
    if tr is not None and getattr(tr, "enabled", False):
        event["trace"] = tr.summary()
    with open(_app_path(base), "a") as f:
        f.write(json.dumps(event) + "\n")
    _prune_event_logs(conf, base)


def read_event_logs(path: str) -> Iterator[dict]:
    """Every parseable event under a log dir (or a single file); torn
    trailing lines from crashed writers are skipped."""
    files: List[str] = []
    if os.path.isdir(path):
        files = [os.path.join(path, n) for n in sorted(os.listdir(path))
                 if n.endswith(".jsonl")]
    elif os.path.exists(path):
        files = [path]
    for fp in files:
        with open(fp) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn write
