"""Cross-process ICI shuffle: the in-process collective epochs of
`shuffle/ici.py`, run as ONE SPMD program over the multi-host mesh.

Every gang member (one worker process per mesh row) executes the same
plan; each `TpuShuffleExchangeExec` binds to a `GangIciShuffleTransport`
and contributes its local map blocks. The collective epoch is then:

1. **manifest barrier** (once per shuffle id) — each member publishes
   its local sizing (block count, caps, var-width buckets, schema
   fingerprint) to the exchange's rendezvous dir; everyone adopts the
   field-wise MAXIMA, so all members enter identical jit programs with
   identical static shapes — the SPMD contract. Zero-block members
   participate with empty slots (schema via `set_shuffle_schema`).
2. **host-boundary assembly** — each member's packed lane stacks
   (L local slots) become rows of one GLOBAL array via
   `jax.make_array_from_process_local_data`; the existing
   `make_ici_all_to_all` kernel then routes rows across the process
   boundary exactly as it routes them across local devices — the
   hierarchical (dcn, ici) axes map inter-process x intra-process hops
   onto the matching interconnect.
3. **local readback** — results come back through each member's
   addressable shards only (a `device_get` of the global array would
   span non-addressable devices); partition p lands on global device
   p mod D, so exactly one member owns and emits it.

Shuffle identity across processes is the transport's own REGISTRATION
ordinal, not the module-global shuffle-id counter: registration order
follows plan structure, which is identical on every member; per-process
id counters drift on long-lived workers.
"""
from __future__ import annotations

import itertools
import json
import os
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..columnar.batch import TpuBatch, bucket_bytes, bucket_rows
from ..lifecycle import QueryCancelled
from ..obs.metrics import REGISTRY as _METRICS
from ..obs.recorder import RECORDER as _FLIGHT
from ..shuffle.ici import (IciShuffleTransport, _discover_epoch_caps,
                           _lane_layout, _lane_spec, _len_lane_indices,
                           _node_at, _pack_block, _pad1, _unpack_device)
from ..shuffle.transport import FetchFailure
from .runtime import MeshRuntime

__all__ = ["GangIciShuffleTransport"]

MESH_COLLECTIVE_EPOCHS = _METRICS.counter(
    "rapids_mesh_collective_epochs_total",
    "Cross-process all-to-all epochs run by the gang shuffle.")
MESH_COLLECTIVE_BYTES = _METRICS.counter(
    "rapids_mesh_collective_bytes_total",
    "Bytes this process contributed to cross-process collective "
    "epochs (packed lane stacks, structural — not wall-clock).")

_BARRIER_POLL_S = 0.005


def _enc(key: Tuple[int, tuple]) -> str:
    ci, path = key
    return f"{ci}:" + ".".join(str(p) for p in path)


def _dec(s: str) -> Tuple[int, tuple]:
    ci, _, path = s.partition(":")
    return int(ci), tuple(int(p) for p in path.split(".") if p != "")


def _max_merge(dicts: List[Dict[str, int]]) -> Dict[tuple, int]:
    out: Dict[tuple, int] = {}
    for d in dicts:
        for k, v in d.items():
            kk = _dec(k)
            out[kk] = max(out.get(kk, 0), int(v))
    return out


def _schema_fp(schema) -> str:
    return ";".join(f"{f.name}:{f.dtype.simple_string()}"
                    for f in schema.fields)


class GangIciShuffleTransport(IciShuffleTransport):
    """`IciShuffleTransport` whose mesh spans N worker processes.

    Single-process runtimes (the graceful fallback) delegate straight
    to the base class — same kernels, no barrier, no rendezvous I/O.
    """

    def __init__(self, runtime: MeshRuntime, exchange_root: str,
                 conf=None, qctx=None):
        super().__init__(runtime.mesh, axis=runtime.axis, conf=conf)
        self._passthrough_excs = (IciShuffleTransport._passthrough_excs
                                  + (QueryCancelled,))
        self._rt = runtime
        self._root = exchange_root
        self._qctx = qctx
        from ..config import MESH_BARRIER_TIMEOUT, RapidsConf
        self._barrier_timeout = (conf or RapidsConf()).get(
            MESH_BARRIER_TIMEOUT)
        self._ord_seq = itertools.count()
        self._ordinals: Dict[int, int] = {}
        self._schemas: Dict[int, object] = {}

    # -- identity / metadata ----------------------------------------------

    def register_shuffle(self, shuffle_id: int, num_partitions: int):
        super().register_shuffle(shuffle_id, num_partitions)
        with self._lock:
            if shuffle_id not in self._ordinals:
                self._ordinals[shuffle_id] = next(self._ord_seq)

    def set_shuffle_schema(self, shuffle_id: int, schema) -> None:
        """Exchange-declared output schema: lets a member with ZERO
        local blocks still pack empty slots and join the collective."""
        with self._lock:
            self._schemas[shuffle_id] = schema

    def partition_stats(self, shuffle_id: int, free_only: bool = False):
        # per-process stats diverge across members; a divergent AQE
        # replan would break the identical-program contract
        if self._rt.distributed:
            return None
        return super().partition_stats(shuffle_id, free_only=free_only)

    def stage_bytes(self, shuffle_id: int):
        # same divergence hazard: the AQE join-strategy switch compares
        # this against a threshold, and members must pick one strategy
        if self._rt.distributed:
            return None
        return super().stage_bytes(shuffle_id)

    def _owns_partition(self, partition_id: int, nparts: int) -> bool:
        if not self._rt.distributed:
            return True
        g = partition_id % self.ndev if nparts != self.ndev \
            else partition_id
        return self._rt.owns_device(g)

    def _check_cancel(self) -> None:
        if self._qctx is not None:
            self._qctx.check()

    # -- the gang collective ----------------------------------------------

    def _realize(self, sid: int):
        if not self._rt.distributed:
            return super()._realize(sid)
        with self._lock:
            if sid in self._results:
                return
            blocks = list(self._pending.get(sid, []))
            nparts = self._nparts.get(sid, self.ndev)
        blocks.sort(key=lambda e: e[0])
        t0 = time.perf_counter()
        results: List[List[TpuBatch]] = [[] for _ in range(nparts)]
        plan = self._gang_plan(sid, blocks, nparts)
        if plan is not None:
            schema, epochs, cap, widths, char_caps, src_caps = plan
            L = self._rt.local_devices
            for e in range(epochs):
                self._check_cancel()
                self._run_gang_epoch(
                    blocks[e * L:(e + 1) * L], schema, nparts, cap,
                    widths, char_caps, src_caps, results, sid, e)
            if blocks:
                from ..shuffle.host import (SHUF_BYTES_WRITTEN,
                                            SHUF_FETCH_WAIT,
                                            SHUF_PARTS_WRITTEN)
                SHUF_FETCH_WAIT.labels("ici").observe(
                    time.perf_counter() - t0)
                SHUF_PARTS_WRITTEN.labels("ici").inc(len(blocks))
                SHUF_BYTES_WRITTEN.labels("ici").inc(
                    sum(b.device_size_bytes() for _, b, _ in blocks))
        with self._lock:
            self._results[sid] = results
            self._pending.pop(sid, None)

    def _gang_plan(self, sid: int, blocks, nparts: int):
        """Publish this member's sizing manifest, wait for all N, adopt
        the global maxima. Returns None when the WHOLE gang has zero
        blocks (nothing to exchange), else
        (schema, epochs, cap, widths, char_caps, src_caps)."""
        schema = blocks[0][1].schema if blocks \
            else self._schemas.get(sid)
        spec = _lane_spec(schema) if schema is not None else None
        fold = nparts != self.ndev
        if blocks:
            widths, char_caps = _discover_epoch_caps(
                blocks, spec, self.ndev, fold, self._jit_widths)
            src_caps = {}
            for ci, path, kind, _ in spec:
                if kind == "str_mat":
                    src_caps[(ci, path)] = bucket_bytes(max(
                        [int(_node_at(b.column(ci), path).chars.shape[0])
                         for _, b, _ in blocks] + [1]), minimum=16)
        else:
            widths, char_caps, src_caps = {}, {}, {}
        man = {"process_id": self._rt.process_id,
               "nblocks": len(blocks),
               "cap": max([b.capacity for _, b, _ in blocks] + [1]),
               "nparts": int(nparts),
               "schema_fp": _schema_fp(schema) if schema is not None
               else "",
               "widths": {_enc(k): int(v) for k, v in widths.items()},
               "char_caps": {_enc(k): int(v)
                             for k, v in char_caps.items()},
               "src_caps": {_enc(k): int(v)
                            for k, v in src_caps.items()}}
        mans = self._barrier(sid, man)
        total = sum(m["nblocks"] for m in mans)
        if total == 0:
            return None
        fps = {m["schema_fp"] for m in mans if m["schema_fp"]}
        if len(fps) > 1:
            raise FetchFailure(
                sid, None, self._xdir(sid), "corrupt",
                f"gang members disagree on the exchange schema: {fps}")
        if {m["nparts"] for m in mans} != {int(nparts)}:
            raise FetchFailure(
                sid, None, self._xdir(sid), "corrupt",
                "gang members disagree on the partition count")
        if schema is None:
            raise FetchFailure(
                sid, None, self._xdir(sid), "io",
                "member has blocks nowhere to learn the schema from "
                "and the exchange never declared one")
        L = self._rt.local_devices
        epochs = max(-(-m["nblocks"] // L) for m in mans)
        cap = max(m["cap"] for m in mans)
        g_widths = _max_merge([m["widths"] for m in mans])
        g_chars = _max_merge([m["char_caps"] for m in mans])
        g_src = _max_merge([m["src_caps"] for m in mans])
        return schema, epochs, cap, g_widths, g_chars, g_src

    def _xdir(self, sid: int) -> str:
        return os.path.join(self._root, f"x{self._ordinals[sid]}")

    def _barrier(self, sid: int, man: Dict) -> List[Dict]:
        """One filesystem rendezvous per shuffle id: every member's
        manifest, or a classified io failure on timeout. Polls the
        query's cancel token so a cancelled member exits the barrier
        (and, via the shared cancel marker, frees the others too)."""
        xdir = self._xdir(sid)
        os.makedirs(xdir, exist_ok=True)
        path = os.path.join(xdir, f"m{self._rt.process_id}.json")
        with open(path + ".tmp", "w") as f:
            json.dump(man, f)
        os.replace(path + ".tmp", path)
        n = self._rt.num_processes
        deadline = time.monotonic() + self._barrier_timeout
        mans: Dict[int, Dict] = {}
        while True:
            self._check_cancel()
            for k in range(n):
                if k in mans:
                    continue
                try:
                    with open(os.path.join(xdir, f"m{k}.json")) as f:
                        mans[k] = json.load(f)
                except (OSError, json.JSONDecodeError):
                    pass
            if len(mans) == n:
                return [mans[k] for k in range(n)]
            if time.monotonic() > deadline:
                raise FetchFailure(
                    sid, None, xdir, "io",
                    f"mesh manifest barrier timed out after "
                    f"{self._barrier_timeout:.0f}s "
                    f"({len(mans)}/{n} members present)")
            time.sleep(_BARRIER_POLL_S)

    def _global(self, stack):
        """Local (L, ...) lane stack -> rows of the (D, ...) global
        array: the per-process addressable-shard assembly at the host
        boundary. Accepts device stacks or host ndarrays."""
        host = stack if isinstance(stack, np.ndarray) \
            else np.asarray(jax.device_get(stack))
        sh = NamedSharding(self.mesh,
                           P(self.axis, *([None] * (host.ndim - 1))))
        return jax.make_array_from_process_local_data(
            sh, host, (self.ndev,) + host.shape[1:])

    @staticmethod
    def _local_rows(garr, out: Dict[int, np.ndarray]) -> None:
        for s in garr.addressable_shards:
            g = s.index[0].start if isinstance(s.index[0], slice) \
                else int(s.index[0])
            out[int(g)] = np.asarray(s.data)[0]

    def _run_gang_epoch(self, blocks, schema, nparts: int, cap: int,
                        widths, char_caps, src_caps, results,
                        sid: int, epoch: int):
        ndev = self.ndev
        L = self._rt.local_devices
        fold = nparts != ndev
        spec = _lane_spec(schema)

        lane_meta, lane_datas, lane_valids = _lane_layout(spec)
        if fold:
            lane_meta.append((-1, (), "pid", None))
            lane_datas.append([])
            lane_valids.append([])

        pids_all, live_all = [], []
        char_stacks: Dict[tuple, tuple] = {}
        for slot in range(L):
            if slot < len(blocks):
                _, b, pids = blocks[slot]
                live = _pad1(b.live_mask(), cap)
                pids = _pad1(pids.astype(jnp.int32), cap)
            else:
                b = None
                pids = jnp.zeros((cap,), jnp.int32)
                live = jnp.zeros((cap,), jnp.bool_)
            pids_all.append(pids % ndev if fold else pids)
            live_all.append(live)
            _pack_block(b, schema, cap, widths, lane_datas, lane_valids,
                        spec, char_stacks=char_stacks)
            if fold:
                lane_datas[-1].append(pids)
                lane_valids[-1].append(live)

        host_stacks = [np.asarray(jax.device_get(jnp.stack(ls)))
                       for ls in lane_datas]
        datas = tuple(self._global(h) for h in host_stacks)
        valids = tuple(self._global(jnp.stack(ls))
                       for ls in lane_valids)
        pids_g = self._global(jnp.stack(pids_all))
        live_g = self._global(jnp.stack(live_all))
        sent = sum(h.nbytes for h in host_stacks)

        str_keys = [(ci, path) for ci, path, kind, _ in spec
                    if kind == "str_mat"]
        char_offs, char_bytes, cb_list = [], [], []
        for keyk in str_keys:
            # every member must even pack ABSENT string lanes (a member
            # whose epoch slots are all empty never touched char_stacks)
            offs_list, chars_list = char_stacks.get(
                keyk, ([jnp.zeros((cap + 1,), jnp.int32)] * L,
                       [jnp.zeros((0,), jnp.uint8)] * L))
            ch_cap = src_caps.get(keyk, 16)
            char_offs.append(self._global(jnp.stack(offs_list)))
            ch_host = np.asarray(jax.device_get(jnp.stack(
                [_pad1(c, ch_cap) for c in chars_list])))
            char_bytes.append(self._global(ch_host))
            cb_list.append(char_caps[keyk])
            sent += ch_host.nbytes

        self._check_cancel()
        out_datas, out_valids, out_live, out_rc, out_chars = \
            self._exchange(datas, valids, pids_g, live_g,
                           char_offs=char_offs, char_bytes=char_bytes,
                           char_caps=tuple(cb_list))
        MESH_COLLECTIVE_EPOCHS.inc()
        MESH_COLLECTIVE_BYTES.inc(sent)
        _FLIGHT.record("shuffle", ev="mesh_epoch", sid=int(sid),
                       epoch=int(epoch), bytes=int(sent),
                       nproc=self._rt.num_processes,
                       process=self._rt.process_id,
                       # owning query: the warehouse attributes gang-DCN
                       # bytes to the query that ran the collective
                       query=(self._qctx.query_id
                              if self._qctx is not None else ""))

        # readback through ADDRESSABLE shards only — a device_get of the
        # global arrays would span devices this process cannot address
        loc_datas: List[Dict[int, np.ndarray]] = \
            [{} for _ in lane_meta]
        loc_valids: List[Dict[int, np.ndarray]] = \
            [{} for _ in lane_meta]
        for li in range(len(lane_meta)):
            self._local_rows(out_datas[li], loc_datas[li])
            self._local_rows(out_valids[li], loc_valids[li])
        loc_live: Dict[int, np.ndarray] = {}
        loc_rc: Dict[int, np.ndarray] = {}
        self._local_rows(out_live, loc_live)
        self._local_rows(out_rc, loc_rc)
        payloads = {}
        si = 0
        for li, (ci, path, kind, _) in enumerate(spec):
            if kind == "str_mat":
                chunks: Dict[int, np.ndarray] = {}
                self._local_rows(out_chars[si], chunks)
                payloads[li] = (chunks, cb_list[si])
                si += 1

        len_lanes = _len_lane_indices(spec)
        for g in self._rt.owned_rows:
            if int(loc_rc[g]) == 0:
                continue
            live_d = jnp.asarray(loc_live[g])
            live_np = loc_live[g]
            flat_caps = {}
            for li in len_lanes:
                total = max(int(np.sum(np.where(
                    live_np, loc_datas[li][g], 0))), 1)
                if spec[li][2] == "str_len":
                    flat_caps[li - 1] = bucket_bytes(total, minimum=16)
                else:
                    flat_caps[li - 2] = bucket_rows(total)
            cols, pid_lane = _unpack_device(
                schema, lane_meta, loc_datas, loc_valids, g, live_d,
                flat_caps, payloads=payloads, ndev=ndev)
            landed = TpuBatch(cols, schema, ndev * cap,
                              selection=live_d)
            if not fold:
                results[g].append(landed)
            else:
                pid_j = jnp.asarray(pid_lane)
                for p in range(g, nparts, ndev):
                    results[p].append(
                        landed.with_selection(pid_j == p))
