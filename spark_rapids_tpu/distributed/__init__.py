"""Multi-host mesh runtime: one logical device mesh spanning the
TpuProcessCluster's worker processes, with the ICI shuffle collective
routed across the process boundary (SURVEY.md §5.8, §7.2-P4;
SNIPPETS.md [1] — "on multi-process platforms such as TPU pods, pjit
can be used to run computations across all available devices across
processes").

- `runtime` — per-process bootstrap of `jax.distributed` + the global
  (dcn, ici) Mesh, with a graceful single-process fallback.
- `gang` — `GangIciShuffleTransport`: the cross-process exchange, a
  filesystem manifest barrier for global epoch sizing, per-process
  addressable-shard assembly at the host boundary.
"""
from .runtime import (MeshRuntime, bootstrap_from_env, get_runtime,
                      mesh_env, read_mesh_markers, set_runtime)

__all__ = ["MeshRuntime", "bootstrap_from_env", "get_runtime",
           "set_runtime", "mesh_env", "read_mesh_markers",
           "GangIciShuffleTransport"]


def __getattr__(name):
    # gang imports jax at module load; keep the package importable for
    # env-only helpers (mesh_env, read_mesh_markers) without it
    if name == "GangIciShuffleTransport":
        from .gang import GangIciShuffleTransport
        return GangIciShuffleTransport
    raise AttributeError(name)
