"""Per-process mesh bootstrap.

Each cluster worker calls `bootstrap_from_env` BEFORE its first device
touch: the rendezvous env (written by `TpuProcessCluster` at spawn)
names the coordinator address, the process's rank, the fleet size, and
the per-process device count. The worker then

1. provisions its local devices (on the CPU backend: XLA virtual
   devices via ``--xla_force_host_platform_device_count``, exactly the
   dryrun_multichip posture),
2. selects the cross-process collective implementation (gloo on CPU —
   without it XLA rejects multiprocess CPU computations),
3. joins ``jax.distributed.initialize`` with a bounded rendezvous, and
4. builds ONE global `Mesh` over every process's devices, ordered
   process-major and shaped hierarchically as (dcn, ici) =
   inter-process x intra-process, so XLA routes each collective hop
   over the matching interconnect (SURVEY.md §5.8).

Failure is graceful: a timeout or version skew writes an error marker
and the worker keeps running in single-process mode — the driver reads
the markers and keeps mesh queries off the fleet. With no mesh env (or
one process) the runtime is a local, non-distributed mesh: the
single-process fallback the local `IciShuffleTransport` tests run on.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

import numpy as np

from ..obs.metrics import REGISTRY as _METRICS

__all__ = ["MeshRuntime", "bootstrap_from_env", "get_runtime",
           "set_runtime", "mesh_env", "read_mesh_markers",
           "MESH_AXES"]

#: hierarchical axis names: ("dcn", "ici") = processes x local devices
MESH_AXES = ("dcn", "ici")

ENV_COORD = "RAPIDS_TPU_MESH_COORD"
ENV_NPROC = "RAPIDS_TPU_MESH_NPROC"
ENV_PID = "RAPIDS_TPU_MESH_PID"
ENV_LOCAL_DEVICES = "RAPIDS_TPU_MESH_LOCAL_DEVICES"
ENV_TIMEOUT = "RAPIDS_TPU_MESH_TIMEOUT"
ENV_INCARNATION = "RAPIDS_TPU_MESH_INCARNATION"

MESH_PROCESSES = _METRICS.gauge(
    "rapids_mesh_processes",
    "Processes participating in the bootstrapped device mesh (0 = no "
    "mesh this process).")
MESH_DEVICES = _METRICS.gauge(
    "rapids_mesh_devices",
    "Global devices in the bootstrapped mesh (all processes).")

_runtime: Optional["MeshRuntime"] = None


def get_runtime() -> Optional["MeshRuntime"]:
    return _runtime


def set_runtime(rt: Optional["MeshRuntime"]) -> None:
    global _runtime
    _runtime = rt


def mesh_env(coordinator: str, num_processes: int, local_devices: int,
             timeout_s: float, incarnation: int) -> Dict[str, str]:
    """The env slice a worker needs to join the mesh — everything but
    its rank (`ENV_PID`), which the pool stamps per spawn."""
    return {ENV_COORD: coordinator,
            ENV_NPROC: str(int(num_processes)),
            ENV_LOCAL_DEVICES: str(int(local_devices)),
            ENV_TIMEOUT: str(float(timeout_s)),
            ENV_INCARNATION: str(int(incarnation))}


class MeshRuntime:
    """One process's handle on the global mesh: the Mesh itself, this
    process's rank and device rows, and the ownership map partition
    routing needs (global device g belongs to process g // L — devices
    are ordered process-major, asserted at build)."""

    def __init__(self, mesh, process_id: int, num_processes: int,
                 incarnation: int = 0, distributed: bool = False):
        self.mesh = mesh
        self.axis = MESH_AXES
        self.process_id = int(process_id)
        self.num_processes = int(num_processes)
        self.incarnation = int(incarnation)
        self.distributed = distributed
        devs = list(np.asarray(mesh.devices).reshape(-1))
        self.global_devices = len(devs)
        assert self.global_devices % self.num_processes == 0
        self.local_devices = self.global_devices // self.num_processes
        self.device_kind = getattr(devs[0], "platform", "cpu")
        lo = self.process_id * self.local_devices
        #: global device indices this process can address
        self.owned_rows = list(range(lo, lo + self.local_devices))

    def owns_device(self, g: int) -> bool:
        return g // self.local_devices == self.process_id

    def owner_of(self, g: int) -> int:
        return g // self.local_devices

    def describe(self) -> Dict:
        return {"process_id": self.process_id,
                "num_processes": self.num_processes,
                "local_devices": self.local_devices,
                "global_devices": self.global_devices,
                "incarnation": self.incarnation,
                "distributed": self.distributed,
                "device_kind": self.device_kind}


def _build_mesh(process_id: int, num_processes: int,
                incarnation: int, distributed: bool) -> MeshRuntime:
    import jax
    from jax.sharding import Mesh
    devs = sorted(jax.devices(),
                  key=lambda d: (d.process_index, d.id))
    n = len(devs)
    if n % num_processes:
        raise RuntimeError(
            f"{n} global devices do not divide over {num_processes} "
            "processes — uneven per-process device counts cannot form "
            "a (dcn, ici) mesh")
    local = n // num_processes
    # ownership math (owns_device) requires process-major global order;
    # assert it instead of trusting the backend's enumeration
    for g, d in enumerate(devs):
        if d.process_index != g // local:
            raise RuntimeError(
                f"device order is not process-major at index {g} "
                f"(process {d.process_index}); cannot map partitions "
                "to owners")
    arr = np.asarray(devs, dtype=object).reshape(num_processes, local)
    mesh = Mesh(arr, MESH_AXES)
    rt = MeshRuntime(mesh, process_id, num_processes,
                     incarnation=incarnation, distributed=distributed)
    MESH_PROCESSES.set(num_processes)
    MESH_DEVICES.set(n)
    return rt


def bootstrap_local(num_devices: Optional[int] = None,
                    incarnation: int = 0) -> MeshRuntime:
    """Single-process fallback: a (1, L) mesh over this process's own
    devices — no coordinator, no gloo, no rendezvous. The gang
    transport degenerates to the in-process collective on it."""
    if num_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{int(num_devices)}").strip()
    rt = _build_mesh(0, 1, incarnation=incarnation, distributed=False)
    set_runtime(rt)
    return rt


def bootstrap_from_env(root: Optional[str] = None,
                       worker_id: Optional[int] = None,
                       env=None) -> Optional[MeshRuntime]:
    """Worker-side entry: join the mesh named by the rendezvous env.

    Returns the runtime on success, None when no mesh is configured
    (classic cluster mode). A FAILED bootstrap also returns None after
    writing the error marker — the worker stays useful for file-based
    stages and the driver routes mesh queries away. Must run before
    this process's first device touch (XLA_FLAGS are read at backend
    init)."""
    env = env if env is not None else os.environ
    coord = env.get(ENV_COORD)
    if not coord:
        return None
    nproc = int(env.get(ENV_NPROC, "1"))
    pid = int(env.get(ENV_PID, "0"))
    incarnation = int(env.get(ENV_INCARNATION, "0"))
    timeout_s = float(env.get(ENV_TIMEOUT, "45"))
    local = int(env.get(ENV_LOCAL_DEVICES, "2"))
    try:
        if nproc <= 1:
            rt = bootstrap_local(num_devices=local,
                                 incarnation=incarnation)
        else:
            platform = env.get("JAX_PLATFORMS", "")
            if "cpu" in platform or platform == "":
                # REPLACE an inherited device-count flag (the driver's
                # test env pins its own): the mesh contract is exactly
                # `local` addressable devices per process
                import re
                flags = os.environ.get("XLA_FLAGS", "")
                want = (f"--xla_force_host_platform_device_count="
                        f"{local}")
                if "xla_force_host_platform_device_count" in flags:
                    flags = re.sub(
                        r"--xla_force_host_platform_device_count=\d+",
                        want, flags)
                else:
                    flags = (flags + " " + want).strip()
                os.environ["XLA_FLAGS"] = flags
            import jax
            if "cpu" in platform or platform == "":
                # without gloo, XLA rejects multiprocess CPU
                # computations outright ("Multiprocess computations
                # aren't implemented on the CPU backend")
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            jax.distributed.initialize(
                coordinator_address=coord, num_processes=nproc,
                process_id=pid,
                initialization_timeout=int(max(1, timeout_s)))
            rt = _build_mesh(pid, nproc, incarnation=incarnation,
                             distributed=True)
            set_runtime(rt)
    except Exception as exc:  # noqa: BLE001 — bootstrap must degrade,
        # not kill the worker: classic file-based stages still run
        if root is not None and worker_id is not None:
            _write_marker(root, worker_id, {
                "ok": False, "incarnation": incarnation,
                "error": f"{type(exc).__name__}: {exc}"[:500]})
        return None
    if root is not None and worker_id is not None:
        _write_marker(root, worker_id,
                      dict(rt.describe(), ok=True))
    return rt


def _write_marker(root: str, worker_id: int, doc: Dict) -> None:
    d = os.path.join(root, "mesh")
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"w{worker_id}.mesh.json")
        with open(path + ".tmp", "w") as f:
            json.dump(dict(doc, ts=time.time()), f)
        os.replace(path + ".tmp", path)
    except OSError:
        pass  # driver-side readiness just times out


def read_mesh_markers(root: str, n_workers: int,
                      incarnation: int) -> Optional[List[Dict]]:
    """Driver-side readiness: every worker's marker for the CURRENT
    incarnation, or None while any is missing/stale. A marker with
    ok=False is returned too — the caller distinguishes 'not ready
    yet' (None) from 'bootstrap failed' (ok=False entries)."""
    out = []
    for w in range(n_workers):
        path = os.path.join(root, "mesh", f"w{w}.mesh.json")
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        if not isinstance(doc, dict) \
                or int(doc.get("incarnation", -1)) != incarnation:
            return None
        out.append(doc)
    return out
